"""Unified deterministic FaultPlan: ONE seeded fault schedule driving
both tiers of the transport seam.

The reference bakes Antithesis-style fault campaigns into its test rig
(.antithesis/config/docker-compose.yaml: partitions, crashes, degraded
links) and always/sometimes assertions into production code
(`corrosion_tpu.invariants`).  Before this module each tier had its own
ad-hoc fault knobs — `LinkModel(loss, latency_s)` on the in-memory
cluster, a hard-coded WAN partition in sim config #4, kill -9 in the
process campaign — so the *same* adversarial schedule could never be
replayed against both tiers and compared.  A FaultPlan is the single
source of truth:

- a **schedule** of timed :class:`FaultEvent`\\ s — per-link loss /
  latency / jitter (jitter also produces message REORDERING on both
  tiers: each message draws its own extra delay), message duplication,
  asymmetric partitions (A hears B but not vice versa), node
  crash+restart with or without state wipe, and HLC clock skew;
- ``plan.schedule()`` expands events into a canonical per-round table —
  a pure function of the plan, so both compilers consume identical
  per-round fault decisions;
- :class:`HostFaultDriver` replays the schedule against an in-process
  cluster (`corrosion_tpu.testing.Cluster` on a `MemoryNetwork`),
  installing seed-derived :class:`~corrosion_tpu.agent.transport.LinkModel`
  instances, directed partition edges, crash/restart/wipe, and HLC skew;
- `corrosion_tpu.sim.faults.compile_plan` lowers the SAME schedule into
  per-round mask/delay tensors threaded through the sim kernels.

Seed derivation (the PeerSwap randomness-reproducibility discipline,
arxiv 2408.03829): every stochastic stream is derived from the ONE plan
seed via :func:`derive_seed` — a blake2b fold over ``(seed, *tokens)``
— so two links never share an RNG stream and a replay with the same
seed reproduces the exact per-draw decisions on each tier.

Time base: a plan is denominated in ROUNDS (one sim round ≈ one
broadcast flush tick).  The host driver converts rounds to wall-clock
via ``plan.round_s``; the sim indexes its schedule tensors by ``state.t``
directly.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple, Union

from .invariants import CATALOG, Catalog, sometimes

#: event kinds a plan may schedule (doc/faults.md documents each)
KINDS = (
    "loss",        # per-link Bernoulli drop of fire-and-forget payloads
    "delay",       # fixed added latency, in rounds
    "jitter",      # per-message uniform extra delay 0..delay_rounds (reorders)
    "duplicate",   # per-link Bernoulli duplication of delivered payloads
    "partition",   # directed (or symmetric) edge cut
    "crash",       # node down [start, end); restarts at `end`, optionally wiped
    "clock_skew",  # HLC physical-clock offset on one node
    "slow",        # gray failure: commit/stream stall on a LIVE node
                   # (delay_rounds × round_s seconds per gated operation;
                   # degraded-not-dead — SWIM suspects + saturation, never
                   # lost writes).  No sim twin (doc/faults.md).
)

#: node-level kinds (selected via ``node=``, no link rectangle)
NODE_KINDS = ("crash", "clock_skew", "slow")

NodeSel = Union[int, str]  # node index, "*", or a "lo:hi" half-open range


def sel_indices(sel: NodeSel, n: int) -> range:
    """Node selector → index range: an int selects one node, ``"*"``
    every node, and ``"lo:hi"`` the half-open range [lo, hi) — the
    storm-scale selector (a 100k-node half-split partition must be ONE
    event, not 2.5e9 expanded pairs; the factored sim compiler lowers a
    range straight to a node mask)."""
    if sel == "*":
        return range(n)
    if isinstance(sel, str) and ":" in sel:
        lo, hi = sel.split(":", 1)
        return range(int(lo), int(hi))
    i = int(sel)
    return range(i, i + 1)


def derive_seed(seed: int, *tokens) -> int:
    """Stable 63-bit child seed from the plan seed and a token path.

    blake2b over the repr of ``(seed, *tokens)`` — byte-stable across
    processes and Python hash randomization (``hash()`` is salted per
    process; it would break replay).  This is THE seed-derivation rule
    for every FaultPlan stream: per-link loss streams use
    ``derive_seed(seed, "link", src, dst, epoch)``, so two links with
    the same base seed never share an RNG stream, and the epoch (index
    of the link's parameter change in the schedule) restarts the stream
    deterministically whenever a link's fault parameters change.
    """
    h = hashlib.blake2b(
        repr((int(seed),) + tokens).encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") & (2**63 - 1)


@dataclass(frozen=True)
class FaultEvent:
    """One timed fault.  Active over rounds ``[start, end)`` (for
    ``crash``, the node is down over [start, end) and restarts at round
    ``end``; ``wipe=True`` loses its durable state at restart)."""

    kind: str
    start: int
    end: int
    src: NodeSel = "*"   # link faults: sending side ("*" = every node)
    dst: NodeSel = "*"   # link faults: receiving side
    # crash / clock_skew target: a node index, or (ISSUE 9, crash) a
    # "lo:hi" range / "*" selector — a 25k-node flash-crowd join must be
    # ONE event, not 25k (`corrosion_tpu.topo.churn` relies on it)
    node: Optional[NodeSel] = None
    p: float = 0.0       # loss / duplicate probability
    delay_rounds: int = 0  # delay magnitude (fixed for `delay`, max for `jitter`)
    wipe: bool = False   # crash: lose durable state at restart
    skew_ns: int = 0     # clock_skew offset (may be negative)
    symmetric: bool = False  # partition: cut both directions

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (use one of {KINDS})")
        if self.end <= self.start:
            raise ValueError(f"{self.kind}: end {self.end} must be > start {self.start}")
        if self.kind in NODE_KINDS and self.node is None:
            raise ValueError(f"{self.kind} needs node=")
        if self.kind == "slow" and self.delay_rounds <= 0:
            raise ValueError(
                "slow needs delay_rounds= (the stall magnitude: each gated "
                "operation on the node stalls delay_rounds * round_s seconds)"
            )
        if self.kind in ("loss", "duplicate") and not (0.0 <= self.p <= 1.0):
            raise ValueError(f"{self.kind}: p={self.p} outside [0, 1]")
        if self.delay_rounds > 255:
            # the sim's matrix compiler stores delays at u8 grain; a
            # silent clamp there would diverge from the factored form
            raise ValueError(
                f"{self.kind}: delay_rounds={self.delay_rounds} exceeds "
                "the 255-round schedule grain"
            )


@dataclass(frozen=True)
class LinkFault:
    """Resolved per-(directed-link, round) fault parameters."""

    loss: float = 0.0
    delay_rounds: int = 0
    jitter_rounds: int = 0
    duplicate: float = 0.0
    blocked: bool = False

    def merge(self, other: "LinkFault") -> "LinkFault":
        """Overlapping events compose: losses combine as independent
        drops, delays add, jitter/duplicate take the max, block ORs."""
        return LinkFault(
            loss=1.0 - (1.0 - self.loss) * (1.0 - other.loss),
            delay_rounds=self.delay_rounds + other.delay_rounds,
            jitter_rounds=max(self.jitter_rounds, other.jitter_rounds),
            duplicate=max(self.duplicate, other.duplicate),
            blocked=self.blocked or other.blocked,
        )


CLEAR = LinkFault()


def _event_link_fault(ev: "FaultEvent") -> LinkFault:
    """The LinkFault one active link event contributes — the single
    lowering rule both the pairwise (`schedule_at`) and range-atom
    (`range_link_epochs`) expansions share, so they cannot drift."""
    if ev.kind == "loss":
        return LinkFault(loss=ev.p)
    if ev.kind == "delay":
        return LinkFault(delay_rounds=ev.delay_rounds)
    if ev.kind == "jitter":
        return LinkFault(jitter_rounds=ev.delay_rounds)
    if ev.kind == "duplicate":
        return LinkFault(duplicate=ev.p)
    return LinkFault(blocked=True)  # partition


@dataclass(frozen=True)
class RoundSchedule:
    """Canonical fault state of ONE round — what both compilers consume."""

    links: Dict[Tuple[int, int], LinkFault]  # directed (src, dst) -> fault
    down: FrozenSet[int]        # nodes down this round
    restart: FrozenSet[int]     # nodes restarting this round (were down)
    wipe: FrozenSet[int]        # restarting nodes that lost durable state
    skews: Dict[int, int]       # node -> HLC offset (ns) active this round
    # node -> stall magnitude in rounds (the `slow` gray failure);
    # overlapping slow events take the max, like jitter
    slow: Dict[int, int] = field(default_factory=dict)

    def active_kinds(self) -> List[str]:
        """Fault kinds in effect this round — the single source for
        coverage-marker firing on BOTH tiers (`fault-<kind>-active`), so
        the drivers can't drift from `FaultPlan.coverage_markers`."""
        kinds = set()
        for f in self.links.values():
            if f.blocked:
                kinds.add("partition")
            if f.loss > 0:
                kinds.add("loss")
            if f.delay_rounds > 0:
                kinds.add("delay")
            if f.jitter_rounds > 0:
                kinds.add("jitter")
            if f.duplicate > 0:
                kinds.add("duplicate")
        if self.down:
            kinds.add("crash")
        if self.skews:
            kinds.add("clock_skew")
        if self.slow:
            kinds.add("slow")
        return sorted(kinds)


@dataclass(frozen=True)
class FaultPlan:
    """A seeded, declarative fault schedule for an ``n_nodes`` cluster."""

    n_nodes: int
    seed: int
    events: Tuple[FaultEvent, ...]
    round_s: float = 0.05  # host wall-clock per round (≈ fast_perf flush tick)

    def __post_init__(self):
        object.__setattr__(self, "events", tuple(self.events))
        for ev in self.events:
            for sel in (ev.src, ev.dst):
                r = sel_indices(sel, self.n_nodes)
                if len(r) == 0 or r.start < 0 or r.stop > self.n_nodes:
                    raise ValueError(f"node selector {sel} outside 0..{self.n_nodes - 1}")
            if ev.node is not None:
                r = sel_indices(ev.node, self.n_nodes)
                if len(r) == 0 or r.start < 0 or r.stop > self.n_nodes:
                    raise ValueError(
                        f"node {ev.node} outside 0..{self.n_nodes - 1}"
                    )

    # -- schedule expansion (pure; shared by both compilers) ---------------

    @property
    def horizon(self) -> int:
        """First round with no scheduled fault activity left (restart
        rounds included, so a crash's rejoin is inside the horizon)."""
        return max((ev.end for ev in self.events), default=0) + 1

    def _pairs(self, ev: FaultEvent):
        srcs = sel_indices(ev.src, self.n_nodes)
        dsts = sel_indices(ev.dst, self.n_nodes)
        for s in srcs:
            for d in dsts:
                if s != d:
                    yield (s, d)
                    if ev.kind == "partition" and ev.symmetric:
                        yield (d, s)

    def schedule_at(self, r: int, include_links: bool = True) -> RoundSchedule:
        """The resolved fault state of round ``r`` — a pure function of
        the plan, so the host driver and the sim compiler can never
        disagree on what round r looks like.

        ``include_links=False`` skips the pairwise link expansion and
        returns an empty ``links`` dict — the node-fault-only view the
        range-aware drivers use (ISSUE 7 satellite: a storm-shaped
        ``"lo:hi"`` plan must never expand |src|·|dst| pairs per round;
        link state rides `range_link_epochs` / `blocked_pairs_at`
        instead)."""
        links: Dict[Tuple[int, int], LinkFault] = {}
        down, restart, wipe = set(), set(), set()
        skews: Dict[int, int] = {}
        slow: Dict[int, int] = {}
        for ev in self.events:
            if ev.kind == "crash":
                # crash targets may be range selectors (ISSUE 9 churn)
                sel = sel_indices(ev.node, self.n_nodes)
                if ev.start <= r < ev.end:
                    down.update(sel)
                elif r == ev.end:
                    restart.update(sel)
                    if ev.wipe:
                        wipe.update(sel)
                continue
            if not ev.start <= r < ev.end:
                continue
            if ev.kind == "clock_skew":
                for i in sel_indices(ev.node, self.n_nodes):
                    skews[i] = skews.get(i, 0) + ev.skew_ns
                continue
            if ev.kind == "slow":
                for i in sel_indices(ev.node, self.n_nodes):
                    slow[i] = max(slow.get(i, 0), ev.delay_rounds)
                continue
            if not include_links:
                continue
            f = _event_link_fault(ev)
            for pair in self._pairs(ev):
                links[pair] = links.get(pair, CLEAR).merge(f)
        return RoundSchedule(
            links=links, down=frozenset(down), restart=frozenset(restart),
            wipe=frozenset(wipe), skews=skews, slow=slow,
        )

    def _has_pair(self, ev: FaultEvent) -> bool:
        """Whether an event's src × dst rectangle contains any s ≠ d
        pair (the only degenerate case is a 1×1 rectangle on the
        diagonal)."""
        sr = sel_indices(ev.src, self.n_nodes)
        dr = sel_indices(ev.dst, self.n_nodes)
        return not (
            len(sr) == 1 and len(dr) == 1 and sr.start == dr.start
        )

    def active_kinds_at(self, r: int) -> List[str]:
        """Fault kinds in effect at round ``r``, straight from the event
        table — equal to ``schedule_at(r).active_kinds()`` (zero-effect
        events filtered the same way the pairwise expansion drops them)
        but O(events) instead of O(events · pairs), so the range-aware
        drivers can fire coverage markers at storm scale."""
        kinds = set()
        for ev in self.events:
            if not ev.start <= r < ev.end:
                continue
            if ev.kind in ("loss", "duplicate") and ev.p <= 0:
                continue
            if ev.kind in ("delay", "jitter") and ev.delay_rounds <= 0:
                continue
            if ev.kind not in NODE_KINDS and not self._has_pair(ev):
                continue
            kinds.add(ev.kind)
        return sorted(kinds)

    def blocked_pairs_at(self, r: int):
        """Directed (src, dst) edges partition-cut at round ``r`` —
        yielded lazily so a driver can build its blocked set without the
        full pairwise `schedule_at` links dict.  The edge count itself
        is irreducible (the transports key partitions per edge), but
        nothing else pays the expansion.  Drivers should gate the
        expansion on `partition_epoch` so an UNCHANGED partition set is
        never rebuilt round over round."""
        seen = set()
        for ev in self.events:
            if ev.kind != "partition" or not ev.start <= r < ev.end:
                continue
            for pair in self._pairs(ev):
                if pair not in seen:
                    seen.add(pair)
                    yield pair

    def partition_epoch(self, r: int):
        """Hashable identity of the ACTIVE partition-event set at round
        ``r``: the blocked edge set is a pure function of it, so a
        driver rebuilds its `blocked_pairs_at` expansion only when this
        changes (O(events) per round instead of O(pairs))."""
        return tuple(
            i
            for i, ev in enumerate(self.events)
            if ev.kind == "partition" and ev.start <= r < ev.end
        )

    def _link_rects(self):
        """Directed link-event rectangles in merge order: (event,
        src_range, dst_range), with symmetric partitions expanded into
        their reversed twin — exactly the pair stream `_pairs` yields,
        lifted to ranges."""
        rects = []
        for ev in self.events:
            if ev.kind in NODE_KINDS:
                continue
            sr = sel_indices(ev.src, self.n_nodes)
            dr = sel_indices(ev.dst, self.n_nodes)
            rects.append((ev, sr, dr))
            if ev.kind == "partition" and ev.symmetric:
                rects.append((ev, dr, sr))
        return rects

    def range_link_epochs(self):
        """Range-level twin of `link_epochs` (ISSUE 7 satellite): the
        plan's link parameter-change timeline, grouped into **atoms** —
        (src_range, dst_range, [(round, LinkFault), ...]) rectangles
        over the interval partition induced by every event's selector
        boundaries.  Within an atom every s ≠ d pair has the IDENTICAL
        change list (an event's rectangle is a union of atoms by
        construction), so a driver walks O(atoms · horizon) instead of
        O(pairs · horizon) and only touches per-edge state at install
        time — what lets host-tier parity replay a storm-shaped
        ``"lo:hi"`` `FactoredFaultPlan` without expanding 2.5e9 pairs.
        Epoch indices and parameters match the pairwise walk exactly
        (tests/cluster/test_fault_parity.py pins it), so the installed
        ``derive_seed(seed, "link", src, dst, epoch)`` streams are
        byte-identical."""
        rects = self._link_rects()
        if not rects:
            return []
        n = self.n_nodes
        src_b, dst_b = set(), set()
        for _, sr, dr in rects:
            src_b.update((sr.start, sr.stop))
            dst_b.update((dr.start, dr.stop))
        src_iv = sorted(src_b)
        dst_iv = sorted(dst_b)
        atoms = []
        for s_lo, s_hi in zip(src_iv, src_iv[1:]):
            for d_lo, d_hi in zip(dst_iv, dst_iv[1:]):
                cover = [
                    ev
                    for ev, sr, dr in rects
                    if sr.start <= s_lo
                    and s_hi <= sr.stop
                    and dr.start <= d_lo
                    and d_hi <= dr.stop
                ]
                if not cover:
                    continue
                changes: List[Tuple[int, LinkFault]] = []
                prev = CLEAR
                for r in range(self.horizon + 1):
                    cur = CLEAR
                    for ev in cover:
                        if ev.start <= r < ev.end:
                            cur = cur.merge(_event_link_fault(ev))
                    if cur != prev:
                        changes.append((r, cur))
                        prev = cur
                if changes:
                    atoms.append(
                        (range(s_lo, s_hi), range(d_lo, d_hi), changes)
                    )
        return atoms

    def schedule(self) -> List[RoundSchedule]:
        """Every round of the plan, rounds ``0..horizon`` inclusive (the
        final entry is all-clear by construction — the steady state both
        tiers converge under)."""
        return [self.schedule_at(r) for r in range(self.horizon + 1)]

    def link_epochs(self) -> Dict[Tuple[int, int], List[Tuple[int, LinkFault]]]:
        """Per-link parameter-change points: ``(src, dst) -> [(round,
        params), ...]``.  The index of a change is that link's RNG
        **epoch** — `HostFaultDriver` re-seeds the link's LinkModel at
        every epoch with ``derive_seed(seed, "link", src, dst, epoch)``,
        which is what makes a replay reproduce the exact drop/dup/jitter
        draw sequence regardless of wall-clock timing."""
        epochs: Dict[Tuple[int, int], List[Tuple[int, LinkFault]]] = {}
        prev: Dict[Tuple[int, int], LinkFault] = {}
        for r, sched in enumerate(self.schedule()):
            for pair in set(prev) | set(sched.links):
                cur = sched.links.get(pair, CLEAR)
                if prev.get(pair, CLEAR) != cur:
                    epochs.setdefault(pair, []).append((r, cur))
                    prev[pair] = cur
        return epochs

    def coverage_markers(self) -> List[str]:
        """`sometimes` markers this plan is expected to fire — one per
        fault kind present (the Antithesis coverage property: a campaign
        that never exercised a declared fault is a broken campaign)."""
        return sorted({f"fault-{ev.kind}-active" for ev in self.events})


def demo_plan(n_nodes: int = 3, seed: int = 0, rounds: int = 36) -> FaultPlan:
    """The canonical example campaign (doc/faults.md; the CLI's
    `sim fault-campaign-3node` scenario): a loss burst over everything,
    a mid-run asymmetric partition, delay+jitter on one link, and a
    crash-with-wipe of the last node in the final third."""
    third = rounds // 3
    return FaultPlan(
        n_nodes=n_nodes, seed=seed,
        events=(
            FaultEvent("loss", 0, rounds, p=0.4),
            FaultEvent("partition", third // 2, third, src=n_nodes - 1, dst=0),
            FaultEvent("delay", 2, 2 * third, src=0, dst=1, delay_rounds=1),
            FaultEvent("jitter", 2, 2 * third, src=0, dst=1, delay_rounds=1),
            FaultEvent(
                "crash", 2 * third, rounds - 2, node=n_nodes - 1, wipe=True
            ),
        ),
    )


def plan_to_dict(plan: FaultPlan) -> dict:
    """JSON-safe encoding of a FaultPlan — the ``[faults]`` config
    payload a devcluster parent hands each agent process (ISSUE 15).
    Round-trips exactly through :func:`plan_from_dict`, so the child's
    ``derive_seed`` streams are computed from the identical plan."""
    return {
        "n_nodes": plan.n_nodes,
        "seed": plan.seed,
        "round_s": plan.round_s,
        "events": [dataclasses.asdict(ev) for ev in plan.events],
    }


def plan_from_dict(d: dict) -> FaultPlan:
    """Inverse of :func:`plan_to_dict` (validation re-runs in
    ``FaultEvent.__post_init__`` — a corrupt payload fails loudly)."""
    return FaultPlan(
        n_nodes=int(d["n_nodes"]),
        seed=int(d["seed"]),
        round_s=float(d.get("round_s", 0.05)),
        events=tuple(FaultEvent(**ev) for ev in d["events"]),
    )


def advance_link_epochs(
    epochs: Dict[Tuple[int, int], List[Tuple[int, LinkFault]]],
    epoch_idx: Dict[Tuple[int, int], int],
    r: int,
    install,
) -> None:
    """Walk every link's parameter-change list up to round ``r``,
    calling ``install(src, dst, epoch_index, params)`` at each boundary
    crossed and advancing ``epoch_idx`` in place.

    This is THE shared epoch-indexing rule for every real-time driver
    (host memory AND real sockets): the ``epoch_index`` handed to
    ``install`` is the one a driver folds into ``derive_seed(seed,
    "link", src, dst, epoch)``, so cross-tier seed parity cannot drift
    as long as both drivers route through here."""
    for pair, changes in epochs.items():
        idx = epoch_idx.get(pair, 0)
        while idx < len(changes) and changes[idx][0] <= r:
            _, params = changes[idx]
            install(pair[0], pair[1], idx, params)
            idx += 1
            epoch_idx[pair] = idx


def advance_range_epochs(
    atoms,
    epoch_idx: Dict[int, int],
    r: int,
    install,
) -> None:
    """Range-atom twin of `advance_link_epochs` (ISSUE 7 satellite):
    walk each atom of `FaultPlan.range_link_epochs` up to round ``r``,
    calling ``install(src, dst, epoch_index, params)`` for every s ≠ d
    edge in the atom at each boundary crossed.  Per-edge work happens
    only AT install boundaries (where it is irreducible — the network
    keys LinkModels per edge); the schedule walk itself is O(atoms).
    Every pair in an atom shares one change timeline by construction,
    so the ``epoch_index`` handed to ``install`` — the one drivers fold
    into ``derive_seed(seed, "link", src, dst, epoch)`` — is exactly
    what the pairwise walk would have produced."""
    for a, (src_r, dst_r, changes) in enumerate(atoms):
        idx = epoch_idx.get(a, 0)
        while idx < len(changes) and changes[idx][0] <= r:
            _, params = changes[idx]
            for s in src_r:
                for d in dst_r:
                    if s != d:
                        install(s, d, idx, params)
            idx += 1
            epoch_idx[a] = idx


class CampaignCoverage:
    """Scoped `sometimes` coverage over one campaign: snapshot the pass
    counters at entry, and :meth:`assert_covered` demands every expected
    marker fired SINCE then (the reference's "did every sometimes fire"
    stress-test property, scoped so earlier tests can't donate passes)."""

    def __init__(self, expected: Sequence[str], catalog: Catalog = CATALOG):
        self.expected = sorted(set(expected))
        self.catalog = catalog
        self._at_entry: Dict[str, int] = {}

    def __enter__(self):
        self.catalog.expect_sometimes(*self.expected)
        report = self.catalog.report()
        self._at_entry = {
            name: report.get(name, {}).get("passes", 0) for name in self.expected
        }
        return self

    def __exit__(self, *exc):
        return False

    def unfired(self) -> List[str]:
        report = self.catalog.report()
        return [
            name
            for name in self.expected
            if report.get(name, {}).get("passes", 0) <= self._at_entry[name]
        ]

    def coverage(self) -> float:
        if not self.expected:
            return 1.0
        return 1.0 - len(self.unfired()) / len(self.expected)

    def assert_covered(self):
        missing = self.unfired()
        assert not missing, (
            f"campaign sometimes-coverage {self.coverage():.0%}: "
            f"never fired {missing}"
        )


class HostFaultDriver:
    """Replay a FaultPlan against an in-process cluster.

    One driver round ≈ one sim round: every ``plan.round_s`` of
    wall-clock the driver advances its round counter and installs that
    round's :class:`RoundSchedule` — per-link LinkModels (seed-derived,
    epoch-reset; see :meth:`FaultPlan.link_epochs`), directed partition
    edges on the `MemoryNetwork`, crash/restart/wipe through the
    Cluster, and HLC skew on the target agent's clock.  After the final
    scheduled round everything is healed/cleared, so the cluster can
    converge in the all-clear steady state (the campaign's eventual
    checker runs after :meth:`run` returns).
    """

    def __init__(self, plan: FaultPlan, cluster, catalog: Catalog = CATALOG):
        from .testing import Cluster  # local import: avoid test-dep at import

        assert isinstance(cluster, Cluster)
        if cluster.n != plan.n_nodes:
            raise ValueError(
                f"plan is for {plan.n_nodes} nodes, cluster has {cluster.n}"
            )
        self.plan = plan
        self.cluster = cluster
        self.catalog = catalog
        self.round = -1
        # range atoms, not pairwise link_epochs (ISSUE 7 satellite):
        # a storm-shaped "lo:hi" plan walks O(atoms · horizon), and
        # per-edge LinkModels are only materialized at install time
        self._atoms = plan.range_link_epochs()
        self._epoch_idx: Dict[int, int] = {}
        self._partition_epoch = None  # last applied partition-event set
        self._skewed: Dict[int, object] = {}  # node -> original _now_ns
        self._skew_offset: Dict[int, int] = {}  # node -> installed offset
        self.log: List[Tuple[int, str, object]] = []  # (round, action, detail)

    def _addr(self, i: int) -> str:
        return f"{self.cluster.addr_prefix}{i}"

    def _mark(self, kind: str):
        self.catalog.sometimes(True, f"fault-{kind}-active")

    async def apply_round(self, r: int) -> None:
        """Install round ``r``'s schedule (idempotent per round)."""
        from .agent.transport import LinkModel

        plan, net = self.plan, self.cluster.net
        # node faults only — link state rides the range atoms below
        sched = plan.schedule_at(r, include_links=False)

        # -- link faults: (re)install LinkModels at epoch boundaries
        def install(src, dst, idx, params):
            edge = (self._addr(src), self._addr(dst))
            if params == CLEAR:
                # back to the network's own (per-link derived) model
                net.links.pop(edge, None)
            else:
                base = net.default_link
                net.links[edge] = LinkModel(
                    latency_s=base.latency_s
                    + params.delay_rounds * plan.round_s,
                    loss=1.0 - (1.0 - base.loss) * (1.0 - params.loss),
                    jitter_s=params.jitter_rounds * plan.round_s,
                    duplicate=params.duplicate,
                    seed=derive_seed(plan.seed, "link", src, dst, idx),
                )
            self.log.append((r, "link", ((src, dst), idx, params)))

        advance_range_epochs(self._atoms, self._epoch_idx, r, install)

        # -- coverage markers for whatever is active this round
        for kind in plan.active_kinds_at(r):
            self._mark(kind)

        # -- partitions: the driver owns the directed blocked-edge set,
        # rebuilt only when the ACTIVE partition-event set changes (the
        # pair expansion is the one irreducibly per-edge cost — never
        # pay it for a round whose partitions are unchanged)
        pepoch = plan.partition_epoch(r)
        if pepoch != self._partition_epoch:
            self._partition_epoch = pepoch
            net.partitioned = {
                (self._addr(s), self._addr(d))
                for s, d in plan.blocked_pairs_at(r)
            }

        # -- crash / restart / wipe
        for i in sorted(sched.down):
            if i not in self.cluster.down:
                self.log.append((r, "crash", i))
                # the crashed agent's clock dies with it: a skew spanning
                # the crash re-installs cleanly on the restarted agent
                self._skewed.pop(i, None)
                self._skew_offset.pop(i, None)
                await self.cluster.crash_node(i)
        for i in sorted(sched.restart):
            if i in self.cluster.down:
                self.log.append((r, "restart", (i, i in sched.wipe)))
                await self.cluster.restart_node(i, wipe=i in sched.wipe)

        # -- HLC clock skew (host tier only: the sim has no clock; see
        # doc/faults.md "tier coverage").  Re-installed whenever the
        # SCHEDULED offset moves (overlapping skew events sum, so the
        # offset can change mid-plan) — install-once would freeze the
        # first round's value
        for i, offset in sched.skews.items():
            if i in self.cluster.down or self._skew_offset.get(i) == offset:
                continue
            clock = self.cluster.agents[i].clock
            if i not in self._skewed:
                self._skewed[i] = clock._now_ns
            base = self._skewed[i]
            clock._now_ns = lambda base=base, off=offset: base() + off
            self._skew_offset[i] = offset
            self.log.append((r, "clock_skew", (i, offset)))
        for i in list(self._skewed):
            if i not in sched.skews:
                self.cluster.agents[i].clock._now_ns = self._skewed.pop(i)
                self._skew_offset.pop(i, None)
                self.log.append((r, "clock_skew_clear", i))

        # -- slow gray failure: arm/clear the per-agent stall gate (the
        # agent stays LIVE — its gated operations just crawl; doc/faults.md
        # explains why this kind has no sim twin).  A crashed node's gate
        # dies with the process; the restarted agent starts un-stalled and
        # re-arms here if its slow window is still open.
        for i, stall_rounds in sched.slow.items():
            if i in self.cluster.down:
                continue
            stall_s = stall_rounds * plan.round_s
            agent = self.cluster.agents[i]
            if getattr(agent, "slow_inject_s", 0.0) != stall_s:
                agent.set_slow_inject(stall_s)
                self.log.append((r, "slow", (i, stall_s)))
        for i, agent in enumerate(self.cluster.agents):
            if i in sched.slow or i in self.cluster.down:
                continue
            if getattr(agent, "slow_inject_s", 0.0):
                agent.set_slow_inject(0.0)
                self.log.append((r, "slow_clear", i))

    async def run(self) -> None:
        """Drive the whole schedule in real time, one round per
        ``plan.round_s``; returns with every fault healed."""
        import asyncio

        for r in range(self.plan.horizon + 1):
            self.round = r
            await self.apply_round(r)
            if r < self.plan.horizon:
                await asyncio.sleep(self.plan.round_s)
        sometimes(True, "fault-campaign-completed")


#: fault kinds the raw-socket driver can express at the transport seam
#: (crash/clock_skew are PROCESS-level faults — the devcluster campaign
#: owns those via CORRO_CAMPAIGN_SEED; a transport injector can't kill
#: its own process)
REALSOCKET_KINDS = frozenset(
    {"loss", "delay", "jitter", "duplicate", "partition"}
)


class RealSocketFaultDriver:
    """Compile a FaultPlan onto REAL sockets: the third backend of the
    transport seam.  Each node's `UdpTcpTransport` gets a
    :class:`~corrosion_tpu.agent.transport.FaultInjector`, and per round
    the driver installs that round's :class:`RoundSchedule` into it:

    - **link faults** become per-DESTINATION LinkModel streams on the
      SENDING node's injector, re-seeded at every epoch boundary with
      ``derive_seed(seed, "link", src, dst, epoch)`` — byte-for-byte the
      derivation the host tier's `HostFaultDriver` uses, so the k-th
      decision on a directed edge is the same pure function of
      (seed, src, dst, epoch, k) on BOTH tiers regardless of wall-clock
      timing;
    - **partitions** become the egress ``blocked_peers`` set (installed
      on the src side; a symmetric event lands on both sides via its
      expanded directed pairs), severing established TCP like the
      Antithesis rig's iptables cut;
    - **slow** (the gray failure) stalls a LIVE node's gated operations
      — an AGENT-level fault, so it needs the optional ``agents``
      sequence; scheduling ``slow`` without handing agents over is a
      loud refusal (a transport injector cannot stall its own agent);
    - **crash/clock_skew** are out of scope at this seam
      (`REALSOCKET_KINDS`): they are process-level faults the
      multi-process campaign drives separately.

    ``transports[i]`` is node i's transport, ``addrs[i]`` the gossip
    addr its peers dial it at (the string other nodes pass to
    send_datagram/send_uni/open_bi — blocking and per-dst streams key
    on it).
    """

    def __init__(
        self,
        plan: FaultPlan,
        transports: Sequence,
        addrs: Sequence[str],
        catalog: Catalog = CATALOG,
        agents: Optional[Sequence] = None,
    ):
        from .agent.transport import FaultInjector

        if len(transports) != plan.n_nodes or len(addrs) != plan.n_nodes:
            raise ValueError(
                f"plan is for {plan.n_nodes} nodes, got "
                f"{len(transports)} transports / {len(addrs)} addrs"
            )
        self.agents = list(agents) if agents is not None else None
        if (
            any(ev.kind == "slow" for ev in plan.events)
            and self.agents is None
        ):
            raise ValueError(
                "plan schedules `slow` but no agents= were handed to "
                "RealSocketFaultDriver — the stall gate lives on the "
                "Agent, not the transport injector"
            )
        self.plan = plan
        self.transports = list(transports)
        self.addrs = list(addrs)
        self.catalog = catalog
        self.round = -1
        # range atoms (ISSUE 7 satellite; see HostFaultDriver)
        self._atoms = plan.range_link_epochs()
        self._epoch_idx: Dict[int, int] = {}
        self._partition_epoch = None  # last applied partition-event set
        self.injectors = []
        for t in self.transports:
            fi = FaultInjector()
            t.install_faults(fi)
            self.injectors.append(fi)
        self.log: List[Tuple[int, str, object]] = []

    def apply_round(self, r: int) -> None:
        """Install round ``r``'s schedule into every injector
        (idempotent per round; synchronous — socket injectors mutate
        plain state, no awaits)."""
        from .agent.transport import LinkModel

        plan = self.plan

        # -- link faults: (re)install per-dst LinkModels at epoch bounds
        # (the SAME range-atom walk as HostFaultDriver — the epoch index
        # it hands us is the cross-tier seed-parity anchor)
        def install(src, dst, idx, params):
            inj = self.injectors[src]
            if params == CLEAR:
                inj.links.pop(self.addrs[dst], None)
            else:
                inj.links[self.addrs[dst]] = LinkModel(
                    latency_s=params.delay_rounds * plan.round_s,
                    loss=params.loss,
                    jitter_s=params.jitter_rounds * plan.round_s,
                    duplicate=params.duplicate,
                    seed=derive_seed(plan.seed, "link", src, dst, idx),
                )
            self.log.append((r, "link", ((src, dst), idx, params)))

        advance_range_epochs(self._atoms, self._epoch_idx, r, install)

        # -- partitions: per-src egress blocked sets, rebuilt only at
        # partition-epoch boundaries (see HostFaultDriver.apply_round)
        pepoch = plan.partition_epoch(r)
        if pepoch != self._partition_epoch:
            self._partition_epoch = pepoch
            blocked: Dict[int, set] = {}
            for s, d in plan.blocked_pairs_at(r):
                blocked.setdefault(s, set()).add(self.addrs[d])
            for i, inj in enumerate(self.injectors):
                inj.set_partition(blocked.get(i, set()))

        # -- slow gray failure: arm/clear the per-agent stall gate (only
        # when the caller handed us agents; see __init__'s loud refusal)
        if self.agents is not None:
            slow = plan.schedule_at(r, include_links=False).slow
            for i, agent in enumerate(self.agents):
                stall_s = slow.get(i, 0) * plan.round_s
                if getattr(agent, "slow_inject_s", 0.0) != stall_s:
                    agent.set_slow_inject(stall_s)
                    self.log.append((r, "slow", (i, stall_s)))

        # -- coverage markers for the kinds this seam can express
        for kind in plan.active_kinds_at(r):
            if kind in REALSOCKET_KINDS or (
                kind == "slow" and self.agents is not None
            ):
                self.catalog.sometimes(True, f"fault-{kind}-active")

    async def run(self) -> None:
        """Drive the whole schedule in real time, one round per
        ``plan.round_s``; uninstalls every injector at the end (the
        all-clear steady state)."""
        import asyncio

        for r in range(self.plan.horizon + 1):
            self.round = r
            self.apply_round(r)
            if r < self.plan.horizon:
                await asyncio.sleep(self.plan.round_s)
        self.clear()
        sometimes(True, "fault-campaign-completed")

    def clear(self) -> None:
        for t in self.transports:
            t.install_faults(None)
        if self.agents is not None:
            for agent in self.agents:
                if getattr(agent, "slow_inject_s", 0.0):
                    agent.set_slow_inject(0.0)


#: fault kinds `AgentFaultRuntime` replays INSIDE an agent process —
#: everything except `crash`, which only the parent (the process owner)
#: can express; `devcluster.DEVCLUSTER_KINDS` is the union of both
AGENT_RUNTIME_KINDS = frozenset(
    {"loss", "delay", "jitter", "duplicate", "partition", "slow",
     "clock_skew"}
)


class AgentFaultRuntime:
    """Node-local FaultPlan replay INSIDE one agent process — what makes
    the devcluster the third FULL fault seam (ISSUE 15).

    The devcluster parent can kill -9 a process, but link faults live at
    each node's transport and the `slow`/`clock_skew` gray failures on
    its Agent — all inside the child.  So the parent ships the plan into
    every agent via the ``[faults]`` config section (``plan_to_dict``
    JSON + this node's index + every node's gossip addr in
    ``topo.nodes`` order), and each agent arms one of these runtimes at
    startup:

    - **link faults** install per-destination LinkModel streams into
      this node's own :class:`~corrosion_tpu.agent.transport.FaultInjector`
      through the SAME ``advance_range_epochs`` walk both host drivers
      use.  The walk visits every atom — the install callback merely
      skips edges whose ``src`` isn't this node — so the epoch index
      handed to ``derive_seed(seed, "link", src, dst, epoch)`` is
      exactly what `RealSocketFaultDriver` computes for the same plan:
      the schedule is byte-identical across the process boundary
      (pinned by tests/cluster/test_devcluster_faults.py);
    - **partitions** become this node's egress ``blocked_peers`` set
      (each side of a symmetric cut installs its own direction);
    - **slow / clock_skew** arm the Agent's stall gate / wrap its HLC
      clock, same as `HostFaultDriver`;
    - **crash** stays with the parent — a child cannot respawn itself.

    **Epoch-advance control signal**: the parent's
    `devcluster.DevClusterFaultDriver` atomically publishes the current
    round to ``control_path`` every ``plan.round_s``; the runtime polls
    at twice that cadence and fast-forwards through every boundary ≤ the
    published round.  Because ``advance_range_epochs`` walks
    cumulatively, a node respawned mid-plan re-arms from round 0 state
    straight to the current round — the correct link/partition/slow
    state, with the correct epoch indices.

    Coverage markers are NOT fired here: `sometimes` counters are
    per-process, and the campaign's `CampaignCoverage` lives in the
    parent (the devcluster driver fires them).
    """

    def __init__(
        self,
        plan: FaultPlan,
        node_index: int,
        addrs: Sequence[str],
        transport,
        agent=None,
        control_path: str = "",
    ):
        from .agent.transport import FaultInjector

        if len(addrs) != plan.n_nodes:
            raise ValueError(
                f"plan is for {plan.n_nodes} nodes, got {len(addrs)} addrs"
            )
        if not 0 <= node_index < plan.n_nodes:
            raise ValueError(
                f"node_index {node_index} outside 0..{plan.n_nodes - 1}"
            )
        bad = sorted(
            {ev.kind for ev in plan.events} - AGENT_RUNTIME_KINDS - {"crash"}
        )
        if bad:
            raise ValueError(
                f"agent fault runtime cannot replay {bad} "
                f"(supported: {sorted(AGENT_RUNTIME_KINDS)} + parent-owned "
                "crash)"
            )
        self.plan = plan
        self.node_index = node_index
        self.addrs = list(addrs)
        self.transport = transport
        self.agent = agent
        self.control_path = control_path
        self.round = -1
        self._atoms = plan.range_link_epochs()
        self._epoch_idx: Dict[int, int] = {}
        self._partition_epoch = None
        self._node_sched = any(
            ev.kind in ("slow", "clock_skew") for ev in plan.events
        )
        self._skew_base = None   # original clock._now_ns while skewed
        self._skew_offset = None
        self.injector = FaultInjector()
        transport.install_faults(self.injector)
        self.log: List[Tuple[int, str, object]] = []

    def apply_round(self, r: int) -> None:
        """Fast-forward this node's fault state through every boundary
        ≤ round ``r`` (idempotent; cumulative, so it also serves as the
        respawn-resume path)."""
        from .agent.transport import LinkModel

        plan, me = self.plan, self.node_index

        def install(src, dst, idx, params):
            # the walk advances EVERY atom's epoch index — only the
            # install itself is node-local, so `idx` here matches the
            # all-nodes drivers byte for byte
            if src != me:
                return
            if params == CLEAR:
                self.injector.links.pop(self.addrs[dst], None)
            else:
                self.injector.links[self.addrs[dst]] = LinkModel(
                    latency_s=params.delay_rounds * plan.round_s,
                    loss=params.loss,
                    jitter_s=params.jitter_rounds * plan.round_s,
                    duplicate=params.duplicate,
                    seed=derive_seed(plan.seed, "link", src, dst, idx),
                )
            self.log.append((r, "link", ((src, dst), idx, params)))

        advance_range_epochs(self._atoms, self._epoch_idx, r, install)

        # -- partitions: this node's egress blocked set only
        pepoch = plan.partition_epoch(r)
        if pepoch != self._partition_epoch:
            self._partition_epoch = pepoch
            self.injector.set_partition(
                {
                    self.addrs[d]
                    for s, d in plan.blocked_pairs_at(r)
                    if s == me
                }
            )

        # -- node faults on the local agent (slow stall gate, HLC skew)
        if self.agent is not None and self._node_sched:
            sched = plan.schedule_at(r, include_links=False)
            stall_s = sched.slow.get(me, 0) * plan.round_s
            if getattr(self.agent, "slow_inject_s", 0.0) != stall_s:
                self.agent.set_slow_inject(stall_s)
                self.log.append((r, "slow", stall_s))
            offset = sched.skews.get(me)
            clock = self.agent.clock
            if offset is not None and offset != self._skew_offset:
                if self._skew_base is None:
                    self._skew_base = clock._now_ns
                base = self._skew_base
                clock._now_ns = lambda base=base, off=offset: base() + off
                self._skew_offset = offset
                self.log.append((r, "clock_skew", offset))
            elif offset is None and self._skew_base is not None:
                clock._now_ns = self._skew_base
                self._skew_base = None
                self._skew_offset = None
                self.log.append((r, "clock_skew_clear", me))

    def _read_control(self) -> Optional[dict]:
        try:
            with open(self.control_path) as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            return None  # not written yet / mid-replace on exotic fs

    async def run(self) -> None:
        """Follow the parent's control file until it declares the
        campaign done, then clear every installed fault (the all-clear
        steady state the settle sweep converges under)."""
        import asyncio

        poll_s = max(self.plan.round_s / 2.0, 0.01)
        try:
            while True:
                ctl = self._read_control()
                if ctl is not None:
                    r = int(ctl.get("round", -1))
                    if r > self.round:
                        self.apply_round(r)
                        self.round = r
                    if ctl.get("done"):
                        break
                await asyncio.sleep(poll_s)
        finally:
            self.clear()

    def clear(self) -> None:
        self.transport.install_faults(None)
        if self.agent is not None:
            if getattr(self.agent, "slow_inject_s", 0.0):
                self.agent.set_slow_inject(0.0)
            if self._skew_base is not None:
                self.agent.clock._now_ns = self._skew_base
                self._skew_base = None
                self._skew_offset = None
