"""Dev-cluster harness: topology file → real multi-process cluster.

Rebuild of corro-devcluster (corro-devcluster/src/main.rs:102-240): parse
an ``A -> B`` topology DSL (A bootstraps to B; a bare ``A`` line declares
a node with no links), generate a per-node state dir + TOML config with
the bootstrap edges, spawn one real agent process per node (pure
responders first), tee each node's output to ``<state>/<name>/node.log``,
and supervise until the first node dies or the caller interrupts.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Topology:
    """node → outgoing bootstrap links (Simple in the reference)."""

    links: Dict[str, List[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, text: str) -> "Topology":
        topo = cls()
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if "->" in line:
                # chains are allowed: "A -> B -> C" is the edges A->B, B->C
                parts = [s.strip() for s in line.split("->")]
                if not all(parts):
                    raise ValueError(f"line {lineno}: malformed link {raw!r}")
                for left, right in zip(parts, parts[1:]):
                    topo.links.setdefault(left, []).append(right)
                    topo.links.setdefault(right, [])
            else:
                topo.links.setdefault(line, [])
        if not topo.links:
            raise ValueError("empty topology")
        return topo

    @classmethod
    def load(cls, path: str) -> "Topology":
        with open(path) as f:
            return cls.parse(f.read())

    @property
    def nodes(self) -> List[str]:
        return sorted(self.links)


def generate_config(
    state_dir: str,
    schema_dir: str,
    gossip_port: int,
    api_port: int,
    bootstrap: List[str],
) -> str:
    """Per-node TOML (generate_config, corro-devcluster/src/main.rs:176-208)."""
    boots = ", ".join(f'"{b}"' for b in bootstrap)
    return f"""[db]
path = "{state_dir}/corrosion.db"
schema_paths = ["{schema_dir}"]

[gossip]
addr = "127.0.0.1:{gossip_port}"
bootstrap = [{boots}]

[api]
addr = "127.0.0.1:{api_port}"

[admin]
path = "{state_dir}/admin.sock"
"""


@dataclass
class Node:
    name: str
    state_dir: str
    gossip_port: int
    api_port: int
    proc: Optional[subprocess.Popen] = None

    @property
    def api_addr(self) -> str:
        return f"127.0.0.1:{self.api_port}"


class DevCluster:
    def __init__(self, topo: Topology, state_dir: str, schema_dir: str,
                 base_port: int = 0):
        self.topo = topo
        self.state_dir = state_dir
        self.schema_dir = schema_dir
        self._base_port = base_port
        self.nodes: Dict[str, Node] = {}

    def _alloc_ports(self) -> None:
        import socket

        # hold every probe socket open until ALL ports are assigned —
        # releasing one early lets the OS hand it to the next bind
        held: List["socket.socket"] = []
        try:
            for i, name in enumerate(self.topo.nodes):
                if self._base_port:
                    gp = self._base_port + 2 * i
                    ap = self._base_port + 2 * i + 1
                else:
                    pair = [socket.socket() for _ in range(2)]
                    for s in pair:
                        s.bind(("127.0.0.1", 0))
                    held.extend(pair)
                    gp, ap = (s.getsockname()[1] for s in pair)
                self.nodes[name] = Node(
                    name=name,
                    state_dir=os.path.join(self.state_dir, name),
                    gossip_port=gp,
                    api_port=ap,
                )
        finally:
            for s in held:
                s.close()

    def write_configs(self) -> None:
        self._alloc_ports()
        for name, node in self.nodes.items():
            os.makedirs(node.state_dir, exist_ok=True)
            boots = [
                f"127.0.0.1:{self.nodes[peer].gossip_port}"
                for peer in self.topo.links[name]
            ]
            cfg = generate_config(
                node.state_dir, self.schema_dir, node.gossip_port,
                node.api_port, boots,
            )
            with open(os.path.join(node.state_dir, "config.toml"), "w") as f:
                f.write(cfg)

    def start(self, stagger_s: float = 0.25) -> None:
        """Spawn agents: pure responders (no outgoing links) first
        (run_simple_topology, main.rs:158-168)."""
        order = [n for n in self.topo.nodes if not self.topo.links[n]] + [
            n for n in self.topo.nodes if self.topo.links[n]
        ]
        for name in order:
            node = self.nodes[name]
            # the child inherits the descriptor; close the parent's copy
            with open(os.path.join(node.state_dir, "node.log"), "w") as log:
                node.proc = subprocess.Popen(
                    [
                        sys.executable, "-m", "corrosion_tpu.cli.main",
                        "-c", os.path.join(node.state_dir, "config.toml"),
                        "agent",
                    ],
                    stdout=log,
                    stderr=subprocess.STDOUT,
                )
            time.sleep(stagger_s)

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until every node's log announces readiness."""
        deadline = time.monotonic() + timeout
        for node in self.nodes.values():
            logpath = os.path.join(node.state_dir, "node.log")
            while True:
                if node.proc and node.proc.poll() is not None:
                    raise RuntimeError(
                        f"node {node.name} exited rc={node.proc.returncode}; "
                        f"see {logpath}"
                    )
                try:
                    with open(logpath) as f:
                        if "agent running" in f.read():
                            break
                except FileNotFoundError:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"node {node.name} never became ready")
                time.sleep(0.05)

    def poll_dead(self) -> Optional[Node]:
        for node in self.nodes.values():
            if node.proc and node.proc.poll() is not None:
                return node
        return None

    def stop(self, timeout: float = 15.0) -> None:
        for node in self.nodes.values():
            if node.proc and node.proc.poll() is None:
                node.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout
        for node in self.nodes.values():
            if node.proc:
                try:
                    node.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    node.proc.kill()
                    node.proc.wait()

    def run_forever(self) -> int:
        """Supervise until SIGINT/SIGTERM or the first node death."""
        stop_requested = False

        def _on_term(_sig, _frame):
            nonlocal stop_requested
            stop_requested = True

        prev = signal.signal(signal.SIGTERM, _on_term)
        try:
            while not stop_requested:
                dead = self.poll_dead()
                if dead is not None:
                    print(
                        f"node {dead.name} exited rc={dead.proc.returncode}",
                        file=sys.stderr,
                    )
                    return 1
                time.sleep(0.5)
            return 0
        except KeyboardInterrupt:
            return 0
        finally:
            signal.signal(signal.SIGTERM, prev)
            self.stop()
