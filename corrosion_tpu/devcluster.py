"""Dev-cluster harness: topology file → real multi-process cluster.

Rebuild of corro-devcluster (corro-devcluster/src/main.rs:102-240): parse
an ``A -> B`` topology DSL (A bootstraps to B; a bare ``A`` line declares
a node with no links), generate a per-node state dir + TOML config with
the bootstrap edges, spawn one real agent process per node (pure
responders first), tee each node's output to ``<state>/<name>/node.log``,
and supervise until the first node dies or the caller interrupts.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional


@dataclass
class Topology:
    """node → outgoing bootstrap links (Simple in the reference)."""

    links: Dict[str, List[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, text: str) -> "Topology":
        topo = cls()
        for lineno, raw in enumerate(text.splitlines(), 1):
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            if "->" in line:
                # chains are allowed: "A -> B -> C" is the edges A->B, B->C
                parts = [s.strip() for s in line.split("->")]
                if not all(parts):
                    raise ValueError(f"line {lineno}: malformed link {raw!r}")
                for left, right in zip(parts, parts[1:]):
                    topo.links.setdefault(left, []).append(right)
                    topo.links.setdefault(right, [])
            else:
                topo.links.setdefault(line, [])
        if not topo.links:
            raise ValueError("empty topology")
        return topo

    @classmethod
    def load(cls, path: str) -> "Topology":
        with open(path) as f:
            return cls.parse(f.read())

    @property
    def nodes(self) -> List[str]:
        return sorted(self.links)


def generate_config(
    state_dir: str,
    schema_dir: str,
    gossip_port: int,
    api_port: int,
    bootstrap: List[str],
    flight_path: str = "",
    perf: Optional[Dict[str, object]] = None,
    faults: Optional[Dict[str, object]] = None,
) -> str:
    """Per-node TOML (generate_config, corro-devcluster/src/main.rs:176-208).
    ``flight_path`` arms the node's host flight recorder (ISSUE 13): the
    agent snapshots per-write stage stamps + saturation gauges to that
    JSONL every few seconds, so even a kill -9'd node leaves evidence.
    ``perf`` emits a ``[perf]`` section — how a loadgen campaign pins
    the admission-control / queue bounds it means to stress.
    ``faults`` emits a ``[faults]`` section (ISSUE 15): the FaultPlan
    JSON + this node's index + every node's gossip addr + the parent's
    round control file — what arms the in-process `AgentFaultRuntime`
    so link/slow/clock faults replay INSIDE the agent."""
    boots = ", ".join(f'"{b}"' for b in bootstrap)
    tel = (
        f'\n[telemetry]\nflight_path = "{flight_path}"\n' if flight_path else ""
    )
    if perf:
        lines = "\n".join(
            f"{k} = {json.dumps(v)}" for k, v in sorted(perf.items())
        )
        tel += f"\n[perf]\n{lines}\n"
    if faults:
        # json.dumps doubles as a TOML basic-string/value emitter here:
        # the plan payload is itself a JSON string, escaped once more so
        # quotes inside survive the TOML parse
        lines = "\n".join(
            f"{k} = {json.dumps(v)}" for k, v in sorted(faults.items())
        )
        tel += f"\n[faults]\n{lines}\n"
    return f"""[db]
path = "{state_dir}/corrosion.db"
schema_paths = ["{schema_dir}"]

[gossip]
addr = "127.0.0.1:{gossip_port}"
bootstrap = [{boots}]

[api]
addr = "127.0.0.1:{api_port}"

[admin]
path = "{state_dir}/admin.sock"
{tel}"""


@dataclass
class Node:
    name: str
    state_dir: str
    gossip_port: int
    api_port: int
    proc: Optional[subprocess.Popen] = None

    @property
    def api_addr(self) -> str:
        return f"127.0.0.1:{self.api_port}"


class DevCluster:
    def __init__(self, topo: Topology, state_dir: str, schema_dir: str,
                 base_port: int = 0, flight_recorder: bool = False,
                 perf: Optional[Dict[str, object]] = None,
                 plan=None):
        self.topo = topo
        self.state_dir = state_dir
        self.schema_dir = schema_dir
        self._base_port = base_port
        # arm each node's host flight recorder (ISSUE 13): JSONL
        # snapshots at <state>/<name>/flight.jsonl
        self.flight_recorder = flight_recorder
        # PerfConfig overrides for every node ([perf] TOML section) —
        # the loadgen campaign's admission/queue-bound knobs
        self.perf = dict(perf or {})
        # FaultPlan shipped into every agent via [faults] (ISSUE 15):
        # link/slow/clock kinds replay in-process, driven by the round
        # control file the DevClusterFaultDriver publishes
        self.fault_plan = plan
        self.nodes: Dict[str, Node] = {}

    @property
    def control_path(self) -> str:
        """The epoch-advance control file every agent polls (written
        atomically by `DevClusterFaultDriver`)."""
        return os.path.join(self.state_dir, "faults.round")

    def _alloc_ports(self) -> None:
        import socket

        # hold every probe socket open until ALL ports are assigned —
        # releasing one early lets the OS hand it to the next bind
        held: List["socket.socket"] = []
        try:
            for i, name in enumerate(self.topo.nodes):
                if self._base_port:
                    gp = self._base_port + 2 * i
                    ap = self._base_port + 2 * i + 1
                else:
                    pair = [socket.socket() for _ in range(2)]
                    for s in pair:
                        s.bind(("127.0.0.1", 0))
                    held.extend(pair)
                    gp, ap = (s.getsockname()[1] for s in pair)
                self.nodes[name] = Node(
                    name=name,
                    state_dir=os.path.join(self.state_dir, name),
                    gossip_port=gp,
                    api_port=ap,
                )
        finally:
            for s in held:
                s.close()

    def write_configs(self) -> None:
        self._alloc_ports()
        fault_base: Optional[Dict[str, object]] = None
        if self.fault_plan is not None:
            from .faults import plan_to_dict

            if self.fault_plan.n_nodes != len(self.topo.nodes):
                raise ValueError(
                    f"plan is for {self.fault_plan.n_nodes} nodes, "
                    f"topology has {len(self.topo.nodes)}"
                )
            fault_base = {
                "plan": json.dumps(plan_to_dict(self.fault_plan)),
                # every node's gossip addr in topo.nodes order — plan
                # node indices resolve against THIS list on every node,
                # so src/dst selectors mean the same thing everywhere
                "gossip_addrs": [
                    f"127.0.0.1:{self.nodes[n].gossip_port}"
                    for n in self.topo.nodes
                ],
                "control_path": self.control_path,
            }
        for i, name in enumerate(self.topo.nodes):
            node = self.nodes[name]
            os.makedirs(node.state_dir, exist_ok=True)
            boots = [
                f"127.0.0.1:{self.nodes[peer].gossip_port}"
                for peer in self.topo.links[name]
            ]
            cfg = generate_config(
                node.state_dir, self.schema_dir, node.gossip_port,
                node.api_port, boots,
                flight_path=(
                    os.path.join(node.state_dir, "flight.jsonl")
                    if self.flight_recorder
                    else ""
                ),
                perf=self.perf,
                faults=(
                    {**fault_base, "node_index": i}
                    if fault_base is not None
                    else None
                ),
            )
            with open(os.path.join(node.state_dir, "config.toml"), "w") as f:
                f.write(cfg)

    @property
    def api_addrs(self) -> List[str]:
        """Every node's HTTP API address, in topology-node order — the
        loadgen's write/read address vocabulary."""
        return [self.nodes[n].api_addr for n in self.topo.nodes]

    def _spawn(self, name: str, append_log: bool = False) -> None:
        node = self.nodes[name]
        # the child inherits the descriptor; close the parent's copy
        mode = "a" if append_log else "w"
        with open(os.path.join(node.state_dir, "node.log"), mode) as log:
            node.proc = subprocess.Popen(
                [
                    sys.executable, "-m", "corrosion_tpu.cli.main",
                    "-c", os.path.join(node.state_dir, "config.toml"),
                    "agent",
                ],
                stdout=log,
                stderr=subprocess.STDOUT,
            )

    def start(self, stagger_s: float = 0.25) -> None:
        """Spawn agents: pure responders (no outgoing links) first
        (run_simple_topology, main.rs:158-168)."""
        order = [n for n in self.topo.nodes if not self.topo.links[n]] + [
            n for n in self.topo.nodes if self.topo.links[n]
        ]
        for name in order:
            self._spawn(name)
            time.sleep(stagger_s)

    # -- process-level faults (ISSUE 13) -----------------------------------

    def kill_node(self, name: str) -> None:
        """kill -9 the node's agent process — the FaultPlan ``crash``
        event at the PROCESS seam.  Durable state (sqlite WAL) stays on
        disk, so every ACKED write survives the kill by construction."""
        node = self.nodes[name]
        if node.proc is not None and node.proc.poll() is None:
            node.proc.kill()
            node.proc.wait()

    def restart_node(self, name: str, wipe: bool = False) -> None:
        """Respawn a killed node on its original config/state dir.
        ``wipe=True`` deletes the durable state first (the
        crash-with-wipe rejoin: a cold joiner that must recover purely
        via anti-entropy).  The node keeps its ports, so bootstrap
        edges in the other nodes' configs stay valid."""
        import glob
        import shutil

        node = self.nodes[name]
        if node.proc is not None and node.proc.poll() is None:
            raise RuntimeError(f"node {name} is still running")
        if wipe:
            for path in glob.glob(
                os.path.join(node.state_dir, "corrosion.db*")
            ):
                if os.path.isdir(path):
                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.unlink(path)
        self._spawn(name, append_log=True)

    def wait_ready(self, timeout: float = 30.0) -> None:
        """Block until every node's log announces readiness."""
        deadline = time.monotonic() + timeout
        for node in self.nodes.values():
            logpath = os.path.join(node.state_dir, "node.log")
            while True:
                if node.proc and node.proc.poll() is not None:
                    raise RuntimeError(
                        f"node {node.name} exited rc={node.proc.returncode}; "
                        f"see {logpath}"
                    )
                try:
                    with open(logpath) as f:
                        if "agent running" in f.read():
                            break
                except FileNotFoundError:
                    pass
                if time.monotonic() > deadline:
                    raise TimeoutError(f"node {node.name} never became ready")
                time.sleep(0.05)

    def poll_dead(self) -> Optional[Node]:
        for node in self.nodes.values():
            if node.proc and node.proc.poll() is not None:
                return node
        return None

    def stop(self, timeout: float = 15.0) -> None:
        for node in self.nodes.values():
            if node.proc and node.proc.poll() is None:
                node.proc.send_signal(signal.SIGTERM)
        deadline = time.monotonic() + timeout
        for node in self.nodes.values():
            if node.proc:
                try:
                    node.proc.wait(timeout=max(0.1, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    node.proc.kill()
                    node.proc.wait()

    def fault_driver(self, plan) -> "DevClusterFaultDriver":
        return DevClusterFaultDriver(plan, self)

    def run_forever(self) -> int:
        """Supervise until SIGINT/SIGTERM or the first node death."""
        stop_requested = False

        def _on_term(_sig, _frame):
            nonlocal stop_requested
            stop_requested = True

        prev = signal.signal(signal.SIGTERM, _on_term)
        try:
            while not stop_requested:
                dead = self.poll_dead()
                if dead is not None:
                    print(
                        f"node {dead.name} exited rc={dead.proc.returncode}",
                        file=sys.stderr,
                    )
                    return 1
                time.sleep(0.5)
            return 0
        except KeyboardInterrupt:
            return 0
        finally:
            signal.signal(signal.SIGTERM, prev)
            self.stop()


#: fault kinds the PROCESS seam can express (ISSUE 15: the FULL matrix).
#: ``crash`` is the parent's — only the process owner can SIGKILL and
#: respawn.  Everything else (link faults, the `slow` gray failure,
#: clock skew — faults.AGENT_RUNTIME_KINDS) replays INSIDE each agent
#: via the [faults] config section + the round control file this
#: driver publishes; scheduling those against a cluster that was NOT
#: built with ``plan=`` would silently not inject, so the driver
#: refuses that loudly below.
DEVCLUSTER_KINDS = frozenset(
    {"crash", "loss", "delay", "jitter", "duplicate", "partition",
     "slow", "clock_skew"}
)

#: the subset each agent's in-process runtime owns (parent owns crash)
_IN_AGENT_KINDS = DEVCLUSTER_KINDS - {"crash"}


class DevClusterFaultDriver:
    """Replay a FaultPlan against REAL agent processes — the full fault
    matrix at the process seam (ISSUE 13 crash, ISSUE 15 everything
    else).  One driver round ≈ ``plan.round_s`` of wall clock, the same
    time base as `HostFaultDriver`:

    - ``crash``: a node down over rounds [start, end) is SIGKILLed at
      ``start`` and respawned on its original state dir at ``end``
      (``wipe=True`` deletes the durable state first, the cold-rejoin
      shape);
    - link faults / ``slow`` / ``clock_skew``: the driver only
      PUBLISHES the current round to the cluster's control file
      (atomic replace); each agent's `faults.AgentFaultRuntime` polls
      it and installs its node-local share — including a node respawned
      mid-plan, which fast-forwards through every boundary it missed.

    Crash targets index ``topo.nodes`` order — the same order
    `DevCluster.api_addrs` exposes, so a loadgen can steer watchers
    away from scheduled kills."""

    def __init__(self, plan, cluster: DevCluster):
        n = len(cluster.topo.nodes)
        if plan.n_nodes != n:
            raise ValueError(
                f"plan is for {plan.n_nodes} nodes, devcluster has {n}"
            )
        bad = sorted({ev.kind for ev in plan.events} - DEVCLUSTER_KINDS)
        if bad:
            raise ValueError(
                f"devcluster fault driver replays {sorted(DEVCLUSTER_KINDS)} "
                f"events only (got {bad})"
            )
        in_agent = sorted(
            {ev.kind for ev in plan.events} & _IN_AGENT_KINDS
        )
        if in_agent and cluster.fault_plan is not plan:
            # the agents compile their fault state from the [faults]
            # config section at spawn — a plan the cluster wasn't built
            # with would publish rounds nobody is listening to
            raise ValueError(
                f"plan schedules {in_agent}, which replay INSIDE the "
                "agents: build the DevCluster with plan=<this plan> so "
                "write_configs ships it via [faults]"
            )
        self.plan = plan
        self.cluster = cluster
        self.round = -1
        self.down: set = set()
        self.log: List[tuple] = []  # (round, action, node-name)

    def _publish_round(self, r: int, done: bool = False) -> None:
        """Atomically publish the current round — the epoch-advance
        control signal every agent's fault runtime follows."""
        path = self.cluster.control_path
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps({"round": r, "done": done}))
        os.replace(tmp, path)

    def apply_round(self, r: int) -> None:
        """Install round ``r``'s crash state and publish the round
        (idempotent per round)."""
        sched = self.plan.schedule_at(r, include_links=False)
        names = self.cluster.topo.nodes
        for i in sorted(sched.down):
            if i not in self.down:
                self.down.add(i)
                self.log.append((r, "kill", names[i]))
                self.cluster.kill_node(names[i])
        for i in sorted(sched.restart):
            if i in self.down:
                wipe = i in sched.wipe
                self.log.append((r, "restart", (names[i], wipe)))
                self.cluster.restart_node(names[i], wipe=wipe)
                self.down.discard(i)
        self._publish_round(r, done=r > self.plan.horizon)

    async def run(self) -> None:
        """Drive the schedule in real time; returns with every node
        respawned and every in-agent fault cleared (the all-clear
        steady state the settle checker needs)."""
        import asyncio

        from .invariants import sometimes

        for r in range(self.plan.horizon + 1):
            self.round = r
            # kill/respawn are subprocess signals — fast, but keep them
            # off the loop so a slow spawn can't stall other tasks
            await asyncio.to_thread(self.apply_round, r)
            if r < self.plan.horizon:
                await asyncio.sleep(self.plan.round_s)
        # final control write: done=True tells every agent runtime to
        # clear its injector; give the pollers one cadence to see it
        await asyncio.to_thread(
            self._publish_round, self.plan.horizon + 1, True
        )
        await asyncio.sleep(self.plan.round_s)
        for kind in {ev.kind for ev in self.plan.events}:
            sometimes(True, f"fault-{kind}-active")
        sometimes(True, "fault-campaign-completed")
