"""Changeset chunking for wire transfer.

Rebuild of the reference's ``ChunkedChanges`` iterator
(`corro-types/src/change.rs:66-180`): splits one transaction's ordered
column-change stream into chunks of at most ``max_buf_size`` estimated wire
bytes, each tagged with the exact inclusive seq range it covers so receivers
can gap-track partial versions.  Matches the reference's edge cases (ported
test change.rs:262-402 lives in `tests/core/test_chunker.py`):

- an empty stream still yields one (empty, start..=last_seq) chunk;
- the final chunk's range always extends to ``last_seq``;
- seq gaps inside the stream are absorbed into the chunk ranges;
- a chunk closes early when the next peeked item is absent.

``MAX_CHANGES_BYTE_SIZE`` = 8 KiB (change.rs:180); senders adapt down to
``MIN_CHANGES_BYTE_SIZE`` for slow peers (peer/mod.rs:365-368).
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

from .types import Change, Range

MAX_CHANGES_BYTE_SIZE = 8 * 1024
MIN_CHANGES_BYTE_SIZE = 1024


class ChunkedChanges:
    """Iterator of ``(changes, (start_seq, end_seq))`` chunks."""

    def __init__(
        self,
        changes: Iterable[Change],
        start_seq: int,
        last_seq: int,
        max_buf_size: int = MAX_CHANGES_BYTE_SIZE,
    ):
        self._iter = iter(changes)
        self._peeked: List[Change] = []
        self._start_seq = start_seq
        self._last_seq = last_seq
        self.max_buf_size = max_buf_size
        self._done = False

    def _next_change(self):
        if self._peeked:
            return self._peeked.pop()
        return next(self._iter, None)

    def _peek(self):
        if not self._peeked:
            nxt = next(self._iter, None)
            if nxt is None:
                return None
            self._peeked.append(nxt)
        return self._peeked[-1]

    def __iter__(self) -> Iterator[Tuple[List[Change], Range]]:
        return self

    def __next__(self) -> Tuple[List[Change], Range]:
        if self._done:
            raise StopIteration
        buf: List[Change] = []
        buffered_size = 0
        last_pushed_seq = 0
        while True:
            change = self._next_change()
            if change is None:
                break
            last_pushed_seq = change.seq
            buffered_size += change.estimated_byte_size()
            buf.append(change)
            if last_pushed_seq == self._last_seq:
                break  # that was the last seq of the transaction
            if buffered_size >= self.max_buf_size:
                if self._peek() is None:
                    break  # no more rows: fall through to final chunk
                start = self._start_seq
                self._start_seq = last_pushed_seq + 1
                return buf, (start, last_pushed_seq)
        self._done = True
        return buf, (self._start_seq, self._last_seq)
