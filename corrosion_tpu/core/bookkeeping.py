"""Version bookkeeping: which (actor, db_version) ranges a node has, needs,
or holds partially.

Rebuild of the reference's L2 layer (`corro-types/src/agent.rs:1057-1444`):
``BookedVersions`` (per-origin-actor needed-gap set + partials + max),
``VersionsSnapshot`` (the transactional mutation view whose gap algebra is
persisted alongside the data commit), ``PartialVersion`` (seq-range tracking
for chunked large changesets).

The reference persists gap changes to the `__corro_bookkeeping_gaps` SQLite
table inside the same transaction as the data write (`agent.rs:1108-1168`);
here that's the pluggable ``GapsSink`` so the pure algebra is testable and the
host store provides the SQLite-backed sink.  The algebra itself
(`compute_gaps_change`, `agent.rs:1170-1235`) is reproduced exactly — the
reference's own unit test (`agent.rs:1600-1922`) is ported in
`tests/core/test_bookkeeping.py` and must stay green.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Protocol, Tuple

from .intervals import Range, RangeSet
from .types import ActorId


@dataclass
class PartialVersion:
    """Seq ranges received so far for one buffered (actor, db_version)
    (reference `agent.rs:1057-1075`)."""

    seqs: RangeSet = field(default_factory=RangeSet)
    last_seq: int = 0
    ts: int = 0

    def is_complete(self) -> bool:
        # NOTE: the reference checks gaps over CrsqlSeq(1)..=last_seq
        # (`full_range`, agent.rs:1072) even though seqs start at 0 — seq 0
        # presence is implied by receipt.  We keep 0..=last_seq which is
        # strictly stronger and matches actual usage (sync.rs:324 gaps over
        # 0..=last_seq).
        return next(self.seqs.gaps(0, self.last_seq), None) is None

    def gap_list(self) -> List[Range]:
        return list(self.seqs.gaps(0, self.last_seq))


class GapsSink(Protocol):
    """Persistence hook for gap mutations (the `__corro_bookkeeping_gaps`
    table writes in the reference)."""

    def delete_gap(self, actor_id: ActorId, lo: int, hi: int) -> None: ...

    def insert_gap(self, actor_id: ActorId, lo: int, hi: int) -> None: ...


class NullSink:
    def delete_gap(self, actor_id: ActorId, lo: int, hi: int) -> None:
        pass

    def insert_gap(self, actor_id: ActorId, lo: int, hi: int) -> None:
        pass


NULL_SINK = NullSink()


def _contains(
    needed: RangeSet,
    partials: Dict[int, "PartialVersion"],
    max_: Optional[int],
    version: int,
    seqs: Optional[Range],
) -> bool:
    """Shared known-check (reference `agent.rs:1353-1390`): a version is known
    iff it is not in the needed-gap set and <= max; when a seq range is given
    and the version is held partially, the partial must cover it."""
    if needed.contains(version) or (max_ or 0) < version:
        return False
    if seqs is None:
        return True
    partial = partials.get(version)
    if partial is None:
        return True  # fully applied or cleared
    return partial.seqs.covers(*seqs)


def _contains_all(
    needed: RangeSet,
    partials: Dict[int, "PartialVersion"],
    max_: Optional[int],
    versions: Range,
    seqs: Optional[Range],
) -> bool:
    """Range variant in O(log n + partials-in-range), not O(range width) —
    EMPTY changesets can span millions of versions."""
    lo, hi = versions
    if (max_ or 0) < hi:
        return False
    if next(needed.overlapping(lo, hi), None) is not None:
        return False
    if seqs is None:
        return True
    return all(
        p.seqs.covers(*seqs)
        for v, p in partials.items()
        if lo <= v <= hi
    )


@dataclass
class _GapsChanges:
    """Reference `agent.rs:1439-1444` GapsChanges."""

    max: Optional[int]
    insert_set: RangeSet = field(default_factory=RangeSet)
    remove_ranges: set = field(default_factory=set)  # set[Range] — exact stored ranges


class VersionsSnapshot:
    """Mutable copy of a BookedVersions taken for the duration of one write
    transaction; committed back on success (reference `agent.rs:1092-1236`)."""

    def __init__(
        self,
        actor_id: ActorId,
        needed: RangeSet,
        partials: Dict[int, PartialVersion],
        max_: Optional[int],
    ):
        self.actor_id = actor_id
        self.needed = needed
        self.partials = partials
        self.max = max_

    def insert_gaps(self, ranges: Iterable[Range]) -> None:
        self.needed.extend(ranges)

    def contains_version(self, version: int) -> bool:
        return not self.needed.contains(version) and (self.max or 0) >= version

    def contains(self, version: int, seqs: Optional[Range] = None) -> bool:
        """Same known-check as BookedVersions.contains, against this
        in-transaction view (the reference re-checks inside
        process_multiple_changes, util.rs:704-739)."""
        return _contains(self.needed, self.partials, self.max, version, seqs)

    def contains_all(self, versions: Range, seqs: Optional[Range] = None) -> bool:
        return _contains_all(self.needed, self.partials, self.max, versions, seqs)

    def insert_db(self, sink: GapsSink, db_versions: RangeSet) -> None:
        """Record [ranges of] db_versions as known/applied, updating the
        needed-gap set and persisting gap deletions/insertions through
        ``sink`` (reference `insert_db`, agent.rs:1108-1168)."""
        changes = self._compute_gaps_change(db_versions)

        for lo, hi in changes.remove_ranges:
            sink.delete_gap(self.actor_id, lo, hi)
            for v in range(lo, hi + 1):
                self.partials.pop(v, None)
            self.needed.remove(lo, hi)

        for lo, hi in changes.insert_set:
            sink.insert_gap(self.actor_id, lo, hi)
            self.needed.insert(lo, hi)

        self.max = changes.max

    def _compute_gaps_change(self, versions: RangeSet) -> _GapsChanges:
        """Exact port of reference `compute_gaps_change` (agent.rs:1170-1235)."""
        changes = _GapsChanges(max=self.max)

        for vlo, vhi in versions:
            if changes.max is None or vhi > changes.max:
                changes.max = vhi

            # stored gap ranges overlapping the inserted range get rewritten
            for r in self.needed.overlapping(vlo, vhi):
                changes.insert_set.insert(*r)
                changes.remove_ranges.add(r)

            # collapse an adjacent previous range (end == start - 1)
            prev = self.needed.get(vlo - 1)
            if prev is not None:
                changes.insert_set.insert(*prev)
                changes.remove_ranges.add(prev)

            # collapse an adjacent next range (start == end + 1)
            nxt = self.needed.get(vhi + 1)
            if nxt is not None:
                changes.insert_set.insert(*nxt)
                changes.remove_ranges.add(nxt)

            # a gap opens between the current max and the inserted start
            current_max = self.max if self.max is not None else 0
            gap_start = current_max + 1
            if gap_start < vlo:
                changes.insert_set.insert(gap_start, vlo)
                for r in self.needed.overlapping(gap_start, vlo):
                    changes.insert_set.insert(*r)
                    changes.remove_ranges.add(r)

        for vlo, vhi in versions:
            # the inserted versions themselves are now known
            changes.insert_set.remove(vlo, vhi)

        return changes


class BookedVersions:
    """Per-origin-actor version knowledge (reference `agent.rs:1260-1437`).

    Thread-safe for the concurrent-apply-lane architecture: apply
    sessions run in worker threads (commit_snapshot) while the event
    loop dedups incoming changesets against the same state (contains*).
    An internal lock makes every read see a CONSISTENT
    (needed, partials, max) triple — a torn read can judge a chunk
    "already known" and silently drop it (the round-2 lost-chunk bug:
    expected 173 duplicate frames, observed 177 dedups)."""

    def __init__(self, actor_id: ActorId):
        self.actor_id = actor_id
        self.partials: Dict[int, PartialVersion] = {}
        self._needed = RangeSet()
        self._max: Optional[int] = None
        self._tlock = threading.RLock()

    # -- snapshots --------------------------------------------------------

    def snapshot(self) -> VersionsSnapshot:
        # deep-copy partials: the snapshot mutates them mid-transaction and
        # must not leak into the committed view before commit_snapshot
        with self._tlock:
            return VersionsSnapshot(
                self.actor_id,
                self._needed.copy(),
                {
                    v: PartialVersion(seqs=p.seqs.copy(), last_seq=p.last_seq, ts=p.ts)
                    for v, p in self.partials.items()
                },
                self._max,
            )

    def commit_snapshot(self, snap: VersionsSnapshot) -> None:
        with self._tlock:
            self._needed = snap.needed
            self.partials = snap.partials
            self._max = snap.max

    # -- queries ----------------------------------------------------------

    def contains_version(self, version: int) -> bool:
        """Reference `agent.rs:1353-1362`: known iff not needed and <= max."""
        with self._tlock:
            return not self._needed.contains(version) and (self._max or 0) >= version

    def contains(self, version: int, seqs: Optional[Range] = None) -> bool:
        with self._tlock:
            return _contains(self._needed, self.partials, self._max, version, seqs)

    def contains_all(self, versions: Range, seqs: Optional[Range] = None) -> bool:
        with self._tlock:
            return _contains_all(
                self._needed, self.partials, self._max, versions, seqs
            )

    def last(self) -> Optional[int]:
        with self._tlock:
            return self._max

    def serve_view(self):
        """One CONSISTENT (needed copy, partial version keys, max) triple
        for serve-side computations: the empty-runs derivation in
        _serve_need must not mix attributes from different commits, or a
        freshly committed version can be mis-advertised as cleared."""
        with self._tlock:
            return self._needed.copy(), list(self.partials.keys()), self._max

    def needed(self) -> RangeSet:
        with self._tlock:
            return self._needed.copy()

    def get_partial(self, version: int) -> Optional[PartialVersion]:
        with self._tlock:
            return self.partials.get(version)

    # -- mutation ---------------------------------------------------------

    def insert_partial(self, version: int, partial: PartialVersion) -> PartialVersion:
        """Merge newly received seq ranges for a buffered version
        (reference `agent.rs:1414-1432`)."""
        existing = self.partials.get(version)
        if existing is None:
            if self._max is None or version > self._max:
                self._max = version
            self.partials[version] = partial
            return partial
        existing.seqs.extend(partial.seqs)
        return existing
