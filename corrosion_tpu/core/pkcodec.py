"""Canonical binary encoding for primary keys and SQLite values.

The reference ships pk bytes in cr-sqlite's internal format (opaque on the
wire, e.g. ``x'010901'`` in doc/crdts.md:70).  Ours is a tagged
self-delimiting encoding with the property that equal value tuples encode to
equal bytes (pk identity on the wire and in clock tables).  Not
order-preserving — only equality matters for pks.

Layout per value: 1 tag byte + payload
  0x00 NULL | 0x01 int (8B signed BE) | 0x02 float (8B IEEE BE)
  0x03 str (u32 len + utf8) | 0x04 bytes (u32 len + raw)
A tuple is count byte + concatenated values (pks have <=255 columns).
"""

from __future__ import annotations

import struct
from typing import Sequence, Tuple

from .types import SqliteValue


def encode_value(v: SqliteValue) -> bytes:
    if v is None:
        return b"\x00"
    if isinstance(v, bool) or isinstance(v, int):
        return b"\x01" + struct.pack(">q", int(v))
    if isinstance(v, float):
        return b"\x02" + struct.pack(">d", v)
    if isinstance(v, str):
        b = v.encode("utf-8")
        return b"\x03" + struct.pack(">I", len(b)) + b
    if isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        return b"\x04" + struct.pack(">I", len(b)) + b
    raise TypeError(f"not a SQLite value: {type(v)!r}")


def decode_value(buf: bytes, offset: int = 0) -> Tuple[SqliteValue, int]:
    tag = buf[offset]
    offset += 1
    if tag == 0x00:
        return None, offset
    if tag == 0x01:
        return struct.unpack_from(">q", buf, offset)[0], offset + 8
    if tag == 0x02:
        return struct.unpack_from(">d", buf, offset)[0], offset + 8
    if tag in (0x03, 0x04):
        (n,) = struct.unpack_from(">I", buf, offset)
        offset += 4
        raw = bytes(buf[offset : offset + n])
        return (raw.decode("utf-8") if tag == 0x03 else raw), offset + n
    raise ValueError(f"bad value tag {tag:#x}")


def encode_pk(values: Sequence[SqliteValue]) -> bytes:
    if len(values) > 255:
        raise ValueError("pk too wide")
    return bytes([len(values)]) + b"".join(encode_value(v) for v in values)


def decode_pk(buf: bytes) -> Tuple[SqliteValue, ...]:
    n = buf[0]
    out = []
    offset = 1
    for _ in range(n):
        v, offset = decode_value(buf, offset)
        out.append(v)
    return tuple(out)
