"""Protocol core: the L1/L2 types and algebra shared by the host agent and
the TPU simulator (see SURVEY.md §1 layers L1-L2)."""

from .intervals import Range, RangeSet
from .types import (
    Actor,
    ActorId,
    BroadcastV1,
    Change,
    ChangeSource,
    Changeset,
    ChangesetPart,
    ClusterId,
    SqliteValue,
    SyncNeed,
    SyncState,
)
from .bookkeeping import BookedVersions, PartialVersion, VersionsSnapshot
from .changes import MAX_CHANGES_BYTE_SIZE, MIN_CHANGES_BYTE_SIZE, ChunkedChanges
from .crdt import MergeOutcome, merge_cell, merge_row_cl, row_alive, value_cmp
from .hlc import HLC, ClockDriftError, ntp64_from_unix_ns, ntp64_to_unix_ns
from .sync import compute_available_needs, generate_sync

__all__ = [
    "Actor",
    "ActorId",
    "BookedVersions",
    "BroadcastV1",
    "Change",
    "ChangeSource",
    "Changeset",
    "ChangesetPart",
    "ChunkedChanges",
    "ClusterId",
    "ClockDriftError",
    "HLC",
    "MAX_CHANGES_BYTE_SIZE",
    "MIN_CHANGES_BYTE_SIZE",
    "MergeOutcome",
    "PartialVersion",
    "Range",
    "RangeSet",
    "SqliteValue",
    "SyncNeed",
    "SyncState",
    "VersionsSnapshot",
    "compute_available_needs",
    "generate_sync",
    "merge_cell",
    "merge_row_cl",
    "ntp64_from_unix_ns",
    "ntp64_to_unix_ns",
    "row_alive",
    "value_cmp",
]
