"""Inclusive integer interval-set algebra.

This is the rebuild's equivalent of the reference's `rangemap::RangeInclusiveSet`
(used throughout `crates/corro-types/src/agent.rs` and `sync.rs` for version-gap
and sequence-gap tracking). Semantics matched:

- ``insert`` coalesces overlapping *and adjacent* ranges (1..=3 + 4..=6 -> 1..=6).
- ``remove`` splits stored ranges.
- ``gaps(lo, hi)`` yields maximal uncovered subranges inside [lo, hi].
- ``overlapping(lo, hi)`` yields stored ranges intersecting [lo, hi].
- ``get(v)`` returns the stored range containing v, if any.

Stored ranges are plain ``(lo, hi)`` int tuples, always disjoint,
non-adjacent, and sorted.  All bounds are inclusive.
"""

from __future__ import annotations

import bisect
from typing import Iterable, Iterator, Optional, Tuple

Range = Tuple[int, int]


class RangeSet:
    """A set of disjoint, coalesced, inclusive integer ranges."""

    __slots__ = ("_starts", "_ends")

    def __init__(self, ranges: Iterable[Range] = ()):  # noqa: D107
        self._starts: list[int] = []
        self._ends: list[int] = []
        for lo, hi in ranges:
            self.insert(lo, hi)

    # -- construction -----------------------------------------------------

    def copy(self) -> "RangeSet":
        rs = RangeSet()
        rs._starts = list(self._starts)
        rs._ends = list(self._ends)
        return rs

    def insert(self, lo: int, hi: int) -> None:
        """Insert [lo, hi], coalescing with overlapping/adjacent ranges."""
        if hi < lo:
            raise ValueError(f"invalid range {lo}..={hi}")
        # find all ranges touching [lo-1, hi+1] (adjacency coalesces)
        i = bisect.bisect_left(self._ends, lo - 1)
        j = bisect.bisect_right(self._starts, hi + 1)
        if i < j:
            lo = min(lo, self._starts[i])
            hi = max(hi, self._ends[j - 1])
            del self._starts[i:j]
            del self._ends[i:j]
        self._starts.insert(i, lo)
        self._ends.insert(i, hi)

    def extend(self, other: "RangeSet | Iterable[Range]") -> None:
        for lo, hi in other:
            self.insert(lo, hi)

    def remove(self, lo: int, hi: int) -> None:
        """Remove [lo, hi], splitting stored ranges as needed."""
        if hi < lo:
            raise ValueError(f"invalid range {lo}..={hi}")
        i = bisect.bisect_left(self._ends, lo)
        j = bisect.bisect_right(self._starts, hi)
        if i >= j:
            return
        left: list[Range] = []
        if self._starts[i] < lo:
            left.append((self._starts[i], lo - 1))
        if self._ends[j - 1] > hi:
            left.append((hi + 1, self._ends[j - 1]))
        del self._starts[i:j]
        del self._ends[i:j]
        for k, (s, e) in enumerate(left):
            self._starts.insert(i + k, s)
            self._ends.insert(i + k, e)

    def clear(self) -> None:
        self._starts.clear()
        self._ends.clear()

    # -- queries ----------------------------------------------------------

    def contains(self, v: int) -> bool:
        return self.get(v) is not None

    def get(self, v: int) -> Optional[Range]:
        """The stored range containing v, if any."""
        i = bisect.bisect_left(self._ends, v)
        if i < len(self._starts) and self._starts[i] <= v <= self._ends[i]:
            return (self._starts[i], self._ends[i])
        return None

    def overlapping(self, lo: int, hi: int) -> Iterator[Range]:
        """Stored ranges intersecting [lo, hi] (strict overlap, not adjacency)."""
        i = bisect.bisect_left(self._ends, lo)
        while i < len(self._starts) and self._starts[i] <= hi:
            yield (self._starts[i], self._ends[i])
            i += 1

    def gaps(self, lo: int, hi: int) -> Iterator[Range]:
        """Maximal subranges of [lo, hi] not covered by the set."""
        cur = lo
        for s, e in self.overlapping(lo, hi):
            if s > cur:
                yield (cur, min(s - 1, hi))
            cur = max(cur, e + 1)
            if cur > hi:
                return
        if cur <= hi:
            yield (cur, hi)

    def covers(self, lo: int, hi: int) -> bool:
        """True if every integer of [lo, hi] is in the set."""
        r = self.get(lo)
        return r is not None and r[1] >= hi

    def span_count(self) -> int:
        """Total count of integers covered."""
        return sum(e - s + 1 for s, e in self)

    def first(self) -> Optional[int]:
        return self._starts[0] if self._starts else None

    def last(self) -> Optional[int]:
        return self._ends[-1] if self._ends else None

    # -- dunder -----------------------------------------------------------

    def __iter__(self) -> Iterator[Range]:
        return iter(zip(self._starts, self._ends))

    def __len__(self) -> int:
        return len(self._starts)

    def __bool__(self) -> bool:
        return bool(self._starts)

    def __eq__(self, other: object) -> bool:
        if not isinstance(other, RangeSet):
            return NotImplemented
        return self._starts == other._starts and self._ends == other._ends

    def __hash__(self):  # pragma: no cover - sets aren't hashable containers
        return hash((tuple(self._starts), tuple(self._ends)))

    def __repr__(self) -> str:
        inner = ", ".join(f"{s}..={e}" for s, e in self)
        return f"RangeSet[{inner}]"
