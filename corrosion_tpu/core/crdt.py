"""CRDT merge semantics: per-column last-write-wins + causal-length rows.

This is the rebuild's replacement for the cr-sqlite C extension's merge rules
(reference `doc/crdts.md:15-17,235-248`; loaded at
`corro-types/src/sqlite.rs:121-139`).  The same rules are implemented three
times, deliberately kept in exact agreement:

1. here (Python reference implementation; the spec),
2. `corrosion_tpu/native/crdt_core.cpp` (C++ fast path for bulk applies),
3. `corrosion_tpu/sim/` (vectorised: max-reduction over packed
   (col_version, value_rank, site_id) keys).

Rules for an existing (table, pk, cid) cell receiving an incoming change
(doc/crdts.md:237 — "The order in which crsql checks for which value is
'larger' is: col_version, followed by the value, and finally the site_id"):

1. bigger ``col_version`` wins;
2. tie → bigger value, per SQLite value ordering
   (NULL < INTEGER/REAL numeric < TEXT < BLOB);
3. tie → bigger ``site_id``.

Row existence is governed by causal length ``cl`` (Causal-Length CRDT,
doc/crdts.md:13): odd = alive, even = deleted; bigger cl wins; a delete
resets column state so a resurrected row starts fresh.

With ``merge_equal_values`` (reference `crsql_config_set('merge-equal-values',1)`,
`agent.rs:358-362`): an incoming change that compares exactly equal in
(col_version, value) but loses on site_id is still *recorded* as the winner's
metadata (keeps clocks identical across nodes without dirtying the row).
"""

from __future__ import annotations

from typing import Optional, Tuple

from .types import ActorId, SqliteValue

# SQLite storage-class ranks (BINARY collation semantics).
_RANK_NULL = 0
_RANK_NUMERIC = 1
_RANK_TEXT = 2
_RANK_BLOB = 3


def value_rank(v: SqliteValue) -> int:
    if v is None:
        return _RANK_NULL
    if isinstance(v, bool):  # bools are ints in SQLite
        return _RANK_NUMERIC
    if isinstance(v, (int, float)):
        return _RANK_NUMERIC
    if isinstance(v, str):
        return _RANK_TEXT
    if isinstance(v, (bytes, bytearray, memoryview)):
        return _RANK_BLOB
    raise TypeError(f"not a SQLite value: {type(v)!r}")


def value_cmp(a: SqliteValue, b: SqliteValue) -> int:
    """SQLite ORDER BY semantics: -1/0/+1.

    NULL < numbers (int/real compared numerically) < text (memcmp of UTF-8,
    BINARY collation) < blob (memcmp).
    """
    ra, rb = value_rank(a), value_rank(b)
    if ra != rb:
        return -1 if ra < rb else 1
    if ra == _RANK_NULL:
        return 0
    if ra == _RANK_NUMERIC:
        return -1 if a < b else (1 if a > b else 0)
    if ra == _RANK_TEXT:
        ab, bb = a.encode("utf-8"), b.encode("utf-8")
    else:
        ab, bb = bytes(a), bytes(b)
    return -1 if ab < bb else (1 if ab > bb else 0)


class MergeOutcome:
    """What to do with an incoming change against the current cell state."""

    LOSE = 0  # drop it; local state stands
    WIN = 1  # incoming replaces the cell (value + clock)
    EQUAL_METADATA = 2  # equal (col_version, value): record clock metadata only


def merge_cell(
    existing: Optional[Tuple[int, SqliteValue, ActorId]],
    incoming: Tuple[int, SqliteValue, ActorId],
    merge_equal_values: bool = True,
) -> int:
    """Decide a per-column merge.

    ``existing``/``incoming`` are ``(col_version, value, site_id)``;
    ``existing is None`` means the cell has no recorded clock → incoming wins.
    Returns a MergeOutcome constant.
    """
    if existing is None:
        return MergeOutcome.WIN
    e_ver, e_val, e_site = existing
    i_ver, i_val, i_site = incoming
    if i_ver != e_ver:
        return MergeOutcome.WIN if i_ver > e_ver else MergeOutcome.LOSE
    c = value_cmp(i_val, e_val)
    if c != 0:
        return MergeOutcome.WIN if c > 0 else MergeOutcome.LOSE
    # equal (col_version, value): site id breaks the tie
    if i_site.bytes_ > e_site.bytes_:
        return MergeOutcome.WIN
    if merge_equal_values:
        return MergeOutcome.EQUAL_METADATA
    return MergeOutcome.LOSE


def merge_row_cl(existing_cl: int, incoming_cl: int) -> int:
    """Causal-length merge for row existence: the larger cl wins.

    Returns the merged cl.  Row is alive iff merged cl is odd.
    """
    return max(existing_cl, incoming_cl)


def row_alive(cl: int) -> bool:
    return cl % 2 == 1
