"""Hybrid Logical Clock.

Rebuild of the reference's `uhlc`-based clock (`corro-types/src/broadcast.rs:292`
`Timestamp` = NTP64 wrapper; agent setup at `corro-agent/src/agent/setup.rs:101-106`
creates the HLC with the actor id and a 300 ms max drift delta).

Timestamps are u64 NTP64: upper 32 bits = seconds since UNIX epoch, lower
32 bits = fraction of a second.  The logical component rides in the lowest
bits of the fraction — physical time quantised, bumped monotonically.
"""

from __future__ import annotations

import threading
import time

# Max accepted drift of a remote timestamp ahead of local wall clock
# (reference setup.rs:104: 300 ms).
DEFAULT_MAX_DELTA_NS = 300_000_000

# Low bits of the fraction reserved for the logical counter (uhlc uses the
# full NTP64 with a counter in the low bits; 8 bits = 256 events per ~60ns).
_CMASK = 0xF


def ntp64_from_unix_ns(ns: int) -> int:
    secs, rem = divmod(ns, 1_000_000_000)
    frac = (rem << 32) // 1_000_000_000
    return ((secs & 0xFFFFFFFF) << 32) | (frac & 0xFFFFFFFF)


def ntp64_to_unix_ns(ts: int) -> int:
    secs = ts >> 32
    frac = ts & 0xFFFFFFFF
    return secs * 1_000_000_000 + ((frac * 1_000_000_000) >> 32)


class ClockDriftError(Exception):
    def __init__(self, delta_ns: int):
        super().__init__(f"remote timestamp ahead of local clock by {delta_ns} ns")
        self.delta_ns = delta_ns


class HLC:
    """Monotonic hybrid logical clock producing NTP64 ints."""

    def __init__(self, max_delta_ns: int = DEFAULT_MAX_DELTA_NS, _now_ns=None):
        self._last = 0
        self._lock = threading.Lock()
        self.max_delta_ns = max_delta_ns
        self._now_ns = _now_ns or time.time_ns

    def now(self) -> int:
        """A new timestamp strictly greater than any previously issued."""
        with self._lock:
            phys = ntp64_from_unix_ns(self._now_ns()) & ~_CMASK
            if phys > self._last:
                self._last = phys
            else:
                self._last += 1
            return self._last

    def peek(self) -> int:
        return self._last

    def update(self, remote_ts: int) -> None:
        """Merge a remote timestamp (reference updates the clock on every
        received change / sync handshake).  Raises ClockDriftError when the
        remote is too far ahead of local wall time."""
        with self._lock:
            local_ns = self._now_ns()
            remote_ns = ntp64_to_unix_ns(remote_ts)
            if remote_ns > local_ns + self.max_delta_ns:
                raise ClockDriftError(remote_ns - local_ns)
            if remote_ts > self._last:
                self._last = remote_ts
