"""Anti-entropy need computation.

Rebuild of the reference's sync-state algebra (`corro-types/src/sync.rs`):
``compute_available_needs`` (sync.rs:127-249) decides, given our frontier and
a peer's advertised frontier, exactly which version ranges and partial seq
ranges the peer can supply.  ``generate_sync`` (sync.rs:284-333) builds our
advertisement from the bookie.  The reference's unit test
(sync.rs:380-501) is ported in `tests/core/test_sync_needs.py`.

The same algebra runs vectorised on device: `corrosion_tpu.sim.gaps`
holds the fixed-K gap interval tensors (extract_gaps/gaps_to_mask) and
`corrosion_tpu.sim.sync.edge_needs` evaluates the three need classes per
sampled sync edge.  This module is the scalar spec;
tests/sim/test_gap_kernels.py property-tests the two against each other
on randomized two-node traces (identical effective transfers).
"""

from __future__ import annotations

from typing import Dict, List, Mapping

from .bookkeeping import BookedVersions
from .intervals import RangeSet
from .types import ActorId, SyncNeed, SyncState


def compute_available_needs(
    ours: SyncState, other: SyncState
) -> Dict[ActorId, List[SyncNeed]]:
    """What *we* need that *other* can actually provide.

    Exact port of reference sync.rs:127-249: for each origin actor in the
    peer's heads, build the peer's definitely-fully-held set
    (1..=head minus their needs minus their partials), intersect with our
    needs / partial gaps, then add the head catch-up range.
    """
    needs: Dict[ActorId, List[SyncNeed]] = {}

    def push(actor: ActorId, need: SyncNeed) -> None:
        needs.setdefault(actor, []).append(need)

    for actor_id, head in other.heads.items():
        if actor_id == ours.actor_id:
            continue
        if head == 0:
            continue

        # versions the peer fully has
        other_haves = RangeSet([(1, head)])
        for lo, hi in other.need.get(actor_id, ()):
            other_haves.remove(lo, hi)
        for v in other.partial_need.get(actor_id, {}):
            other_haves.remove(v, v)

        # full-version needs they can serve
        for rlo, rhi in ours.need.get(actor_id, ()):
            for olo, ohi in other_haves.overlapping(rlo, rhi):
                push(actor_id, SyncNeed.full(max(rlo, olo), min(rhi, ohi)))

        # partial (seq-gap) needs
        for v, seqs in ours.partial_need.get(actor_id, {}).items():
            if other_haves.contains(v):
                push(actor_id, SyncNeed.partial(v, list(seqs)))
            else:
                other_seqs = other.partial_need.get(actor_id, {}).get(v)
                if other_seqs is None:
                    continue
                max_other = max((hi for _, hi in other_seqs), default=None)
                max_ours = max((hi for _, hi in seqs), default=None)
                ends = [e for e in (max_other, max_ours) if e is not None]
                if not ends:
                    continue
                end = max(ends)
                # seqs the peer has within the version = 0..=end minus their gaps
                other_seq_haves = RangeSet([(0, end)])
                for lo, hi in other_seqs:
                    other_seq_haves.remove(lo, hi)
                overlap_seqs = [
                    (max(rlo, olo), min(rhi, ohi))
                    for rlo, rhi in seqs
                    for olo, ohi in other_seq_haves.overlapping(rlo, rhi)
                ]
                if overlap_seqs:
                    push(actor_id, SyncNeed.partial(v, overlap_seqs))

        # head catch-up
        our_head = ours.heads.get(actor_id)
        if our_head is None:
            push(actor_id, SyncNeed.full(1, head))
        elif head > our_head:
            push(actor_id, SyncNeed.full(our_head + 1, head))

    return needs


def generate_sync(
    booked_by_actor: Mapping[ActorId, BookedVersions], self_actor_id: ActorId
) -> SyncState:
    """Build our frontier advertisement (reference sync.rs:284-333)."""
    state = SyncState(actor_id=self_actor_id)
    for actor_id, booked in booked_by_actor.items():
        last = booked.last()
        if last is None:
            continue
        need = list(booked.needed())
        if need:
            state.need[actor_id] = need
        for v, partial in booked.partials.items():
            if partial.is_complete():
                continue
            state.partial_need.setdefault(actor_id, {})[v] = partial.gap_list()
        state.heads[actor_id] = last
    return state
