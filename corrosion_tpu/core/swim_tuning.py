"""Cluster-size → SWIM parameter formulas, shared by the host runtime
(`agent/swim.py`) and the simulator (`sim/state.py` ``wan_tuned``).

Rebuild of the reference's cluster-size feedback loop: every membership
change re-derives the SWIM config from the live cluster-size estimate
(`corro-agent/src/broadcast/mod.rs:236-256` FocaInput::ClusterSize →
set_config) and the config constructor scales its timing with that size
(`make_foca_config`, `broadcast/mod.rs:951-960`, built on foca's
WAN-tuned constructor).  We keep the *feedback-loop shape* — live size
in, timing out, re-evaluated on every membership change — with explicit,
documented formulas instead of a third-party constructor:

- **suspicion window** must outlast the longer gossip paths of a bigger
  cluster: classic SWIM scales it with log(N) of the cluster size.
- **probe cadence** stays at the configured base for small clusters and
  stretches gently at storm sizes, bounding per-node probe/ack traffic.
- **per-update transmission budget** (gossip retransmissions AND the
  broadcast relay budget — the reference uses one knob for both) grows
  log2 with size so updates still reach everyone as paths lengthen; the
  configured base is treated as the right budget for a ~32-node cluster
  and is never shrunk (small clusters keep their configured floor).
"""

from __future__ import annotations

import math


def suspicion_factor(n_live: int) -> float:
    """Multiplier on the configured suspicion window: 1.0 for tiny
    clusters, log2(N)/3 beyond ~8 live members."""
    return max(1.0, math.log2(max(2, n_live + 1)) / 3.0)


def probe_interval_factor(n_live: int) -> float:
    """Multiplier on the configured probe period: 1.0 below ~64 live
    members, log2(N)/6 beyond (2x at ~4k, 2.8x at ~100k)."""
    return max(1.0, math.log2(max(2, n_live + 2)) / 6.0)


def max_transmissions_for(n_live: int, base: int) -> int:
    """Per-update transmission budget for a cluster with ``n_live``
    members, where ``base`` is the configured budget (calibrated for
    ~32 nodes).  Grows ~log2, never below ``base``."""
    return max(base, round(base * math.log2(max(2, n_live + 2)) / 5.0))
