"""Schema parsing + constraint checking for CRR tables.

Rebuild of the reference's schema model (`corro-types/src/schema.rs`):
`parse_sql` builds a Table/Column/Index model from schema files
(schema.rs:609-748) and `constrain` rejects shapes that break CRDT
replication (schema.rs:113-168): primary-key expressions, non-nullable
non-PK columns without defaults, foreign keys, and unique indexes.

Instead of hand-writing an SQL parser, the desired schema is executed into
a scratch in-memory SQLite and read back through PRAGMA introspection —
SQLite itself is the parser, so accepted syntax matches the storage engine
exactly.
"""

from __future__ import annotations

import re
import sqlite3
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class SchemaError(Exception):
    """A schema file is invalid or the migration it implies is destructive."""


@dataclass(frozen=True)
class SchemaColumn:
    name: str
    type: str  # uppercased declared type
    notnull: bool
    default: Optional[str]  # DEFAULT expression as SQL text, None if absent
    pk: int  # 0 = not part of the PK, else 1-based ordinal within the PK
    generated: bool = False

    def ddl(self) -> str:
        parts = [f'"{self.name}"']
        if self.type:
            parts.append(self.type)
        if self.notnull:
            parts.append("NOT NULL")
        if self.default is not None:
            parts.append(f"DEFAULT {self.default}")
        return " ".join(parts)


@dataclass(frozen=True)
class SchemaIndex:
    name: str
    table: str
    sql: str


@dataclass
class SchemaTable:
    name: str
    sql: str
    columns: List[SchemaColumn]
    indexes: List[SchemaIndex] = field(default_factory=list)

    @property
    def pk_cols(self) -> Tuple[str, ...]:
        """PK columns in declared PK order (the ordinal, not column order) —
        pk order defines the cross-node pk blob encoding."""
        return tuple(
            c.name for c in sorted((c for c in self.columns if c.pk), key=lambda c: c.pk)
        )

    def shape(self) -> Tuple:
        """Comparable identity used for adopt-or-reject reconciliation
        (schema.rs:343-357: pk mismatch — including PK column order — and
        column mismatch both reject)."""
        return tuple((c.name, c.type, c.notnull, c.default, c.pk) for c in self.columns)

    def column_ddl(self, name: str) -> Optional[str]:
        """The raw column definition text from the CREATE TABLE source —
        used for ALTER TABLE ADD COLUMN so clauses introspection can't
        reconstruct (GENERATED ALWAYS AS, COLLATE, CHECK) survive."""
        paren = _find_body_start(self.sql)
        if paren is None:
            return None
        for item in _split_top_level(self.sql[paren + 1 : _match_paren(self.sql, paren)]):
            first = _first_identifier(item)
            if first is not None and first.lower() == name.lower():
                return item.strip()
        return None


@dataclass
class ParsedSchema:
    tables: Dict[str, SchemaTable]


def table_columns(conn: sqlite3.Connection, name: str) -> List[SchemaColumn]:
    """Introspect a live table into the comparable column model."""
    cols = []
    for row in conn.execute(f'PRAGMA table_xinfo("{name}")'):
        # hidden: 0 normal, 1 hidden, 2/3 generated (virtual/stored)
        hidden = row[6] if len(row) > 6 else 0
        if hidden == 1:
            continue
        cols.append(
            SchemaColumn(
                name=row[1],
                type=(row[2] or "").upper(),
                notnull=bool(row[3]),
                default=row[4],
                pk=row[5],
                generated=hidden in (2, 3),
            )
        )
    return cols


def table_shape(conn: sqlite3.Connection, name: str) -> Tuple:
    return tuple(
        (c.name, c.type, c.notnull, c.default, c.pk)
        for c in table_columns(conn, name)
    )


def _find_body_start(sql: str) -> Optional[int]:
    """Index of the '(' opening the CREATE TABLE column list."""
    in_str = None
    for i, ch in enumerate(sql):
        if in_str:
            if ch == in_str:
                in_str = None
        elif ch in ("'", '"', "`"):
            in_str = ch
        elif ch == "(":
            return i
    return None


def _match_paren(sql: str, start: int) -> int:
    """Index of the ')' matching sql[start] == '(' (string-aware)."""
    depth, in_str = 0, None
    for i in range(start, len(sql)):
        ch = sql[i]
        if in_str:
            if ch == in_str:
                in_str = None
        elif ch in ("'", '"', "`"):
            in_str = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return i
    return len(sql)


def _split_top_level(body: str) -> List[str]:
    """Split a column-list body on depth-0 commas (string-aware)."""
    out, buf, depth, in_str = [], [], 0, None
    for ch in body:
        if in_str:
            buf.append(ch)
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"', "`"):
            in_str = ch
            buf.append(ch)
        elif ch == "(":
            depth += 1
            buf.append(ch)
        elif ch == ")":
            depth -= 1
            buf.append(ch)
        elif ch == "," and depth == 0:
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


_IDENT = re.compile(r'\s*(?:"([^"]+)"|`([^`]+)`|\[([^\]]+)\]|([A-Za-z_][\w$]*))')


def _first_identifier(item: str) -> Optional[str]:
    m = _IDENT.match(item)
    if not m:
        return None
    return next(g for g in m.groups() if g is not None)


_WS = re.compile(r"\s+")


def normalize_sql(sql: str) -> str:
    return _WS.sub(" ", sql.strip().rstrip(";")).lower()


_ALLOWED_STMT = re.compile(r"(?is)^\s*create\s+(table|(unique\s+)?index)\b")
_FORBIDDEN_STMT = re.compile(r"(?is)^\s*create\s+(temp|temporary)\b")
_AS_SELECT = re.compile(r"(?is)\bas\s+select\b")


def strip_comments(sql: str) -> str:
    """Remove -- line and /* */ block comments (outside string literals)."""
    out, i, n, in_str = [], 0, len(sql), None
    while i < n:
        ch = sql[i]
        if in_str:
            out.append(ch)
            if ch == in_str:
                in_str = None
            i += 1
        elif ch in ("'", '"'):
            in_str = ch
            out.append(ch)
            i += 1
        elif ch == "-" and sql[i : i + 2] == "--":
            j = sql.find("\n", i)
            i = n if j == -1 else j  # keep the newline
        elif ch == "/" and sql[i : i + 2] == "/*":
            j = sql.find("*/", i + 2)
            i = n if j == -1 else j + 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def split_statements(sql: str) -> List[str]:
    """Split SQL into statements (semicolons outside string literals);
    comments are stripped first so they neither hide semicolons nor trip
    the statement-kind allowlist."""
    out, buf, in_str = [], [], None
    for ch in strip_comments(sql):
        if in_str:
            buf.append(ch)
            if ch == in_str:
                in_str = None
            continue
        if ch in ("'", '"'):
            in_str = ch
            buf.append(ch)
        elif ch == ";":
            stmt = "".join(buf).strip()
            if stmt:
                out.append(stmt)
            buf = []
        else:
            buf.append(ch)
    stmt = "".join(buf).strip()
    if stmt:
        out.append(stmt)
    return out


def parse_schema(schema_sql: str) -> ParsedSchema:
    """Execute the schema into a scratch DB, introspect, and constrain.

    Only CREATE TABLE / CREATE INDEX statements are allowed in schema files
    — anything else (views, triggers, seed data, temp tables, CREATE TABLE
    AS SELECT) is rejected like the reference's `UnsupportedCmd` /
    `TemporaryTable` errors (schema.rs:667-721)."""
    for stmt in split_statements(schema_sql):
        if _FORBIDDEN_STMT.match(stmt) or not _ALLOWED_STMT.match(stmt):
            raise SchemaError(
                f"unsupported statement in schema (only CREATE TABLE / "
                f"CREATE INDEX are allowed): {stmt[:80]!r}"
            )
        if _AS_SELECT.search(stmt):
            raise SchemaError(
                f"CREATE TABLE ... AS SELECT is not allowed in schemas: "
                f"{stmt[:80]!r}"
            )
    scratch = sqlite3.connect(":memory:")
    try:
        try:
            scratch.executescript(schema_sql)
        except sqlite3.Error as e:
            raise SchemaError(f"invalid schema SQL: {e}") from e

        tables: Dict[str, SchemaTable] = {}
        for name, sql in scratch.execute(
            "SELECT name, sql FROM sqlite_master WHERE type = 'table' "
            "AND name NOT LIKE 'sqlite_%'"
        ).fetchall():
            tables[name] = SchemaTable(
                name=name, sql=sql, columns=table_columns(scratch, name)
            )
        for idx_name, tbl_name, sql, uniq in scratch.execute(
            "SELECT il.name, il.tbl_name, il.sql, ix.\"unique\" FROM sqlite_master il "
            "JOIN pragma_index_list(il.tbl_name) ix ON ix.name = il.name "
            "WHERE il.type = 'index' AND il.sql IS NOT NULL"
        ).fetchall():
            if uniq:
                raise SchemaError(
                    f"unique indexes are not supported for CRRs: {idx_name!r} "
                    "(schema.rs:164)"
                )
            tables[tbl_name].indexes.append(
                SchemaIndex(name=idx_name, table=tbl_name, sql=sql)
            )

        for tbl in tables.values():
            _constrain(scratch, tbl)
        return ParsedSchema(tables=tables)
    finally:
        scratch.close()


def _constrain(scratch: sqlite3.Connection, tbl: SchemaTable) -> None:
    """The reference's `constrain` pass (schema.rs:113-168)."""
    if not tbl.pk_cols:
        raise SchemaError(f"CRR table {tbl.name!r} must have a primary key")
    if scratch.execute(f'PRAGMA foreign_key_list("{tbl.name}")').fetchall():
        raise SchemaError(
            f"foreign keys are not supported for CRRs: table {tbl.name!r} "
            "(schema.rs:155)"
        )
    for col in tbl.columns:
        if col.pk:
            continue
        if col.notnull and col.default is None and not col.generated:
            raise SchemaError(
                f"non-nullable column {tbl.name}.{col.name} needs a DEFAULT "
                "(schema.rs:143)"
            )
    # UNIQUE table constraints surface as unique indexes without sql; catch them
    for row in scratch.execute(f'PRAGMA index_list("{tbl.name}")'):
        if row[2] and row[3] == "u":  # unique, origin 'u' = UNIQUE constraint
            raise SchemaError(
                f"UNIQUE constraints are not supported for CRRs: table "
                f"{tbl.name!r} (schema.rs:164)"
            )
