"""Core protocol types shared by the host agent and the TPU simulator.

Rebuild of the reference's L1 layer (`crates/corro-types/src/actor.rs`,
`broadcast.rs`, `change.rs`, `corro-base-types/src/lib.rs`,
`corro-api-types/src/lib.rs`) as plain Python dataclasses.  These are the
types that become on-device tensors in `corrosion_tpu.sim` — the host agent
and the simulator share this single protocol definition, which is the
rebuild's version of the reference's "same types above the transport seam"
design.

Versions and sequences are plain ints (the reference's `CrsqlDbVersion` /
`CrsqlSeq` u64 newtypes); ranges are inclusive ``(lo, hi)`` tuples matching
`corrosion_tpu.core.intervals.RangeSet` entries.
"""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Tuple, Union

Range = Tuple[int, int]

# SQLite-compatible value: the reference's `SqliteValue`
# (corro-api-types/src/lib.rs:422) — Null / Integer / Real / Text / Blob.
SqliteValue = Union[None, int, float, str, bytes]


# ---------------------------------------------------------------------------
# Identity


@dataclass(frozen=True, order=True)
class ActorId:
    """16-byte unique node identity (reference `actor.rs:26`, crsql site_id)."""

    bytes_: bytes = b"\x00" * 16

    def __post_init__(self):
        if len(self.bytes_) != 16:
            raise ValueError("ActorId must be 16 bytes")

    @classmethod
    def random(cls) -> "ActorId":
        return cls(uuid.uuid4().bytes)

    @classmethod
    def from_hex(cls, s: str) -> "ActorId":
        return cls(bytes.fromhex(s))

    def hex(self) -> str:
        return self.bytes_.hex()

    def short(self) -> str:
        return self.bytes_.hex()[:8]

    def __repr__(self) -> str:
        return f"ActorId({self.short()})"

    def __bool__(self) -> bool:
        return self.bytes_ != b"\x00" * 16


@dataclass(frozen=True, order=True)
class ClusterId:
    """u16 cluster discriminator (reference `actor.rs:222`)."""

    value: int = 0


@dataclass(frozen=True)
class Actor:
    """A cluster member as carried by SWIM (reference `actor.rs:133`)."""

    id: ActorId
    addr: str  # "host:port" gossip address
    ts: int = 0  # HLC timestamp of identity creation/renewal
    cluster_id: ClusterId = ClusterId(0)

    def renew(self, ts: int) -> "Actor":
        """Fresh identity so a down node can rejoin (reference `actor.rs:199`)."""
        return Actor(self.id, self.addr, ts, self.cluster_id)


# ---------------------------------------------------------------------------
# Changes


# Row-deletion sentinel column id.  cr-sqlite uses a special cid for row
# deletes; we use this marker (doc/crdts.md:84 — causal length `cl` tracks
# delete/resurrect; even cl = deleted).
DELETE_SENTINEL = "__crdt_del"
# Pk-only row creation (INSERT with only the primary key, no other columns).
PKONLY_SENTINEL = "__crdt_pko"


@dataclass(frozen=True)
class Change:
    """One column-level CRDT change (reference `change.rs:20`, crsql_changes row).

    ``pk`` is the encoded primary key (opaque bytes on the wire);
    ``cl`` is the causal length: odd = row alive, even = row deleted.
    """

    table: str
    pk: bytes
    cid: str
    val: SqliteValue
    col_version: int
    db_version: int
    seq: int
    site_id: ActorId
    cl: int = 1

    def estimated_byte_size(self) -> int:
        """Rough wire-size estimate used for chunking (reference
        `change.rs:100-130` estimate_bytes)."""
        v = self.val
        if v is None:
            vsz = 1
        elif isinstance(v, (int, float)):
            vsz = 8
        elif isinstance(v, str):
            vsz = len(v.encode("utf-8"))
        else:
            vsz = len(v)
        return (
            len(self.table)
            + len(self.pk)
            + len(self.cid)
            + vsz
            + 8 * 4  # col_version, db_version, seq, cl
            + 16  # site_id
        )


class ChangesetPart(Enum):
    FULL = "full"
    PARTIAL = "partial"
    EMPTY = "empty"


@dataclass(frozen=True)
class Changeset:
    """A (possibly partial) set of changes for one (actor, db_version)
    (reference `broadcast.rs:128` `Changeset::{Empty,Full}` / ChangeV1).

    - FULL: ``changes`` carries the seq range ``seqs``; ``last_seq`` is the
      final seq of the originating transaction — when ``seqs`` spans 0..last_seq
      the version is complete.
    - EMPTY: versions known-cleared (compacted); carries no changes.
    """

    actor_id: ActorId
    version: int  # db_version (lo of `versions` for EMPTY ranges)
    changes: Tuple[Change, ...] = ()
    seqs: Range = (0, 0)
    last_seq: int = 0
    ts: int = 0
    part: ChangesetPart = ChangesetPart.FULL
    # EMPTY uses an inclusive version range (cleared compaction)
    versions_hi: Optional[int] = None

    def is_complete(self) -> bool:
        return self.part is ChangesetPart.EMPTY or (
            self.seqs[0] == 0 and self.seqs[1] == self.last_seq
        )

    @property
    def versions(self) -> Range:
        return (self.version, self.versions_hi if self.versions_hi is not None else self.version)

    def processing_cost(self) -> int:
        """Ingest batching cost (reference `broadcast.rs:182-193`)."""
        if self.part is ChangesetPart.EMPTY:
            lo, hi = self.versions
            return min(hi - lo + 1, 20)
        return len(self.changes)


class ChangeSource(Enum):
    BROADCAST = "broadcast"
    SYNC = "sync"


# ---------------------------------------------------------------------------
# Sync protocol


@dataclass(frozen=True)
class SyncNeed:
    """One need entry (reference `sync.rs:253` SyncNeedV1)."""

    kind: str  # "full" | "partial" | "empty"
    versions: Range = (0, 0)  # for full
    version: int = 0  # for partial
    seqs: Tuple[Range, ...] = ()  # for partial
    ts: Optional[int] = None  # for empty

    @classmethod
    def full(cls, lo: int, hi: int) -> "SyncNeed":
        return cls(kind="full", versions=(lo, hi))

    @classmethod
    def partial(cls, version: int, seqs: List[Range]) -> "SyncNeed":
        return cls(kind="partial", version=version, seqs=tuple(seqs))

    def count(self) -> int:
        """Reference `sync.rs:267-273`."""
        if self.kind == "full":
            return self.versions[1] - self.versions[0] + 1
        return 1


@dataclass
class SyncState:
    """A node's replication frontier advertisement (reference `sync.rs:80`
    SyncStateV1): per-origin heads, needed version ranges, and partial
    (seq-gapped) versions."""

    actor_id: ActorId = field(default_factory=ActorId)
    heads: Dict[ActorId, int] = field(default_factory=dict)
    need: Dict[ActorId, List[Range]] = field(default_factory=dict)
    partial_need: Dict[ActorId, Dict[int, List[Range]]] = field(default_factory=dict)
    last_cleared_ts: Optional[int] = None

    def need_len(self) -> int:
        """Reference `sync.rs:90-109`."""
        full = sum(hi - lo + 1 for v in self.need.values() for lo, hi in v)
        partial = sum(
            hi - lo + 1
            for partials in self.partial_need.values()
            for ranges in partials.values()
            for lo, hi in ranges
        )
        return full + partial // 50

    def need_len_for_actor(self, actor_id: ActorId) -> int:
        """Reference `sync.rs:111-125`."""
        return sum(hi - lo + 1 for lo, hi in self.need.get(actor_id, ())) + len(
            self.partial_need.get(actor_id, {})
        )


# ---------------------------------------------------------------------------
# Gossip payloads (the transport-seam messages; reference broadcast.rs:40-148)


@dataclass(frozen=True)
class BroadcastV1:
    """Uni-stream gossip payload: a changeset being disseminated."""

    changeset: Changeset


@dataclass(frozen=True)
class SwimPayload:
    """Datagram payload: opaque SWIM bytes (the reference hands Foca's bytes
    straight to the wire; our host SWIM does the same)."""

    data: bytes


class MemberEventKind(Enum):
    UP = "up"
    DOWN = "down"
