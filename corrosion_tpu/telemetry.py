"""Host-tier flight recorder: per-write serving-path telemetry.

The sim tier has had a flight recorder since ISSUE 5
(`sim/telemetry.py`): every round of the jitted kernels lands in a
preallocated trace, exported as Prometheus families, span trees, and
flight-recorder JSONL.  The HOST tier — the path "heavy traffic from
millions of users" actually rides (`api/http.py` → `agent/agent.py`
broadcast/sync → `pubsub/manager.py` fan-out) — had a
`metrics.Registry` nobody exercised under load and no record of where
a write's end-to-end latency went.  This module is the host twin:

- :class:`HostFlightRecorder` — per-write stage stamps, keyed by the
  write's replication identity ``(actor, db_version)``:

  * ``publish``        — local commit on the writer (wall + HLC ts);
  * ``broadcast_out``  — the version's first frame hit the wire;
  * ``apply``          — the version committed on an observing node;
  * ``visible``        — the node's matcher fanned the change out to
    attached subscriber queues (the server-side "subscriber-visible"
    moment; the client-observed moment is the loadgen's own clock).

  ``publish → visible`` is SWARM's metric of record for a replicated
  store, and the one the campaign bands regression-track
  (`campaign/spec.py` host-serving cells).

- :class:`HostTelemetry` — the per-agent instrumentation handle: stage
  methods feed the recorder AND the serving metric families
  (histograms on `metrics.LATENCY_BUCKETS`, queue-depth gauges,
  wire-byte counters) on a `metrics.Registry`.  Agents carry
  ``agent.telemetry = None`` by default; every hook site is a single
  attribute check when off, so the uninstrumented serving path stays a
  measured no-op (the `config_serving_loadgen` rung records the
  realized overhead fraction every bench run).

- :func:`write_host_flight_jsonl` — the host flight artifact, sharing
  the PR 5 schema: line 1 a ``{"kind": "flight_recorder", ...}``
  header (with ``"tier": "host"``) + summary, then one JSON line per
  write record.  `sim trace show` renders both tiers.

Clocking across HLC skew (doc/telemetry/host.md): stage stamps come
from ONE process `time.monotonic` (NTP steps must not corrupt sub-ms
stage latencies; the JSONL rows are t0-relative offsets, so no
absolute time is needed), making deltas true latencies in-process;
each stage also records the local HLC reading, so `hlc_lag_s`
survives skewed clocks as the causal (skew-inclusive) proxy — under a
`clock_skew` FaultPlan the monotonic and HLC columns disagree by
exactly the injected offset.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from .core.hlc import ntp64_to_unix_ns

HOST_FLIGHT_TIER = "host"
#: shared with sim/telemetry.py — one flight-record schema, two tiers
FLIGHT_VERSION = 1

#: per-write stage names, in causal order
STAGES = ("publish", "broadcast_out", "apply", "visible")


def _pct(sorted_vals: List[float], q: float) -> Optional[float]:
    """'lower'-interpolation percentile of a pre-sorted list — rank
    floor(q/100 · (n-1)), the SAME rule as numpy's method='lower' that
    `campaign.report.bands` uses, so a lane's p50 and the band that
    summarizes it are computed identically."""
    if not sorted_vals:
        return None
    return sorted_vals[int((len(sorted_vals) - 1) * q / 100.0)]


def latency_block(values: List[float]) -> Optional[dict]:
    """p50/p95/p99/max/mean summary of a latency sample list (seconds),
    None when empty — the shape LoadReport and the campaign serving
    cells both record."""
    if not values:
        return None
    vals = sorted(values)
    return {
        "p50": round(_pct(vals, 50), 6),
        "p95": round(_pct(vals, 95), 6),
        "p99": round(_pct(vals, 99), 6),
        "max": round(vals[-1], 6),
        "mean": round(sum(vals) / len(vals), 6),
        "samples": len(vals),
    }


class _WriteRecord:
    """One write's stage stamps.  ``apply``/``visible`` are per-node
    maps — a 3-node cluster yields up to 3 visibility stamps per
    write; the record's end-to-end latency is the LAST node's."""

    __slots__ = (
        "actor", "version", "node", "publish_s", "publish_hlc",
        "n_changes", "broadcast_out_s", "apply_s", "visible_s",
        "visible_hlc",
    )

    def __init__(self, actor: str, version: int):
        self.actor = actor
        self.version = version
        self.node = ""
        self.publish_s: Optional[float] = None
        self.publish_hlc: Optional[int] = None
        self.n_changes = 0
        self.broadcast_out_s: Optional[float] = None
        self.apply_s: Dict[str, float] = {}
        self.visible_s: Dict[str, float] = {}
        self.visible_hlc: Dict[str, int] = {}

    def to_dict(self, t0: float) -> dict:
        """JSONL row: offsets relative to ``t0`` (the first publish) so
        rows are small and self-aligned; per-stage latencies in ms."""
        out = {
            "actor": self.actor,
            "version": self.version,
            "node": self.node,
            "t": round((self.publish_s or t0) - t0, 6),
            "n_changes": self.n_changes,
        }
        p = self.publish_s
        if p is not None and self.broadcast_out_s is not None:
            out["broadcast_out_ms"] = round(
                (self.broadcast_out_s - p) * 1e3, 3
            )
        if p is not None and self.apply_s:
            out["apply_ms"] = {
                n: round((s - p) * 1e3, 3)
                for n, s in sorted(self.apply_s.items())
            }
        if p is not None and self.visible_s:
            out["visible_ms"] = {
                n: round((s - p) * 1e3, 3)
                for n, s in sorted(self.visible_s.items())
            }
            out["publish_to_visible_ms"] = round(
                (max(self.visible_s.values()) - p) * 1e3, 3
            )
        if self.publish_hlc is not None and self.visible_hlc:
            # the causal proxy: survives skewed wall clocks (NTP64
            # difference → seconds); negative under backward skew
            lag_ns = ntp64_to_unix_ns(
                max(self.visible_hlc.values())
            ) - ntp64_to_unix_ns(self.publish_hlc)
            out["hlc_lag_ms"] = round(lag_ns / 1e6, 3)
        return out


class HostFlightRecorder:
    """Bounded per-write stage-stamp collector, shared by every agent
    of an in-process cluster (each agent's :class:`HostTelemetry` feeds
    it under its own node label).  Thread-safe — the metrics scrape
    path and the event loop may both read it."""

    def __init__(self, cap: int = 65536, clock=time.monotonic):
        self._records: Dict[Tuple[str, int], _WriteRecord] = {}
        self._lock = threading.Lock()
        self.cap = cap
        self.clock = clock
        self.dropped = 0
        # serving saturation side-channel (ISSUE 13): counters (429
        # admissions, slow-consumer disconnects) and high-water gauges
        # (in-flight tx, queue depths) keyed kind -> node — surfaced in
        # `summary()` so every backpressure limit the serving tier
        # enforces is VISIBLE in the host flight JSONL header
        self._sat_counts: Dict[str, Dict[str, float]] = {}
        self._sat_highs: Dict[str, Dict[str, float]] = {}

    def _rec(self, actor: str, version: int) -> Optional[_WriteRecord]:
        key = (actor, version)
        rec = self._records.get(key)
        if rec is None:
            if len(self._records) >= self.cap:
                # drop-oldest keeps the recorder bounded under a flood
                # the consumer never drains; the drop is COUNTED so a
                # truncated summary says so
                self._records.pop(next(iter(self._records)))
                self.dropped += 1
            rec = _WriteRecord(actor, version)
            self._records[key] = rec
        return rec

    # -- stage stamps (called by HostTelemetry; every method is one
    # dict update under the lock — safe from loop or thread) ----------

    def publish(
        self, node: str, actor: str, version: int,
        hlc_ts: Optional[int] = None, n_changes: int = 0,
    ) -> float:
        now = self.clock()
        with self._lock:
            rec = self._rec(actor, version)
            rec.node = node
            rec.publish_s = now
            rec.publish_hlc = hlc_ts
            rec.n_changes = n_changes
        return now

    def broadcast_out(self, node: str, actor: str, version: int) -> Optional[float]:
        """Returns the record's publish stamp ONLY when this call newly
        stamped broadcast_out (None on re-sends), so callers observe the
        publish→wire histogram exactly once per version however many
        flush passes retransmit the frame."""
        now = self.clock()
        with self._lock:
            rec = self._rec(actor, version)
            if rec.broadcast_out_s is not None:
                return None
            rec.broadcast_out_s = now
            return rec.publish_s

    def apply(self, node: str, actor: str, version: int) -> Optional[float]:
        """Publish stamp ONLY on this node's first apply of the version
        (None on retries) — same once-per-stage histogram contract as
        `broadcast_out`."""
        now = self.clock()
        with self._lock:
            rec = self._rec(actor, version)
            if node in rec.apply_s:
                return None
            rec.apply_s[node] = now
            return rec.publish_s

    def visible(
        self, node: str, actor: str, version: int,
        hlc_now: Optional[int] = None,
    ) -> Optional[float]:
        now = self.clock()
        with self._lock:
            rec = self._rec(actor, version)
            if node in rec.visible_s:
                return None
            rec.visible_s[node] = now
            if hlc_now is not None:
                rec.visible_hlc.setdefault(node, hlc_now)
            return rec.publish_s

    # -- saturation side-channel (ISSUE 13) ---------------------------

    def sat_count(self, kind: str, node: str, n: float = 1) -> None:
        """Advance a saturation counter (e.g. ``admission_rejected``,
        ``slow_consumer_disconnects``) for one node."""
        with self._lock:
            per = self._sat_counts.setdefault(kind, {})
            per[node] = per.get(node, 0) + n

    def sat_high(self, kind: str, node: str, value: float) -> None:
        """Record a queue-depth/inflight high-water mark (e.g.
        ``tx_inflight_max``, ``sub_queue_max``)."""
        with self._lock:
            per = self._sat_highs.setdefault(kind, {})
            if value > per.get(node, 0):
                per[node] = value

    def saturation(self) -> dict:
        """The saturation block: ``counters`` (totals + per node) and
        ``high_water`` gauges — deterministic key order."""
        with self._lock:
            return {
                "counters": {
                    kind: {
                        "total": sum(per.values()),
                        "by_node": dict(sorted(per.items())),
                    }
                    for kind, per in sorted(self._sat_counts.items())
                },
                "high_water": {
                    kind: dict(sorted(per.items()))
                    for kind, per in sorted(self._sat_highs.items())
                },
            }

    # -- exports ------------------------------------------------------

    def records(self) -> List[_WriteRecord]:
        with self._lock:
            return list(self._records.values())

    def __len__(self) -> int:
        return len(self._records)

    def summary(self) -> dict:
        """Deterministic-shape summary block (values are measured wall
        clock): stage coverage counts + publish→{broadcast_out, apply,
        visible} latency percentiles across every recorded write."""
        recs = self.records()
        pub = [r for r in recs if r.publish_s is not None]
        bcast, apply_, vis, hlc = [], [], [], []
        for r in pub:
            if r.broadcast_out_s is not None:
                bcast.append(r.broadcast_out_s - r.publish_s)
            if r.apply_s:
                apply_.append(max(r.apply_s.values()) - r.publish_s)
            if r.visible_s:
                vis.append(max(r.visible_s.values()) - r.publish_s)
            if r.publish_hlc is not None and r.visible_hlc:
                hlc.append(
                    (
                        ntp64_to_unix_ns(max(r.visible_hlc.values()))
                        - ntp64_to_unix_ns(r.publish_hlc)
                    )
                    / 1e9
                )
        return {
            "writes": len(pub),
            "records": len(recs),
            "dropped_records": self.dropped,
            "stages": {
                "broadcast_out": len(bcast),
                "apply": len(apply_),
                "visible": len(vis),
            },
            "publish_to_broadcast_out_s": latency_block(bcast),
            "publish_to_apply_s": latency_block(apply_),
            "publish_to_visible_s": latency_block(vis),
            "hlc_lag_s": latency_block(hlc),
            "saturation": self.saturation(),
        }


class HostTelemetry:
    """One agent's serving instrumentation handle: stage methods feed
    the shared :class:`HostFlightRecorder` and the ``corro_serving_*``
    metric families.  Construction registers the families once per
    registry (the `metrics.Registry` dedupes by name); every hook call
    is a couple of dict updates — and the OFF state is
    ``agent.telemetry is None``, a single attribute test."""

    def __init__(
        self,
        node: str,
        recorder: Optional[HostFlightRecorder] = None,
        registry=None,
    ):
        from .metrics import LATENCY_BUCKETS, REGISTRY

        reg = registry if registry is not None else REGISTRY
        self.node = node
        self.recorder = recorder
        self.registry = reg
        lb = LATENCY_BUCKETS
        # per-stage latency histograms (the sub-ms serving ladder)
        self.h_api = reg.histogram("corro_api_request_seconds", lb)
        self.h_commit = reg.histogram("corro_serving_commit_seconds", lb)
        self.h_store = reg.histogram("corro_store_transact_seconds", lb)
        self.h_bcast = reg.histogram(
            "corro_serving_publish_broadcast_seconds", lb
        )
        self.h_apply = reg.histogram(
            "corro_serving_publish_apply_seconds", lb
        )
        self.h_visible = reg.histogram(
            "corro_serving_publish_visible_seconds", lb
        )
        # queue depths
        self.g_ingest_q = reg.gauge("corro_serving_ingest_queue_depth")
        self.g_bcast_q = reg.gauge("corro_serving_bcast_queue_depth")
        self.g_sub_q = reg.gauge("corro_serving_sub_queue_depth")
        # wire bytes / frames by path
        self.c_wire_bytes = reg.counter("corro_serving_wire_bytes_total")
        self.c_wire_frames = reg.counter("corro_serving_wire_frames_total")
        # pubsub fan-out + SWIM membership events
        self.c_fanout = reg.counter("corro_serving_fanout_events_total")
        self.c_swim = reg.counter("corro_serving_swim_events_total")
        # visible stamps dropped because their only deliverer (a
        # fallback matcher) failed its flush — a counted gap, never a
        # fabricated visibility moment
        self.c_vis_dropped = reg.counter(
            "corro_serving_visible_stamps_dropped_total"
        )
        # serving backpressure (ISSUE 13): admission control + the
        # slow-consumer policy, each limit paired with its saturation
        # signal so the flight recorder can SEE degradation
        self.g_tx_inflight = reg.gauge("corro_serving_tx_inflight")
        self.c_admission = reg.counter(
            "corro_serving_admission_rejected_total"
        )
        self.c_slow_consumer = reg.counter(
            "corro_serving_slow_consumer_disconnects_total"
        )
        self.c_write_batches = reg.counter(
            "corro_serving_write_batches_total"
        )

    # -- flight-record stages -----------------------------------------

    def publish(self, actor_id, version: int, hlc_ts: int, n_changes: int):
        if self.recorder is not None:
            self.recorder.publish(
                self.node, actor_id.hex()[:12], version,
                hlc_ts=hlc_ts, n_changes=n_changes,
            )

    def broadcast_out(self, actor_id, version: int):
        if self.recorder is not None:
            pub = self.recorder.broadcast_out(
                self.node, actor_id.hex()[:12], version
            )
            if pub is not None:
                self.h_bcast.observe(self.recorder.clock() - pub)

    def apply(self, actor_id, version: int):
        if self.recorder is not None:
            pub = self.recorder.apply(
                self.node, actor_id.hex()[:12], version
            )
            if pub is not None:
                self.h_apply.observe(
                    self.recorder.clock() - pub, node=self.node
                )

    def visible(self, actor_id, version: int, hlc_now: Optional[int] = None):
        if self.recorder is not None:
            pub = self.recorder.visible(
                self.node, actor_id.hex()[:12], version, hlc_now=hlc_now
            )
            if pub is not None:
                self.h_visible.observe(
                    self.recorder.clock() - pub, node=self.node
                )

    # -- metric-only hooks ---------------------------------------------

    def api_request(self, route: str, seconds: float, bytes_in: int):
        self.h_api.observe(seconds, route=route)
        self.c_wire_bytes.inc(bytes_in, path="api_in", node=self.node)

    def commit(self, seconds: float):
        self.h_commit.observe(seconds, node=self.node)

    def store_transact(self, seconds: float):
        """Whole-store-transaction wall (CrrStore.transact — PG and
        interactive paths included, unlike `commit` which is the
        agent's HTTP write lane)."""
        self.h_store.observe(seconds, node=self.node)

    def wire(self, path: str, nbytes: int):
        """One frame transmitted/received on ``path`` (broadcast_out,
        broadcast_in, sync_out, sync_in)."""
        self.c_wire_bytes.inc(nbytes, path=path, node=self.node)
        self.c_wire_frames.inc(1, path=path, node=self.node)

    def queue_depths(self, ingest: int, bcast: int):
        self.g_ingest_q.set(ingest, node=self.node)
        self.g_bcast_q.set(bcast, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_high("ingest_queue_max", self.node, ingest)
            self.recorder.sat_high("bcast_queue_max", self.node, bcast)

    def sub_fanout(self, n_events: int, max_depth: int):
        if n_events:
            self.c_fanout.inc(n_events, node=self.node)
        self.g_sub_q.set(max_depth, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_high("sub_queue_max", self.node, max_depth)

    # -- backpressure hooks (ISSUE 13) ---------------------------------

    def tx_inflight(self, depth: int):
        """Admission-control occupancy sampled at admit/release."""
        self.g_tx_inflight.set(depth, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_high("tx_inflight_max", self.node, depth)

    def admission_rejected(self):
        """One write refused with 429 + Retry-After (never queued)."""
        self.c_admission.inc(1, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_count("admission_rejected", self.node)

    def slow_consumer(self, n: int):
        """Subscriber queues force-disconnected by the bound."""
        self.c_slow_consumer.inc(n, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_count(
                "slow_consumer_disconnects", self.node, n
            )

    def write_batch(self, n: int):
        """One write-lane drain committed ``n`` admitted writes."""
        self.c_write_batches.inc(1, node=self.node)
        if self.recorder is not None:
            self.recorder.sat_high("write_batch_max", self.node, n)

    def swim_event(self, event: str):
        self.c_swim.inc(1, event=event, node=self.node)

    def visible_dropped(self, n: int):
        self.c_vis_dropped.inc(n, node=self.node)


def attach_host_telemetry(
    agent,
    node: Optional[str] = None,
    recorder: Optional[HostFlightRecorder] = None,
    registry=None,
) -> HostTelemetry:
    """Arm one agent's serving instrumentation: sets
    ``agent.telemetry`` (read by the api/agent/swim hook sites) and
    threads the handle into the pubsub managers.  ``node`` defaults to
    the agent's transport address; pass one shared ``recorder`` across
    a cluster so cross-node stages land in the same write records."""
    node = node or getattr(agent.transport, "addr", "") or agent.actor_id.hex()[:12]
    tel = HostTelemetry(node, recorder=recorder, registry=registry)
    agent.telemetry = tel
    agent.subs.telemetry = tel
    agent.updates.telemetry = tel
    agent.store.telemetry = tel
    return tel


def detach_host_telemetry(agent) -> None:
    agent.telemetry = None
    agent.subs.telemetry = None
    agent.updates.telemetry = None
    agent.store.telemetry = None


def write_host_flight_jsonl(
    path: str,
    recorder: HostFlightRecorder,
    header: Optional[dict] = None,
) -> None:
    """The host flight artifact, sharing the sim recorder's schema
    (`sim/telemetry.write_flight_jsonl`): line 1 a header dict —
    ``kind: flight_recorder``, ``version``, ``tier: host``, summary,
    caller context — then one JSON line per write record, publish-time
    ordered.  Atomic replace like every artifact writer in the tree."""
    recs = sorted(
        (r for r in recorder.records() if r.publish_s is not None),
        key=lambda r: (r.publish_s, r.actor, r.version),
    )
    t0 = recs[0].publish_s if recs else 0.0
    head = {
        "kind": "flight_recorder",
        "version": FLIGHT_VERSION,
        "tier": HOST_FLIGHT_TIER,
        "writes": len(recs),
        "summary": recorder.summary(),
    }
    if header:
        head.update(header)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(head, sort_keys=True, default=float) + "\n")
        for rec in recs:
            f.write(json.dumps(rec.to_dict(t0), sort_keys=True) + "\n")
    os.replace(tmp, path)
