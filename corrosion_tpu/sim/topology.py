"""Topology and link model for the simulator.

The reference tiers peers into RTT rings (members.rs:38: [0,6) [6,15) [15,50)
[50,100) [100,200) [200,300) ms) and broadcasts ring-0 first; the sim maps
rings onto round-delay classes (one round ≈ the 500 ms flush tick, so WAN
rings land in delay 1-2 rounds, ICI-local in 0).

Nodes get a static ``region[N]`` label; the delay class of an edge is 0
within a region and grows with region distance.  Partitions cut edges whose
endpoints are in different ``group``s (healing resets groups to 0).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static per-scenario topology parameters."""

    n_regions: int = 1
    intra_delay: int = 0  # rounds
    inter_delay: int = 1  # rounds
    loss: float = 0.0  # per-message drop probability


def regions(n_nodes: int, n_regions: int) -> jnp.ndarray:
    """Contiguous region assignment (Fly.io-style geographic pools)."""
    per = max(1, n_nodes // n_regions)
    return jnp.minimum(jnp.arange(n_nodes, dtype=jnp.int32) // per, n_regions - 1)


def edge_delay(
    topo: Topology, region: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Delay class (rounds) per edge, from region distance."""
    same = region[src] == region[dst]
    return jnp.where(same, topo.intra_delay, topo.inter_delay).astype(jnp.int32)


def edge_alive(
    group: jnp.ndarray, alive: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Reachability mask per edge: same partition group, both endpoints up."""
    from .state import ALIVE

    return (
        (group[src] == group[dst])
        & (alive[src] == ALIVE)
        & (alive[dst] == ALIVE)
    )


def edge_payload_drop(
    topo: Topology, key: jax.Array, n_edges: int, n_payloads: int
) -> jnp.ndarray:
    """Per-(edge, payload) Bernoulli loss for fire-and-forget traffic.

    Each broadcast changeset rides its own uni frame (the reference
    length-delimits changesets individually inside the flush,
    broadcast/mod.rs:529-571; the host tier's LinkModel drops per
    send_uni call), so loss must be drawn per payload, not per edge —
    one edge-level draw would make 20 versions share a single coin flip
    and collapse the retransmission dynamics the calibration tier
    measures.  Free when loss == 0 (trace-time constant zeros).

    The draw is an 8-bit threshold compare (`random.bits < p*256`), not
    bernoulli's f32 uniform: the [E, P] mask is the lossy configs'
    biggest per-round tensor (100M cells at the gapstress shape) and u8
    bits cost 4× less RNG + HBM traffic.  Loss probabilities quantize
    to 1/256 steps (0.3 → 0.30078) — three orders of magnitude below
    the ×1.5 calibration bands."""
    threshold = int(round(topo.loss * 256.0))
    if topo.loss <= 0.0 or threshold == 0:
        # loss below 1/512 quantizes to zero drops — return the free
        # constant mask rather than drawing a pointless all-False tensor
        return jnp.zeros((n_edges, n_payloads), jnp.bool_)
    if threshold >= 256:
        # loss ≈ 1.0: a severed channel must stay severed (u8 compare
        # can't express an always-true threshold)
        return jnp.ones((n_edges, n_payloads), jnp.bool_)
    bits = aligned_u8_bits(key, (n_edges, n_payloads))
    return bits < jnp.uint8(threshold)


def aligned_u8_bits(key, shape) -> jnp.ndarray:
    """u8 threefry draw whose u32→u8 unpack stays WORD-ALIGNED per
    shard (ISSUE 7).  jax lowers a u8 bits draw of flat size S through
    a ceil(S/4) u32 intermediate; when a node-sharded consumer makes
    GSPMD partition that production on a non-word-aligned boundary
    (e.g. S = 1008 over 8 devices → 31.5 words per shard), this
    jax/XLA version produces bit values that DIFFER from the
    single-device draw — silently, and only at shard-unaligned sizes
    (tests/sim/test_packed_sharded.py would catch the drift as a
    sharded-vs-single mismatch in the loss masks).  Padding the flat
    draw to a multiple of 128 bytes (32 words — word-aligned for every
    power-of-two mesh up to 32 devices) and slicing keeps the unpack
    word-aligned under any such partitioning.  Sizes already
    128-aligned take the identical unpadded draw, so every storm-scale
    [E, P] mask (P a multiple of 128) is byte-identical to prior
    builds; only shard-unaligned shapes (small-N tests, non-128-aligned
    clusters) re-roll."""
    size = 1
    for d in shape:
        size *= int(d)
    if size % 128 == 0:
        return jax.random.bits(key, shape, dtype=jnp.uint8)
    pad = -(-size // 128) * 128
    flat = jax.random.bits(key, (pad,), dtype=jnp.uint8)
    return flat[:size].reshape(shape)
