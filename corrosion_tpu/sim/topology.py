"""Topology and link model for the simulator.

The reference tiers peers into RTT rings (members.rs:38: [0,6) [6,15) [15,50)
[50,100) [100,200) [200,300) ms) and broadcasts ring-0 first; the sim maps
rings onto round-delay classes (one round ≈ the 500 ms flush tick, so WAN
rings land in delay 1-2 rounds, ICI-local in 0).

Nodes get a static ``region[N]`` label; the delay class of an edge is 0
within a region and grows with region distance.  Partitions cut edges whose
endpoints are in different ``group``s (healing resets groups to 0).

Since ISSUE 9 the topology is **geo-tiered**: a region subdivides into
``n_azs`` availability zones (the Fly.io deployment shape — region × AZ
latency/loss classes), so an edge has THREE delay/loss classes: same-AZ
(``intra_delay``/``loss``), cross-AZ within a region
(``az_delay``/``az_loss``), and cross-region
(``inter_delay``/``inter_loss``).  ``degree_classes`` assigns
heterogeneous broadcast fan-out caps per node (hub/leaf shapes).  Every
new field defaults to the legacy single-tier behavior and the kernels
branch at trace time, so default-topology runs compile to byte-identical
programs (tests/sim/test_topo.py pins the digests).  Named topology
families live in `corrosion_tpu.topo.families`; churn schedules and the
host-tier compilation of a tiered topology in `corrosion_tpu.topo`.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static per-scenario topology parameters (hashable: jit key)."""

    n_regions: int = 1
    intra_delay: int = 0  # rounds, same-AZ (same-region pre-ISSUE 9)
    inter_delay: int = 1  # rounds, cross-region
    loss: float = 0.0  # per-message drop probability, same-AZ edges
    # -- geo-tiered WAN (ISSUE 9); defaults = the legacy single tier ----
    n_azs: int = 1  # availability zones per region (region × AZ grid)
    az_delay: int = 0  # rounds, cross-AZ within a region
    # cross-AZ / cross-region loss: 0.0 = inherit the base ``loss``
    # (so a flat lossy topology stays ONE class and compiles to the
    # legacy scalar-threshold kernel); > 0 overrides for that tier
    az_loss: float = 0.0
    inter_loss: float = 0.0
    # heterogeneous broadcast fan-out: per-class degree caps assigned
    # round-robin over node ids (node n sends to at most
    # degree_classes[n % len] of its cfg.fanout slots); () = every node
    # uses the full fanout.  Values are validated ≤ cfg.fanout by
    # `round.validate` — a class above the slot count would silently
    # clamp, not expand.
    degree_classes: Tuple[int, ...] = ()
    # measured-RTT WAN matrix (ISSUE 13 satellite): per-(region,
    # region) delay classes in ROUNDS, quantized from a real RTT table
    # (`corrosion_tpu.topo.FLY_RTT_MS` → the ``wan-fly-6r`` family).
    # () = the 3-class tier model above; non-empty replaces the
    # region-distance rule entirely (so it requires n_azs == 1 — a
    # measured matrix and the AZ tier model would double-count), and
    # the kernels branch at trace time, so matrix-free topologies
    # compile byte-identically.
    region_delay_matrix: Tuple[Tuple[int, ...], ...] = ()

    def __post_init__(self):
        object.__setattr__(
            self, "degree_classes",
            tuple(int(d) for d in self.degree_classes),
        )
        # JSON round-trips the matrix as nested lists; jit keys need
        # nested tuples
        object.__setattr__(
            self, "region_delay_matrix",
            tuple(
                tuple(int(d) for d in row)
                for row in self.region_delay_matrix
            ),
        )
        if self.n_regions < 1 or self.n_azs < 1:
            raise ValueError("n_regions and n_azs must be >= 1")
        for name in ("loss", "az_loss", "inter_loss"):
            p = getattr(self, name)
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"{name}={p} outside [0, 1]")
        if any(d < 1 for d in self.degree_classes):
            raise ValueError("degree_classes entries must be >= 1")
        if self.region_delay_matrix:
            m = self.region_delay_matrix
            if len(m) != self.n_regions or any(
                len(row) != self.n_regions for row in m
            ):
                raise ValueError(
                    f"region_delay_matrix must be {self.n_regions}×"
                    f"{self.n_regions} (n_regions rows and columns)"
                )
            if self.n_azs != 1:
                raise ValueError(
                    "region_delay_matrix replaces the tier model — it "
                    "needs n_azs == 1 (AZ classes would double-count)"
                )
            if any(d < 0 for row in m for d in row):
                raise ValueError("region_delay_matrix entries must be >= 0")

    @property
    def max_delay(self) -> int:
        if self.region_delay_matrix:
            return max(d for row in self.region_delay_matrix for d in row)
        return max(self.intra_delay, self.az_delay, self.inter_delay)


def regions(n_nodes: int, n_regions: int) -> jnp.ndarray:
    """Contiguous region assignment (Fly.io-style geographic pools)."""
    per = max(1, n_nodes // n_regions)
    return jnp.minimum(jnp.arange(n_nodes, dtype=jnp.int32) // per, n_regions - 1)


def azs(n_nodes: int, topo: Topology) -> jnp.ndarray:
    """i32[N] global AZ id = region * n_azs + local AZ — contiguous AZ
    blocks inside each contiguous region block (the same block rule as
    `regions`, one level down), so range selectors cover an AZ exactly
    (`corrosion_tpu.topo.topology_link_events` relies on it)."""
    per_r = max(1, n_nodes // topo.n_regions)
    reg = regions(n_nodes, topo.n_regions)
    local = jnp.arange(n_nodes, dtype=jnp.int32) - reg * per_r
    per_az = max(1, per_r // topo.n_azs)
    az_local = jnp.minimum(local // per_az, topo.n_azs - 1)
    return reg * topo.n_azs + az_local


def node_degrees(n_nodes: int, topo: Topology) -> jnp.ndarray:
    """i32[N] per-node broadcast fan-out caps from ``degree_classes``
    (round-robin over node ids — deterministic, seed-free, and stable
    under resharding).  Callers only reach here when the tuple is
    non-empty (a trace-time fact)."""
    classes = jnp.asarray(topo.degree_classes, jnp.int32)
    return classes[jnp.arange(n_nodes, dtype=jnp.int32) % len(topo.degree_classes)]


def apply_degree_caps(
    targets: jnp.ndarray, topo: Topology
) -> jnp.ndarray:
    """Mask fan-out target slots past each node's degree cap to -1 (the
    unfilled-slot sentinel every consumer already handles).  Trace-time
    identity when ``degree_classes`` is empty — the legacy uniform
    fan-out compiles unchanged."""
    if not topo.degree_classes:
        return targets
    n, f = targets.shape
    deg = node_degrees(n, topo)  # [N]
    slot = jnp.arange(f, dtype=jnp.int32)[None, :]
    return jnp.where(slot < deg[:, None], targets, -1)


def edge_delay(
    topo: Topology, region: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Delay class (rounds) per edge, from region (and AZ) distance.
    Single-AZ topologies compile the exact legacy two-class expression
    (a trace-time branch — default runs stay byte-identical).  A
    measured-RTT ``region_delay_matrix`` (ISSUE 13) replaces the
    distance rule with a per-(region, region) gather — same trace-time
    branching discipline."""
    if topo.region_delay_matrix:
        m = jnp.asarray(topo.region_delay_matrix, jnp.int32)
        return m[region[src], region[dst]]
    same_r = region[src] == region[dst]
    if topo.n_azs <= 1:
        return jnp.where(same_r, topo.intra_delay, topo.inter_delay).astype(
            jnp.int32
        )
    az = azs(region.shape[0], topo)
    same_az = az[src] == az[dst]
    return jnp.where(
        same_r,
        jnp.where(same_az, topo.intra_delay, topo.az_delay),
        topo.inter_delay,
    ).astype(jnp.int32)


def edge_alive(
    group: jnp.ndarray, alive: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Reachability mask per edge: same partition group, both endpoints up."""
    from .state import ALIVE

    return (
        (group[src] == group[dst])
        & (alive[src] == ALIVE)
        & (alive[dst] == ALIVE)
    )


def _thr(p: float) -> int:
    """Loss probability → the u8 compare threshold (p·256, the repo-wide
    8-bit loss quantization)."""
    return int(round(p * 256.0))


def loss_tiers(topo: Topology) -> Tuple[int, int, int]:
    """(same-AZ, cross-AZ, cross-region) u8 drop thresholds.  A tier
    loss of 0.0 inherits the base ``loss`` (see the field docs)."""
    base = _thr(topo.loss)
    az = _thr(topo.az_loss) if topo.az_loss > 0 else base
    inter = _thr(topo.inter_loss) if topo.inter_loss > 0 else base
    return base, az, inter


def loss_tiered(topo: Topology) -> bool:
    """Trace-time fact: do the loss tiers actually differ?  False keeps
    the legacy single-threshold kernel (byte-identical draws)."""
    base, az, inter = loss_tiers(topo)
    tiers = {base}
    if topo.n_azs > 1:
        tiers.add(az)
    if topo.n_regions > 1:
        tiers.add(inter)
    return len(tiers) > 1


def edge_loss_thresholds(
    topo: Topology,
    region: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
) -> jnp.ndarray:
    """u8[E] per-edge drop thresholds from the geo tiers (callers gate
    on `loss_tiered` — the flat case never builds this tensor).  The u8
    compare saturates at 255: a certainty tier (p·256 ≥ 256) must ALSO
    be pinned via `edge_loss_thresholds_raw` — there is exactly one
    tier-selection expression (the raw form), so the two views cannot
    drift."""
    return jnp.minimum(
        edge_loss_thresholds_raw(topo, region, src, dst), 255
    ).astype(jnp.uint8)


def tiered_edge_drop(
    topo: Topology,
    key: jax.Array,
    region: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    shape,
) -> jnp.ndarray:
    """bool[shape] tiered drop decisions — the ONE implementation of
    the three-step rule (clamped-threshold compare on an aligned draw,
    plus the raw ``>= 256`` certainty pin) shared by the per-payload
    wire path (`edge_payload_drop`) and the probe/swap path
    (`swim._reachable`), so the two loss seams cannot drift.  ``shape``
    leads with the edge axis; per-edge thresholds broadcast over any
    trailing axes (the per-payload grain)."""
    thr = edge_loss_thresholds(topo, region, src, dst)  # u8[E]
    extra = (1,) * (len(shape) - 1)
    bits = aligned_u8_bits(key, shape)
    drop = bits < thr.reshape(thr.shape + extra)
    if max(loss_tiers(topo)) >= 256:
        # a certainty tier saturates the u8 compare at 255/256 — pin
        # those edges fully dropped (the legacy threshold>=256 rule)
        raw = edge_loss_thresholds_raw(topo, region, src, dst)
        drop = drop | (raw >= 256).reshape(raw.shape + extra)
    return drop


def edge_payload_drop(
    topo: Topology,
    key: jax.Array,
    n_edges: int,
    n_payloads: int,
    src: jnp.ndarray = None,
    dst: jnp.ndarray = None,
    region: jnp.ndarray = None,
) -> jnp.ndarray:
    """Per-(edge, payload) Bernoulli loss for fire-and-forget traffic.

    Each broadcast changeset rides its own uni frame (the reference
    length-delimits changesets individually inside the flush,
    broadcast/mod.rs:529-571; the host tier's LinkModel drops per
    send_uni call), so loss must be drawn per payload, not per edge —
    one edge-level draw would make 20 versions share a single coin flip
    and collapse the retransmission dynamics the calibration tier
    measures.  Free when loss == 0 (trace-time constant zeros).

    The draw is an 8-bit threshold compare (`random.bits < p*256`), not
    bernoulli's f32 uniform: the [E, P] mask is the lossy configs'
    biggest per-round tensor (100M cells at the gapstress shape) and u8
    bits cost 4× less RNG + HBM traffic.  Loss probabilities quantize
    to 1/256 steps (0.3 → 0.30078) — three orders of magnitude below
    the ×1.5 calibration bands.

    Geo-tiered topologies (ISSUE 9) pass ``src``/``dst``/``region``:
    the SAME aligned draw is compared against per-edge tier thresholds
    (`edge_loss_thresholds`), so a WAN graph's cross-region links drop
    more without a second RNG stream.  Untied topologies ignore the
    extra args and compile the exact legacy kernel."""
    if loss_tiered(topo) and src is not None:
        return tiered_edge_drop(
            topo, key, region, src, dst, (n_edges, n_payloads)
        )
    threshold = _thr(topo.loss)
    if topo.loss <= 0.0 or threshold == 0:
        # loss below 1/512 quantizes to zero drops — return the free
        # constant mask rather than drawing a pointless all-False tensor
        return jnp.zeros((n_edges, n_payloads), jnp.bool_)
    if threshold >= 256:
        # loss ≈ 1.0: a severed channel must stay severed (u8 compare
        # can't express an always-true threshold)
        return jnp.ones((n_edges, n_payloads), jnp.bool_)
    bits = aligned_u8_bits(key, (n_edges, n_payloads))
    return bits < jnp.uint8(threshold)


def edge_loss_thresholds_raw(
    topo: Topology,
    region: jnp.ndarray,
    src: jnp.ndarray,
    dst: jnp.ndarray,
) -> jnp.ndarray:
    """i32[E] UNclamped tier thresholds — only consulted when some tier
    sits at certainty (p·256 ≥ 256), to pin those edges fully dropped."""
    base, az_t, inter_t = loss_tiers(topo)
    same_r = region[src] == region[dst]
    az = azs(region.shape[0], topo)
    same_az = (az[src] == az[dst]) if topo.n_azs > 1 else same_r
    return jnp.where(
        same_r,
        jnp.where(same_az, jnp.int32(base), jnp.int32(az_t)),
        jnp.int32(inter_t),
    )


def aligned_u8_bits(key, shape) -> jnp.ndarray:
    """u8 threefry draw that is WORD-ALIGNED per shard on ANY mesh size.

    jax lowers a u8 bits draw of flat size S through a ceil(S/4) u32
    intermediate; when a node-sharded consumer makes GSPMD partition
    that production on a non-word-aligned boundary (e.g. S = 1008 over
    8 devices → 31.5 words per shard), this jax/XLA version produces
    bit values that DIFFER from the single-device draw — silently, and
    only at shard-unaligned sizes (ISSUE 7; tests/sim/test_packed_sharded
    .py catches the drift as a sharded-vs-single mismatch in the loss
    masks).

    Two defenses, composed (ISSUE 9 generalized the second):

    - the padding rule is unchanged from ISSUE 7 — sizes already a
      multiple of 128 bytes take the unpadded draw, smaller sizes pad
      the flat draw to the next 128-byte multiple and slice — so every
      previously-drawn value is **byte-identical** (committed replay
      digests and campaign baselines stand);
    - the draw itself is now an explicit u32-word draw plus a manual
      little-endian byte unpack — bit-for-bit what jax's u8 path
      computes (pinned by tests/sim/test_topo.py), but with the RNG's
      shardable atoms being whole u32 WORDS.  A shard boundary can then
      never split a word, whatever the device count — including
      odd-sized real meshes (e.g. 6 chips), where the previous
      128-multiple pad was NOT a multiple of 4·d and the u8 unpack
      could still land shard boundaries mid-word (the old rule was only
      safe for power-of-two meshes ≤ 32; the closed carried edge asked
      for lcm(4·d) padding, which the word-atom formulation subsumes
      without re-rolling any existing draw)."""
    size = 1
    for d in shape:
        size *= int(d)
    pad = size if size % 128 == 0 else -(-size // 128) * 128
    words = jax.random.bits(key, (pad // 4,), dtype=jnp.uint32)
    shifts = jnp.arange(4, dtype=jnp.uint32) * jnp.uint32(8)
    flat = (
        ((words[:, None] >> shifts) & jnp.uint32(0xFF))
        .astype(jnp.uint8)
        .reshape(pad)
    )
    if pad != size:
        flat = flat[:size]
    return flat.reshape(shape)
