"""Topology and link model for the simulator.

The reference tiers peers into RTT rings (members.rs:38: [0,6) [6,15) [15,50)
[50,100) [100,200) [200,300) ms) and broadcasts ring-0 first; the sim maps
rings onto round-delay classes (one round ≈ the 500 ms flush tick, so WAN
rings land in delay 1-2 rounds, ICI-local in 0).

Nodes get a static ``region[N]`` label; the delay class of an edge is 0
within a region and grows with region distance.  Partitions cut edges whose
endpoints are in different ``group``s (healing resets groups to 0).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static per-scenario topology parameters."""

    n_regions: int = 1
    intra_delay: int = 0  # rounds
    inter_delay: int = 1  # rounds
    loss: float = 0.0  # per-message drop probability


def regions(n_nodes: int, n_regions: int) -> jnp.ndarray:
    """Contiguous region assignment (Fly.io-style geographic pools)."""
    per = max(1, n_nodes // n_regions)
    return jnp.minimum(jnp.arange(n_nodes, dtype=jnp.int32) // per, n_regions - 1)


def edge_delay(
    topo: Topology, region: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Delay class (rounds) per edge, from region distance."""
    same = region[src] == region[dst]
    return jnp.where(same, topo.intra_delay, topo.inter_delay).astype(jnp.int32)


def edge_alive(
    group: jnp.ndarray, alive: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Reachability mask per edge: same partition group, both endpoints up."""
    from .state import ALIVE

    return (
        (group[src] == group[dst])
        & (alive[src] == ALIVE)
        & (alive[dst] == ALIVE)
    )


def edge_drop(
    topo: Topology, key: jax.Array, n_edges: int
) -> jnp.ndarray:
    """Per-edge Bernoulli loss (the Antithesis-style fault injection knob)."""
    if topo.loss <= 0.0:
        return jnp.zeros((n_edges,), jnp.bool_)
    return jax.random.bernoulli(key, topo.loss, (n_edges,))
