"""Topology and link model for the simulator.

The reference tiers peers into RTT rings (members.rs:38: [0,6) [6,15) [15,50)
[50,100) [100,200) [200,300) ms) and broadcasts ring-0 first; the sim maps
rings onto round-delay classes (one round ≈ the 500 ms flush tick, so WAN
rings land in delay 1-2 rounds, ICI-local in 0).

Nodes get a static ``region[N]`` label; the delay class of an edge is 0
within a region and grows with region distance.  Partitions cut edges whose
endpoints are in different ``group``s (healing resets groups to 0).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Topology:
    """Static per-scenario topology parameters."""

    n_regions: int = 1
    intra_delay: int = 0  # rounds
    inter_delay: int = 1  # rounds
    loss: float = 0.0  # per-message drop probability


def regions(n_nodes: int, n_regions: int) -> jnp.ndarray:
    """Contiguous region assignment (Fly.io-style geographic pools)."""
    per = max(1, n_nodes // n_regions)
    return jnp.minimum(jnp.arange(n_nodes, dtype=jnp.int32) // per, n_regions - 1)


def edge_delay(
    topo: Topology, region: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Delay class (rounds) per edge, from region distance."""
    same = region[src] == region[dst]
    return jnp.where(same, topo.intra_delay, topo.inter_delay).astype(jnp.int32)


def edge_alive(
    group: jnp.ndarray, alive: jnp.ndarray, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Reachability mask per edge: same partition group, both endpoints up."""
    from .state import ALIVE

    return (
        (group[src] == group[dst])
        & (alive[src] == ALIVE)
        & (alive[dst] == ALIVE)
    )


def edge_payload_drop(
    topo: Topology, key: jax.Array, n_edges: int, n_payloads: int
) -> jnp.ndarray:
    """Per-(edge, payload) Bernoulli loss for fire-and-forget traffic.

    Each broadcast changeset rides its own uni frame (the reference
    length-delimits changesets individually inside the flush,
    broadcast/mod.rs:529-571; the host tier's LinkModel drops per
    send_uni call), so loss must be drawn per payload, not per edge —
    one edge-level draw would make 20 versions share a single coin flip
    and collapse the retransmission dynamics the calibration tier
    measures.  Free when loss == 0 (trace-time constant zeros).

    The draw is an 8-bit threshold compare (`random.bits < p*256`), not
    bernoulli's f32 uniform: the [E, P] mask is the lossy configs'
    biggest per-round tensor (100M cells at the gapstress shape) and u8
    bits cost 4× less RNG + HBM traffic.  Loss probabilities quantize
    to 1/256 steps (0.3 → 0.30078) — three orders of magnitude below
    the ×1.5 calibration bands."""
    threshold = int(round(topo.loss * 256.0))
    if topo.loss <= 0.0 or threshold == 0:
        # loss below 1/512 quantizes to zero drops — return the free
        # constant mask rather than drawing a pointless all-False tensor
        return jnp.zeros((n_edges, n_payloads), jnp.bool_)
    if threshold >= 256:
        # loss ≈ 1.0: a severed channel must stay severed (u8 compare
        # can't express an always-true threshold)
        return jnp.ones((n_edges, n_payloads), jnp.bool_)
    bits = jax.random.bits(key, (n_edges, n_payloads), dtype=jnp.uint8)
    return bits < jnp.uint8(threshold)
