"""One-pass fused traversal helpers for the round kernels (ISSUE 19).

The flight-recorder counters used to be a SECOND trip over the round's
hottest tensors: `word_bit_counts` issued 32 separate shifted reductions
over the u32 payload words, `word_byte_totals` accumulated a 32-iteration
Python loop of masked sums, and the broadcast kernels computed per-node
frame and byte totals as two independent passes over the same ``sending``
buffer.  At the 100k storm shape that second trip was the bulk of the
~20% telemetry-on overhead (doc/experiments/PROFILE_BASELINE.json's
``corro.telemetry`` ledger line).

This module holds BOTH forms of every traversal:

- the **fused** form — a formulation whose total memory traffic is a
  small constant number of trips over the words instead of one per bit.
  Two building blocks, picked per reduction direction and verified on
  the 25k-node bench shape (where XLA CPU *materializes* the naive
  ``[..., W, 32]`` bit-plane broadcast instead of fusing it, making the
  textbook one-pass expression 4x SLOWER than the loops it replaced):

  * **SWAR nibble accumulators** for cross-row bit-position counts
    (`word_bit_counts`): fifteen rows sum into packed 4-bit lanes of a
    u32 (a nibble saturates at 15), four shifted lane groups cover all
    32 bit positions, and the 15x-smaller partials finish in i32.  Four
    reads of the words replace 32 — measured 4.1x faster at [25k, 16].
  * **byte-LUT folds** for within-row weighted totals
    (`word_byte_totals`, the bytes half of `word_send_stats`): a
    ``[4W, 256]`` table maps (byte position, byte value) to the exact
    i32 sum of that byte's selected payload sizes; one shift-extracted
    byte view plus one gather replaces the 32-iteration masked
    accumulation — measured 2.4x faster at [25k, 16].

- the **legacy** form — the exact per-bit loops the fused expressions
  replaced, kept verbatim as the reference oracle.

Both forms produce the SAME exact integers: every intermediate is exact
integer arithmetic (nibble lanes cannot overflow at chunk 15, table
entries are i32 partial sums of the same addends), i32 addition is
associative and commutative, and the final f32 folds consume
identically-valued i32 inputs — so every pinned digest (dense==packed
bit-equality, proto families, solo==vmapped==mesh-sharded byte-identity,
campaign baselines) is unmoved by the seam position.

The seam: ``CORRO_FUSED_ROUND`` is read at TRACE TIME (like profile.py's
``CORRO_PHASE_SCOPES``), default ON; ``=0`` selects the legacy oracle.
The env var is not part of the jit cache key — tests toggling it must
``jax.clear_caches()`` between settings (tests/sim/test_fused.py and the
proto-family matrix in tests/sim/test_proto.py do).

corrolint CT011 flags the legacy anti-pattern — a per-bit reduction loop
over round-kernel state words — everywhere EXCEPT this module: the loops
below are the oracle and the only sanctioned home for that shape.
"""

from __future__ import annotations

import os
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

# numpy on purpose (see packed.ONES): module-level jnp constants would be
# created inside whichever trace first imports this module and leak as
# tracers into every later jit; numpy arrays convert per-use
_NIBBLE_LANES = np.uint32(0x11111111)  # bit j of every nibble: lane group j
_NIBBLE_CHUNK = 15  # a 4-bit lane saturates at 15 rows
_NIBBLE_UNPACK = np.arange(8, dtype=np.uint32) * np.uint32(4)
_BYTE_SHIFTS = (np.arange(4, dtype=np.uint32) * np.uint32(8))
# [256, 8] bit matrix of byte values — the LUT builder's static half
_BYTE_BITS = (
    (np.arange(256, dtype=np.uint32)[:, None] >> np.arange(8, dtype=np.uint32))
    & np.uint32(1)
).astype(np.int32)


def fused_round_enabled() -> bool:
    """Trace-time seam: fused one-pass traversals (default) vs the legacy
    per-bit-loop oracle.  Mirrors profile.py's CORRO_PHASE_SCOPES
    discipline — read when the kernel TRACES, not when it runs, and
    invisible to the jit cache key (toggle + jax.clear_caches in tests)."""
    return os.environ.get("CORRO_FUSED_ROUND", "1") != "0"


def _byte_view(words: jnp.ndarray) -> jnp.ndarray:
    """i32[..., 4W] shift-extracted byte view of u32 words, little-endian
    within each word (byte b holds bits 8b..8b+7 = payloads 32k+8b..+7).
    Shifts, not ``bitcast_convert_type``: the narrowing bitcast's minor-
    dim ordering is backend-defined, and it measured slower on CPU."""
    by = (words[..., None] >> _BYTE_SHIFTS) & np.uint32(0xFF)
    return by.reshape(words.shape[:-1] + (words.shape[-1] * 4,)).astype(
        jnp.int32
    )


def _byte_weight_table(nbytes: jnp.ndarray, w: int) -> jnp.ndarray:
    """i32[4W, 256] fold table: entry [b, v] is the exact i32 sum of
    payload sizes selected by byte value ``v`` at byte position ``b``.
    4W*256 entries from a [P] vector — building it is noise next to one
    traversal of the words it saves."""
    nbb = nbytes.astype(jnp.int32).reshape(w * 4, 8)
    return jnp.dot(nbb, jnp.asarray(_BYTE_BITS.T))


# -- per-payload bit counts (coverage / delivered / sync grant counts) -------


def word_bit_counts(words: jnp.ndarray, n_payloads: int) -> jnp.ndarray:
    """i32[P] per-bit-position set counts over the leading (node or edge)
    axis of u32 payload words — the per-payload coverage/delivered/grant
    counters.  Fused: SWAR nibble accumulators — rows sum 15 at a time
    into packed 4-bit lanes (4 shifted reads of the words instead of 32),
    then the 15x-smaller u32 partials unpack and finish in i32.  Legacy:
    32 separate shifted reductions.  Same exact integers either way: a
    nibble lane counts at most 15 ones, so no lane ever carries into its
    neighbour, and i32 addition is order-insensitive."""
    # NOTE: callers whose ``words`` is a large fused expression must pin
    # it with lax.optimization_barrier AT THE SOURCE (so every consumer
    # shares one materialization) — a barrier here would pin a private
    # copy and duplicate the producer pipeline instead
    if fused_round_enabled():
        n, w = words.shape
        # head/tail split, NOT pad-and-concat: padding n to a multiple of
        # 15 would pay a full-array copy (an extra memory pass — the very
        # thing this module removes) whenever 15 ∤ n, which includes the
        # bench shapes (25600, 100000).  The remainder rows run through
        # the same lane trick as one short chunk (< 15 rows cannot carry
        # either), and a prefix slice fuses where a concat never does.
        g15 = (n // _NIBBLE_CHUNK) * _NIBBLE_CHUNK
        grouped = words[:g15].reshape(-1, _NIBBLE_CHUNK, w)
        # [4, G, W] u32: lane group j's nibble k counts bit position
        # j + 4k over its 15-row group
        accs = jnp.stack(
            [
                jnp.sum((grouped >> np.uint32(lane)) & _NIBBLE_LANES, axis=1)
                for lane in range(4)
            ]
        )
        # unpack all 8 nibbles at once over the 15x-smaller partials and
        # finish in i32; [4, W, 8] → [W, 8, 4] flattens as 4k + lane = bit
        nibs = (accs[..., None] >> _NIBBLE_UNPACK) & np.uint32(0xF)
        part = jnp.sum(nibs, axis=1, dtype=jnp.int32)
        if g15 < n:
            tail = words[g15:][None]  # one short chunk [1, n-g15, W]
            taccs = jnp.stack(
                [
                    jnp.sum((tail >> np.uint32(lane)) & _NIBBLE_LANES, axis=1)
                    for lane in range(4)
                ]
            )
            tnibs = (taccs[..., None] >> _NIBBLE_UNPACK) & np.uint32(0xF)
            part = part + jnp.sum(tnibs, axis=1, dtype=jnp.int32)
        return jnp.transpose(part, (1, 2, 0)).reshape(n_payloads)
    one = jnp.uint32(1)
    cols = [
        jnp.sum((words >> jnp.uint32(j)) & one, axis=0, dtype=jnp.int32)
        for j in range(32)  # corrolint: disable=CT011 — the legacy oracle
    ]
    return jnp.stack(cols, axis=-1).reshape(n_payloads)  # [W, 32] → [P]


# -- masked per-row byte totals (wire-byte accounting) -----------------------


def word_byte_totals(words: jnp.ndarray, nbytes: jnp.ndarray) -> jnp.ndarray:
    """i32[...] masked per-row byte totals of u32 bit-words — the packed
    twin of ``where(granted, nbytes, 0).sum(-1)``: exact integer totals
    wherever a row's selected bytes stay under i32 (every current
    scenario: the payload-size validator caps P·64 KiB well below the
    exactness envelope the budget kernels already assume), so the packed
    and dense byte channels agree bit-for-bit before the final f32 fold.
    Fused: one byte-LUT gather — each of the row's 4W bytes indexes its
    own 256-entry column of exact i32 partial sums, one trip over the
    words; legacy: a 32-iteration accumulation loop."""
    w = words.shape[-1]
    if fused_round_enabled():
        table = _byte_weight_table(nbytes, w)
        picked = table[jnp.arange(w * 4), _byte_view(words)]
        return jnp.sum(picked, axis=-1)
    nb = nbytes.astype(jnp.int32).reshape(w, 32)
    tot = jnp.zeros(words.shape[:-1], jnp.int32)
    for j in range(32):  # corrolint: disable=CT011 — the legacy oracle
        bit = ((words >> j) & jnp.uint32(1)).astype(jnp.int32)
        tot = tot + (bit * nb[None, :, j]).sum(axis=-1)
    return tot


# -- combined per-node send stats (frames + bytes from the same loads) -------


def word_send_stats(
    sending: jnp.ndarray, nbytes: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(frames i32[N], bytes i32[N]) per-node wire totals of a packed
    send set ``sending[N, W]`` — what broadcast telemetry folds over the
    edge mask.  Fused: a word popcount for frames plus the byte-LUT fold
    for bytes — two compact trips over the words the governor just
    produced, replacing the legacy popcount + 32-iteration byte loop
    (33 trips)."""
    frames = jnp.sum(
        jax.lax.population_count(sending), axis=-1, dtype=jnp.int32
    )
    return frames, word_byte_totals(sending, nbytes)


def grant_fold(
    counts: jnp.ndarray, nbytes: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(frames i32, bytes f32) from per-payload sync grant counts — the
    ONE final fold both sync kernels perform on their [P] count vector
    (the dense kernel's counts come from a single bool reduction, the
    packed kernel's from `word_bit_counts`; the integers are identical,
    so this shared fold keeps the sync channels bit-equal by
    construction).  [P]-shaped inputs: no traversal to fuse, the point
    is structural sharing."""
    return (
        jnp.sum(counts, dtype=jnp.int32),
        jnp.dot(
            counts.astype(jnp.float32), nbytes.astype(jnp.float32)
        ),
    )


def dense_send_stats(
    sending: jnp.ndarray, nbytes: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Dense twin of `word_send_stats`: (frames i32[N], bytes i32[N])
    from a bool send set ``sending[N, P]``.  Fused: one i32 cast shared
    by both reductions (one pass — ``where(sending, nbytes, 0)`` equals
    ``sending * nbytes`` exactly for bool masks and i32 sizes); legacy:
    two independent masked reductions over the bools.  Identical
    integers to the packed twin on identical-valued send sets, so the
    dense and packed wire channels stay bit-equal."""
    if fused_round_enabled():
        sb = sending.astype(jnp.int32)  # shared producer for both folds
        frames = jnp.sum(sb, axis=-1)
        byte_tot = jnp.sum(sb * nbytes.astype(jnp.int32)[None, :], axis=-1)
        return frames, byte_tot
    frames = jnp.sum(sending, axis=-1, dtype=jnp.int32)
    byte_tot = jnp.sum(
        jnp.where(sending, nbytes[None, :], 0), axis=-1, dtype=jnp.int32
    )
    return frames, byte_tot
