"""Flight recorder: in-kernel per-round telemetry for the simulator.

The jitted round loop used to be a black box — a run reported only
terminal scalars (``converged_at`` / ``coverage_at``), so a bad
convergence run was undebuggable without rerunning and ROADMAP's richer
band metrics (coverage-latency percentiles, detect-round bands) had no
data to stand on.  `RoundTrace` fixes that with **preallocated
[R_max, ·] device buffers written inside the loop via indexed updates**:

- ``coverage[R, P] i32``  — up nodes holding each payload at round end;
- ``delivered[R, P] i32`` — (node, payload) bits newly held this round
  (inject + broadcast + sync deliveries);
- ``up_nodes[R] i32``     — denominator for coverage fractions;
- broadcast wire: ``bcast_bytes f32`` / ``bcast_frames`` /
  ``bcast_dropped`` (frames eaten by wire loss, topology + fault) /
  ``bcast_cut`` (edges severed by FaultPlan cuts this round);
- sync wire: ``sync_bytes f32`` / ``sync_frames`` / ``sync_sessions``
  (due sessions established) / ``sync_refused`` (sessions killed by a
  cut in either direction);
- fault seam: ``crashes`` (nodes held down by the schedule) /
  ``wipes`` (state wipes fired) — written by `record_node_faults` from
  the run loop, where the RoundFaults slice lives;
- SWIM: ``swim_suspect`` / ``swim_down`` belief totals (both tiers);
- ``gap_overflow`` — (node, actor) pairs in the K-slot clamp.

Contract (pinned by tests/sim/test_telemetry.py):

- **zero host syncs per round** — buffers live on device, read once
  after the run;
- **compiled out entirely when ``telemetry=False``** — the flag is a
  static jit arg, telemetry draws no RNG and feeds nothing back, so
  off-runs are byte-identical to pre-telemetry builds;
- **identical on the dense and packed kernels** — integer channels are
  exact counts of the same sets; the two float byte channels reduce
  identically-shaped per-edge i32 totals, so dense-vs-packed traces are
  bit-equal under the same FaultPlan;
- **vmap-safe** — the trace is allocated inside the jitted run, so an
  ensemble lane's trace slice equals its solo run's trace.

Host-side exports: `trace_summary` (deterministic dict for artifacts),
`write_flight_jsonl` (the flight-recorder artifact, one row per round),
and `trace_to_registry` (sim_* Prometheus families on the process
`metrics.Registry`, scraped by `MetricsServer`).
"""

from __future__ import annotations

import functools
import hashlib
import json
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .profile import phase_scope
from .state import ALIVE, DOWN, SUSPECT, PayloadMeta, SimConfig, SimState
from .topology import Topology, regions

# The telemetry kernels pin shared intermediates with
# lax.optimization_barrier (one materialization instead of XLA
# duplicating a producer pipeline into each telemetry consumer), and the
# campaign engine vmaps those kernels over ensemble lanes — but this JAX
# version ships no batching rule for the primitive.  The barrier is
# elementwise in the batch dimension, so the rule is the identity map.
from jax.interpreters import batching as _batching  # noqa: E402

_ob_p = getattr(jax.lax, "optimization_barrier_p", None)
if _ob_p is None:  # pragma: no cover - layout varies across jax versions
    try:
        from jax._src.lax import lax as _lax_internal

        _ob_p = getattr(_lax_internal, "optimization_barrier_p", None)
    except ImportError:
        _ob_p = None
if _ob_p is not None and _ob_p not in _batching.primitive_batchers:

    def _optimization_barrier_batcher(args, dims):
        return _ob_p.bind(*args), dims

    _batching.primitive_batchers[_ob_p] = _optimization_barrier_batcher


class WireTel(NamedTuple):
    """One round's broadcast-wire telemetry (device scalars).

    ``frames``/``bytes`` count what was TRANSMITTED on live edges — the
    wire carried lost frames too; ``dropped`` says how many of them the
    loss processes (topology + FaultPlan) then ate.  This framing is
    also what keeps telemetry off the hot path: transmitted totals fold
    per-NODE sending stats over the edge list (no [E, P] traversal);
    only the drop count needs the per-(edge, payload) mask, and only
    when a loss class is active at trace time (`wire_loss_active`)."""

    frames: jnp.ndarray   # i32 payload frames transmitted on live edges
    bytes: jnp.ndarray    # f32 bytes transmitted
    dropped: jnp.ndarray  # i32 frames eaten by wire loss (topology+fault)
    cut: jnp.ndarray      # i32 edges severed by FaultPlan cuts


class SyncTel(NamedTuple):
    """One round's sync-session telemetry (device scalars)."""

    sessions: jnp.ndarray  # i32 due sessions established
    refused: jnp.ndarray   # i32 sessions refused by fault cuts
    frames: jnp.ndarray    # i32 chunk frames granted
    bytes: jnp.ndarray     # f32 bytes granted


class RoundTrace(NamedTuple):
    """Preallocated per-round telemetry buffers (device; see module doc)."""

    coverage: jnp.ndarray       # i32[R, P]
    delivered: jnp.ndarray      # i32[R, P]
    up_nodes: jnp.ndarray       # i32[R]
    bcast_bytes: jnp.ndarray    # f32[R]
    bcast_frames: jnp.ndarray   # i32[R]
    bcast_dropped: jnp.ndarray  # i32[R]
    bcast_cut: jnp.ndarray      # i32[R]
    sync_bytes: jnp.ndarray     # f32[R]
    sync_frames: jnp.ndarray    # i32[R]
    sync_sessions: jnp.ndarray  # i32[R]
    sync_refused: jnp.ndarray   # i32[R]
    swim_suspect: jnp.ndarray   # i32[R]
    swim_down: jnp.ndarray      # i32[R]
    crashes: jnp.ndarray        # i32[R]
    wipes: jnp.ndarray          # i32[R]
    gap_overflow: jnp.ndarray   # i32[R]


def trace_rows_for(max_rounds: int, every: int = 1) -> int:
    """Sampled rows a decimated trace holds for ``max_rounds`` executed
    rounds: the rounds t with t % every == 0 in [0, max_rounds)."""
    return -(-int(max_rounds) // max(int(every), 1))


def _trace_row(trace: RoundTrace, t, every: int):
    """Buffer row for round ``t`` under a ``trace_every`` stride: row
    t // every when t is a sample round, else the SCRATCH row (the extra
    last row `new_trace` allocates when every > 1) — a predicated write
    target, so non-sample rounds cost the same indexed update but land
    in a row no exporter ever reads.  every == 1 compiles to ``t``
    exactly (the digest-stable off state)."""
    if every <= 1:
        return t
    n_rows = trace.up_nodes.shape[0]
    return jnp.where(t % every == 0, t // every, n_rows - 1)


def new_trace(cfg: SimConfig, max_rounds: int) -> RoundTrace:
    """Preallocate the trace buffers.  ``cfg.trace_every`` > 1 (the
    decimated variant — ISSUE 7 satellite) allocates ceil(R/every) + 1
    rows instead of R: one row per sampled round plus one scratch row
    that absorbs the predicated writes of non-sample rounds, so a
    10k-payload × high-max_rounds sweep stops paying a full [R_max, P]
    channel.  every == 1 (the default) allocates exactly the original
    [R, ·] buffers — byte-identical traces, stable digests."""
    every = max(int(cfg.trace_every), 1)
    r = max_rounds if every == 1 else trace_rows_for(max_rounds, every) + 1
    p = cfg.n_payloads
    z = functools.partial(jnp.zeros, dtype=jnp.int32)
    return RoundTrace(
        coverage=z((r, p)),
        delivered=z((r, p)),
        up_nodes=z((r,)),
        bcast_bytes=jnp.zeros((r,), jnp.float32),
        bcast_frames=z((r,)),
        bcast_dropped=z((r,)),
        bcast_cut=z((r,)),
        sync_bytes=jnp.zeros((r,), jnp.float32),
        sync_frames=z((r,)),
        sync_sessions=z((r,)),
        sync_refused=z((r,)),
        swim_suspect=z((r,)),
        swim_down=z((r,)),
        crashes=z((r,)),
        wipes=z((r,)),
        gap_overflow=z((r,)),
    )


def swim_belief_counts(state: SimState, cfg: SimConfig):
    """(suspect, down) belief totals — both SWIM tiers read the slim
    state's membership fields, which the dense and packed paths share,
    so the counts are structurally identical across kernels."""
    if cfg.swim_full_view:
        return (
            jnp.sum(state.view == SUSPECT, dtype=jnp.int32),
            jnp.sum(state.view == DOWN, dtype=jnp.int32),
        )
    if cfg.swim_partial_view:
        valid = state.pid >= 0
        st = state.pkey & 3  # == pkey % 4 for two's complement i32
        return (
            jnp.sum(valid & (st == SUSPECT), dtype=jnp.int32),
            jnp.sum(valid & (st == DOWN), dtype=jnp.int32),
        )
    return jnp.int32(0), jnp.int32(0)


def record_round(
    trace: RoundTrace,
    t: jnp.ndarray,
    *,
    coverage: jnp.ndarray,
    delivered: jnp.ndarray,
    up_nodes: jnp.ndarray,
    wire: WireTel,
    sync: SyncTel,
    swim_suspect: jnp.ndarray,
    swim_down: jnp.ndarray,
    gap_overflow: jnp.ndarray,
    every: int = 1,
) -> RoundTrace:
    """Write round ``t``'s row (the pre-increment round counter — run
    loops guarantee t < R_max).  One indexed update per channel, no host
    sync; `crashes`/`wipes` ride `record_node_faults` instead (the
    RoundFaults slice lives in the run loop, not the round step).
    ``every`` > 1 routes non-sample rounds to the scratch row
    (`_trace_row`); 1 writes row t exactly as before.  Self-scoped
    ``corro.telemetry`` (profile.py): the row writes are flight-recorder
    cost wherever the caller sits in the phase tree."""
    with phase_scope("telemetry"):
        row = _trace_row(trace, t, every)
        return trace._replace(
            coverage=trace.coverage.at[row].set(coverage),
            delivered=trace.delivered.at[row].set(delivered),
            up_nodes=trace.up_nodes.at[row].set(up_nodes),
            bcast_bytes=trace.bcast_bytes.at[row].set(wire.bytes),
            bcast_frames=trace.bcast_frames.at[row].set(wire.frames),
            bcast_dropped=trace.bcast_dropped.at[row].set(wire.dropped),
            bcast_cut=trace.bcast_cut.at[row].set(wire.cut),
            sync_bytes=trace.sync_bytes.at[row].set(sync.bytes),
            sync_frames=trace.sync_frames.at[row].set(sync.frames),
            sync_sessions=trace.sync_sessions.at[row].set(sync.sessions),
            sync_refused=trace.sync_refused.at[row].set(sync.refused),
            swim_suspect=trace.swim_suspect.at[row].set(swim_suspect),
            swim_down=trace.swim_down.at[row].set(swim_down),
            gap_overflow=trace.gap_overflow.at[row].set(gap_overflow),
        )


def record_node_faults(
    trace: RoundTrace, t: jnp.ndarray, rf, every: int = 1
) -> RoundTrace:
    """Fault-seam node channels for round ``t``: nodes the schedule holds
    DOWN this round and wipes fired.  Called from the fault run loops
    right after `round_faults` slices the plan (same row the round step
    fills)."""
    row = _trace_row(trace, t, every)
    return trace._replace(
        crashes=trace.crashes.at[row].set(
            jnp.sum(rf.alive == DOWN, dtype=jnp.int32)
        ),
        wipes=trace.wipes.at[row].set(jnp.sum(rf.wipe, dtype=jnp.int32)),
    )


def wire_loss_active(topo, faults) -> bool:
    """Trace-time fact: can the broadcast wire drop frames in this
    scenario?  False ⇒ the dropped channel is the constant 0 and the
    [E, P] drop-mask reduction is never emitted (the one telemetry
    term that would otherwise cost a full edge×payload traversal).
    Geo-tiered topologies (ISSUE 9) drop on ANY applicable tier's
    threshold — a WAN trunk's loss must not read as a constant-zero
    channel.  (`loss_tiered` is exactly "some applicable tier differs",
    which with thresholds ≥ 0 implies one is nonzero.)"""
    from .topology import loss_tiered

    if int(round(topo.loss * 256.0)) > 0 or loss_tiered(topo):
        return True
    if faults is None:
        return False
    from .faults import RoundFaults

    if isinstance(faults, RoundFaults):
        return faults.loss is not None
    return faults.loss_thr.shape[0] > 0


# the traversal counters live in sim/fused.py since ISSUE 19 — one
# fused memory pass by default, the legacy per-bit loops as the oracle
# behind the CORRO_FUSED_ROUND seam.  Re-exported here because this
# module is the flight recorder's public face (both round kernels and
# the tests import the counters from telemetry).
from .fused import word_bit_counts, word_byte_totals  # noqa: E402,F401


def word_coverage_delivered(
    held_w: jnp.ndarray,
    held0_w: jnp.ndarray,
    up: jnp.ndarray,
    n_payloads: int,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(coverage, delivered) i32[P] from u32[N, W] payload words at
    round start (``held0_w``) and end (``held_w``) — the ONE
    implementation both the dense and packed round kernels record, so
    the tested dense==packed bit-equality of these channels cannot
    drift between two copies.  The barrier pins the two masked buffers
    at the source (one cheap elementwise pass each) so the fused count
    traversals re-read small L2-resident buffers instead of recomputing
    the masks per trip."""
    cov_w, del_w = jax.lax.optimization_barrier((
        jnp.where(up[:, None], held_w, jnp.uint32(0)),
        held_w & ~held0_w,
    ))
    return (
        word_bit_counts(cov_w, n_payloads),
        word_bit_counts(del_w, n_payloads),
    )


# -- the membership-churn driver (runner configs #2/#2b, engine-routed) ------


@functools.partial(
    jax.jit, static_argnames=("cfg", "topo", "max_rounds", "telemetry")
)
def run_membership_detect(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    max_rounds: int = 400,
    telemetry: bool = False,
):
    """Membership-churn run: advance rounds until every survivor marks
    every dead node DOWN (full-view: all watched (up, dead) pairs;
    partial-view: every live table entry referencing a dead member), or
    ``max_rounds``.  The detection predicate runs ON DEVICE inside the
    while_loop — the runner configs #2/#2b loops, lifted here so the
    campaign engine can vmap seed ensembles over them and band the
    detect rounds (ROADMAP "detect-round bands for membership
    scenarios").  Returns (state, metrics, detect_round[, trace])."""
    from .round import new_metrics, round_step

    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    up_mask = state.alive == ALIVE  # static after t=0 (kill pre-applied)

    if cfg.swim_full_view:
        pair_watched = up_mask[:, None] & ~up_mask[None, :]

        def detected(s):
            return jnp.all(jnp.where(pair_watched, s.view == DOWN, True))

    elif cfg.swim_partial_view:

        def detected(s):
            watcher_up = up_mask[:, None]
            entry_dead = (s.pid >= 0) & ~up_mask[jnp.maximum(s.pid, 0)]
            marked = s.pkey % 4 == DOWN
            return jnp.all(jnp.where(watcher_up & entry_dead, marked, True))

    else:
        raise ValueError(
            "membership detection needs a SWIM tier "
            "(swim_full_view or swim_partial_view)"
        )

    trace = new_trace(cfg, max_rounds) if telemetry else None

    def cond(carry):
        detect_round = carry[2]
        return (detect_round < 0) & (carry[0].t < max_rounds)

    def body(carry):
        if telemetry:
            state, metrics, detect_round, trace = carry
            state, metrics, trace = round_step(
                state, metrics, meta, cfg, topo, region, trace=trace
            )
        else:
            state, metrics, detect_round = carry
            state, metrics = round_step(
                state, metrics, meta, cfg, topo, region
            )
        detect_round = jnp.where(
            (detect_round < 0) & detected(state), state.t, detect_round
        )
        if telemetry:
            return state, metrics, detect_round, trace
        return state, metrics, detect_round

    init = (state, metrics, jnp.int32(-1))
    if telemetry:
        init = init + (trace,)
    return jax.lax.while_loop(cond, body, init)


# -- host-side exports -------------------------------------------------------


FLIGHT_VERSION = 1


def trace_host(trace, rounds: int, every: int = 1):
    """Host copies of every channel, sliced to the executed rounds
    (``every`` > 1: to the SAMPLED rows — ceil(rounds/every), which
    excludes the scratch row by construction).  Idempotent: a dict from
    a previous call passes through (re-sliced; slicing an already-short
    array is a no-op), so callers that fan a trace out to several
    consumers — summary, digest, JSONL rows — pay the device-to-host
    copy exactly once.  Every exporter below accepts either a RoundTrace
    or this dict."""
    r = trace_rows_for(rounds, every)
    if isinstance(trace, dict):
        return {f: v[:r] for f, v in trace.items()}
    return {f: np.asarray(getattr(trace, f))[:r] for f in RoundTrace._fields}


def coverage_curve_digest(trace, rounds: int, every: int = 1) -> str:
    """Replay identity of the per-round per-payload coverage curve —
    the compact fingerprint bench/campaign artifacts record so a
    convergence trajectory (not just its endpoint) is regression-
    checkable across runs."""
    r = trace_rows_for(rounds, every)
    cov = (
        trace["coverage"][:r]
        if isinstance(trace, dict)
        else np.asarray(trace.coverage)[:r]
    )
    cov = np.ascontiguousarray(cov, np.int32)
    return hashlib.blake2b(cov.tobytes(), digest_size=8).hexdigest()


def coverage_latency_rounds(
    trace, rounds: int, every: int = 1
) -> np.ndarray:
    """i32[P] first round each payload reached FULL coverage (held by
    every up node), -1 if never — computed from the trace alone, so the
    per-payload coverage-latency percentiles ROADMAP asks for need no
    extra kernel output.  Decimated traces report the first SAMPLED
    round (i·every — an upper bound within one stride of the true
    latency; the knob is off by default)."""
    t = trace_host(trace, rounds, every)
    full = (t["coverage"] == t["up_nodes"][:, None]) & (
        t["up_nodes"][:, None] > 0
    )  # [R, P]
    if full.shape[0] == 0:  # zero-round run: argmax chokes on an empty axis
        return np.full(full.shape[1], -1, np.int32)
    any_full = full.any(axis=0)
    first = full.argmax(axis=0) * every
    return np.where(any_full, first, -1).astype(np.int32)


def trace_summary(trace, rounds: int, cfg: SimConfig) -> dict:
    """Deterministic per-run summary block (bench records / campaign
    artifacts): coverage-curve digest, coverage-latency percentiles,
    bytes/round, fault-seam and SWIM totals.  Every value derives from
    device-deterministic integers, so a replay reproduces it exactly.
    ``cfg.trace_every`` > 1 summarizes the sampled rows (wire/fault
    totals become stride samples, labeled by a ``trace_every`` key);
    the default stride 1 emits the exact block prior builds did."""
    r = int(rounds)
    every = max(int(cfg.trace_every), 1)
    t = trace_host(trace, r, every)
    lat = coverage_latency_rounds(t, r, every)
    covered = lat[lat >= 0]

    def pct(q):
        if covered.size == 0:
            return None
        return float(np.percentile(covered, q, method="lower"))

    bcast = float(t["bcast_bytes"].sum())
    sync = float(t["sync_bytes"].sum())
    sampled = trace_rows_for(r, every)
    out = {
        "rounds": r,
        "coverage_curve_digest": coverage_curve_digest(t, r),
        "coverage_latency_rounds": {
            "p50": pct(50), "p95": pct(95), "p99": pct(99),
            "uncovered_payloads": int((lat < 0).sum()),
        },
        "wire_bytes": {
            "broadcast": round(bcast, 1),
            "sync": round(sync, 1),
            # mean over the rows the trace actually holds (== rounds at
            # the default stride; sampled rows when decimated)
            "per_round_mean": round((bcast + sync) / max(sampled, 1), 1),
        },
        "wire_frames": {
            "broadcast": int(t["bcast_frames"].sum()),
            "sync": int(t["sync_frames"].sum()),
        },
        "fault": {
            "dropped_frames": int(t["bcast_dropped"].sum()),
            "cut_edges": int(t["bcast_cut"].sum()),
            "refused_sessions": int(t["sync_refused"].sum()),
            "crash_node_rounds": int(t["crashes"].sum()),
            "wipes": int(t["wipes"].sum()),
        },
        "sync_sessions": int(t["sync_sessions"].sum()),
        "swim": {
            "peak_suspect": int(t["swim_suspect"].max(initial=0)),
            "peak_down": int(t["swim_down"].max(initial=0)),
        },
        "gap_overflow_rounds": int((t["gap_overflow"] > 0).sum()),
    }
    if every > 1:
        # self-describing only when the knob is ON: the default-stride
        # summary dict is byte-identical to prior builds (digest-stable)
        out["trace_every"] = every
    return out


def trace_rows(trace, rounds: int, cfg: SimConfig, per_payload: bool = None):
    """Per-round dict rows for the flight-recorder JSONL / CLI table
    (sampled rows when ``cfg.trace_every`` > 1 — each row's ``t`` is the
    real round it recorded).  ``per_payload`` includes the raw coverage
    vector per row (defaults to on for P ≤ 256 — the debuggable scales —
    off at storm shape)."""
    every = max(int(cfg.trace_every), 1)
    t = trace_host(trace, rounds, every)
    r = trace_rows_for(rounds, every)
    if per_payload is None:
        per_payload = cfg.n_payloads <= 256
    rows = []
    for i in range(r):
        up = int(t["up_nodes"][i])
        cov = t["coverage"][i]
        row = {
            "t": i * every,
            "up_nodes": up,
            "coverage_frac": round(
                float(cov.sum()) / max(up * cfg.n_payloads, 1), 6
            ),
            "delivered": int(t["delivered"][i].sum()),
            "bcast_bytes": round(float(t["bcast_bytes"][i]), 1),
            "bcast_frames": int(t["bcast_frames"][i]),
            "bcast_dropped": int(t["bcast_dropped"][i]),
            "bcast_cut": int(t["bcast_cut"][i]),
            "sync_bytes": round(float(t["sync_bytes"][i]), 1),
            "sync_frames": int(t["sync_frames"][i]),
            "sync_sessions": int(t["sync_sessions"][i]),
            "sync_refused": int(t["sync_refused"][i]),
            "swim_suspect": int(t["swim_suspect"][i]),
            "swim_down": int(t["swim_down"][i]),
            "crashes": int(t["crashes"][i]),
            "wipes": int(t["wipes"][i]),
            "gap_overflow": int(t["gap_overflow"][i]),
        }
        if per_payload:
            row["coverage"] = [int(c) for c in cov]
        rows.append(row)
    return rows


def write_flight_jsonl(
    path: str,
    trace,
    rounds: int,
    cfg: SimConfig,
    header: Optional[dict] = None,
    per_payload: bool = None,
) -> None:
    """The flight-recorder artifact: line 1 is a header (shape, summary,
    any caller context — campaign cell params, seeds, traceparent), then
    one JSON line per executed round.  Atomic replace, like every other
    artifact writer in the tree."""
    import os

    t = trace_host(trace, rounds, max(int(cfg.trace_every), 1))
    head = {
        "kind": "flight_recorder",
        "version": FLIGHT_VERSION,
        "n_nodes": cfg.n_nodes,
        "n_payloads": cfg.n_payloads,
        "rounds": int(rounds),
        "summary": trace_summary(t, rounds, cfg),
    }
    if cfg.trace_every > 1:
        head["trace_every"] = int(cfg.trace_every)
    if header:
        head.update(header)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(json.dumps(head, sort_keys=True, default=float) + "\n")
        for row in trace_rows(t, rounds, cfg, per_payload=per_payload):
            f.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, path)


#: coverage-latency histogram buckets (rounds — round counts, not the
#: host ladder's seconds)
LATENCY_ROUND_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def trace_to_registry(
    trace,
    rounds: int,
    cfg: SimConfig,
    registry=None,
    **labels,
) -> None:
    """Export a completed trace as ``sim_*`` Prometheus families on a
    `metrics.Registry` (the process-wide one by default), so
    `MetricsServer` scrapes sim runs exactly like host-agent state.
    ``labels`` (e.g. run="packed_fault_storm") tag every family."""
    from ..metrics import REGISTRY

    reg = registry if registry is not None else REGISTRY
    r = int(rounds)
    every = max(int(cfg.trace_every), 1)
    t = trace_host(trace, r, every)

    reg.counter("sim_rounds_total").inc(r, **labels)
    wire = reg.counter("sim_wire_bytes_total")
    wire.inc(float(t["bcast_bytes"].sum()), path="broadcast", **labels)
    wire.inc(float(t["sync_bytes"].sum()), path="sync", **labels)
    frames = reg.counter("sim_wire_frames_total")
    frames.inc(int(t["bcast_frames"].sum()), path="broadcast", **labels)
    frames.inc(int(t["sync_frames"].sum()), path="sync", **labels)
    reg.counter("sim_fault_dropped_frames_total").inc(
        int(t["bcast_dropped"].sum()), **labels
    )
    reg.counter("sim_fault_cut_edges_total").inc(
        int(t["bcast_cut"].sum()), **labels
    )
    reg.counter("sim_fault_refused_sessions_total").inc(
        int(t["sync_refused"].sum()), **labels
    )
    reg.counter("sim_fault_crash_node_rounds_total").inc(
        int(t["crashes"].sum()), **labels
    )
    reg.counter("sim_fault_wipes_total").inc(int(t["wipes"].sum()), **labels)
    reg.counter("sim_sync_sessions_total").inc(
        int(t["sync_sessions"].sum()), **labels
    )
    reg.counter("sim_gap_overflow_rounds_total").inc(
        int((t["gap_overflow"] > 0).sum()), **labels
    )
    reg.gauge("sim_swim_suspect_peak").set(
        int(t["swim_suspect"].max(initial=0)), **labels
    )
    reg.gauge("sim_swim_down_peak").set(
        int(t["swim_down"].max(initial=0)), **labels
    )
    hist = reg.histogram(
        "sim_coverage_latency_rounds", buckets=LATENCY_ROUND_BUCKETS
    )
    for lat in coverage_latency_rounds(t, r, every):
        if lat >= 0:
            hist.observe(float(lat), **labels)
