"""Measurement integrity for the benchmark walls (VERDICT r2 item 1).

Round 2 committed a "100k nodes converged in 1.6 ms" wall that its own
COO spike (doc/experiments/COO_SPIKE.md, 329 ms *per dispatch*) and basic
physics both contradict: a [E=300k, P=512] gather/scatter per round cannot
finish 27 rounds in 1.6 ms on any single chip.  The likely culprit is the
axon device tunnel acknowledging `block_until_ready` on a scalar output
before the computation's full effects are observable host-side.  This
module makes every reported wall defensible by construction:

1. ``measure_per_round`` — an explicit k-round `fori_loop` microbenchmark
   that blocks on **all** outputs (the whole carry pytree, converted to
   host numpy so no async handle can lie) and reports per-round seconds.
2. ``carry_write_bytes`` — the analytic lower bound on HBM traffic per
   round: the round kernel rewrites the dense carry (`have`, `relay_left`,
   `inflight`, ...) every round, so wall/round < bytes/HBM-bandwidth is
   physically impossible.  ``HBM_BYTES_PER_S_CEILING`` is set far above
   any current single chip (v5e ≈ 0.8 TB/s, v5p ≈ 2.8 TB/s) so the bound
   can only fire on measurement artifacts, never on a fast chip.
3. ``verify_wall`` — cross-checks a full-run wall against
   rounds × per-round and the physical bound, and returns the
   *defensible* wall (the conservative max) plus a verdict string.

bench_child.py refuses to mark a storm attempt ``ok`` unless the verdict
machinery ran; BENCH_DIAG.json records both raw and corrected walls.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from .round import new_metrics, new_sim, round_step
from .state import PayloadMeta, SimConfig
from .topology import Topology, regions

# Generous single-chip HBM bandwidth ceiling (bytes/s).  No accelerator
# this framework can run on sustains 4 TB/s of HBM writes; a measured
# per-round wall implying more is a broken measurement, not a fast chip.
HBM_BYTES_PER_S_CEILING = 4e12

# Conservative single-chip HBM CAPACITY floor (bytes): v5e carries
# 16 GB, larger chips more — a rung whose compiled memory budget
# (profile.memory_budget: argument+output+temp−alias) exceeds
# capacity × n_devices cannot run on the floor chip and verify_wall
# flags it (ISSUE 16: the committed 100k/1M budgets feed this check,
# and ROADMAP item 2's 10M plan is sized against the same record).
HBM_BYTES_CAPACITY_PER_CHIP = 16e9


def carry_write_bytes(cfg: SimConfig, packed: bool = False) -> int:
    """Bytes the round kernel must WRITE per round: the carry tensors
    are rewritten every round (scatter-max into `inflight`, delivery
    merge into `have`, relay decay into `relay_left`).  This is a
    deliberate under-count — reads, the [E, P] sync/broadcast masks, and
    the bookkeeping refresh are ignored — so the derived minimum round
    time is a true lower bound.  ``packed`` sizes the bitpacked carry
    (u32 have words + 4 bitsliced relay planes + the dense u8 ring —
    sim/packed.py's hybrid layout) so the bound stays a LOWER bound on
    whichever path actually dispatched."""
    n, p, d = cfg.n_nodes, cfg.n_payloads, cfg.n_delay_slots
    inflight = d * n * p  # u8 ring in both layouts
    if packed:
        have = n * (p // 8)  # u32[N, P/32]
        relay = n * (p // 2)  # 4 × u32[N, P/32] planes
    else:
        have = n * p  # u8
        relay = n * p  # u8
    return have + relay + inflight


def analytic_min_round_s(
    cfg: SimConfig, n_devices: int = 1, packed: bool = False
) -> float:
    """Physical lower bound on one round's wall-clock (see module doc).
    An n-chip mesh shards the node axis, so aggregate write bandwidth
    scales with device count (ADVICE r3: an 8×v5e slice legitimately
    sustains ~8× the single-chip ceiling)."""
    return carry_write_bytes(cfg, packed) / (
        HBM_BYTES_PER_S_CEILING * max(1, n_devices)
    )


def _per_round_runner(
    cfg: SimConfig,
    meta: PayloadMeta,
    topo: Topology,
    seed: int,
    k_rounds: int,
    mesh,
    fplan,
    telemetry: bool,
):
    """Build the timed single-execution closure `measure_per_round` and
    `measure_overhead_pair` share: a jitted k-round `fori_loop` of the
    real round body (faultless / fault-seam / flight-recorder variants),
    blocked on the ENTIRE output pytree via host transfer."""
    from .faults import apply_node_faults, round_faults
    from .packed import (
        _converged_done,
        _pin,
        all_have_words,
        apply_carry_faults,
        pack_bits,
        pack_state,
        packed_round_step,
        packed_supported,
        shrink_state,
        unpack_into_state,
    )

    from ..parallel.mesh import place_run

    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    state, meta, fplan = place_run(new_sim(cfg, seed), meta, fplan, mesh)

    # microbench the SAME path run_to_convergence/run_fault_plan
    # dispatches, else the ×3 consistency check compares apples to oranges
    use_packed = packed_supported(cfg, topo)

    @jax.jit
    def k_rounds_fn(state, metrics):
        from .telemetry import new_trace, record_node_faults

        trace0 = new_trace(cfg, k_rounds) if telemetry else None
        if use_packed:
            carry0 = pack_state(state, cfg)
            inj0 = pack_bits(state.injected)
            slim = shrink_state(state)

            def body(_, c):
                if telemetry:
                    s, carry, inj, m, trace = c
                else:
                    s, carry, inj, m = c
                    trace = None
                # the microbenched body must match the run loops' real
                # per-round work, which since ISSUE 7 includes the
                # per-lane done predicate + gated masks and (sharded)
                # the per-round layout pins
                if fplan is not None:
                    horizon = fplan.alive.shape[0] - 1
                    done = (s.t >= horizon) & all_have_words(
                        carry, inj, s, meta, cfg
                    )
                    rf = round_faults(fplan, s.t)
                    if trace is not None:
                        trace = record_node_faults(
                            trace, s.t, rf, every=cfg.trace_every
                        )
                    s = apply_node_faults(s, rf)
                    carry = apply_carry_faults(carry, rf)
                    out = packed_round_step(
                        s, carry, inj, m, meta, cfg, topo, region,
                        faults=rf, trace=trace, done=done,
                    )
                else:
                    done = _converged_done(s, m, meta)
                    out = packed_round_step(
                        s, carry, inj, m, meta, cfg, topo, region,
                        trace=trace, done=done,
                    )
                trace2 = out[4] if len(out) > 4 else None
                s2, carry2, m2, trace2 = _pin(
                    mesh, out[0], out[1], out[3], trace2
                )
                if trace2 is not None:
                    return (s2, carry2, out[2], m2, trace2)
                return (s2, carry2, out[2], m2)

            init = (slim, carry0, inj0, metrics)
            if telemetry:
                init = init + (trace0,)
            out = jax.lax.fori_loop(0, k_rounds, body, init)
            slim, carry, m = out[0], out[1], out[3]
            return (unpack_into_state(carry, slim, cfg), m) + (
                (out[4],) if telemetry else ()
            )

        def body(_, c):
            if telemetry:
                s, m, trace = c
            else:
                s, m = c
                trace = None
            if fplan is not None:
                rf = round_faults(fplan, s.t)
                if trace is not None:
                    trace = record_node_faults(
                        trace, s.t, rf, every=cfg.trace_every
                    )
                s = apply_node_faults(s, rf)
                return round_step(
                    s, m, meta, cfg, topo, region, faults=rf, trace=trace
                )
            return round_step(s, m, meta, cfg, topo, region, trace=trace)

        init = (state, metrics) + ((trace0,) if telemetry else ())
        return jax.lax.fori_loop(0, k_rounds, body, init)

    def run_once() -> float:
        t0 = time.monotonic()
        out = k_rounds_fn(state, metrics)
        out_state, out_metrics = out[0], out[1]
        jax.block_until_ready(out)
        # belt and braces: force a real host read of the large carries
        np.asarray(out_state.have[0, 0])
        np.asarray(out_state.inflight[0, 0, 0])
        np.asarray(out_metrics.converged_at[0])
        if telemetry:
            np.asarray(out[2].coverage[0, 0])
        return time.monotonic() - t0

    # the phase-attribution rung (profile.py) needs the SAME jitted
    # body this microbench times: it lowers+compiles it for the HLO
    # text (the op→phase map) and memory_analysis(), then executes it
    # under the profiler capture — exposing the pieces keeps the
    # profiled program and the timed program one and the same
    run_once.k_rounds_fn = k_rounds_fn
    run_once.args = (state, metrics)
    run_once.k_rounds = k_rounds
    return run_once


def measure_per_round(
    cfg: SimConfig,
    meta: PayloadMeta,
    topo: Topology = Topology(),
    seed: int = 17,
    k_rounds: int = 8,
    reps: int = 3,
    mesh=None,
    fplan=None,
    telemetry: bool = False,
) -> float:
    """Honest per-round seconds: jit a k-round `fori_loop` of the real
    `round_step`, block on the ENTIRE output pytree via host transfer,
    take the min over ``reps`` timed executions after a warmup.

    ``fplan`` (a compiled SimFaultPlan/FactoredFaultPlan, or None)
    microbenches the FAULT round body — per-round node-fault application
    plus the fault seam through every phase — so a fault-storm wall is
    verified against its own path's per-round cost, not the cheaper
    faultless body.

    ``telemetry=True`` microbenches the flight-recorder round body
    (RoundTrace threaded through the loop).  For the telemetry/plain
    OVERHEAD ratio use `measure_overhead_pair` — two sequential
    `measure_per_round` blocks are not comparable on a contended box.

    Host-transferring (`np.asarray`) one element of every output array is
    the strongest completion barrier available — it cannot return until
    the device actually produced the data, unlike an async-ready signal
    a tunnel plugin might fake."""
    run_once = _per_round_runner(
        cfg, meta, topo, seed, k_rounds, mesh, fplan, telemetry
    )
    run_once()  # warmup (pays compile)
    walls = [run_once() for _ in range(reps)]
    return min(walls) / k_rounds


def measure_overhead_pair(
    cfg: SimConfig,
    meta: PayloadMeta,
    topo: Topology = Topology(),
    seed: int = 17,
    k_rounds: int = 8,
    reps: int = 5,
    mesh=None,
    fplan=None,
) -> Tuple[float, float]:
    """Interleaved plain/telemetry per-round pair — the defensible form
    of the "telemetry adds ≤ 10%" acceptance ratio.  Single-shot walls
    on this box swing ±30% between a fast and a slow scheduling regime,
    so two sequential min-of-reps blocks can fake a 25% overhead or mask
    a real one; alternating the two compiled bodies A/B/A/B exposes both
    to the same load profile, and the per-variant MIN over the
    interleaved reps (the same estimator `measure_per_round` uses)
    compares best-case against best-case.  Returns
    ``(per_round_plain_s, per_round_telemetry_s)``."""
    run_plain = _per_round_runner(
        cfg, meta, topo, seed, k_rounds, mesh, fplan, telemetry=False
    )
    run_tel = _per_round_runner(
        cfg, meta, topo, seed, k_rounds, mesh, fplan, telemetry=True
    )
    run_plain()  # warmups (pay both compiles before any timed pair)
    run_tel()
    plain, tel = [], []
    for _ in range(reps):
        plain.append(run_plain())
        tel.append(run_tel())
    return min(plain) / k_rounds, min(tel) / k_rounds


def verify_wall(
    full_wall_s: float,
    rounds: int,
    per_round_s: float,
    cfg: SimConfig,
    n_devices: int = 1,
    packed: bool = False,
    mem_budget: Optional[Dict[str, object]] = None,
) -> Tuple[float, Dict[str, object]]:
    """Cross-check a full-run wall and return (defensible_wall, report).

    - If per_round itself beats the HBM bound, the whole measurement
      chain is broken: report ``hbm-bound-violated`` and surface the
      analytic minimum as the floor (callers should refuse the record).
    - If full_wall is >3× *below* rounds × per_round, the full-run timing
      is an async artifact; the defensible wall is rounds × per_round.
    - If full_wall is >3× above, the run carried overhead (compile,
      tunnel stall); full_wall stands (conservative) but is flagged.

    ``mem_budget`` (a `profile.memory_budget` record, or None) extends
    the report with the compiled executable's measured HBM CAPACITY
    demand: ``fits_hbm`` says whether peak_bytes_est fits the
    conservative per-chip floor × n_devices.  Capacity doesn't change
    the defensible wall (it bounds feasibility, not time), so the
    verdict string is untouched; a non-fitting budget is flagged in
    ``memory_flag`` for the rung record to surface.
    """
    min_round = analytic_min_round_s(cfg, n_devices, packed)
    expected = rounds * per_round_s
    report: Dict[str, object] = {
        "per_round_ms": round(per_round_s * 1e3, 3),
        "analytic_min_round_ms": round(min_round * 1e3, 4),
        "carry_write_mb": round(carry_write_bytes(cfg, packed) / 1e6, 1),
        "n_devices": n_devices,
        "carry_layout": "packed" if packed else "dense",
        "rounds_x_per_round_s": round(expected, 4),
        "full_run_wall_s": round(full_wall_s, 4),
    }
    if mem_budget is not None:
        cap = HBM_BYTES_CAPACITY_PER_CHIP * max(1, n_devices)
        peak = int(mem_budget.get("peak_bytes_est", 0))
        report["memory_budget"] = mem_budget
        report["hbm_capacity_bytes"] = int(cap)
        report["fits_hbm"] = peak <= cap
        if peak > cap:
            report["memory_flag"] = (
                f"peak {peak / 1e9:.2f} GB exceeds the "
                f"{cap / 1e9:.0f} GB conservative capacity of "
                f"{n_devices} chip(s)"
            )
    if per_round_s < min_round:
        report["verdict"] = "hbm-bound-violated"
        report["consistency_ratio"] = None
        return max(expected, rounds * min_round), report

    ratio = full_wall_s / expected if expected > 0 else float("inf")
    report["consistency_ratio"] = round(ratio, 3)
    if ratio < 1 / 3:
        report["verdict"] = "async-artifact-corrected"
        return expected, report
    if ratio > 3:
        report["verdict"] = "overhead-flagged"
        return full_wall_s, report
    report["verdict"] = "ok"
    return full_wall_s, report
