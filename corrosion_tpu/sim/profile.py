"""Phase-attribution profiler — the named-scope cost ledger (ISSUE 16).

The round kernels annotate their phases with `jax.named_scope` strings
from the `PHASES` registry below (`corro.<phase>`).  The scopes are
METADATA-ONLY: they ride the HLO `op_name` metadata and change no
computation, so every pinned digest (dense==packed, solo==vmapped==
sharded, proto families) stays byte-identical with annotations compiled
in — tests/sim/test_profile.py pins that, and corrolint CT010 keeps the
kernel scopes and this registry from drifting apart.

Attribution is a TWO-PART join, because the profiler's trace-event file
does not carry scope names on CPU/TPU device ops — events only carry
``args.hlo_op`` (the HLO instruction name) and ``args.hlo_module``:

1. at capture time, the caller saves the compiled executable's HLO text
   (`lowered.compile().as_text()`), and `write_phase_map` extracts each
   instruction's ``metadata={op_name="..."}`` path into an op → phase
   map (`phase_map.json`, next to the capture);
2. offline — JAX-FREE, so `sim profile show|compare` and the nightly
   gate run without a backend — `parse_phase_profile` joins the trace's
   device ops against that map and folds op time into per-phase seconds
   and fractions.

Innermost scope wins (the `sampler` scope nested inside `sync`/`swim`
attributes the member draws to the sampler), container ops (`while`,
`conditional`, `call` — whose spans cover their body ops' spans) are
excluded from the fold so the loop wrapper never double-counts its body,
and any device time in a captured module that carries NO registered
scope is reported LOUDLY as the unattributed residual (the acceptance
bar: < 15% on the 25k packed storm baseline).

Wall-clock never enters the record's gated fields: phase FRACTIONS are
banded (doc/experiments/PROFILE_BASELINE.json), absolute seconds are
informational, and run digests exclude the profile block entirely.

The memory side: `memory_budget` snapshots `compiled.memory_analysis()`
(argument/output/temp/alias bytes) per rung shape — committed for the
100k and 1M rungs (doc/experiments/MEMORY_BUDGET.json) and consumed by
`perf.verify_wall`'s HBM bound as a capacity check.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# Phase registry.
#
# BOTH assignments below must stay PURE LITERALS: corrolint CT010 parses
# them with `ast.literal_eval` (no jax, no import of this module) to
# learn the registered scope strings, and flags any `jax.named_scope`
# string in the sim tier that is not `_SCOPE_PREFIX + <key>` here.  An
# unregistered scope would not be a crash — it would silently inflate
# the unattributed residual, which is exactly the failure mode the lint
# exists to catch.
# ---------------------------------------------------------------------------

_SCOPE_PREFIX = "corro."

PHASES = {
    "sampler": "peer-sampler target draws (PeerSwap ticks + member sampling)",
    "inject": "payload injection (writer commits entering the system)",
    "broadcast": "broadcast scatter (fan-out sends into the delay ring)",
    "sync": "anti-entropy sync gather (needs, grants, ring writes, backoff)",
    "deliver": "delay-ring pop and holdings merge",
    "swim": "SWIM probe/suspicion/gossip membership pass",
    "gaps": "bookkeeping refresh (touched/heads/gap interval extraction)",
    "converge": "convergence record (coverage/converged-at metrics)",
    "telemetry": "flight-recorder counters (RoundTrace channels)",
}

# Fallback attribution for ops whose scope path XLA DROPPED: the
# scatter expander (and friends) rebuild instructions keeping only the
# inner computation's short op_name + source_file, so a `corro.sampler`
# scatter resurfaces as `/max @ pswim.py:298`.  Files listed here are
# SINGLE-PHASE kernels — an op sourced from one of them belongs to that
# phase whenever its op_name carries no registered scope.  Multi-phase
# files (round.py, packed.py, faults.py, state.py) are deliberately
# absent: guessing there would silently misattribute, and the loud
# residual is the honest answer.
FILE_PHASE_HINTS = {
    "broadcast.py": "broadcast",
    # the fused traversal helpers (ISSUE 19) are only ever called from
    # telemetry consumers — counter math is flight-recorder cost even
    # when the expression fuses into a kernel's word pass
    "fused.py": "telemetry",
    "gaps.py": "gaps",
    "pswim.py": "sampler",
    "swim.py": "swim",
    "sync.py": "sync",
    "telemetry.py": "telemetry",
}

# Multi-phase files need FUNCTION-level hints: source_line → enclosing
# top-level `def` (resolved by reading the source at capture time) →
# phase.  Only the four packed phase kernels are listed; the pack/
# unpack envelope and shared word utilities stay unhinted — their time
# belongs to whoever fused them, or honestly to the residual.
FUNC_PHASE_HINTS = {
    "packed.py": {
        "inject_packed": "inject",
        "broadcast_packed": "broadcast",
        "sync_packed": "sync",
        "deliver_packed": "deliver",
    },
}

# default band half-width for committed baselines (fraction points) and
# the loud-residual ceiling the 25k storm baseline is accepted against
DEFAULT_PHASE_TOL = 0.05
DEFAULT_UNATTRIBUTED_MAX = 0.15

# The xplane → trace.json converter silently drops device events past
# ~1M; a capture that dense has biased fractions and must not band a
# baseline.  One captured round has to fit under this — the profile
# rung captures a k_rounds=1 body for exactly that reason.
TRACE_EVENT_CAP = 950_000

# HLO opcodes whose trace span COVERS their body ops' spans — summing
# them alongside their children would double-count the whole loop
_CONTAINER_OPS = frozenset(
    {"while", "conditional", "call", "async-start", "async-update",
     "async-done"}
)


def scope_name(phase: str) -> str:
    """The `jax.named_scope` string for a registered phase key."""
    if phase not in PHASES:
        raise KeyError(
            f"unregistered profiler phase {phase!r}; add it to "
            f"corrosion_tpu/sim/profile.py PHASES (corrolint CT010 "
            f"enforces the registry)"
        )
    return _SCOPE_PREFIX + phase


def phase_scope(phase: str):
    """Context manager annotating traced ops with a registered phase.

    Metadata-only by construction (`jax.named_scope` changes op_name
    metadata, never the computation); ``CORRO_PHASE_SCOPES=0`` disables
    annotation entirely (a nullcontext) so the byte-identity test can
    compile both variants and compare executables.  jax is imported
    lazily — this module stays importable on the jax-free CLI paths.
    """
    name = scope_name(phase)  # registry check even when disabled
    if os.environ.get("CORRO_PHASE_SCOPES", "1") == "0":
        return contextlib.nullcontext()
    import jax

    return jax.named_scope(name)


@contextlib.contextmanager
def trace_capture(profile_dir: str):
    """Profiler capture window (`jax.profiler.start_trace/stop_trace`)
    with the stop riding a finally, so a crashing captured region still
    flushes the trace it exists to explain."""
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    jax.profiler.start_trace(profile_dir)
    try:
        yield profile_dir
    finally:
        jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# Capture-time op → phase map (needs the compiled HLO text, not jax).
# ---------------------------------------------------------------------------

_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(.*)$")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_MODULE_RE = re.compile(r"^HloModule\s+([\w.\-]+)", re.M)
_SCOPE_RE = re.compile(re.escape(_SCOPE_PREFIX) + r"([A-Za-z0-9_]+)")
_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_CALLS_RE = re.compile(r"calls=%([\w.\-]+)")
_SOURCE_RE = re.compile(r'source_file="([^"]*)"')
_SOURCELINE_RE = re.compile(r"source_line=(\d+)")

_DEF_CACHE: Dict[str, List[Tuple[int, str]]] = {}


def _func_at_line(path: str, lineno: int) -> Optional[str]:
    """Name of the top-level `def` enclosing ``lineno`` in ``path``
    (used to resolve FUNC_PHASE_HINTS at capture time, where the repo
    source exists; returns None when the file is unreadable — the
    offline parser never needs it, the hints are baked into the map)."""
    defs = _DEF_CACHE.get(path)
    if defs is None:
        defs = []
        try:
            with open(path) as f:
                for i, line in enumerate(f, 1):
                    dm = re.match(r"def\s+(\w+)", line)
                    if dm:
                        defs.append((i, dm.group(1)))
        except OSError:
            pass
        _DEF_CACHE[path] = defs
    name = None
    for start, fn in defs:
        if start > lineno:
            break
        name = fn
    return name


def _opcode_of(rhs: str) -> Optional[str]:
    """Opcode of an HLO instruction right-hand side: skip the result
    type (possibly a parenthesised tuple type with nested parens), then
    take the identifier before the operand list's '('."""
    rhs = rhs.lstrip()
    if rhs.startswith("("):
        depth = 0
        for i, ch in enumerate(rhs):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    rhs = rhs[i + 1:].lstrip()
                    break
        else:
            return None
    else:
        sp = rhs.find(" ")
        if sp > 0:
            rhs = rhs[sp + 1:].lstrip()
    m = re.match(r"([\w\-]+)\(", rhs)
    return m.group(1) if m else None


def hlo_op_phase_map(
    hlo_text: str,
) -> Tuple[Optional[str], Dict[str, Dict[str, object]]]:
    """Extract (module_name, {instruction_name: {phase?, container?}})
    from a compiled executable's HLO text.

    Every instruction gets an entry — an empty dict means "in this
    module but carries no registered scope", which the parser must count
    as unattributed rather than silently dropping.  Innermost (last)
    ``corro.<phase>`` occurrence in the op_name path wins.  On the rare
    duplicate instruction name across computations, a phased entry is
    never overwritten by an unphased one (fusion-internal instructions
    share the namespace but never execute as trace events).

    XLA's optimization pipeline strips or rewrites the scope path on
    many ops, so attribution falls back in three steps, each of which
    can relabel a dropped scope but never move time between phases:

    - ``source_file`` hint: the scatter expander rebuilds instructions
      keeping only the inner computation's short op_name + source file
      (`/max @ pswim.py:298`); `FILE_PHASE_HINTS` lists the
      single-phase kernel files.
    - UNANIMOUS-context inheritance, iterated to fixpoint: an op with
      no scope inherits a phase when the computation it calls
      (``calls=%fused_computation.N``) or the computation it is a
      member of resolves to exactly ONE phase.  A scatter's expanded
      while-body is unanimous (all its phased members came from the
      one scattered op), so its loop glue — the `add`/`copy`/
      index-fusion thunks that dominate CPU trace time — lands on the
      right phase; the outer round body is multi-phase, so its glue
      stays in the loud residual rather than being guessed at.
    """
    m = _MODULE_RE.search(hlo_text)
    module = m.group(1) if m else None
    ops: Dict[str, Dict[str, object]] = {}
    members: Dict[str, List[str]] = {}  # comp -> instruction names
    calls: Dict[str, str] = {}  # instruction -> called computation
    comp_of: Dict[str, str] = {}  # instruction -> enclosing computation
    comp = ""
    for line in hlo_text.splitlines():
        cm = _COMP_RE.match(line)
        if cm:
            comp = cm.group(1)
            continue
        im = _INSTR_RE.match(line)
        if not im:
            continue
        name, rhs = im.groups()
        entry: Dict[str, object] = {}
        om = _OPNAME_RE.search(line)
        if om:
            hits = _SCOPE_RE.findall(om.group(1))
            for h in reversed(hits):  # innermost scope wins
                if h in PHASES:
                    entry["phase"] = h
                    break
        if "phase" not in entry:
            sm = _SOURCE_RE.search(line)
            if sm:
                src = sm.group(1)
                base = src.rsplit("/", 1)[-1]
                hint = FILE_PHASE_HINTS.get(base)
                if hint is None and base in FUNC_PHASE_HINTS:
                    lm = _SOURCELINE_RE.search(line)
                    if lm:
                        fn = _func_at_line(src, int(lm.group(1)))
                        hint = FUNC_PHASE_HINTS[base].get(fn)
                if hint:
                    entry["phase"] = hint
        if _opcode_of(rhs) in _CONTAINER_OPS:
            entry["container"] = True
        else:
            callm = _CALLS_RE.search(rhs)
            if callm:
                calls[name] = callm.group(1)
        old = ops.get(name)
        if old is None or ("phase" in entry or "phase" not in old):
            ops[name] = entry
            members.setdefault(comp, []).append(name)
            comp_of[name] = comp

    def _unanimous(comp_name: str) -> Optional[str]:
        found = {
            ops[n]["phase"]
            for n in members.get(comp_name, ())
            if "phase" in ops[n]
        }
        return found.pop() if len(found) == 1 else None

    changed = True
    while changed:
        changed = False
        uni = {c: _unanimous(c) for c in members}
        for name, entry in ops.items():
            if "phase" in entry or entry.get("container"):
                continue
            phase = uni.get(calls[name]) if name in calls else None
            if phase is None:
                phase = uni.get(comp_of.get(name, ""))
            if phase is not None:
                entry["phase"] = phase
                changed = True
    return module, ops


def write_phase_map(
    profile_dir: str, hlo_texts: Iterable[str]
) -> str:
    """Write ``phase_map.json`` next to a profiler capture, from the
    compiled HLO text(s) of the executables that ran under the capture
    window.  The offline parser joins trace events against this file."""
    modules: Dict[str, Dict[str, Dict[str, object]]] = {}
    for text in hlo_texts:
        module, ops = hlo_op_phase_map(text)
        if module is None:
            continue
        modules.setdefault(module, {}).update(ops)
    doc = {
        "kind": "phase_map",
        "prefix": _SCOPE_PREFIX,
        "phases": sorted(PHASES),
        "modules": modules,
    }
    path = os.path.join(profile_dir, "phase_map.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, sort_keys=True)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# Offline trace parsing (jax-free).
# ---------------------------------------------------------------------------


def find_trace_file(profile_dir: str) -> str:
    """Newest trace-event file under a profiler capture directory
    (`plugins/profile/<ts>/<host>.trace.json.gz` in current jax)."""
    cands: List[str] = []
    for pat in ("**/*.trace.json.gz", "**/*.trace.json"):
        cands.extend(
            glob.glob(os.path.join(profile_dir, pat), recursive=True)
        )
    if not cands:
        raise FileNotFoundError(
            f"no *.trace.json[.gz] under {profile_dir!r} — was the "
            "profiler capture flushed (stop_trace)?"
        )
    return max(cands, key=os.path.getmtime)


def load_trace_events(trace_path: str) -> List[dict]:
    opener = gzip.open if trace_path.endswith(".gz") else open
    with opener(trace_path, "rt") as f:
        doc = json.load(f)
    events = doc.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError(f"{trace_path!r}: traceEvents is not a list")
    return events


def parse_phase_profile(
    profile_dir: str,
    phase_map: Optional[dict] = None,
    trace_path: Optional[str] = None,
) -> Dict[str, object]:
    """Fold a profiler capture into the deterministic ``phase_profile``
    record: per-phase device seconds + fraction, with the unattributed
    residual reported loudly (top offending ops by time included).

    Only complete-duration ("X") events whose ``args.hlo_module`` is in
    the phase map are folded — the capture window may also contain other
    modules (warmup jits, harness glue), which are NOT this ledger's
    subject.  Container ops are skipped (their spans cover their body).
    Absolute seconds are informational; the committed baseline bands
    FRACTIONS only, so the record is wall-insensitive by construction.
    """
    if phase_map is None:
        map_path = os.path.join(profile_dir, "phase_map.json")
        with open(map_path) as f:
            phase_map = json.load(f)
    if trace_path is None:
        trace_path = find_trace_file(profile_dir)
    modules = phase_map.get("modules", {})
    per: Dict[str, float] = {k: 0.0 for k in phase_map.get(
        "phases", sorted(PHASES)
    )}
    unattr = 0.0
    unattr_ops: Dict[str, float] = {}
    total = 0.0
    n_events = 0
    for ev in load_trace_events(trace_path):
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        mod = args.get("hlo_module")
        if mod not in modules:
            continue
        op = args.get("hlo_op") or ev.get("name")
        info = modules[mod].get(op)
        if info is not None and info.get("container"):
            continue
        dur_s = float(ev.get("dur", 0)) * 1e-6  # trace durs are µs
        total += dur_s
        n_events += 1
        phase = info.get("phase") if info is not None else None
        if phase in per:
            per[phase] += dur_s
        else:
            unattr += dur_s
            unattr_ops[op] = unattr_ops.get(op, 0.0) + dur_s
    top_unattr = sorted(
        unattr_ops.items(), key=lambda kv: (-kv[1], kv[0])
    )[:8]
    return {
        "kind": "phase_profile",
        "trace_file": os.path.basename(trace_path),
        "modules": sorted(modules),
        "device_events": n_events,
        # the trace converter DROPS events past ~1M — a saturated
        # capture has biased fractions, and the compare gate refuses it
        "trace_saturated": n_events >= TRACE_EVENT_CAP,
        "total_s": round(total, 6),
        "phases": {
            name: {
                "s": round(s, 6),
                "frac": round(s / total, 4) if total > 0 else 0.0,
            }
            for name, s in per.items()
        },
        "unattributed": {
            "s": round(unattr, 6),
            "frac": round(unattr / total, 4) if total > 0 else 0.0,
            "top_ops": [
                {"op": op, "s": round(s, 6)} for op, s in top_unattr
            ],
        },
    }


# ---------------------------------------------------------------------------
# Baselines and comparison (jax-free; the nightly profile-smoke gate).
# ---------------------------------------------------------------------------


def baseline_from_profile(
    record: Dict[str, object],
    scenario: str,
    tol: float = DEFAULT_PHASE_TOL,
    unattributed_frac_max: float = DEFAULT_UNATTRIBUTED_MAX,
    extra: Optional[Dict[str, object]] = None,
) -> Dict[str, object]:
    """Band a measured ``phase_profile`` into a committable baseline:
    per-phase fraction ± tol, plus the unattributed ceiling.  Seconds
    and walls are deliberately NOT banded (the gate must hold across
    machines; only the phase SHAPE is claimed).  ``extra`` merges
    caller keys into the doc — notably ``phase_frac_max`` (one-sided
    per-phase ceilings, e.g. the ISSUE 19 telemetry-collapse proof),
    which `compare_profiles` enforces alongside the two-sided bands."""
    doc: Dict[str, object] = {
        "kind": "profile_baseline",
        "scenario": scenario,
        "phases": {
            name: {"frac": rec["frac"], "tol": tol}
            for name, rec in sorted(record["phases"].items())
        },
        "unattributed_frac_max": unattributed_frac_max,
    }
    if extra:
        doc.update(extra)
    return doc


def compare_profiles(
    baseline: Dict[str, object], candidate: Dict[str, object]
) -> List[str]:
    """Gate a candidate ``phase_profile`` against a committed baseline.
    Returns the list of violations (empty = pass).  Fractions only —
    a faster or slower machine shifts every phase's seconds together
    and leaves the fractions (and this gate) alone."""
    failures: List[str] = []
    if candidate.get("trace_saturated"):
        failures.append(
            f"trace saturated ({candidate.get('device_events')} device "
            f"events >= {TRACE_EVENT_CAP} converter cap) — fractions "
            "are biased; capture fewer rounds or a smaller shape"
        )
    cand_phases = candidate.get("phases", {})
    for name, band in sorted(baseline.get("phases", {}).items()):
        base = float(band["frac"])
        tol = float(band.get("tol", DEFAULT_PHASE_TOL))
        got = float(cand_phases.get(name, {}).get("frac", 0.0))
        if abs(got - base) > tol:
            failures.append(
                f"phase {name}: frac {got:.4f} outside "
                f"{base:.4f} ± {tol:.4f}"
            )
    # one-sided phase ceilings (``phase_frac_max``, an ISSUE 19 baseline
    # key): unlike the two-sided bands above, a ceiling encodes "this
    # phase COLLAPSED into the traversal and must stay collapsed" — the
    # telemetry ceiling is the mechanical proof a future counter
    # unfusion regresses red instead of drifting inside a wide band
    for name, cap in sorted(
        (baseline.get("phase_frac_max") or {}).items()
    ):
        got = float(cand_phases.get(name, {}).get("frac", 0.0))
        if got > float(cap):
            failures.append(
                f"phase {name}: frac {got:.4f} exceeds the "
                f"{float(cap):.4f} phase_frac_max ceiling (a "
                "counter-unfusion regression? see doc/telemetry/"
                "profiling.md, fused round)"
            )
    cap = baseline.get("unattributed_frac_max")
    if cap is not None:
        got = float(
            candidate.get("unattributed", {}).get("frac", 1.0)
        )
        if got > float(cap):
            failures.append(
                f"unattributed residual {got:.4f} exceeds the "
                f"{float(cap):.4f} ceiling (a kernel grew an "
                "unregistered scope? see corrolint CT010)"
            )
    return failures


# ---------------------------------------------------------------------------
# Memory budgets (compiled.memory_analysis() snapshots).
# ---------------------------------------------------------------------------

_MEM_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def memory_budget(compiled, label: Optional[str] = None) -> Dict[str, object]:
    """Snapshot a compiled executable's memory analysis into the
    ``memory_budget`` record `verify_wall` consumes: argument / output /
    temp / alias bytes plus the peak-device estimate (arguments and
    outputs double-count donated aliases, hence the subtraction)."""
    ma = compiled.memory_analysis()
    rec: Dict[str, object] = {"kind": "memory_budget"}
    if label is not None:
        rec["label"] = label
    for field in _MEM_FIELDS:
        rec[field.replace("_size_in_bytes", "_bytes")] = int(
            getattr(ma, field, 0) or 0
        )
    rec["peak_bytes_est"] = (
        rec["argument_bytes"]
        + rec["output_bytes"]
        + rec["temp_bytes"]
        - rec["alias_bytes"]
    )
    return rec


# ---------------------------------------------------------------------------
# Rendering (the `sim profile show|compare` tables; jax-free).
# ---------------------------------------------------------------------------


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def render_phase_table(record: Dict[str, object]) -> str:
    """The phase ledger as an aligned text table, largest phase first,
    residual last and flagged when it breaches the default ceiling."""
    lines = [
        f"phase ledger  ({record.get('device_events', 0)} device ops, "
        f"{float(record.get('total_s', 0.0)) * 1e3:.1f} ms device time, "
        f"trace {record.get('trace_file', '?')})",
        f"  {'phase':<12} {'seconds':>10} {'frac':>7}",
    ]
    phases = record.get("phases", {})
    for name, rec in sorted(
        phases.items(), key=lambda kv: (-kv[1]["s"], kv[0])
    ):
        lines.append(
            f"  {name:<12} {rec['s']:>10.4f} {rec['frac']:>7.1%}"
        )
    un = record.get("unattributed", {"s": 0.0, "frac": 0.0})
    flag = (
        "  <-- above the "
        f"{DEFAULT_UNATTRIBUTED_MAX:.0%} ceiling"
        if un.get("frac", 0.0) > DEFAULT_UNATTRIBUTED_MAX
        else ""
    )
    lines.append(
        f"  {'unattributed':<12} {un['s']:>10.4f} "
        f"{un['frac']:>7.1%}{flag}"
    )
    for op in un.get("top_ops", [])[:4]:
        lines.append(f"    residual op {op['op']}: {op['s']:.4f}s")
    return "\n".join(lines)


def render_memory_table(record: Dict[str, object]) -> str:
    label = record.get("label")
    head = "memory budget" + (f"  [{label}]" if label else "")
    rows = [head]
    for key in (
        "argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
        "generated_code_bytes", "peak_bytes_est",
    ):
        if key in record:
            rows.append(f"  {key:<22} {_fmt_bytes(record[key]):>12}")
    return "\n".join(rows)


def render_compare(
    baseline: Dict[str, object],
    candidate: Dict[str, object],
    failures: Sequence[str],
) -> str:
    lines = [
        f"baseline scenario: {baseline.get('scenario', '?')}",
        f"  {'phase':<12} {'baseline':>9} {'candidate':>10} {'tol':>6}",
    ]
    cand_phases = candidate.get("phases", {})
    for name, band in sorted(baseline.get("phases", {}).items()):
        got = cand_phases.get(name, {}).get("frac", 0.0)
        lines.append(
            f"  {name:<12} {band['frac']:>9.1%} {got:>10.1%} "
            f"{band.get('tol', DEFAULT_PHASE_TOL):>6.1%}"
        )
    for name, pcap in sorted(
        (baseline.get("phase_frac_max") or {}).items()
    ):
        got = cand_phases.get(name, {}).get("frac", 0.0)
        lines.append(
            f"  ceiling {name}: {got:.1%} (max {float(pcap):.1%})"
        )
    un = candidate.get("unattributed", {}).get("frac", 0.0)
    cap = baseline.get("unattributed_frac_max", DEFAULT_UNATTRIBUTED_MAX)
    lines.append(f"  unattributed {un:.1%} (ceiling {cap:.1%})")
    if failures:
        lines.append("FAIL:")
        lines.extend(f"  - {f}" for f in failures)
    else:
        lines.append("OK: candidate within every baseline band")
    return "\n".join(lines)
