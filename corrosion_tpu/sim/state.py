"""Simulator state: the cluster as device arrays.

This is the north star's "node×changeset-version matrix" (BASELINE.json):
the reference's per-node `BookedVersions`/broadcast queues/SWIM state
(SURVEY.md §2.3) become node-major tensors, and one jitted `round_step`
advances every node at once.

State layout:
- ``have[N, P] u8``     — node n holds payload p (a changeset chunk).  This is
  the on-device form of corro-types' `Changeset` dissemination state: L6
  broadcast marks bits via sampled fan-out edges, L7 sync fills them via
  pairwise need pulls (need = ~have[i] & have[j], which is exactly
  `compute_available_needs` restricted to the active window).
- ``relay_left[N, P] u8`` — remaining epidemic retransmissions
  (`max_transmissions` decay, broadcast/mod.rs:653-778).
- ``inflight[D, N, P] u8`` — latency ring buffer: deliveries scheduled d
  rounds ahead (RTT-ring classes, members.rs:38).
- SWIM (full-view mode, for N ≤ a few thousand):
  ``view[N, N] i8`` (what i believes about j: 0 alive / 1 suspect / 2 down),
  ``vinc[N, N] i32`` believed incarnations, ``suspect_since[N, N] i32``.
  At 100k nodes the sim runs ground-truth membership (alive mask only) —
  the dissemination question doesn't need per-node views at that scale.
- ``alive[N] u8`` ground truth up/down; ``incarnation[N] u32``.
- ``group[N] i32`` partition group (edges across groups are cut).

Payload metadata (static per scenario): ``p_actor[P]``, ``p_version[P]``,
``p_chunk[P]``, ``p_nchunks[P]``, ``p_bytes[P]``, ``p_round[P]`` (injection
round; a payload activates once the sim reaches it).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

ALIVE, SUSPECT, DOWN = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static configuration (hashable: goes into jit closure).

    Defaults mirror the reference's operating envelope (BASELINE.md):
    fanout from `choose_count` (broadcast/mod.rs:653-680), max_transmissions
    and WAN SWIM parameters from foca's config (broadcast/mod.rs:951-960),
    sync cadence from config.rs:49-59, 10 MiB/s rate limit from
    broadcast/mod.rs:460-463.  One round ≈ one broadcast flush tick (500 ms).
    """

    n_nodes: int
    n_payloads: int
    # broadcast (L6)
    fanout: int = 3  # num_indirect_probes floor of choose_count
    max_transmissions: int = 10
    rate_limit_bytes_round: int = 5 * 1024 * 1024  # 10 MiB/s * 0.5 s tick
    # sync (L7) — cadence in rounds: backoff 1-15 s ≈ 2-30 rounds
    sync_interval_rounds: int = 8
    sync_peers: int = 3  # (n/100).clamp(3,10)
    sync_budget_bytes: int = 4 * 1024 * 1024
    # SWIM (L5)
    swim_full_view: bool = False
    probe_period_rounds: int = 2  # probe every ~1 s
    suspect_timeout_rounds: int = 6  # ~3 s suspicion
    indirect_probes: int = 3
    # latency model: delivery delay in rounds per latency class
    n_delay_slots: int = 4
    # payload byte size assumed when metadata gives none
    default_payload_bytes: int = 8 * 1024

    def sync_peers_clamped(self) -> int:
        return max(3, min(10, self.n_nodes // 100 or 3))


class PayloadMeta(NamedTuple):
    """Static per-payload metadata arrays (device)."""

    actor: jnp.ndarray  # i32[P] origin node index
    version: jnp.ndarray  # i32[P] db_version
    chunk: jnp.ndarray  # i32[P] chunk index within version
    nchunks: jnp.ndarray  # i32[P]
    nbytes: jnp.ndarray  # i32[P]
    round: jnp.ndarray  # i32[P] injection round


class SimState(NamedTuple):
    """Dynamic per-round state (device pytree)."""

    t: jnp.ndarray  # i32 scalar round counter
    key: jnp.ndarray  # PRNG key
    have: jnp.ndarray  # u8[N, P]
    injected: jnp.ndarray  # u8[P] payload entered the system (origin was up)
    relay_left: jnp.ndarray  # u8[N, P]
    inflight: jnp.ndarray  # u8[D, N, P]
    sync_countdown: jnp.ndarray  # i32[N]
    alive: jnp.ndarray  # u8[N] ground truth (0 = up!  uses ALIVE/DOWN consts)
    incarnation: jnp.ndarray  # u32[N]
    group: jnp.ndarray  # i32[N] partition group
    # SWIM full-view mode (zero-sized when disabled)
    view: jnp.ndarray  # i8[N, N] or [0, 0]
    vinc: jnp.ndarray  # i32[N, N] or [0, 0]
    suspect_since: jnp.ndarray  # i32[N, N] or [0, 0]
    # per-node converged-at round (-1 while not converged) for p99 stats
    converged_at: jnp.ndarray  # i32[N]


def init_state(cfg: SimConfig, key: jax.Array) -> SimState:
    n, p = cfg.n_nodes, cfg.n_payloads
    swim_n = cfg.n_nodes if cfg.swim_full_view else 0
    key, sub = jax.random.split(key)
    return SimState(
        t=jnp.zeros((), jnp.int32),
        key=key,
        have=jnp.zeros((n, p), jnp.uint8),
        injected=jnp.zeros((p,), jnp.uint8),
        relay_left=jnp.zeros((n, p), jnp.uint8),
        inflight=jnp.zeros((cfg.n_delay_slots, n, p), jnp.uint8),
        sync_countdown=jax.random.randint(
            sub, (n,), 0, cfg.sync_interval_rounds, jnp.int32
        ),
        alive=jnp.zeros((n,), jnp.uint8),
        incarnation=jnp.zeros((n,), jnp.uint32),
        group=jnp.zeros((n,), jnp.int32),
        view=jnp.zeros((swim_n, swim_n), jnp.int8),
        vinc=jnp.zeros((swim_n, swim_n), jnp.int32),
        suspect_since=jnp.full((swim_n, swim_n), -1, jnp.int32),
        converged_at=jnp.full((n,), -1, jnp.int32),
    )


def budget_prefix_mask(mask: jnp.ndarray, budget_bytes: int, cfg: SimConfig) -> jnp.ndarray:
    """Oldest-first byte budget as a count rank: keep the first
    ``budget_bytes // default_payload_bytes`` True entries along the last
    (payload) axis.  Payload size is uniform (uniform_payloads enforces
    it), payloads are version-major, so a prefix of the index order is
    exactly the reference's oldest-first drain.  Shared by the broadcast
    governor and the sync budget."""
    p = mask.shape[-1]
    # clamp to p: rank never exceeds p, and an unclamped "unlimited"
    # budget must not overflow the narrow rank dtype.  A budget below one
    # payload sends NOTHING — matching the reference's governor, which
    # simply blocks until the limiter has room (broadcast/mod.rs:460-463)
    max_count = min(budget_bytes // cfg.default_payload_bytes, p)
    if max_count <= 0:
        return jnp.zeros_like(mask)
    rank_dtype = jnp.int16 if p <= 32767 else jnp.int32
    cum = jnp.cumsum(mask, axis=-1, dtype=rank_dtype)  # 1-indexed rank
    return mask & (cum <= max_count)


def uniform_payloads(
    cfg: SimConfig,
    n_writers: int = 1,
    versions_per_writer: Optional[int] = None,
    chunks_per_version: int = 1,
    inject_every: int = 1,
    payload_bytes: Optional[int] = None,
) -> PayloadMeta:
    """A write-storm scenario: ``n_writers`` origins each commit versions of
    ``chunks_per_version`` chunks, injected ``inject_every`` rounds apart.

    The payload axis is **version-major** — index order IS (version,
    actor, chunk) order, which is also injection order since the inject
    round is monotone in version.  Both hot kernels rely on this: the
    broadcast rate limiter drains oldest-first by index
    (broadcast.py) and the sync budget grants oldest-version-first
    WITHOUT any per-round permutation (sync.py)."""
    p = cfg.n_payloads
    if n_writers > p:
        raise ValueError(
            f"n_writers={n_writers} exceeds n_payloads={p}: every writer "
            "needs at least one payload"
        )
    if payload_bytes is not None and payload_bytes != cfg.default_payload_bytes:
        # the kernels' byte budgets are count-ranks derived from the
        # static cfg.default_payload_bytes — set that instead
        raise ValueError(
            "payload_bytes must equal cfg.default_payload_bytes "
            f"({cfg.default_payload_bytes}); set it on SimConfig"
        )
    wave = n_writers * chunks_per_version  # payloads per version wave
    if wave > p:
        # version-major layout fills whole waves; a partial first wave
        # would silently leave the highest-index writers with nothing
        raise ValueError(
            f"n_writers*chunks_per_version={wave} exceeds n_payloads={p}: "
            "every writer needs at least one full version"
        )
    per_writer = p // n_writers
    vpw = versions_per_writer or max(1, per_writer // chunks_per_version)
    idx = jnp.arange(p, dtype=jnp.int32)
    raw_version = 1 + idx // wave
    actor = (idx % wave) // chunks_per_version
    chunk = idx % chunks_per_version
    # writers spread across the node id space
    actor_node = (actor * max(1, cfg.n_nodes // n_writers)) % cfg.n_nodes
    return PayloadMeta(
        actor=actor_node.astype(jnp.int32),
        version=jnp.minimum(raw_version, vpw).astype(jnp.int32),
        chunk=chunk.astype(jnp.int32),
        nchunks=jnp.full((p,), chunks_per_version, jnp.int32),
        nbytes=jnp.full(
            (p,), payload_bytes or cfg.default_payload_bytes, jnp.int32
        ),
        # schedule from the UNCLAMPED version so payloads past the vpw
        # cap keep injecting inject_every rounds apart instead of
        # collapsing into one burst
        round=((raw_version - 1) * inject_every).astype(jnp.int32),
    )


