"""Simulator state: the cluster as device arrays.

This is the north star's "node×changeset-version matrix" (BASELINE.json):
the reference's per-node `BookedVersions`/broadcast queues/SWIM state
(SURVEY.md §2.3) become node-major tensors, and one jitted `round_step`
advances every node at once.

State layout (the north-star "node×changeset-version matrix"):
- ``have[N, P] u8``     — node n holds payload p (a changeset chunk).  The
  payload axis is a flattened (version, actor, chunk) grid — ``have`` IS the
  seq-occupancy bitmap of SURVEY §5's long-context analog.  A version counts
  as **applied** only when every one of its chunks arrived (the reference's
  fully-buffered gate, util.rs:986-1005, run_root.rs:180-194); convergence
  counts applied versions, never loose chunks.
- ``heads[N, A] i32``   — per (node, origin-actor) max version seen (any
  chunk), ≡ `BookedVersions.last()` / the `heads` advertised in
  `generate_sync` (sync.rs:284-333).
- ``gap_lo/gap_hi[N, A, K] i32`` — fixed-K needed version ranges per
  (node, actor), 1-based inclusive, 0 = empty slot: the device form of the
  `__corro_bookkeeping_gaps` interval algebra (agent.rs:1092-1236).  L7 sync
  computes needs from these tensors (see sim/gaps.py).
- ``relay_left[N, P] u8`` — remaining epidemic retransmissions
  (`max_transmissions` decay, broadcast/mod.rs:653-778).
- ``inflight[D, N, P] u8`` — latency ring buffer: deliveries scheduled d
  rounds ahead (RTT-ring classes, members.rs:38).
- SWIM (full-view mode, for N ≤ a few thousand):
  ``view[N, N] i8`` (what i believes about j: 0 alive / 1 suspect / 2 down),
  ``vinc[N, N] i32`` believed incarnations, ``suspect_since[N, N] i32``.
  At 100k nodes the sim runs ground-truth membership (alive mask only) —
  the dissemination question doesn't need per-node views at that scale.
- ``alive[N] u8`` ground truth up/down; ``incarnation[N] u32``.
- ``group[N] i32`` partition group (edges across groups are cut).

Payload metadata (static per scenario): ``p_actor[P]``, ``p_version[P]``,
``p_chunk[P]``, ``p_nchunks[P]``, ``p_bytes[P]``, ``p_round[P]`` (injection
round; a payload activates once the sim reaches it).
"""

from __future__ import annotations

import dataclasses
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

ALIVE, SUSPECT, DOWN = 0, 1, 2


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Static configuration (hashable: goes into jit closure).

    Defaults mirror the reference's operating envelope (BASELINE.md):
    fanout from `choose_count` (broadcast/mod.rs:653-680), max_transmissions
    and WAN SWIM parameters from foca's config (broadcast/mod.rs:951-960),
    sync cadence from config.rs:49-59, 10 MiB/s rate limit from
    broadcast/mod.rs:460-463.  One round ≈ one broadcast flush tick (500 ms).
    """

    n_nodes: int
    n_payloads: int
    # payload layout: P = n_versions * n_writers * chunks_per_version in
    # version-major order (see uniform_payloads); the kernels reshape
    # have[N, P] into the (node, actor, version, chunk) grid with these
    n_writers: int = 1
    chunks_per_version: int = 1
    # fixed-K gap interval slots per (node, actor) — SURVEY §7 state layout
    gap_slots: int = 8
    # broadcast (L6)
    fanout: int = 3  # num_indirect_probes floor of choose_count
    max_transmissions: int = 10
    # None = statically unmetered (caller proved the budget can't bind)
    rate_limit_bytes_round: Optional[int] = 5 * 1024 * 1024  # 10 MiB/s * 0.5 s tick
    # sync (L7) — cadence in rounds: backoff 1-15 s ≈ 2-30 rounds
    sync_interval_rounds: int = 8
    # fruitless syncs DOUBLE the re-arm window up to this cap, fruitful
    # syncs reset it to sync_interval_rounds — the host tier's
    # decorrelated backoff with reset-on-ingest (agent.py _sync_loop,
    # util.rs:347-393).  0 = default 4× the base interval (host
    # max/min backoff ratio is 6×; the uniform re-arm draw halves the
    # mean, so 4× lands the same effective cadence).  Ground-truth
    # fidelity: without growth the sim recovered from partitions
    # unrealistically fast (r4 calibration sweep).
    sync_backoff_max_rounds: int = 0
    sync_peers: int = 3  # (n/100).clamp(3,10)
    sync_budget_bytes: Optional[int] = 4 * 1024 * 1024
    # SWIM (L5)
    swim_full_view: bool = False
    # partial-view SWIM (sim/pswim.py): O(N·M) direct-mapped member
    # tables instead of O(N²) belief matrices — the scale tier that lets
    # the 10k/100k configs run real membership (VERDICT r1 item 3)
    swim_partial_view: bool = False
    member_slots: int = 64  # M buckets per node
    gossip_entries: int = 8  # table entries piggybacked per gossip push
    # DOWN table entries resist eviction until this old (the
    # remove_down_after=48h analog, broadcast/mod.rs:951-960) so a
    # rejoining member can still be healed in place by precedence; kept
    # MUCH longer than refute/rejoin latency, as in the reference
    down_gc_rounds: int = 600
    # couple dissemination to membership: broadcast/sync/probe targets are
    # drawn from each node's believed member list (view != DOWN), so false
    # suspicion slows convergence exactly as in the reference, where
    # targets come from Members.states with down members removed
    # (broadcast/mod.rs:653-680, handlers.rs:279-366)
    couple_membership: bool = True
    # re-announce cadence: every tick each up node pushes its self-belief
    # to ONE uniformly random node, bypassing its own member list — the
    # bootstrap/announcer seam (spawn_swim_announcer, util.rs:104-123)
    # that recovers from mutual false-DOWN after partitions
    announce_interval_rounds: int = 4
    probe_period_rounds: int = 2  # probe every ~1 s
    suspect_timeout_rounds: int = 6  # ~3 s suspicion
    indirect_probes: int = 3
    # ring0-first broadcast tiering: the first fanout slot targets a
    # same-region (lowest-RTT-ring) member, mirroring the reference's
    # local-broadcast-to-ring0-first policy (members.rs:38-178,
    # broadcast/mod.rs:589-651); remaining slots sample globally
    ring0_first: bool = True
    # latency model: delivery delay in rounds per latency class
    n_delay_slots: int = 4
    # opt-out of the bitpacked round (sim/packed.py) even when the
    # scenario fits its envelope — a SimConfig field (not an env var) so
    # the choice is part of the jit cache key; the bench A/B rung flips
    # it to measure packed-vs-dense on identical scenarios
    allow_packed: bool = True
    # minimum n_nodes*n_payloads before the packed round dispatches: the
    # pack/unpack boundary has per-round fixed cost, so packing only wins
    # once the payload tensors are HBM-sized (measured CPU A/B r4 after
    # the kernel optimizations: 0.97x at 8k×512=4.2M cells, 1.15x at
    # 25k×512=12.8M, 1.24x at 100k×512=51M — crossover ≈ 10M);
    # tests force 0
    packed_min_cells: int = 10 * 1024 * 1024
    # payload byte size assumed when metadata gives none
    default_payload_bytes: int = 8 * 1024
    # flight-recorder round stride (ISSUE 7 satellite): record row t
    # only when t % trace_every == 0, so a 10k-payload × high-max_rounds
    # sweep allocates ceil(R/stride) trace rows instead of a full
    # [R_max, P] channel set.  1 (the default) is the exact recorder —
    # byte-identical buffers, stable digests; >1 is a deliberate
    # sampling (summary totals become stride samples, labeled as such)
    trace_every: int = 1
    # pluggable peer-selection seam (ISSUE 9): "uniform" draws every
    # broadcast/sync/probe target uniformly at random (the legacy
    # default — byte-identical programs, no extra state or RNG);
    # "peerswap" maintains an on-device per-node view
    # (`SimState.pview`, mixed by seeded pairwise entry swaps each
    # round — PAPERS.md "PeerSwap: A Peer-Sampler with Randomness
    # Guarantees") and draws targets from it.  A campaign axis: the
    # field rides `CampaignSpec.scenario`/`grid` like any SimConfig key.
    peer_sampler: str = "uniform"
    # PeerSwap view width V (SimState.pview is i32[N, V]; 0-width when
    # the sampler is uniform, the zero-cost off state)
    view_slots: int = 16
    # -- protocol-variant knobs (ISSUE 11; corrosion_tpu/proto) --------
    # Each defaults to the legacy protocol point and rides a trace-time
    # branch, so the default compiles byte-identically to pre-ISSUE-11
    # kernels (digest-pinned).  Named bundles live in
    # proto.families.FAMILIES (the `proto_family` campaign meta key);
    # doc/protocols.md is the catalog.
    # "push" = the reference's fire-and-forget fanout; "push-pull" =
    # every broadcast contact also pulls the contacted node's eligible
    # buffer back (a round-trip exchange, refused across a cut in
    # either direction — proto/dissemination.py)
    dissemination: str = "push"
    # "flat" = all fanout slots every round; "decay" = the active slot
    # count halves every fanout_decay_rounds, floored at 1
    # (proto/schedule.py — front-load the flood)
    fanout_schedule: str = "flat"
    fanout_decay_rounds: int = 8
    # "periodic" = the countdown/backoff sync loop (config.rs:49-59);
    # "eager" = every node syncs every round (the SWARM-style
    # near-zero-round replication limit)
    sync_cadence: str = "periodic"
    # "none" = gossip order; "fifo" = per-origin FIFO delivery ordering
    # ENFORCED at the delivery seam (out-of-order arrivals discarded,
    # re-served later — proto/ordering.py) with the delivery-order
    # invariant counted on-device (sim/invariants.py,
    # RunMetrics.order_violations); "fifo-unchecked" = the invariant is
    # measured but NOT enforced (the negative control that must trip it)
    ordering: str = "none"

    def __post_init__(self) -> None:
        if self.trace_every < 1:
            raise ValueError(
                f"trace_every must be >= 1, got {self.trace_every}"
            )
        wave = self.n_writers * self.chunks_per_version
        if self.n_payloads % wave != 0:
            raise ValueError(
                f"n_payloads={self.n_payloads} must be a multiple of "
                f"n_writers*chunks_per_version={wave} (version-major grid)"
            )
        if self.swim_full_view and self.swim_partial_view:
            raise ValueError("pick ONE of swim_full_view / swim_partial_view")
        if self.swim_partial_view and self.n_nodes > 262144:
            # pswim packs (belief_key, id) into one i32 scatter word:
            # id needs 18 bits (see pswim.py pack-bound asserts)
            raise ValueError("partial-view SWIM supports at most 2^18 nodes")
        if self.peer_sampler not in ("uniform", "peerswap"):
            raise ValueError(
                f"unknown peer_sampler {self.peer_sampler!r} "
                "(use 'uniform' or 'peerswap')"
            )
        if self.peer_sampler == "peerswap":
            if self.view_slots < 2:
                raise ValueError("peerswap needs view_slots >= 2")
            if self.swim_partial_view:
                # two competing member-state systems would fight over
                # target selection; pick one sampler per scenario
                raise ValueError(
                    "peer_sampler='peerswap' is incompatible with "
                    "swim_partial_view (the member tables ARE a sampler)"
                )
        # protocol-variant knobs (ISSUE 11): loud refusals — an unknown
        # or unsupported combination must never silently measure the
        # baseline protocol under a variant's name
        if self.dissemination not in ("push", "push-pull"):
            raise ValueError(
                f"unknown dissemination {self.dissemination!r} "
                "(use 'push' or 'push-pull')"
            )
        if self.fanout_schedule not in ("flat", "decay"):
            raise ValueError(
                f"unknown fanout_schedule {self.fanout_schedule!r} "
                "(use 'flat' or 'decay')"
            )
        if self.fanout_decay_rounds < 1:
            raise ValueError(
                f"fanout_decay_rounds must be >= 1, got "
                f"{self.fanout_decay_rounds}"
            )
        if self.sync_cadence not in ("periodic", "eager"):
            raise ValueError(
                f"unknown sync_cadence {self.sync_cadence!r} "
                "(use 'periodic' or 'eager')"
            )
        if self.ordering not in ("none", "fifo", "fifo-unchecked"):
            raise ValueError(
                f"unknown ordering {self.ordering!r} "
                "(use 'none', 'fifo', or 'fifo-unchecked')"
            )
        if self.ordering != "none" and self.n_versions < 2:
            # a single version per writer has no order to impose; a
            # membership/detect scenario naming an ordering variant
            # would otherwise silently measure nothing on that axis
            raise ValueError(
                "ordering variants need >= 2 versions per writer "
                f"(n_payloads={self.n_payloads}, n_writers="
                f"{self.n_writers}, chunks_per_version="
                f"{self.chunks_per_version} gives {self.n_versions})"
            )

    @classmethod
    def wan_tuned(cls, n_nodes: int, **kw) -> "SimConfig":
        """Cluster-size-adaptive SWIM timing — the analog of the reference
        re-tuning foca's WAN config as the cluster-size estimate moves
        (broadcast/mod.rs:236-256, 951-960): suspicion windows grow with
        log₂(N) so detection stays accurate as gossip paths lengthen, and
        the per-payload transmission budget follows the SAME formula the
        host runtime derives from live membership (core/swim_tuning.py),
        capped at 15 — the packed path's 4-bit relay planes
        (packed.py packed_supported).  A/B at 16k nodes: the derived
        budget leaves storm convergence identical (26 rounds, same p99)."""
        from ..core.swim_tuning import max_transmissions_for

        log = max(3, math.ceil(math.log2(n_nodes + 1)))
        kw.setdefault("probe_period_rounds", 2)
        kw.setdefault("suspect_timeout_rounds", log)
        kw.setdefault("indirect_probes", 3)
        kw.setdefault("announce_interval_rounds", max(4, log // 2))
        base = cls.__dataclass_fields__["max_transmissions"].default
        kw.setdefault(
            "max_transmissions", min(15, max_transmissions_for(n_nodes, base))
        )
        return cls(n_nodes=n_nodes, **kw)

    @property
    def n_versions(self) -> int:
        return self.n_payloads // (self.n_writers * self.chunks_per_version)

    def sync_backoff_cap(self) -> int:
        return self.sync_backoff_max_rounds or 4 * self.sync_interval_rounds

    def sync_peers_clamped(self) -> int:
        return max(3, min(10, self.n_nodes // 100 or 3))


# -- (actor, version, chunk) grid views of the payload axis ------------------
#
# Payload index p = (v * A + a) * C + c (version-major, uniform_payloads).
# These helpers are the only place that layout knowledge lives.


def chunk_grid(have: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[N, A, V, C] chunk-occupancy grid from have[N, P]."""
    n = have.shape[0]
    g = (have > 0).reshape(n, cfg.n_versions, cfg.n_writers, cfg.chunks_per_version)
    return g.transpose(0, 2, 1, 3)


def complete_versions(have: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[N, A, V]: version fully received (every chunk) — the apply gate
    (`process_fully_buffered_changes` fires only at gaps==0, util.rs:986)."""
    return chunk_grid(have, cfg).all(axis=3)


def touched_versions(have: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[N, A, V]: any chunk of the version arrived (≡ the version is in
    the bookie — complete or partial)."""
    return chunk_grid(have, cfg).any(axis=3)


def version_heads(touched: jnp.ndarray) -> jnp.ndarray:
    """i32[N, A] max 1-based version touched (BookedVersions.last())."""
    v = jnp.arange(1, touched.shape[2] + 1, dtype=jnp.int32)
    return (touched * v[None, None, :]).max(axis=2)


def grid_to_payload(x_av: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """Broadcast a per-(actor, version) array [..., A, V] back onto the
    payload axis [..., P]."""
    swapped = jnp.swapaxes(x_av, -1, -2)  # [..., V, A]
    tiled = jnp.repeat(swapped[..., None], cfg.chunks_per_version, axis=-1)
    return tiled.reshape(*x_av.shape[:-2], cfg.n_payloads)


def version_active(injected: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[A, V]: some chunk of the version was injected (the version
    exists cluster-wide)."""
    g = (injected > 0).reshape(cfg.n_versions, cfg.n_writers, cfg.chunks_per_version)
    return g.any(axis=2).T


MAX_PAYLOAD_BYTES = 64 * 1024  # keeps the i32 budget cumsum exact


def _payload_sizes(p: int, payload_bytes, cfg: SimConfig) -> jnp.ndarray:
    """i32[P] per-payload sizes from None | scalar | sequence, validated
    ≤ MAX_PAYLOAD_BYTES (the budget kernels' overflow contract)."""
    if payload_bytes is None:
        sizes = jnp.full((p,), cfg.default_payload_bytes, jnp.int32)
    elif jnp.ndim(payload_bytes) == 0:
        sizes = jnp.full((p,), int(payload_bytes), jnp.int32)
    else:
        sizes = jnp.asarray(payload_bytes, jnp.int32).reshape(p)
    import numpy as _np

    hi = int(_np.asarray(sizes).max()) if p else 0
    if hi > MAX_PAYLOAD_BYTES:
        raise ValueError(
            f"payload sizes must be ≤ {MAX_PAYLOAD_BYTES} B (got {hi}): "
            "the two-lane byte-budget cumsum (budget_prefix_mask) is "
            "exact only for sizes ≤ 64 KiB"
        )
    return sizes


class PayloadMeta(NamedTuple):
    """Static per-payload metadata arrays (device)."""

    actor: jnp.ndarray  # i32[P] origin node index
    version: jnp.ndarray  # i32[P] db_version
    chunk: jnp.ndarray  # i32[P] chunk index within version
    nchunks: jnp.ndarray  # i32[P]
    nbytes: jnp.ndarray  # i32[P]
    round: jnp.ndarray  # i32[P] injection round


class SimState(NamedTuple):
    """Dynamic per-round state (device pytree)."""

    t: jnp.ndarray  # i32 scalar round counter
    key: jnp.ndarray  # PRNG key
    have: jnp.ndarray  # u8[N, P]
    injected: jnp.ndarray  # u8[P] payload entered the system (origin was up)
    relay_left: jnp.ndarray  # u8[N, P]
    inflight: jnp.ndarray  # u8[D, N, P]
    # sync pulls in flight: granted in round t, delivered at slot
    # (t + 1 + fault_delay) — a delay ring like ``inflight`` so
    # FaultPlan latency can slow the bi-stream RTT (without faults only
    # slot t+1 is ever written, the classic one-round RTT).  Kept
    # SEPARATE from the broadcast ring because sync-received changesets
    # carry no retransmission budget in the reference (only the
    # rebroadcast path re-arms, handlers.rs:768-779) — r4 ground-truth:
    # conflating them let one early post-heal sync flood the cluster
    # via rebroadcast, several× faster than the host tier recovers
    sync_inflight: jnp.ndarray  # u8[D, N, P]
    sync_countdown: jnp.ndarray  # i32[N]
    # per-node re-arm window: grows ×2 on fruitless due syncs up to
    # cfg.sync_backoff_cap(), resets to sync_interval_rounds on ingest
    sync_backoff: jnp.ndarray  # i32[N]
    alive: jnp.ndarray  # u8[N] ground truth (0 = up!  uses ALIVE/DOWN consts)
    incarnation: jnp.ndarray  # u32[N]
    group: jnp.ndarray  # i32[N] partition group
    # SWIM full-view mode (zero-sized when disabled)
    view: jnp.ndarray  # i8[N, N] or [0, 0]
    vinc: jnp.ndarray  # i32[N, N] or [0, 0]
    suspect_since: jnp.ndarray  # i32[N, N] or [0, 0]
    # per-node converged-at round (-1 while not converged) for p99 stats
    converged_at: jnp.ndarray  # i32[N]
    # bookkeeping tensors (north-star layout; refreshed once per round from
    # `have` by round_step, consumed by the next round's sync)
    heads: jnp.ndarray  # i32[N, A] max version touched per (node, actor)
    gap_lo: jnp.ndarray  # i32[N, A, K] needed-range starts (1-based, 0=empty)
    gap_hi: jnp.ndarray  # i32[N, A, K] needed-range ends (inclusive)
    # partial-view SWIM member tables ([0, 0] when disabled; see pswim.py)
    pid: jnp.ndarray  # i32[N, M] member id per bucket, -1 = empty
    pkey: jnp.ndarray  # i32[N, M] belief key inc*4 + state
    psince: jnp.ndarray  # i32[N, M] round the entry became SUSPECT/DOWN, -1 = n/a
    # PeerSwap sampler view (ISSUE 9; [N, 0] when peer_sampler is
    # "uniform" — the same zero-width off pattern as view/pid): slot
    # entries are peer ids (-1 = empty), mixed by seeded pairwise swaps
    # each round (topo/sampler.py) and sampled for every fan-out/sync/
    # probe target draw
    pview: jnp.ndarray  # i32[N, V] or [N, 0]


def init_pview(cfg: SimConfig, key: jax.Array) -> jnp.ndarray:
    """i32[N, M] initial member tables: bucket b of node n holds a random
    id with residue b mod M (a random M-member sample of the cluster —
    the bootstrap-seeded member list each node starts from); -1 where the
    draw lands on self or past N."""
    n, m = cfg.n_nodes, cfg.member_slots
    per = (n + m - 1) // m  # ids per residue class
    r = jax.random.randint(key, (n, m), 0, per, jnp.int32)
    pid = jnp.arange(m, dtype=jnp.int32)[None, :] + m * r
    me = jnp.arange(n, dtype=jnp.int32)[:, None]
    return jnp.where((pid < n) & (pid != me), pid, -1)


def init_state(cfg: SimConfig, key: jax.Array) -> SimState:
    n, p = cfg.n_nodes, cfg.n_payloads
    swim_n = cfg.n_nodes if cfg.swim_full_view else 0
    pm = cfg.member_slots if cfg.swim_partial_view else 0
    if cfg.peer_sampler == "peerswap":
        # the extra split rides a trace-time branch: uniform scenarios
        # consume the exact pre-ISSUE-9 key stream (byte-identity)
        from ..topo.sampler import init_peer_view

        key, sub, kview, kpv = jax.random.split(key, 4)
        pview = init_peer_view(cfg, kpv)
    else:
        key, sub, kview = jax.random.split(key, 3)
        pview = jnp.zeros((n, 0), jnp.int32)
    pid = (
        init_pview(cfg, kview)
        if cfg.swim_partial_view
        else jnp.zeros((n, 0), jnp.int32)
    )
    return SimState(
        t=jnp.zeros((), jnp.int32),
        key=key,
        have=jnp.zeros((n, p), jnp.uint8),
        injected=jnp.zeros((p,), jnp.uint8),
        relay_left=jnp.zeros((n, p), jnp.uint8),
        inflight=jnp.zeros((cfg.n_delay_slots, n, p), jnp.uint8),
        sync_inflight=jnp.zeros((cfg.n_delay_slots, n, p), jnp.uint8),
        sync_countdown=jax.random.randint(
            sub, (n,), 0, cfg.sync_interval_rounds, jnp.int32
        ),
        sync_backoff=jnp.full((n,), cfg.sync_interval_rounds, jnp.int32),
        alive=jnp.zeros((n,), jnp.uint8),
        incarnation=jnp.zeros((n,), jnp.uint32),
        group=jnp.zeros((n,), jnp.int32),
        view=jnp.zeros((swim_n, swim_n), jnp.int8),
        vinc=jnp.zeros((swim_n, swim_n), jnp.int32),
        suspect_since=jnp.full((swim_n, swim_n), -1, jnp.int32),
        converged_at=jnp.full((n,), -1, jnp.int32),
        heads=jnp.zeros((n, cfg.n_writers), jnp.int32),
        gap_lo=jnp.zeros((n, cfg.n_writers, cfg.gap_slots), jnp.int32),
        gap_hi=jnp.zeros((n, cfg.n_writers, cfg.gap_slots), jnp.int32),
        pid=pid,
        pkey=jnp.where(pid >= 0, jnp.int32(ALIVE), jnp.int32(-1))
        if cfg.swim_partial_view
        else jnp.zeros((n, pm), jnp.int32),
        psince=jnp.full((n, pm), -1, jnp.int32),
        pview=pview,
    )


def _cumsum_last(x: jnp.ndarray, block: int = 64) -> jnp.ndarray:
    """Exact i32 prefix sum over the last axis, two-level blocked: one
    short scan within blocks + one short scan across block totals
    vectorizes ~25% faster than a single length-P scan on CPU and maps
    onto the TPU VPU as wide adds."""
    *lead, p = x.shape
    if p % block or p < 2 * block:
        return jnp.cumsum(x, axis=-1)
    xb = x.reshape(*lead, p // block, block)
    within = jnp.cumsum(xb, axis=-1)
    tot = within[..., -1]
    off = jnp.cumsum(tot, axis=-1) - tot
    return (within + off[..., None]).reshape(*lead, p)


def budget_prefix_mask(
    mask: jnp.ndarray, budget_bytes: Optional[int], nbytes: jnp.ndarray
) -> jnp.ndarray:
    """Oldest-first BYTE-accurate budget: keep the prefix of True entries
    along the last (payload) axis whose cumulative byte size fits
    ``budget_bytes``.  ``nbytes`` is the per-payload size vector
    (meta.nbytes) — mixed 1 B–8 KiB changesets meter correctly, unlike a
    uniform count rank (VERDICT r1 weak #8).  Payloads are version-major,
    so the index-order prefix is exactly the reference's oldest-first
    drain under the governor (broadcast/mod.rs:453-463); a budget below
    the first payload's size sends NOTHING (the limiter blocks).

    ``budget_bytes=None`` = statically unmetered: the caller has PROVEN
    its budget can never bind (sum of all payload sizes ≤ budget), so
    the prefix-sum — the single hottest op in the sync kernel at bench
    shape — is skipped entirely at trace time."""
    if budget_bytes is None:
        return mask
    p = mask.shape[-1]
    if p >= 1 << 21:
        # the sub-KiB lane's cumsum wraps i32 past p × 1023 ≥ 2^31; a
        # silent wrap would un-bound the governor, so refuse loudly
        raise ValueError(
            f"byte budget supports at most 2^21-1 payloads, got {p}"
        )
    sizes = jnp.where(mask, nbytes.astype(jnp.int32), 0)
    if p <= 32767:
        cum = _cumsum_last(sizes)  # ≤ 32767 × 64 KiB < 2^31
        return mask & (cum <= budget_bytes)
    # Large payload spaces (VERDICT r2 weak #5): jax runs without x64, so
    # instead of an i64 cumsum the sum is carried exactly in two i32
    # lanes — KiB units and sub-KiB remainders — then compared to the
    # budget lexicographically after carry normalization.  Exact for
    # p < 2^21 payloads of ≤ 64 KiB (sizes validated at meta build).
    hi = _cumsum_last(sizes >> 10)  # ≤ p × 64 < 2^31 for p < 2^25
    lo = _cumsum_last(sizes & 1023)  # ≤ p × 1023 < 2^31 for p < 2^21
    hi = hi + (lo >> 10)
    lo = lo & 1023
    bhi, blo = budget_bytes >> 10, budget_bytes & 1023
    fits = (hi < bhi) | ((hi == bhi) & (lo <= blo))
    return mask & fits


def optimize_budgets(cfg: SimConfig, meta: PayloadMeta) -> SimConfig:
    """Derive the 'budget provably cannot bind' proof from the ACTUAL
    payload metadata (concrete at scenario-build time, before tracing):
    when the sum of every payload's size fits a budget, that budget is
    replaced by None and the per-round prefix-sum metering — the
    hottest op in the sync kernel at bench shape — is skipped at trace
    time.  Computing the proof from meta.nbytes itself (not from a
    duplicated default-size constant) means a scenario that later grows
    mixed or larger payloads automatically falls back to real metering.
    """
    import dataclasses as _dc

    import numpy as _np

    total = int(_np.asarray(meta.nbytes).sum())
    changes = {}
    if (
        cfg.rate_limit_bytes_round is not None
        and total <= cfg.rate_limit_bytes_round
    ):
        changes["rate_limit_bytes_round"] = None
    if (
        cfg.sync_budget_bytes is not None
        and total <= cfg.sync_budget_bytes
    ):
        changes["sync_budget_bytes"] = None
    return _dc.replace(cfg, **changes) if changes else cfg


def uniform_payloads(
    cfg: SimConfig,
    inject_every: int = 1,
    payload_bytes=None,  # None | int | per-payload sequence
) -> PayloadMeta:
    """A write-storm scenario: ``cfg.n_writers`` origins each commit
    versions of ``cfg.chunks_per_version`` chunks, injected
    ``inject_every`` rounds apart.

    The payload axis is **version-major** — index order IS (version,
    actor, chunk) order, which is also injection order since the inject
    round is monotone in version.  Both hot kernels rely on this: the
    broadcast rate limiter drains oldest-first by index (broadcast.py)
    and the sync budget grants oldest-version-first WITHOUT any per-round
    permutation (sync.py).  The layout lives on SimConfig so the kernels
    can reshape have[N, P] into the (actor, version, chunk) grid."""
    p = cfg.n_payloads
    n_writers, chunks = cfg.n_writers, cfg.chunks_per_version
    wave = n_writers * chunks  # payloads per version wave
    idx = jnp.arange(p, dtype=jnp.int32)
    version = 1 + idx // wave
    actor = (idx % wave) // chunks
    chunk = idx % chunks
    # writers spread across the node id space
    actor_node = (actor * max(1, cfg.n_nodes // n_writers)) % cfg.n_nodes
    return PayloadMeta(
        actor=actor_node.astype(jnp.int32),
        version=version.astype(jnp.int32),
        chunk=chunk.astype(jnp.int32),
        nchunks=jnp.full((p,), chunks, jnp.int32),
        # scalar or per-payload sizes: the byte-accurate budget kernels
        # meter mixed 1 B–8 KiB changesets (the reference's reality)
        nbytes=_payload_sizes(p, payload_bytes, cfg),
        round=((version - 1) * inject_every).astype(jnp.int32),
    )


