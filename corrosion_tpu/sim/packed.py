"""Bitpacked round kernels: the payload axis as u32 words.

The dense round keeps one BYTE per (node, payload) bit in its hottest
carries (`have`, `inflight`) and per-edge masks; doc/experiments/
BITPACK_SPIKE.md measured the packed equivalents at ×4-×30 per
primitive (8× less HBM traffic, VPU-friendly bitwise ops).  This module
is the full packed round for the scenario class the headline bench
runs, kept EXACTLY equivalent to the dense kernels (tests/sim/
test_packed_equivalence.py compares round-by-round bit-for-bit):

- ``have_p[N, W] u32`` — W = P/32 words, payload p lives at word p//32
  bit p%32 (LSB-first);
- ``inflight[D, N, P] u8`` — the delay ring stays DENSE: it is the
  broadcast scatter's target and XLA has no fast bitwise-OR scatter on
  words (see PackedCarry docstring); the ring boundary pays one
  pack/unpack per round instead;
- ``relay planes r0..r3[N, W] u32`` — the 0..15 retransmission counter
  BITSLICED: bit b of plane k is bit k of payload b's counter.
  Decrement-where-mask is 4 bitwise ops of ripple borrow; "counter > 0"
  is ``r0|r1|r2|r3`` — the counter never leaves packed form;
- chunk completeness without unpacking: ``chunks_per_version`` is a
  power of two ≤ 32, so a version's chunks are CONTIGUOUS bits inside
  one word and "all chunks present" is a log2(C)-step bitwise fold.

Supported scenario envelope (validated by ``packed_supported``):
P % 32 == 0, chunks_per_version ∈ {1, 2, 4, 8, 16, 32}, and
max_transmissions < 16.  Since r5 the LIMITERS run packed too — the
reference never runs unmetered (its 10 MiB/s governor is always on,
broadcast/mod.rs:460-463), so the adversarial envelope had to stop
being a dense-path exile:

- byte budgets (broadcast governor + sync budget) evaluate via
  ``budget_prefix_words``: per-word masked byte totals, a word-level
  prefix, and a 32-step in-word scan — bit-identical to the dense
  ``budget_prefix_mask`` (including its exact two-lane i32 arithmetic
  past 32767 payloads) at O(N·W) HBM instead of O(N·P);
- payload loss draws the SAME per-(edge, payload) u8 threshold mask as
  the dense kernel (same key, same shape → same bits); the [E, P]
  tensor is dense, but so is the broadcast scatter's delay ring — the
  packed win stays on have/relay/sync/bookkeeping.

Since ISSUE 4 the FAULT SEAM rides the packed carry too — the reference
never runs faultless (gossip under loss/partitions/crashes IS the
workload), so fault campaigns must not be a dense-path exile either:

- per-edge cut/loss masks apply as word operations on have/relay (loss
  draws the same per-(edge, payload) threshold key as dense, so the
  bits match);
- crash-with-wipe zeroes the packed carry (`apply_carry_faults`) while
  `apply_node_faults` on the slim state wipes membership — both SWIM
  tiers — and bookkeeping;
- fault latency stretches the packed sync ring by OR-folding each
  session-delay class into its own slot (`sync_packed`), and jitter
  rides the dense broadcast ring's per-element scatter exactly as the
  dense kernel does;
- the limiters (`budget_prefix_words`) compose with fault loss: the
  budget spends on the attempt, loss eats the wire, as in
  `broadcast_step`.

Everything outside the envelope stays on the dense path — same
results, just slower.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .profile import phase_scope
from .state import ALIVE, PayloadMeta, SimConfig, SimState
from .swim import sample_member_targets
from .topology import (
    Topology,
    apply_degree_caps,
    edge_alive,
    edge_delay,
    edge_payload_drop,
)

U32 = jnp.uint32
# a NUMPY scalar on purpose: a module-level jnp constant would be
# created inside whichever trace first imports this module (the round
# kernels import packed lazily) and leak as a tracer into every later
# jit; np.uint32 converts per-use and cannot leak
ONES = np.uint32(0xFFFFFFFF)


def packed_supported(cfg: SimConfig, topo: Topology) -> bool:
    c = cfg.chunks_per_version
    return (
        cfg.allow_packed
        and cfg.n_nodes * cfg.n_payloads >= cfg.packed_min_cells
        and cfg.n_payloads % 32 == 0
        and c in (1, 2, 4, 8, 16, 32)
        and cfg.max_transmissions < 16
    )


def budget_prefix_words(
    elig_w: jnp.ndarray, budget_bytes, nbytes: jnp.ndarray
) -> jnp.ndarray:
    """Packed twin of ``state.budget_prefix_mask``: keep the
    version-major prefix of set bits whose cumulative byte size fits
    ``budget_bytes``, entirely in the word domain.  Three stages — (1)
    per-word masked byte totals (32 fused elementwise steps over
    [.., W]), (2) an exclusive word-level prefix sum, (3) a 32-step
    in-word scan emitting the output bits — reproduce the dense mask's
    inclusive-cumsum-vs-budget comparison EXACTLY, including the
    two-lane (KiB + sub-KiB) exact i32 arithmetic the dense path uses
    past 32767 payloads.  HBM cost is O(N·W) i32 instead of the dense
    cumsum's O(N·P) — the budget was the single hottest dense-sync op
    at bench shape and the reason limiters used to force the dense
    path."""
    if budget_bytes is None:
        return elig_w
    w = elig_w.shape[-1]
    p = w * 32
    if p >= 1 << 21:
        # same loud refusal as the dense mask: a wrapped i32 cumsum
        # would silently un-bound the governor
        raise ValueError(
            f"byte budget supports at most 2^21-1 payloads, got {p}"
        )
    nb = nbytes.astype(jnp.int32).reshape(w, 32)

    def word_tot(lane_nb):
        tot = jnp.zeros(elig_w.shape, jnp.int32)
        for j in range(32):
            bit = ((elig_w >> j) & U32(1)).astype(jnp.int32)
            tot = tot + bit * lane_nb[:, j]
        return tot

    if p <= 32767:
        tot = word_tot(nb)
        run = jnp.cumsum(tot, axis=-1) - tot  # exclusive word prefix
        out = jnp.zeros_like(elig_w)
        for j in range(32):
            bit = (elig_w >> j) & U32(1)
            run = run + bit.astype(jnp.int32) * nb[:, j]
            ok = (run <= budget_bytes) & (bit != U32(0))
            out = out | (ok.astype(U32) << j)
        return out

    # two-lane exact arithmetic (dense budget_prefix_mask's large-P
    # branch): KiB lane + sub-KiB remainder lane, carry-normalized
    # lexicographic compare against the budget
    nb_hi, nb_lo = nb >> 10, nb & 1023
    tot_hi, tot_lo = word_tot(nb_hi), word_tot(nb_lo)
    run_hi = jnp.cumsum(tot_hi, axis=-1) - tot_hi
    run_lo = jnp.cumsum(tot_lo, axis=-1) - tot_lo
    bhi, blo = budget_bytes >> 10, budget_bytes & 1023
    out = jnp.zeros_like(elig_w)
    for j in range(32):
        bit = (elig_w >> j) & U32(1)
        bi = bit.astype(jnp.int32)
        run_hi = run_hi + bi * nb_hi[:, j]
        run_lo = run_lo + bi * nb_lo[:, j]
        nh = run_hi + (run_lo >> 10)
        nl = run_lo & 1023
        ok = ((nh < bhi) | ((nh == bhi) & (nl <= blo))) & (bit != U32(0))
        out = out | (ok.astype(U32) << j)
    return out


def pack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """bool/u8[..., P] → u32[..., P/32], LSB-first within each word."""
    *lead, p = x.shape
    b = (x > 0).reshape(*lead, p // 32, 32).astype(U32)
    return (b << jnp.arange(32, dtype=U32)).sum(axis=-1, dtype=U32)


def unpack_bits(w: jnp.ndarray, p: int) -> jnp.ndarray:
    """u32[..., W] → bool[..., P]."""
    bits = (w[..., None] >> jnp.arange(32, dtype=U32)) & U32(1)
    return bits.astype(jnp.bool_).reshape(*w.shape[:-1], p)


# -- bitsliced 4-bit counters ------------------------------------------------


class Planes(NamedTuple):
    r0: jnp.ndarray
    r1: jnp.ndarray
    r2: jnp.ndarray
    r3: jnp.ndarray

    @property
    def nonzero(self) -> jnp.ndarray:
        return self.r0 | self.r1 | self.r2 | self.r3


def planes_set(planes: Planes, where: jnp.ndarray, value: int) -> Planes:
    """Set the counter to ``value`` (0..15) at every bit of ``where``."""
    out = []
    for k, plane in enumerate(planes):
        bit = (value >> k) & 1
        plane = (plane & ~where) | (where if bit else U32(0))
        out.append(plane)
    return Planes(*out)


def planes_dec(planes: Planes, where: jnp.ndarray) -> Planes:
    """Saturating decrement at every bit of ``where`` (ripple borrow);
    callers guarantee where ⊆ nonzero, so saturation never triggers."""
    r0, r1, r2, r3 = planes
    borrow = where
    n0 = r0 ^ borrow
    borrow &= ~r0
    n1 = r1 ^ borrow
    borrow &= ~r1
    n2 = r2 ^ borrow
    borrow &= ~r2
    n3 = r3 ^ borrow
    return Planes(n0, n1, n2, n3)


# -- chunk-group folds (all/any chunks of each version, packed) --------------


def _fold_all(w: jnp.ndarray, c: int) -> jnp.ndarray:
    """Within every aligned c-bit group: all bits set ⇒ the group's LOW
    bit is 1 in the result (other group bits undefined — mask after)."""
    step = 1
    while step < c:
        w = w & (w >> step)
        step *= 2
    return w


def _fold_any(w: jnp.ndarray, c: int) -> jnp.ndarray:
    step = 1
    while step < c:
        w = w | (w >> step)
        step *= 2
    return w


def _group_low_bits_mask(c: int) -> jnp.ndarray:
    """u32 mask with bit set at every multiple of c (group low bits)."""
    m = 0
    for i in range(0, 32, c):
        m |= 1 << i
    return U32(m)


def group_grid(w: jnp.ndarray, cfg: SimConfig, mode: str) -> jnp.ndarray:
    """have-words [..., W] → bool[..., A, V] version grid (all/any chunks).

    Payload index = (v * A + a) * C + c (version-major), so each (v, a)
    owns C contiguous bits; with C a power of two ≤ 32 groups never
    straddle words."""
    c = cfg.chunks_per_version
    fold = _fold_all if mode == "all" else _fold_any
    low = fold(w, c) & _group_low_bits_mask(c)
    # extract the 32/c group bits per word → [..., P/C] = [..., V*A]
    groups_per_word = 32 // c
    shifts = jnp.arange(0, 32, c, dtype=U32)
    bits = (low[..., None] >> shifts) & U32(1)  # [..., W, 32/c]
    va = bits.reshape(*w.shape[:-1], cfg.n_versions * cfg.n_writers)
    grid = va.reshape(*w.shape[:-1], cfg.n_versions, cfg.n_writers)
    return jnp.swapaxes(grid, -1, -2).astype(jnp.bool_)  # [..., A, V]


def grid_to_words(x_av: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[..., A, V] → u32 words [..., W] with each (v, a) group's C
    bits all set where the grid is True (inverse of group_grid)."""
    c = cfg.chunks_per_version
    va = jnp.swapaxes(x_av, -1, -2).reshape(
        *x_av.shape[:-2], cfg.n_versions * cfg.n_writers
    )  # [..., V*A] in payload-group order
    groups_per_word = 32 // c
    g = va.reshape(*va.shape[:-1], va.shape[-1] // groups_per_word,
                   groups_per_word).astype(U32)
    shifts = jnp.arange(0, 32, c, dtype=U32)
    low = (g << shifts).sum(axis=-1, dtype=U32)  # group low bits
    return _smear_groups(low, c)


# -- packed state ------------------------------------------------------------


class PackedCarry(NamedTuple):
    """Hybrid carry: ``have``/``relay`` ride as u32 words (8× less HBM
    traffic on the elementwise-heavy fields), but the ``inflight`` delay
    ring stays DENSE u8 — it is the target of the broadcast fan-out
    scatter, and a bitwise-OR scatter on packed words has no cheap XLA
    primitive (at[].max is arithmetic max, wrong for words; the bool-
    plane expansion measured 7× slower than the plain u8 scatter).  The
    u8 ring keeps the dense path's proven scatter and pays one
    pack/unpack per round at the ring boundary instead."""

    have: jnp.ndarray  # u32[N, W]
    inflight: jnp.ndarray  # u8[D, N, P] — dense, see docstring
    relay: Planes  # 4 × u32[N, W]
    # sync delivery ring (SimState.sync_inflight) — stays PACKED: the
    # sync fold produces words directly, no scatter.  Latency-free runs
    # write only slot (t+1) % D (the one-round bi-stream RTT); FaultPlan
    # latency partitions edges by session delay and OR-folds each delay
    # class into its own slot (sync_packed), still scatter-free
    sync_buf: jnp.ndarray  # u32[D, N, W]


def pack_state(state: SimState, cfg: SimConfig) -> PackedCarry:
    relay = state.relay_left.astype(jnp.int32)
    planes = Planes(*(
        pack_bits((relay >> k) & 1) for k in range(4)
    ))
    return PackedCarry(
        have=pack_bits(state.have),
        inflight=state.inflight,
        relay=planes,
        sync_buf=pack_bits(state.sync_inflight),
    )


def unpack_into_state(carry: PackedCarry, state: SimState, cfg: SimConfig) -> SimState:
    p = cfg.n_payloads
    relay = sum(
        unpack_bits(plane, p).astype(jnp.uint8) << k
        for k, plane in enumerate(carry.relay)
    )
    return state._replace(
        have=unpack_bits(carry.have, p).astype(jnp.uint8),
        inflight=carry.inflight,
        relay_left=relay.astype(jnp.uint8),
        sync_inflight=unpack_bits(carry.sync_buf, p).astype(jnp.uint8),
    )


# -- the packed phases -------------------------------------------------------


def inject_packed(
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    t: jnp.ndarray,
    meta: PayloadMeta,
    cfg: SimConfig,
    alive: jnp.ndarray,
) -> Tuple[PackedCarry, jnp.ndarray]:
    n = cfg.n_nodes
    w = cfg.n_payloads // 32
    injecting = (meta.round == t) & (alive[meta.actor] == ALIVE)  # [P]
    inj_words = pack_bits(injecting)  # [W]
    # scatter each payload's bit into its origin row: build [N, W] where
    # row meta.actor[p] gets bit p.  Payloads share origin rows, so OR
    # via segment: one-hot word contribution per payload is heavy; use
    # the (actor, word) scatter over the P payloads instead.
    word_idx = jnp.arange(cfg.n_payloads, dtype=jnp.int32) // 32
    bit = (U32(1) << (jnp.arange(cfg.n_payloads, dtype=U32) % 32))
    contrib = jnp.where(injecting, bit, U32(0))
    own = jnp.zeros((n, w), U32)
    # add == OR here: every payload owns a DISTINCT bit, so contributions
    # landing on the same (actor, word) cell never overlap
    own = own.at[meta.actor, word_idx].add(contrib)
    newly = own & ~carry.have
    have = carry.have | own
    relay = planes_set(carry.relay, newly, cfg.max_transmissions)
    return (
        PackedCarry(have=have, inflight=carry.inflight, relay=relay,
                    sync_buf=carry.sync_buf),
        injected_p | inj_words,
    )


def broadcast_packed(
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    state: SimState,
    cfg: SimConfig,
    topo: Topology,
    region: jnp.ndarray,
    key: jax.Array,
    meta: PayloadMeta,
    faults=None,
    telem: bool = False,
    done=None,
):
    n = cfg.n_nodes
    f = cfg.fanout
    k_targets, k_drop, k_ring0 = jax.random.split(key, 3)

    eligible = carry.have & carry.relay.nonzero & injected_p[None, :]  # [N, W]
    # rate-limit governor, FIFO oldest-first within the per-round byte
    # budget — word-domain twin of broadcast_step's budget_prefix_mask
    sending = budget_prefix_words(
        eligible, cfg.rate_limit_bytes_round, meta.nbytes
    )
    if done is not None:
        # per-lane early-exit gate (ISSUE 7 satellite): a converged
        # lane's scatter work is pure waste — its carry is select-frozen
        # by the batched while_loop anyway, so zeroing the send set is
        # unobservable (and in solo runs the loop's cond guarantees the
        # body never executes with done=True, making this an identity)
        sending = jnp.where(done, U32(0), sending)

    targets = sample_member_targets(state, cfg, k_targets, f)  # [N, F]
    if cfg.ring0_first and topo.n_regions > 1:
        me = jnp.arange(n, dtype=jnp.int32)
        per = max(1, n // topo.n_regions)
        start = region * per
        size = jnp.where(
            region == topo.n_regions - 1, n - start, per
        ).astype(jnp.int32)
        local = start + jax.random.randint(
            k_ring0, (n,), 0, jnp.iinfo(jnp.int32).max
        ) % jnp.maximum(size, 1)
        ok_local = local != me
        if cfg.couple_membership and cfg.swim_full_view:
            from .state import DOWN

            ok_local &= state.view[me, local] != DOWN
        elif cfg.couple_membership and cfg.swim_partial_view:
            from .state import DOWN

            m = state.pid.shape[1]
            bucket = local % m
            known = state.pid[me, bucket] == local
            ok_local &= known & (state.pkey[me, bucket] % 4 != DOWN)
        targets = targets.at[:, 0].set(
            jnp.where(ok_local, local, targets[:, 0])
        )
    # heterogeneous fan-out (ISSUE 9) — identical masking to the dense
    # kernel, applied before the edge list so both paths agree
    targets = apply_degree_caps(targets, topo)
    if cfg.fanout_schedule != "flat":
        # fanout schedule (ISSUE 11) — the identical mask the dense
        # kernel applies, so both paths' edge lists agree
        from ..proto.schedule import scheduled_fanout_targets

        targets = scheduled_fanout_targets(targets, cfg, state.t)
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), f)  # [E]
    dst = targets.reshape(-1)
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)
    ok &= edge_alive(state.group, state.alive, src, dst)
    ok &= dst != src
    delay = edge_delay(topo, region, src, dst)

    # the ring is dense u8 (PackedCarry docstring): unpack the sending
    # words once, then the fan-out scatter is the dense path's plain
    # at[].max — the only correct-and-fast OR scatter XLA offers.
    # `elig8[src]` is a regular f-fold repeat, written as a broadcast so
    # XLA doesn't emit a 150 MB random gather for it.  Loss draws the
    # SAME per-(edge, payload) mask as the dense kernel — same key, same
    # shape, same bits (trace-time constant when loss == 0).
    p = cfg.n_payloads
    drop = edge_payload_drop(
        topo, k_drop, src.shape[0], p, src=src, dst=dst, region=region
    )
    delay_ep = None
    cut = jnp.int32(0)
    if telem:
        from .telemetry import wire_loss_active

        _tel_loss = wire_loss_active(topo, faults)
    if faults is not None:
        # FaultPlan seam, word-path edition (ISSUE 4): the ONE shared
        # implementation (`faults.fault_wire_effects`) — same keys, same
        # draws, same per-(edge, payload) grain as broadcast_step, so
        # the loss bits match bit for bit by construction.  The [E, P]
        # mask is dense on both paths; the packed win stays on
        # have/relay/sync.  Absent classes are trace-time no-ops, so a
        # loss+partition storm pays neither the jitter draw nor the
        # per-element ring scatter.
        from .faults import fault_wire_effects

        ok_pre = ok
        ok, drop, delay, delay_ep = fault_wire_effects(
            faults, key, src, dst, p, ok, drop, delay
        )
        if telem:
            # cuts are the only ok-mask fault_wire_effects applies
            cut = jnp.sum(ok_pre & ~ok, dtype=jnp.int32)
    if telem and _tel_loss:
        # pin ONE materialization of the loss mask: the telemetry drop
        # count below consumes it too, and without the barrier XLA
        # duplicates the whole drop expression (threefry included) into
        # that second consumer
        drop = jax.lax.optimization_barrier(drop)
    elig8 = unpack_bits(sending, p).astype(carry.inflight.dtype)  # [N, P]
    sent = jnp.where(
        ok.reshape(n, f, 1) & ~drop.reshape(n, f, p),
        elig8[:, None, :],
        jnp.uint8(0),
    ).reshape(n * f, p)  # [E, P]

    d_slots = carry.inflight.shape[0]
    if delay_ep is not None:
        # per-(edge, payload) delays (fault jitter): elementwise scatter
        # into the dense u8 ring — same element count as the row
        # scatter, only the indexing is finer-grained (broadcast_step's
        # fault branch, unchanged semantics)
        slot_ep = (state.t + delay_ep) % d_slots  # [E, P]
        flat = (slot_ep * n + dst[:, None]) * p + jnp.arange(
            p, dtype=jnp.int32
        )[None, :]
        inflight = (
            carry.inflight.reshape(-1)
            .at[flat.reshape(-1)]
            .max(sent.reshape(-1))
            .reshape(d_slots, n, p)
        )
    else:
        slot = (state.t + delay) % d_slots
        flat_idx = slot * n + dst
        inflight = carry.inflight.reshape(d_slots * n, p)
        inflight = inflight.at[flat_idx].max(sent)
        inflight = inflight.reshape(d_slots, n, p)

    if cfg.dissemination == "push-pull":
        # push-pull exchange (ISSUE 11) — the dense kernel's branch on
        # the packed envelope: the shared proto/dissemination helpers
        # draw the same keys and shapes, the response set is the
        # responder's unpacked sending buffer (elig8 == the dense
        # `sending` bools), and the scatter rides the dense u8 ring
        # like every broadcast delivery — bit-identical across paths.
        from ..proto.dissemination import pull_session_ok, pull_wire_drop

        ok_pull = pull_session_ok(ok, faults, src, dst)
        drop_pull = pull_wire_drop(
            topo, faults, k_drop, src, dst, p, region
        )
        if telem and _tel_loss:
            drop_pull = jax.lax.optimization_barrier(drop_pull)
        resp = jnp.where(
            ok_pull[:, None] & ~drop_pull, elig8[dst], jnp.uint8(0)
        )  # [E, P]
        slot_pull = (state.t + delay) % d_slots
        flat_pull = slot_pull * n + src  # responses land at the PULLER
        inflight = (
            inflight.reshape(d_slots * n, p)
            .at[flat_pull]
            .max(resp)
            .reshape(d_slots, n, p)
        )

    # budget spends on the ATTEMPT (see broadcast.broadcast_step): a
    # sender can't observe partitions, dead targets, or wire loss —
    # only what the governor let through this round spends
    attempted = (targets >= 0) & (targets != jnp.arange(n)[:, None])
    any_attempt = attempted.any(axis=1) & (state.alive == ALIVE)  # [N]
    spent = sending & jnp.where(any_attempt[:, None], ONES, U32(0))
    relay = planes_dec(carry.relay, spent)
    out = PackedCarry(have=carry.have, inflight=inflight, relay=relay,
                      sync_buf=carry.sync_buf)
    if not telem:
        return out
    # wire telemetry — same quantities as broadcast_step's telem branch
    # from identical-valued tensors (elig8 == the dense `sending`):
    # per-node frames AND bytes come out of ONE pass over the governor's
    # send words (fused.word_send_stats — the same loads the ring-slot
    # update consumed), and the drop count packs the (barrier-pinned)
    # loss mask to words + popcounts, emitted only when a loss class
    # exists at trace time — bit-equal traces, none of the hot-path cost
    from .fused import word_send_stats
    from .telemetry import WireTel

    # innermost-wins "telemetry" scope: flight-recorder cost, pulled out
    # of the broadcast ledger line (the dense kernel does the same)
    with phase_scope("telemetry"):
        send_frames, send_bytes = word_send_stats(
            sending, meta.nbytes
        )  # i32[N] each, one traversal
        okf = ok.reshape(n, f)
        frames = jnp.sum(
            jnp.where(okf, send_frames[:, None], 0), dtype=jnp.int32
        )
        dropped = jnp.int32(0)
        if _tel_loss:
            dw = pack_bits(drop).reshape(n, f, sending.shape[-1])
            hit = dw & sending[:, None, :] & jnp.where(
                okf[:, :, None], ONES, U32(0)
            )
            dropped = jnp.sum(
                jax.lax.population_count(hit), dtype=jnp.int32
            )
        bytes_out = jnp.sum(
            jnp.where(okf, send_bytes.astype(jnp.float32)[:, None], 0.0)
        )
        if cfg.dissemination == "push-pull":
            # pull-direction wire accounting — the dense kernel's fold
            # shapes on word-derived integers (send_frames/send_bytes
            # are the identical values), so the channels stay bit-equal
            okpf = ok_pull.reshape(n, f)
            frames = frames + jnp.sum(
                jnp.where(okpf, send_frames[dst].reshape(n, f), 0),
                dtype=jnp.int32,
            )
            bytes_out = bytes_out + jnp.sum(
                jnp.where(
                    okpf,
                    send_bytes[dst].astype(jnp.float32).reshape(n, f),
                    0.0,
                )
            )
            if _tel_loss:
                w = sending.shape[-1]
                hitp = pack_bits(drop_pull).reshape(n, f, w) & sending[
                    dst
                ].reshape(n, f, w) & jnp.where(
                    okpf[:, :, None], ONES, U32(0)
                )
                dropped = dropped + jnp.sum(
                    jax.lax.population_count(hitp), dtype=jnp.int32
                )
        tel = WireTel(
            frames=frames,
            bytes=bytes_out,
            dropped=dropped,
            cut=cut,
        )
    return out, tel


def _fold_or_regular(words: jnp.ndarray, n: int, per: int) -> jnp.ndarray:
    """OR-reduce [n*per, W] edge words to [n, W] — the regular pattern
    where edge e belongs to source e // per.  Pure reshape + OR-reduce:
    no scatter, fully packed."""
    w = words.shape[-1]
    grouped = words.reshape(n, per, w)
    out = grouped[:, 0]
    for k in range(1, per):  # per is small & static (sync_peers)
        out = out | grouped[:, k]
    return out


def deliver_packed(
    carry: PackedCarry,
    t: jnp.ndarray,
    cfg: SimConfig,
) -> PackedCarry:
    """Broadcast arrivals re-arm the relay budget (rebroadcast path);
    the sync ring's slot t (grants from 1+delay rounds ago, packed
    words) merges into have WITHOUT re-arming — mirrors
    broadcast.deliver_step."""
    d_slots = carry.inflight.shape[0]
    slot = t % d_slots
    arriving = pack_bits(carry.inflight[slot])  # u8[N, P] → u32[N, W]
    pending_sync = carry.sync_buf[slot]  # u32[N, W]
    if cfg.ordering == "fifo":
        # FIFO ordering gate (ISSUE 11) — the word-domain twin of
        # deliver_step's admit mask (proto/ordering.py): same
        # predecessor predicate, same drop-and-reserve semantics, both
        # rings gated on the one mask
        from ..proto.ordering import admit_words

        admit = admit_words(carry.have, cfg)  # u32[N, W]
        arriving &= admit
        pending_sync &= admit
    newly = arriving & ~carry.have
    have = carry.have | arriving | pending_sync
    relay = planes_set(carry.relay, newly, max(cfg.max_transmissions - 1, 1))
    inflight = carry.inflight.at[slot].set(jnp.uint8(0))
    sync_buf = carry.sync_buf.at[slot].set(U32(0))
    return PackedCarry(have=have, inflight=inflight, relay=relay,
                       sync_buf=sync_buf)


def shrink_state(state: SimState) -> SimState:
    """Zero-width payload-axis tensors: the packed while_loop carries the
    PackedCarry instead, so the dense [N, P]/[D, N, P] arrays must not
    ride the loop carry (they'd cost the HBM traffic packing removes).
    SWIM/sync/sampling only read membership + bookkeeping fields, which
    stay full-size."""
    n = state.have.shape[0]
    d = state.inflight.shape[0]
    u8 = state.have.dtype
    return state._replace(
        have=jnp.zeros((n, 0), u8),
        injected=jnp.zeros((0,), u8),
        relay_left=jnp.zeros((n, 0), u8),
        inflight=jnp.zeros((d, n, 0), u8),
        sync_inflight=jnp.zeros((d, n, 0), u8),
    )


def packed_round_step(
    state: SimState,
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    metrics,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    region: jnp.ndarray,
    faults=None,
    trace=None,
    done=None,
):
    """One gossip tick on packed words — phase-for-phase and PRNG-stream
    identical to `round.round_step` (inject → broadcast → sync → deliver →
    SWIM → bookkeeping refresh → convergence record), including the
    FaultPlan seam (``faults`` is a RoundFaults/FactoredRoundFaults
    slice, same draws and keys as the dense kernels); tests/sim/
    test_packed_equivalence.py holds the two bit-for-bit equal.

    ``trace`` (a `telemetry.RoundTrace`, or None) mirrors the dense
    round's flight-recorder seam: same channels, same values (integer
    counts of the same sets; byte channels fold identically-shaped
    per-edge totals), appended to the return when given.

    ``done`` (a per-lane bool scalar, or None) is the vmapped-ensemble
    early-exit gate: a lane whose flag is set sends and pulls nothing
    (broadcast `sending` and sync `due` zeroed).  Metrics stay
    byte-identical — in solo runs the loop cond guarantees the body
    never executes with done=True, and in batched loops a done lane's
    carry is select-frozen, so the gated body's output is discarded.
    RNG draws are untouched either way (the gate masks AFTER the
    draws), so the PRNG stream cannot shift."""
    from .gaps import extract_gaps
    from .round import RunMetrics
    from .state import version_heads

    if cfg.peer_sampler == "peerswap":
        key, k_bcast, k_sync, k_swim, k_swap = jax.random.split(
            state.key, 5
        )
    else:
        key, k_bcast, k_sync, k_swim = jax.random.split(state.key, 4)
    state = state._replace(key=key)
    if cfg.peer_sampler == "peerswap":
        # PeerSwap view mixing (ISSUE 9), same phase position as the
        # dense round — pview rides the slim state, so the swap step is
        # shared verbatim with round.round_step
        from ..topo.sampler import peerswap_step

        with phase_scope("sampler"):
            state = peerswap_step(state, cfg, topo, k_swap, faults)

    have0_w = carry.have  # pre-round holdings (delivered-count base)
    with phase_scope("inject"):
        carry, injected_p = inject_packed(
            carry, injected_p, state.t, meta, cfg, state.alive
        )
    with phase_scope("broadcast"):
        if trace is None:
            carry = broadcast_packed(
                carry, injected_p, state, cfg, topo, region, k_bcast,
                meta, faults, done=done,
            )
        else:
            carry, wire = broadcast_packed(
                carry, injected_p, state, cfg, topo, region, k_bcast,
                meta, faults, telem=True, done=done,
            )
    # sync writes ring slots t+1.., deliver pops slot t: no ordering
    # hazard (round.round_step's contract; compile_plan validated
    # 1 + fault delay < n_delay_slots)
    with phase_scope("sync"):
        if trace is None:
            carry, countdown, backoff = sync_packed(
                carry, state, cfg, topo, k_sync, meta, faults, done=done
            )
        else:
            carry, countdown, backoff, stel = sync_packed(
                carry, state, cfg, topo, k_sync, meta, faults,
                telem=True, done=done,
            )
    state = state._replace(sync_countdown=countdown, sync_backoff=backoff)
    with phase_scope("deliver"):
        carry = deliver_packed(carry, state.t, cfg)

    from .swim import swim_step

    with phase_scope("swim"):
        state = swim_step(state, cfg, topo, k_swim, faults)

    with phase_scope("gaps"):
        touched = group_grid(carry.have, cfg, "any")  # [N, A, V]
        heads = version_heads(touched)
        gaps = extract_gaps(touched, heads, cfg)
        state = state._replace(
            heads=heads, gap_lo=gaps.lo, gap_hi=gaps.hi
        )
        overflow_frac = jnp.maximum(
            metrics.overflow_frac, gaps.overflow.mean(dtype=jnp.float32)
        )

    # convergence record on WORDS: comp/act are group-uniform (every
    # chunk bit of a version carries the version's value), so the grid
    # reductions collapse to bitwise folds — version_done = AND over up
    # nodes of comp words, node_done = "every payload bit satisfied".
    # Exactly the dense formulas per bit; the equivalence suite compares
    # the resulting metrics every round.
    with phase_scope("converge"):
        up = state.alive == ALIVE
        c = cfg.chunks_per_version
        comp_w = all_chunks_words(carry.have, cfg)  # [N, W]
        act_w = _smear_groups(
            _fold_any(injected_p, c) & _group_low_bits_mask(c), c
        )  # [W]
        masked = jnp.where(up[:, None], comp_w, ONES)
        # AND-fold over the NODE axis — the mesh-sharded axis.  A
        # bitwise u32 reduction is a custom GSPMD reduction computation
        # XLA:CPU rejects (UNIMPLEMENTED), so go through the PRED plane:
        # unpack to bool, jnp.all over nodes (a supported reduce_and
        # collective), re-pack.  Bit-identical to
        # lax.reduce(bitwise_and); [N,P] bool is the same footprint the
        # dense path's comp grid already pays.
        payload_done = (
            jnp.all(unpack_bits(masked, cfg.n_payloads), axis=0)
            & unpack_bits(act_w, cfg.n_payloads)
        )  # [P]
        coverage_at = jnp.where(
            (metrics.coverage_at < 0) & payload_done,
            state.t,
            metrics.coverage_at,
        )
        node_done = ((comp_w | ~act_w[None, :]) == ONES).all(axis=1) & up
        all_injected = jnp.all(meta.round <= state.t)
        converged_at = jnp.where(
            (metrics.converged_at < 0) & node_done & all_injected,
            state.t,
            metrics.converged_at,
        )

        # delivery-order invariant (ISSUE 11): the dense round's check
        # on the packed path's version grids — `touched` is already
        # materialized above; the completeness grid is variant-only cost
        # (a trace-time branch, ordering="none" carries the constant 0)
        order_violations = metrics.order_violations
        if cfg.ordering != "none":
            from .invariants import order_violation_count

            comp_g = group_grid(carry.have, cfg, "all")  # [N, A, V]
            order_violations = order_violations + order_violation_count(
                touched, comp_g, meta, cfg
            )

    out_metrics = RunMetrics(
        coverage_at=coverage_at,
        converged_at=converged_at,
        overflow_frac=overflow_frac,
        order_violations=order_violations,
    )
    if trace is not None:
        from .telemetry import (
            record_round,
            swim_belief_counts,
            word_coverage_delivered,
        )

        with phase_scope("telemetry"):
            susp, dn = swim_belief_counts(state, cfg)
            coverage, delivered = word_coverage_delivered(
                carry.have, have0_w, up, cfg.n_payloads
            )
            trace = record_round(
                trace,
                state.t,
                coverage=coverage,
                delivered=delivered,
                up_nodes=jnp.sum(up, dtype=jnp.int32),
                wire=wire,
                sync=stel,
                swim_suspect=susp,
                swim_down=dn,
                gap_overflow=jnp.sum(gaps.overflow, dtype=jnp.int32),
                every=cfg.trace_every,
            )
    state = state._replace(t=state.t + 1)
    if trace is not None:
        return state, carry, injected_p, out_metrics, trace
    return state, carry, injected_p, out_metrics


def _converged_done(slim: SimState, metrics, meta: PayloadMeta) -> jnp.ndarray:
    """The convergence exit predicate, as a carried per-lane flag: every
    payload injected and every up node converged.  Computed ONCE at the
    end of each round body (on the fresh metrics) instead of re-scanned
    in the while cond — under vmap this is what lets a converged lane's
    next-round work be gated off (the `done` seam in
    `packed_round_step`) while the cond check itself is O(1)."""
    all_injected = jnp.all(meta.round <= slim.t)
    return all_injected & jnp.all(
        (metrics.converged_at >= 0) | (slim.alive != ALIVE)
    )


def _pin(mesh, slim, carry, metrics, trace=None):
    """Re-pin the loop carry's sharded layout each round (identity when
    ``mesh`` is None): packed carry node-split, metrics per their
    `metrics_shardings` (converged_at with its nodes, fold results
    replicated), and the flight-recorder buffers REPLICATED (every
    trace channel is a finished cross-shard fold — a node-split row
    would hold one shard's partial sums), so GSPMD keeps one stable
    layout across the whole while_loop instead of re-deriving it per
    iteration."""
    if mesh is None:
        return slim, carry, metrics, trace
    from ..parallel.mesh import (
        constrain_metrics,
        constrain_packed,
        constrain_replicated,
    )

    if trace is not None:
        trace = constrain_replicated(trace, mesh)
    return (
        slim,
        constrain_packed(carry, mesh),
        constrain_metrics(metrics, mesh),
        trace,
    )


def run_packed(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    max_rounds: int,
    telemetry: bool = False,
    mesh=None,
):
    """Packed-carry `run_to_convergence` body: pack once, loop on u32
    words, unpack once at the end.  Returns the same (SimState,
    RunMetrics[, RoundTrace]) as the dense loop — bit-identical over the
    supported envelope.  Called from round.run_to_convergence under jit
    when `packed_supported(cfg, topo)`; not jitted itself.

    ``mesh`` (a 1-D ``nodes`` `jax.sharding.Mesh`, or None) shards the
    node axis of the packed carry across the mesh: the carry layout is
    re-pinned every round (`parallel.mesh.constrain_packed`) so GSPMD
    partitions the gossip scatter/gather while the per-round convergence
    folds become cross-shard all-reduces.  Bit-identical to the
    single-device run (tests/sim/test_packed_sharded.py)."""
    from .round import new_metrics
    from .topology import regions

    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    carry0 = pack_state(state, cfg)
    injected0 = pack_bits(state.injected)
    slim = shrink_state(state)
    slim, carry0, metrics, _ = _pin(mesh, slim, carry0, metrics)
    done0 = _converged_done(slim, metrics, meta)

    def cond(c):
        s, done = c[0], c[4]
        return (s.t < max_rounds) & ~done

    if telemetry:
        from .telemetry import new_trace

        def body(c):
            s, carry, inj, m, done, trace = c
            s, carry, inj, m, trace = packed_round_step(
                s, carry, inj, m, meta, cfg, topo, region, trace=trace,
                done=done,
            )
            s, carry, m, trace = _pin(mesh, s, carry, m, trace)
            return s, carry, inj, m, _converged_done(s, m, meta), trace

        slim, carry, inj, metrics, _, trace = jax.lax.while_loop(
            cond, body,
            (slim, carry0, injected0, metrics, done0,
             new_trace(cfg, max_rounds)),
        )
    else:

        def body(c):
            s, carry, inj, m, done = c
            s, carry, inj, m = packed_round_step(
                s, carry, inj, m, meta, cfg, topo, region, done=done
            )
            s, carry, m, _ = _pin(mesh, s, carry, m)
            return s, carry, inj, m, _converged_done(s, m, meta)

        slim, carry, inj, metrics, _ = jax.lax.while_loop(
            cond, body, (slim, carry0, injected0, metrics, done0)
        )
    full = unpack_into_state(carry, slim, cfg)
    full = full._replace(
        injected=unpack_bits(inj, cfg.n_payloads).astype(full.have.dtype)
    )
    if telemetry:
        return full, metrics, trace
    return full, metrics


# -- the packed fault seam (ISSUE 4) -----------------------------------------


def apply_carry_faults(carry: PackedCarry, rf) -> PackedCarry:
    """Packed twin of `faults.apply_node_faults`' payload-carry wipe: a
    crash-with-wipe zeroes the node's have words, all four bitsliced
    relay planes, its column of the dense broadcast ring, and its packed
    sync-ring words — exactly the rows the dense path zeroes.  (The
    membership/bookkeeping wipe — both SWIM tiers, heads, gaps — rides
    `apply_node_faults` on the slim state, whose payload tensors are
    zero-width in the packed loop.)"""
    w = rf.wipe
    wn = jnp.where(w[:, None], ONES, U32(0))  # [N, 1] word mask
    return PackedCarry(
        have=carry.have & ~wn,
        inflight=jnp.where(w[None, :, None], jnp.uint8(0), carry.inflight),
        relay=Planes(*(plane & ~wn for plane in carry.relay)),
        sync_buf=jnp.where(w[None, :, None], U32(0), carry.sync_buf),
    )


def all_have_words(
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
) -> jnp.ndarray:
    """Word-domain twin of `faults._all_have` (computed FRESH — the
    sticky metrics must not mask a post-convergence wipe): every up node
    holds every injected version completely."""
    up = state.alive == ALIVE
    c = cfg.chunks_per_version
    comp_w = all_chunks_words(carry.have, cfg)  # [N, W]
    act_w = _smear_groups(
        _fold_any(injected_p, c) & _group_low_bits_mask(c), c
    )  # [W]
    node_done = ((comp_w | ~act_w[None, :]) == ONES).all(axis=1) | ~up
    return jnp.all(meta.round <= state.t) & jnp.all(node_done)


def run_packed_faults(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    fplan,
    max_rounds: int,
    telemetry: bool = False,
    mesh=None,
):
    """Packed-carry `run_fault_plan` body: the fault schedule drives the
    u32-word round loop — pack once, apply each round's node faults to
    BOTH the slim state (membership, bookkeeping) and the packed carry
    (payload words), unpack once at the end.  Same exit rule as the
    dense loop: never before the schedule's horizon (a plan may crash a
    node after convergence), then the fresh all-have predicate.  Called
    from `faults.run_fault_plan` under jit when `packed_supported`.

    ``mesh`` shards the node axis exactly as in `run_packed`; callers
    place the compiled plan with `parallel.mesh.shard_fault_plan` so the
    rank-1 fault masks ride sharded with their node rows and the
    all-have exit fold is a cross-shard all-reduce."""
    from .faults import apply_node_faults, round_faults
    from .round import new_metrics
    from .topology import regions

    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    carry0 = pack_state(state, cfg)
    injected0 = pack_bits(state.injected)
    slim = shrink_state(state)
    slim, carry0, metrics, _ = _pin(mesh, slim, carry0, metrics)
    horizon = fplan.alive.shape[0] - 1  # static

    def _fault_done(s, carry, inj):
        # never before the horizon, then the FRESH all-have predicate
        # (sticky metrics must not mask a post-convergence wipe)
        return (s.t >= horizon) & all_have_words(carry, inj, s, meta, cfg)

    done0 = _fault_done(slim, carry0, injected0)

    def cond(c):
        s, done = c[0], c[4]
        return (s.t < max_rounds) & ~done

    if telemetry:
        from .telemetry import new_trace, record_node_faults

        def body(c):
            s, carry, inj, m, done, trace = c
            rf = round_faults(fplan, s.t)
            trace = record_node_faults(trace, s.t, rf, every=cfg.trace_every)
            s = apply_node_faults(s, rf)
            carry = apply_carry_faults(carry, rf)
            s, carry, inj, m, trace = packed_round_step(
                s, carry, inj, m, meta, cfg, topo, region, faults=rf,
                trace=trace, done=done,
            )
            s, carry, m, trace = _pin(mesh, s, carry, m, trace)
            return s, carry, inj, m, _fault_done(s, carry, inj), trace

        slim, carry, inj, metrics, _, trace = jax.lax.while_loop(
            cond, body,
            (slim, carry0, injected0, metrics, done0,
             new_trace(cfg, max_rounds)),
        )
    else:

        def body(c):
            s, carry, inj, m, done = c
            rf = round_faults(fplan, s.t)
            s = apply_node_faults(s, rf)
            carry = apply_carry_faults(carry, rf)
            s, carry, inj, m = packed_round_step(
                s, carry, inj, m, meta, cfg, topo, region, faults=rf,
                done=done,
            )
            s, carry, m, _ = _pin(mesh, s, carry, m)
            return s, carry, inj, m, _fault_done(s, carry, inj)

        slim, carry, inj, metrics, _ = jax.lax.while_loop(
            cond, body, (slim, carry0, injected0, metrics, done0)
        )
    full = unpack_into_state(carry, slim, cfg)
    full = full._replace(
        injected=unpack_bits(inj, cfg.n_payloads).astype(full.have.dtype)
    )
    if telemetry:
        return full, metrics, trace
    return full, metrics


def _smear_groups(low: jnp.ndarray, c: int) -> jnp.ndarray:
    """Broadcast each aligned c-bit group's LOW bit across the group."""
    w = low
    step = 1
    while step < c:
        w = w | (w << step)
        step *= 2
    return w


def all_chunks_words(have_w: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """u32[..., W] word mask: every bit of version v's C-bit group set
    iff ALL of v's chunks are held — `complete_versions` as group-uniform
    words, no [..., A, V] grid round-trip."""
    c = cfg.chunks_per_version
    low = _fold_all(have_w, c) & _group_low_bits_mask(c)
    return _smear_groups(low, c)


def sync_packed(
    carry: PackedCarry,
    state: SimState,
    cfg: SimConfig,
    topo: Topology,
    key: jax.Array,
    meta: PayloadMeta,
    faults=None,
    telem: bool = False,
    done=None,
):
    """Anti-entropy on packed words: needs computed from the SAME
    advertised gap/head tensors as the dense path (state.heads/gap_lo/
    gap_hi), but factored into per-NODE group-uniform word masks first —
    the per-edge work is then eight u32 gathers + bitwise ops on
    [E, W] words, never an [E, A, V] grid (the dense kernel's hottest
    tensor).  Group-uniformity (every chunk bit of a version carries the
    version's value) makes the word algebra exactly `edge_needs`:
    full/partial/head-catchup classes per sync.rs:127-249."""
    from .gaps import gaps_to_mask

    n = cfg.n_nodes
    s = cfg.sync_peers
    k_peers, _k_drop, k_rearm = jax.random.split(key, 3)

    due = state.sync_countdown <= 0
    if cfg.sync_cadence != "periodic":
        # sync-cadence variant (ISSUE 11) — identical override to the
        # dense kernel's, BEFORE the early-exit gate so a converged
        # lane still pulls nothing under the eager cadence
        from ..proto.schedule import cadence_due

        due = cadence_due(due, cfg)
    if done is not None:
        # early-exit gate (see broadcast_packed): a converged lane pulls
        # nothing — identical semantics, the batched loop discards its
        # carry, and solo loops never reach here with done=True
        due &= ~done

    peers = sample_member_targets(state, cfg, k_peers, s)
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)
    dst = peers.reshape(-1)
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)
    ok &= edge_alive(state.group, state.alive, src, dst)
    ok &= due[src]
    ok &= dst != src
    refused_cnt = jnp.int32(0)
    if faults is not None:
        # sync is a bidirectional stream: a cut in EITHER direction
        # refuses the session (the shared `fault_session_refused`, same
        # implementation as sync_step); fault loss never bites the
        # reliable bi-stream
        from .faults import fault_session_refused

        refused = fault_session_refused(faults, src, dst)
        if refused is not None:
            if telem:
                refused_cnt = jnp.sum(ok & refused, dtype=jnp.int32)
            ok &= ~refused

    v = cfg.n_versions
    v_idx = jnp.arange(1, v + 1, dtype=jnp.int32)
    miss_full = gaps_to_mask(state.gap_lo, state.gap_hi, v)  # [N, A, V]
    below_head = v_idx[None, None, :] <= state.heads[:, :, None]
    # node-level word masks (all group-uniform by construction)
    miss_w = grid_to_words(miss_full, cfg)  # [N, W]
    below_w = grid_to_words(below_head, cfg)  # [N, W]
    comp_w = all_chunks_words(carry.have, cfg)  # [N, W]
    haves_w = below_w & ~miss_w & comp_w
    partial_w = below_w & ~miss_w & ~comp_w

    # src-side masks ride BROADCASTS ([N, 1, W] against the s-axis): the
    # src index is `repeat(arange, s)` — regular, not a random gather.
    # Only the four dst-side masks pay a real gather (r4 profile: this
    # halves sync's HBM traffic at the 100k storm shape).
    w = miss_w.shape[1]
    # one fused random gather for the four dst-side masks (contiguous
    # 4×W-word rows gather better than four separate W-word lookups)
    dmasks = jnp.stack(
        [haves_w, partial_w, below_w, carry.have], axis=1
    )  # [N, 4, W]
    dd = dmasks[dst].reshape(n, s, 4, w)
    haves_d = dd[:, :, 0]
    partial_d = dd[:, :, 1]
    below_d = dd[:, :, 2]
    have_d = dd[:, :, 3]
    wanted = (
        (miss_w[:, None, :] & haves_d)  # full needs
        | (partial_w[:, None, :] & (haves_d | partial_d))  # partial
        | (~below_w[:, None, :] & below_d)  # head catch-up
    )  # [N, S, W]
    need = wanted & have_d & ~carry.have[:, None, :]
    need &= jnp.where(
        ok.reshape(n, s)[:, :, None], ONES, U32(0)
    )
    need = need.reshape(n * s, w)  # [E, W] for the fold below

    # per-sync byte budget, oldest-version-first (sync_step's
    # budget_prefix_mask) — evaluated per edge row in the word domain
    granted = budget_prefix_words(need, cfg.sync_budget_bytes, meta.nbytes)
    if telem:
        # pin ONE materialization: the telemetry grant counts below
        # consume `granted` too, and without a source-level barrier XLA
        # would recompute the whole need/budget pipeline into them
        granted = jax.lax.optimization_barrier(granted)

    # pulls land at the PULLER (src): exactly S edges per source in a
    # regular layout, so the OR-reduce is a packed fold — no scatter.
    # Latency-free rounds (faultless, or a plan with no delay events)
    # write the one-round-RTT slot t+1; FaultPlan latency instead
    # partitions the edges by session delay (the slower direction of the
    # bi-stream pair) and OR-folds each delay class into its own ring
    # slot — a static D-1-step loop, never a word scatter (at[].max on
    # u32 words is arithmetic max, NOT bitwise OR, and a slot written by
    # two consecutive rounds under differing delays would corrupt).
    d_slots = carry.sync_buf.shape[0]
    sdelay = None
    if faults is not None:
        from .faults import fault_session_delay

        sdelay = fault_session_delay(faults, src, dst)  # i32[E] | None
    if sdelay is None:
        pulled = _fold_or_regular(granted, n, s)  # [N, W] — stays packed
        sync_buf = carry.sync_buf.at[(state.t + 1) % d_slots].max(pulled)
        fruitful = (pulled != U32(0)).any(axis=1)  # [N]
    else:
        sync_buf = carry.sync_buf
        for d in range(d_slots - 1):  # compile validated 1+delay < D
            g_d = granted & jnp.where(
                (sdelay == d)[:, None], ONES, U32(0)
            )
            pulled_d = _fold_or_regular(g_d, n, s)  # [N, W]
            slot = (state.t + 1 + d) % d_slots
            # read-OR-write, not at[].max: the slot may already hold an
            # earlier round's slower-delay grant words
            sync_buf = sync_buf.at[slot].set(sync_buf[slot] | pulled_d)
        # fruitfulness counts every granted word regardless of delay
        # class — identical to sync_step's granted.any reduction
        fruitful = (
            (granted != U32(0)).any(axis=1).reshape(n, s).any(axis=1)
        )

    # fruitfulness-adaptive backoff, bit-identical to sync.sync_step
    cap = cfg.sync_backoff_cap()
    backoff = jnp.where(
        due & fruitful,
        jnp.int32(cfg.sync_interval_rounds),
        jnp.where(
            due,
            jnp.minimum(state.sync_backoff * 2, cap),
            state.sync_backoff,
        ),
    )
    rearm = jax.random.randint(k_rearm, (n,), 1, backoff + 1, jnp.int32)
    countdown = jnp.where(due, rearm, state.sync_countdown - 1)
    out = (
        PackedCarry(have=carry.have, inflight=carry.inflight,
                    relay=carry.relay, sync_buf=sync_buf),
        countdown,
        backoff,
    )
    if not telem:
        return out
    # session telemetry in the word domain: per-PAYLOAD grant counts in
    # ONE reduction over the [E, W] words (`fused.word_bit_counts`; the
    # legacy 32-shifted-reduction oracle sits behind CORRO_FUSED_ROUND)
    # — the exact integers the dense kernel sums over its [E, P] bools —
    # then the identical [P]-shaped f32 dot, so both paths' channels
    # match bit-for-bit
    from .fused import grant_fold
    from .telemetry import SyncTel, word_bit_counts

    # innermost-wins "telemetry" scope: flight-recorder cost, pulled out
    # of the sync ledger line (the dense kernel does the same)
    with phase_scope("telemetry"):
        counts = word_bit_counts(granted, cfg.n_payloads)  # i32[P]
        frames, byte_tot = grant_fold(counts, meta.nbytes)
        tel = SyncTel(
            sessions=jnp.sum(ok, dtype=jnp.int32),
            refused=refused_cnt,
            frames=frames,
            bytes=byte_tot,
        )
    return out + (tel,)
