"""Bitpacked round kernels: the payload axis as u32 words.

The dense round keeps one BYTE per (node, payload) bit in its hottest
carries (`have`, `inflight`) and per-edge masks; doc/experiments/
BITPACK_SPIKE.md measured the packed equivalents at ×4-×30 per
primitive (8× less HBM traffic, VPU-friendly bitwise ops).  This module
is the full packed round for the scenario class the headline bench
runs, kept EXACTLY equivalent to the dense kernels (tests/sim/
test_packed_equivalence.py compares round-by-round bit-for-bit):

- ``have_p[N, W] u32`` — W = P/32 words, payload p lives at word p//32
  bit p%32 (LSB-first);
- ``inflight_p[D, N, W] u32`` — the delay ring, bitwise-OR merged;
- ``relay planes r0..r3[N, W] u32`` — the 0..15 retransmission counter
  BITSLICED: bit b of plane k is bit k of payload b's counter.
  Decrement-where-mask is 4 bitwise ops of ripple borrow; "counter > 0"
  is ``r0|r1|r2|r3`` — the counter never leaves packed form;
- chunk completeness without unpacking: ``chunks_per_version`` is a
  power of two ≤ 32, so a version's chunks are CONTIGUOUS bits inside
  one word and "all chunks present" is a log2(C)-step bitwise fold.

Supported scenario envelope (validated by ``packed_supported``):
P % 32 == 0, chunks_per_version ∈ {1, 2, 4, 8, 16, 32}, statically
unmetered budgets (optimize_budgets), zero payload loss, and
max_transmissions < 16.  Everything outside stays on the dense path —
same results, just slower.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .state import ALIVE, PayloadMeta, SimConfig, SimState
from .swim import sample_member_targets
from .topology import Topology, edge_alive, edge_delay

U32 = jnp.uint32
ONES = jnp.uint32(0xFFFFFFFF)


def packed_supported(cfg: SimConfig, topo: Topology) -> bool:
    c = cfg.chunks_per_version
    return (
        cfg.n_payloads % 32 == 0
        and c in (1, 2, 4, 8, 16, 32)
        and cfg.rate_limit_bytes_round is None
        and cfg.sync_budget_bytes is None
        and topo.loss == 0.0
        and cfg.max_transmissions < 16
    )


def pack_bits(x: jnp.ndarray) -> jnp.ndarray:
    """bool/u8[..., P] → u32[..., P/32], LSB-first within each word."""
    *lead, p = x.shape
    b = (x > 0).reshape(*lead, p // 32, 32).astype(U32)
    return (b << jnp.arange(32, dtype=U32)).sum(axis=-1, dtype=U32)


def unpack_bits(w: jnp.ndarray, p: int) -> jnp.ndarray:
    """u32[..., W] → bool[..., P]."""
    bits = (w[..., None] >> jnp.arange(32, dtype=U32)) & U32(1)
    return bits.astype(jnp.bool_).reshape(*w.shape[:-1], p)


# -- bitsliced 4-bit counters ------------------------------------------------


class Planes(NamedTuple):
    r0: jnp.ndarray
    r1: jnp.ndarray
    r2: jnp.ndarray
    r3: jnp.ndarray

    @property
    def nonzero(self) -> jnp.ndarray:
        return self.r0 | self.r1 | self.r2 | self.r3


def planes_set(planes: Planes, where: jnp.ndarray, value: int) -> Planes:
    """Set the counter to ``value`` (0..15) at every bit of ``where``."""
    out = []
    for k, plane in enumerate(planes):
        bit = (value >> k) & 1
        plane = (plane & ~where) | (where if bit else U32(0))
        out.append(plane)
    return Planes(*out)


def planes_dec(planes: Planes, where: jnp.ndarray) -> Planes:
    """Saturating decrement at every bit of ``where`` (ripple borrow);
    callers guarantee where ⊆ nonzero, so saturation never triggers."""
    r0, r1, r2, r3 = planes
    borrow = where
    n0 = r0 ^ borrow
    borrow &= ~r0
    n1 = r1 ^ borrow
    borrow &= ~r1
    n2 = r2 ^ borrow
    borrow &= ~r2
    n3 = r3 ^ borrow
    return Planes(n0, n1, n2, n3)


# -- chunk-group folds (all/any chunks of each version, packed) --------------


def _fold_all(w: jnp.ndarray, c: int) -> jnp.ndarray:
    """Within every aligned c-bit group: all bits set ⇒ the group's LOW
    bit is 1 in the result (other group bits undefined — mask after)."""
    step = 1
    while step < c:
        w = w & (w >> step)
        step *= 2
    return w


def _fold_any(w: jnp.ndarray, c: int) -> jnp.ndarray:
    step = 1
    while step < c:
        w = w | (w >> step)
        step *= 2
    return w


def _group_low_bits_mask(c: int) -> jnp.ndarray:
    """u32 mask with bit set at every multiple of c (group low bits)."""
    m = 0
    for i in range(0, 32, c):
        m |= 1 << i
    return U32(m)


def group_grid(w: jnp.ndarray, cfg: SimConfig, mode: str) -> jnp.ndarray:
    """have-words [..., W] → bool[..., A, V] version grid (all/any chunks).

    Payload index = (v * A + a) * C + c (version-major), so each (v, a)
    owns C contiguous bits; with C a power of two ≤ 32 groups never
    straddle words."""
    c = cfg.chunks_per_version
    fold = _fold_all if mode == "all" else _fold_any
    low = fold(w, c) & _group_low_bits_mask(c)
    # extract the 32/c group bits per word → [..., P/C] = [..., V*A]
    groups_per_word = 32 // c
    shifts = jnp.arange(0, 32, c, dtype=U32)
    bits = (low[..., None] >> shifts) & U32(1)  # [..., W, 32/c]
    va = bits.reshape(*w.shape[:-1], cfg.n_versions * cfg.n_writers)
    grid = va.reshape(*w.shape[:-1], cfg.n_versions, cfg.n_writers)
    return jnp.swapaxes(grid, -1, -2).astype(jnp.bool_)  # [..., A, V]


def grid_to_words(x_av: jnp.ndarray, cfg: SimConfig) -> jnp.ndarray:
    """bool[..., A, V] → u32 words [..., W] with each (v, a) group's C
    bits all set where the grid is True (inverse of group_grid)."""
    c = cfg.chunks_per_version
    va = jnp.swapaxes(x_av, -1, -2).reshape(
        *x_av.shape[:-2], cfg.n_versions * cfg.n_writers
    )  # [..., V*A] in payload-group order
    groups_per_word = 32 // c
    g = va.reshape(*va.shape[:-1], va.shape[-1] // groups_per_word,
                   groups_per_word).astype(U32)
    shifts = jnp.arange(0, 32, c, dtype=U32)
    low = (g << shifts).sum(axis=-1, dtype=U32)  # group low bits
    # smear each group's low bit across its C bits
    w = low
    step = 1
    while step < c:
        w = w | (w << step)
        step *= 2
    return w


# -- packed state ------------------------------------------------------------


class PackedCarry(NamedTuple):
    have: jnp.ndarray  # u32[N, W]
    inflight: jnp.ndarray  # u32[D, N, W]
    relay: Planes  # 4 × u32[N, W]


def pack_state(state: SimState, cfg: SimConfig) -> PackedCarry:
    relay = state.relay_left.astype(jnp.int32)
    planes = Planes(*(
        pack_bits((relay >> k) & 1) for k in range(4)
    ))
    return PackedCarry(
        have=pack_bits(state.have),
        inflight=pack_bits(state.inflight),
        relay=planes,
    )


def unpack_into_state(carry: PackedCarry, state: SimState, cfg: SimConfig) -> SimState:
    p = cfg.n_payloads
    relay = sum(
        unpack_bits(plane, p).astype(jnp.uint8) << k
        for k, plane in enumerate(carry.relay)
    )
    return state._replace(
        have=unpack_bits(carry.have, p).astype(jnp.uint8),
        inflight=unpack_bits(carry.inflight, p).astype(jnp.uint8),
        relay_left=relay.astype(jnp.uint8),
    )


# -- the packed phases -------------------------------------------------------


def inject_packed(
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    t: jnp.ndarray,
    meta: PayloadMeta,
    cfg: SimConfig,
    alive: jnp.ndarray,
) -> Tuple[PackedCarry, jnp.ndarray]:
    n = cfg.n_nodes
    w = cfg.n_payloads // 32
    injecting = (meta.round == t) & (alive[meta.actor] == ALIVE)  # [P]
    inj_words = pack_bits(injecting)  # [W]
    # scatter each payload's bit into its origin row: build [N, W] where
    # row meta.actor[p] gets bit p.  Payloads share origin rows, so OR
    # via segment: one-hot word contribution per payload is heavy; use
    # the (actor, word) scatter over the P payloads instead.
    word_idx = jnp.arange(cfg.n_payloads, dtype=jnp.int32) // 32
    bit = (U32(1) << (jnp.arange(cfg.n_payloads, dtype=U32) % 32))
    contrib = jnp.where(injecting, bit, U32(0))
    own = jnp.zeros((n, w), U32)
    # add == OR here: every payload owns a DISTINCT bit, so contributions
    # landing on the same (actor, word) cell never overlap
    own = own.at[meta.actor, word_idx].add(contrib)
    newly = own & ~carry.have
    have = carry.have | own
    relay = planes_set(carry.relay, newly, cfg.max_transmissions)
    return (
        PackedCarry(have=have, inflight=carry.inflight, relay=relay),
        injected_p | inj_words,
    )


def broadcast_packed(
    carry: PackedCarry,
    injected_p: jnp.ndarray,
    state: SimState,
    cfg: SimConfig,
    topo: Topology,
    region: jnp.ndarray,
    key: jax.Array,
) -> PackedCarry:
    n = cfg.n_nodes
    f = cfg.fanout
    k_targets, _k_drop, k_ring0 = jax.random.split(key, 3)

    eligible = carry.have & carry.relay.nonzero & injected_p[None, :]  # [N, W]

    targets = sample_member_targets(state, cfg, k_targets, f)  # [N, F]
    if cfg.ring0_first and topo.n_regions > 1:
        me = jnp.arange(n, dtype=jnp.int32)
        per = max(1, n // topo.n_regions)
        start = region * per
        size = jnp.where(
            region == topo.n_regions - 1, n - start, per
        ).astype(jnp.int32)
        local = start + jax.random.randint(
            k_ring0, (n,), 0, jnp.iinfo(jnp.int32).max
        ) % jnp.maximum(size, 1)
        ok_local = local != me
        if cfg.couple_membership and cfg.swim_full_view:
            from .state import DOWN

            ok_local &= state.view[me, local] != DOWN
        elif cfg.couple_membership and cfg.swim_partial_view:
            from .state import DOWN

            m = state.pid.shape[1]
            bucket = local % m
            known = state.pid[me, bucket] == local
            ok_local &= known & (state.pkey[me, bucket] % 4 != DOWN)
        targets = targets.at[:, 0].set(
            jnp.where(ok_local, local, targets[:, 0])
        )
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), f)  # [E]
    dst = targets.reshape(-1)
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)
    ok &= edge_alive(state.group, state.alive, src, dst)
    ok &= dst != src
    delay = edge_delay(topo, region, src, dst)

    sent = jnp.where(ok[:, None], eligible[src], U32(0))  # [E, W]

    d_slots = carry.inflight.shape[0]
    slot = (state.t + delay) % d_slots
    flat_idx = slot * n + dst
    inflight = carry.inflight.reshape(d_slots * n, -1)
    # .at[].max == OR here? not for u32 words with differing bits — use
    # a real OR scatter via bitwise accumulation: max is WRONG for
    # packed words, so scatter-OR through index_add on disjoint... use
    # jnp's scatter with `or` mode via segment trick: at[].apply is slow;
    # instead: at[].max is wrong; at[].add overflows.  Use the supported
    # scatter mode: jax.lax.scatter with or is not exposed — emulate by
    # int32 bitwise trick: split into two scatters of 16-bit halves via
    # max?  Simplest correct: at[flat_idx].max on each BIT PLANE is
    # still wrong.  jnp.ndarray.at[].max works per ELEMENT (u32 compare)
    # — not bitwise OR.  Use at[idx].set(current | value) is racy for
    # duplicate indices.  The robust primitive: at[].add on one-hot is
    # out.  => use at[].max on the BITWISE-EXPANDED representation is
    # the dense path.  jax DOES expose at[].max/min/add/mul/set — and
    # 'or' arrives via at[].max only for booleans.  For u32 words use
    # the two-pass trick below instead.
    inflight = _scatter_or(inflight, flat_idx, sent)
    inflight = inflight.reshape(d_slots, n, -1)

    any_edge_ok = ok.reshape(n, f).any(axis=1)
    spent = eligible & jnp.where(any_edge_ok[:, None], ONES, U32(0))
    relay = planes_dec(carry.relay, spent)
    return PackedCarry(have=carry.have, inflight=inflight, relay=relay)


def _scatter_or(table: jnp.ndarray, idx: jnp.ndarray, words: jnp.ndarray) -> jnp.ndarray:
    """Exact OR-scatter of u32 words into table rows, duplicate indices
    allowed.  jnp's at[].max is ARITHMETIC max — wrong for packed words
    (max(0b01, 0b10) drops a bit) — and no public scatter exposes a
    bitwise combiner.  OR does hold per BIT, so the scatter runs on the
    boolean expansion: unpack updates to bool planes, one at[].max into
    a bool view of the table, repack.  XLA fuses the unpack/repack into
    the scatter's operand/result, so this costs about the DENSE bool
    scatter — acceptable for the broadcast fan-out (random duplicate
    destinations); regular-pattern callers (sync: exactly S edges per
    source) must use _fold_or_regular instead, which stays packed."""
    rows = table.shape[0]
    w = table.shape[1]
    tbl_bits = unpack_bits(table, w * 32).reshape(rows, w, 32)
    upd_bits = unpack_bits(words, w * 32).reshape(words.shape[0], w, 32)
    tbl_bits = tbl_bits.at[idx].max(upd_bits)
    packed = (
        tbl_bits.astype(U32) << jnp.arange(32, dtype=U32)[None, None, :]
    ).sum(axis=2, dtype=U32)
    return packed


def _fold_or_regular(words: jnp.ndarray, n: int, per: int) -> jnp.ndarray:
    """OR-reduce [n*per, W] edge words to [n, W] — the regular pattern
    where edge e belongs to source e // per.  Pure reshape + OR-reduce:
    no scatter, fully packed."""
    w = words.shape[-1]
    grouped = words.reshape(n, per, w)
    out = grouped[:, 0]
    for k in range(1, per):  # per is small & static (sync_peers)
        out = out | grouped[:, k]
    return out


def deliver_packed(
    carry: PackedCarry, t: jnp.ndarray, cfg: SimConfig
) -> PackedCarry:
    d_slots = carry.inflight.shape[0]
    slot = t % d_slots
    arriving = carry.inflight[slot]  # [N, W]
    newly = arriving & ~carry.have
    have = carry.have | arriving
    relay = planes_set(carry.relay, newly, max(cfg.max_transmissions - 1, 1))
    inflight = carry.inflight.at[slot].set(U32(0))
    return PackedCarry(have=have, inflight=inflight, relay=relay)


def sync_packed(
    carry: PackedCarry,
    state: SimState,
    cfg: SimConfig,
    topo: Topology,
    key: jax.Array,
) -> Tuple[PackedCarry, jnp.ndarray]:
    """Anti-entropy on packed words: needs computed from the SAME
    advertised gap/head tensors as the dense path (state.heads/gap_lo/
    gap_hi), grants as word masks."""
    from .gaps import gaps_to_mask

    n = cfg.n_nodes
    s = cfg.sync_peers
    k_peers, _k_drop, k_rearm = jax.random.split(key, 3)

    due = state.sync_countdown <= 0

    peers = sample_member_targets(state, cfg, k_peers, s)
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)
    dst = peers.reshape(-1)
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)
    ok &= edge_alive(state.group, state.alive, src, dst)
    ok &= due[src]
    ok &= dst != src

    v = cfg.n_versions
    v_idx = jnp.arange(1, v + 1, dtype=jnp.int32)
    miss_full = gaps_to_mask(state.gap_lo, state.gap_hi, v)  # [N, A, V]
    below_head = v_idx[None, None, :] <= state.heads[:, :, None]
    comp = group_grid(carry.have, cfg, "all")  # [N, A, V]
    partial = below_head & ~miss_full & ~comp
    haves = below_head & ~miss_full & comp

    full_need = miss_full[src] & haves[dst]
    partial_need = partial[src] & (haves[dst] | partial[dst])
    catchup = (v_idx[None, None, :] > state.heads[src][:, :, None]) & (
        v_idx[None, None, :] <= state.heads[dst][:, :, None]
    )
    wanted = full_need | partial_need | catchup  # [E, A, V]
    wanted_w = grid_to_words(wanted, cfg)  # [E, W]
    need = wanted_w & carry.have[dst] & ~carry.have[src]
    need &= jnp.where(ok[:, None], ONES, U32(0))

    # pulls land at the PULLER (src): exactly S edges per source in a
    # regular layout, so the OR-reduce is a packed fold — no scatter
    pulled = _fold_or_regular(need, n, s)  # [N, W]
    d_slots = carry.inflight.shape[0]
    slot = (state.t + 1) % d_slots
    inflight = carry.inflight.at[slot].set(carry.inflight[slot] | pulled)

    rearm = jax.random.randint(
        k_rearm, (n,), 1, cfg.sync_interval_rounds + 1, jnp.int32
    )
    countdown = jnp.where(due, rearm, state.sync_countdown - 1)
    return (
        PackedCarry(have=carry.have, inflight=inflight, relay=carry.relay),
        countdown,
    )
