"""Broadcast dissemination kernel (L6).

Vectorized rebuild of `handle_broadcasts` (broadcast/mod.rs:410-1042): every
round, each node holding payloads with remaining transmission budget picks
``fanout`` random up targets and sends its whole eligible buffer to them
(the reference drains its queue to one chosen member set per 500 ms flush
tick, so shared targets per round is the faithful model).  Receivers start
relaying with one transmission already spent (the rebroadcast path,
handlers.rs:768-779).  A per-node byte budget models the 10 MiB/s governor;
payloads beyond the budget wait (prefix-sum mask).

Delivery is a scatter-or over sampled edges — `at[dst].max` — into the
latency ring buffer slot matching the edge's delay class.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import ALIVE, PayloadMeta, SimConfig, SimState, budget_prefix_mask
from .swim import sample_member_targets
from .topology import (
    Topology,
    apply_degree_caps,
    edge_alive,
    edge_delay,
    edge_payload_drop,
)


def broadcast_step(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    region: jnp.ndarray,
    key: jax.Array,
    faults=None,
    telem: bool = False,
):
    """``telem=True`` (static, the RoundTrace seam) additionally returns
    a `telemetry.WireTel` of this round's wire activity — pure
    reductions over tensors the kernel already materializes, no RNG, so
    the telem=False path is untouched."""
    n, p = state.have.shape
    f = cfg.fanout
    k_targets, k_drop, k_ring0 = jax.random.split(key, 3)

    active = (state.injected > 0)[None, :]  # [1, P]
    # what each node would send: held, budget left, payload active
    eligible = (state.have > 0) & (state.relay_left > 0) & active  # [N, P]

    # rate limit: FIFO prefix (payload-index == injection order, the
    # version-major layout guarantee) within the per-round byte budget —
    # the reference drains its broadcast queue oldest-first under the
    # governor (broadcast/mod.rs:453-463)
    sending = budget_prefix_mask(
        eligible, cfg.rate_limit_bytes_round, meta.nbytes
    )

    # fanout targets come from each node's believed member list (the
    # reference's choose_count sample over Members.states,
    # broadcast/mod.rs:653-680) — false suspicion starves a live node;
    # ground-truth delivery masks still apply below
    targets = sample_member_targets(state, cfg, k_targets, f)  # [N, F]
    if cfg.ring0_first and topo.n_regions > 1:
        # ring0 tiering: slot 0 targets a SAME-REGION member (the lowest
        # RTT ring), so local broadcasts land intra-region first
        # (members.rs:38-178 ring buckets, broadcast/mod.rs:589-651).
        # The ring0 candidate must STILL be a believed member in coupled
        # modes — the reference picks ring0 from the member list's RTT
        # buckets, so a believed-down (or unknown) node stays starved
        me = jnp.arange(n, dtype=jnp.int32)
        per = max(1, n // topo.n_regions)
        start = region * per
        size = jnp.where(
            region == topo.n_regions - 1, n - start, per
        ).astype(jnp.int32)
        local = start + jax.random.randint(
            k_ring0, (n,), 0, jnp.iinfo(jnp.int32).max
        ) % jnp.maximum(size, 1)
        ok_local = local != me
        if cfg.couple_membership and cfg.swim_full_view:
            from .state import DOWN

            ok_local &= state.view[me, local] != DOWN
        elif cfg.couple_membership and cfg.swim_partial_view:
            from .state import DOWN

            m = state.pid.shape[1]
            bucket = local % m
            known = state.pid[me, bucket] == local
            ok_local &= known & (state.pkey[me, bucket] % 4 != DOWN)
        targets = targets.at[:, 0].set(
            jnp.where(ok_local, local, targets[:, 0])
        )
    # heterogeneous fan-out (ISSUE 9): slots past a node's degree cap
    # become the -1 sentinel — trace-time identity without classes
    targets = apply_degree_caps(targets, topo)
    if cfg.fanout_schedule != "flat":
        # fanout schedule (ISSUE 11): mask slots beyond this round's
        # scheduled count — the same -1 discipline as degree caps, a
        # trace-time branch (flat compiles the pre-change kernel)
        from ..proto.schedule import scheduled_fanout_targets

        targets = scheduled_fanout_targets(targets, cfg, state.t)
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), f)  # [E]
    dst = targets.reshape(-1)  # [E]
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)

    ok &= edge_alive(state.group, state.alive, src, dst)
    ok &= dst != src
    delay = edge_delay(topo, region, src, dst)  # [E]

    # loss is drawn per (edge, payload): each changeset is its own uni
    # frame on the wire (see edge_payload_drop); geo-tiered topologies
    # compare the same draw against per-edge tier thresholds
    drop = edge_payload_drop(
        topo, k_drop, src.shape[0], p, src=src, dst=dst, region=region
    )

    delay_ep = None
    cut = jnp.int32(0)
    if telem:
        from .telemetry import wire_loss_active

        _tel_loss = wire_loss_active(topo, faults)
    if faults is not None:
        # FaultPlan seam (sim/faults.py `fault_wire_effects`, shared
        # verbatim with the packed path): directed cuts, extra per-link
        # loss, fixed delay + jitter drawn per (edge, PAYLOAD) — each
        # changeset rides its own uni frame on the wire (the same grain
        # as edge_payload_drop), so jitter reorders traffic within one
        # flush exactly like the host tier's per-message draw.  Classes
        # the plan never schedules are trace-time no-ops — same results
        # as all-zero tensors, none of the draws.
        from .faults import fault_wire_effects

        ok_pre = ok
        ok, drop, delay, delay_ep = fault_wire_effects(
            faults, key, src, dst, p, ok, drop, delay
        )
        if telem:
            # the only thing fault_wire_effects masks out of ``ok`` is
            # the directed-cut class, so this IS the cut-edge count
            cut = jnp.sum(ok_pre & ~ok, dtype=jnp.int32)
    payload = state.have.dtype
    # `sending[src]` is a regular f-fold repeat (src = repeat(arange, f))
    # — a broadcast, not a 100M-cell random gather at the gapstress shape
    if telem and _tel_loss:
        # pin ONE materialization of the loss mask: the telemetry drop
        # count consumes it too, and without the barrier XLA duplicates
        # the whole drop expression (threefry included) into that
        # second consumer
        drop = jax.lax.optimization_barrier(drop)
    sent = jnp.where(
        ok.reshape(n, f, 1) & ~drop.reshape(n, f, p),
        sending[:, None, :],
        False,
    ).astype(payload).reshape(n * f, p)  # [E, P]

    # scatter into the delay ring: slot (t + delay) mod D
    d_slots = state.inflight.shape[0]
    if delay_ep is not None:
        # per-(edge, payload) delays (fault jitter): elementwise scatter
        # — same element count as the row scatter, only the indexing is
        # finer-grained; fault runs ride the dense path at small N
        slot_ep = (state.t + delay_ep) % d_slots  # [E, P]
        flat = (slot_ep * n + dst[:, None]) * p + jnp.arange(
            p, dtype=jnp.int32
        )[None, :]
        inflight = (
            state.inflight.reshape(-1)
            .at[flat.reshape(-1)]
            .max(sent.reshape(-1))
            .reshape(d_slots, n, p)
        )
    else:
        slot = (state.t + delay) % d_slots  # [E]
        flat_idx = slot * n + dst  # [E] into [D*N]
        inflight = state.inflight.reshape(d_slots * n, p)
        inflight = inflight.at[flat_idx].max(sent)
        inflight = inflight.reshape(d_slots, n, p)

    if cfg.dissemination == "push-pull":
        # push-pull exchange (ISSUE 11): the contacted node answers with
        # its OWN eligible buffer over the same edge — a round trip, so
        # a cut in either direction refuses the response, the response
        # draws its own (reverse-direction) wire loss, and it lands at
        # the puller at the same per-edge delay class (the documented
        # contracts live in proto/dissemination.py).  A trace-time
        # branch: the default "push" compiles the pre-change kernel and
        # the pull drop key is fold_in-derived inside the branch.
        from ..proto.dissemination import pull_session_ok, pull_wire_drop

        ok_pull = pull_session_ok(ok, faults, src, dst)
        drop_pull = pull_wire_drop(
            topo, faults, k_drop, src, dst, p, region
        )
        if telem and _tel_loss:
            # same one-materialization rule as the push drop mask: the
            # telemetry drop count below consumes it too
            drop_pull = jax.lax.optimization_barrier(drop_pull)
        resp = jnp.where(
            ok_pull[:, None] & ~drop_pull, sending[dst], False
        ).astype(payload)  # [E, P] — the dst gather is variant-only cost
        slot_pull = (state.t + delay) % d_slots
        flat_pull = slot_pull * n + src  # responses land at the PULLER
        inflight = (
            inflight.reshape(d_slots * n, p)
            .at[flat_pull]
            .max(resp)
            .reshape(d_slots, n, p)
        )

    # transmission budget decays once per flush that actually SENT —
    # i.e. handed datagrams to the transport.  A sender cannot know the
    # target is partitioned away or dead (that's what SWIM is for), so
    # unreachable targets still spend budget (the reference's decay
    # happens at send, broadcast/mod.rs:653-778; r4 ground-truth sweep:
    # refund-on-partition made the sim recover unrealistically fast).
    attempted = (targets >= 0) & (targets != jnp.arange(n)[:, None])
    node_up = state.alive == ALIVE
    any_attempt = attempted.any(axis=1) & node_up  # [N]
    spent = sending & any_attempt[:, None]
    relay_left = state.relay_left - spent.astype(state.relay_left.dtype)

    state = state._replace(inflight=inflight, relay_left=relay_left)
    if not telem:
        return state
    # wire telemetry off the hot path: per-node transmitted frames AND
    # byte totals come out of ONE pass over the `sending` bools
    # (fused.dense_send_stats — the same loads the ring scatter's `sent`
    # mask consumed), folded over the [E]-shaped edge mask — no extra
    # [E, P] traversal; the drop count packs the loss mask to words and
    # popcounts, and only when a loss class exists at trace time.  The
    # packed kernel computes the SAME quantities from identical-valued
    # tensors with identical reduction shapes, so the two paths'
    # channels agree bit-for-bit (test_telemetry pins it).
    from .fused import dense_send_stats
    from .profile import phase_scope
    from .telemetry import WireTel

    # innermost-wins "telemetry" scope (profile.py): these folds are
    # flight-recorder cost, not broadcast cost, and the ledger's
    # telemetry fraction is cross-checked against the interleaved
    # overhead measurement
    with phase_scope("telemetry"):
        # exact i32 per-node totals — the identical integers the packed
        # twin computes on words, so the f32 fold below matches
        # bit-for-bit
        send_frames, send_bytes = dense_send_stats(sending, meta.nbytes)
        okf = ok.reshape(n, f)
        frames = jnp.sum(
            jnp.where(okf, send_frames[:, None], 0), dtype=jnp.int32
        )
        dropped = jnp.int32(0)
        if _tel_loss:
            if p % 32 == 0:
                # word-domain count of loss hits on eligible live frames
                # — the packed kernel's formula on identical values
                from .packed import pack_bits

                w = p // 32
                hit = pack_bits(drop).reshape(n, f, w) & pack_bits(
                    sending
                )[:, None, :] & jnp.where(
                    okf[:, :, None], jnp.uint32(0xFFFFFFFF),
                    jnp.uint32(0),
                )
                dropped = jnp.sum(
                    jax.lax.population_count(hit), dtype=jnp.int32
                )
            else:  # outside the word envelope: small P, plain reduce
                dropped = jnp.sum(
                    ok.reshape(n, f, 1) & drop.reshape(n, f, p)
                    & sending[:, None, :],
                    dtype=jnp.int32,
                )
        bytes_out = jnp.sum(
            jnp.where(okf, send_bytes.astype(jnp.float32)[:, None], 0.0)
        )
        if cfg.dissemination == "push-pull":
            # the pull responses are wire traffic too (the exchange's
            # cost side of the Pareto): same fold shapes as the push
            # direction, responder-side per-node stats gathered by dst —
            # the packed twin computes the identical integers on words,
            # so the channels stay bit-equal across kernels
            okpf = ok_pull.reshape(n, f)
            frames = frames + jnp.sum(
                jnp.where(okpf, send_frames[dst].reshape(n, f), 0),
                dtype=jnp.int32,
            )
            bytes_out = bytes_out + jnp.sum(
                jnp.where(
                    okpf,
                    send_bytes[dst].astype(jnp.float32).reshape(n, f),
                    0.0,
                )
            )
            if _tel_loss:
                if p % 32 == 0:
                    from .packed import pack_bits

                    w = p // 32
                    hitp = pack_bits(drop_pull).reshape(
                        n, f, w
                    ) & pack_bits(sending)[dst].reshape(
                        n, f, w
                    ) & jnp.where(
                        okpf[:, :, None], jnp.uint32(0xFFFFFFFF),
                        jnp.uint32(0),
                    )
                    dropped = dropped + jnp.sum(
                        jax.lax.population_count(hitp), dtype=jnp.int32
                    )
                else:
                    dropped = dropped + jnp.sum(
                        ok_pull.reshape(n, f, 1)
                        & drop_pull.reshape(n, f, p)
                        & sending[dst].reshape(n, f, p),
                        dtype=jnp.int32,
                    )
        tel = WireTel(
            frames=frames,
            bytes=bytes_out,
            dropped=dropped,
            cut=cut,
        )
    return state, tel


def deliver_step(state: SimState, cfg: SimConfig) -> SimState:
    """Pop this round's delay slot of BOTH rings: newly BROADCAST-received
    payloads become held and start relaying with one transmission spent
    (rebroadcast semantics, handlers.rs:768-779).  The sync ring's slot
    (pulls granted 1+fault_delay rounds ago) merges into ``have`` too but
    does NOT re-arm the relay budget — sync-received changesets are
    never rebroadcast in the reference."""
    d_slots = state.inflight.shape[0]
    slot = state.t % d_slots
    arriving = state.inflight[slot]  # [N, P]
    sync_arrivals = state.sync_inflight[slot]  # [N, P]
    if cfg.ordering == "fifo":
        # FIFO ordering gate (ISSUE 11; proto/ordering.py): admit a
        # chunk of version v only once v-1 from the same origin is
        # completely held BEFORE this round's merge; rejected arrivals
        # are discarded (the ring slot zeroes below) and re-served by
        # retransmission or anti-entropy.  Both rings gate on the one
        # mask — sync-pulled chunks obey the same delivery order.
        from ..proto.ordering import admit_payload_mask

        admit = admit_payload_mask(state.have, cfg)  # bool[N, P]
        arriving = jnp.where(admit, arriving, jnp.zeros_like(arriving))
        sync_arrivals = jnp.where(
            admit, sync_arrivals, jnp.zeros_like(sync_arrivals)
        )
    newly = (arriving > 0) & (state.have == 0)
    have = jnp.maximum(jnp.maximum(state.have, arriving), sync_arrivals)
    relay_init = max(cfg.max_transmissions - 1, 1)
    relay_left = jnp.where(
        newly, jnp.uint8(relay_init), state.relay_left
    ).astype(state.relay_left.dtype)
    inflight = state.inflight.at[slot].set(0)
    sync_inflight = state.sync_inflight.at[slot].set(0)
    return state._replace(
        have=have, relay_left=relay_left, inflight=inflight,
        sync_inflight=sync_inflight,
    )


def inject_step(state: SimState, meta: PayloadMeta, cfg: SimConfig) -> SimState:
    """Origin nodes learn their own commits the round they're injected
    (the local write path: commit → broadcast queue, broadcast.rs:511)."""
    n, p = state.have.shape
    injecting = (meta.round == state.t) & (state.alive[meta.actor] == ALIVE)
    own = jnp.zeros((n, p), state.have.dtype)
    own = own.at[meta.actor, jnp.arange(p)].max(injecting.astype(state.have.dtype))
    newly = (own > 0) & (state.have == 0)
    have = jnp.maximum(state.have, own)
    relay_left = jnp.where(
        newly, jnp.uint8(cfg.max_transmissions), state.relay_left
    ).astype(state.relay_left.dtype)
    injected = jnp.maximum(state.injected, injecting.astype(state.injected.dtype))
    return state._replace(have=have, relay_left=relay_left, injected=injected)
