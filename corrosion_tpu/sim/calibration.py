"""Round-Δt calibration constants (ground-truth tier ↔ sim rounds).

The dissemination kernels are calibrated structurally — after the r4
fidelity fixes (budget spend-on-attempt, sync-received payloads never
rebroadcast, fruitfulness-adaptive sync backoff) the host and sim
convergence distributions agree within ×1.5 + 1 round across the loss
sweep, partition/heal, and chunked-write scenarios
(tests/sim/test_ground_truth_sweep.py), so no fudge factor is applied
there.

SWIM detection is the one place a residual constant remains: the sim
suspects the round a probe fails, while the host pipeline's failed-ack
await serializes with its probe loop and gossip fan-in adds tail
latency.  Paired measurements (doc/experiments/NORTH_STAR.md r3-r4:
host 27-35 probe periods vs sim 20 on the 64-node kill scenario) put
the host/sim ratio at 1.35-1.75; the constant below is the midpoint
estimate used when converting sim detection rounds to expected host
probe periods.  tests/sim/test_ground_truth.py asserts the calibrated
prediction lands within ×1.5."""

#: expected host probe periods per sim detection probe period
SWIM_HOST_PERIODS_PER_SIM_PERIOD = 1.45
