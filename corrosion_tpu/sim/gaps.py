"""Fixed-K version-gap interval tensors — the device form of the
reference's gap algebra.

The reference tracks, per (node, origin-actor), the set of version ranges
it has never seen any data for: `BookedVersions`'s `RangeInclusiveSet`
persisted to `__corro_bookkeeping_gaps` (agent.rs:1092-1236, 1261-1437).
`generate_sync` advertises them as `need`; `compute_available_needs`
(sync.rs:127-249) intersects our needs with a peer's fully-held set.

On device the rangemap becomes two fixed-K tensors per (node, actor):
``gap_lo/gap_hi[N, A, K]`` (1-based inclusive version ranges, 0 = empty
slot).  K overflow is handled conservatively: the K-th slot's hi is
extended to the last missing version, merging every overflow run into one
range.  That direction is SAFE — a node may *request* versions it already
has (the chunk-level grant mask filters those out), and a server may
*under-advertise* (versions inside the merged range look missing), which
slows convergence but never corrupts it.  `gap_overflow` counts clamped
(node, actor) pairs so runs can report the distortion.

The scalar spec for all of this is `corrosion_tpu.core.sync` /
`core.bookkeeping`; tests/sim/test_gap_kernels.py property-tests the two
against each other on randomized traces.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp

from .state import SimConfig


class GapTensors(NamedTuple):
    lo: jnp.ndarray  # i32[N, A, K] 1-based range starts, 0 = empty slot
    hi: jnp.ndarray  # i32[N, A, K] inclusive ends
    overflow: jnp.ndarray  # bool[N, A] had more than K runs (clamped)


def extract_gaps(
    touched: jnp.ndarray, heads: jnp.ndarray, cfg: SimConfig
) -> GapTensors:
    """Run-length-extract needed version ranges into fixed-K interval slots.

    ``touched[N, A, V]`` — any chunk of the version arrived (the bookie
    knows the version, complete or partial); ``heads[N, A]`` — max touched
    version.  A *gap* is a maximal run of untouched versions below the
    head — exactly the ranges `VersionsSnapshot::insert_db` would persist
    (agent.rs:1092-1236).  Untouched versions above the head are not gaps;
    they are the head-catchup range of `compute_available_needs`.

    Pure gather/scatter + cumsum — one fused XLA pass per round.
    """
    n, a, v = touched.shape
    k = cfg.gap_slots
    v_idx = jnp.arange(1, v + 1, dtype=jnp.int32)  # 1-based versions

    missing = (~touched) & (v_idx[None, None, :] <= heads[:, :, None])
    prev = jnp.pad(missing[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
    nxt = jnp.pad(missing[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
    start = missing & ~prev
    end = missing & ~nxt
    # run index (1-based) at every position of its run
    rank = jnp.cumsum(start, axis=2, dtype=jnp.int32)

    # scatter run boundaries into K slots (runs beyond K contribute 0)
    rows = jnp.arange(n * a, dtype=jnp.int32)[:, None]  # [N*A, 1]
    slot = jnp.clip(rank - 1, 0, k - 1).reshape(n * a, v)
    keep = (rank <= k).reshape(n * a, v)
    lo_vals = jnp.where(start.reshape(n * a, v) & keep, v_idx[None, :], 0)
    hi_vals = jnp.where(end.reshape(n * a, v) & keep, v_idx[None, :], 0)
    lo = jnp.zeros((n * a, k), jnp.int32).at[rows, slot].max(lo_vals)
    hi = jnp.zeros((n * a, k), jnp.int32).at[rows, slot].max(hi_vals)
    lo = lo.reshape(n, a, k)
    hi = hi.reshape(n, a, k)

    # overflow clamp: merge runs K.. into slot K-1 by extending its hi to
    # the last missing version (over-covers; see module docstring)
    overflow = rank[:, :, -1] > k
    last_missing = (missing * v_idx[None, None, :]).max(axis=2)  # [N, A]
    hi = hi.at[:, :, k - 1].set(
        jnp.where(overflow, last_missing, hi[:, :, k - 1])
    )
    return GapTensors(lo=lo, hi=hi, overflow=overflow)


def gaps_to_mask(lo: jnp.ndarray, hi: jnp.ndarray, n_versions: int) -> jnp.ndarray:
    """Expand interval tensors [..., K] back to a dense bool mask
    [..., V] over 1-based versions, via the difference-array trick (no
    [..., V, K] intermediate): +1 at each lo, -1 past each hi, cumsum.
    """
    *batch, k = lo.shape
    rows_n = math.prod(batch) if batch else 1
    flat_lo = lo.reshape(rows_n, k)
    flat_hi = hi.reshape(rows_n, k)
    valid = (flat_lo > 0).astype(jnp.int32)
    rows = jnp.arange(rows_n, dtype=jnp.int32)[:, None]
    # index v (1-based) lives at delta position v; empty slots hit 0
    delta = jnp.zeros((rows_n, n_versions + 2), jnp.int32)
    delta = delta.at[rows, jnp.clip(flat_lo, 0, n_versions + 1)].add(valid)
    delta = delta.at[rows, jnp.clip(flat_hi + 1, 0, n_versions + 1)].add(-valid)
    covered = jnp.cumsum(delta, axis=1)[:, 1 : n_versions + 1] > 0
    return covered.reshape(*batch, n_versions)
