"""Fixed-K version-gap interval tensors — the device form of the
reference's gap algebra.

The reference tracks, per (node, origin-actor), the set of version ranges
it has never seen any data for: `BookedVersions`'s `RangeInclusiveSet`
persisted to `__corro_bookkeeping_gaps` (agent.rs:1092-1236, 1261-1437).
`generate_sync` advertises them as `need`; `compute_available_needs`
(sync.rs:127-249) intersects our needs with a peer's fully-held set.

On device the rangemap becomes two fixed-K tensors per (node, actor):
``gap_lo/gap_hi[N, A, K]`` (1-based inclusive version ranges, 0 = empty
slot).  K overflow is handled conservatively: the K-th slot's hi is
extended to the last missing version, merging every overflow run into one
range.  That direction is SAFE — a node may *request* versions it already
has (the chunk-level grant mask filters those out), and a server may
*under-advertise* (versions inside the merged range look missing), which
slows convergence but never corrupts it.  `gap_overflow` counts clamped
(node, actor) pairs so runs can report the distortion.

The scalar spec for all of this is `corrosion_tpu.core.sync` /
`core.bookkeeping`; tests/sim/test_gap_kernels.py property-tests the two
against each other on randomized traces.
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax.numpy as jnp

from .profile import phase_scope
from .state import SimConfig


class GapTensors(NamedTuple):
    lo: jnp.ndarray  # i32[N, A, K] 1-based range starts, 0 = empty slot
    hi: jnp.ndarray  # i32[N, A, K] inclusive ends
    overflow: jnp.ndarray  # bool[N, A] had more than K runs (clamped)


def extract_gaps(
    touched: jnp.ndarray, heads: jnp.ndarray, cfg: SimConfig
) -> GapTensors:
    """Run-length-extract needed version ranges into fixed-K interval slots.

    ``touched[N, A, V]`` — any chunk of the version arrived (the bookie
    knows the version, complete or partial); ``heads[N, A]`` — max touched
    version.  A *gap* is a maximal run of untouched versions below the
    head — exactly the ranges `VersionsSnapshot::insert_db` would persist
    (agent.rs:1092-1236).  Untouched versions above the head are not gaps;
    they are the head-catchup range of `compute_available_needs`.

    Pure gather/scatter + cumsum — one fused XLA pass per round.
    Self-scoped ``corro.gaps`` (profile.py) so the interval machinery
    attributes to the gap-tracking ledger line from every caller.
    """
    with phase_scope("gaps"):
        if touched.shape[2] <= 32:
            return _extract_gaps_words(touched, heads, cfg)
        return _extract_gaps_dense(touched, heads, cfg)


def _extract_gaps_dense(
    touched: jnp.ndarray, heads: jnp.ndarray, cfg: SimConfig
) -> GapTensors:
    n, a, v = touched.shape
    k = cfg.gap_slots
    v_idx = jnp.arange(1, v + 1, dtype=jnp.int32)  # 1-based versions

    missing = (~touched) & (v_idx[None, None, :] <= heads[:, :, None])
    prev = jnp.pad(missing[:, :, :-1], ((0, 0), (0, 0), (1, 0)))
    nxt = jnp.pad(missing[:, :, 1:], ((0, 0), (0, 0), (0, 1)))
    start = missing & ~prev
    end = missing & ~nxt
    # run index (1-based) at every position of its run
    rank = jnp.cumsum(start, axis=2, dtype=jnp.int32)

    # select run boundaries into K slots (runs beyond K contribute 0).
    # A scatter into [N*A, K] did this job before, but a 12.8M-element
    # random scatter cost ~300 ms/round on CPU at the 100k storm shape
    # and scatters are the weakest op on TPU too; the r4 rewrite used K
    # static masked reductions instead (r4 profile: 343 ms → see
    # BENCH_DIAG).  Since ISSUE 19 the default is ONE-PASS: both
    # boundary selections reduce a virtual [N, A, V, K] slot expansion
    # (XLA fuses the compare+select into the reduce loop — each is one
    # traversal of the V axis), and the overflow clamp's last-missing
    # scan rides the SAME hi reduction as a K+1-th column.  Identical
    # results either way: each (row, slot) receives AT MOST one
    # boundary, so a masked max ≡ the scatter, and the largest missing
    # version is always a run END (its successor is non-missing or
    # absent), so max-over-ends == max-over-missing.  The legacy
    # 2K+1-reduction form stays behind CORRO_FUSED_ROUND as the oracle
    # (tests/sim/test_fused.py holds the two equal).
    from .fused import fused_round_enabled

    overflow = rank[:, :, -1] > k
    if fused_round_enabled():
        slot_ids = jnp.arange(1, k + 1, dtype=jnp.int32)  # [K]
        in_slot = rank[:, :, :, None] == slot_ids  # [N, A, V, K] virtual
        vcol = v_idx[None, None, :, None]
        lo = jnp.where(start[..., None] & in_slot, vcol, 0).max(axis=2)
        # hi + last_missing in one reduction: column K's mask is every
        # run end, whose max IS the last missing version
        in_slot_ext = jnp.concatenate(
            [in_slot, jnp.ones(in_slot.shape[:3] + (1,), bool)], axis=-1
        )
        hi_ext = jnp.where(
            end[..., None] & in_slot_ext, vcol, 0
        ).max(axis=2)  # [N, A, K+1]
        hi, last_missing = hi_ext[..., :k], hi_ext[..., k]
    else:
        los = []
        his = []
        for slot_k in range(k):
            in_slot = rank == slot_k + 1
            los.append(
                jnp.where(
                    start & in_slot, v_idx[None, None, :], 0
                ).max(axis=2)
            )
            his.append(
                jnp.where(end & in_slot, v_idx[None, None, :], 0).max(axis=2)
            )
        lo = jnp.stack(los, axis=-1)  # [N, A, K]
        hi = jnp.stack(his, axis=-1)
        last_missing = (missing * v_idx[None, None, :]).max(axis=2)  # [N, A]

    # overflow clamp: merge runs K.. into slot K-1 by extending its hi to
    # the last missing version (over-covers; see module docstring)
    hi = hi.at[:, :, k - 1].set(
        jnp.where(overflow, last_missing, hi[:, :, k - 1])
    )
    return GapTensors(lo=lo, hi=hi, overflow=overflow)


def _extract_gaps_words(
    touched: jnp.ndarray, heads: jnp.ndarray, cfg: SimConfig
) -> GapTensors:
    """V ≤ 32 fast path: the whole version axis packs into ONE u32 word
    per (node, actor), so run extraction is bitwise on [N, A] words —
    32× less data than the [N, A, V] formulation (the r4 profile put
    the grid version at ~350 ms/round at the 100k storm shape; this is
    a few ms).  Semantics identical: K 1-based inclusive ranges,
    overflow clamp extends slot K-1 to the last missing version."""
    import jax.lax as lax

    n, a, v = touched.shape
    k = cfg.gap_slots
    u32 = jnp.uint32
    one = u32(1)

    shifts = jnp.arange(v, dtype=u32)
    tv = (touched.astype(u32) << shifts[None, None, :]).sum(
        axis=2, dtype=u32
    )  # [N, A] version-bit words (bit i = version i+1 touched)
    h = heads.astype(u32)
    below = jnp.where(
        h >= 32, u32(0xFFFFFFFF), (one << h) - one
    )  # bits [0, head)
    missing = ~tv & below  # [N, A]

    start = missing & ~(missing << one)
    end = missing & ~(missing >> one)

    def nth_positions(bits: jnp.ndarray, count: int) -> jnp.ndarray:
        """1-based position of the j-th set bit for j < count (0 when
        absent), via iterated lowest-set-bit extraction."""
        out = []
        s = bits
        for _ in range(count):
            low = s & (~s + one)  # lowest set bit (two's complement)
            pos = lax.population_count(low - one) + 1  # 1-based
            out.append(jnp.where(s != 0, pos, u32(0)).astype(jnp.int32))
            s &= s - one
        return jnp.stack(out, axis=-1)  # [N, A, count]

    lo = nth_positions(start, k)
    hi = nth_positions(end, k)

    n_runs = lax.population_count(start).astype(jnp.int32)  # [N, A]
    overflow = n_runs > k
    # last missing version: smear below the MSB, popcount = position
    sm = missing
    for sh in (1, 2, 4, 8, 16):
        sm = sm | (sm >> u32(sh))
    last_missing = lax.population_count(sm).astype(jnp.int32)  # [N, A]
    hi = hi.at[:, :, k - 1].set(
        jnp.where(overflow, last_missing, hi[:, :, k - 1])
    )
    return GapTensors(lo=lo, hi=hi, overflow=overflow)


def gaps_to_mask(lo: jnp.ndarray, hi: jnp.ndarray, n_versions: int) -> jnp.ndarray:
    """Expand interval tensors [..., K] back to a dense bool mask
    [..., V] over 1-based versions.

    K-unrolled interval comparisons in a TRANSPOSED [V, rows] layout:
    the natural [rows, V] orientation leaves V (= 8 at the storm shape)
    in the 128-wide lane dimension — 94% padding — and the previous
    difference-array formulation added two scatter-adds on top of it;
    together they were the single hottest op of the 100k round (~300 ms
    of the 704 ms TPU round, r4 micro-profile).  With rows in the lane
    dimension every comparison is lane-full, there are no scatters, and
    the final transpose moves one 12.8 MB bool tensor.
    """
    *batch, k = lo.shape
    rows_n = math.prod(batch) if batch else 1
    flat_lo = lo.reshape(rows_n, k).T  # [K, rows]
    flat_hi = hi.reshape(rows_n, k).T
    v_idx = jnp.arange(1, n_versions + 1, dtype=lo.dtype)[:, None]  # [V, 1]
    covered = jnp.zeros((n_versions, rows_n), bool)
    for slot in range(k):  # K is a small static carry dimension
        covered |= (flat_lo[slot] > 0) & (flat_lo[slot] <= v_idx) & (
            v_idx <= flat_hi[slot]
        )
    return covered.T.reshape(*batch, n_versions)
