"""Partial-view SWIM membership kernel — the O(N·M) scale tier.

Full-view SWIM (sim/swim.py) carries O(N²) belief matrices: right for the
64-4096-node membership configs, impossible at 100k.  This module runs the
same probe/suspect/down/refute/gossip state machine on **direct-mapped
member tables**: node n tracks beliefs about at most M members in
``pid/pkey/psince[N, M]``, where member id x can only live in bucket
x mod M.  Expected watchers per member ≈ M, so detection quality per
member matches SWIM's k-watcher analysis while total state is O(N·M).

The reference's Foca holds the full member list per node; the partial view
is the TPU-native compromise that keeps the COUPLING (targets drawn from
the believed member list, down members unreachable, rejoin via announce)
at 100k nodes — VERDICT r1 item 3.  Mechanics mirrored from the
reference:

- probe/indirect-probe/suspect/down: runtime_loop (broadcast/mod.rs:
  122-386) with WAN timing scaled by cluster size (SimConfig.wan_tuned ≈
  broadcast/mod.rs:236-256, 951-960);
- gossip piggyback of ``gossip_entries`` table rows + the sender's own
  claim; receivers ignore pushes from senders they believe DOWN (foca
  drops down members' traffic);
- announce/rejoin: periodic self-claim to a uniformly random node
  bypassing the table (spawn_swim_announcer, util.rs:104-123), with
  feedback driving incarnation bumps (Actor::renew, actor.rs:199-209);
- down-member GC: a DOWN or empty bucket is reclaimed by any ALIVE entry
  of a matching-residue id (remove_down_after analog).

Belief precedence rides one scatter word: ``pkey = inc*4 + state`` (max =
higher incarnation wins, then worse state), and bucket replacement packs
``pkey * ID_CAP + id`` into an i32 — hence the 2^18 node cap and the
incarnation clamp at 2046 (see the bound asserts below).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import ALIVE, DOWN, SUSPECT, SimConfig, SimState
from .swim import (  # shared sampling/reachability
    _compact_targets,
    _dup_before,
    _reachable,
)
from .topology import Topology

ID_BITS = 18  # r5: widened 17→18 for the 250k north-star headroom tier
ID_CAP = 1 << ID_BITS  # 262144
# incarnation clamp sized to the pack bounds below (r5: 4000→2046 to
# buy the extra id bit; foca's incarnation is a u16 and refutation
# episodes per member stay far below 2k in any scenario tier)
INC_CLAMP = 2046
# the merge gather packs (pkey+1) above (pid+1): the +1 offsets absorb
# the -1 empty markers, so the pid field needs ID_BITS+1 bits.  Bounds:
# u32 gather word  (INC_CLAMP*4+3+1) << 19 | 2^18        < 2^32
# i32 scatter word (INC_CLAMP*4+3) * ID_CAP + (ID_CAP-1) < 2^31
PACK_SHIFT = ID_BITS + 1
PACK_MASK = (1 << PACK_SHIFT) - 1
assert (INC_CLAMP * 4 + 4) << PACK_SHIFT | ID_CAP < 1 << 32
assert (INC_CLAMP * 4 + 3) * ID_CAP + ID_CAP - 1 < 1 << 31


def _pack_tables(pid: jnp.ndarray, pkey: jnp.ndarray) -> jnp.ndarray:
    """One u32 word per bucket: ``(pkey+1) << PACK_SHIFT | (pid+1)`` —
    the same packing `_merge_entries` uses for its fused gather.  Every
    random (pid, pkey) pair read then costs ONE 4-byte gather instead of
    two (r5, VERDICT r4 weak #4: the sampler + gossip-filter gathers
    were the pswim phase's remaining hot spot after the r4 scatter
    purge).  The pack itself is elementwise and CSE'd by XLA across the
    call sites inside one jitted round."""
    u32 = jnp.uint32
    return ((pkey + 1).astype(u32) << PACK_SHIFT) | (pid + 1).astype(u32)


def _unpack_word(w: jnp.ndarray):
    pid = (w & jnp.uint32(PACK_MASK)).astype(jnp.int32) - 1
    pkey = (w >> PACK_SHIFT).astype(jnp.int32) - 1
    return pid, pkey


def psample_member_targets(
    state: SimState, cfg: SimConfig, key: jax.Array, count: int
) -> jnp.ndarray:
    """i32[N, count] targets drawn from each node's member table (believed
    not-DOWN buckets); -1 marks unfilled slots.  The partial-view analog
    of swim.sample_member_targets."""
    n, m = state.pid.shape
    over = 4 * count
    # transposed [over, N] layout (see swim._compact_targets) + one
    # packed gather for the (pid, pkey) pair per sampled bucket
    slots = jax.random.randint(key, (over, n), 0, m, jnp.int32)
    me = jnp.arange(n, dtype=jnp.int32)[None, :]
    # static trace-time guard: the flat index me*m+slots rides i32
    assert n * m < 2**31, "flat gather index would overflow int32"
    flat = _pack_tables(state.pid, state.pkey).reshape(-1)
    cand, ckey = _unpack_word(flat[me * m + slots])  # [over, N]
    valid = (cand >= 0) & (cand != me) & (ckey % 4 != DOWN) & (ckey >= 0)
    valid &= ~_dup_before(cand, valid)  # distinct targets (choose_multiple)
    return _compact_targets(cand, valid, count)


def _merge_entries(
    pid: jnp.ndarray,
    pkey: jnp.ndarray,
    psince: jnp.ndarray,
    e_dst: jnp.ndarray,
    e_id: jnp.ndarray,
    e_key: jnp.ndarray,
    e_ok: jnp.ndarray,
    t: jnp.ndarray,
    cfg: SimConfig,
):
    """Merge flat gossip/announce entries into the receivers' tables.

    Matching-id entries merge by belief precedence (scatter-max on pkey);
    non-matching ALIVE entries compete for empty or AGED-DOWN buckets via
    a packed (pkey, id) scatter-max — the down-member GC.  Young DOWN
    entries resist eviction (remove_down_after analog) so a rejoining
    member still has its table slots healable by precedence.
    """
    n, m = pid.shape
    old_pkey = pkey
    bucket = jnp.where(e_id >= 0, e_id % m, 0)
    # ONE fused random gather for the three table reads: the per-entry
    # (dst, bucket) accesses are the step's cache-miss hot spot.  pid
    # (< 2^18) and pkey (≤ INC_CLAMP*4+3 < 2^13) pack into one u32
    # word (+1 offsets absorb the -1 empty markers; bounds statically
    # asserted at module level), shrinking the gather from 3×i32 to
    # 2×u32 — a third of
    # the merge's random-access traffic (r4 profile: 121 ms on CPU,
    # 36 ms on TPU, at the 100k shape)
    u32 = jnp.uint32
    tbl = jnp.stack(
        [_pack_tables(pid, pkey), (psince + 1).astype(u32)], axis=-1
    )  # [N, M, 2] u32
    cur = tbl[e_dst, bucket]  # [E, 2]
    cur_id, cur_key = _unpack_word(cur[:, 0])
    cur_since = cur[:, 1].astype(jnp.int32) - 1

    # 1. matching id → belief precedence merge
    match = e_ok & (cur_id == e_id)
    pkey = pkey.at[e_dst, bucket].max(jnp.where(match, e_key, -1))

    # 2. empty or aged-DOWN bucket + incoming ALIVE claim of another id →
    # replace.  Pack (key, id) so one scatter-max picks the strongest.
    aged_down = (
        (cur_key % 4 == DOWN)
        & ((cur_since < 0) | (t - cur_since >= cfg.down_gc_rounds))
    )
    repl_ok = (
        e_ok
        & ~match
        & (e_key % 4 == ALIVE)
        & ((cur_id < 0) | aged_down)
    )
    packed = jnp.where(repl_ok, e_key * ID_CAP + e_id, -1)
    winner = jnp.full((n, m), -1, jnp.int32).at[e_dst, bucket].max(packed)
    # re-check on the post-merge table: a simultaneous matching-id merge
    # may have revived the bucket — replacement only claims buckets that
    # are STILL empty or DOWN
    still_free = (pid < 0) | (pkey % 4 == DOWN)
    do_repl = (winner >= 0) & still_free
    pid = jnp.where(do_repl, winner % ID_CAP, pid)
    pkey = jnp.where(do_repl, winner // ID_CAP, pkey)
    psince = jnp.where(do_repl, -1, psince)

    # stamp state transitions: newly SUSPECT/DOWN records t (suspicion
    # timeout + down GC age); healed-to-ALIVE clears the stamp
    changed = pkey != old_pkey
    st = pkey % 4
    psince = jnp.where(changed & (st != ALIVE), t, psince)
    psince = jnp.where(changed & (st == ALIVE), -1, psince)
    return pid, pkey, psince


def pswim_step(
    state: SimState, cfg: SimConfig, topo: Topology, key: jax.Array,
    faults=None,
) -> SimState:
    """``faults`` (sim/faults.py RoundFaults, or None) threads the
    FaultPlan seam through every probe/relay/gossip/announce message via
    `_reachable` — directed cuts and extra per-link loss apply to the
    partial-view tier exactly as to the full-view one (the ROADMAP gap
    where probes sailed through partitions is closed).  Fault keys are
    fold_in-derived inside `_reachable`'s ``faults is not None`` branch,
    so the None path stays byte-identical to the pre-fault kernel."""
    n, m = state.pid.shape
    k = cfg.gossip_entries
    (
        k_probe, k_ploss, k_relay, k_rloss,
        k_gossip, k_pick, k_gloss, k_ann, k_aloss, k_rot, k_rid,
    ) = jax.random.split(key, 11)
    me = jnp.arange(n, dtype=jnp.int32)
    up = state.alive == ALIVE
    pid, pkey, psince = state.pid, state.pkey, state.psince

    # -- 1. probe ---------------------------------------------------------
    target = psample_member_targets(state, cfg, k_probe, 1)[:, 0]
    do_probe = up & (state.t % cfg.probe_period_rounds == 0) & (target >= 0)
    target = jnp.maximum(target, 0)
    direct = _reachable(state, topo, k_ploss, me, target, faults)
    relays = psample_member_targets(state, cfg, k_relay, cfg.indirect_probes)
    relay_ok = relays >= 0
    relays = jnp.maximum(relays, 0)
    hop_keys = jax.random.split(k_rloss, 2)
    leg1 = _reachable(
        state, topo, hop_keys[0],
        jnp.repeat(me, cfg.indirect_probes), relays.reshape(-1), faults,
    ).reshape(n, cfg.indirect_probes)
    leg2 = _reachable(
        state, topo, hop_keys[1],
        relays.reshape(-1), jnp.repeat(target, cfg.indirect_probes), faults,
    ).reshape(n, cfg.indirect_probes)
    acked = direct | (leg1 & leg2 & relay_ok).any(axis=1)
    probe_failed = do_probe & ~acked

    t_bucket = target % m
    cur = pkey[me, t_bucket]
    newly_suspect = (
        probe_failed & (pid[me, t_bucket] == target) & (cur % 4 == ALIVE)
    )
    pkey = pkey.at[me, t_bucket].set(
        jnp.where(newly_suspect, cur - ALIVE + SUSPECT, cur)
    )
    psince = psince.at[me, t_bucket].set(
        jnp.where(newly_suspect, state.t, psince[me, t_bucket])
    )

    # -- 2. suspicion timeout --------------------------------------------
    expired = (
        (pkey >= 0)
        & (pkey % 4 == SUSPECT)
        & (psince >= 0)
        & (state.t - psince >= cfg.suspect_timeout_rounds)
    )
    pkey = jnp.where(expired, pkey - SUSPECT + DOWN, pkey)
    psince = jnp.where(expired, state.t, psince)  # down-since (GC age)

    # -- 3. gossip + announce entries ------------------------------------
    # each up node pushes k sampled table rows + its own claim to fanout
    # believed-alive targets; plus (on its stagger tick) its own claim to
    # one uniformly random node (the announce/rejoin path)
    f = cfg.fanout
    g_targets = psample_member_targets(state, cfg, k_gossip, f)  # [N, F]
    gsrc = jnp.repeat(me, f)
    gdst = g_targets.reshape(-1)
    g_valid = gdst >= 0
    gdst = jnp.maximum(gdst, 0)
    g_ok = _reachable(state, topo, k_gloss, gsrc, gdst, faults) & g_valid
    # post-probe packed table: one u32 gather per random (pid, pkey)
    # read below (sender filter, gossip picks, announce feedback)
    ptbl = _pack_tables(pid, pkey)
    # receiver-side down filter: the receiver's bucket for the SENDER
    snd_bucket = gsrc % m
    snd_id, snd_key = _unpack_word(ptbl[gdst, snd_bucket])
    snd_down = (snd_id == gsrc) & (snd_key % 4 == DOWN)
    g_ok &= ~snd_down

    # each node picks ONE entry set per tick and piggybacks it to every
    # fanout target (the reference buffers updates and sends the same
    # frame to its chosen member set per flush tick)
    picks = jax.random.randint(k_pick, (n, k), 0, m, jnp.int32)
    sel_id, sel_key = _unpack_word(
        jnp.take_along_axis(ptbl, picks, axis=1)
    )  # [N, k]
    self_claim = (
        jnp.minimum(state.incarnation.astype(jnp.int32), INC_CLAMP) * 4 + ALIVE
    )
    # append the sender's own claim as entry k
    ent_id = jnp.concatenate([sel_id, me[:, None]], axis=1)  # [N, k+1]
    ent_key = jnp.concatenate([sel_key, self_claim[:, None]], axis=1)
    # regular-index expansion as broadcasts, not gathers: gsrc repeats
    # each row f times and every entry repeats per target — a random
    # gather for these cost ~1/3 of the 100k-node step (r4 profile)
    e_dst = jnp.broadcast_to(gdst.reshape(n, f, 1), (n, f, k + 1)).reshape(-1)
    e_id = jnp.broadcast_to(
        ent_id[:, None, :], (n, f, k + 1)
    ).reshape(-1)
    e_key = jnp.broadcast_to(
        ent_key[:, None, :], (n, f, k + 1)
    ).reshape(-1)
    e_ok = (
        jnp.broadcast_to(
            g_ok.reshape(n, f, 1), (n, f, k + 1)
        ).reshape(-1)
        & (e_id >= 0)
        & (e_key >= 0)
    )
    # an entry about the RECEIVER is a refutation trigger, not a table
    # merge: SWIM nodes learn of their own suspicion from piggybacked
    # gossip and bump their incarnation (the full-view view[me,me] path)
    self_hit = e_ok & (e_id == e_dst) & (e_key % 4 != ALIVE)
    # ONE fused scatter-max for (heard?, incarnation): max over e_key
    # and max over e_key // 4 agree (the state bits only tie-break
    # within an incarnation), and each [N]-target random scatter cost
    # ~40 ms at the 100k shape (r4 profile)
    heard = jnp.full((n,), -1, jnp.int32).at[e_dst].max(
        jnp.where(self_hit, e_key, -1)
    )
    heard_suspect = heard >= 0
    heard_inc = jnp.where(heard_suspect, heard // 4, -1)
    # nodes never adopt beliefs about themselves via the table
    e_ok &= e_id != e_dst

    # announce entries (bypass the member list and the down filter)
    stagger = (state.t + me) % cfg.announce_interval_rounds == 0
    ann_target = jax.random.randint(k_ann, (n,), 0, n, jnp.int32)
    ann_ok = (
        stagger & up & (ann_target != me)
        & _reachable(state, topo, k_aloss, me, ann_target, faults)
    )
    all_dst = jnp.concatenate([e_dst, ann_target])
    all_id = jnp.concatenate([e_id, me])
    all_ok = jnp.concatenate([e_ok, ann_ok])

    # feedback: an announcer whose target believes it DOWN learns the
    # believed incarnation and refutes WITHIN the exchange — SWIM handles
    # suspicion→refutation in the message round-trip, so the announce
    # entry carries the already-bumped claim (Actor::renew + rejoin)
    my_bucket = me % m
    tgt_id, tgt_key = _unpack_word(ptbl[ann_target, my_bucket])
    # feedback on any non-ALIVE belief (SUSPECT refutes too, like the
    # full-view path — code-review r2 finding)
    ann_fb = ann_ok & (tgt_id == me) & (tgt_key % 4 != ALIVE)
    fb_inc = jnp.where(ann_fb, tgt_key // 4, -1)
    refuted_claim = (
        jnp.minimum(jnp.maximum(self_claim // 4, fb_inc) + 1, INC_CLAMP) * 4
        + ALIVE
    )
    all_key = jnp.concatenate(
        [e_key, jnp.where(ann_fb, refuted_claim, self_claim)]
    )

    pid, pkey, psince = _merge_entries(
        pid, pkey, psince, all_dst, all_id, all_key, all_ok, state.t, cfg
    )

    # -- 3c. bucket refill (down-GC reclamation + bootstrap discovery) ---
    # on its announce tick each node also re-samples ONE random bucket IF
    # that bucket is empty or holds an aged DOWN entry: the slot refills
    # with a random matching-residue id as an unverified ALIVE belief
    # (bootstrap DNS re-resolution, agent/bootstrap.rs:14-150); probing
    # re-detects it if it is actually dead
    rb = jax.random.randint(k_rot, (n,), 0, m, jnp.int32)
    cur_rb_key = pkey[me, rb]
    cur_rb_since = psince[me, rb]
    rb_aged_down = (cur_rb_key % 4 == DOWN) & (
        (cur_rb_since < 0) | (state.t - cur_rb_since >= cfg.down_gc_rounds)
    )
    per = (n + m - 1) // m
    rid = rb + m * jax.random.randint(k_rid, (n,), 0, per, jnp.int32)
    refill = (
        stagger & up & ((pid[me, rb] < 0) | rb_aged_down)
        & (rid < n) & (rid != me)
    )
    pid = pid.at[me, rb].set(jnp.where(refill, rid, pid[me, rb]))
    pkey = pkey.at[me, rb].set(
        jnp.where(refill, jnp.int32(ALIVE), pkey[me, rb])
    )
    psince = psince.at[me, rb].set(
        jnp.where(refill, -1, psince[me, rb])
    )

    # -- 4. refute --------------------------------------------------------
    refuting = (ann_fb | heard_suspect) & up
    bumped = jnp.minimum(
        jnp.maximum(
            jnp.maximum(state.incarnation.astype(jnp.int32), fb_inc),
            heard_inc,
        )
        + 1,
        INC_CLAMP,
    ).astype(jnp.uint32)
    incarnation = jnp.where(refuting, bumped, state.incarnation)

    return state._replace(
        pid=pid, pkey=pkey, psince=psince, incarnation=incarnation
    )
