"""Sim-round invariant catalog — the device-state twin of the host's
`corrosion_tpu.invariants` (SURVEY §4.5: the reference bakes Antithesis
`assert_always` properties into production code; the sim's analog is a
set of always-properties over the state tensors, evaluated between
rounds by tests and debug runs).

Checked properties:

- **no-phantom-data** — ``have ⊆ injected``: no node holds a chunk that
  never entered the system (inject_step is the only creation point).
- **bookkeeping-heads** — ``state.heads`` equals the max touched version
  per (node, actor) derived from ``have`` (round_step's refresh
  contract; `BookedVersions.last()`).
- **bookkeeping-gaps** — the gap interval tensors cover EXACTLY the
  missing-run decomposition of touched versions below the head when runs
  fit in K slots, and a superset (never a subset) under K-overflow
  clamping — under-coverage would silently starve sync needs.
- **relay-budget** — ``relay_left ≤ max_transmissions``.
- **dead-nodes-inert** — nodes down since round 0 hold nothing (their
  edges are masked at delivery).
"""

from __future__ import annotations

import numpy as np

from .gaps import gaps_to_mask
from .state import ALIVE, SimConfig, SimState, touched_versions, version_heads


def check_state(
    state: SimState,
    cfg: SimConfig,
    dead_since_start: np.ndarray | None = None,
) -> None:
    """Assert the always-properties on a (host-fetched) state snapshot.
    Raises AssertionError with the violated property's name."""
    have = np.asarray(state.have)
    injected = np.asarray(state.injected)
    assert (have <= injected[None, :]).all(), (
        "no-phantom-data: a node holds a never-injected chunk"
    )

    touched = np.asarray(touched_versions(state.have, cfg))
    heads = np.asarray(state.heads)
    expect_heads = np.asarray(version_heads(touched))
    assert (heads == expect_heads).all(), (
        "bookkeeping-heads: state.heads diverged from chunk truth"
    )

    v = cfg.n_versions
    v_idx = np.arange(1, v + 1)
    missing = (~touched) & (v_idx[None, None, :] <= heads[:, :, None])
    covered = np.asarray(gaps_to_mask(state.gap_lo, state.gap_hi, v))
    # never under-cover (would starve sync); exact when runs fit in K
    assert (covered >= missing).all(), (
        "bookkeeping-gaps: gap tensors under-cover the missing runs"
    )
    n_runs = (missing & ~np.pad(missing[:, :, :-1], ((0, 0), (0, 0), (1, 0)))).sum(
        axis=2
    )
    fits = n_runs <= cfg.gap_slots
    assert (covered[fits] == missing[fits]).all(), (
        "bookkeeping-gaps: inexact coverage without K-overflow"
    )
    # gaps never extend past the head
    assert not (covered & (v_idx[None, None, :] > heads[:, :, None])).any(), (
        "bookkeeping-gaps: gap covers a version above the head"
    )

    relay = np.asarray(state.relay_left)
    assert (relay <= cfg.max_transmissions).all(), "relay-budget exceeded"

    if dead_since_start is not None:
        dead = np.asarray(dead_since_start, bool)
        assert (have[dead] == 0).all(), (
            "dead-nodes-inert: a node down since round 0 holds data"
        )
