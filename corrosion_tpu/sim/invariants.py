"""Sim-round invariant catalog — the device-state twin of the host's
`corrosion_tpu.invariants` (SURVEY §4.5: the reference bakes Antithesis
`assert_always` properties into production code; the sim's analog is a
set of always-properties over the state tensors, evaluated between
rounds by tests and debug runs).

Checked properties:

- **no-phantom-data** — ``have ⊆ injected``: no node holds a chunk that
  never entered the system (inject_step is the only creation point).
- **bookkeeping-heads** — ``state.heads`` equals the max touched version
  per (node, actor) derived from ``have`` (round_step's refresh
  contract; `BookedVersions.last()`).
- **bookkeeping-gaps** — the gap interval tensors cover EXACTLY the
  missing-run decomposition of touched versions below the head when runs
  fit in K slots, and a superset (never a subset) under K-overflow
  clamping — under-coverage would silently starve sync needs.
- **relay-budget** — ``relay_left ≤ max_transmissions``.
- **dead-nodes-inert** — nodes down since round 0 hold nothing (their
  edges are masked at delivery).
- **delivery-order agreement** (ISSUE 11; ordering variants only) —
  under a FIFO broadcast-ordering discipline every node's touched
  versions per origin form a gapless prefix: no node holds version v
  without having completely delivered v-1 from the same origin first,
  so all nodes agree on each writer's delivery order.  Unlike the
  host-snapshot checks above, this one ALSO runs ON DEVICE inside the
  jitted round loops (`order_violation_count`, accumulated into
  `RunMetrics.order_violations` with zero host syncs — corrolint CT002
  clean): an enforced ``ordering="fifo"`` run must end at 0, and the
  ``fifo-unchecked`` negative control must trip it (pinned by
  tests/sim/test_proto.py).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .gaps import gaps_to_mask
from .state import (
    ALIVE,
    PayloadMeta,
    SimConfig,
    SimState,
    complete_versions,
    touched_versions,
    version_heads,
)


def order_violation_count(
    touched: jnp.ndarray,
    comp: jnp.ndarray,
    meta: PayloadMeta,
    cfg: SimConfig,
) -> jnp.ndarray:
    """i32 scalar, ON DEVICE: (node, origin, version) triples violating
    the FIFO delivery-order agreement this round — version v touched
    while v-1 from the same origin is not completely held.  Origin rows
    are exempt: a writer's own injections are ordered by construction,
    and a crash-WIPED origin legitimately re-injects past its lost
    history (the gate never applies to local commits), so counting it
    would page on every wipe-composed fault plan.

    Pure version-grid algebra over tensors the round kernels already
    materialize (``touched``/``comp`` are [N, A, V] grids) — no RNG, no
    host syncs; called inside the jitted loops only when
    ``cfg.ordering != "none"`` (a trace-time branch, so the default
    protocol compiles without it).  Counted in the GRID domain so a
    multi-chunk version is one triple, not chunks_per_version of them
    (the payload-domain sum would inflate by C)."""
    from ..proto.ordering import prev_complete

    viol = touched & ~prev_complete(comp)  # [N, A, V]
    n = touched.shape[0]
    # per-actor origin node: actor a's first payload (v=0, c=0) sits at
    # index a*C in the version-major layout (uniform_payloads)
    a_idx = jnp.arange(cfg.n_writers, dtype=jnp.int32)
    origin = meta.actor[a_idx * cfg.chunks_per_version]  # [A]
    not_origin = (
        jnp.arange(n, dtype=jnp.int32)[:, None] != origin[None, :]
    )  # [N, A]
    return jnp.sum(viol & not_origin[:, :, None], dtype=jnp.int32)


def check_state(
    state: SimState,
    cfg: SimConfig,
    dead_since_start: np.ndarray | None = None,
    meta: PayloadMeta | None = None,
) -> None:
    """Assert the always-properties on a (host-fetched) state snapshot.
    Raises AssertionError with the violated property's name.
    ``meta`` (optional) additionally arms the delivery-order check on
    enforced-ordering configs — the host-snapshot twin of the on-device
    `order_violation_count`."""
    have = np.asarray(state.have)
    injected = np.asarray(state.injected)
    assert (have <= injected[None, :]).all(), (
        "no-phantom-data: a node holds a never-injected chunk"
    )

    touched = np.asarray(touched_versions(state.have, cfg))
    heads = np.asarray(state.heads)
    expect_heads = np.asarray(version_heads(touched))
    assert (heads == expect_heads).all(), (
        "bookkeeping-heads: state.heads diverged from chunk truth"
    )

    v = cfg.n_versions
    v_idx = np.arange(1, v + 1)
    missing = (~touched) & (v_idx[None, None, :] <= heads[:, :, None])
    covered = np.asarray(gaps_to_mask(state.gap_lo, state.gap_hi, v))
    # never under-cover (would starve sync); exact when runs fit in K
    assert (covered >= missing).all(), (
        "bookkeeping-gaps: gap tensors under-cover the missing runs"
    )
    n_runs = (missing & ~np.pad(missing[:, :, :-1], ((0, 0), (0, 0), (1, 0)))).sum(
        axis=2
    )
    fits = n_runs <= cfg.gap_slots
    assert (covered[fits] == missing[fits]).all(), (
        "bookkeeping-gaps: inexact coverage without K-overflow"
    )
    # gaps never extend past the head
    assert not (covered & (v_idx[None, None, :] > heads[:, :, None])).any(), (
        "bookkeeping-gaps: gap covers a version above the head"
    )

    relay = np.asarray(state.relay_left)
    assert (relay <= cfg.max_transmissions).all(), "relay-budget exceeded"

    if dead_since_start is not None:
        dead = np.asarray(dead_since_start, bool)
        assert (have[dead] == 0).all(), (
            "dead-nodes-inert: a node down since round 0 holds data"
        )

    if meta is not None and cfg.ordering == "fifo":
        viol = int(
            np.asarray(order_violation_count(
                touched_versions(state.have, cfg),
                complete_versions(state.have, cfg),
                meta, cfg,
            ))
        )
        assert viol == 0, (
            f"delivery-order: {viol} (node, origin, version) triples "
            "hold a version whose predecessor was never delivered"
        )
