"""SWIM membership kernel (L5).

Vectorized rebuild of the Foca-driven `runtime_loop` (broadcast/mod.rs:
122-386) in full-view mode: per-node belief matrices instead of per-node
state machines.

- ``view[i, j]``: what i believes about j (ALIVE/SUSPECT/DOWN),
- ``vinc[i, j]``: the incarnation that belief refers to,
- ``suspect_since[i, j]``: round when i started suspecting j.

Round phases (each a masked tensor update):
1. **Probe** — every up node probes one sampled target; an unreachable
   target (down, partitioned, or lossy) falls back to ``indirect_probes``
   sampled relays; if none reach it either, the prober marks SUSPECT.
2. **Suspicion timeout** — SUSPECT older than ``suspect_timeout_rounds``
   becomes DOWN (foca's WAN-tuned suspicion window).
3. **Gossip merge** — sampled edges push belief rows; the receiver keeps,
   per column, whichever belief has the higher incarnation, or at equal
   incarnation the worse state (DOWN > SUSPECT > ALIVE) — SWIM's refutation
   ordering.
4. **Refute** — a live node that sees itself suspected bumps its own
   incarnation and re-asserts ALIVE (Actor::renew's auto-rejoin analog,
   actor.rs:199-209).

Full-view SWIM is O(N²) state — right for the 64-4096-node membership-churn
configs; the 100k dissemination configs run ground-truth membership.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import ALIVE, DOWN, SUSPECT, SimConfig, SimState
from .topology import Topology


def _reachable(
    state: SimState, topo: Topology, key: jax.Array, src: jnp.ndarray, dst: jnp.ndarray
) -> jnp.ndarray:
    """Ground-truth reachability of a probe message src→dst."""
    ok = (
        (state.group[src] == state.group[dst])
        & (state.alive[src] == ALIVE)
        & (state.alive[dst] == ALIVE)
    )
    if topo.loss > 0:
        ok &= ~jax.random.bernoulli(key, topo.loss, src.shape)
    return ok


def swim_step(
    state: SimState, cfg: SimConfig, topo: Topology, key: jax.Array
) -> SimState:
    if not cfg.swim_full_view:
        return state
    n = state.alive.shape[0]
    k_probe, k_ploss, k_relay, k_rloss, k_gossip, k_gloss = jax.random.split(key, 6)
    me = jnp.arange(n, dtype=jnp.int32)
    up = state.alive == ALIVE

    view, vinc, since = state.view, state.vinc, state.suspect_since

    # -- 1. probe ---------------------------------------------------------
    do_probe = up & (state.t % cfg.probe_period_rounds == 0)
    target = jax.random.randint(k_probe, (n,), 0, n, jnp.int32)
    direct = _reachable(state, topo, k_ploss, me, target)
    # indirect probes through sampled relays (handlers: ping-req path)
    relays = jax.random.randint(k_relay, (n, cfg.indirect_probes), 0, n, jnp.int32)
    hop_keys = jax.random.split(k_rloss, 2)
    leg1 = _reachable(
        state, topo, hop_keys[0],
        jnp.repeat(me, cfg.indirect_probes), relays.reshape(-1),
    ).reshape(n, cfg.indirect_probes)
    leg2 = _reachable(
        state, topo, hop_keys[1],
        relays.reshape(-1), jnp.repeat(target, cfg.indirect_probes),
    ).reshape(n, cfg.indirect_probes)
    indirect = (leg1 & leg2).any(axis=1)
    acked = direct | indirect
    probe_failed = do_probe & ~acked & (target != me)

    # mark suspect (only if we currently think it alive at that incarnation)
    cur = view[me, target]
    newly_suspect = probe_failed & (cur == ALIVE)
    view = view.at[me, target].set(
        jnp.where(newly_suspect, jnp.int8(SUSPECT), cur)
    )
    since = since.at[me, target].set(
        jnp.where(newly_suspect, state.t, since[me, target])
    )

    # -- 2. suspicion timeout --------------------------------------------
    expired = (view == SUSPECT) & (since >= 0) & (
        state.t - since >= cfg.suspect_timeout_rounds
    )
    view = jnp.where(expired, jnp.int8(DOWN), view)

    # -- 3. gossip merge --------------------------------------------------
    # Parallel scatter-max over sampled edges.  Beliefs are encoded as a
    # single key inc*4 + state so that max() implements SWIM precedence:
    # higher incarnation wins; at equal incarnation the worse state wins
    # (DOWN=2 > SUSPECT=1 > ALIVE=0).
    g_targets = jax.random.randint(k_gossip, (n, cfg.fanout), 0, n, jnp.int32)
    gsrc = jnp.repeat(me, cfg.fanout)
    gdst = g_targets.reshape(-1)
    g_ok = _reachable(state, topo, k_gloss, gsrc, gdst)

    belief_key = vinc.astype(jnp.int32) * 4 + view.astype(jnp.int32)  # [N, N]
    contrib = jnp.where(g_ok[:, None], belief_key[gsrc], jnp.int32(-1))  # [E, N]
    merged = belief_key.at[gdst].max(contrib)
    changed = merged > belief_key
    new_view = (merged % 4).astype(jnp.int8)
    view = jnp.where(changed, new_view, view)
    vinc = jnp.where(changed, (merged // 4).astype(jnp.int32), vinc)
    since = jnp.where(changed & (new_view == SUSPECT), state.t, since)

    # -- 4. refute --------------------------------------------------------
    self_belief = view[me, me]
    refuting = up & (self_belief != ALIVE)
    incarnation = state.incarnation + refuting.astype(jnp.uint32)
    new_inc = incarnation.astype(jnp.int32)
    view = view.at[me, me].set(
        jnp.where(refuting, jnp.int8(ALIVE), self_belief)
    )
    vinc = vinc.at[me, me].set(jnp.where(refuting, new_inc, vinc[me, me]))

    return state._replace(
        view=view, vinc=vinc, suspect_since=since, incarnation=incarnation
    )
