"""SWIM membership kernel (L5).

Vectorized rebuild of the Foca-driven `runtime_loop` (broadcast/mod.rs:
122-386) in full-view mode: per-node belief matrices instead of per-node
state machines.

- ``view[i, j]``: what i believes about j (ALIVE/SUSPECT/DOWN),
- ``vinc[i, j]``: the incarnation that belief refers to,
- ``suspect_since[i, j]``: round when i started suspecting j.

Round phases (each a masked tensor update):
1. **Probe** — every up node probes one sampled target; an unreachable
   target (down, partitioned, or lossy) falls back to ``indirect_probes``
   sampled relays; if none reach it either, the prober marks SUSPECT.
2. **Suspicion timeout** — SUSPECT older than ``suspect_timeout_rounds``
   becomes DOWN (foca's WAN-tuned suspicion window).
3. **Gossip merge** — sampled edges push belief rows; the receiver keeps,
   per column, whichever belief has the higher incarnation, or at equal
   incarnation the worse state (DOWN > SUSPECT > ALIVE) — SWIM's refutation
   ordering.
4. **Refute** — a live node that sees itself suspected bumps its own
   incarnation and re-asserts ALIVE (Actor::renew's auto-rejoin analog,
   actor.rs:199-209).

Full-view SWIM is O(N²) state — right for the 64-4096-node membership-churn
configs; the 100k dissemination configs run ground-truth membership.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .profile import phase_scope
from .state import ALIVE, DOWN, SUSPECT, SimConfig, SimState
from .topology import Topology


def _compact_targets(
    cand: jnp.ndarray, valid: jnp.ndarray, count: int
) -> jnp.ndarray:
    """Prefix-compact the valid candidates of each node into the first
    ``count`` slots (-1 pads); inputs are TRANSPOSED [over, N] (r5: N in
    the minor axis keeps the VPU's 128 lanes full — over is 4-12, so
    the [N, over] layout ran every elementwise sampler op at <10% lane
    utilization; fused 4-call block at 100k: 163 ms → 105 ms even on
    CPU).  Masked reduce over the small oversample axis instead of a
    scatter: the pre-r4 ``out.at[rows, slot].max`` cost ~40 ms PER CALL
    at 100k nodes on TPU, and the sampler runs four times per round.
    Returns [N, count]."""
    rank = jnp.cumsum(valid, axis=0)  # [over, N]
    sel = valid[:, None, :] & (
        rank[:, None, :]
        == jnp.arange(1, count + 1, dtype=rank.dtype)[None, :, None]
    )  # [over, count, N] — exactly one True per (slot, node) pair
    return jnp.max(jnp.where(sel, cand[:, None, :], -1), axis=0).T


def sample_member_targets(
    state: SimState, cfg: SimConfig, key: jax.Array, count: int
) -> jnp.ndarray:
    """i32[N, count] fan-out targets drawn from each node's *believed*
    member list; -1 marks unfilled slots.

    The reference picks broadcast/sync/probe targets from `Members.states`
    — a list that membership maintains and from which down members are
    removed (broadcast/mod.rs:653-680, handlers.rs:330-352) — so a false
    DOWN belief starves a live node of traffic until it rejoins.  Here:
    sample 4×count uniform candidates, drop self, duplicates (the
    reference's choose_multiple picks DISTINCT members), and (in coupled
    full-view mode) believed-DOWN nodes, then prefix-compact the
    survivors into the first slots.  Uncoupled or oracle-membership
    runs skip only the belief filter (ground-truth delivery masks still
    apply).

    The whole draw is scoped ``corro.sampler`` (profile.py): it runs
    nested inside the sync/swim phases, and innermost-wins attribution
    pulls the member draws out of them into the sampler ledger line —
    every variant (uniform, PeerSwap view, partial view) included.
    """
    with phase_scope("sampler"):
        if cfg.swim_partial_view and cfg.couple_membership:
            from .pswim import psample_member_targets

            return psample_member_targets(state, cfg, key, count)
        if cfg.peer_sampler == "peerswap":
            # the pluggable peer-selection seam (ISSUE 9): candidates
            # come from the node's PeerSwap view instead of a uniform
            # draw; the filters and compaction below are shared.  A
            # trace-time branch — the uniform default compiles the exact
            # legacy kernel.
            from ..topo.sampler import psample_view_targets

            return psample_view_targets(state, cfg, key, count)
        n = state.alive.shape[0]
        # 4× oversample: with fraction d of members believed DOWN,
        # expected filled slots ≈ 4·count·(1-d) — still ≥ count at
        # d=0.75, so coupled runs don't starve fanout beyond what the
        # reference's pick-from-list sampling would (it only falls short
        # when the live list itself is)
        over = 4 * count
        cand = jax.random.randint(key, (over, n), 0, n, jnp.int32)
        me = jnp.arange(n, dtype=jnp.int32)[None, :]
        valid = cand != me
        if cfg.swim_full_view and cfg.couple_membership:
            valid &= state.view[me, cand] != DOWN
        valid &= ~_dup_before(cand, valid)
        return _compact_targets(cand, valid, count)


def _dup_before(cand: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """bool[over, N]: candidate j repeats an EARLIER valid candidate
    (transposed layout — see _compact_targets).  The reference samples
    targets with `choose_multiple` — DISTINCT members — and the host
    tier uses rng.sample; drawing with replacement made the sim's
    effective fan-out ~25% smaller in tiny clusters (r4 calibration:
    3-node loss-0.7 recovery ran ~1.4× slow).  ``over`` is small and
    static, so the pairwise compare is cheap."""
    over = cand.shape[0]
    eq = cand[None, :, :] == cand[:, None, :]  # [j, i, N]
    earlier = jnp.tril(jnp.ones((over, over), bool), k=-1)  # i < j
    return (eq & earlier[:, :, None] & valid[None, :, :]).any(axis=1)


def _reachable(
    state: SimState,
    topo: Topology,
    key: jax.Array,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    faults=None,
) -> jnp.ndarray:
    """Ground-truth reachability of a probe message src→dst.  ``faults``
    (sim/faults.py RoundFaults) adds directed FaultPlan cuts and extra
    per-link loss; its key is fold_in-derived so the faults=None path
    consumes RNG byte-identically to the pre-fault kernel."""
    ok = (
        (state.group[src] == state.group[dst])
        & (state.alive[src] == ALIVE)
        & (state.alive[dst] == ALIVE)
    )
    from .topology import loss_tiered

    if loss_tiered(topo):
        # geo-tiered loss (ISSUE 9): the probe draw compares the same
        # aligned u8 stream against per-edge tier thresholds, so WAN
        # trunks eat probes at their own rate.  Flat topologies keep
        # the legacy bernoulli branch below, byte-identically.
        from .topology import regions, tiered_edge_drop

        n = state.alive.shape[0]
        region = regions(n, topo.n_regions)
        # the SAME three-step rule (clamped compare + certainty pin) as
        # the per-payload wire path — one implementation, no drift
        ok &= ~tiered_edge_drop(
            topo, jax.random.fold_in(key, 104), region, src, dst,
            src.shape,
        )
    elif topo.loss > 0:
        ok &= ~jax.random.bernoulli(key, topo.loss, src.shape)
    if faults is not None:
        from .faults import fault_edge_block, fault_edge_loss

        blk = fault_edge_block(faults, src, dst)
        if blk is not None:
            ok &= ~blk
        thr = fault_edge_loss(faults, src, dst)
        if thr is not None:
            from .topology import aligned_u8_bits

            # aligned draw (ISSUE 7): probe/announce edge sets are
            # [N]-shaped, which shards on non-word boundaries at
            # non-128-aligned N — the raw u8 draw silently diverges
            # from single-device there (see aligned_u8_bits)
            bits = aligned_u8_bits(
                jax.random.fold_in(
                    jax.random.fold_in(key, faults.seed), 103
                ),
                src.shape,
            )
            ok &= ~(bits < thr)
    return ok


def swim_step(
    state: SimState, cfg: SimConfig, topo: Topology, key: jax.Array,
    faults=None,
) -> SimState:
    if cfg.swim_partial_view:
        from .pswim import pswim_step

        return pswim_step(state, cfg, topo, key, faults)
    if not cfg.swim_full_view:
        return state
    n = state.alive.shape[0]
    (
        k_probe, k_ploss, k_relay, k_rloss, k_gossip, k_gloss, k_ann, k_aloss,
    ) = jax.random.split(key, 8)
    me = jnp.arange(n, dtype=jnp.int32)
    up = state.alive == ALIVE

    view, vinc, since = state.view, state.vinc, state.suspect_since

    # -- 1. probe ---------------------------------------------------------
    # probe targets come from the believed member list (foca probes active
    # members only; down members left the list)
    target = sample_member_targets(state, cfg, k_probe, 1)[:, 0]
    do_probe = up & (state.t % cfg.probe_period_rounds == 0) & (target >= 0)
    target = jnp.maximum(target, 0)
    direct = _reachable(state, topo, k_ploss, me, target, faults)
    # indirect probes through sampled believed-member relays (ping-req)
    relays = sample_member_targets(state, cfg, k_relay, cfg.indirect_probes)
    relay_ok = relays >= 0
    relays = jnp.maximum(relays, 0)
    hop_keys = jax.random.split(k_rloss, 2)
    leg1 = _reachable(
        state, topo, hop_keys[0],
        jnp.repeat(me, cfg.indirect_probes), relays.reshape(-1), faults,
    ).reshape(n, cfg.indirect_probes)
    leg2 = _reachable(
        state, topo, hop_keys[1],
        relays.reshape(-1), jnp.repeat(target, cfg.indirect_probes), faults,
    ).reshape(n, cfg.indirect_probes)
    indirect = (leg1 & leg2 & relay_ok).any(axis=1)
    acked = direct | indirect
    probe_failed = do_probe & ~acked & (target != me)

    # mark suspect (only if we currently think it alive at that incarnation)
    cur = view[me, target]
    newly_suspect = probe_failed & (cur == ALIVE)
    view = view.at[me, target].set(
        jnp.where(newly_suspect, jnp.int8(SUSPECT), cur)
    )
    since = since.at[me, target].set(
        jnp.where(newly_suspect, state.t, since[me, target])
    )

    # -- 2. suspicion timeout --------------------------------------------
    expired = (view == SUSPECT) & (since >= 0) & (
        state.t - since >= cfg.suspect_timeout_rounds
    )
    view = jnp.where(expired, jnp.int8(DOWN), view)

    # -- 3. gossip merge --------------------------------------------------
    # Parallel scatter-max over sampled edges.  Beliefs are encoded as a
    # single key inc*4 + state so that max() implements SWIM precedence:
    # higher incarnation wins; at equal incarnation the worse state wins
    # (DOWN=2 > SUSPECT=1 > ALIVE=0).  Targets come from the believed
    # member list, and receivers IGNORE pushes from senders they believe
    # DOWN (foca drops traffic from down members) — so a falsely-downed
    # node is fully starved until the announce path (3b) rehabilitates it,
    # exactly the reference's rejoin dynamics.
    g_targets = sample_member_targets(state, cfg, k_gossip, cfg.fanout)
    gsrc = jnp.repeat(me, cfg.fanout)
    gdst = g_targets.reshape(-1)
    g_valid = gdst >= 0
    gdst = jnp.maximum(gdst, 0)
    g_ok = _reachable(state, topo, k_gloss, gsrc, gdst, faults) & g_valid
    g_ok &= view[gdst, gsrc] != DOWN  # receiver-side down filter

    belief_key = vinc.astype(jnp.int32) * 4 + view.astype(jnp.int32)  # [N, N]
    contrib = jnp.where(g_ok[:, None], belief_key[gsrc], jnp.int32(-1))  # [E, N]
    merged = belief_key.at[gdst].max(contrib)

    # -- 3b. announce -----------------------------------------------------
    # every announce tick each up node pushes its OWN claim
    # (ALIVE @ own incarnation) to one uniformly random node, bypassing
    # its member list — the bootstrap re-announce (spawn_swim_announcer,
    # util.rs:104-123) that re-establishes contact after a partition has
    # driven both sides' views mutually DOWN.  The reply path carries the
    # receiver's belief back (feedback), so a refuted claim goes out one
    # announce tick later at a winning incarnation.
    stagger = (state.t + me) % cfg.announce_interval_rounds == 0
    ann_target = jax.random.randint(k_ann, (n,), 0, n, jnp.int32)
    ann_ok = (
        stagger
        & up
        & (ann_target != me)
        & _reachable(state, topo, k_aloss, me, ann_target, faults)
    )
    self_claim = state.incarnation.astype(jnp.int32) * 4 + ALIVE
    merged = merged.at[ann_target, me].max(
        jnp.where(ann_ok, self_claim, jnp.int32(-1))
    )
    ann_fb = ann_ok & (view[ann_target, me] == DOWN)
    heard_down = ann_fb
    fb_inc = jnp.where(ann_fb, vinc[ann_target, me], -1)

    changed = merged > belief_key
    new_view = (merged % 4).astype(jnp.int8)
    view = jnp.where(changed, new_view, view)
    vinc = jnp.where(changed, (merged // 4).astype(jnp.int32), vinc)
    since = jnp.where(changed & (new_view == SUSPECT), state.t, since)

    # -- 4. refute --------------------------------------------------------
    # a live node that sees itself suspected/downed (in its own row via
    # gossip, or via feedback) bumps its incarnation past every belief it
    # knows of and re-asserts ALIVE (Actor::renew, actor.rs:199-209)
    self_belief = view[me, me]
    refuting = up & ((self_belief != ALIVE) | heard_down)
    bumped = (
        jnp.maximum(
            jnp.maximum(state.incarnation.astype(jnp.int32), fb_inc),
            vinc[me, me],
        )
        + 1
    ).astype(jnp.uint32)
    incarnation = jnp.where(refuting, bumped, state.incarnation)
    new_inc = incarnation.astype(jnp.int32)
    view = view.at[me, me].set(
        jnp.where(refuting, jnp.int8(ALIVE), self_belief)
    )
    vinc = vinc.at[me, me].set(jnp.where(refuting, new_inc, vinc[me, me]))

    return state._replace(
        view=view, vinc=vinc, suspect_since=since, incarnation=incarnation
    )
