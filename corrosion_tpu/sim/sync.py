"""Anti-entropy sync kernel (L7).

Vectorized rebuild of `sync_loop`/`parallel_sync` (util.rs:347-393,
peer/mod.rs:1003-1403): each node counts down to its next sync round
(decorrelated 1-15 s backoff ≈ uniform re-arm over the interval); when due,
it samples ``sync_peers`` peers and pulls what they can serve:

    pulled = ~have[i] & have[peer] & active      (per payload)

— which is the active-window form of `compute_available_needs`
(sync.rs:127-249): the peer's fully-held set intersected with our needs.
Transfers respect a per-round sync byte budget with oldest-version-first
priority (the reference requests needs in version order and chunks at
8 KiB); leftovers are picked up next round.  Sync delivery takes one round
(the bi-stream RTT).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .state import PayloadMeta, SimConfig, SimState, budget_prefix_mask
from .topology import Topology, edge_alive, edge_drop


def sync_step(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    key: jax.Array,
) -> SimState:
    n, p = state.have.shape
    s = cfg.sync_peers
    k_peers, k_drop, k_rearm = jax.random.split(key, 3)

    due = state.sync_countdown <= 0  # [N]
    active = (state.injected > 0)[None, :]

    peers = jax.random.randint(k_peers, (n, s), 0, n, jnp.int32)  # [N, S]
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)  # [E] the puller
    dst = peers.reshape(-1)  # [E] the server

    ok = edge_alive(state.group, state.alive, src, dst)
    ok &= ~edge_drop(topo, k_drop, src.shape[0])
    ok &= due[src]
    ok &= dst != src

    # need computation per edge: what the server has that the puller lacks
    need = (state.have[dst] > 0) & (state.have[src] == 0) & active  # [E, P]
    need &= ok[:, None]

    # oldest-first budget: the payload axis is version-major BY
    # CONSTRUCTION (uniform_payloads), so index order is already global
    # (version, actor) request order — no per-round permutation needed
    # (the argsort + two [E, P] permuted gathers this replaces dominated
    # the whole round's cost)
    granted = budget_prefix_mask(need, cfg.sync_budget_bytes, cfg)

    # deliver next round via the delay ring (bi-stream round trip)
    d_slots = state.inflight.shape[0]
    slot = (state.t + 1) % d_slots
    flat_idx = slot * n + src  # pulls arrive at the puller
    inflight = state.inflight.reshape(d_slots * n, p)
    inflight = inflight.at[flat_idx].max(granted.astype(state.have.dtype))
    inflight = inflight.reshape(d_slots, n, p)

    # re-arm countdowns: due nodes pick a fresh uniform backoff
    rearm = jax.random.randint(
        k_rearm, (n,), 1, cfg.sync_interval_rounds + 1, jnp.int32
    )
    countdown = jnp.where(due, rearm, state.sync_countdown - 1)

    return state._replace(inflight=inflight, sync_countdown=countdown)
