"""Anti-entropy sync kernel (L7) — interval algebra on gap tensors.

Vectorized rebuild of `sync_loop`/`parallel_sync` (util.rs:347-393,
peer/mod.rs:1003-1403): each node counts down to its next sync round
(decorrelated 1-15 s backoff ≈ uniform re-arm over the interval); when due,
it samples ``sync_peers`` peers and computes needs the way
`compute_available_needs` (sync.rs:127-249, scalar spec:
`corrosion_tpu.core.sync`) does — from the advertised bookkeeping state
(``heads[N, A]`` + ``gap_lo/gap_hi[N, A, K]`` refreshed by round_step each
round), not from ground-truth chunk bits:

1. **full needs** — my gap ranges ∩ the peer's fully-held set, where the
   peer's fully-held set is [1..head_j] minus the peer's own gaps minus its
   partial versions (spec's `other_haves`);
2. **partial needs** — versions I hold some chunks of, served by peers
   that fully hold them or hold overlapping chunks (the chunk-level grant
   mask IS the seq-range overlap of sync.rs:176-227);
3. **head catch-up** — (my_head, peer_head] (sync.rs:229-246).

The actual transfer grants only chunks the server really holds and the
puller really lacks, so the K-clamped interval approximation can only slow
convergence, never corrupt state (see sim/gaps.py).  Transfers respect a
per-round sync byte budget with oldest-version-first priority (the
reference requests needs in version order and chunks at 8 KiB); leftovers
are picked up next round.  Sync delivery takes one round (the bi-stream
RTT).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .gaps import gaps_to_mask
from .profile import phase_scope
from .state import (
    PayloadMeta,
    SimConfig,
    SimState,
    budget_prefix_mask,
    complete_versions,
    grid_to_payload,
)
from .swim import sample_member_targets
from .topology import Topology, edge_alive


def node_sync_masks(state: SimState, cfg: SimConfig):
    """Per-node version masks [N, A, V] derived from the advertised
    bookkeeping tensors (the device form of `generate_sync`,
    sync.rs:284-333) plus chunk truth for completeness.

    Returns (miss_full, partial, haves):
    - miss_full — versions in my advertised gap ranges (never seen);
    - partial   — versions I touched but haven't completed;
    - haves     — versions I can serve whole: [1..head] − gaps − partials
      (spec's `other_haves`, sync.rs:150-160).
    """
    v = cfg.n_versions
    v_idx = jnp.arange(1, v + 1, dtype=jnp.int32)
    miss_full = gaps_to_mask(state.gap_lo, state.gap_hi, v)  # [N, A, V]
    below_head = v_idx[None, None, :] <= state.heads[:, :, None]
    comp = complete_versions(state.have, cfg)
    partial = below_head & ~miss_full & ~comp
    haves = below_head & ~miss_full & comp
    return miss_full, partial, haves


def edge_needs(
    state: SimState,
    cfg: SimConfig,
    src: jnp.ndarray,
    dst: jnp.ndarray,
    regular_fanout: Optional[int] = None,
) -> jnp.ndarray:
    """bool[E, P] — chunks ``dst`` (server) can supply to ``src`` (puller),
    per the three need classes of `compute_available_needs`
    (sync.rs:127-249) evaluated on the advertised interval state.  Shared
    by the sync kernel and the kernel-vs-scalar-spec property test.

    ``regular_fanout=s`` declares the kernel's regular edge layout
    (src = repeat(arange(n), s)): src-side tensors then ride broadcasts
    instead of random gathers — at the gapstress shape those gathers
    were 100M cells each.  Callers with irregular edge lists (the
    property test) omit it and get plain indexing."""
    miss_full, partial, haves = node_sync_masks(state, cfg)
    v_idx = jnp.arange(1, cfg.n_versions + 1, dtype=jnp.int32)[None, None, :]
    n = state.have.shape[0]
    e = src.shape[0]
    if regular_fanout is not None:
        s = regular_fanout
        assert e == n * s, "regular_fanout does not match the edge count"

        def at_src(x):  # [N, ...] -> [E, ...] via broadcast
            return jnp.broadcast_to(
                x[:, None], (n, s) + x.shape[1:]
            ).reshape((e,) + x.shape[1:])
    else:

        def at_src(x):
            return x[src]

    full_need = at_src(miss_full) & haves[dst]  # [E, A, V]
    partial_need = at_src(partial) & (haves[dst] | partial[dst])
    catchup = (v_idx > at_src(state.heads)[:, :, None]) & (
        v_idx <= state.heads[dst][:, :, None]
    )
    wanted = full_need | partial_need | catchup

    # chunk-level grant: only chunks the server holds and the puller lacks
    # (the seq-range overlap of partial needs, sync.rs:176-227, falls out
    # of the have-bit intersection)
    return (
        grid_to_payload(wanted, cfg)
        & (state.have[dst] > 0)
        & (at_src(state.have) == 0)
    )  # [E, P]


def sync_step(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    key: jax.Array,
    faults=None,
    telem: bool = False,
):
    """``telem=True`` (static, the RoundTrace seam) additionally returns
    a `telemetry.SyncTel` of this round's session/grant activity — pure
    reductions, no RNG, telem=False untouched."""
    n, p = state.have.shape
    s = cfg.sync_peers
    k_peers, k_drop, k_rearm = jax.random.split(key, 3)

    due = state.sync_countdown <= 0  # [N]
    if cfg.sync_cadence != "periodic":
        # sync-cadence variant (ISSUE 11): "eager" makes every node due
        # every round (the SWARM-style near-zero-round limit); the
        # countdown/backoff machinery below keeps running — and keeps
        # drawing its re-arm randomness — so both cadences consume the
        # identical RNG stream (proto/schedule.py)
        from ..proto.schedule import cadence_due

        due = cadence_due(due, cfg)

    # sync peers come from the believed member list (handle_sync chooses
    # candidates from Members.states, handlers.rs:808-863)
    peers = sample_member_targets(state, cfg, k_peers, s)  # [N, S]
    src = jnp.repeat(jnp.arange(n, dtype=jnp.int32), s)  # [E] the puller
    dst = peers.reshape(-1)  # [E] the server
    ok = dst >= 0
    dst = jnp.maximum(dst, 0)

    ok &= edge_alive(state.group, state.alive, src, dst)
    # no stochastic loss on sync edges: sync is a reliable bi-stream
    # session (QUIC bi / our TCP TAG_BI), which retransmits within the
    # round — packet loss only starves the fire-and-forget uni/datagram
    # paths (LinkModel marks bi streams reliable on the host tier too)
    ok &= due[src]
    ok &= dst != src
    refused_cnt = jnp.int32(0)
    if faults is not None:
        # a sync session is a BIDIRECTIONAL stream: an asymmetric cut in
        # either direction refuses the session here, while one-way
        # broadcast still flows in the hearing direction.  (The host
        # tier is slightly more permissive: a bi stream OPENED from the
        # unblocked side keeps flowing, like established TCP across a
        # young one-way partition — doc/faults.md "tier coverage" pins
        # the divergence; it only lets the host converge faster.)  Fault
        # loss doesn't bite here for the same reliable-bi reason as
        # topology loss above; fault LATENCY does — it slows the
        # session's RTT, applied below as extra ring slots on delivery
        # (jitter stays out: retransmission inside the reliable stream
        # smooths per-message jitter, only the fixed floor shifts RTT).
        # `fault_session_refused` is the ONE implementation shared with
        # the packed path, so the two can't drift.
        from .faults import fault_session_refused

        refused = fault_session_refused(faults, src, dst)
        if refused is not None:
            if telem:
                refused_cnt = jnp.sum(ok & refused, dtype=jnp.int32)
            ok &= ~refused

    # self-scoped "sync" (nested inside the round's scope — same phase,
    # so attribution is unchanged there) so direct microbench callers
    # (doc/experiments/round_phase_profile.py) attribute the hot
    # needs/grant pipeline too
    with phase_scope("sync"):
        need = (
            edge_needs(state, cfg, src, dst, regular_fanout=s)
            & ok[:, None]
        )  # [E, P]

        # oldest-first budget: the payload axis is version-major BY
        # CONSTRUCTION (uniform_payloads), so index order is already
        # global (version, actor) request order — no per-round
        # permutation needed
        granted = budget_prefix_mask(
            need, cfg.sync_budget_bytes, meta.nbytes
        )
    if telem:
        # pin ONE materialization (the packed twin does the same): the
        # telemetry grant counts below add a reduce consumer to
        # `granted`, and without a source-level barrier XLA can
        # duplicate the need/budget pipeline into it (measured
        # cost-neutral at small dense shapes, load-bearing at scale)
        granted = jax.lax.optimization_barrier(granted)

    # pulls land in the sync delay ring at slot t+1+fault_delay (the
    # bi-stream round trip, stretched by any FaultPlan latency) — a ring
    # separate from the broadcast one because sync-received changesets
    # carry no retransmission budget (see SimState.sync_inflight).
    d_slots = state.sync_inflight.shape[0]
    sdelay = None
    if faults is not None:
        # per-edge session latency: the slower direction bounds the
        # bi-stream RTT (compile_plan validated 1+delay < n_delay_slots,
        # so the target slot never collides with this round's pop);
        # shared implementation with the packed path
        from .faults import fault_session_delay

        sdelay = fault_session_delay(faults, src, dst)  # i32[E] | None
    if sdelay is None:
        # every edge delivers at t+1 (latency-free plans included): fold
        # the s edges per puller first (regular layout ⇒ reshape-reduce,
        # no scatter) and write the one slot.  deliver_step zeroed this
        # slot when it last popped, so max() is a plain fill.
        pulled = (
            granted.reshape(n, s, p).max(axis=1).astype(state.have.dtype)
        )  # [N, P]
        sync_inflight = state.sync_inflight.at[
            (state.t + 1) % d_slots
        ].max(pulled)
    else:
        slot = (state.t + 1 + sdelay) % d_slots
        flat_idx = slot * n + src  # deliveries land at the PULLER
        ring = state.sync_inflight.reshape(d_slots * n, p)
        ring = ring.at[flat_idx].max(granted.astype(state.have.dtype))
        sync_inflight = ring.reshape(d_slots, n, p)

    # fruitfulness-adaptive backoff (host _sync_loop: decorrelated
    # backoff, reset when a sync ingested changes): a due sync that
    # granted nothing DOUBLES the node's re-arm window up to the cap; a
    # fruitful one resets it to the base interval.  Ground-truth
    # calibration r4: without growth the sim recovered from partitions
    # several× faster than the host tier.
    fruitful = granted.reshape(n, s, p).any(axis=(1, 2))  # [N] puller got data
    cap = cfg.sync_backoff_cap()
    backoff = jnp.where(
        due & fruitful,
        jnp.int32(cfg.sync_interval_rounds),
        jnp.where(
            due,
            jnp.minimum(state.sync_backoff * 2, cap),
            state.sync_backoff,
        ),
    )
    # re-arm countdowns: due nodes draw uniform over their window
    rearm = jax.random.randint(k_rearm, (n,), 1, backoff + 1, jnp.int32)
    countdown = jnp.where(due, rearm, state.sync_countdown - 1)

    state = state._replace(
        sync_inflight=sync_inflight,
        sync_countdown=countdown,
        sync_backoff=backoff,
    )
    if not telem:
        return state
    # session telemetry: per-PAYLOAD grant counts are exact i32 (≤ E per
    # payload) from ONE pass over the grant bools, then the shared
    # `fused.grant_fold` — the identical [P]-shaped fold the packed
    # kernel performs on its word counts, so both paths' sync channels
    # agree bit-for-bit by construction
    from .fused import grant_fold
    from .telemetry import SyncTel

    # innermost scope wins: these reductions are TELEMETRY cost even
    # though they live in the sync kernel — the ledger's telemetry
    # fraction is what the ±5-point cross-check against
    # measure_overhead_pair's interleaved number gates on
    with phase_scope("telemetry"):
        counts = jnp.sum(granted, axis=0, dtype=jnp.int32)  # [P]
        frames, byte_tot = grant_fold(counts, meta.nbytes)
        tel = SyncTel(
            sessions=jnp.sum(ok, dtype=jnp.int32),
            refused=refused_cnt,
            frames=frames,
            bytes=byte_tot,
        )
    return state, tel
