"""Benchmark scenario runner: the five BASELINE.json configs.

1. 3-node single-writer ground truth (validated against the host agent
   cluster in tests/sim/test_ground_truth.py);
2. 64-node SWIM membership churn (no payload);
3. 1k-node changeset broadcast sweep;
4. 10k-node WAN partition + heal;
5. 100k-node write storm (multi-writer, chunked versions).

Each returns a metrics dict with rounds-to-convergence percentiles and
wall-clock; `ROUND_SECONDS` converts rounds to simulated time (one round =
the 500 ms broadcast flush tick, BASELINE.md)."""

from __future__ import annotations

import functools
import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .round import new_metrics, new_sim, round_step, run_to_convergence
from .state import (
    ALIVE,
    PayloadMeta,
    SimConfig,
    optimize_budgets,
    uniform_payloads,
)
from .topology import Topology, regions

ROUND_SECONDS = 0.5


def _percentile(arr: np.ndarray, q: float) -> float:
    valid = arr[arr >= 0]
    if valid.size == 0:
        return float("nan")
    return float(np.percentile(valid, q))


def run_scenario(
    cfg: SimConfig,
    meta: PayloadMeta,
    topo: Topology = Topology(),
    seed: int = 0,
    max_rounds: int = 2000,
    state_mutator=None,
    compile_only: bool = False,
    mesh=None,
    telemetry: bool = False,
    trace_path: Optional[str] = None,
    profile_dir: Optional[str] = None,
) -> Optional[Dict[str, float]]:
    """Run one scenario to convergence.  ``compile_only`` lowers and
    compiles the whole run without executing it (cheap warmup for
    benchmarks — priming the XLA cache costs compile time, not a full
    convergence run).

    ``profile_dir`` (ISSUE 16) wraps the measured run in a profiler
    capture: the compiled loop's op→phase map and memory budget are
    taken from the SAME executable the run dispatches (AOT lower +
    compile, a cache hit when the caller already primed), the capture is
    parsed into the deterministic ``phase_profile`` ledger, and the
    record gains ``phase_profile`` + ``memory_budget`` blocks.  Wall
    timing still brackets only the run itself; the capture adds trace
    writing around it, so profiled walls are informational, not
    baseline-grade.

    ``mesh`` (VERDICT r2 item 4): a `jax.sharding.Mesh` with a "nodes"
    axis — the SimState carry is placed node-axis-split before the jitted
    while_loop, so GSPMD partitions every round kernel across the mesh
    and the cross-shard scatters ride ICI collectives.  jit infers the
    shardings from the committed inputs; the carry keeps them across
    rounds.  Results are bit-identical to single-device (the math is
    unchanged — tests/sim/test_mesh_storm.py proves it).

    ``telemetry`` (ISSUE 5) threads the flight recorder through the run:
    the metrics dict gains a deterministic ``telemetry`` summary block
    and ``trace_path`` writes the per-round flight-recorder JSONL."""
    telemetry = telemetry or trace_path is not None
    state = new_sim(cfg, seed)
    if state_mutator is not None:
        state = state_mutator(state)
    if mesh is not None:
        from ..parallel.mesh import replicate_meta, shard_state

        state = shard_state(state, mesh)
        meta = replicate_meta(meta, mesh)

    if compile_only:
        run_to_convergence.lower(
            state, meta, cfg, topo, max_rounds, telemetry=telemetry,
            mesh=mesh,
        ).compile()
        return None

    profile_record = mem_record = None
    if profile_dir is not None:
        from . import profile as prof

        compiled = run_to_convergence.lower(
            state, meta, cfg, topo, max_rounds, telemetry=telemetry,
            mesh=mesh,
        ).compile()
        prof.write_phase_map(profile_dir, [compiled.as_text()])
        mem_record = prof.memory_budget(
            compiled,
            label=f"run_to_convergence n={cfg.n_nodes} "
            f"p={cfg.n_payloads} telemetry={telemetry}",
        )
        capture = prof.trace_capture(profile_dir)
        capture.__enter__()

    t0 = time.monotonic()
    try:
        out = run_to_convergence(
            state, meta, cfg, topo, max_rounds, telemetry=telemetry,
            mesh=mesh,
        )
        jax.block_until_ready(out)
    finally:
        if profile_dir is not None:
            capture.__exit__(None, None, None)
    final, metrics = out[0], out[1]
    trace = out[2] if telemetry else None
    if profile_dir is not None:
        profile_record = prof.parse_phase_profile(profile_dir)
    # block on the WHOLE output pytree, then force a host read: an async
    # ready-signal on one scalar is exactly the artifact that produced the
    # round-2 "1.6 ms" wall (VERDICT r2 weak #1; sim/perf.py)
    jax.block_until_ready(out)
    np.asarray(final.have[0, 0])
    wall = time.monotonic() - t0

    cov = np.asarray(metrics.coverage_at)
    inj = np.asarray(meta.round)
    lat = np.where(cov >= 0, cov - inj, -1)
    node_conv = np.asarray(metrics.converged_at)
    alive = np.asarray(final.alive)
    rounds = int(final.t)
    unconverged = int(((node_conv < 0) & (alive == ALIVE)).sum())
    from .packed import packed_supported

    from ..parallel.mesh import mesh_record, mesh_size

    result = {
        "n_nodes": cfg.n_nodes,
        "n_payloads": cfg.n_payloads,
        "n_devices": mesh_size(mesh),
        "mesh": mesh_record(mesh),
        # which round implementation run_to_convergence dispatched to
        # (VERDICT r3 item 2: the bench must say which path ran)
        "round_path": "packed" if packed_supported(cfg, topo) else "dense",
        "rounds": rounds,
        "wall_clock_s": wall,
        "converged": unconverged == 0,
        "unconverged_nodes": unconverged,
        "p50_payload_latency_rounds": _percentile(lat, 50),
        "p99_payload_latency_rounds": _percentile(lat, 99),
        "p99_payload_latency_sim_s": _percentile(lat, 99) * ROUND_SECONDS,
        "p99_node_convergence_round": _percentile(node_conv, 99),
        "gap_overflow_frac_max": float(metrics.overflow_frac),
        "rounds_per_sec": rounds / wall if wall > 0 else float("inf"),
        "node_rounds_per_sec": rounds * cfg.n_nodes / wall if wall > 0 else 0.0,
    }
    if trace is not None:
        from .telemetry import trace_host, trace_summary, write_flight_jsonl

        host = trace_host(trace, rounds)
        result["telemetry"] = trace_summary(host, rounds, cfg)
        if trace_path:
            write_flight_jsonl(
                trace_path, host, rounds, cfg,
                header={"seed": seed, "scenario": "run_scenario"},
            )
    if profile_record is not None:
        result["phase_profile"] = profile_record
    if mem_record is not None:
        result["memory_budget"] = mem_record
    return result


# -- the five configs -------------------------------------------------------


def config_ground_truth_3node(
    seed: int = 0, telemetry: bool = False, trace_path: Optional[str] = None
) -> Dict[str, float]:
    cfg = SimConfig(n_nodes=3, n_payloads=64, fanout=2, sync_interval_rounds=4)
    meta = uniform_payloads(cfg, inject_every=1)
    return run_scenario(
        cfg, meta, seed=seed, telemetry=telemetry, trace_path=trace_path
    )


def config_fault_campaign_3node(seed: int = 0) -> Dict[str, float]:
    """The FaultPlan demo campaign (doc/faults.md) on the sim tier: loss
    burst + asymmetric partition + delay/jitter + crash-with-wipe, all
    from ONE plan seed; the identical schedule replays against the
    in-process host cluster via `faults.HostFaultDriver`.

    Since ISSUE 3 this routes through the campaign engine
    (`corrosion_tpu.campaign`): a single-cell single-seed spec run as a
    (degenerate) vmapped ensemble — the same code path `sim campaign
    run` exercises at ≥8 seeds.  The emitted record keeps the legacy
    keys, still replay-identical across processes minus the wall."""
    from ..campaign.engine import run_campaign
    from ..campaign.spec import fault_campaign_3node_spec

    spec = fault_campaign_3node_spec(seed=seed)
    artifact = run_campaign(spec, out_path=None)
    cell = artifact["cells"][0]
    per_seed = cell["per_seed"]
    return {
        "n_nodes": cell["n_nodes"],
        # which round kernels ran (ISSUE 4: dense fallbacks are visible,
        # not silent — 3 nodes sit under the packed size gate, so this
        # demo campaign legitimately reports "dense"; "unknown" only for
        # cells resumed from a pre-round_path artifact)
        "round_path": cell.get("round_path", "unknown"),
        "plan_seed": seed,
        "plan_horizon": cell["plan_horizon"],
        "rounds": per_seed["rounds"][0],
        "wall_clock_s": cell["wall_clock_s"],
        "converged": per_seed["converged"][0],
        "unconverged_nodes": per_seed["unconverged_nodes"][0],
        "p99_node_convergence_round": per_seed[
            "p99_node_convergence_round"
        ][0],
        "spec_hash": artifact["spec_hash"],
        "result_digest": artifact["result_digest"],
    }


def _churn_record(artifact, n: int) -> Dict[str, float]:
    """Legacy-shaped record from a membership-churn campaign cell (the
    pre-ISSUE-5 config #2/#2b keys, so BENCH_CONFIGS.json lineage and
    existing tests read unchanged)."""
    cell = artifact["cells"][0]
    ps = cell["per_seed"]
    # the engine records None for a never-detected lane (band hygiene);
    # the legacy record keeps the old -1 sentinel
    dr = ps["detect_round"][0]
    dr = -1 if dr is None else int(dr)
    return {
        "n_nodes": n,
        "detect_round": dr,
        "detect_sim_s": dr * ROUND_SECONDS if dr >= 0 else -1,
        "detected_fraction": float(ps["detected_fraction"][0]),
        "wall_clock_s": cell["wall_clock_s"],
        "converged": bool(ps["converged"][0]),
        "spec_hash": artifact["spec_hash"],
        "result_digest": artifact["result_digest"],
    }


def config_swim_churn_64(
    seed: int = 0, max_rounds: int = 400, n: int = 64
) -> Dict[str, float]:
    """Config #2: membership only — kill a third of the cluster, measure
    rounds until every survivor marks every dead node DOWN.

    The detection predicate runs ON DEVICE inside one `lax.while_loop`
    (`telemetry.run_membership_detect`).  Since ISSUE 5 this routes
    through the campaign engine — a single-seed degenerate ensemble of
    the `swim-churn-64` spec, the same code path `sim campaign run`
    sweeps at ≥8 seeds to produce detect-round BANDS (the ROADMAP
    "runner configs #2/#2b don't flow through the engine yet" item).
    The emitted record keeps the legacy keys."""
    from ..campaign.engine import run_campaign
    from ..campaign.spec import swim_churn_64_spec

    spec = swim_churn_64_spec(seeds=(seed,), n=n, max_rounds=max_rounds)
    artifact = run_campaign(spec, out_path=None)
    rec = _churn_record(artifact, n)
    rec["false_positive_downs"] = int(
        artifact["cells"][0]["per_seed"]["false_positive_downs"][0]
    )
    return rec


def config_swim_churn_partial(
    seed: int = 0, max_rounds: int = 600, n: int = 4096
) -> Dict[str, float]:
    """Config #2 at the partial-view scale tier: kill a third of an
    n-node cluster running O(N·M) member tables (sim/pswim.py) and
    measure rounds until every LIVE table entry referencing a dead
    member is marked DOWN.  Engine-routed like `config_swim_churn_64`
    (the `swim-churn-partial` builtin spec); legacy record keys kept."""
    from ..campaign.engine import run_campaign
    from ..campaign.spec import swim_churn_partial_spec

    spec = swim_churn_partial_spec(
        seeds=(seed,), n=n, max_rounds=max_rounds
    )
    rec = _churn_record(run_campaign(spec, out_path=None), n)
    rec["member_slots"] = spec.sim_config({}).member_slots
    return rec


def _resolve_topo(topo_family: Optional[str]) -> Topology:
    """Named topology family → Topology (ISSUE 9; None = flat default)."""
    if not topo_family:
        return Topology()
    from ..topo import family_topology

    return Topology(**family_topology(topo_family))


def _resolve_proto(proto_family: Optional[str]) -> Dict[str, object]:
    """Named protocol family → SimConfig protocol kwargs (ISSUE 11;
    None = the baseline point, an empty overlay)."""
    if not proto_family:
        return {}
    from ..proto import family_proto

    return family_proto(proto_family)


def config_broadcast_1k(
    seed: int = 0,
    telemetry: bool = False,
    trace_path: Optional[str] = None,
    topo_family: Optional[str] = None,
    sampler: Optional[str] = None,
    proto_family: Optional[str] = None,
) -> Dict[str, float]:
    """Config #3, with the ISSUE 9/11 axes exposed: ``--topology`` picks
    a named family, ``--sampler`` the peer-selection seam, ``--proto``
    a named protocol variant."""
    topo = _resolve_topo(topo_family)
    cfg = SimConfig(
        n_nodes=1000, n_payloads=256, n_writers=8, fanout=3,
        n_delay_slots=max(4, topo.max_delay + 1),
        peer_sampler=sampler or "uniform",
        **_resolve_proto(proto_family),
    )
    meta = uniform_payloads(cfg, inject_every=2)
    # 256 × 8 KiB = 2 MiB ≤ both budgets ⇒ metering skipped (proof
    # derived from meta.nbytes in optimize_budgets)
    return run_scenario(
        optimize_budgets(cfg, meta), meta, topo=topo, seed=seed,
        telemetry=telemetry, trace_path=trace_path,
    )


def config_partition_heal_10k(seed: int = 0) -> Dict[str, float]:
    """Config #4: two halves partitioned for the first 60 rounds, writers on
    both sides, convergence measured after heal."""
    # real membership at scale: partial-view SWIM coupled to dissemination
    # (VERDICT r1 item 3 — no more ground-truth oracle in configs #4/#5)
    cfg = SimConfig.wan_tuned(
        10_000, n_payloads=256, n_writers=4, fanout=3,
        swim_partial_view=True, member_slots=32,
        # inter_delay 2 + sync t+1 fit in 3 ring slots (validate checks)
        n_delay_slots=3,
    )
    meta = uniform_payloads(cfg, inject_every=1)
    # 2 MiB total ≤ both budgets ⇒ metering skipped (optimize_budgets)
    cfg = optimize_budgets(cfg, meta)
    topo = Topology(n_regions=2, inter_delay=2)
    region = regions(cfg.n_nodes, topo.n_regions)

    state = new_sim(cfg, seed)
    group = (jnp.arange(cfg.n_nodes) >= cfg.n_nodes // 2).astype(jnp.int32)
    state = state._replace(group=group)
    metrics = new_metrics(cfg)

    @jax.jit
    def run_partitioned(state, metrics):
        def body(_, carry):
            return round_step(*carry, meta, cfg, topo, region)

        return jax.lax.fori_loop(0, 60, body, (state, metrics))

    t0 = time.monotonic()
    state, metrics = run_partitioned(state, metrics)
    state = state._replace(group=jnp.zeros((cfg.n_nodes,), jnp.int32))
    heal_round = int(state.t)
    final, metrics = run_to_convergence(state, meta, cfg, topo, 2000)
    jax.block_until_ready(final.t)
    wall = time.monotonic() - t0

    node_conv = np.asarray(metrics.converged_at)
    alive = np.asarray(final.alive)
    unconverged = int(((node_conv < 0) & (alive == ALIVE)).sum())
    return {
        "n_nodes": cfg.n_nodes,
        "heal_round": heal_round,
        "rounds": int(final.t),
        "rounds_after_heal": int(final.t) - heal_round,
        "p99_node_convergence_round": _percentile(node_conv, 99),
        "converged": unconverged == 0,
        "unconverged_nodes": unconverged,
        "wall_clock_s": wall,
    }


def _write_storm(
    n_nodes: int,
    n_payloads: int,
    topo: Topology = Topology(),
    sampler: Optional[str] = None,
    proto_family: Optional[str] = None,
):
    # partial-view SWIM packs (belief, id) into one i32 scatter word —
    # 2^18 nodes max (SimConfig validation).  Beyond that cap (the 1M
    # tier) the storm runs ground-truth membership (alive mask only),
    # the scale regime state.py's layout doc already describes: at 1M
    # nodes the dissemination question doesn't need per-node beliefs.
    # A PeerSwap storm (ISSUE 9) also runs ground-truth membership —
    # the view IS the sampler, and two member-state systems would fight.
    partial = n_nodes <= 262144 and (sampler or "uniform") != "peerswap"
    cfg = SimConfig.wan_tuned(
        n_nodes,
        n_payloads=n_payloads,
        n_writers=16,
        chunks_per_version=4,
        fanout=3,
        sync_interval_rounds=8,
        sync_peers=3,
        swim_partial_view=partial,
        member_slots=64,
        peer_sampler=sampler or "uniform",
        # the storm runs one region (intra delay 0) + sync's t+1 slot:
        # 2 ring slots suffice (validate() enforces it), and inflight is
        # the largest carry tensor — 4 slots wasted a third of the
        # per-round HBM writes (sim/perf.py carry model).  A WAN-tiered
        # topology grows the ring just enough for its deepest class.
        n_delay_slots=max(2, topo.max_delay + 1),
        # protocol-variant overlay (ISSUE 11; CLI --proto)
        **_resolve_proto(proto_family),
    )
    meta = uniform_payloads(cfg, inject_every=2)
    # 512 × 8 KiB = 4 MiB fits both budgets ⇒ metering skipped; derived
    # from meta.nbytes itself so changed payload shapes re-enable it
    return optimize_budgets(cfg, meta), meta


def config_write_storm_100k(
    seed: int = 0,
    n_nodes: int = 100_000,
    n_payloads: int = 512,
    compile_only: bool = False,
    mesh=None,
    telemetry: bool = False,
    trace_path: Optional[str] = None,
    topo_family: Optional[str] = None,
    sampler: Optional[str] = None,
    proto_family: Optional[str] = None,
    profile_dir: Optional[str] = None,
) -> Optional[Dict[str, float]]:
    """Config #5: the north-star scale — 100k nodes, multi-writer chunked
    write storm (consul-service style), p99 time-to-convergence.
    ``topo_family``/``sampler``/``proto_family`` (ISSUE 9/11; CLI
    ``--topology``/``--sampler``/``--proto``) run the same storm over a
    named WAN topology, the PeerSwap sampler, and/or a named protocol
    variant — the scenario-diversity axes at the headline scale."""
    topo = _resolve_topo(topo_family)
    cfg, meta = _write_storm(
        n_nodes, n_payloads, topo=topo, sampler=sampler,
        proto_family=proto_family,
    )
    return run_scenario(
        cfg, meta, topo=topo, seed=seed, max_rounds=3000,
        compile_only=compile_only, mesh=mesh, telemetry=telemetry,
        trace_path=trace_path, profile_dir=profile_dir,
    )


def config_storm_ab(
    seed: int = 0,
    n_nodes: int = 25_000,
    n_payloads: int = 512,
) -> Dict[str, float]:
    """Packed-vs-dense A/B on the identical storm scenario (VERDICT r3
    item 2: record the realized speedup, not the primitive spike's).
    ``allow_packed`` is a SimConfig field, so the two runs compile as
    distinct jit entries; results must match exactly (the equivalence
    contract) and the packed wall should be lower."""
    import dataclasses as _dc

    cfg, meta = _write_storm(n_nodes, n_payloads)
    packed = run_scenario(
        _dc.replace(cfg, packed_min_cells=0), meta, seed=seed, max_rounds=3000
    )
    dense = run_scenario(
        _dc.replace(cfg, allow_packed=False), meta, seed=seed, max_rounds=3000
    )
    assert packed["round_path"] == "packed" and dense["round_path"] == "dense"
    mismatch = [
        k
        for k in ("rounds", "p99_payload_latency_rounds", "unconverged_nodes")
        if packed[k] != dense[k]
    ]
    return {
        "n_nodes": n_nodes,
        "n_payloads": n_payloads,
        "rounds": packed["rounds"],
        "converged": packed["converged"] and dense["converged"],
        "results_identical": not mismatch,
        "mismatched_keys": mismatch,
        "wall_clock_s_packed": packed["wall_clock_s"],
        "wall_clock_s_dense": dense["wall_clock_s"],
        "packed_speedup": (
            dense["wall_clock_s"] / packed["wall_clock_s"]
            if packed["wall_clock_s"] > 0
            else float("inf")
        ),
    }


def storm_fault_plan(n_nodes: int, seed: int = 0):
    """The fault-storm bench schedule (ISSUE 4): a cluster-wide loss
    burst, a symmetric half-split partition over the middle of the
    burst, and one crash-with-wipe rejoin — the loss+partition regime
    the campaign engine sweeps (PeerSwap/SWARM shapes), at a horizon
    short enough that post-heal convergence dominates the run.  Range
    selectors keep the plan O(K) at 100k nodes (no pair expansion)."""
    from ..faults import FaultEvent, FaultPlan

    half = n_nodes // 2
    return FaultPlan(
        n_nodes=n_nodes, seed=seed,
        events=(
            FaultEvent("loss", 0, 12, p=0.15),
            FaultEvent(
                "partition", 4, 16,
                src=f"0:{half}", dst=f"{half}:{n_nodes}", symmetric=True,
            ),
            FaultEvent("crash", 8, 20, node=1, wipe=True),
        ),
    )


def _measured_fault_storm(
    cfg, meta, topo, fplan, seed, per_round_s, packed, telemetry=False,
    mesh=None, profile_dir=None,
) -> Dict[str, object]:
    """The measured-run protocol BOTH storm rungs share — AOT-prime the
    convergence loop, time the run behind a full block + host read,
    verify the wall against the caller's per-round cost, and count
    survivors that never converged.  One copy on purpose: the bench
    divides the telemetry rung's wall by the headline rung's, so the two
    must be the same protocol or the ratio silently stops meaning
    anything.

    ``mesh`` (ISSUE 7) shards the node axis: state, payload metadata,
    and the compiled fault plan are mesh-placed before the jitted loop
    and the wall verifies against the mesh's aggregate HBM bound.

    The AOT prime hands back the compiled executable, so every storm
    record carries its measured memory budget (ISSUE 16; verify_wall's
    HBM capacity check), and ``profile_dir`` additionally captures the
    measured run under the profiler and attaches the parsed
    ``phase_profile`` ledger."""
    from . import profile as prof
    from .faults import run_fault_plan
    from .perf import verify_wall

    from ..parallel.mesh import mesh_size, place_run

    state, meta, fplan = place_run(new_sim(cfg, seed), meta, fplan, mesh)
    n_devices = mesh_size(mesh)
    compiled = run_fault_plan.lower(
        state, meta, cfg, topo, fplan, max_rounds=3000,
        telemetry=telemetry, mesh=mesh,
    ).compile()
    mem_record = prof.memory_budget(
        compiled,
        label=f"run_fault_plan n={cfg.n_nodes} p={cfg.n_payloads} "
        f"telemetry={telemetry}",
    )
    if profile_dir is not None:
        prof.write_phase_map(profile_dir, [compiled.as_text()])
        capture = prof.trace_capture(profile_dir)
        capture.__enter__()
    t0 = time.monotonic()
    try:
        out = run_fault_plan(
            state, meta, cfg, topo, fplan, max_rounds=3000,
            telemetry=telemetry, mesh=mesh,
        )
        jax.block_until_ready(out)
    finally:
        if profile_dir is not None:
            capture.__exit__(None, None, None)
    final, metrics = out[0], out[1]
    np.asarray(final.have[0, 0])
    raw_wall = time.monotonic() - t0

    rounds = int(final.t)
    wall, report = verify_wall(
        raw_wall, rounds, per_round_s, cfg, n_devices=n_devices,
        packed=packed, mem_budget=mem_record,
    )
    node_conv = np.asarray(metrics.converged_at)
    alive = np.asarray(final.alive)
    res = {
        "trace": out[2] if telemetry else None,
        "rounds": rounds,
        "wall": wall,
        "report": report,
        "node_conv": node_conv,
        "unconverged": int(((node_conv < 0) & (alive == ALIVE)).sum()),
    }
    if profile_dir is not None:
        res["phase_profile"] = prof.parse_phase_profile(profile_dir)
    return res


def config_packed_fault_storm(
    seed: int = 0,
    n_nodes: int = 100_000,
    n_payloads: int = 512,
    microbench_rounds: int = 4,
    mesh=None,
    profile_dir: Optional[str] = None,
) -> Dict[str, object]:
    """The fault-storm bench rung (ISSUE 4): the headline storm shape
    under `storm_fault_plan`, run through `run_fault_plan` — which
    dispatches the PACKED round kernels over the bitpack envelope — with
    the full defensible-wall protocol (fault-path per-round microbench,
    HBM bound, ×3 consistency) and a faultless packed run of the same
    scenario on the same platform, so the reported
    ``fault_over_faultless`` ratio is apples-to-apples.

    ``mesh`` (ISSUE 7) runs BOTH sides node-axis-sharded — the packed
    carry, the factored fault tensors, and the telemetry folds partition
    across the 1-D ``nodes`` mesh, bit-identically to single-device
    (tests/sim/test_packed_sharded.py)."""
    from ..parallel.mesh import mesh_record, mesh_size
    from .faults import compile_plan
    from .packed import packed_supported
    from .perf import measure_per_round, verify_wall

    cfg, meta = _write_storm(n_nodes, n_payloads)
    topo = Topology()
    plan = storm_fault_plan(n_nodes, seed)
    fplan = compile_plan(plan, cfg, topo)  # auto-factored at storm scale
    packed = packed_supported(cfg, topo)
    n_devices = mesh_size(mesh)

    per_round_s = measure_per_round(
        cfg, meta, seed=seed + 1000, k_rounds=microbench_rounds,
        fplan=fplan, mesh=mesh,
    )
    run = _measured_fault_storm(
        cfg, meta, topo, fplan, seed, per_round_s, packed, mesh=mesh,
        profile_dir=profile_dir,
    )
    rounds, wall = run["rounds"], run["wall"]

    # the faultless reference on the SAME platform, under the SAME
    # defensible-wall protocol — both sides of the ≤2× acceptance ratio
    # must be artifact-proof, or a lying denominator (the round-2
    # "1.6 ms" failure mode) would spuriously fail/pass the bar
    fl_per_round_s = measure_per_round(
        cfg, meta, seed=seed + 2000, k_rounds=microbench_rounds, mesh=mesh
    )
    run_scenario(cfg, meta, topo=topo, seed=seed, max_rounds=3000,
                 compile_only=True, mesh=mesh)
    faultless = run_scenario(
        cfg, meta, topo=topo, seed=seed, max_rounds=3000, mesh=mesh
    )
    fl_wall, fl_report = verify_wall(
        faultless["wall_clock_s"], faultless["rounds"], fl_per_round_s,
        cfg, n_devices=n_devices, packed=packed,
    )
    ratio = wall / fl_wall if fl_wall > 0 else float("inf")
    return {
        "n_nodes": n_nodes,
        "n_payloads": n_payloads,
        "n_devices": n_devices,
        "mesh": mesh_record(mesh),
        "round_path": "packed" if packed else "dense",
        "plan_horizon": plan.horizon,
        "plan_seed": seed,
        "rounds": rounds,
        "converged": run["unconverged"] == 0 and rounds >= plan.horizon,
        "unconverged_nodes": run["unconverged"],
        "p99_node_convergence_round": _percentile(run["node_conv"], 99),
        "wall_clock_s": wall,
        "sanity": run["report"],
        "faultless_wall_clock_s": fl_wall,
        "faultless_sanity": fl_report,
        "fault_over_faultless": ratio,
        **(
            {"phase_profile": run["phase_profile"]}
            if "phase_profile" in run
            else {}
        ),
    }


def config_packed_fault_storm_sharded(
    seed: int = 0,
    n_nodes: int = 100_000,
    n_payloads: int = 512,
    microbench_rounds: int = 4,
    n_devices: Optional[int] = None,
    check_single_device: Optional[bool] = None,
    profile_dir: Optional[str] = None,
) -> Dict[str, object]:
    """The fault-storm rung MESH-SHARDED (ISSUE 7): the identical storm
    schedule with the packed carry's node axis split across every
    available device (or the first ``n_devices``), under the same
    defensible-wall protocol — `verify_wall` holds the wall against the
    mesh's AGGREGATE HBM bound, so a sharded wall can't launder an
    async artifact either.

    ``check_single_device`` (default: on at ≤ 8192 nodes — the CI smoke
    shape; off at storm scale, where a second full run would double the
    rung's budget) re-runs the schedule unsharded and asserts the
    RunMetrics are bit-identical — the sharding-changes-nothing
    contract, enforced in the bench record itself."""
    from ..parallel.mesh import make_mesh

    mesh = make_mesh(n_devices)
    m = config_packed_fault_storm(
        seed=seed, n_nodes=n_nodes, n_payloads=n_payloads,
        microbench_rounds=microbench_rounds, mesh=mesh,
        profile_dir=profile_dir,
    )
    if check_single_device is None:
        check_single_device = n_nodes <= 8192
    if check_single_device:
        single = config_packed_fault_storm(
            seed=seed, n_nodes=n_nodes, n_payloads=n_payloads,
            microbench_rounds=microbench_rounds,
        )
        mismatch = [
            k
            for k in (
                "rounds", "converged", "unconverged_nodes",
                "p99_node_convergence_round",
            )
            if m[k] != single[k]
        ]
        m["sharded_matches_single"] = not mismatch
        m["mismatched_keys"] = mismatch
        m["single_device_wall_clock_s"] = single["wall_clock_s"]
        if mismatch:
            raise AssertionError(
                f"sharded storm diverged from single-device on {mismatch}"
            )
    return m


def config_fault_storm_1m(
    seed: int = 0,
    n_nodes: int = 1_000_000,
    n_payloads: int = 512,
    microbench_rounds: int = 2,
    n_devices: Optional[int] = None,
    profile_dir: Optional[str] = None,
) -> Dict[str, object]:
    """The 1M-node tier (ISSUE 7): the storm fault schedule at a million
    nodes, node-axis-sharded over every available device, ground-truth
    membership (partial-view SWIM caps at 2^18 — `_write_storm` drops
    it above the cap), measured under the defensible-wall protocol
    (fault-path per-round microbench + aggregate HBM bound + ×3
    consistency).  Unlike `config_packed_fault_storm` this rung runs
    the fault side ONLY — at 1M nodes the faultless reference would
    double a rung whose job is the scale point, and the ≤2× ratio is
    already tracked at 100k."""
    from ..parallel.mesh import make_mesh, mesh_record, mesh_size
    from .faults import compile_plan
    from .packed import packed_supported
    from .perf import measure_per_round

    mesh = make_mesh(n_devices)
    cfg, meta = _write_storm(n_nodes, n_payloads)
    topo = Topology()
    plan = storm_fault_plan(n_nodes, seed)
    fplan = compile_plan(plan, cfg, topo)
    packed = packed_supported(cfg, topo)

    per_round_s = measure_per_round(
        cfg, meta, seed=seed + 1000, k_rounds=microbench_rounds,
        reps=2, fplan=fplan, mesh=mesh,
    )
    run = _measured_fault_storm(
        cfg, meta, topo, fplan, seed, per_round_s, packed, mesh=mesh,
        profile_dir=profile_dir,
    )
    return {
        "n_nodes": n_nodes,
        "n_payloads": n_payloads,
        "n_devices": len(mesh.devices.flat),
        "mesh": mesh_record(mesh),
        "round_path": "packed" if packed else "dense",
        "membership": "ground-truth" if not cfg.swim_partial_view
        else "partial-view",
        "plan_horizon": plan.horizon,
        "plan_seed": seed,
        "rounds": run["rounds"],
        "converged": run["unconverged"] == 0
        and run["rounds"] >= plan.horizon,
        "unconverged_nodes": run["unconverged"],
        "p99_node_convergence_round": _percentile(run["node_conv"], 99),
        "wall_clock_s": run["wall"],
        "sanity": run["report"],
        **({"phase_profile": run["phase_profile"]}
           if "phase_profile" in run else {}),
    }


def config_fault_storm_telemetry(
    seed: int = 0,
    n_nodes: int = 100_000,
    n_payloads: int = 512,
    microbench_rounds: int = 4,
    trace_path: Optional[str] = None,
    mesh=None,
) -> Dict[str, object]:
    """The packed fault storm WITH the flight recorder on (ISSUE 5
    acceptance: telemetry adds ≤ 10% wall under the defensible-wall
    protocol).  Two defensible measurements on the same platform:

    - per-round microbench of the telemetry round body vs the plain one
      (interleaved `measure_overhead_pair`) → ``per_round_overhead_frac``;
    - a full telemetry-on run of the storm schedule, wall-verified
      against its OWN per-round cost, plus the flight-recorder summary
      (coverage-curve digest, bytes/round) bench records into
      BENCH_*.json.

    Run as its own bench child so a timeout here can never lose the
    headline fault-storm record."""
    from .faults import compile_plan
    from .packed import packed_supported
    from .perf import measure_overhead_pair
    from .telemetry import trace_host, trace_summary, write_flight_jsonl

    cfg, meta = _write_storm(n_nodes, n_payloads)
    topo = Topology()
    plan = storm_fault_plan(n_nodes, seed)
    fplan = compile_plan(plan, cfg, topo)
    packed = packed_supported(cfg, topo)

    # interleaved A/B pair, NOT two sequential blocks: the recorded
    # per_round_overhead_frac is the ≤10% acceptance metric, and on a
    # contended box sequential min-of-reps blocks swing ±30% against
    # each other
    pr_plain, pr_tel = measure_overhead_pair(
        cfg, meta, seed=seed + 1000, k_rounds=microbench_rounds,
        fplan=fplan, mesh=mesh,
    )
    run = _measured_fault_storm(
        cfg, meta, topo, fplan, seed, pr_tel, packed, telemetry=True,
        mesh=mesh,
    )
    rounds, wall = run["rounds"], run["wall"]
    host = trace_host(run["trace"], rounds)
    summary = trace_summary(host, rounds, cfg)
    if trace_path:
        write_flight_jsonl(
            trace_path, host, rounds, cfg,
            header={"scenario": "packed_fault_storm", "seed": seed},
        )
    return {
        "n_nodes": n_nodes,
        "n_payloads": n_payloads,
        "round_path": "packed" if packed else "dense",
        "plan_seed": seed,
        "rounds": rounds,
        "converged": run["unconverged"] == 0 and rounds >= plan.horizon,
        "unconverged_nodes": run["unconverged"],
        "wall_clock_s": wall,
        "sanity": run["report"],
        "per_round_plain_ms": round(pr_plain * 1e3, 3),
        "per_round_telemetry_ms": round(pr_tel * 1e3, 3),
        # the ≤10% acceptance bar, in defensible per-round terms
        "per_round_overhead_frac": round(pr_tel / pr_plain - 1.0, 4)
        if pr_plain > 0
        else None,
        "telemetry": summary,
    }


def serving_fault_plan(n_nodes: int, seed: int = 0):
    """The serving rung's FaultPlan: the `serving-3node` builtin
    campaign's schedule (loss burst + asymmetric partition + delay) at
    ``n_nodes`` ≥ 3 — ONE schedule shared by the rung, the campaign
    cells, and the chaos tests, so their numbers compare.  The events
    name node indices up to 2, so smaller clusters are refused UP
    FRONT — before a rung spends its flood time — rather than dying in
    FaultPlan validation mid-run."""
    if n_nodes < 3:
        raise ValueError(
            "serving_fault_plan needs n_nodes >= 3 (its partition/delay "
            "events target node 2); run the serving rung faultless "
            "(use_faults=False) at smaller sizes"
        )
    from ..campaign.spec import serving_3node_spec
    from ..faults import FaultPlan

    ref = serving_3node_spec()
    return FaultPlan(
        n_nodes=n_nodes, seed=int(seed), events=ref.events,
        round_s=ref.round_s,
    )


def config_serving_loadgen(
    seed: int = 0,
    n_nodes: int = 3,
    n_writes: int = 96,
    n_writers: int = 2,
    n_watchers: int = 2,
    overhead_passes: int = 2,
    use_faults: bool = True,
    telemetry: bool = True,
    trace_path: Optional[str] = None,
) -> Dict[str, object]:
    """The HOST-SERVING rung (ISSUE 8): flood an in-process ``n_nodes``
    agent cluster through the measured loadgen driver and record
    publish→subscriber-visible latency percentiles — the host twin of
    the storm rungs' convergence walls.  Three measurements:

    - **instrumentation overhead** — interleaved A/B flood pairs
      (telemetry OFF, telemetry ON, repeated ``overhead_passes``
      times), per-variant-MIN flood walls, exactly the discipline the
      sim telemetry rung uses (`measure_overhead_pair`): box walls are
      bimodal, sequential blocks lie.  Recorded as
      ``instrumentation_overhead_frac`` — the ≤5% acceptance form;
    - **faultless serving run** — telemetry on, flight JSONL at
      ``trace_path``, latency percentiles + throughput;
    - **faulted serving run** — the same workload with
      `serving_fault_plan` replayed by the host fault drivers
      underneath (``use_faults``), its own latency percentiles.

    ``converged`` is every run's ``consistent`` (zero lost writes with
    the checker attached) — the record a lost write can never pass."""
    import asyncio as _asyncio

    from ..loadgen import run_serving_cluster_load

    if use_faults and n_nodes < 3:
        # validate BEFORE the floods: a mid-run FaultPlan refusal would
        # discard the A/B and faultless measurements already paid for
        serving_fault_plan(n_nodes, seed)
    t0 = time.monotonic()
    rate = 0.0  # flood form: the overhead A/B must not hide in sleeps

    def one(telemetry_on: bool, plan=None, path=None, s=0):
        return _asyncio.run(
            run_serving_cluster_load(
                n_nodes=n_nodes, n_writes=n_writes,
                n_writers=n_writers, n_watchers=n_watchers,
                rate_hz=rate, settle_timeout_s=30.0, seed=seed + s,
                plan=plan, telemetry=telemetry_on, trace_path=path,
                header={"scenario": "serving_loadgen", "seed": seed},
            )
        )

    # -- interleaved overhead pairs (per-variant min) -------------------
    off_walls, on_walls = [], []
    reports = []
    for i in range(max(1, overhead_passes)):
        off = one(False, s=1000 + i)
        on = one(True, s=2000 + i)
        off_walls.append(off["flood_s"])
        on_walls.append(on["flood_s"])
        reports += [off, on]
    overhead = (
        min(on_walls) / min(off_walls) - 1.0 if min(off_walls) > 0 else None
    )

    # -- the measured runs ---------------------------------------------
    faultless = one(telemetry, path=trace_path if telemetry else None)
    reports.append(faultless)
    faulted = None
    if use_faults:
        faulted = one(telemetry, plan=serving_fault_plan(n_nodes, seed))
        reports.append(faulted)

    consistent = all(r["consistent"] for r in reports)
    out = {
        "n_nodes": n_nodes,
        "round_path": "host",
        "writes": n_writes,
        "writers": n_writers,
        "watchers": n_watchers,
        "seed": seed,
        "converged": consistent,
        "consistent": consistent,
        "lost_writes": any(r["lost_writes"] for r in reports),
        "checker_broken": any(r["checker_broken"] for r in reports),
        "publish_visible_s": faultless["visible_latency_s"],
        "write_latency_s": faultless["write_latency_s"],
        "throughput_wps": faultless["throughput_wps"],
        # the measured-no-op acceptance number, per-variant-min form
        "instrumentation_overhead_frac": (
            round(overhead, 4) if overhead is not None else None
        ),
        "overhead_passes": max(1, overhead_passes),
        "wall_clock_s": round(time.monotonic() - t0, 3),
    }
    if faulted is not None:
        out["faulted"] = {
            "publish_visible_s": faulted["visible_latency_s"],
            "throughput_wps": faulted["throughput_wps"],
            "consistent": faulted["consistent"],
            "plan_horizon": faulted.get("plan_horizon"),
        }
    if telemetry and "telemetry" in faultless:
        out["telemetry"] = faultless["telemetry"]
    return out


def config_serving_loadgen_mp(
    seed: int = 0,
    n_nodes: int = 3,
    n_workers: int = 8,
    n_writers: int = 1024,
    n_watchers: int = 8,
    n_writes: int = 2048,
    rate_hz: float = 0.0,
    overload_inflight: Optional[int] = None,
    settle_timeout_s: float = 60.0,
    global_settle_s: float = 60.0,
) -> Dict[str, object]:
    """The MULTI-PROCESS serving rung (ISSUE 13): ``n_writers`` writer
    lanes sharded across ``n_workers`` loadgen WORKER PROCESSES against
    a real ``n_nodes`` devcluster (one agent process per node, flight
    recorders armed) — the ≥1000-writers form of the serving-tier
    claim.  Three measured conditions:

    - **faultless** — full writer count, publish→visible percentiles
      joined across processes (one machine-wide monotonic clock);
    - **kill + restart** — a FaultPlan crash event replayed as SIGKILL
      + respawn of the last node mid-flood (`DevClusterFaultDriver`);
      the checker proves zero ACKED writes lost across the restart;
    - **overload** — every node's admission limit pinned to
      ``overload_inflight`` (far below the writer count): saturated
      nodes must answer 429 + Retry-After, writers back off and retry,
      and the server-side ``admission_rejected`` counters (read from
      the nodes' flight JSONLs) must match the degradation story — no
      silent drops, no unbounded queues.

    ``converged`` ≡ every condition ``consistent`` AND the overload
    condition actually observed backpressure (a rung that never hit
    the limit measured nothing)."""
    import asyncio as _asyncio

    from ..faults import FaultEvent, FaultPlan
    from ..loadgen_mp import run_devcluster_load

    if overload_inflight is None:
        # scale the limit with the workload so the overload condition
        # actually overloads at ANY --writers: ~1/16th of the writer
        # count (64 at the 1024-writer acceptance shape), floored so a
        # tiny smoke still has a meaningful bound to hit
        overload_inflight = max(2, min(64, n_writers // 16))
    t0 = time.monotonic()

    def one(plan=None, perf=None, s=0):
        return _asyncio.run(
            run_devcluster_load(
                n_nodes=n_nodes, n_workers=n_workers,
                n_writes=n_writes, n_writers=n_writers,
                n_watchers=n_watchers, rate_hz=rate_hz,
                settle_timeout_s=settle_timeout_s,
                global_settle_s=global_settle_s,
                seed=seed + s, plan=plan, perf=perf,
            )
        )

    faultless = one(s=0)
    crash_plan = FaultPlan(
        n_nodes=n_nodes, seed=seed,
        events=(FaultEvent("crash", 8, 40, node=n_nodes - 1),),
        round_s=0.05,
    )
    crashed = one(plan=crash_plan, s=100)
    overload = one(perf={"api_max_inflight_tx": overload_inflight}, s=200)

    def _sat_total(rep, kind):
        total = 0
        for f in (rep.get("node_flights") or {}).values():
            c = (f.get("saturation") or {}).get("counters", {})
            total += int(c.get(kind, {}).get("total", 0))
        return total

    rejected = _sat_total(overload, "admission_rejected")
    runs = (faultless, crashed, overload)
    consistent = all(r["consistent"] for r in runs)
    backpressure_seen = (
        overload["retries_429"] > 0 and rejected > 0
    )
    out = {
        "n_nodes": n_nodes,
        "round_path": "host-mp",
        "workers": n_workers,
        "writers": n_writers,
        "watchers": n_watchers,
        "writes": n_writes,
        "seed": seed,
        "converged": consistent and backpressure_seen,
        "consistent": consistent,
        "lost_writes": any(r["lost_writes"] for r in runs),
        "checker_broken": any(r["checker_broken"] for r in runs),
        "publish_visible_s": faultless["visible_latency_s"],
        "write_latency_s": faultless["write_latency_s"],
        "throughput_wps": faultless["throughput_wps"],
        "crash": {
            "publish_visible_s": crashed["visible_latency_s"],
            "consistent": crashed["consistent"],
            "lost_writes": crashed["lost_writes"],
            "killed_nodes": crashed.get("killed_nodes"),
            "retries_transport": crashed["retries_transport"],
            "write_failovers": crashed["write_failovers"],
            "settle_missing": crashed.get("settle_missing"),
            "plan_horizon": crash_plan.horizon,
        },
        "overload": {
            "inflight_limit": overload_inflight,
            "retries_429": overload["retries_429"],
            "admission_rejected_total": rejected,
            "backpressure_seen": backpressure_seen,
            "consistent": overload["consistent"],
            "publish_visible_s": overload["visible_latency_s"],
            "writes_gave_up": overload["writes_gave_up"],
        },
        # per-node saturation evidence from the faultless run's flight
        # JSONLs (queue-depth high-water marks): the gauges the host
        # flight recorder surfaces for the serving tier's limits
        "saturation_high_water": {
            name: (f.get("saturation") or {}).get("high_water")
            for name, f in (faultless.get("node_flights") or {}).items()
        },
        "wall_clock_s": round(time.monotonic() - t0, 3),
    }
    return out


def config_peer_sampler_frontier(
    seed: int = 0,
    n_nodes: int = 96,
    n_seeds: int = 4,
    max_rounds: int = 400,
) -> Dict[str, object]:
    """The uniform-vs-PeerSwap frontier rung (ISSUE 9): run the
    `peer-sampler-frontier` builtin campaign — both samplers × two
    topology families, wire bytes banded per lane — and reduce it to
    the comparison record bench.py tracks: per family, convergence
    rounds and wire bytes for each sampler plus their ratios
    (peerswap / uniform; < 1.0 means PeerSwap wins that axis)."""
    from ..campaign.engine import run_campaign
    from ..campaign.spec import peer_sampler_frontier_spec

    spec = peer_sampler_frontier_spec(
        seeds=tuple(seed + i for i in range(n_seeds)), n=n_nodes,
        max_rounds=max_rounds,
    )
    t0 = time.monotonic()
    artifact = run_campaign(spec, out_path=None)
    families: Dict[str, Dict[str, object]] = {}
    for cell in artifact["cells"]:
        fam = cell["params"]["topo_family"]
        samp = cell["params"]["peer_sampler"]
        families.setdefault(fam, {})[samp] = {
            "rounds_p50": cell["bands"]["rounds"]["p50"],
            "rounds_p99": cell["bands"]["rounds"]["p99"],
            "wire_bytes_p50": cell["bands"]["wire_bytes"]["p50"],
            "converged": cell["all_converged"],
        }
    for fam, d in families.items():
        uni, ps = d.get("uniform"), d.get("peerswap")
        if uni and ps and uni["rounds_p50"]:
            d["rounds_ratio"] = round(
                ps["rounds_p50"] / uni["rounds_p50"], 3
            )
        if uni and ps and uni["wire_bytes_p50"]:
            d["wire_ratio"] = round(
                ps["wire_bytes_p50"] / uni["wire_bytes_p50"], 3
            )
    return {
        "n_nodes": n_nodes,
        "seeds": n_seeds,
        "converged": all(
            c["all_converged"] for c in artifact["cells"]
        ),
        "families": families,
        "spec_hash": artifact["spec_hash"],
        "result_digest": artifact["result_digest"],
        "wall_clock_s": round(time.monotonic() - t0, 3),
    }


def config_protocol_frontier(
    seed: int = 0,
    n_nodes: int = 96,
    n_seeds: int = 4,
    max_rounds: int = 500,
    sampler_storm_nodes: int = 25_600,
    sampler_storm_payloads: int = 512,
    proto_families: Optional[Sequence[str]] = None,
    topo_families: Optional[Sequence[str]] = None,
    profile_dir: Optional[str] = None,
) -> Dict[str, object]:
    """The protocol-variant frontier rung (ISSUE 11): run the
    `protocol-frontier` builtin campaign — four named protocol families
    × two topology families, wire bytes banded per lane — and reduce it
    to the comparison record bench.py tracks: per topology family, each
    variant's convergence rounds and wire bytes plus their ratios
    against the ``baseline`` family (rounds_ratio < 1.0 means the
    variant converges faster; wire_ratio > 1.0 means it pays more
    wire — the two axes of the Pareto).  Ordering cells also report
    their banded on-device delivery-order violation totals (must be 0
    for the enforced discipline).

    ``sampler_storm_nodes`` > 0 additionally folds a STORM-SCALE
    sampler cell into the record (ISSUE 11 carried edge: the sampler
    frontier's 96-node CPU rung must not stay the only sampler
    number) — the packed write storm at ≥25k nodes under the PeerSwap
    sampler, reported alongside the proto families.

    ``proto_families``/``topo_families`` shrink the grid for smoke runs
    (None = the builtin's canonical 4 × 2 grid, which the bench rung
    and the committed baseline always use)."""
    import dataclasses as _dc

    from ..campaign.engine import run_campaign
    from ..campaign.spec import protocol_frontier_spec

    spec = protocol_frontier_spec(
        seeds=tuple(seed + i for i in range(n_seeds)), n=n_nodes,
        max_rounds=max_rounds,
    )
    if proto_families is not None or topo_families is not None:
        grid = dict(spec.grid)
        if proto_families is not None:
            grid["proto_family"] = list(proto_families)
        if topo_families is not None:
            grid["topo_family"] = list(topo_families)
        spec = _dc.replace(spec, grid=grid)
    t0 = time.monotonic()
    artifact = run_campaign(spec, out_path=None)
    families: Dict[str, Dict[str, object]] = {}
    for cell in artifact["cells"]:
        fam = cell["params"]["topo_family"]
        proto = cell["params"]["proto_family"]
        entry = {
            "rounds_p50": cell["bands"]["rounds"]["p50"],
            "rounds_p99": cell["bands"]["rounds"]["p99"],
            "wire_bytes_p50": cell["bands"]["wire_bytes"]["p50"],
            "converged": cell["all_converged"],
        }
        if "order_violations" in cell["bands"]:
            entry["order_violations_max"] = cell["bands"][
                "order_violations"
            ]["max"]
        families.setdefault(fam, {})[proto] = entry
    for fam, d in families.items():
        base = d.get("baseline")
        if not base:
            continue
        for proto, entry in list(d.items()):
            if proto == "baseline" or not isinstance(entry, dict):
                continue
            if base["rounds_p50"]:
                entry["rounds_ratio"] = round(
                    entry["rounds_p50"] / base["rounds_p50"], 3
                )
            if base["wire_bytes_p50"]:
                entry["wire_ratio"] = round(
                    entry["wire_bytes_p50"] / base["wire_bytes_p50"], 3
                )
    converged = all(c["all_converged"] for c in artifact["cells"])

    sampler_storm = None
    if sampler_storm_nodes:
        storm = config_write_storm_100k(
            seed=seed, n_nodes=sampler_storm_nodes,
            n_payloads=sampler_storm_payloads, sampler="peerswap",
            profile_dir=profile_dir,
        )
        sampler_storm = {
            "sampler": "peerswap",
            "n_nodes": sampler_storm_nodes,
            "n_payloads": sampler_storm_payloads,
            "round_path": storm["round_path"],
            "rounds": storm["rounds"],
            "wall_clock_s": storm["wall_clock_s"],
            "converged": storm["converged"],
            "p99_node_convergence_round": storm[
                "p99_node_convergence_round"
            ],
            **({"phase_profile": storm["phase_profile"]}
               if "phase_profile" in storm else {}),
        }
        converged = converged and bool(storm["converged"])

    out = {
        "n_nodes": n_nodes,
        "seeds": n_seeds,
        "converged": converged,
        "families": families,
        "spec_hash": artifact["spec_hash"],
        "result_digest": artifact["result_digest"],
        "wall_clock_s": round(time.monotonic() - t0, 3),
    }
    if sampler_storm is not None:
        out["sampler_storm"] = sampler_storm
    return out


def _gapstress_cfg(n_nodes: int, gap_slots: int) -> SimConfig:
    return SimConfig.wan_tuned(
        n_nodes,
        n_payloads=8192,  # 128 versions × 8 writers × 8 chunks: V ≫ K
        n_writers=8,
        chunks_per_version=8,
        gap_slots=gap_slots,
        fanout=3,
        sync_interval_rounds=8,
        sync_peers=3,
        swim_partial_view=True,
        member_slots=64,
    )


def gapstress_payload_sizes(p: int):
    """Mixed 1 B – 8 KiB changeset sizes (the reference's reality: a
    consul check update is bytes, a service blob is the 8 KiB chunk
    ceiling, change.rs:180) in a deterministic cycle."""
    cycle = np.array([1, 64, 512, 1024, 4096, 8192], np.int32)
    return np.resize(cycle, p)


def config_write_storm_gapstress(
    seed: int = 0,
    n_nodes: int = 10_000,
    gap_slots: int = 8,
    loss: float = 0.3,
    max_rounds: int = 4000,
    telemetry: bool = False,
    trace_path: Optional[str] = None,
    profile_dir: Optional[str] = None,
) -> Optional[Dict[str, float]]:
    """Config #5b (VERDICT r2 item 3): a storm that actually stresses the
    fixed-K interval machinery.  V=128 versions per writer with K=8 gap
    slots, BURST injection + 30% broadcast loss so early arrivals are a
    loss-scattered random subset of the version space and gap runs
    exceed K (the clamp path, gaps.py:78-85), and mixed 1 B–8 KiB
    payloads so the byte-accurate budget actually meters heterogeneous
    sizes.  Reports ``gap_overflow_frac_max``."""
    cfg = _gapstress_cfg(n_nodes, gap_slots)
    # BURST injection: all 128 versions enter at round 0, so early
    # arrivals are a loss-scattered random subset of the whole version
    # space — dozens of gap runs per (node, actor), far over K=8.
    # Staggered injection never overflows (holes trail the head in a
    # short contiguous window); the burst is the shape that stresses
    # the clamp, mirroring a node rejoining mid-storm.
    meta = uniform_payloads(
        cfg, inject_every=0,
        payload_bytes=gapstress_payload_sizes(cfg.n_payloads),
    )
    topo = Topology(loss=loss)
    # prime the XLA cache so the official wall is execution, not compile
    # (the storm rung does the same before its measured run; telemetry is
    # part of the jit cache key, so the prime must match the real run)
    run_scenario(
        cfg, meta, topo=topo, seed=seed, max_rounds=max_rounds,
        compile_only=True, telemetry=telemetry or trace_path is not None,
    )
    return run_scenario(
        cfg, meta, topo=topo, seed=seed, max_rounds=max_rounds,
        telemetry=telemetry, trace_path=trace_path,
        profile_dir=profile_dir,
    )


def config_gapstress_distortion(
    seed: int = 0, n_nodes: int = 1024, control_slots: int = 64
) -> Dict[str, object]:
    """Quantify the K-clamp distortion: the same #5b scenario at K=8
    (overflow forced) vs a large-K control where every gap run fits.
    The clamp direction is conservative (over-advertised needs slow
    convergence, never corrupt it — gaps.py docstring), so distortion =
    how many extra rounds K=8 costs."""
    stressed = config_write_storm_gapstress(seed, n_nodes, gap_slots=8)
    control = config_write_storm_gapstress(
        seed, n_nodes, gap_slots=control_slots
    )
    return {
        "stressed": stressed,
        "control": control,
        "overflow_frac_max_stressed": stressed["gap_overflow_frac_max"],
        "overflow_frac_max_control": control["gap_overflow_frac_max"],
        "distortion_rounds": stressed["rounds"] - control["rounds"],
        "distortion_p99_latency_rounds": (
            stressed["p99_payload_latency_rounds"]
            - control["p99_payload_latency_rounds"]
        ),
    }


def config_write_storm_verified(
    seed: int = 0,
    n_nodes: int = 100_000,
    n_payloads: int = 512,
    microbench_rounds: int = 8,
    mesh=None,
    profile_dir: Optional[str] = None,
) -> Dict[str, float]:
    """Config #5 with the VERDICT r2 item-1 integrity protocol: an
    explicit per-round `fori_loop` microbenchmark (blocking on every
    output), the analytic HBM lower bound, and the ×3 full-run/per-round
    consistency check.  The returned ``wall_clock_s`` is the *defensible*
    wall (conservative max of measured and rounds × per-round); the raw
    measurement and the verdict live under ``sanity``."""
    from .perf import measure_per_round, verify_wall

    cfg, meta = _write_storm(n_nodes, n_payloads)
    per_round_s = measure_per_round(
        cfg, meta, seed=seed + 1000, k_rounds=microbench_rounds, mesh=mesh
    )
    # prime run_to_convergence's compile so the measured wall is steady-
    # state execution, not compile (the ×3 consistency check would
    # otherwise flag every cold run as overhead)
    run_scenario(cfg, meta, seed=seed, max_rounds=3000, compile_only=True,
                 mesh=mesh)
    m = run_scenario(cfg, meta, seed=seed, max_rounds=3000, mesh=mesh,
                     profile_dir=profile_dir)
    from ..parallel.mesh import mesh_size
    from .packed import packed_supported

    wall, report = verify_wall(
        m["wall_clock_s"], m["rounds"], per_round_s, cfg,
        n_devices=mesh_size(mesh),
        packed=packed_supported(cfg, Topology()),
        mem_budget=m.get("memory_budget"),
    )
    m["wall_clock_s"] = wall
    m["rounds_per_sec"] = m["rounds"] / wall if wall > 0 else 0.0
    m["node_rounds_per_sec"] = (
        m["rounds"] * cfg.n_nodes / wall if wall > 0 else 0.0
    )
    m["sanity"] = report
    return m


def config_phase_profile(
    seed: int = 0,
    n_nodes: int = 2048,
    n_payloads: int = 512,
    k_rounds: int = 8,
    profile_dir: Optional[str] = None,
) -> Dict[str, object]:
    """The phase-attribution rung (ISSUE 16): capture the packed storm
    round body under the profiler and reduce device op time to the
    named-scope cost ledger, then CROSS-CHECK the telemetry cost against
    `measure_overhead_pair`'s interleaved number — two independent
    instruments that must agree within the baseline tolerance, or one
    of them is lying.  The trace-side number is a DUAL-capture delta
    (telemetry-on vs telemetry-off device totals), not the scoped
    `phases["telemetry"]` entry alone: XLA re-draws fusion boundaries
    and loop-carry copies around the trace buffers, so roughly half of
    the induced work lands in shared fusions the telemetry scope cannot
    own — the record reports that split as ``telemetry_scoped_frac`` /
    ``telemetry_smeared_frac`` next to the cross-checkable total.

    The CAPTURE runs a dedicated k_rounds=1 body: tracing slows a round
    ~100× (every thunk is an event) and the trace converter drops events
    past ~1M, so one round at a shape that fits under the cap is the
    largest honest capture — `parse_phase_profile` flags saturation and
    `compare_profiles` refuses a saturated candidate.  Phase FRACTIONS
    are loop-invariant, so one round is the whole ledger.  The A/B
    overhead pair still runs the full k-round body untraced.

    The profiled program is the same jitted round body the
    defensible-wall microbench times (`_per_round_runner` builds both);
    lowering it again hits jax's jit cache, so the HLO instruction
    names in `compiled.as_text()` are the ones the trace events carry.
    The expected telemetry fraction from the A/B pair is
    overhead/(1+overhead) = 1 − plain/tel: the telemetry phase's share
    of the telemetry-on round is exactly the time the plain round
    doesn't pay.

    ``packed_min_cells=0`` forces the PACKED round kernels (the storm's
    real path) at this sub-storm node count — the same move
    `config_storm_ab` uses.  The node count is capacity-bound, not
    taste: the two scatter-expansion loops (pswim view merge + member
    scatter) emit ~345·n trace events per round on CPU, so n=2048 is
    the largest storm-aspect round that fits under the converter's ~1M
    cap; 25k nodes saturates 36× over and the gate would (rightly)
    refuse the capture."""
    import dataclasses as _dc
    import tempfile

    from . import profile as prof
    from .packed import packed_supported
    from .perf import _per_round_runner, measure_overhead_pair

    cfg, meta = _write_storm(n_nodes, n_payloads)
    cfg = _dc.replace(cfg, packed_min_cells=0)
    topo = Topology()
    run_cap = _per_round_runner(
        cfg, meta, topo, seed + 1000, 1, None, None, telemetry=True
    )
    run_cap()  # warmup: pay the compile before the capture window
    compiled = run_cap.k_rounds_fn.lower(*run_cap.args).compile()
    mem_record = prof.memory_budget(
        compiled,
        label=f"phase_profile round n={n_nodes} p={n_payloads}",
    )

    def _capture(pdir: str, run, hlo_text: str) -> Dict[str, object]:
        prof.write_phase_map(pdir, [hlo_text])
        with prof.trace_capture(pdir):
            run()
        return prof.parse_phase_profile(pdir)

    if profile_dir is None:
        with tempfile.TemporaryDirectory(prefix="corro_prof_") as pdir:
            record = _capture(pdir, run_cap, compiled.as_text())
    else:
        record = _capture(profile_dir, run_cap, compiled.as_text())

    # the telemetry cost has TWO honest instruments, and neither is the
    # scoped `phases["telemetry"]` entry alone: XLA re-draws fusion
    # boundaries and loop-carry copies around the trace buffers, so a
    # large share of the induced work lands in ops the telemetry scope
    # cannot own (it is smeared through multi-phase fusions).  Measure
    # the TOTAL induced cost both ways — a second capture of the
    # telemetry-off body (trace instrument: device-time delta) and the
    # interleaved A/B wall pair (wall instrument) — and report the
    # scoped/smeared split instead of pretending the scoped number is
    # the whole cost.
    run_plain = _per_round_runner(
        cfg, meta, topo, seed + 1000, 1, None, None, telemetry=False
    )
    run_plain()  # warmup
    plain_hlo = run_plain.k_rounds_fn.lower(*run_plain.args).compile()

    def _total(run, hlo_text) -> float:
        with tempfile.TemporaryDirectory(prefix="corro_prof_ab_") as pdir:
            return float(_capture(pdir, run, hlo_text)["total_s"])

    # single-shot capture totals swing ±30% with box contention (op
    # durations are measured walls), so the delta uses the wall pair's
    # estimator: interleaved repeats, min per variant — best-case
    # against best-case.  The ledger capture above doubles as the first
    # telemetry-on sample.
    on_totals = [float(record["total_s"])]
    off_totals = [_total(run_plain, plain_hlo.as_text())]
    on_totals.append(_total(run_cap, compiled.as_text()))
    off_totals.append(_total(run_plain, plain_hlo.as_text()))
    tel_total = min(on_totals)
    plain_total = min(off_totals)
    device_delta_frac = (
        max(0.0, 1.0 - plain_total / tel_total) if tel_total > 0 else 0.0
    )

    pr_plain, pr_tel = measure_overhead_pair(
        cfg, meta, topo=topo, seed=seed + 1000, k_rounds=k_rounds
    )
    overhead = pr_tel / pr_plain - 1.0 if pr_plain > 0 else 0.0
    tel_frac_expected = max(0.0, 1.0 - pr_plain / pr_tel) \
        if pr_tel > 0 else 0.0
    tel_scoped = record["phases"].get("telemetry", {}).get("frac", 0.0)
    return {
        "n_nodes": n_nodes,
        "n_payloads": n_payloads,
        "k_rounds": k_rounds,
        "round_path": "packed" if packed_supported(cfg, topo) else "dense",
        "phase_profile": record,
        "memory_budget": mem_record,
        "per_round_plain_ms": round(pr_plain * 1e3, 3),
        "per_round_telemetry_ms": round(pr_tel * 1e3, 3),
        "per_round_overhead_frac": round(overhead, 4),
        "plain_device_total_s": round(plain_total, 4),
        "telemetry_device_total_s": round(tel_total, 4),
        # total induced cost, trace instrument — the number comparable
        # to the wall pair's expected fraction
        "telemetry_frac": round(device_delta_frac, 4),
        # the share the telemetry scope itself owns, and the remainder
        # XLA smeared through shared fusions / loop-carry copies
        "telemetry_scoped_frac": round(tel_scoped, 4),
        "telemetry_smeared_frac": round(
            max(0.0, device_delta_frac - tel_scoped), 4
        ),
        "telemetry_frac_expected": round(tel_frac_expected, 4),
        "telemetry_frac_delta": round(
            device_delta_frac - tel_frac_expected, 4
        ),
    }


def config_memory_budget(
    seed: int = 0,
    rungs: Sequence[Tuple[int, int]] = ((100_000, 512), (1_000_000, 512)),
) -> Dict[str, object]:
    """Static memory budgets for the storm rungs (ISSUE 16): lower
    `run_fault_plan` at each (n_nodes, n_payloads) shape over ABSTRACT
    state (`jax.eval_shape` — no 1M-node allocation on the build box)
    and read `compile().memory_analysis()`.  The committed record is
    what `verify_wall`'s HBM capacity check consumes before anyone pays
    for a real device: if a rung's peak no longer fits the chip floor,
    the nightly job says so from CPU."""
    from . import profile as prof
    from .faults import compile_plan, run_fault_plan
    from .perf import HBM_BYTES_CAPACITY_PER_CHIP

    budgets = []
    for n_nodes, n_payloads in rungs:
        cfg, meta = _write_storm(n_nodes, n_payloads)
        topo = Topology()
        fplan = compile_plan(storm_fault_plan(n_nodes, seed), cfg, topo)
        abstract_state = jax.eval_shape(lambda: new_sim(cfg, seed))
        compiled = run_fault_plan.lower(
            abstract_state, meta, cfg, topo, fplan, max_rounds=3000,
            telemetry=False, mesh=None,
        ).compile()
        rec = prof.memory_budget(
            compiled,
            label=f"run_fault_plan n={n_nodes} p={n_payloads}",
        )
        rec["n_nodes"] = n_nodes
        rec["n_payloads"] = n_payloads
        rec["fits_hbm_single_chip"] = bool(
            rec["peak_bytes_est"] <= HBM_BYTES_CAPACITY_PER_CHIP
        )
        budgets.append(rec)
    return {
        "hbm_bytes_per_chip": HBM_BYTES_CAPACITY_PER_CHIP,
        "budgets": budgets,
    }
