"""FaultPlan → sim tensors: the device backend of the unified fault seam.

`corrosion_tpu.faults.FaultPlan.schedule()` is the single source of
truth; this module lowers that per-round table into stacked mask/delay
tensors indexed by ``state.t`` and threads them through the round
kernels (broadcast / sync / SWIM reachability), extending the existing
DOWN/latency-class machinery:

- ``block[R+1, N, N] bool`` — directed edge cut (asymmetric partitions:
  block[r, a, b] stops a→b while b→a still flows);
- ``loss[R+1, N, N] u8``   — extra per-link drop threshold (p·256, the
  same 8-bit quantization as `topology.edge_payload_drop`); a loss of
  ~1.0 compiles into ``block`` instead (a u8 threshold cannot express
  certainty);
- ``delay/jitter[R+1, N, N] u8`` — extra delivery delay in rounds:
  fixed + uniform 0..jitter drawn per (edge, PAYLOAD) — each changeset
  rides its own uni frame (the edge_payload_drop grain), so jitter
  reorders traffic within a single flush exactly like the host tier's
  per-message draw; fault latency also stretches sync delivery (the
  bi-stream RTT rides the sync delay ring, slower direction wins);
- ``alive[R+1, N] i8``     — scheduled alive override (-1 = leave to
  the scenario; ALIVE/DOWN during crash windows and at restart);
- ``wipe[R+1, N] bool``    — the restart round of a crash with
  ``wipe=True``: the node's ``have``/relay/inflight/bookkeeping rows
  are zeroed, so it rejoins empty and must recover via anti-entropy.

Row ``R`` (one past the last scheduled round) is all-clear by
construction, and `round_faults` clamps its index there — after the
horizon the sim runs fault-free, the steady state convergence is
measured in.

Two compiled representations, one consumer surface (ISSUE 4):

- **matrix** (`SimFaultPlan`): the [R+1, N, N] tensors above — exact
  for arbitrary per-link schedules, O(R·N²) HBM, the campaign-scale
  form.  A fault class absent from the plan compiles to ``None`` (a
  trace-time fact: the kernels skip that class's gathers and RNG draws
  entirely — bit-identical to all-zero tensors, since fault keys are
  fold_in-derived, never split from the phase stream).
- **factored** (`FactoredFaultPlan`): each link event as a rank-1
  (active[R+1], src_mask[N], dst_mask[N]) term — O(K·(R+N)) HBM, which
  is what makes a 100k-node fault storm compilable at all (the matrix
  form would be 10 GB *per round*).  Exact for block (OR of terms),
  delay (sum — `LinkFault.merge` adds), jitter (max), AND loss:
  overlapping loss events compile to one composite factor per
  pairwise-overlapping subset carrying the matrix compiler's exact
  merged u8 threshold (`_compose_overlapping_losses`, ISSUE 13 —
  closing the PR 4 carried edge), capped at `MAX_OVERLAPPING_LOSS`
  mutually-overlapping events with a loud matrix-fallback refusal.

The kernels never index the tensors directly: `fault_edge_block` /
`fault_edge_loss` / `fault_edge_delay` / `fault_edge_jitter` evaluate
either form at an edge list and return ``None`` when the class is
absent, so both round paths (dense AND packed — the seam rides the
packed carry since ISSUE 4) consume identical per-edge fault decisions.

Tier coverage caveats (doc/faults.md): ``duplicate`` compiles to a
no-op here — sim delivery is an idempotent scatter-max, so a duplicated
payload is indistinguishable from the original (the host tier delivers
it twice and the dedup cache absorbs it); ``clock_skew`` is host-only —
the sim carries no HLC.  Both still count toward schedule coverage via
the plan's markers, fired by `run_fault_plan_checked`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..faults import CLEAR, FaultPlan
from .round import RunMetrics, new_metrics, round_step
from .state import (
    ALIVE,
    DOWN,
    PayloadMeta,
    SimConfig,
    SimState,
    complete_versions,
    version_active,
)
from .topology import Topology, regions


class SimFaultPlan(NamedTuple):
    """Stacked per-round fault tensors (device); index with `round_faults`.

    A fault class with no events compiles to ``None`` (pytree structure,
    i.e. trace-time knowledge): the kernels skip that class's gathers
    and RNG draws — results identical to all-zero tensors, cheaper."""

    block: Optional[jnp.ndarray]   # bool[R+1, N, N] directed src→dst cut
    loss: Optional[jnp.ndarray]    # u8[R+1, N, N] extra drop threshold (p·256)
    delay: Optional[jnp.ndarray]   # u8[R+1, N, N] fixed extra delay, rounds
    jitter: Optional[jnp.ndarray]  # u8[R+1, N, N] max per-message extra delay
    alive: jnp.ndarray   # i8[R+1, N] override: -1 none, else ALIVE/DOWN
    wipe: jnp.ndarray    # bool[R+1, N] zero the node's state this round
    # plan-seed fold (derive_seed(seed, "sim")): every stochastic fault
    # draw folds this in, so the PLAN seed — not just the scenario's
    # PRNG key — determines the per-round fault decisions, mirroring the
    # host tier where the plan seed derives every LinkModel stream
    seed: jnp.ndarray    # i32 scalar


class RoundFaults(NamedTuple):
    """One round's slice of a SimFaultPlan, consumed by the kernels
    (through the `fault_edge_*` helpers; ``None`` = class absent)."""

    block: Optional[jnp.ndarray]   # bool[N, N]
    loss: Optional[jnp.ndarray]    # u8[N, N]
    delay: Optional[jnp.ndarray]   # u8[N, N]
    jitter: Optional[jnp.ndarray]  # u8[N, N]
    alive: jnp.ndarray   # i8[N]
    wipe: jnp.ndarray    # bool[N]
    seed: jnp.ndarray    # i32 scalar (see SimFaultPlan.seed)


class FactoredFaultPlan(NamedTuple):
    """Rank-1-factored fault schedule: each link event is one
    (active-rounds, src-mask, dst-mask) term instead of a [R+1, N, N]
    slab — the representation that makes 100k-node fault storms
    compilable (O(K·(R+N)) HBM).  The K axes are static shapes, so a
    class with zero factors is trace-time absent exactly like a ``None``
    matrix.  Node-level tensors (alive/wipe) stay dense [R+1, N]."""

    alive: jnp.ndarray          # i8[R+1, N]
    wipe: jnp.ndarray           # bool[R+1, N]
    seed: jnp.ndarray           # i32 scalar (see SimFaultPlan.seed)
    block_active: jnp.ndarray   # bool[Kb, R+1]
    block_src: jnp.ndarray      # bool[Kb, N]
    block_dst: jnp.ndarray      # bool[Kb, N]
    loss_active: jnp.ndarray    # bool[Kl, R+1]
    loss_src: jnp.ndarray       # bool[Kl, N]
    loss_dst: jnp.ndarray       # bool[Kl, N]
    loss_thr: jnp.ndarray       # u8[Kl] (non-overlap validated at compile)
    delay_active: jnp.ndarray   # bool[Kd, R+1]
    delay_src: jnp.ndarray      # bool[Kd, N]
    delay_dst: jnp.ndarray      # bool[Kd, N]
    delay_rounds: jnp.ndarray   # i32[Kd] (overlaps ADD, as LinkFault.merge)
    jitter_active: jnp.ndarray  # bool[Kj, R+1]
    jitter_src: jnp.ndarray     # bool[Kj, N]
    jitter_dst: jnp.ndarray     # bool[Kj, N]
    jitter_rounds: jnp.ndarray  # i32[Kj] (overlaps take the max)


class FactoredRoundFaults(NamedTuple):
    """One round's slice of a FactoredFaultPlan (the per-factor active
    bits replace the matrix slices; masks are round-independent)."""

    alive: jnp.ndarray          # i8[N]
    wipe: jnp.ndarray           # bool[N]
    seed: jnp.ndarray           # i32 scalar
    block_on: jnp.ndarray       # bool[Kb]
    block_src: jnp.ndarray      # bool[Kb, N]
    block_dst: jnp.ndarray      # bool[Kb, N]
    loss_on: jnp.ndarray        # bool[Kl]
    loss_src: jnp.ndarray       # bool[Kl, N]
    loss_dst: jnp.ndarray       # bool[Kl, N]
    loss_thr: jnp.ndarray       # u8[Kl]
    delay_on: jnp.ndarray       # bool[Kd]
    delay_src: jnp.ndarray      # bool[Kd, N]
    delay_dst: jnp.ndarray      # bool[Kd, N]
    delay_rounds: jnp.ndarray   # i32[Kd]
    jitter_on: jnp.ndarray      # bool[Kj]
    jitter_src: jnp.ndarray     # bool[Kj, N]
    jitter_dst: jnp.ndarray     # bool[Kj, N]
    jitter_rounds: jnp.ndarray  # i32[Kj]


#: auto-factor threshold: above this node count `compile_plan` lowers to
#: the factored form (the matrix form's schedule() expansion alone is
#: O(R·N²) Python at "*" selectors — already hopeless at 4096 nodes)
FACTORED_MIN_NODES = 1024


# -- per-edge fault evaluation (the ONE consumer surface) --------------------


def _factored_hits(
    on: jnp.ndarray, src_m: jnp.ndarray, dst_m: jnp.ndarray,
    src: jnp.ndarray, dst: jnp.ndarray,
) -> jnp.ndarray:
    """bool[K, E]: factor k applies to edge e this round.  Self-edges
    never fault (the matrix compiler's `_pairs` skips s == d; rank-1
    masks would otherwise cover the diagonal — probe relay legs DO
    evaluate (x, x) edges)."""
    return (
        on[:, None] & src_m[:, src] & dst_m[:, dst] & (src != dst)[None, :]
    )


def fault_edge_block(faults, src, dst):
    """bool[E] directed-cut mask at the given edges, or None when the
    plan schedules no cuts (trace-time: the kernel skips the class)."""
    if isinstance(faults, RoundFaults):
        return None if faults.block is None else faults.block[src, dst]
    if faults.block_src.shape[0] == 0:
        return None
    return _factored_hits(
        faults.block_on, faults.block_src, faults.block_dst, src, dst
    ).any(axis=0)


def fault_edge_loss(faults, src, dst):
    """u8[E] extra-loss threshold (p·256) at the given edges, or None."""
    if isinstance(faults, RoundFaults):
        return None if faults.loss is None else faults.loss[src, dst]
    if faults.loss_src.shape[0] == 0:
        return None
    hit = _factored_hits(
        faults.loss_on, faults.loss_src, faults.loss_dst, src, dst
    )
    # factors compose by MAX: overlapping loss events compile to one
    # composite factor per overlapping subset (`_compose_overlapping_
    # losses`), the maximal active subset carries the matrix-merged
    # threshold, and every other hitting factor is ≤ it by fold
    # monotonicity — so the max IS the merged threshold, bit-exactly
    return jnp.max(
        jnp.where(hit, faults.loss_thr[:, None], jnp.uint8(0)), axis=0
    )


def fault_edge_delay(faults, src, dst):
    """i32[E] extra fixed delay (rounds) at the given edges, or None.
    Overlapping delay events ADD (`LinkFault.merge`)."""
    if isinstance(faults, RoundFaults):
        if faults.delay is None:
            return None
        return faults.delay[src, dst].astype(jnp.int32)
    if faults.delay_src.shape[0] == 0:
        return None
    hit = _factored_hits(
        faults.delay_on, faults.delay_src, faults.delay_dst, src, dst
    )
    return jnp.sum(
        jnp.where(hit, faults.delay_rounds[:, None], 0), axis=0
    )


def fault_edge_jitter(faults, src, dst):
    """i32[E] max per-message extra delay at the given edges, or None.
    Overlapping jitter events take the max (`LinkFault.merge`)."""
    if isinstance(faults, RoundFaults):
        if faults.jitter is None:
            return None
        return faults.jitter[src, dst].astype(jnp.int32)
    if faults.jitter_src.shape[0] == 0:
        return None
    hit = _factored_hits(
        faults.jitter_on, faults.jitter_src, faults.jitter_dst, src, dst
    )
    return jnp.max(
        jnp.where(hit, faults.jitter_rounds[:, None], 0), axis=0
    )


def fault_wire_effects(faults, key, src, dst, n_payloads, ok, drop, delay):
    """The fire-and-forget (broadcast) fault seam, shared VERBATIM by
    the dense and packed round paths — one implementation is what makes
    their bit-identity structural rather than hand-synchronized: cuts
    mask ``ok``, extra loss ORs into ``drop`` (per-(edge, payload) u8
    threshold bits, fold_in key 101), fixed delay adds to ``delay``, and
    jitter (fold_in key 102) expands to a per-(edge, payload)
    ``delay_ep`` (None when the plan schedules no jitter).  All keys are
    fold_in-derived from the PHASE key + plan seed, never split from the
    phase stream, so a plan without a class consumes RNG identically to
    one with all-zero tensors."""
    blk = fault_edge_block(faults, src, dst)
    if blk is not None:
        ok = ok & ~blk
    thr = fault_edge_loss(faults, src, dst)  # u8[E] | None
    if thr is not None:
        from .topology import aligned_u8_bits

        k_floss = jax.random.fold_in(
            jax.random.fold_in(key, faults.seed), 101
        )
        # aligned draw (ISSUE 7): byte-identical to the raw u8 draw at
        # every 128-aligned [E, P] (all storm shapes); shard-safe always
        fbits = aligned_u8_bits(k_floss, (src.shape[0], n_payloads))
        drop = drop | (fbits < thr[:, None])
    fdelay = fault_edge_delay(faults, src, dst)  # i32[E] | None
    if fdelay is not None:
        delay = delay + fdelay
    delay_ep = None
    jit = fault_edge_jitter(faults, src, dst)  # i32[E] | None
    if jit is not None:
        k_fjit = jax.random.fold_in(
            jax.random.fold_in(key, faults.seed), 102
        )
        draw = jax.random.randint(
            k_fjit, (src.shape[0], n_payloads), 0, jnp.iinfo(jnp.int32).max
        )
        delay_ep = delay[:, None] + jnp.where(
            jit[:, None] > 0, draw % (jit[:, None] + 1), 0
        )  # [E, P]
    return ok, drop, delay, delay_ep


def fault_session_refused(faults, src, dst):
    """bool[E] (or None): the sync session is refused — a cut in EITHER
    direction kills the bidirectional stream.  Shared by both paths."""
    blk = fault_edge_block(faults, src, dst)
    if blk is None:
        return None
    return blk | fault_edge_block(faults, dst, src)


def fault_session_delay(faults, src, dst):
    """i32[E] (or None): extra sync-session RTT — the slower direction
    of the pair bounds the bi-stream.  Shared by both paths."""
    d_fwd = fault_edge_delay(faults, src, dst)
    if d_fwd is None:
        return None
    return jnp.maximum(d_fwd, fault_edge_delay(faults, dst, src))


def compile_plan(
    plan: FaultPlan,
    cfg: SimConfig,
    topo: Topology = Topology(),
    factored: Optional[bool] = None,
):
    """Lower ``plan.schedule()`` into device tensors.

    ``factored=None`` auto-selects: clusters at/above FACTORED_MIN_NODES
    lower to the rank-1 `FactoredFaultPlan` (the matrix form is O(R·N²)
    — un-materializable at storm scale); smaller clusters keep the
    proven matrix form.  Both forms produce identical per-edge fault
    decisions through the `fault_edge_*` helpers (pinned by
    tests/sim/test_fault_plan.py).

    Validates the delay-ring envelope at compile time: the ring must be
    able to represent every (topology + fault) delay, or a wrapped slot
    would deliver EARLY, silently (`round.validate`'s contract)."""
    if plan.n_nodes != cfg.n_nodes:
        raise ValueError(
            f"plan is for {plan.n_nodes} nodes, SimConfig has {cfg.n_nodes}"
        )
    if any(ev.kind == "slow" for ev in plan.events):
        # the `slow` gray failure is a WALL-CLOCK stall on a live node's
        # gated operations — the sim has no wall clock, only
        # round-denominated link delays, and a node-level stall is not a
        # link property; refusing loudly beats silently dropping the
        # event (doc/faults.md, "three-seam kind matrix")
        raise ValueError(
            "the sim tier cannot express `slow` (wall-clock node stall); "
            "replay it on the host or devcluster seam"
        )
    if factored is None:
        factored = cfg.n_nodes >= FACTORED_MIN_NODES
    if factored:
        return compile_plan_factored(plan, cfg, topo)
    n, rounds = plan.n_nodes, plan.horizon
    shape = (rounds + 1, n, n)
    block = np.zeros(shape, np.bool_)
    loss = np.zeros(shape, np.uint8)
    delay = np.zeros(shape, np.uint8)
    jitter = np.zeros(shape, np.uint8)
    alive = np.full((rounds + 1, n), -1, np.int8)
    wipe = np.zeros((rounds + 1, n), np.bool_)

    max_extra = 0
    for r, sched in enumerate(plan.schedule()):
        for (s, d), f in sched.links.items():
            if f is CLEAR:
                continue
            thr = int(round(f.loss * 256.0))
            if f.blocked or thr >= 256:
                # certainty can't ride the u8 threshold: sever the edge
                block[r, s, d] = True
            elif thr > 0:
                loss[r, s, d] = thr
            if f.delay_rounds > 255 or f.jitter_rounds > 255:
                # the u8 tensors can't carry it, and a silent clamp
                # would diverge from the factored form's exact sum
                raise ValueError(
                    f"merged link delay/jitter ({f.delay_rounds}/"
                    f"{f.jitter_rounds} rounds at round {r}) exceeds the "
                    "255-round schedule grain"
                )
            delay[r, s, d] = f.delay_rounds
            jitter[r, s, d] = f.jitter_rounds
            max_extra = max(max_extra, f.delay_rounds + f.jitter_rounds)
        for i in sched.down:
            alive[r, i] = DOWN
        for i in sched.restart:
            alive[r, i] = ALIVE
        for i in sched.wipe:
            wipe[r, i] = True

    base = max(topo.max_delay, 1)
    if base + max_extra >= cfg.n_delay_slots:
        raise ValueError(
            f"max edge delay {base + max_extra} rounds (topology {base} + "
            f"fault {max_extra}) needs n_delay_slots > {base + max_extra}, "
            f"got {cfg.n_delay_slots}"
        )
    from ..faults import derive_seed

    return SimFaultPlan(
        # absent classes ride as None (pytree structure = trace-time
        # fact): the kernels then skip the class's gathers/draws — same
        # results as all-zero tensors, none of the cost
        block=jnp.asarray(block) if block.any() else None,
        loss=jnp.asarray(loss) if loss.any() else None,
        delay=jnp.asarray(delay) if delay.any() else None,
        jitter=jnp.asarray(jitter) if jitter.any() else None,
        alive=jnp.asarray(alive), wipe=jnp.asarray(wipe),
        seed=jnp.int32(derive_seed(plan.seed, "sim") & 0x7FFFFFFF),
    )


def _sel_mask(sel, n: int) -> np.ndarray:
    from ..faults import sel_indices

    m = np.zeros(n, np.bool_)
    r = sel_indices(sel, n)
    m[r.start:r.stop] = True
    return m


def _events_overlap(a, b, n: int) -> bool:
    """Can events a and b affect the same (round, directed link)?"""
    from ..faults import sel_indices

    if a.end <= b.start or b.end <= a.start:
        return False

    def hits(x, y):
        return max(x.start, y.start) < min(x.stop, y.stop)

    return hits(sel_indices(a.src, n), sel_indices(b.src, n)) and hits(
        sel_indices(a.dst, n), sel_indices(b.dst, n)
    )


#: largest mutually-overlapping loss-event set the factored compiler
#: composes exactly: the composition emits one rank-1 factor per
#: pairwise-overlapping SUBSET (2^k - k - 1 composites for a k-clique),
#: so an adversarial plan must not explode compile time.  Above the cap
#: the compiler refuses loudly — compile with ``factored=False`` (the
#: matrix form has no restriction; at ≥1024 nodes that fallback is the
#: documented O(R·N²) cost the refusal message names).
MAX_OVERLAPPING_LOSS = 8


def _compose_overlapping_losses(losses, loss_events, blocks, n: int) -> None:
    """EXACT integer composition of overlapping loss events (ISSUE 13
    satellite, closing the PR 4 carried edge).

    The matrix compiler merges concurrent losses per (round, link) as
    independent drops — a float64 fold of ``1-(1-a)(1-b)`` in
    plan-event order — and quantizes ONCE at the end
    (``int(round(p·256))``).  That merged u8 is not a function of the
    per-event u8 thresholds, which is why the factored form used to
    refuse overlapping losses outright.

    The composition that IS rank-1 exact: for every pairwise-
    overlapping subset S of loss events, emit one composite factor
    whose window/rectangle is the subset's intersection (selectors are
    contiguous ranges, so 1-D Helly gives pairwise ⇒ joint) and whose
    threshold is the SAME plan-order float64 fold the matrix compiler
    computes, quantized the same way.  `fault_edge_loss` composes
    factors by MAX: at any (round, edge) the hitting factors are
    exactly the subsets of the active covering set A, the S = A
    composite carries the matrix-merged threshold, and every proper
    subset's fold is ≤ it (the fold is monotone in adding events, and
    round is monotone) — so max == the matrix value, bit-exactly.
    A composite that folds to certainty (p·256 ≥ 256) lowers to a cut,
    the same rule a single p≈1 event follows."""
    k = len(loss_events)
    if k < 2:
        return
    # overlap graph: DFS extends a combo ONLY by events overlapping
    # every member, so the walk touches exactly the pairwise-
    # overlapping subsets — a plan of many DISJOINT loss events (e.g.
    # topology_link_events rectangles) costs O(k²) like the old check,
    # never 2^k
    neighbors = [
        {
            j
            for j in range(k)
            if j != i and _events_overlap(loss_events[i], loss_events[j], n)
        }
        for i in range(k)
    ]

    def _emit(combo):
        act = np.logical_and.reduce([losses[i][0] for i in combo])
        sm = np.logical_and.reduce([losses[i][1] for i in combo])
        dm = np.logical_and.reduce([losses[i][2] for i in combo])
        if not (act.any() and sm.any() and dm.any()):
            return
        # the matrix compiler's fold, verbatim: plan-event order,
        # float64, quantized once (LinkFault.merge's loss rule)
        p = 0.0
        for i in combo:
            p = 1.0 - (1.0 - p) * (1.0 - loss_events[i].p)
        thr = int(round(p * 256.0))
        if thr >= 256:
            blocks.append((act, sm, dm))
        elif thr > 0:
            losses.append((act, sm, dm, thr))

    def _extend(combo, cands):
        if not cands:
            return
        if len(combo) >= MAX_OVERLAPPING_LOSS:
            raise ValueError(
                f"factored loss composition caps at "
                f"{MAX_OVERLAPPING_LOSS} mutually-overlapping loss "
                "events (subset composition is exponential in the "
                "clique size); compile with factored=False — the "
                "matrix form handles any overlap at O(R·N²) memory"
            )
        for j in sorted(cands):
            grown = combo + (j,)
            if len(grown) >= 2:
                _emit(grown)
            _extend(
                grown, {c for c in cands if c > j and c in neighbors[j]}
            )

    _extend((), set(range(k)))


def compile_plan_factored(
    plan: FaultPlan, cfg: SimConfig, topo: Topology = Topology()
) -> FactoredFaultPlan:
    """Lower the plan into rank-1 link-event factors, straight from the
    events (never via ``schedule()`` — its per-round dict is O(N²) at
    "*" selectors).  Semantics match the matrix compiler exactly: block
    ORs, delays add, jitter maxes, loss p≈1 compiles to a cut; the one
    restriction is that 0<p<1 loss events must not overlap on a (round,
    link) — combined-drop quantization (1-∏(1-pᵢ) → u8) is not
    factorable bit-exactly, so the compiler refuses loudly rather than
    approximate."""
    if plan.n_nodes != cfg.n_nodes:
        raise ValueError(
            f"plan is for {plan.n_nodes} nodes, SimConfig has {cfg.n_nodes}"
        )
    if any(ev.kind == "slow" for ev in plan.events):
        # same refusal as compile_plan (direct callers bypass it): a
        # wall-clock node stall has no tensor lowering
        raise ValueError(
            "the sim tier cannot express `slow` (wall-clock node stall); "
            "replay it on the host or devcluster seam"
        )
    n, rounds = plan.n_nodes, plan.horizon
    alive = np.full((rounds + 1, n), -1, np.int8)
    wipe = np.zeros((rounds + 1, n), np.bool_)
    blocks, losses, delays, jitters = [], [], [], []
    loss_events = []

    def _act(ev):
        a = np.zeros(rounds + 1, np.bool_)
        a[ev.start:ev.end] = True
        return a

    from ..faults import sel_indices

    crash_events = [ev for ev in plan.events if ev.kind == "crash"]
    # two passes mirror the matrix compiler's per-round down-then-restart
    # write order (overlapping crash windows: the restart wins the round);
    # crash targets may be "lo:hi" range selectors (ISSUE 9 churn) —
    # sel_indices ranges are contiguous, so each event is one numpy slice
    for ev in crash_events:
        sel = sel_indices(ev.node, n)
        alive[ev.start:ev.end, sel.start:sel.stop] = DOWN
    for ev in crash_events:
        sel = sel_indices(ev.node, n)
        alive[ev.end, sel.start:sel.stop] = ALIVE
        if ev.wipe:
            wipe[ev.end, sel.start:sel.stop] = True

    for ev in plan.events:
        if ev.kind in ("crash", "clock_skew", "duplicate"):
            # crash handled above; clock_skew is host-only; duplicate is
            # a sim no-op (idempotent scatter-max delivery) — coverage
            # markers still fire via schedule_at on the checked tier
            continue
        term = (_act(ev), _sel_mask(ev.src, n), _sel_mask(ev.dst, n))
        if ev.kind == "partition":
            blocks.append(term)
            if ev.symmetric:
                blocks.append((term[0], term[2], term[1]))
        elif ev.kind == "loss":
            thr = int(round(ev.p * 256.0))
            if thr >= 256:
                blocks.append(term)  # certainty can't ride u8: sever
            elif thr > 0:
                losses.append(term + (thr,))
                loss_events.append(ev)
        elif ev.kind == "delay":
            delays.append(term + (ev.delay_rounds,))
        elif ev.kind == "jitter":
            jitters.append(term + (ev.delay_rounds,))

    _compose_overlapping_losses(losses, loss_events, blocks, n)

    # ring-envelope validation: per round, a link's worst extra delay is
    # the sum of the delay events covering it — bounded here by, for
    # each active event, its delay plus every other active event it can
    # share a (round, link) with (pairwise selector intersection).
    # Exact when concurrent events either share links or are disjoint;
    # never looser than the matrix compiler's per-link max, and never
    # rejects a plan of pairwise-disjoint delays the matrix form accepts.
    delay_events = [ev for ev in plan.events if ev.kind == "delay"]
    max_extra = 0
    for r in range(rounds + 1):
        active = [ev for ev in delay_events if ev.start <= r < ev.end]
        d = max(
            (
                ev.delay_rounds
                + sum(
                    o.delay_rounds for o in active
                    if o is not ev and _events_overlap(ev, o, n)
                )
                for ev in active
            ),
            default=0,
        )
        j = max(
            (ev.delay_rounds for ev in plan.events
             if ev.kind == "jitter" and ev.start <= r < ev.end),
            default=0,
        )
        max_extra = max(max_extra, d + j)
    base = max(topo.max_delay, 1)
    if base + max_extra >= cfg.n_delay_slots:
        raise ValueError(
            f"max edge delay {base + max_extra} rounds (topology {base} + "
            f"fault {max_extra}) needs n_delay_slots > {base + max_extra}, "
            f"got {cfg.n_delay_slots}"
        )

    def _stack(terms, extra_dtype=None):
        k = len(terms)
        act = np.zeros((k, rounds + 1), np.bool_)
        sm = np.zeros((k, n), np.bool_)
        dm = np.zeros((k, n), np.bool_)
        vals = np.zeros((k,), extra_dtype) if extra_dtype else None
        for i, t in enumerate(terms):
            act[i], sm[i], dm[i] = t[0], t[1], t[2]
            if extra_dtype:
                vals[i] = t[3]
        out = [jnp.asarray(act), jnp.asarray(sm), jnp.asarray(dm)]
        if extra_dtype:
            out.append(jnp.asarray(vals))
        return out

    from ..faults import derive_seed

    b_act, b_src, b_dst = _stack(blocks)
    l_act, l_src, l_dst, l_thr = _stack(losses, np.uint8)
    d_act, d_src, d_dst, d_val = _stack(delays, np.int32)
    j_act, j_src, j_dst, j_val = _stack(jitters, np.int32)
    return FactoredFaultPlan(
        alive=jnp.asarray(alive), wipe=jnp.asarray(wipe),
        seed=jnp.int32(derive_seed(plan.seed, "sim") & 0x7FFFFFFF),
        block_active=b_act, block_src=b_src, block_dst=b_dst,
        loss_active=l_act, loss_src=l_src, loss_dst=l_dst, loss_thr=l_thr,
        delay_active=d_act, delay_src=d_src, delay_dst=d_dst,
        delay_rounds=d_val,
        jitter_active=j_act, jitter_src=j_src, jitter_dst=j_dst,
        jitter_rounds=j_val,
    )


def round_faults(fplan, t: jnp.ndarray):
    """Slice round ``t``'s fault state; past the horizon every round
    reads the final all-clear row (index clamp, not wraparound)."""
    i = jnp.minimum(t, fplan.alive.shape[0] - 1)
    if isinstance(fplan, FactoredFaultPlan):
        return FactoredRoundFaults(
            alive=fplan.alive[i], wipe=fplan.wipe[i], seed=fplan.seed,
            block_on=fplan.block_active[:, i],
            block_src=fplan.block_src, block_dst=fplan.block_dst,
            loss_on=fplan.loss_active[:, i],
            loss_src=fplan.loss_src, loss_dst=fplan.loss_dst,
            loss_thr=fplan.loss_thr,
            delay_on=fplan.delay_active[:, i],
            delay_src=fplan.delay_src, delay_dst=fplan.delay_dst,
            delay_rounds=fplan.delay_rounds,
            jitter_on=fplan.jitter_active[:, i],
            jitter_src=fplan.jitter_src, jitter_dst=fplan.jitter_dst,
            jitter_rounds=fplan.jitter_rounds,
        )
    return RoundFaults(
        block=None if fplan.block is None else fplan.block[i],
        loss=None if fplan.loss is None else fplan.loss[i],
        delay=None if fplan.delay is None else fplan.delay[i],
        jitter=None if fplan.jitter is None else fplan.jitter[i],
        alive=fplan.alive[i], wipe=fplan.wipe[i],
        seed=fplan.seed,
    )


def apply_node_faults(state: SimState, rf: RoundFaults) -> SimState:
    """Crash/restart/wipe, applied BEFORE the round's phases: the alive
    override makes `edge_alive` mask the node's edges this very round,
    and a wipe zeroes everything the node 'knew' — chunk bits, relay
    budgets, in-flight deliveries addressed to it, the advertised
    bookkeeping tensors (heads/gaps), AND its own membership beliefs
    (full-view row back to the all-ALIVE init; partial-view table to
    EMPTY, so the announce/refill/gossip paths must repopulate it) — so
    the node rejoins as a cold joiner and must recover purely via
    anti-entropy (the crash-with-state-wipe shape of the reference's
    restore campaign).  Other nodes' beliefs ABOUT the wiped node are
    untouched: refutation/rejoin heals them, as on the host tier."""
    alive = jnp.where(
        rf.alive >= 0, rf.alive.astype(state.alive.dtype), state.alive
    )
    w = rf.wipe
    wn = w[:, None]
    state = state._replace(
        alive=alive,
        have=jnp.where(wn, 0, state.have),
        relay_left=jnp.where(wn, 0, state.relay_left),
        sync_inflight=jnp.where(w[None, :, None], 0, state.sync_inflight),
        inflight=jnp.where(w[None, :, None], 0, state.inflight),
        heads=jnp.where(wn, 0, state.heads),
        gap_lo=jnp.where(w[:, None, None], 0, state.gap_lo),
        gap_hi=jnp.where(w[:, None, None], 0, state.gap_hi),
    )
    if state.view.size:  # full-view SWIM: row back to the optimistic init
        state = state._replace(
            view=jnp.where(wn, jnp.int8(0), state.view),
            vinc=jnp.where(wn, 0, state.vinc),
            suspect_since=jnp.where(wn, -1, state.suspect_since),
        )
    if state.pid.size:  # partial-view SWIM: member table emptied
        state = state._replace(
            pid=jnp.where(wn, -1, state.pid),
            pkey=jnp.where(wn, -1, state.pkey),
            psince=jnp.where(wn, -1, state.psince),
        )
    if state.pview.size:  # PeerSwap view (ISSUE 9): wiped to empty —
        # the rejoiner repopulates via incoming swaps + staggered refill
        state = state._replace(pview=jnp.where(wn, -1, state.pview))
    return state


def _all_have(state: SimState, meta: PayloadMeta, cfg: SimConfig) -> jnp.ndarray:
    """bool: every up node holds every injected version completely (the
    check_bookkeeping property, computed FRESH — `metrics.converged_at`
    is sticky and a post-convergence wipe must un-converge the node)."""
    up = state.alive == ALIVE
    comp = complete_versions(state.have, cfg)
    act = version_active(state.injected, cfg)
    node_done = jnp.all(comp | ~act[None], axis=(1, 2)) | ~up
    return jnp.all(meta.round <= state.t) & jnp.all(node_done)


@functools.partial(
    jax.jit, static_argnames=("cfg", "topo", "max_rounds", "telemetry", "mesh")
)
def run_fault_plan(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    fplan,
    max_rounds: int = 1000,
    telemetry: bool = False,
    mesh=None,
):
    """Advance rounds under the fault schedule until the cluster holds
    every payload AND the schedule is exhausted (a plan may crash a node
    after convergence — early exit would miss the rejoin), or
    ``max_rounds``.  Over the bitpack envelope (`packed.packed_supported`)
    the loop runs on the u32-packed carry — the fault seam rides the
    packed kernels since ISSUE 4, bit-identical to the dense path
    (tests/sim/test_packed_equivalence.py); cfg/topo are static, so the
    dispatch is a trace-time branch and one path compiles.

    ``telemetry=True`` (static) threads a `telemetry.RoundTrace` through
    the loop — including the fault-seam crash/wipe channels — and
    returns (state, metrics, trace); False compiles to exactly the
    pre-telemetry program.

    ``mesh`` (static) shards the node axis across a 1-D ``nodes`` mesh
    (ISSUE 7): callers place state with `parallel.mesh.shard_state` and
    the compiled plan with `parallel.mesh.shard_fault_plan`; the packed
    loop re-pins the word-carry layout per round.  Bit-identical to
    single-device (tests/sim/test_packed_sharded.py)."""
    from .packed import packed_supported, run_packed_faults

    if packed_supported(cfg, topo):
        return run_packed_faults(
            state, meta, cfg, topo, fplan, max_rounds, telemetry, mesh=mesh
        )
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    horizon = fplan.alive.shape[0] - 1  # static

    def _done(state):
        return (state.t >= horizon) & _all_have(state, meta, cfg)

    def cond(carry):
        return (carry[0].t < max_rounds) & ~carry[2]

    # per-lane done flag in the carry (ISSUE 7 satellite; see
    # round.run_to_convergence): O(1) cond, frozen converged lanes
    if telemetry:
        from .telemetry import new_trace, record_node_faults

        def body(carry):
            state, metrics, _, trace = carry
            rf = round_faults(fplan, state.t)
            trace = record_node_faults(trace, state.t, rf, every=cfg.trace_every)
            state = apply_node_faults(state, rf)
            state, metrics, trace = round_step(
                state, metrics, meta, cfg, topo, region, faults=rf,
                trace=trace,
            )
            return state, metrics, _done(state), trace

        state, metrics, _, trace = jax.lax.while_loop(
            cond, body,
            (state, metrics, _done(state), new_trace(cfg, max_rounds)),
        )
        return state, metrics, trace

    def body(carry):
        state, metrics, _ = carry
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        state, metrics = round_step(
            state, metrics, meta, cfg, topo, region, faults=rf
        )
        return state, metrics, _done(state)

    state, metrics, _ = jax.lax.while_loop(
        cond, body, (state, metrics, _done(state))
    )
    return state, metrics


def run_fault_plan_checked(
    plan: FaultPlan,
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology = Topology(),
    max_rounds: int = 1000,
    check_every: int = 1,
    catalog=None,
) -> Tuple[SimState, RunMetrics, list]:
    """The test-tier driver: same schedule, Python round loop, with the
    sim invariant catalog (`sim.invariants.check_state`) asserted every
    ``check_every`` rounds and the plan's `sometimes` coverage markers
    fired as scheduled faults take effect.  Returns (state, metrics,
    digests) where ``digests`` is a per-round fingerprint of the fault
    decisions + resulting state — two runs from the same seed must
    produce identical digest sequences (the replay-determinism
    contract)."""
    import hashlib

    from ..faults import CATALOG
    from .invariants import check_state

    catalog = catalog or CATALOG
    fplan = compile_plan(plan, cfg, topo)
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    digests = []
    for r in range(max_rounds):
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        sched = plan.schedule_at(min(r, plan.horizon))
        for kind in sched.active_kinds():
            catalog.sometimes(True, f"fault-{kind}-active")
        state, metrics = round_step(
            state, metrics, meta, cfg, topo, region, faults=rf
        )
        h = hashlib.blake2b(digest_size=8)
        h.update(np.asarray(state.have).tobytes())
        h.update(np.asarray(state.alive).tobytes())
        h.update(np.asarray(state.heads).tobytes())
        digests.append(h.hexdigest())
        if r % check_every == 0:
            check_state(state, cfg)
        if r >= plan.horizon and bool(_all_have(state, meta, cfg)):
            break
    return state, metrics, digests
