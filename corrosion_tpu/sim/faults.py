"""FaultPlan → sim tensors: the device backend of the unified fault seam.

`corrosion_tpu.faults.FaultPlan.schedule()` is the single source of
truth; this module lowers that per-round table into stacked mask/delay
tensors indexed by ``state.t`` and threads them through the round
kernels (broadcast / sync / SWIM reachability), extending the existing
DOWN/latency-class machinery:

- ``block[R+1, N, N] bool`` — directed edge cut (asymmetric partitions:
  block[r, a, b] stops a→b while b→a still flows);
- ``loss[R+1, N, N] u8``   — extra per-link drop threshold (p·256, the
  same 8-bit quantization as `topology.edge_payload_drop`); a loss of
  ~1.0 compiles into ``block`` instead (a u8 threshold cannot express
  certainty);
- ``delay/jitter[R+1, N, N] u8`` — extra delivery delay in rounds:
  fixed + uniform 0..jitter drawn per (edge, PAYLOAD) — each changeset
  rides its own uni frame (the edge_payload_drop grain), so jitter
  reorders traffic within a single flush exactly like the host tier's
  per-message draw; fault latency also stretches sync delivery (the
  bi-stream RTT rides the sync delay ring, slower direction wins);
- ``alive[R+1, N] i8``     — scheduled alive override (-1 = leave to
  the scenario; ALIVE/DOWN during crash windows and at restart);
- ``wipe[R+1, N] bool``    — the restart round of a crash with
  ``wipe=True``: the node's ``have``/relay/inflight/bookkeeping rows
  are zeroed, so it rejoins empty and must recover via anti-entropy.

Row ``R`` (one past the last scheduled round) is all-clear by
construction, and `round_faults` clamps its index there — after the
horizon the sim runs fault-free, the steady state convergence is
measured in.

Tier coverage caveats (doc/faults.md): ``duplicate`` compiles to a
no-op here — sim delivery is an idempotent scatter-max, so a duplicated
payload is indistinguishable from the original (the host tier delivers
it twice and the dedup cache absorbs it); ``clock_skew`` is host-only —
the sim carries no HLC.  Both still count toward schedule coverage via
the plan's markers, fired by `run_fault_plan_checked`.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..faults import CLEAR, FaultPlan
from .round import RunMetrics, new_metrics, round_step
from .state import (
    ALIVE,
    DOWN,
    PayloadMeta,
    SimConfig,
    SimState,
    complete_versions,
    version_active,
)
from .topology import Topology, regions


class SimFaultPlan(NamedTuple):
    """Stacked per-round fault tensors (device); index with `round_faults`."""

    block: jnp.ndarray   # bool[R+1, N, N] directed src→dst cut
    loss: jnp.ndarray    # u8[R+1, N, N] extra drop threshold (p·256)
    delay: jnp.ndarray   # u8[R+1, N, N] fixed extra delay, rounds
    jitter: jnp.ndarray  # u8[R+1, N, N] max per-message extra delay, rounds
    alive: jnp.ndarray   # i8[R+1, N] override: -1 none, else ALIVE/DOWN
    wipe: jnp.ndarray    # bool[R+1, N] zero the node's state this round
    # plan-seed fold (derive_seed(seed, "sim")): every stochastic fault
    # draw folds this in, so the PLAN seed — not just the scenario's
    # PRNG key — determines the per-round fault decisions, mirroring the
    # host tier where the plan seed derives every LinkModel stream
    seed: jnp.ndarray    # i32 scalar


class RoundFaults(NamedTuple):
    """One round's slice of a SimFaultPlan, consumed by the kernels."""

    block: jnp.ndarray   # bool[N, N]
    loss: jnp.ndarray    # u8[N, N]
    delay: jnp.ndarray   # u8[N, N]
    jitter: jnp.ndarray  # u8[N, N]
    alive: jnp.ndarray   # i8[N]
    wipe: jnp.ndarray    # bool[N]
    seed: jnp.ndarray    # i32 scalar (see SimFaultPlan.seed)


def compile_plan(
    plan: FaultPlan, cfg: SimConfig, topo: Topology = Topology()
) -> SimFaultPlan:
    """Lower ``plan.schedule()`` into device tensors.

    Validates the delay-ring envelope at compile time: the ring must be
    able to represent every (topology + fault) delay, or a wrapped slot
    would deliver EARLY, silently (`round.validate`'s contract)."""
    if plan.n_nodes != cfg.n_nodes:
        raise ValueError(
            f"plan is for {plan.n_nodes} nodes, SimConfig has {cfg.n_nodes}"
        )
    n, rounds = plan.n_nodes, plan.horizon
    shape = (rounds + 1, n, n)
    block = np.zeros(shape, np.bool_)
    loss = np.zeros(shape, np.uint8)
    delay = np.zeros(shape, np.uint8)
    jitter = np.zeros(shape, np.uint8)
    alive = np.full((rounds + 1, n), -1, np.int8)
    wipe = np.zeros((rounds + 1, n), np.bool_)

    max_extra = 0
    for r, sched in enumerate(plan.schedule()):
        for (s, d), f in sched.links.items():
            if f is CLEAR:
                continue
            thr = int(round(f.loss * 256.0))
            if f.blocked or thr >= 256:
                # certainty can't ride the u8 threshold: sever the edge
                block[r, s, d] = True
            elif thr > 0:
                loss[r, s, d] = thr
            delay[r, s, d] = min(f.delay_rounds, 255)
            jitter[r, s, d] = min(f.jitter_rounds, 255)
            max_extra = max(max_extra, f.delay_rounds + f.jitter_rounds)
        for i in sched.down:
            alive[r, i] = DOWN
        for i in sched.restart:
            alive[r, i] = ALIVE
        for i in sched.wipe:
            wipe[r, i] = True

    base = max(topo.intra_delay, topo.inter_delay, 1)
    if base + max_extra >= cfg.n_delay_slots:
        raise ValueError(
            f"max edge delay {base + max_extra} rounds (topology {base} + "
            f"fault {max_extra}) needs n_delay_slots > {base + max_extra}, "
            f"got {cfg.n_delay_slots}"
        )
    from ..faults import derive_seed

    return SimFaultPlan(
        block=jnp.asarray(block), loss=jnp.asarray(loss),
        delay=jnp.asarray(delay), jitter=jnp.asarray(jitter),
        alive=jnp.asarray(alive), wipe=jnp.asarray(wipe),
        seed=jnp.int32(derive_seed(plan.seed, "sim") & 0x7FFFFFFF),
    )


def round_faults(fplan: SimFaultPlan, t: jnp.ndarray) -> RoundFaults:
    """Slice round ``t``'s fault state; past the horizon every round
    reads the final all-clear row (index clamp, not wraparound)."""
    i = jnp.minimum(t, fplan.block.shape[0] - 1)
    return RoundFaults(
        block=fplan.block[i], loss=fplan.loss[i], delay=fplan.delay[i],
        jitter=fplan.jitter[i], alive=fplan.alive[i], wipe=fplan.wipe[i],
        seed=fplan.seed,
    )


def apply_node_faults(state: SimState, rf: RoundFaults) -> SimState:
    """Crash/restart/wipe, applied BEFORE the round's phases: the alive
    override makes `edge_alive` mask the node's edges this very round,
    and a wipe zeroes everything the node 'knew' — chunk bits, relay
    budgets, in-flight deliveries addressed to it, the advertised
    bookkeeping tensors (heads/gaps), AND its own membership beliefs
    (full-view row back to the all-ALIVE init; partial-view table to
    EMPTY, so the announce/refill/gossip paths must repopulate it) — so
    the node rejoins as a cold joiner and must recover purely via
    anti-entropy (the crash-with-state-wipe shape of the reference's
    restore campaign).  Other nodes' beliefs ABOUT the wiped node are
    untouched: refutation/rejoin heals them, as on the host tier."""
    alive = jnp.where(
        rf.alive >= 0, rf.alive.astype(state.alive.dtype), state.alive
    )
    w = rf.wipe
    wn = w[:, None]
    state = state._replace(
        alive=alive,
        have=jnp.where(wn, 0, state.have),
        relay_left=jnp.where(wn, 0, state.relay_left),
        sync_inflight=jnp.where(w[None, :, None], 0, state.sync_inflight),
        inflight=jnp.where(w[None, :, None], 0, state.inflight),
        heads=jnp.where(wn, 0, state.heads),
        gap_lo=jnp.where(w[:, None, None], 0, state.gap_lo),
        gap_hi=jnp.where(w[:, None, None], 0, state.gap_hi),
    )
    if state.view.size:  # full-view SWIM: row back to the optimistic init
        state = state._replace(
            view=jnp.where(wn, jnp.int8(0), state.view),
            vinc=jnp.where(wn, 0, state.vinc),
            suspect_since=jnp.where(wn, -1, state.suspect_since),
        )
    if state.pid.size:  # partial-view SWIM: member table emptied
        state = state._replace(
            pid=jnp.where(wn, -1, state.pid),
            pkey=jnp.where(wn, -1, state.pkey),
            psince=jnp.where(wn, -1, state.psince),
        )
    return state


def _all_have(state: SimState, meta: PayloadMeta, cfg: SimConfig) -> jnp.ndarray:
    """bool: every up node holds every injected version completely (the
    check_bookkeeping property, computed FRESH — `metrics.converged_at`
    is sticky and a post-convergence wipe must un-converge the node)."""
    up = state.alive == ALIVE
    comp = complete_versions(state.have, cfg)
    act = version_active(state.injected, cfg)
    node_done = jnp.all(comp | ~act[None], axis=(1, 2)) | ~up
    return jnp.all(meta.round <= state.t) & jnp.all(node_done)


@functools.partial(jax.jit, static_argnames=("cfg", "topo", "max_rounds"))
def run_fault_plan(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    fplan: SimFaultPlan,
    max_rounds: int = 1000,
) -> Tuple[SimState, RunMetrics]:
    """Advance rounds under the fault schedule until the cluster holds
    every payload AND the schedule is exhausted (a plan may crash a node
    after convergence — early exit would miss the rejoin), or
    ``max_rounds``.  Always the DENSE round path: the packed kernels
    don't carry the fault seam (doc/faults.md)."""
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    horizon = fplan.block.shape[0] - 1  # static

    def cond(carry):
        state, metrics = carry
        done = (state.t >= horizon) & _all_have(state, meta, cfg)
        return (state.t < max_rounds) & ~done

    def body(carry):
        state, metrics = carry
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        return round_step(state, metrics, meta, cfg, topo, region, faults=rf)

    return jax.lax.while_loop(cond, body, (state, metrics))


def run_fault_plan_checked(
    plan: FaultPlan,
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology = Topology(),
    max_rounds: int = 1000,
    check_every: int = 1,
    catalog=None,
) -> Tuple[SimState, RunMetrics, list]:
    """The test-tier driver: same schedule, Python round loop, with the
    sim invariant catalog (`sim.invariants.check_state`) asserted every
    ``check_every`` rounds and the plan's `sometimes` coverage markers
    fired as scheduled faults take effect.  Returns (state, metrics,
    digests) where ``digests`` is a per-round fingerprint of the fault
    decisions + resulting state — two runs from the same seed must
    produce identical digest sequences (the replay-determinism
    contract)."""
    import hashlib

    from ..faults import CATALOG
    from .invariants import check_state

    catalog = catalog or CATALOG
    fplan = compile_plan(plan, cfg, topo)
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)
    digests = []
    for r in range(max_rounds):
        rf = round_faults(fplan, state.t)
        state = apply_node_faults(state, rf)
        sched = plan.schedule_at(min(r, plan.horizon))
        for kind in sched.active_kinds():
            catalog.sometimes(True, f"fault-{kind}-active")
        state, metrics = round_step(
            state, metrics, meta, cfg, topo, region, faults=rf
        )
        h = hashlib.blake2b(digest_size=8)
        h.update(np.asarray(state.have).tobytes())
        h.update(np.asarray(state.alive).tobytes())
        h.update(np.asarray(state.heads).tobytes())
        digests.append(h.hexdigest())
        if r % check_every == 0:
            check_state(state, cfg)
        if r >= plan.horizon and bool(_all_have(state, meta, cfg)):
            break
    return state, metrics, digests
