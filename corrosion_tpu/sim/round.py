"""The round step: one gossip tick for the whole cluster, jit-compiled.

Composition per round t (order matters — intra-region delay 0 means
same-round delivery, so delivery pops after send):

    inject → broadcast → sync → deliver(slot t) → SWIM → convergence record

The run driver is a `lax.while_loop` over rounds with a convergence
early-exit, so an entire simulation (the reference's minutes of wall-clock
per convergence experiment) is ONE XLA computation on device.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .broadcast import broadcast_step, deliver_step, inject_step
from .gaps import extract_gaps
from .profile import phase_scope
from .state import (
    ALIVE,
    PayloadMeta,
    SimConfig,
    SimState,
    complete_versions,
    init_state,
    touched_versions,
    version_active,
    version_heads,
    grid_to_payload,
)
from .swim import swim_step
from .sync import sync_step
from .topology import Topology, regions


class RunMetrics(NamedTuple):
    """Per-run convergence record (device)."""

    coverage_at: jnp.ndarray  # i32[P] round when payload's VERSION was applied cluster-wide
    converged_at: jnp.ndarray  # i32[N] round when node applied all active versions
    # f32 scalar: max over rounds of the fraction of (node, actor) pairs
    # whose gap run-count exceeded the fixed K slots (the clamp path,
    # gaps.py:78-85) — config #5b reports this so K-overflow distortion
    # is measured, not assumed away (VERDICT r2 weak #4)
    overflow_frac: jnp.ndarray
    # i32 scalar: running total of delivery-order invariant violations
    # (ISSUE 11; `invariants.order_violation_count`, accumulated inside
    # the jitted loops on ordering variants — zero host syncs).  Stays
    # the constant 0 on ordering="none" (a trace-time branch): the
    # default protocol pays nothing and existing digests stand.
    order_violations: jnp.ndarray


def new_metrics(cfg: SimConfig) -> RunMetrics:
    return RunMetrics(
        coverage_at=jnp.full((cfg.n_payloads,), -1, jnp.int32),
        converged_at=jnp.full((cfg.n_nodes,), -1, jnp.int32),
        overflow_frac=jnp.zeros((), jnp.float32),
        order_violations=jnp.zeros((), jnp.int32),
    )


def validate(cfg: SimConfig, topo: Topology) -> None:
    """Trace-time sanity: the delay ring must be able to represent every
    edge delay (a wrapped slot delivers EARLY, silently) — the AZ tier
    included since ISSUE 9 — and the heterogeneous degree classes must
    fit inside the fan-out slot count (a class above it would silently
    clamp, not expand)."""
    max_delay = max(topo.max_delay, 1)  # sync uses t+1
    if max_delay >= cfg.n_delay_slots:
        raise ValueError(
            f"max edge delay {max_delay} rounds needs n_delay_slots > "
            f"{max_delay}, got {cfg.n_delay_slots}"
        )
    if topo.degree_classes and max(topo.degree_classes) > cfg.fanout:
        raise ValueError(
            f"degree_classes {topo.degree_classes} exceed fanout="
            f"{cfg.fanout}; degree caps mask fan-out slots, they cannot "
            "add slots"
        )


def round_step(
    state: SimState,
    metrics: RunMetrics,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    region: jnp.ndarray,
    faults=None,
    trace=None,
):
    """``faults`` (a `sim.faults.RoundFaults` slice, or None) threads
    the FaultPlan seam through every phase: directed edge cuts, extra
    per-link loss, delay/jitter on the fire-and-forget paths, and SWIM
    probe reachability.  The None path is byte-identical to the
    pre-fault kernels — fault keys are `fold_in`-derived inside the
    ``faults is not None`` trace branch, never split from the phase
    keys, so existing seeded runs replay unchanged.

    ``trace`` (a `sim.telemetry.RoundTrace`, or None) is the flight-
    recorder seam: when given, the phases report wire telemetry, row
    ``state.t`` is written via indexed updates, and the return grows to
    (state, metrics, trace).  Telemetry consumes NO RNG and feeds
    nothing back into the round, so the trace=None path compiles to
    exactly the pre-telemetry kernel."""
    validate(cfg, topo)
    if cfg.peer_sampler == "peerswap":
        # the swap tick consumes its own key via a trace-time branch —
        # uniform scenarios split exactly as before (byte-identity)
        key, k_bcast, k_sync, k_swim, k_swap = jax.random.split(
            state.key, 5
        )
    else:
        key, k_bcast, k_sync, k_swim = jax.random.split(state.key, 4)
    state = state._replace(key=key)
    if cfg.peer_sampler == "peerswap":
        # PeerSwap view mixing (ISSUE 9) runs BEFORE the phases so this
        # round's target draws sample the freshly-swapped views; the
        # swap messages ride the same reachability/fault seam as probes
        from ..topo.sampler import peerswap_step

        with phase_scope("sampler"):
            state = peerswap_step(state, cfg, topo, k_swap, faults)

    have0 = state.have  # pre-round holdings (the delivered-count base)
    with phase_scope("inject"):
        state = inject_step(state, meta, cfg)
    with phase_scope("broadcast"):
        if trace is None:
            state = broadcast_step(
                state, meta, cfg, topo, region, k_bcast, faults
            )
        else:
            state, wire = broadcast_step(
                state, meta, cfg, topo, region, k_bcast, faults,
                telem=True,
            )
    # sync pulls granted in round t land in ring slot t+1+fault_delay
    # (≠ slot t: compile_plan/validate guarantee 1+delay < n_delay_slots),
    # so deliver_step can pop slot t AFTER sync_step without ordering
    # hazards — the bi-stream RTT plus any FaultPlan latency
    with phase_scope("sync"):
        if trace is None:
            state = sync_step(state, meta, cfg, topo, k_sync, faults)
        else:
            state, stel = sync_step(
                state, meta, cfg, topo, k_sync, faults, telem=True
            )
    with phase_scope("deliver"):
        state = deliver_step(state, cfg)
    with phase_scope("swim"):
        state = swim_step(state, cfg, topo, k_swim, faults)

    # refresh the advertised bookkeeping tensors from this round's chunk
    # arrivals (generate_sync's snapshot; next round's sync reads them)
    with phase_scope("gaps"):
        touched = touched_versions(state.have, cfg)  # [N, A, V]
        heads = version_heads(touched)  # [N, A]
        gaps = extract_gaps(touched, heads, cfg)
        state = state._replace(
            heads=heads, gap_lo=gaps.lo, gap_hi=gaps.hi
        )
        overflow_frac = jnp.maximum(
            metrics.overflow_frac, gaps.overflow.mean(dtype=jnp.float32)
        )

    # convergence bookkeeping: a node holds a version only when EVERY
    # chunk arrived (the fully-buffered apply gate, util.rs:986-1005);
    # only versions that actually entered the system count (a dead
    # origin's commits never existed cluster-wide)
    with phase_scope("converge"):
        up = state.alive == ALIVE  # [N]
        comp = complete_versions(state.have, cfg)  # [N, A, V]
        act = version_active(state.injected, cfg)  # [A, V]

        version_done = (
            jnp.all(comp | ~up[:, None, None], axis=0) & act
        )  # [A, V] applied at every up node
        payload_done = grid_to_payload(version_done, cfg)  # [P]
        coverage_at = jnp.where(
            (metrics.coverage_at < 0) & payload_done,
            state.t,
            metrics.coverage_at,
        )
        node_done = jnp.all(comp | ~act[None], axis=(1, 2)) & up  # [N]
        all_injected = jnp.all(meta.round <= state.t)
        converged_at = jnp.where(
            (metrics.converged_at < 0) & node_done & all_injected,
            state.t,
            metrics.converged_at,
        )

        # delivery-order invariant (ISSUE 11): counted on-device every
        # round of an ordering-variant run — `touched`/`comp` are
        # already materialized above, so the check is pure grid algebra.
        # A trace-time branch: ordering="none" compiles the pre-change
        # program and carries the constant 0.
        order_violations = metrics.order_violations
        if cfg.ordering != "none":
            from .invariants import order_violation_count

            order_violations = order_violations + order_violation_count(
                touched, comp, meta, cfg
            )

    out_metrics = RunMetrics(
        coverage_at=coverage_at,
        converged_at=converged_at,
        overflow_frac=overflow_frac,
        order_violations=order_violations,
    )
    if trace is not None:
        from .telemetry import (
            record_round,
            swim_belief_counts,
            word_coverage_delivered,
        )

        with phase_scope("telemetry"):
            if cfg.n_payloads % 32 == 0:
                # word-domain counters (pack once, 32 shifted
                # reductions): ~10× cheaper than the bool pass, and the
                # exact integers the packed round computes on its words
                from .packed import pack_bits

                coverage, delivered = word_coverage_delivered(
                    pack_bits(state.have),
                    pack_bits(have0),
                    up,
                    cfg.n_payloads,
                )
            else:
                # P outside the word envelope (e.g. membership configs'
                # single payload) — small by construction, the bool pass
                # is fine and the packed path can't run here anyway
                held = state.have > 0
                coverage = jnp.sum(
                    held & up[:, None], axis=0, dtype=jnp.int32
                )
                delivered = jnp.sum(
                    held & ~(have0 > 0), axis=0, dtype=jnp.int32
                )
            susp, dn = swim_belief_counts(state, cfg)
            trace = record_round(
                trace,
                state.t,
                coverage=coverage,
                delivered=delivered,
                up_nodes=jnp.sum(up, dtype=jnp.int32),
                wire=wire,
                sync=stel,
                swim_suspect=susp,
                swim_down=dn,
                gap_overflow=jnp.sum(gaps.overflow, dtype=jnp.int32),
                every=cfg.trace_every,
            )
    state = state._replace(t=state.t + 1)
    if trace is not None:
        return state, out_metrics, trace
    return state, out_metrics


@functools.partial(
    jax.jit, static_argnames=("cfg", "topo", "max_rounds", "telemetry", "mesh")
)
def run_to_convergence(
    state: SimState,
    meta: PayloadMeta,
    cfg: SimConfig,
    topo: Topology,
    max_rounds: int = 1000,
    telemetry: bool = False,
    mesh=None,
):
    """Advance rounds until every up node holds every payload (the
    check_bookkeeping.py property: need == 0 ∧ equal heads) or max_rounds.

    Over the bitpack envelope (P % 32 == 0, power-of-two chunking,
    statically unmetered budgets, zero loss — `packed.packed_supported`)
    the loop runs on u32-packed payload words instead: 8× less HBM
    traffic on the hot carries, bit-identical results
    (tests/sim/test_packed_equivalence.py).  cfg/topo are static args,
    so the dispatch is a trace-time Python branch — one path compiles.

    ``telemetry=True`` (static) threads a `telemetry.RoundTrace` through
    the loop carry and returns (state, metrics, trace); False compiles
    to exactly the pre-telemetry program.

    ``mesh`` (static; a 1-D ``nodes`` `jax.sharding.Mesh` or None)
    shards the node axis across the mesh — the packed path re-pins the
    word-carry layout every round (doc/sharding.md); the dense path
    keeps relying on input placement (`parallel.mesh.shard_state`),
    which GSPMD already propagates through the loop.  Results are
    bit-identical either way (tests/sim/test_mesh_storm.py,
    tests/sim/test_packed_sharded.py).
    """
    from .packed import packed_supported, run_packed

    validate(cfg, topo)
    if packed_supported(cfg, topo):
        return run_packed(
            state, meta, cfg, topo, max_rounds, telemetry, mesh=mesh
        )
    region = regions(cfg.n_nodes, topo.n_regions)
    metrics = new_metrics(cfg)

    def _done(state, metrics):
        all_injected = jnp.all(meta.round <= state.t)
        return all_injected & jnp.all(
            (metrics.converged_at >= 0) | (state.alive != ALIVE)
        )

    def cond(carry):
        return (carry[0].t < max_rounds) & ~carry[2]

    # the per-lane done flag rides the carry (ISSUE 7 satellite): cond
    # reads a precomputed scalar instead of re-scanning converged_at,
    # and vmapped ensembles freeze converged lanes on an O(1) check
    if telemetry:
        from .telemetry import new_trace

        def body(carry):
            state, metrics, _, trace = carry
            state, metrics, trace = round_step(
                state, metrics, meta, cfg, topo, region, trace=trace
            )
            return state, metrics, _done(state, metrics), trace

        state, metrics, _, trace = jax.lax.while_loop(
            cond, body,
            (state, metrics, _done(state, metrics),
             new_trace(cfg, max_rounds)),
        )
        return state, metrics, trace

    def body(carry):
        state, metrics, _ = carry
        state, metrics = round_step(state, metrics, meta, cfg, topo, region)
        return state, metrics, _done(state, metrics)

    state, metrics, _ = jax.lax.while_loop(
        cond, body, (state, metrics, _done(state, metrics))
    )
    return state, metrics


def new_sim(cfg: SimConfig, seed: int = 0) -> SimState:
    return init_state(cfg, jax.random.PRNGKey(seed))
