"""The `corrosion-tpu` command-line interface.

Rebuild of the reference's `corrosion` binary command surface
(`crates/corrosion/src/main.rs:152-560,649`): agent, backup, restore,
cluster {rejoin,members,membership-states,set-id}, query, exec, reload,
sync {generate,reconcile-gaps}, locks, tls {ca,server,client} generate,
actor version, db lock, subs {info,list}, log {set,reset} — plus the
rebuild-specific `sim` command that runs the TPU epidemic-simulator
benchmark configs (template and consul land with their subsystems).

Run as `python -m corrosion_tpu.cli.main <command> ...`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import List, Optional

from ..agent.config import Config


def _load_config(args) -> Config:
    import os

    if args.config and os.path.exists(args.config):
        cfg = Config.load(args.config)
    else:
        cfg = Config()
    if getattr(args, "api_addr", None):
        cfg.api_addr = args.api_addr
    if getattr(args, "db_path", None):
        cfg.db_path = args.db_path
    if getattr(args, "admin_path", None):
        cfg.admin_path = args.admin_path
    return cfg


def _admin(cfg: Config, req: dict) -> dict:
    from ..admin import AdminClient

    if not cfg.admin_path:
        raise SystemExit("no admin socket configured (set [admin] path)")
    resp = AdminClient(cfg.admin_path).send_sync(req)
    if "error" in resp:
        raise SystemExit(f"admin error: {resp['error']}")
    return resp["ok"]


def _api(cfg: Config):
    from ..api.client import ApiClient

    if not cfg.api_addr:
        raise SystemExit("no API address configured (set [api] addr)")
    return ApiClient(cfg.api_addr)


def _print_json(obj) -> None:
    print(json.dumps(obj, indent=2, default=str))


# -- commands ------------------------------------------------------------


def cmd_agent(args) -> int:
    """Run the full agent: UDP/TCP gossip transport, HTTP API, admin socket
    (command/agent.rs:19)."""
    cfg = _load_config(args)
    if not cfg.gossip_addr:
        raise SystemExit("gossip addr required to run an agent")

    async def run():
        import signal

        from ..agent.agent import Agent
        from ..agent.transport import transport_from_config
        from ..api.http import ApiServer

        transport = transport_from_config(cfg)
        bound = await transport.start()
        cfg.gossip_addr = bound  # port-0 binds resolve here
        agent = Agent(cfg, transport)
        await agent.start()
        api = None
        if cfg.api_addr:
            host, _, port = cfg.api_addr.rpartition(":")
            api = ApiServer(agent, host or "127.0.0.1", int(port))
            cfg.api_addr = await api.start()  # port-0 binds resolve here
        admin = None
        if cfg.admin_path:
            from ..admin import AdminServer

            admin = AdminServer(agent, cfg.admin_path)
            await admin.start()
        pg = None
        if cfg.pg_addr:
            from ..pg import PgServer

            host, _, port = cfg.pg_addr.rpartition(":")
            pg = PgServer(agent, host or "127.0.0.1", int(port))
            cfg.pg_addr = await pg.start()
        prom = None
        if cfg.prometheus_addr:
            from ..metrics import MetricsServer

            host, _, port = cfg.prometheus_addr.rpartition(":")
            prom = MetricsServer(agent, host or "127.0.0.1", int(port))
            cfg.prometheus_addr = await prom.start()
        flight_task = None
        if cfg.telemetry_flight_path:
            # [telemetry].flight_path (ISSUE 13): arm the host flight
            # recorder on this agent and snapshot it to JSONL every few
            # seconds (atomic replace) — a kill -9'd devcluster node
            # leaves its last snapshot, so saturation gauges and
            # per-write stage stamps survive the crash the FaultPlan
            # injected
            from ..telemetry import (
                HostFlightRecorder,
                attach_host_telemetry,
                write_host_flight_jsonl,
            )

            flight_rec = HostFlightRecorder()
            # the GLOBAL registry (attach's default): a configured
            # prometheus_addr must scrape the corro_serving_* families
            # — a private registry here would hide every 429/saturation
            # signal from /metrics
            attach_host_telemetry(agent, recorder=flight_rec)
            head = {"node": cfg.gossip_addr, "api": cfg.api_addr}

            async def _flight_flush_loop():
                while True:
                    await asyncio.sleep(2.0)
                    await asyncio.to_thread(
                        write_host_flight_jsonl,
                        cfg.telemetry_flight_path, flight_rec, head,
                    )

            flight_task = asyncio.ensure_future(_flight_flush_loop())
        fault_task = None
        if cfg.faults:
            # [faults] (ISSUE 15): arm the node-local fault runtime —
            # link faults / slow / clock skew from the shipped FaultPlan
            # replay INSIDE this process, following the parent
            # devcluster driver's round control file.  Armed before the
            # "agent running" line so no fault round can race the
            # supervisor's readiness signal.
            from ..faults import AgentFaultRuntime, plan_from_dict

            fault_runtime = AgentFaultRuntime(
                plan_from_dict(json.loads(cfg.faults["plan"])),
                int(cfg.faults["node_index"]),
                list(cfg.faults["gossip_addrs"]),
                transport,
                agent=agent,
                control_path=str(cfg.faults.get("control_path", "")),
            )
            fault_task = asyncio.ensure_future(fault_runtime.run())
        # first SIGINT/SIGTERM begins graceful shutdown; a second
        # force-exits (tripwire.rs signal stream).  Armed BEFORE the
        # "agent running" line so a supervisor reacting to that line
        # can't beat the handler installation.
        from ..utils.tripwire import Tripwire, wait_for_all_pending_handles

        tripwire = Tripwire.from_signals(signal.SIGINT, signal.SIGTERM)
        print(
            f"agent running: actor {agent.actor_id.hex()} "
            f"gossip {cfg.gossip_addr} api {cfg.api_addr or '-'} "
            f"pg {cfg.pg_addr or '-'} prometheus {cfg.prometheus_addr or '-'}",
            flush=True,
        )
        await tripwire.wait()
        if fault_task is not None:
            fault_task.cancel()
            await asyncio.gather(fault_task, return_exceptions=True)
        if flight_task is not None:
            flight_task.cancel()
            await asyncio.gather(flight_task, return_exceptions=True)
            from ..telemetry import write_host_flight_jsonl

            # final flush: the graceful-shutdown snapshot
            write_host_flight_jsonl(
                cfg.telemetry_flight_path, flight_rec, head
            )
        if admin:
            await admin.stop()
        if prom:
            await prom.stop()
        if pg:
            await pg.stop()
        if api:
            await api.stop()
        await agent.stop()
        await transport.close()
        # drain counted background work before exiting
        # (wait_for_all_pending_handles, spawn/src/lib.rs:117)
        await wait_for_all_pending_handles(timeout=60.0)

    asyncio.run(run())
    return 0


def cmd_backup(args) -> int:
    from ..agent.backup import backup_db

    cfg = _load_config(args)
    backup_db(cfg.db_path, args.path)
    print(f"backed up {cfg.db_path} -> {args.path}")
    return 0


def cmd_restore(args) -> int:
    from ..agent.backup import restore_db
    from ..core.types import ActorId

    cfg = _load_config(args)
    site = ActorId.from_hex(args.site_id) if args.site_id else None
    actor = restore_db(args.path, cfg.db_path, site_id=site)
    print(f"restored {args.path} -> {cfg.db_path} as actor {actor.hex()}")
    return 0


def cmd_db_lock(args) -> int:
    """Hold exclusive locks on the DB files until interrupted
    (main.rs:478-497)."""
    from ..agent.backup import db_lock

    cfg = _load_config(args)
    with db_lock(cfg.db_path):
        print(f"locked {cfg.db_path} (Ctrl-C to release)", flush=True)
        if args.once:  # test hook: acquire, report, release
            return 0
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return 0


def cmd_query(args) -> int:
    """`corrosion query` (main.rs:459-470): rows tab-separated, optional
    column header and timing."""
    cfg = _load_config(args)

    async def run():
        client = _api(cfg)
        stmt = [args.sql, args.param or []]
        t0 = time.monotonic()
        events = client.query_stream(stmt)
        async for ev in events:
            if "columns" in ev and args.columns:
                print("\t".join(ev["columns"]))
            elif "row" in ev:
                _, vals = ev["row"]
                print("\t".join("" if v is None else str(v) for v in vals))
            elif "error" in ev:
                raise SystemExit(f"query error: {ev['error']}")
        if args.timer:
            print(f"time: {time.monotonic() - t0:.6f}s", file=sys.stderr)

    asyncio.run(run())
    return 0


def cmd_exec(args) -> int:
    cfg = _load_config(args)

    async def run():
        client = _api(cfg)
        resp = await client.execute([[args.sql, args.param or []]])
        if args.timer:
            print(f"time: {resp.get('time', 0):.6f}s", file=sys.stderr)
        _print_json(resp)

    asyncio.run(run())
    return 0


def cmd_reload(args) -> int:
    cfg = _load_config(args)
    out = _admin(cfg, {"cmd": "reload", "schema_paths": cfg.schema_paths})
    _print_json(out)
    return 0


def cmd_sync(args) -> int:
    cfg = _load_config(args)
    sub = "generate" if args.sync_cmd == "generate" else "reconcile_gaps"
    _print_json(_admin(cfg, {"cmd": "sync", "sub": sub}))
    return 0


def cmd_locks(args) -> int:
    cfg = _load_config(args)
    _print_json(_admin(cfg, {"cmd": "locks", "top": args.top}))
    return 0


def cmd_cluster(args) -> int:
    cfg = _load_config(args)
    sub = args.cluster_cmd.replace("-", "_")
    req = {"cmd": "cluster", "sub": sub}
    if sub == "set_id":
        req["id"] = args.id
    _print_json(_admin(cfg, req))
    return 0


def cmd_actor(args) -> int:
    cfg = _load_config(args)
    _print_json(
        _admin(
            cfg,
            {
                "cmd": "actor", "sub": "version",
                "actor_id": args.actor_id, "version": args.version,
            },
        )
    )
    return 0


def cmd_subs(args) -> int:
    cfg = _load_config(args)
    req = {"cmd": "subs", "sub": args.subs_cmd}
    if args.subs_cmd == "info":
        req["id"] = args.id
    _print_json(_admin(cfg, req))
    return 0


def cmd_log(args) -> int:
    cfg = _load_config(args)
    req = {"cmd": "log", "sub": args.log_cmd}
    if args.log_cmd == "set":
        req["filter"] = args.filter
    _print_json(_admin(cfg, req))
    return 0


def cmd_tls(args) -> int:
    from ..utils import tls

    if args.tls_kind == "ca":
        cert, key = tls.generate_ca(args.output)
    elif args.tls_kind == "server":
        cert, key = tls.generate_server_cert(
            args.ca_cert, args.ca_key, args.ip, args.output
        )
    else:
        cert, key = tls.generate_client_cert(args.ca_cert, args.ca_key, args.output)
    print(f"wrote {cert}\nwrote {key}")
    return 0


def cmd_template(args) -> int:
    """`corrosion template` (command/tpl.rs): render templates against the
    agent's API, optionally re-rendering as the data changes."""
    cfg = _load_config(args)
    from ..tpl.engine import render_to_file, watch_and_render

    if not args.once:
        asyncio.run(
            watch_and_render(
                _api(cfg), args.template, args.output or _strip_tpl(args.template)
            )
        )
        return 0
    asyncio.run(
        render_to_file(
            _api(cfg), args.template, args.output or _strip_tpl(args.template)
        )
    )
    return 0


def _strip_tpl(path: str) -> str:
    return path[: -len(".tpl")] if path.endswith(".tpl") else path + ".out"


def cmd_consul(args) -> int:
    """`corrosion consul sync` (command/consul/sync.rs)."""
    cfg = _load_config(args)
    from ..consul.sync import run_sync

    asyncio.run(
        run_sync(
            _api(cfg),
            consul_addr=args.consul_addr,
            node=args.node,
            once=args.once,
        )
    )
    return 0


#: scenario name → sim.runner config-fn attribute.  ONE registry: the
#: CLI choices derive from the keys and scalability from each resolved
#: fn's signature; values are attr names so building the argparser never
#: imports jax (the sim stack loads only when `sim` actually runs).
_SIM_SCENARIOS = {
    "ground-truth-3node": "config_ground_truth_3node",
    # FaultPlan demo campaign (doc/faults.md): one seeded fault schedule,
    # also replayable against the in-process host tier
    "fault-campaign-3node": "config_fault_campaign_3node",
    "swim-churn-64": "config_swim_churn_64",
    "swim-churn-partial-4k": "config_swim_churn_partial",
    "broadcast-1k": "config_broadcast_1k",
    "partition-heal-10k": "config_partition_heal_10k",
    "write-storm-100k": "config_write_storm_100k",
    "gapstress": "config_write_storm_gapstress",
    "gapstress-distortion": "config_gapstress_distortion",
    # packed-vs-dense A/B on the storm shape (results must be identical;
    # reports the realized speedup)
    "storm-ab": "config_storm_ab",
    # the storm shape under a loss+partition+crash FaultPlan, on the
    # PACKED round path (ISSUE 4), with the defensible-wall protocol
    "packed-fault-storm": "config_packed_fault_storm",
    # the fault storm WITH the flight recorder on (ISSUE 5): per-round
    # telemetry overhead vs plain + the coverage-curve summary
    "fault-storm-telemetry": "config_fault_storm_telemetry",
    # the fault storm node-axis-SHARDED over a device mesh (ISSUE 7):
    # GSPMD-partitioned packed carry, bit-identical to single-device
    # (--devices caps the mesh; at ≤ 8192 nodes the rung re-runs
    # unsharded and asserts bit-equality in the record itself)
    "packed-fault-storm-sharded": "config_packed_fault_storm_sharded",
    # the 1M-node tier (ISSUE 7): the storm schedule at a million nodes,
    # sharded, ground-truth membership, defensible-wall verified
    "fault-storm-1m": "config_fault_storm_1m",
    # the HOST-SERVING rung (ISSUE 8): flood an in-process agent cluster
    # through the measured loadgen driver — publish→subscriber-visible
    # latency percentiles, instrumentation-overhead A/B, faultless AND
    # FaultPlan conditions, host flight JSONL via --trace-out
    "serving-loadgen": "config_serving_loadgen",
    # the MULTI-PROCESS serving rung (ISSUE 13): ≥1000 writer lanes
    # sharded across loadgen worker processes against a real devcluster
    # — faultless + kill-and-restart FaultPlan + overload (429) runs,
    # zero acked writes lost, saturation gauges from per-node flights
    "serving-loadgen-mp": "config_serving_loadgen_mp",
    # the uniform-vs-PeerSwap frontier (ISSUE 9): both samplers × two
    # topology families as a campaign, reduced to per-family rounds ×
    # wire-bytes ratios (the paper-grounded sampler comparison)
    "peer-sampler-frontier": "config_peer_sampler_frontier",
    # the protocol-variant frontier (ISSUE 11): four named protocol
    # families × two topologies as a campaign, reduced to per-family
    # rounds/wire ratios vs the baseline point, plus a storm-scale
    # PeerSwap sampler cell (the convergence × wire-bytes Pareto)
    "protocol-frontier": "config_protocol_frontier",
    # the phase-attribution rung (ISSUE 16): one forced-packed
    # storm-aspect round under a scoped jax.profiler capture, folded
    # into the named-phase cost ledger, cross-checked against the
    # interleaved telemetry A/B — the capture `sim profile compare`
    # gates against doc/experiments/PROFILE_BASELINE.json
    "phase-profile": "config_phase_profile",
    # static memory budgets (ISSUE 16): compiled.memory_analysis() for
    # the committed rungs via abstract (eval_shape) lowering — no state
    # is allocated, so the 1M-node budget costs compile time only
    "memory-budget": "config_memory_budget",
}


def cmd_sim(args) -> int:
    """Run a TPU-simulator benchmark config (rebuild-specific; these are
    the BASELINE.md scenario tiers), or dispatch `sim campaign ...` /
    `sim trace show ...`."""
    if args.scenario == "lint":
        # corrolint (ISSUE 10): jax-free static analysis — dispatched
        # before the platform setup so a CI lint gate never imports jax
        return cmd_lint(args)
    if args.scenario == "trace":
        # pure host-side artifact rendering — dispatched before the
        # platform setup below so it never pays the jax import
        return cmd_trace(args)
    if args.scenario == "topo":
        # topology-family introspection (ISSUE 9): the listing is
        # jax-free; a tier table imports jax for the Topology dataclass
        # only (no op runs, so no backend/tunnel is touched)
        return cmd_topo(args)
    if args.scenario == "proto":
        # protocol-family introspection (ISSUE 11): entirely jax-free —
        # the registry and its resolved-knob rendering are plain dicts
        # (corrosion_tpu.proto imports no accelerator runtime)
        return cmd_proto(args)
    if args.scenario == "profile":
        # phase-attribution ledger tooling (ISSUE 16): show / compare /
        # baseline are pure JSON→text transforms over records the rungs
        # already emitted — dispatched before the platform setup so the
        # nightly profile gate never imports jax
        return cmd_profile(args)
    # honor JAX_PLATFORMS even when an accelerator plugin would win over
    # the env var (jax.config takes precedence) — tests set cpu to keep
    # subprocess sims off the contended real chip
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    # --trace-out is the scenario form (one JSONL per run/seed);
    # --trace-dir is the campaign form (one JSONL per cell/lane) —
    # refuse the mismatched flag loudly rather than silently
    # recording nothing
    if args.scenario == "campaign" and args.trace_out:
        print(
            "error: campaign runs write per-cell traces via "
            "--trace-dir DIR, not --trace-out",
            file=sys.stderr,
        )
        return 2
    if args.scenario != "campaign" and args.trace_dir:
        print(
            "error: --trace-dir is a campaign flag; scenario runs "
            "take --trace-out FILE",
            file=sys.stderr,
        )
        return 2
    if args.parity or args.round_s is not None:
        # `trace` dispatched above — anything still here would silently
        # ignore the join request (or its bucket width)
        flag = "--parity" if args.parity else "--round-s"
        print(
            f"error: {flag} belongs to `sim trace show --parity` (it "
            "joins a sim lane to its host-parity replay)",
            file=sys.stderr,
        )
        return 2
    profiling = None
    if args.xla_profile:
        # optional XLA profiler capture around the run (jax.profiler
        # TensorBoard trace into DIR) — covers scenario AND campaign
        # runs; the bench storm rungs use the same hook via
        # BENCH_XLA_PROFILE.  Scenarios whose config fn accepts
        # ``profile_dir`` (ISSUE 16) own the capture themselves — a
        # scoped trace + phase map + parsed phase_profile block in the
        # record — so no outer trace is started for them (nested
        # jax.profiler traces error out); _run_sim_scenario threads the
        # dir through instead.
        config_owns = False
        if args.scenario in _SIM_SCENARIOS:
            import inspect

            from ..sim import runner as _runner

            _fn = getattr(_runner, _SIM_SCENARIOS[args.scenario])
            config_owns = "profile_dir" in inspect.signature(_fn).parameters
        if not config_owns:
            import jax

            jax.profiler.start_trace(args.xla_profile)
            profiling = args.xla_profile
    try:
        if args.scenario == "campaign":
            return cmd_campaign(args)
        return _run_sim_scenario(args)
    finally:
        if profiling:
            import jax

            jax.profiler.stop_trace()


def _run_sim_scenario(args) -> int:
    from ..sim import runner

    fn = getattr(runner, _SIM_SCENARIOS[args.scenario])
    kwargs = {}
    # scalability derived from the config fn itself: no parallel literal
    # list to forget when adding a scenario
    import inspect

    params = inspect.signature(fn).parameters
    if args.nodes and "n_nodes" in params:
        kwargs["n_nodes"] = args.nodes
    # serving-rung workload shape (ISSUE 13): only scenarios whose
    # config fn exposes the knob accept the flag — a silently ignored
    # writer count would fake a scale measurement
    for flag, kw in (("workers", "n_workers"), ("writers", "n_writers")):
        val = getattr(args, flag)
        if val:
            if kw not in params:
                print(
                    f"error: scenario {args.scenario!r} does not take "
                    f"--{flag} (serving rungs only)",
                    file=sys.stderr,
                )
                return 2
            kwargs[kw] = val
    # mesh sharding (ISSUE 7): --devices caps the 1-D nodes mesh on
    # scenarios that take one; refuse it loudly elsewhere (a silently
    # ignored device cap would fake a sharded measurement).  The same
    # rule for the campaign-only twin flag: a scenario run given
    # --mesh-devices must not silently execute unsharded.
    if args.mesh_devices:
        print(
            "error: --mesh-devices is a campaign-run flag; scenario "
            "runs take --devices (sharded rungs only)",
            file=sys.stderr,
        )
        return 2
    if args.devices:
        if "n_devices" not in params:
            print(
                f"error: scenario {args.scenario!r} does not take "
                "--devices (sharded rungs: packed-fault-storm-sharded, "
                "fault-storm-1m)",
                file=sys.stderr,
            )
            return 2
        kwargs["n_devices"] = args.devices
    # topology/sampler axes (ISSUE 9): only scenarios whose config fn
    # exposes the axis accept the flag — a silently ignored topology
    # would fake a WAN measurement
    if args.topology:
        if "topo_family" not in params:
            print(
                f"error: scenario {args.scenario!r} does not take "
                "--topology (axis-aware scenarios: broadcast-1k, "
                "write-storm-100k; `sim topo show` lists families)",
                file=sys.stderr,
            )
            return 2
        kwargs["topo_family"] = args.topology
    if args.sampler:
        if "sampler" not in params:
            print(
                f"error: scenario {args.scenario!r} does not take "
                "--sampler (axis-aware scenarios: broadcast-1k, "
                "write-storm-100k)",
                file=sys.stderr,
            )
            return 2
        kwargs["sampler"] = args.sampler
    # protocol-variant axis (ISSUE 11): only scenarios whose config fn
    # exposes it take the flag, and an unknown family exits 2 with the
    # list (the PR 9 --topology rule) instead of a traceback
    if args.proto:
        if "proto_family" not in params:
            print(
                f"error: scenario {args.scenario!r} does not take "
                "--proto (axis-aware scenarios: broadcast-1k, "
                "write-storm-100k; `sim proto show` lists families)",
                file=sys.stderr,
            )
            return 2
        from ..proto import FAMILIES as _PROTO_FAMILIES

        if args.proto not in _PROTO_FAMILIES:
            print(
                f"error: unknown protocol family {args.proto!r} "
                f"(have {sorted(_PROTO_FAMILIES)})",
                file=sys.stderr,
            )
            return 2
        kwargs["proto_family"] = args.proto
    # flight recorder (ISSUE 5): --telemetry adds the summary block to
    # the record; --trace-out also writes the per-round JSONL artifact.
    # A scenario supports the recorder if its config fn takes `telemetry`
    # or `trace_path` (fault-storm-telemetry is always-on: trace_path
    # only); anything else refuses the flags loudly rather than silently
    # running without them.
    if (args.telemetry or args.trace_out) and not (
        "telemetry" in params or "trace_path" in params
    ):
        print(
            f"error: scenario {args.scenario!r} does not support "
            "--telemetry/--trace-out",
            file=sys.stderr,
        )
        return 2
    if args.trace_out and "trace_path" not in params:
        print(
            f"error: scenario {args.scenario!r} supports --telemetry "
            "but not --trace-out",
            file=sys.stderr,
        )
        return 2
    if (args.telemetry or args.trace_out) and "telemetry" in params:
        kwargs["telemetry"] = True
    # phase-attribution capture (ISSUE 16): configs that take
    # `profile_dir` own the scoped trace + phase map + parsed ledger
    # (cmd_sim skipped the outer jax.profiler trace for them)
    if args.xla_profile and "profile_dir" in params:
        kwargs["profile_dir"] = args.xla_profile
    trace_out = args.trace_out
    base_seed = args.seed if args.seed is not None else 0
    n_seeds = args.seeds or 1
    if n_seeds <= 1:
        if trace_out:
            kwargs["trace_path"] = trace_out
        print(json.dumps(fn(seed=base_seed, **kwargs), default=float))
        return 0

    def seed_trace_path(seed: int):
        # one artifact PER SEED: a shared path would atomically replace
        # itself n_seeds times and silently keep only the last trace
        if not trace_out:
            return None
        root, ext = os.path.splitext(trace_out)
        return f"{root}.seed{seed}{ext or '.jsonl'}"

    # multi-seed distribution: per-seed records plus cross-seed
    # percentiles of every numeric field (the convergence-round
    # DISTRIBUTION the calibration contract compares, not one scalar)
    runs = [
        fn(
            seed=base_seed + i,
            **(
                dict(kwargs, trace_path=seed_trace_path(base_seed + i))
                if trace_out
                else kwargs
            ),
        )
        for i in range(n_seeds)
    ]
    numeric = {
        k for k in runs[0]
        if all(isinstance(r.get(k), (int, float)) for r in runs)
    }
    summary = {}
    for k in sorted(numeric):
        vals = sorted(float(r[k]) for r in runs)
        summary[k] = {
            "p50": vals[len(vals) // 2],
            "p99": vals[min(len(vals) - 1, int(len(vals) * 0.99))],
            "min": vals[0],
            "max": vals[-1],
        }
    print(json.dumps(
        {"seeds": args.seeds, "summary": summary, "runs": runs},
        default=float,
    ))
    return 0


def cmd_lint(args) -> int:
    """`sim lint`: run corrolint (corrosion_tpu.analysis, doc/lint.md)
    over the repo — determinism / shard-alignment / async-discipline
    invariants as AST rules, jax-free, in seconds.

    Exit codes: 0 = clean against the committed baseline, 1 = at least
    one non-baselined finding (the CI gate's red), 2 = usage error.
    ``--baseline-write`` regenerates LINT_BASELINE.json
    deterministically (sorted, content-stable fingerprints) and exits 0.
    Findings print as clickable ``file:line`` references."""
    if args.campaign_cmd:
        print(
            "error: sim lint takes no subcommand "
            "(flags: --format, --baseline, --no-baseline, "
            "--baseline-write)",
            file=sys.stderr,
        )
        return 2
    from ..analysis.__main__ import lint_main

    argv = ["--format", "json" if args.json else args.format]
    if args.baseline:
        argv += ["--baseline", args.baseline]
    if args.no_baseline:
        argv.append("--no-baseline")
    if args.baseline_write:
        argv.append("--baseline-write")
    return lint_main(argv)


def cmd_topo(args) -> int:
    """`sim topo show [--topology FAM] [--nodes N]`: render a topology
    family's tier table — region/AZ blocks, delay/loss classes, degree
    histogram, and the host-tier link-event count.  The family LISTING
    is jax-free (`corrosion_tpu.topo` imports no accelerator runtime at
    module level); rendering a tier table constructs a `Topology`
    dataclass, which imports jax but touches no device or computation
    (safe even before cmd_sim's platform setup — backend init happens
    at first op, not import).  Without ``--topology``, list the
    registry."""
    from ..topo import (
        FAMILIES,
        az_blocks,
        family_topology,
        topology_link_events,
    )

    if args.campaign_cmd != "show":
        raise SystemExit("usage: sim topo show [--topology FAM] [--nodes N]")
    if not args.topology:
        out = {name: dict(kw) for name, kw in sorted(FAMILIES.items())}
        if args.json:
            _print_json({"families": out})
        else:
            print("topology families (sim topo show --topology NAME):")
            for name, kw in out.items():
                print(f"  {name}: {json.dumps(kw, sort_keys=True)}")
        return 0
    try:
        kw = family_topology(args.topology)
    except KeyError:
        print(
            f"error: unknown topology family {args.topology!r} "
            f"(have {sorted(FAMILIES)})",
            file=sys.stderr,
        )
        return 2
    n = args.nodes or 96
    from ..sim.topology import Topology, loss_tiers

    topo = Topology(**kw)  # __post_init__ coerces degree_classes
    blocks = az_blocks(n, topo.n_regions, topo.n_azs)
    base, az_t, inter_t = loss_tiers(topo)
    if topo.region_delay_matrix:
        # measured-RTT family (ISSUE 13): the matrix IS the delay rule
        # — render it per region pair instead of the 3-class tiers
        tiers = {
            "in-region": {"delay_rounds": 0, "loss": base / 256.0},
            "cross-region": {
                "delay_rounds": "matrix", "loss": inter_t / 256.0,
            },
            "delay_matrix_rounds": [
                list(row) for row in topo.region_delay_matrix
            ],
        }
    else:
        tiers = {
            "same-az": {
                "delay_rounds": topo.intra_delay, "loss": base / 256.0,
            },
            "cross-az": {"delay_rounds": topo.az_delay, "loss": az_t / 256.0},
            "cross-region": {
                "delay_rounds": topo.inter_delay, "loss": inter_t / 256.0,
            },
        }
    degrees = {}
    if topo.degree_classes:
        k = len(topo.degree_classes)
        for i, d in enumerate(topo.degree_classes):
            share = len(range(i, n, k))
            degrees[str(d)] = degrees.get(str(d), 0) + share
    # the host-tier compilation this family rides for parity points
    events = topology_link_events(topo, n, end=1)
    out = {
        "family": args.topology,
        "topology": kw,
        "n_nodes": n,
        "az_blocks": [
            {"region": r, "range": f"{lo}:{hi}"} for r, lo, hi in blocks
        ],
        "tiers": tiers,
        "degree_histogram": degrees or None,
        "host_link_events": len(events),
    }
    if args.json:
        _print_json(out)
        return 0
    print(f"topology family {args.topology!r} at {n} nodes:")
    print(f"  {json.dumps(kw, sort_keys=True)}")
    print(f"  az blocks: " + ", ".join(
        f"r{r}[{lo}:{hi}]" for r, lo, hi in blocks
    ))
    for name, t in tiers.items():
        if not isinstance(t, dict):
            print(f"  {name}: {json.dumps(t)}")
            continue
        print(
            f"  {name:>13}: delay {t['delay_rounds']} rounds, "
            f"loss {t['loss']:.3f}"
        )
    if degrees:
        print(f"  degree histogram: {json.dumps(degrees, sort_keys=True)}")
    print(f"  host-tier link events (range rectangles): {len(events)}")
    return 0


def cmd_proto(args) -> int:
    """`sim proto show [--proto FAM]`: render the protocol-variant
    registry (ISSUE 11) — entirely jax-free (the families are plain
    dicts of SimConfig protocol knobs; `corrosion_tpu.proto` imports no
    accelerator runtime, mirroring `sim topo show`'s listing).  With
    ``--proto``, print one family's knob overlay and its fully-resolved
    protocol point (family over the documented defaults); without it,
    list the registry."""
    from ..proto import DEFAULTS, FAMILIES, family_proto

    if args.campaign_cmd != "show":
        raise SystemExit("usage: sim proto show [--proto FAM]")
    if not args.proto:
        out = {name: dict(kw) for name, kw in sorted(FAMILIES.items())}
        if args.json:
            _print_json({"families": out, "defaults": dict(DEFAULTS)})
        else:
            print("protocol families (sim proto show --proto NAME):")
            for name, kw in out.items():
                print(f"  {name}: {json.dumps(kw, sort_keys=True)}")
            print(f"  defaults: {json.dumps(DEFAULTS, sort_keys=True)}")
        return 0
    try:
        kw = family_proto(args.proto)
    except KeyError:
        print(
            f"error: unknown protocol family {args.proto!r} "
            f"(have {sorted(FAMILIES)})",
            file=sys.stderr,
        )
        return 2
    resolved = dict(DEFAULTS)
    resolved.update(kw)
    out = {
        "family": args.proto,
        "overlay": kw,
        "resolved": resolved,
    }
    if args.json:
        _print_json(out)
        return 0
    print(f"protocol family {args.proto!r}:")
    print(f"  overlay:  {json.dumps(kw, sort_keys=True)}")
    print(f"  resolved: {json.dumps(resolved, sort_keys=True)}")
    return 0


def _load_profile_record(path: str):
    """Load a phase_profile record from any of its carriers: a raw
    record (``kind == "phase_profile"``), a scenario/bench record with
    a ``phase_profile`` key, or a bench_child result file (the block
    rides ``metrics``).  Returns (record, memory_budget_or_None,
    carrier_doc)."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise SystemExit(f"error: {path} is not a JSON object")
    if doc.get("kind") in ("phase_profile", "profile_baseline"):
        return doc, None, doc
    for carrier in (doc, doc.get("metrics")):
        if isinstance(carrier, dict) and isinstance(
            carrier.get("phase_profile"), dict
        ):
            return (
                carrier["phase_profile"],
                carrier.get("memory_budget"),
                carrier,
            )
    raise SystemExit(
        f"error: no phase_profile record in {path} (expected a raw "
        "record, a scenario record with a phase_profile block, or a "
        "bench_child result)"
    )


def cmd_profile(args) -> int:
    """`sim profile show|compare|baseline` (ISSUE 16): render, gate,
    and band phase-attribution ledgers.  Entirely jax-free — inputs
    are the JSON records the rungs emit, so the nightly profile gate
    runs in milliseconds without touching a backend.

    - ``show --in FILE [--json]``: phase ledger + memory-budget tables
      (FILE may be a record, a rung output, or a committed baseline).
    - ``compare --baseline FILE --candidate FILE [--json]``: gate the
      candidate's phase fractions against the baseline bands; exit 1
      on any violation (the profile-smoke CI job's gate).
    - ``baseline --candidate RECORD --out FILE``: band a measured
      record into a committable baseline (regeneration after a
      justified shift; review the diff before committing).
    """
    from ..sim import profile as prof

    sub = args.campaign_cmd
    if sub == "show":
        if not args.in_path:
            raise SystemExit("sim profile show needs --in FILE")
        with open(args.in_path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("budgets"), list):
            # a config_memory_budget document: one table per rung
            if args.json:
                print(json.dumps(doc, default=float))
                return 0
            hbm = doc.get("hbm_bytes_per_chip")
            if hbm:
                print(f"hbm capacity per chip: {float(hbm) / 1e9:.1f} GB")
            for rung in doc["budgets"]:
                print(prof.render_memory_table(rung))
            return 0
        rec, mem, _carrier = _load_profile_record(args.in_path)
        if args.json:
            out = {"phase_profile": rec}
            if mem:
                out["memory_budget"] = mem
            print(json.dumps(out, default=float))
            return 0
        if rec.get("kind") == "profile_baseline":
            print(
                "profile baseline  "
                f"scenario={rec.get('scenario', '?')}"
            )
            for name, band in sorted(rec.get("phases", {}).items()):
                tol = float(band.get("tol", prof.DEFAULT_PHASE_TOL))
                print(f"  {name:<12} {float(band['frac']):>7.1%} ± {tol:.1%}")
            cap = rec.get("unattributed_frac_max")
            if cap is not None:
                print(f"  unattributed ceiling {float(cap):.1%}")
            return 0
        print(prof.render_phase_table(rec))
        if mem:
            print(prof.render_memory_table(mem))
        return 0
    if sub == "compare":
        if not (args.baseline and args.candidate):
            raise SystemExit(
                "sim profile compare needs --baseline FILE "
                "--candidate FILE"
            )
        with open(args.baseline) as f:
            base = json.load(f)
        if base.get("kind") != "profile_baseline":
            raise SystemExit(
                f"error: {args.baseline} is not a profile_baseline "
                "document"
            )
        cand, _mem, _carrier = _load_profile_record(args.candidate)
        failures = prof.compare_profiles(base, cand)
        if args.json:
            print(json.dumps({"ok": not failures, "failures": failures}))
        else:
            print(prof.render_compare(base, cand, failures))
        return 1 if failures else 0
    if sub == "baseline":
        if not (args.candidate and args.out):
            raise SystemExit(
                "sim profile baseline needs --candidate RECORD "
                "--out FILE"
            )
        cand, _mem, carrier = _load_profile_record(args.candidate)
        # carry the rung's shape + telemetry cross-check fields so the
        # committed baseline documents what it was measured on
        extra = {
            k: carrier[k]
            for k in (
                "n_nodes", "n_payloads", "k_rounds", "round_path",
                "telemetry_frac", "telemetry_scoped_frac",
                "telemetry_smeared_frac", "telemetry_frac_expected",
                "telemetry_frac_delta",
            )
            if k in carrier
        }
        if args.phase_max:
            caps = {}
            for item in args.phase_max:
                name, sep, val = item.partition("=")
                try:
                    cap = float(val) if sep else None
                except ValueError:
                    cap = None
                if not name or cap is None or not 0.0 < cap <= 1.0:
                    raise SystemExit(
                        f"error: --phase-max wants PHASE=FRAC with "
                        f"FRAC in (0, 1], got {item!r}"
                    )
                caps[name] = cap
            extra["phase_frac_max"] = caps
        tol = args.tol if args.tol is not None else prof.DEFAULT_PHASE_TOL
        doc = prof.baseline_from_profile(
            cand, scenario="phase-profile", tol=tol, extra=extra
        )
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=1, default=float)
            f.write("\n")
        print(f"wrote {args.out}")
        return 0
    print(
        "usage: sim profile show --in FILE [--json] | "
        "sim profile compare --baseline FILE --candidate FILE [--json] "
        "| sim profile baseline --candidate RECORD --out FILE",
        file=sys.stderr,
    )
    return 2


def cmd_trace(args) -> int:
    """`sim trace show --in FILE [--parity HOST_FILE]`: render a
    flight-recorder JSONL artifact (header summary + a compact table)
    without touching jax — the artifact is plain JSON lines.  Both
    tiers share one schema (``kind: flight_recorder``): sim files carry
    per-ROUND rows, host files (``tier: host`` — ISSUE 8) per-WRITE
    rows with the publish→broadcast-out→apply→visible stage latencies.

    ``--parity`` (ISSUE 11 carried edge) JOINS a sim lane to its
    host-parity replay side-by-side: the host tier's per-write rows are
    bucketed onto sim rounds via ``--round-s`` (the host wall-clock per
    round — the campaign spec's ``round_s``, default 0.05), so parity
    drift reads off one table instead of two unaligned renders."""
    if args.campaign_cmd != "show":
        raise SystemExit(
            "usage: sim trace show --in FILE [--parity HOST_FILE] [--json]"
        )
    if not args.in_path:
        raise SystemExit("sim trace show needs --in FILE")
    with open(args.in_path) as f:
        head = json.loads(f.readline())
        rows = [json.loads(line) for line in f if line.strip()]
    if head.get("kind") != "flight_recorder":
        raise SystemExit(f"{args.in_path} is not a flight-recorder artifact")
    if args.parity:
        return _trace_show_parity(args, head, rows)
    if args.round_s is not None:
        # the bucket width only exists for the parity join — dropping
        # it silently would be the no-op class the --parity refusal
        # above exists to prevent
        print(
            "error: --round-s needs --parity HOST_FILE (it sets the "
            "join's bucket width)",
            file=sys.stderr,
        )
        return 2
    if args.json:
        _print_json({"header": head, "rounds": rows})
        return 0
    host_tier = head.get("tier") == "host"
    if host_tier:
        print(
            f"flight recorder v{head.get('version')} (host tier): "
            f"{head.get('n_nodes', '?')} nodes, "
            f"{head.get('writes', len(rows))} writes"
        )
    else:
        print(
            f"flight recorder v{head.get('version')}: "
            f"{head['n_nodes']} nodes × {head['n_payloads']} payloads, "
            f"{head['rounds']} rounds"
        )
    for k in (
        "campaign", "cell_index", "seed", "scenario", "writers",
        "watchers", "traceparent",
    ):
        if k in head:
            print(f"  {k}: {head[k]}")
    _print_json(head.get("summary", {}))
    if host_tier:
        cols = (
            "t", "actor", "version", "node", "n_changes",
            "broadcast_out_ms", "publish_to_visible_ms", "hlc_lag_ms",
        )
    else:
        cols = (
            "t", "coverage_frac", "delivered", "bcast_bytes", "sync_bytes",
            "sync_sessions", "bcast_dropped", "bcast_cut", "swim_down",
            "crashes", "wipes", "gap_overflow",
        )
    print("  ".join(f"{c:>13}" for c in cols))
    for row in rows:
        print("  ".join(f"{row.get(c, ''):>13}" for c in cols))
    return 0


def _trace_show_parity(args, head: dict, rows: list) -> int:
    """The ``sim trace show --parity`` join (ISSUE 11 carried edge):
    one table, sim-lane per-round columns on the left, the host-parity
    replay's per-write evidence bucketed onto the same rounds on the
    right.  Both tiers already rendered separately; nothing joined
    them, so debugging parity drift meant eyeballing two artifacts
    against a mental clock — this puts the publish→visible latencies
    next to the round that should have carried them."""
    if head.get("tier") == "host":
        print(
            "error: --in must be the SIM-tier artifact when --parity "
            "is given (the host file goes to --parity)",
            file=sys.stderr,
        )
        return 2
    with open(args.parity) as f:
        phead = json.loads(f.readline())
        prows = [json.loads(line) for line in f if line.strip()]
    if (
        phead.get("kind") != "flight_recorder"
        or phead.get("tier") != "host"
    ):
        print(
            f"error: {args.parity} is not a HOST-tier flight-recorder "
            "artifact (--parity joins a sim lane to its host replay)",
            file=sys.stderr,
        )
        return 2
    if args.round_s is not None and args.round_s <= 0:
        # a non-positive bucket width would drop every host write into
        # rounds the table never renders — the operator would read
        # "host recorded nothing" off a join artifact of their own flag
        print(
            f"error: --round-s must be > 0 (got {args.round_s})",
            file=sys.stderr,
        )
        return 2
    round_s = args.round_s if args.round_s is not None else 0.05
    # bucket host writes by sim round: host row "t" is seconds since
    # the first publish, one sim round ≈ round_s of host wall
    buckets: dict = {}
    for pr in prows:
        t = int(float(pr.get("t", 0.0)) // round_s)
        buckets.setdefault(t, []).append(pr)
    n_rounds = max(
        [len(rows)] + [t + 1 for t in buckets]
    )
    joined = []
    for t in range(n_rounds):
        sim = rows[t] if t < len(rows) else {}
        host = buckets.get(t, [])
        vis = [
            h["publish_to_visible_ms"]
            for h in host
            if h.get("publish_to_visible_ms") is not None
        ]
        lag = [
            h["hlc_lag_ms"] for h in host if h.get("hlc_lag_ms") is not None
        ]
        joined.append(
            {
                "t": t,
                "coverage_frac": sim.get("coverage_frac"),
                "delivered": sim.get("delivered"),
                "bcast_bytes": sim.get("bcast_bytes"),
                "sync_sessions": sim.get("sync_sessions"),
                "host_writes": len(host),
                "host_visible_ms_max": max(vis) if vis else None,
                "host_hlc_lag_ms_max": max(lag) if lag else None,
            }
        )
    if args.json:
        _print_json(
            {
                "header": head,
                "parity_header": phead,
                "round_s": round_s,
                "rounds": joined,
            }
        )
        return 0
    print(
        f"sim lane ⋈ host parity replay (round_s={round_s}): "
        f"{len(rows)} sim rounds, {len(prows)} host writes"
    )
    for k in ("campaign", "cell_index", "seed", "traceparent"):
        if k in head:
            print(f"  sim {k}: {head[k]}")
        if k in phead:
            print(f"  host {k}: {phead[k]}")
    cols = (
        "t", "coverage_frac", "delivered", "bcast_bytes",
        "sync_sessions", "host_writes", "host_visible_ms_max",
        "host_hlc_lag_ms_max",
    )
    print("  ".join(f"{c:>19}" for c in cols))
    for row in joined:
        print(
            "  ".join(
                f"{('' if row.get(c) is None else row.get(c)):>19}"
                for c in cols
            )
        )
    return 0


def _cell_round_path(c: dict) -> str:
    """Which execution path a campaign cell ran: a round kernel
    ("packed" | "dense"), the HOST serving path (ISSUE 8 cells), or
    "unknown" for cells resumed from pre-round_path artifacts — ONE
    mapping shared by the report table and the run summary's
    kernel_paths."""
    if c.get("kind") == "host-serving":
        return "host"
    return c.get("round_path", "unknown")


def cmd_campaign(args) -> int:
    """`sim campaign run|compare|report` (corrosion_tpu.campaign):
    declarative seed-ensemble campaigns with convergence regression
    bands.

    - ``run``: execute a spec (builtin name or JSON file) and write the
      band artifact; resumable via the artifact path, wall-budgeted via
      ``--budget-s``; ``--telemetry``/``--trace-dir`` thread the flight
      recorder through every cell.
    - ``compare``: hold a candidate artifact against a baseline; exits 1
      on a regress verdict (the nightly gate's teeth).
    - ``report``: print an artifact's band summary — with
      ``--telemetry``, the per-cell flight-recorder blocks too.
    """
    import os as _os

    from ..campaign import BUILTIN_SPECS, builtin_spec, load_spec
    from ..campaign.engine import run_campaign
    from ..campaign.report import compare

    if args.campaign_cmd == "report":
        path = args.in_path or args.candidate
        if not path:
            raise SystemExit("sim campaign report needs --in ARTIFACT")
        with open(path) as f:
            art = json.load(f)
        out = {
            "name": art.get("spec", {}).get("name"),
            "spec_hash": art.get("spec_hash"),
            "result_digest": art.get("result_digest"),
            "skipped_cells": art.get("skipped_cells", []),
            "cells": [],
        }
        for c in art.get("cells", []):
            serving = c.get("kind") == "host-serving"
            entry = {
                "params": c.get("params", {}),
                # host-serving cells (ISSUE 8) ran the serving path, not
                # a round kernel — report them in the SAME table, their
                # latency bands alongside the sim cells' round bands
                "round_path": _cell_round_path(c),
                # the realized mesh per cell (ISSUE 7): which devices the
                # round_path above actually partitioned over — None /
                # absent = unsharded (or a pre-sharding artifact)
                "mesh": c.get("mesh"),
                "all_converged": c.get("all_converged"),
                "bands": c.get("bands", {}),
            }
            if serving:
                entry["kind"] = "host-serving"
                entry["consistent"] = c.get("per_seed", {}).get(
                    "consistent"
                )
                entry["use_faults"] = c.get("use_faults")
            if c.get("traceparent"):
                entry["traceparent"] = c["traceparent"]
            if args.telemetry and "telemetry" in c:
                entry["telemetry"] = c["telemetry"]
            out["cells"].append(entry)
        _print_json(out)
        return 0

    if args.campaign_cmd == "compare":
        if not (args.baseline and args.candidate):
            raise SystemExit(
                "sim campaign compare needs --baseline and --candidate"
            )
        with open(args.baseline) as f:
            base = json.load(f)
        with open(args.candidate) as f:
            cand = json.load(f)
        report = compare(
            base, cand, tol_frac=args.tol_frac, tol_abs=args.tol_abs
        )
        print(json.dumps(report, indent=2, default=float))
        return 0 if report["verdict"] == "pass" else 1

    if args.campaign_cmd != "run":
        raise SystemExit("usage: sim campaign {run|compare|report} ...")
    if args.devices:
        # the scenario flag on a campaign run would be silently ignored
        # — same loud refusal the scenario path gives --mesh-devices
        raise SystemExit(
            "error: campaign runs shard via --mesh-devices N, "
            "not --devices"
        )
    for flag, val in (
        ("--proto", args.proto),
        ("--topology", args.topology),
        ("--sampler", args.sampler),
    ):
        if val:
            # the scenario axis flags would be silently ignored here —
            # a user would believe they swept a variant the spec never
            # named; axes ride the spec (scenario/grid keys) on
            # campaign runs
            raise SystemExit(
                f"error: {flag} is a scenario-run flag; campaign cells "
                "take the axis as a spec scenario/grid key "
                f"(e.g. proto_family / topo_family / peer_sampler)"
            )
    if not args.spec:
        raise SystemExit(
            f"--spec required: a JSON spec file or one of "
            f"{sorted(BUILTIN_SPECS)}"
        )
    # --seeds/--seed override the spec's seed set ONLY when given: a
    # builtin (or file) spec keeps its own documented seed set otherwise
    # (fault-parity-3node defaults to 8 seeds — collapsing it to one
    # would silently change the spec hash and break baselines).
    # `--seed 0` counts as given (default is None, not 0).
    seed_override = None
    if args.seeds is not None or args.seed is not None:
        base = args.seed if args.seed is not None else 0
        seed_override = [base + i for i in range(max(1, args.seeds or 1))]
    if _os.path.exists(args.spec):
        spec = load_spec(args.spec)
        if seed_override is not None:
            import dataclasses as _dc

            spec = _dc.replace(spec, seeds=tuple(seed_override))
    else:
        spec = builtin_spec(args.spec, seeds=seed_override)
    out = args.out or f"CAMPAIGN_{spec.name}_{spec.spec_hash()}.json"
    artifact = run_campaign(
        spec, out_path=out, wall_budget_s=args.budget_s,
        resume=not args.no_resume,
        telemetry=args.telemetry or None,
        trace_dir=args.trace_dir,
        mesh_devices=args.mesh_devices,
    )
    summary = {
        "spec_hash": artifact["spec_hash"],
        "result_digest": artifact["result_digest"],
        "artifact": out,
        "cells": len(artifact["cells"]),
        "skipped_cells": artifact["skipped_cells"],
        "all_converged": all(
            c.get("all_converged", False) for c in artifact["cells"]
        ),
        # serving cells band latency seconds, sim cells band rounds —
        # one summary table either way (ISSUE 8)
        "bands": {
            json.dumps(c.get("params", {}), sort_keys=True): (
                c["bands"].get("rounds")
                or c["bands"].get("publish_visible_p99_s")
            )
            for c in artifact["cells"]
        },
        # which round kernels each grid point ran (ISSUE 4): dense
        # fallbacks must be visible, not silent — a fault sweep that
        # quietly dropped off the packed path costs 4-30× per primitive.
        # Cells resumed from a pre-round_path artifact report "unknown",
        # never a false "dense" alarm.  Since ISSUE 7 the path is
        # reported PER MESH — "packed@nodes=8" says the packed kernels
        # ran node-split over 8 devices; no suffix = unsharded.
        "kernel_paths": {
            json.dumps(c.get("params", {}), sort_keys=True): (
                _cell_round_path(c)
                + (
                    "@nodes={}".format(c["mesh"]["axes"]["nodes"])
                    if c.get("mesh")
                    else ""
                )
            )
            for c in artifact["cells"]
        },
    }
    print(json.dumps(summary, indent=2, default=float))
    return 0


# -- parser ---------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corrosion-tpu",
        description="TPU-native gossip-replicated state (corrosion rebuild)",
    )
    p.add_argument("-c", "--config", default="corrosion.toml", help="config file")
    p.add_argument("--api-addr", help="override [api] addr")
    p.add_argument("--db-path", help="override [db] path")
    p.add_argument("--admin-path", help="override [admin] path")
    sp = p.add_subparsers(dest="command", required=True)

    sp.add_parser("agent", help="run the agent").set_defaults(fn=cmd_agent)

    b = sp.add_parser("backup", help="snapshot the DB, stripped of node state")
    b.add_argument("path")
    b.set_defaults(fn=cmd_backup)

    r = sp.add_parser("restore", help="restore a backup over the live DB")
    r.add_argument("path")
    r.add_argument("--site-id", help="pin the restored actor id (hex)")
    r.set_defaults(fn=cmd_restore)

    db = sp.add_parser("db", help="database utilities")
    dbs = db.add_subparsers(dest="db_cmd", required=True)
    lk = dbs.add_parser("lock", help="hold exclusive locks on the DB files")
    lk.add_argument("--once", action="store_true", help=argparse.SUPPRESS)
    lk.set_defaults(fn=cmd_db_lock)

    q = sp.add_parser("query", help="run a SQL query via the HTTP API")
    q.add_argument("sql")
    q.add_argument("--columns", action="store_true", help="print column header")
    q.add_argument("--timer", action="store_true", help="print elapsed time")
    q.add_argument("--param", action="append", help="bind a parameter")
    q.set_defaults(fn=cmd_query)

    e = sp.add_parser("exec", help="execute a write statement via the HTTP API")
    e.add_argument("sql")
    e.add_argument("--param", action="append")
    e.add_argument("--timer", action="store_true")
    e.set_defaults(fn=cmd_exec)

    sp.add_parser(
        "reload", help="hot-reload schema files on a running agent"
    ).set_defaults(fn=cmd_reload)

    sy = sp.add_parser("sync", help="sync bookkeeping introspection")
    sys_ = sy.add_subparsers(dest="sync_cmd", required=True)
    sys_.add_parser("generate", help="dump this node's sync state").set_defaults(
        fn=cmd_sync
    )
    sys_.add_parser(
        "reconcile-gaps", help="clear gaps whose data is actually present"
    ).set_defaults(fn=cmd_sync)

    lo = sp.add_parser("locks", help="dump the lock registry")
    lo.add_argument("--top", type=int, default=10)
    lo.set_defaults(fn=cmd_locks)

    cl = sp.add_parser("cluster", help="cluster membership commands")
    cls_ = cl.add_subparsers(dest="cluster_cmd", required=True)
    for name, help_ in (
        ("rejoin", "rejoin the cluster with a renewed identity"),
        ("members", "list known members"),
        ("membership-states", "dump SWIM state for every member"),
    ):
        cls_.add_parser(name, help=help_).set_defaults(fn=cmd_cluster)
    si = cls_.add_parser("set-id", help="set the cluster id")
    si.add_argument("id", type=int)
    si.set_defaults(fn=cmd_cluster)

    ac = sp.add_parser("actor", help="actor introspection")
    acs = ac.add_subparsers(dest="actor_cmd", required=True)
    av = acs.add_parser("version", help="classify a (actor, version)")
    av.add_argument("actor_id")
    av.add_argument("version", type=int)
    av.set_defaults(fn=cmd_actor)

    su = sp.add_parser("subs", help="subscription introspection")
    sus = su.add_subparsers(dest="subs_cmd", required=True)
    sus.add_parser("list", help="list subscriptions").set_defaults(fn=cmd_subs)
    sin = sus.add_parser("info", help="detail one subscription")
    sin.add_argument("--id", required=True)
    sin.set_defaults(fn=cmd_subs)

    lg = sp.add_parser("log", help="dynamic log filtering")
    lgs = lg.add_subparsers(dest="log_cmd", required=True)
    ls_ = lgs.add_parser("set", help="set the log level")
    ls_.add_argument("filter")
    ls_.set_defaults(fn=cmd_log)
    lgs.add_parser("reset", help="reset the log level").set_defaults(fn=cmd_log)

    tl = sp.add_parser("tls", help="generate TLS certificates")
    tls_ = tl.add_subparsers(dest="tls_kind", required=True)
    ca = tls_.add_parser("ca", help="generate a self-signed CA")
    ca.add_argument("generate", choices=["generate"])
    ca.add_argument("-o", "--output", default=".")
    ca.set_defaults(fn=cmd_tls)
    srv = tls_.add_parser("server", help="generate a server certificate")
    srv.add_argument("generate", choices=["generate"])
    srv.add_argument("ip")
    srv.add_argument("--ca-cert", required=True)
    srv.add_argument("--ca-key", required=True)
    srv.add_argument("-o", "--output", default=".")
    srv.set_defaults(fn=cmd_tls)
    cli = tls_.add_parser("client", help="generate a client certificate")
    cli.add_argument("generate", choices=["generate"])
    cli.add_argument("--ca-cert", required=True)
    cli.add_argument("--ca-key", required=True)
    cli.add_argument("-o", "--output", default=".")
    cli.set_defaults(fn=cmd_tls)

    tp = sp.add_parser("template", help="render a template against the API")
    tp.add_argument("template")
    tp.add_argument("-o", "--output")
    tp.add_argument("--once", action="store_true", help="render once and exit")
    tp.set_defaults(fn=cmd_template)

    co = sp.add_parser("consul", help="consul integration")
    cos = co.add_subparsers(dest="consul_cmd", required=True)
    cs = cos.add_parser("sync", help="replicate consul services/checks")
    cs.add_argument("--consul-addr", default="127.0.0.1:8500")
    cs.add_argument("--node", default=None, help="node name override")
    cs.add_argument("--once", action="store_true", help="one sync pass then exit")
    cs.set_defaults(fn=cmd_consul)

    sm = sp.add_parser(
        "sim",
        help="run a TPU-simulator benchmark config, "
        "`sim campaign run|compare|report` for declarative seed-ensemble "
        "campaigns, `sim trace show` for flight-recorder artifacts, "
        "`sim topo show` for topology families, `sim proto show` for "
        "protocol-variant families, `sim profile show|compare|baseline` "
        "for phase-attribution ledgers (doc/telemetry/profiling.md), or "
        "`sim lint` for the corrolint static-analysis gate (doc/lint.md)",
    )
    sm.add_argument(
        "scenario",
        choices=sorted(_SIM_SCENARIOS)
        + ["campaign", "trace", "topo", "proto", "profile", "lint"],
    )
    sm.add_argument(
        "campaign_cmd", nargs="?",
        choices=["run", "compare", "report", "show", "baseline"],
        help="campaign action (scenario=campaign), `show` "
        "(scenario=trace | topo | proto | profile), or "
        "`compare`/`baseline` (scenario=profile)",
    )
    # default None so "explicitly given" is detectable: campaign run
    # must distinguish `--seed 0` (override to one seed) from "no seed
    # flags at all" (keep the spec's own seed set)
    sm.add_argument("--seed", type=int, default=None)
    sm.add_argument(
        "--seeds", type=int, default=None,
        help="run N seeds and report cross-seed percentiles "
        "(campaign run: the ensemble seed set, seed..seed+N-1; "
        "omitted = the spec's own seed set)",
    )
    sm.add_argument("--nodes", type=int, default=None)
    sm.add_argument(
        "--workers", type=int, default=None,
        help="multi-process serving rung (ISSUE 13): loadgen worker "
        "process count",
    )
    sm.add_argument(
        "--writers", type=int, default=None,
        help="serving rungs: total writer lane count",
    )
    sm.add_argument(
        "--devices", type=int, default=None,
        help="sharded scenarios (ISSUE 7): cap the 1-D nodes mesh at N "
        "devices (default: every visible device)",
    )
    sm.add_argument(
        "--mesh-devices", type=int, default=None,
        help="campaign run: shard every cell's node axis over up to N "
        "devices (mesh × lane batching; results and digests are "
        "unchanged — the realized mesh is recorded per cell)",
    )
    sm.add_argument(
        "--topology", metavar="FAMILY",
        help="topology family (ISSUE 9): axis-aware scenario runs take "
        "it as the cell topology; `sim topo show --topology F` renders "
        "its tier table (omit to list families)",
    )
    sm.add_argument(
        "--sampler", choices=["uniform", "peerswap"],
        help="peer-selection seam (ISSUE 9) on axis-aware scenarios",
    )
    sm.add_argument(
        "--proto", metavar="FAMILY",
        help="protocol-variant family (ISSUE 11): axis-aware scenario "
        "runs take it as the cell's protocol point; `sim proto show "
        "--proto F` renders its resolved knobs (omit to list families)",
    )
    sm.add_argument(
        "--spec", help="campaign run: JSON spec file or builtin name"
    )
    sm.add_argument(
        "--out", help="campaign run: artifact path (resumable)"
    )
    sm.add_argument(
        "--budget-s", type=float, default=None,
        help="campaign run: wall-clock budget; leftover cells are "
        "skipped and resumed next run",
    )
    sm.add_argument(
        "--no-resume", action="store_true",
        help="campaign run: ignore an existing artifact",
    )
    sm.add_argument(
        "--baseline",
        help="campaign compare: baseline artifact; lint: baseline file "
        "(default: <repo>/LINT_BASELINE.json)",
    )
    sm.add_argument("--candidate", help="campaign compare: candidate artifact")
    sm.add_argument(
        "--tol", type=float, default=None,
        help="profile baseline: per-phase fraction tolerance "
        "(default 0.05; widen to absorb box scheduling variance)",
    )
    sm.add_argument(
        "--phase-max", action="append", metavar="PHASE=FRAC",
        help="profile baseline: one-sided phase-fraction CEILING "
        "(repeatable), written into the baseline as phase_frac_max — "
        "unlike the two-sided ± tol bands, a ceiling only pages when "
        "the phase GROWS (the fused-round gate pins corro.telemetry "
        "below its pre-fusion share; ISSUE 19)",
    )
    sm.add_argument(
        "--telemetry", action="store_true",
        help="flight recorder (ISSUE 5): record in-kernel per-round "
        "telemetry (scenario runs gain a summary block; campaign run "
        "threads it through every cell; campaign report prints it)",
    )
    sm.add_argument(
        "--trace-out",
        help="scenario runs: write the flight-recorder JSONL here "
        "(implies --telemetry)",
    )
    sm.add_argument(
        "--trace-dir",
        help="campaign run: write per-(cell, lane) flight-recorder "
        "JSONL traces here (implies --telemetry)",
    )
    sm.add_argument(
        "--in", dest="in_path",
        help="trace show / campaign report: input artifact path",
    )
    sm.add_argument(
        "--parity", metavar="HOST_FILE",
        help="trace show: join the sim lane (--in) to its HOST-tier "
        "parity replay artifact side-by-side (ISSUE 11 — per-write "
        "publish→visible evidence bucketed onto sim rounds)",
    )
    sm.add_argument(
        "--round-s", type=float, default=None,
        help="trace show --parity: host wall-clock seconds per sim "
        "round for the join (default 0.05, the campaign spec round_s)",
    )
    sm.add_argument(
        "--json", action="store_true",
        help="trace show: raw JSON instead of the table",
    )
    sm.add_argument(
        "--xla-profile", metavar="DIR",
        help="capture a jax.profiler (TensorBoard) trace of the run "
        "into DIR; scenarios with phase attribution (ISSUE 16) also "
        "write DIR/phase_map.json and attach a parsed phase_profile "
        "block to the record",
    )
    sm.add_argument(
        "--format", choices=["text", "json"], default="text",
        help="lint: output format (CI archives the json form)",
    )
    sm.add_argument(
        "--no-baseline", action="store_true",
        help="lint: ignore the committed baseline (report everything)",
    )
    sm.add_argument(
        "--baseline-write", action="store_true",
        help="lint: regenerate the baseline from this run's findings "
        "(deterministic: sorted, content-stable fingerprints)",
    )
    sm.add_argument(
        "--tol-frac", type=float, default=0.10,
        help="campaign compare: fractional band tolerance",
    )
    sm.add_argument(
        "--tol-abs", type=float, default=2.0,
        help="campaign compare: absolute band tolerance (rounds)",
    )
    sm.set_defaults(fn=cmd_sim)

    dc = sp.add_parser(
        "devcluster", help="spawn a real multi-process cluster from a topology file"
    )
    dc.add_argument("topology", help="file of 'A -> B' bootstrap edges")
    dc.add_argument("--state-dir", required=True, help="per-node state root")
    dc.add_argument("--schema-dir", required=True, help="schema .sql dir")
    dc.add_argument("--base-port", type=int, default=0, help="0 = OS-assigned")
    dc.set_defaults(fn=cmd_devcluster)

    lgn = sp.add_parser(
        "loadgen",
        help="flood writes + validate subscription consistency "
        "(measured driver: N writers × M watchers, latency percentiles)",
    )
    lgn.add_argument(
        "--write-addr", required=True, action="append",
        help="API addr written to (repeatable: writers round-robin)",
    )
    lgn.add_argument(
        "--read-addr", default=None, action="append",
        help="API addr watched (repeatable; default: write addrs)",
    )
    lgn.add_argument("--table", default="tests")
    lgn.add_argument("--writes", type=int, default=100)
    lgn.add_argument("--writers", type=int, default=1)
    lgn.add_argument("--watchers", type=int, default=1)
    lgn.add_argument("--rate", type=float, default=200.0)
    lgn.add_argument("--settle-timeout", type=float, default=30.0)
    lgn.add_argument(
        "--base-id", type=int, default=None,
        help="first row id (default: microsecond-derived, so repeated "
        "runs against a live cluster don't collide with their own "
        "stale rows — a fixed base would re-see run N-1's rows in the "
        "subscription snapshot and mask lost writes)",
    )
    lgn.set_defaults(fn=cmd_loadgen)

    return p


def cmd_devcluster(args) -> int:
    """Topology-file-driven multi-process cluster
    (corro-devcluster/src/main.rs:102-240)."""
    from ..devcluster import DevCluster, Topology

    topo = Topology.load(args.topology)
    cluster = DevCluster(
        topo, args.state_dir, args.schema_dir, base_port=args.base_port
    )
    cluster.write_configs()
    for name, node in cluster.nodes.items():
        print(f"node {name}: gossip 127.0.0.1:{node.gossip_port} api {node.api_addr}")
    try:
        cluster.start()
        cluster.wait_ready()
    except BaseException:
        cluster.stop()  # don't orphan already-spawned agents
        raise
    print(f"devcluster up: {len(cluster.nodes)} nodes", flush=True)
    return cluster.run_forever()


def cmd_loadgen(args) -> int:
    """Workload driver (.antithesis/client/src/main.rs:65-308): exit 0
    iff every committed write surfaced on every watched subscription.
    The report carries publish→visible latency percentiles (ISSUE 8)."""
    from ..loadgen import LoadGenerator

    gen = LoadGenerator(
        args.write_addr, args.read_addr, table=args.table,
        n_writers=args.writers, n_watchers=args.watchers,
    )
    # microsecond resolution: two scripted runs collide only if they
    # start in the same µs (second-granularity left same-second runs —
    # and >1000-write runs 1 s apart — overlapping their id ranges)
    base_id = (
        args.base_id
        if args.base_id is not None
        else 1_000_000 + time.time_ns() // 1_000 % 10**12
    )
    report = asyncio.run(
        gen.run(
            n_writes=args.writes,
            rate_hz=args.rate,
            settle_timeout_s=args.settle_timeout,
            base_id=base_id,
        )
    )
    _print_json(report.to_dict())
    return 0 if report.consistent else 1


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
