"""Device-mesh sharding for the simulator.

The cluster's node axis is the parallel axis (SURVEY §2.3: full-state
replication ⇒ node-major sharded state matrix): every SimState array is
sharded on its node dimension across a 1-D ``nodes`` mesh, payload metadata
is replicated, and XLA/GSPMD inserts the collectives for the cross-shard
scatters (fan-out targets land on other shards' rows — the ICI all-to-all
the north star describes).

No hand-written shard_map: the round step is pure gather/scatter/elementwise,
exactly the op mix GSPMD partitions well.  `dryrun_multichip` in
`__graft_entry__` compiles this path on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.round import RunMetrics
from ..sim.state import PayloadMeta, SimState

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(devices[:n], (NODE_AXIS,))


def state_shardings(mesh: Mesh, swim_full_view: bool) -> SimState:
    """A SimState-shaped pytree of NamedShardings (node axis split)."""
    r = NamedSharding(mesh, P())  # replicated
    n0 = NamedSharding(mesh, P(NODE_AXIS))
    n0p = NamedSharding(mesh, P(NODE_AXIS, None))
    n0ak = NamedSharding(mesh, P(NODE_AXIS, None, None))
    dn = NamedSharding(mesh, P(None, NODE_AXIS, None))
    swim = n0p if swim_full_view else r
    return SimState(
        t=r, key=r,
        have=n0p, injected=r, relay_left=n0p, inflight=dn,
        sync_inflight=dn,
        sync_countdown=n0, sync_backoff=n0, alive=n0, incarnation=n0,
        group=n0,
        view=swim, vinc=swim, suspect_since=swim,
        converged_at=n0,
        heads=n0p, gap_lo=n0ak, gap_hi=n0ak,
        pid=n0p, pkey=n0p, psince=n0p,
    )


def metrics_shardings(mesh: Mesh) -> RunMetrics:
    return RunMetrics(
        coverage_at=NamedSharding(mesh, P()),
        converged_at=NamedSharding(mesh, P(NODE_AXIS)),
        overflow_frac=NamedSharding(mesh, P()),
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an existing state onto the mesh, node axis split."""
    shardings = state_shardings(mesh, state.view.size > 0)
    return jax.tree.map(jax.device_put, state, shardings)


def replicate_meta(meta: PayloadMeta, mesh: Mesh) -> PayloadMeta:
    r = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, r), meta)
