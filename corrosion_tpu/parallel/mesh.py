"""Device-mesh sharding for the simulator: dense AND packed envelopes.

The cluster's node axis is the parallel axis (SURVEY §2.3: full-state
replication ⇒ node-major sharded state matrix): every SimState array is
sharded on its node dimension across a 1-D ``nodes`` mesh, payload metadata
is replicated, and XLA/GSPMD inserts the collectives for the cross-shard
scatters (fan-out targets land on other shards' rows — the ICI all-to-all
the north star describes).

Since ISSUE 7 the sharding layer covers the BITPACK envelope too — the
only path that reaches 100k+ nodes (`sim/packed.py`):

- `packed_carry_shardings` splits the NODE axis of the u32-word carry
  (``have[N, W]``, the four bitsliced relay planes, the packed sync
  ring) and of the dense u8 broadcast ring.  The payload-WORD axis is
  never split, so shard boundaries are word-aligned by construction and
  every word-local kernel (`pack_bits`, the `_fold_*` group folds,
  `group_grid`, `budget_prefix_words`) runs entirely inside its shard;
- `fault_plan_shardings` keeps the `FactoredFaultPlan` rank-1 node
  masks (``*_src``/``*_dst``/``alive``/``wipe``) sharded WITH their
  nodes, so a 1M-node fault tensor never materializes replicated;
- `constrain_replicated` pins the `RoundTrace` [R_max, ·]
  flight-recorder buffers REPLICATED inside the telemetry loop bodies:
  every telemetry channel is the result of a cross-shard fold
  (psum-style — see doc/sharding.md "collective folds"), so replication
  is the correct (and only safe) layout — a node-split trace row would
  silently record one shard's partial sums;
- `constrain_packed` / `constrain_replicated` re-pin the layouts inside
  the jitted while_loops (`run_packed` / `run_packed_faults`), so GSPMD
  keeps the node split stable across rounds instead of re-deriving it
  per iteration.

The per-round reductions — the convergence AND-fold over nodes, the
`all_have_words` exit predicate, wire-byte and telemetry counter sums —
reduce over the sharded node axis, which GSPMD lowers to all-reduces.
Swing/Flare (PAPERS.md) teach that on a 1-D ring the bandwidth-optimal
schedule for these small folds is the latency-bound one — exactly what
XLA emits for scalar/[P]-sized all-reduces — so no hand-written
collective is needed; the layout's job is to keep the reduced operands
node-split (cheap partial sums per shard) and the results replicated.

No hand-written shard_map: the round step is pure gather/scatter/elementwise,
exactly the op mix GSPMD partitions well.  `dryrun_multichip` in
`__graft_entry__` compiles this path on a virtual 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..sim.round import RunMetrics
from ..sim.state import PayloadMeta, SimState

NODE_AXIS = "nodes"


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(devices[:n], (NODE_AXIS,))


def state_shardings(mesh: Mesh, swim_full_view: bool) -> SimState:
    """A SimState-shaped pytree of NamedShardings (node axis split)."""
    r = NamedSharding(mesh, P())  # replicated
    n0 = NamedSharding(mesh, P(NODE_AXIS))
    n0p = NamedSharding(mesh, P(NODE_AXIS, None))
    n0ak = NamedSharding(mesh, P(NODE_AXIS, None, None))
    dn = NamedSharding(mesh, P(None, NODE_AXIS, None))
    swim = n0p if swim_full_view else r
    return SimState(
        t=r, key=r,
        have=n0p, injected=r, relay_left=n0p, inflight=dn,
        sync_inflight=dn,
        sync_countdown=n0, sync_backoff=n0, alive=n0, incarnation=n0,
        group=n0,
        view=swim, vinc=swim, suspect_since=swim,
        converged_at=n0,
        heads=n0p, gap_lo=n0ak, gap_hi=n0ak,
        pid=n0p, pkey=n0p, psince=n0p,
        pview=n0p,
    )


def metrics_shardings(mesh: Mesh) -> RunMetrics:
    return RunMetrics(
        coverage_at=NamedSharding(mesh, P()),
        converged_at=NamedSharding(mesh, P(NODE_AXIS)),
        overflow_frac=NamedSharding(mesh, P()),
        # cross-shard fold result (ISSUE 11), replicated like every
        # other finished reduction
        order_violations=NamedSharding(mesh, P()),
    )


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place an existing state onto the mesh, node axis split."""
    shardings = state_shardings(mesh, state.view.size > 0)
    return jax.tree.map(jax.device_put, state, shardings)


def replicate_meta(meta: PayloadMeta, mesh: Mesh) -> PayloadMeta:
    r = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, r), meta)


# -- packed envelope (ISSUE 7) ----------------------------------------------


def packed_carry_shardings(mesh: Mesh):
    """A PackedCarry-shaped pytree of NamedShardings: the NODE axis of
    every carry tensor is split, the payload-word axis never is — shard
    boundaries land between node rows, so they are word-aligned by
    construction and `pack_bits`/`_fold_*`/`group_grid` stay local to
    their shard (the module-doc invariant)."""
    from ..sim.packed import PackedCarry, Planes

    n0w = NamedSharding(mesh, P(NODE_AXIS, None))     # u32[N, W]
    dnp = NamedSharding(mesh, P(None, NODE_AXIS, None))  # [D, N, P]
    return PackedCarry(
        have=n0w,
        inflight=dnp,
        relay=Planes(n0w, n0w, n0w, n0w),
        sync_buf=dnp,
    )


def fault_plan_shardings(fplan, mesh: Mesh):
    """A pytree of NamedShardings matching ``fplan``: the
    `FactoredFaultPlan` rank-1 node masks and the [R+1, N] alive/wipe
    schedules shard WITH their nodes (a 1M-node plan's fault tensors
    must never sit replicated on every device); the tiny per-factor
    active/threshold vectors replicate.  The matrix `SimFaultPlan` form
    (only compiled below `FACTORED_MIN_NODES`) replicates whole — its
    [R+1, N, N] slabs are gathered by BOTH endpoints of an edge, so at
    sub-1024-node scale replication is cheaper than the two-sided
    collective a split would force."""
    from ..sim.faults import FactoredFaultPlan

    r = NamedSharding(mesh, P())
    if not isinstance(fplan, FactoredFaultPlan):
        return jax.tree.map(lambda _: r, fplan)
    rn = NamedSharding(mesh, P(None, NODE_AXIS))  # [R+1, N] / [K, N]
    return FactoredFaultPlan(
        alive=rn, wipe=rn, seed=r,
        block_active=r, block_src=rn, block_dst=rn,
        loss_active=r, loss_src=rn, loss_dst=rn, loss_thr=r,
        delay_active=r, delay_src=rn, delay_dst=rn, delay_rounds=r,
        jitter_active=r, jitter_src=rn, jitter_dst=rn, jitter_rounds=r,
    )


def shard_fault_plan(fplan, mesh: Mesh):
    """Place a compiled fault plan onto the mesh (fault rows sharded
    with their nodes; see `fault_plan_shardings`)."""
    return jax.tree.map(jax.device_put, fplan, fault_plan_shardings(fplan, mesh))


def place_run(state: SimState, meta: PayloadMeta, fplan, mesh: Optional[Mesh]):
    """Mesh-place one run's inputs (identity when ``mesh`` is None):
    state node-split, metadata replicated, compiled fault plan (or
    None) riding its `fault_plan_shardings` — the ONE placement rule
    every sharded entry point shares (runner rungs, perf microbench,
    the graft dryrun; `campaign.ensemble.place_ensemble` is the stacked
    [K, ...] twin)."""
    if mesh is None:
        return state, meta, fplan
    state = shard_state(state, mesh)
    meta = replicate_meta(meta, mesh)
    if fplan is not None:
        fplan = shard_fault_plan(fplan, mesh)
    return state, meta, fplan


def constrain_packed(carry, mesh: Optional[Mesh]):
    """Re-pin the packed carry's node-split layout inside a jitted loop
    (identity when ``mesh`` is None — the single-device and vmapped
    ensemble paths compile exactly as before)."""
    if mesh is None:
        return carry
    return jax.lax.with_sharding_constraint(
        carry, packed_carry_shardings(mesh)
    )


def constrain_metrics(metrics: RunMetrics, mesh: Optional[Mesh]) -> RunMetrics:
    """Pin RunMetrics layouts inside a jitted loop: per-node
    ``converged_at`` sharded with its nodes, the per-payload and scalar
    channels replicated (they are cross-shard fold results)."""
    if mesh is None:
        return metrics
    return jax.lax.with_sharding_constraint(metrics, metrics_shardings(mesh))


def constrain_replicated(tree, mesh: Optional[Mesh]):
    """Pin a pytree replicated — the layout of every cross-shard fold
    result (metrics, trace rows, exit predicates)."""
    if mesh is None:
        return tree
    r = NamedSharding(mesh, P())
    return jax.lax.with_sharding_constraint(
        tree, jax.tree.map(lambda _: r, tree)
    )


# -- mesh × lane batching (vmapped seed ensembles over a sharded node axis) --


def _with_lane_axis(sharding_tree):
    """Prepend an UNsharded lane axis to every spec: ensemble lanes are
    batch-replicated across the mesh while the node axis stays split —
    the mesh × lane layout campaign cells run under."""

    def lane(sh: NamedSharding) -> NamedSharding:
        return NamedSharding(sh.mesh, P(None, *sh.spec))

    return jax.tree.map(lane, sharding_tree)


def shard_ensemble_states(states: SimState, mesh: Mesh) -> SimState:
    """Place stacked [K, ...] ensemble states: node axis split, lane
    axis whole (mesh × lane batching)."""
    sh = _with_lane_axis(state_shardings(mesh, states.view.size > 0))
    return jax.tree.map(jax.device_put, states, sh)


def padded_node_count(n_nodes: int, n_devices: int) -> int:
    """Smallest multiple of ``n_devices`` ≥ ``n_nodes``: explicit
    NamedSharding placement requires the sharded axis to divide evenly
    (this JAX rejects uneven shards at device_put/out_shardings), so a
    non-divisible cluster pads its node axis up and marks the tail
    permanently DOWN (`down_padding`)."""
    return -(-int(n_nodes) // int(n_devices)) * int(n_devices)


def down_padding(state: SimState, n_real: int) -> SimState:
    """Mark every node row ≥ ``n_real`` permanently DOWN — the padding
    members a non-divisible cluster carries so its node axis divides the
    mesh.  DOWN rows are excluded from every coverage/convergence fold
    by the existing up-mask algebra (the same masks that exclude crashed
    nodes), so padding can never leak into coverage counts — pinned by
    tests/sim/test_packed_sharded.py."""
    from ..sim.state import DOWN

    idx = jnp.arange(state.alive.shape[0])
    return state._replace(
        alive=jnp.where(
            idx >= n_real, jnp.asarray(DOWN, state.alive.dtype), state.alive
        )
    )


def mesh_size(mesh: Optional[Mesh]) -> int:
    """Device count of a (possibly absent) mesh — the ONE derivation
    the bench records, `verify_wall` floors, and campaign artifacts
    share (None = unsharded = 1)."""
    if mesh is None:
        return 1
    return int(len(mesh.devices.flat))


def mesh_record(mesh: Optional[Mesh]):
    """The artifact/bench description of a mesh: JSON-friendly shape."""
    if mesh is None:
        return None
    return {
        "axes": {k: int(v) for k, v in mesh.shape.items()},
        "n_devices": mesh_size(mesh),
        "platform": mesh.devices.flat[0].platform,
    }
