"""SubsManager + UpdatesManager: subscription registries and change fan-out.

Rebuild of `SubsManager::get_or_insert/restore` (corro-types/src/pubsub.rs:
108-186) and the lighter per-table `UpdatesManager` (updates.rs:61-268).
``match_changes`` is the hook the agent calls after every committed batch
(updates.rs:420-481); subscribers attach asyncio queues that receive the
NDJSON-protocol event dicts (the broadcast::channel fanout, agent/mod.rs:39).
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.pkcodec import decode_pk
from ..core.types import Change, DELETE_SENTINEL, SqliteValue
from .matcher import Matcher, MatcherError, _enc_cell


#: default per-subscriber event queue bound (ISSUE 13): the serving
#: tier's slow-consumer policy is DISCONNECT-WITH-REASON, never a
#: silent drop — a consumer this many events behind can only fall
#: further behind, and an unbounded queue would turn one stalled
#: reader into unbounded server memory.  Agents pass
#: ``perf.sub_queue_cap``; this is the standalone-manager default.
SUB_QUEUE_CAP = 1024


class SubQueue:
    """One subscriber's BOUNDED event queue.  On overflow the queue is
    closed: the backlog (which the consumer was never going to catch up
    on) is replaced by a single ``{"error": reason}`` event, and the
    streaming handler disconnects after sending it — the client re-syncs
    through the snapshot / ``?from=`` path on reconnect, so events are
    re-served, not lost.  Duck-types the asyncio.Queue surface the
    stream handlers use (put_nowait/get/qsize)."""

    __slots__ = ("_q", "closed", "close_reason")

    def __init__(self, maxsize: int = SUB_QUEUE_CAP):
        if maxsize <= 0:
            # asyncio.Queue(0) is INFINITE — a config typo must not
            # silently disable the slow-consumer policy
            raise ValueError(
                f"sub queue bound must be > 0 (got {maxsize}; 0 means "
                "unbounded in asyncio semantics)"
            )
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.closed = False
        self.close_reason: Optional[str] = None

    def put_nowait(self, event: dict) -> None:
        if self.closed:
            return  # disconnecting: the close event is already queued
        self._q.put_nowait(event)

    async def get(self) -> dict:
        return await self._q.get()

    def get_nowait(self) -> dict:
        return self._q.get_nowait()

    def empty(self) -> bool:
        return self._q.empty()

    def qsize(self) -> int:
        return self._q.qsize()

    def close(self, reason: str) -> None:
        """Terminal: drop the undeliverable backlog, queue the one
        explicit close event the handler forwards before hanging up."""
        if self.closed:
            return
        self.closed = True
        self.close_reason = reason
        while True:
            try:
                self._q.get_nowait()
            except asyncio.QueueEmpty:
                break
        self._q.put_nowait({"error": reason})


class SubHandle:
    """One active subscription: matcher + attached subscriber queues."""

    def __init__(self, matcher: Matcher, queue_cap: int = SUB_QUEUE_CAP):
        self.matcher = matcher
        self.id = matcher.id
        self.queue_cap = queue_cap
        self.queues: List[SubQueue] = []
        # events fanned out to attached queues since creation; the
        # serving-telemetry counter advances a per-handle watermark
        # (`_fanout_reported`) so deliveries from the DEFERRED flush
        # path count too, not just the synchronous handle_changes ones
        self.delivered = 0
        self._fanout_reported = 0
        # slow-consumer disconnects since creation (watermarked into the
        # serving saturation counter like `delivered`)
        self.slow_disconnects = 0
        self._slow_reported = 0
        matcher.subscribe(self._on_event)

    def _on_event(self, event: dict):
        dead: List[SubQueue] = []
        delivered = 0
        for q in list(self.queues):
            try:
                q.put_nowait(event)
                delivered += 1
            except asyncio.QueueFull:
                dead.append(q)
        for q in dead:
            # the slow-consumer policy (doc/serving.md): disconnect with
            # an explicit reason — the bound is the queue's whole point,
            # and a silent drop would break the no-lost-events contract
            # the checker certifies
            self.queues.remove(q)
            q.close(
                f"slow consumer: subscriber fell {self.queue_cap} "
                "events behind; reconnect and re-sync"
            )
            self.slow_disconnects += 1
        self.delivered += delivered

    def attach(self) -> SubQueue:
        q = SubQueue(maxsize=self.queue_cap)
        self.queues.append(q)
        return q

    def detach(self, q):
        if q in self.queues:
            self.queues.remove(q)


class SubsManager:
    """Registry of live subscriptions, keyed by id and by normalized SQL
    hash so identical queries share one matcher (pubsub.rs:108-186)."""

    def __init__(
        self,
        store,
        state_dir: Optional[str] = None,
        queue_cap: int = SUB_QUEUE_CAP,
    ):
        self.store = store
        self.state_dir = state_dir
        self.queue_cap = queue_cap
        if state_dir:
            os.makedirs(state_dir, exist_ok=True)
        self.by_id: Dict[str, SubHandle] = {}
        self.by_hash: Dict[str, str] = {}  # sql hash -> sub id
        # serving telemetry handle (ISSUE 8, set by
        # telemetry.attach_host_telemetry): fan-out event counters +
        # subscriber-queue depth gauge; None = off, one attribute test
        self.telemetry = None
        # visible-stamp parking lot: (pairs, hlc, waiting-handle-ids)
        # entries whose fan-out was DEFERRED by a fallback matcher's
        # re-run budget — each entry stamps when the SPECIFIC handles it
        # waited on have flushed (an unrelated table's perpetually-dirty
        # matcher must not postpone, and thus inflate, other tables'
        # publish→visible stamps), and is DROPPED if its only deliverer
        # failed (a fabricated visibility moment is worse than a counted
        # gap).  See Agent._match_changes / _drain_visible.
        self._deferred_visible: List[Tuple[List, object, set]] = []

    def _crr_tables(self) -> Dict[str, Tuple[str, ...]]:
        return {name: info.pk_cols for name, info in self.store._tables.items()}

    @staticmethod
    def _hash(sql: str, params: Sequence[SqliteValue]) -> str:
        norm = " ".join(sql.split()).lower()
        return hashlib.sha256(
            (norm + "\x00" + json.dumps([_enc_cell(p) for p in params])).encode()
        ).hexdigest()

    def _state_path(self, sub_id: str) -> str:
        if self.state_dir:
            return os.path.join(self.state_dir, f"{sub_id}.db")
        return ":memory:"

    def get_or_insert(
        self, sql: str, params: Sequence[SqliteValue] = ()
    ) -> Tuple[SubHandle, bool]:
        """Returns (handle, created).  A matching live subscription is
        shared; otherwise a new matcher runs its initial query."""
        h = self._hash(sql, params)
        sub_id = self.by_hash.get(h)
        if sub_id is not None and sub_id in self.by_id:
            return self.by_id[sub_id], False
        sub_id = str(uuid.uuid4())
        matcher = Matcher(
            sub_id, sql, params, self.store.conn, self._crr_tables(),
            state_path=self._state_path(sub_id),
        )
        matcher.run_initial()
        handle = SubHandle(matcher, queue_cap=self.queue_cap)
        self.by_id[sub_id] = handle
        self.by_hash[h] = sub_id
        self.store.conn.execute(
            "INSERT OR REPLACE INTO __corro_subs (id, sql) VALUES (?, ?)",
            (sub_id, json.dumps([sql, [_enc_cell(p) for p in params]])),
        )
        return handle, True

    def get(self, sub_id: str) -> Optional[SubHandle]:
        return self.by_id.get(sub_id)

    def remove(self, sub_id: str):
        handle = self.by_id.pop(sub_id, None)
        if handle is None:
            return
        self.by_hash = {h: i for h, i in self.by_hash.items() if i != sub_id}
        handle.matcher.close()
        self.store.conn.execute("DELETE FROM __corro_subs WHERE id = ?", (sub_id,))
        path = self._state_path(sub_id)
        if path != ":memory:" and os.path.exists(path):
            os.unlink(path)

    def restore(self):
        """Recreate persisted subscriptions at boot (pubsub.rs:822-858,
        setup.rs:296-349); each matcher resyncs its snapshot so changes
        applied while down appear in the change log."""
        import base64

        for sub_id, blob in self.store.conn.execute(
            "SELECT id, sql FROM __corro_subs"
        ).fetchall():
            if sub_id in self.by_id:
                continue
            sql, enc_params = json.loads(blob)
            params = tuple(
                base64.b64decode(p["$b"]) if isinstance(p, dict) and "$b" in p else p
                for p in enc_params
            )
            try:
                matcher = Matcher(
                    sub_id, sql, params, self.store.conn, self._crr_tables(),
                    state_path=self._state_path(sub_id),
                )
                matcher.run_initial()
            except MatcherError:
                self.store.conn.execute(
                    "DELETE FROM __corro_subs WHERE id = ?", (sub_id,)
                )
                continue
            self.by_id[sub_id] = SubHandle(
                matcher, queue_cap=self.queue_cap
            )
            self.by_hash[self._hash(sql, params)] = sub_id

    def match_changes(self, changes: Sequence[Change]):
        """Feed a committed batch to every live matcher (updates.rs:420-481,
        called from the commit paths in broadcast.rs:544-545 and
        util.rs:1026-1030).

        Fallback (non-keyed) matchers defer inside their re-run budget
        window; a trailing flush is scheduled on the running loop so the
        final coalesced state always lands (VERDICT r3 item 6).  With no
        loop (sync tests) deferral is off and every batch re-runs."""
        if not changes:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        for handle in list(self.by_id.values()):
            try:
                handle.matcher.handle_changes(
                    changes, allow_defer=loop is not None
                )
                if loop is not None and handle.matcher._rerun_dirty:
                    self._schedule_flush(loop, handle)
            except Exception:
                # a broken matcher must not poison the apply path; the
                # reference parks the sub in an errored state
                import traceback

                traceback.print_exc()
        self._report_fanout()

    def has_dirty(self, tables=None) -> bool:
        """True while a fallback matcher holds a coalesced re-run it
        has not flushed yet — events for the last batch may not have
        been delivered to subscriber queues.  ``tables`` narrows the
        question to matchers watching those tables: a dirty sub on an
        UNRELATED table must not defer visible stamps for a batch it
        never matched (keyed subs for that batch delivered
        synchronously)."""
        for h in self.by_id.values():
            if not h.matcher._rerun_dirty:
                continue
            if tables is None or tables & set(h.matcher.tables):
                return True
        return False

    def defer_visible(self, pairs, hlc_now, tables) -> None:
        """Park (actor, version) visible stamps until the dirty
        matchers watching ``tables`` actually flush — stamping at match
        time would record a visibility moment up to a whole re-run
        window before the events reached any subscriber queue."""
        waiting = {
            h.id
            for h in self.by_id.values()
            if h.matcher._rerun_dirty and tables & set(h.matcher.tables)
        }
        self._deferred_visible.append((list(pairs), hlc_now, waiting))

    def _drain_visible(self, failed_id: Optional[str] = None) -> None:
        """Stamp parked entries whose waited-on handles have all
        flushed (the drain runs right after each flush, so the stamp
        time IS the delivery time).  ``failed_id`` marks a handle whose
        flush errored: entries left waiting only on it are dropped with
        a counter — those deliveries never happened."""
        if not self._deferred_visible:
            return
        tel = self.telemetry
        if tel is None:
            self._deferred_visible = []
            return
        dirty = {
            h.id for h in self.by_id.values() if h.matcher._rerun_dirty
        }
        keep = []
        for pairs, hlc_now, waiting in self._deferred_visible:
            if failed_id is not None and failed_id in waiting:
                waiting = waiting - {failed_id}
                if not waiting:
                    tel.visible_dropped(len(pairs))
                    continue
            # handles that flushed (or were removed) are no longer dirty
            waiting = waiting & dirty
            if waiting:
                keep.append((pairs, hlc_now, waiting))
            else:
                for actor_id, version in pairs:
                    tel.visible(actor_id, version, hlc_now=hlc_now)
        self._deferred_visible = keep

    def _report_fanout(self) -> None:
        """Advance the serving fan-out counter + subscriber-queue-depth
        gauge (one pass per committed batch / trailing flush, never per
        event).  Watermark-based: deliveries that happened via the
        deferred flush path since the last report count here too.  Also
        drains parked visible stamps whose waited-on matchers flushed."""
        tel = self.telemetry
        if tel is None:
            self._deferred_visible.clear()
            return
        fanned = 0
        depth = 0
        slow = 0
        for h in self.by_id.values():
            fanned += h.delivered - h._fanout_reported
            h._fanout_reported = h.delivered
            slow += h.slow_disconnects - h._slow_reported
            h._slow_reported = h.slow_disconnects
            for q in h.queues:
                depth = max(depth, q.qsize())
        tel.sub_fanout(fanned, depth)
        if slow:
            tel.slow_consumer(slow)
        self._drain_visible()

    def _schedule_flush(self, loop, handle):
        """One pending trailing flush per dirty fallback sub."""
        if getattr(handle, "_flush_pending", False):
            return
        handle._flush_pending = True
        matcher = handle.matcher
        delay = max(0.0, matcher._next_rerun_at() - time.monotonic())

        def _flush():
            handle._flush_pending = False
            if self.by_id.get(handle.id) is not handle:
                return  # sub removed while the flush was pending
            try:
                matcher.flush_if_due()
                self._report_fanout()
            except Exception:
                import traceback

                traceback.print_exc()
                # give up on this coalesced state: retrying a broken
                # matcher forever would spam a traceback per window; the
                # next committed batch re-marks it dirty.  Parked stamps
                # waiting only on THIS handle are dropped (their
                # delivery never happened — a fabricated visibility
                # moment would corrupt the publish→visible metric); the
                # rest re-check their remaining deliverers
                matcher._rerun_dirty = False
                self._drain_visible(failed_id=handle.id)
                self._report_fanout()
                return
            # a batch may have landed between the due-check and now
            if matcher._rerun_dirty:
                self._schedule_flush(loop, handle)

        loop.call_later(delay + 0.01, _flush)


class UpdatesManager:
    """Per-table change notifier (updates.rs:61-268): no SQL matching, just
    "this pk in this table changed" NotifyEvents
    ({"notify": [type, [pk values...]]}).  Queues are BOUNDED with the
    same slow-consumer policy as SQL subscriptions (ISSUE 13): overflow
    disconnects with a reason, never drops silently."""

    def __init__(self, queue_cap: int = SUB_QUEUE_CAP):
        self.queue_cap = queue_cap
        self.by_table: Dict[str, List[SubQueue]] = {}
        # serving telemetry handle (attach_host_telemetry); None = off
        self.telemetry = None

    def attach(self, table: str) -> SubQueue:
        q = SubQueue(maxsize=self.queue_cap)
        self.by_table.setdefault(table, []).append(q)
        return q

    def detach(self, table: str, q):
        if table in self.by_table and q in self.by_table[table]:
            self.by_table[table].remove(q)

    def match_changes(self, changes: Sequence[Change]):
        """updates.rs:278-300: type = delete when the causal length went
        even (or the delete sentinel rode in), update otherwise."""
        touched: Dict[str, Dict[bytes, str]] = {}
        for ch in changes:
            if ch.table not in self.by_table:
                continue
            typ = "delete" if (ch.cid == DELETE_SENTINEL or ch.cl % 2 == 0) else "update"
            touched.setdefault(ch.table, {})[ch.pk] = typ
        for table, pks in touched.items():
            queues = self.by_table.get(table, [])
            if not queues:
                continue
            dead: List[SubQueue] = []
            for pk, typ in pks.items():
                event = {"notify": [typ, [_enc_cell(v) for v in decode_pk(pk)]]}
                for q in list(queues):
                    try:
                        q.put_nowait(event)
                    except asyncio.QueueFull:
                        if q not in dead:
                            dead.append(q)
            for q in dead:
                queues.remove(q)
                q.close(
                    f"slow consumer: updates watcher fell "
                    f"{self.queue_cap} events behind; reconnect"
                )
                if self.telemetry is not None:
                    self.telemetry.slow_consumer(1)
