"""Matcher: incremental materialization of one SQL subscription.

Rebuild of the reference's `Matcher` (`corro-types/src/pubsub.rs:544-1750`):
parse the subscribed SELECT, find the replicated tables it reads, inject
aliased primary-key columns (`__corro_pk_<table>_<pk>`, pubsub.rs:604-648),
and keep a per-subscription SQLite state DB (`query` result snapshot +
`changes` log + `meta`/`columns`, pubsub.rs:893-926).  When committed changes
touch a referenced table, the rewritten query is re-run restricted to the
changed primary keys and diffed against the snapshot, appending
insert/update/delete rows to the change log (pubsub.rs:1434-1750).

Differences from the reference, by design:

- the reference parses with `sqlite3-parser` and rewrites ASTs; we use
  SQLite's own authorizer callback to discover referenced tables (the
  compiler's ground truth) plus a small tokenizer for the FROM-clause
  aliases, and splice the pk aliases textually;
- queries the keyed rewrite can't handle (DISTINCT, GROUP BY, aggregates,
  compound SELECTs, FROM subqueries, LIMIT, a table joined twice) fall back
  to a full re-run + ordinal diff instead of erroring
  (`MatcherError::UnsupportedStatement`, pubsub.rs:588 — we degrade where
  the reference rejects).  The degradation is BOUNDED: fallback re-runs
  are rate-limited by an adaptive budget window (at least
  ``rerun_min_interval_s``, at least the last re-run's measured cost) —
  change batches inside the window coalesce into one deferred re-run
  scheduled by SubsManager, and `corro_subs_rerun_seconds` /
  `corro_subs_rerun_total` / `corro_subs_rerun_coalesced_total` expose
  the cost (VERDICT r3 item 6);
- events are plain dicts matching the NDJSON protocol of
  doc/api/subscriptions.md:50-135 exactly.
"""

from __future__ import annotations

import json
import re
import sqlite3
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..core.pkcodec import decode_pk
from ..core.types import Change, SqliteValue

# SQLite authorizer action code for column reads
_SQLITE_READ = 20


_KEYED_BREAKERS = re.compile(
    r"(?i)\b(distinct|group|union|intersect|except|limit|having|window)\b"
)
_AGGREGATES = {"count", "sum", "avg", "min", "max", "total", "group_concat"}
_FROM_STOP = {
    "where", "group", "order", "limit", "having", "window",
    "union", "intersect", "except",
}
_JOIN_WORDS = {"join", "left", "right", "full", "inner", "outer", "cross", "natural"}


class MatcherError(Exception):
    pass


def _tokenize(sql: str) -> List[Tuple[str, str, int]]:
    """(kind, text, pos) tokens; kind in {id, num, str, punct, param}.
    Comments are skipped; positions index into the original string."""
    out: List[Tuple[str, str, int]] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
        elif sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
        elif sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            i = n if j < 0 else j + 2
        elif c in "'\"`[":
            close = {"[": "]"}.get(c, c)
            j = i + 1
            while j < n:
                if sql[j] == close:
                    if close in "'\"`" and j + 1 < n and sql[j + 1] == close:
                        j += 2  # doubled quote escape
                        continue
                    break
                j += 1
            out.append(("str" if c == "'" else "id", sql[i : j + 1], i))
            i = j + 1
        elif c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "._+-"):
                if sql[j] in "+-" and sql[j - 1] not in "eE":
                    break
                j += 1
            out.append(("num", sql[i:j], i))
            i = j
        elif c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            out.append(("id", sql[i:j], i))
            i = j
        elif c in "?:@$":
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            out.append(("param", sql[i:j], i))
            i = j
        else:
            out.append(("punct", c, i))
            i += 1
    return out


def _unquote(ident: str) -> str:
    if ident and ident[0] in "\"`[":
        return ident[1:-1].replace(ident[0] * 2, ident[0])
    return ident


def _parse_from_aliases(sql: str) -> Optional[Dict[str, str]]:
    """Map real table name -> alias used in the top-level FROM clause.
    Returns None when the shape defeats the keyed rewrite (subquery in FROM,
    a table referenced twice, unparseable join)."""
    toks = _tokenize(sql)
    depth = 0
    from_ix = None
    for ix, (kind, text, _) in enumerate(toks):
        if kind == "punct" and text == "(":
            depth += 1
        elif kind == "punct" and text == ")":
            depth -= 1
        elif depth == 0 and kind == "id" and text.lower() == "from":
            from_ix = ix
            break
    if from_ix is None:
        return None
    aliases: Dict[str, str] = {}
    ix = from_ix + 1
    expect_table = True
    while ix < len(toks):
        kind, text, _ = toks[ix]
        low = text.lower() if kind == "id" else ""
        if kind == "punct" and text == "(":
            return None  # FROM subquery → full mode
        if depth == 0 and low in _FROM_STOP:
            break
        if expect_table:
            if kind != "id":
                return None
            name = _unquote(text)
            ix += 1
            # optional schema qualifier main.t
            if ix < len(toks) and toks[ix][1] == ".":
                ix += 1
                if ix >= len(toks) or toks[ix][0] != "id":
                    return None
                name = _unquote(toks[ix][1])
                ix += 1
            alias = name
            if ix < len(toks) and toks[ix][0] == "id":
                nxt = toks[ix][1].lower()
                if nxt == "as":
                    ix += 1
                    if ix >= len(toks) or toks[ix][0] != "id":
                        return None
                    alias = _unquote(toks[ix][1])
                    ix += 1
                elif nxt not in _JOIN_WORDS and nxt not in _FROM_STOP and nxt not in (
                    "on", "using",
                ):
                    alias = _unquote(toks[ix][1])
                    ix += 1
            if name in aliases:
                return None  # self-join → full mode
            aliases[name] = alias
            expect_table = False
        else:
            if kind == "punct" and text == ",":
                expect_table = True
                ix += 1
            elif low in _JOIN_WORDS:
                if low == "join":
                    expect_table = True
                ix += 1
            elif low in ("on", "using"):
                # skip the join constraint expression until the next
                # top-level join/comma/stop keyword
                ix += 1
                d = 0
                while ix < len(toks):
                    k2, t2, _ = toks[ix]
                    l2 = t2.lower() if k2 == "id" else ""
                    if k2 == "punct" and t2 == "(":
                        d += 1
                    elif k2 == "punct" and t2 == ")":
                        d -= 1
                    elif d == 0 and (
                        l2 in _JOIN_WORDS or l2 in _FROM_STOP or (k2 == "punct" and t2 == ",")
                    ):
                        break
                    ix += 1
            else:
                ix += 1
    return aliases


def _find_top_level_from(sql: str) -> Optional[int]:
    depth = 0
    for kind, text, pos in _tokenize(sql):
        if kind == "punct" and text == "(":
            depth += 1
        elif kind == "punct" and text == ")":
            depth -= 1
        elif depth == 0 and kind == "id" and text.lower() == "from":
            return pos
    return None


def _has_aggregate(sql: str) -> bool:
    toks = _tokenize(sql)
    for ix, (kind, text, _) in enumerate(toks):
        if (
            kind == "id"
            and text.lower() in _AGGREGATES
            and ix + 1 < len(toks)
            and toks[ix + 1][1] == "("
        ):
            return True
    return False


def _enc_cell(v: SqliteValue):
    if isinstance(v, bytes):
        import base64

        return {"$b": base64.b64encode(v).decode("ascii")}
    return v


def _enc_cells(row: Sequence[SqliteValue]) -> str:
    return json.dumps([_enc_cell(v) for v in row], separators=(",", ":"))


class Matcher:
    """One subscription's incremental view.

    ``main_conn`` is a connection to the node's replicated DB (read side);
    ``state_path`` is this subscription's private state DB
    (pubsub.rs:893-926), ``:memory:`` for ephemeral subs."""

    def __init__(
        self,
        sub_id: str,
        sql: str,
        params: Sequence[SqliteValue],
        main_conn: sqlite3.Connection,
        crr_tables: Dict[str, Sequence[str]],  # table -> pk column names
        state_path: str = ":memory:",
        rerun_min_interval_s: float = 0.25,
    ):
        self.id = sub_id
        self.sql = sql.strip().rstrip(";")
        self.params = tuple(params)
        self.main = main_conn
        head = self.sql.split(None, 1)[0].lower() if self.sql else ""
        if head not in ("select", "with"):
            raise MatcherError("only SELECT statements can be subscribed to")

        referenced = self._referenced_tables()
        self.tables: Dict[str, Tuple[str, ...]] = {
            t: tuple(crr_tables[t]) for t in referenced if t in crr_tables
        }
        if not self.tables:
            raise MatcherError("query references no replicated tables")

        self.keyed = self._plan_keyed()
        self.state = sqlite3.connect(state_path, check_same_thread=False)
        self.state.execute("PRAGMA journal_mode = WAL")
        self._init_state()
        self.columns: List[str] = self._load_columns()
        self.listeners: List[Callable[[dict], None]] = []
        # fallback re-run budget (VERDICT r3 item 6): non-keyed subs pay
        # O(result) per re-run, so re-runs are rate-bounded — change
        # batches landing inside the window coalesce into ONE deferred
        # re-run (the manager schedules the trailing flush).  The window
        # adapts to the measured re-run cost: a sub whose re-run takes
        # 2 s can never consume more than ~50% of a core.
        self.rerun_min_interval_s = rerun_min_interval_s
        self._last_rerun_at = 0.0
        self._last_rerun_cost = 0.0
        self._rerun_dirty = False

    # -- planning ---------------------------------------------------------

    def _referenced_tables(self) -> Set[str]:
        """Ask SQLite's compiler which tables the query reads (the parser
        ground truth the reference gets from sqlite3-parser)."""
        seen: Set[str] = set()

        def auth(action, a1, a2, dbname, trigger):
            if action == _SQLITE_READ and a1:
                seen.add(a1)
            return sqlite3.SQLITE_OK

        # discovery runs on a THROWAWAY in-memory clone of main's
        # schema, never on the shared connection: (a) on some CPython
        # 3.10 sqlite3 builds set_authorizer(None) fails to clear and
        # leaves a DENY-ALL hook, poisoning every later op on the
        # connection ("not authorized" from the db-maintenance PRAGMAs);
        # (b) any Python authorizer left installed is invoked from the
        # maintenance executor thread's long PRAGMAs and deadlocks
        # against the GIL (main thread holds GIL, waits db mutex;
        # checkpoint thread holds db mutex, waits GIL).  The scratch
        # connection is private and closed immediately, so neither
        # failure mode can reach the live connection.
        scratch = sqlite3.connect(":memory:")
        try:
            # custom SQL functions registered on main (crdt_*,
            # corro_json_contains, …) must EXIST on scratch or a valid
            # subscription using one fails to compile; no-arg-checking
            # stubs suffice — EXPLAIN never executes them.  Registered
            # BEFORE the schema replay: a GENERATED column may reference
            # a custom function in its table's DDL
            try:
                have = {
                    (name, narg)
                    for name, narg in scratch.execute(
                        "SELECT name, narg FROM pragma_function_list"
                    )
                }
                for name, narg in self.main.execute(
                    "SELECT DISTINCT name, narg FROM pragma_function_list"
                ):
                    if (name, narg) not in have:
                        # deterministic: generated-column DDL rejects
                        # non-deterministic functions at CREATE time
                        scratch.create_function(
                            name, narg, lambda *a: None,
                            deterministic=True,
                        )
            except sqlite3.Error:
                # pragma_function_list missing (ancient sqlite): fall
                # back to compiling without stubs — only subscriptions
                # using custom functions regress, loudly
                pass
            for (ddl,) in self.main.execute(
                "SELECT sql FROM sqlite_master WHERE sql IS NOT NULL"
            ):
                try:
                    scratch.execute(ddl)
                except sqlite3.Error:
                    # internal/auto indexes etc. — discovery only needs
                    # enough schema for the query to COMPILE
                    pass
            scratch.set_authorizer(auth)
            try:
                scratch.execute(
                    "EXPLAIN " + self.sql, self.params
                ).fetchone()
            except sqlite3.Error as e:
                raise MatcherError(f"invalid query: {e}") from e
        finally:
            scratch.close()
        return seen

    def _plan_keyed(self) -> bool:
        """Decide keyed (pk-alias incremental) vs full (ordinal re-run) and
        build the rewritten query if keyed."""
        if self.sql.split(None, 1)[0].lower() == "with":
            return False
        if _KEYED_BREAKERS.search(self.sql) or _has_aggregate(self.sql):
            return False
        aliases = _parse_from_aliases(self.sql)
        if aliases is None:
            return False
        for t in self.tables:
            if t not in aliases:
                return False  # read outside the FROM clause (subquery)
        # pk alias columns, grouped per table (pubsub.rs:604-648)
        self.pk_cols: Dict[str, List[str]] = {}
        select_extra = []
        for t, pks in self.tables.items():
            a = aliases[t]
            cols = []
            for pk in pks:
                alias_col = f"__corro_pk_{t}_{pk}"
                select_extra.append(f'"{a}"."{pk}" AS "{alias_col}"')
                cols.append(alias_col)
            self.pk_cols[t] = cols
        from_pos = _find_top_level_from(self.sql)
        if from_pos is None:
            return False
        self.rewritten = (
            self.sql[:from_pos].rstrip()
            + ", "
            + ", ".join(select_extra)
            + " "
            + self.sql[from_pos:]
        )
        self.n_alias = len(select_extra)
        return True

    # -- state db ---------------------------------------------------------

    def _init_state(self):
        alias_defs = ""
        if self.keyed:
            all_alias = [c for cols in self.pk_cols.values() for c in cols]
            alias_defs = "".join(f', "{c}"' for c in all_alias)
        self.state.executescript(
            f"""
            CREATE TABLE IF NOT EXISTS q (
                rid INTEGER PRIMARY KEY AUTOINCREMENT,
                k TEXT NOT NULL UNIQUE, cells TEXT NOT NULL{alias_defs});
            CREATE TABLE IF NOT EXISTS changes (
                id INTEGER PRIMARY KEY AUTOINCREMENT,
                type TEXT NOT NULL, rid INTEGER NOT NULL, cells TEXT NOT NULL);
            CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value);
            CREATE TABLE IF NOT EXISTS cols (pos INTEGER PRIMARY KEY, name TEXT);
            """
        )
        if self.keyed:
            for t, cols in self.pk_cols.items():
                cl = ", ".join(f'"{c}"' for c in cols)
                self.state.execute(
                    f'CREATE INDEX IF NOT EXISTS "ix_{t}" ON q ({cl})'
                )
        self.state.execute(
            "INSERT OR REPLACE INTO meta (key, value) VALUES ('sql', ?)",
            (json.dumps([self.sql, list(self.params)]),),
        )
        self.state.commit()

    def _load_columns(self) -> List[str]:
        return [r[0] for r in self.state.execute("SELECT name FROM cols ORDER BY pos")]

    @property
    def last_change_id(self) -> int:
        row = self.state.execute("SELECT MAX(id) FROM changes").fetchone()
        return row[0] or 0

    def subscribe(self, cb: Callable[[dict], None]):
        self.listeners.append(cb)

    def unsubscribe(self, cb: Callable[[dict], None]):
        if cb in self.listeners:
            self.listeners.remove(cb)

    def _emit(self, event: dict):
        for cb in list(self.listeners):
            cb(event)

    # -- initial population ----------------------------------------------

    def run_initial(self) -> List[dict]:
        """Populate the snapshot (first run) or resync after restore; returns
        the columns/row/eoq event list for a fresh subscriber
        (pubsub.rs:1214+ Matcher::run)."""
        import time

        t0 = time.monotonic()
        rows = self._query_all()
        elapsed = time.monotonic() - t0
        if not self.columns:
            self.state.executemany(
                "INSERT INTO cols (pos, name) VALUES (?, ?)",
                list(enumerate(self._result_columns)),
            )
            self.columns = list(self._result_columns)
        has_snapshot = (
            self.state.execute("SELECT 1 FROM q LIMIT 1").fetchone() is not None
        )
        if has_snapshot:
            # restored sub: diff what changed while we were away
            self._diff_against_snapshot(rows)
        else:
            for key, cells, alias_vals in rows:
                self._insert_row(key, cells, alias_vals, log=False)
        self.state.commit()
        events = [{"columns": self.columns}]
        for rid, cells in self.state.execute("SELECT rid, cells FROM q ORDER BY rid"):
            events.append({"row": [rid, json.loads(cells)]})
        events.append(
            {"eoq": {"time": elapsed, "change_id": self.last_change_id}}
        )
        return events

    def _query_all(self):
        """Full run of the (rewritten) query → [(key, cells_json, alias_vals)]."""
        sql = self.rewritten if self.keyed else self.sql
        cur = self.main.execute(sql, self.params)
        desc = [d[0] for d in cur.description]
        if self.keyed:
            self._result_columns = desc[: -self.n_alias]
        else:
            self._result_columns = desc
        out = []
        for i, row in enumerate(cur.fetchall()):
            if self.keyed:
                cells = row[: -self.n_alias]
                alias_vals = tuple(row[-self.n_alias :])
                key = _enc_cells(alias_vals)
            else:
                cells = row
                alias_vals = ()
                key = str(i)
            out.append((key, _enc_cells(cells), alias_vals))
        return out

    # -- change handling --------------------------------------------------

    def filter_tables(self, changes: Sequence[Change]) -> Dict[str, Set[bytes]]:
        """filter_matchable_change (pubsub.rs:294-332): which referenced
        tables did this batch touch, and at which pks."""
        cands: Dict[str, Set[bytes]] = {}
        for ch in changes:
            if ch.table in self.tables:
                cands.setdefault(ch.table, set()).add(ch.pk)
        return cands

    def handle_changes(
        self, changes: Sequence[Change], allow_defer: bool = False
    ) -> List[dict]:
        """Incremental update for one committed batch; returns emitted change
        events (also sent to listeners).

        Non-keyed (fallback) subs re-run the whole query — O(result) per
        batch with no bound would be a foot-gun under a write storm, so
        with ``allow_defer`` the re-run is rate-limited: batches inside
        the budget window only mark the sub dirty (the caller promises a
        later `flush_if_due`/`flush` — SubsManager schedules it)."""
        cands = self.filter_tables(changes)
        if not cands:
            return []
        if not self.keyed:
            self._rerun_dirty = True
            if allow_defer and not self.rerun_due():
                from ..metrics import REGISTRY

                REGISTRY.counter("corro_subs_rerun_coalesced_total").inc()
                return []
            return self._rerun_now()
        events: List[dict] = []
        for table, pks in cands.items():
            events.extend(self._handle_candidates(table, pks))
        self.state.commit()
        return events

    # -- fallback re-run budget ------------------------------------------

    def _next_rerun_at(self) -> float:
        # adaptive window: at least the configured interval, and at least
        # the last measured cost (≤ ~50% duty cycle for expensive subs)
        return self._last_rerun_at + max(
            self.rerun_min_interval_s, self._last_rerun_cost
        )

    def rerun_due(self, now: Optional[float] = None) -> bool:
        import time as _time

        return (now or _time.monotonic()) >= self._next_rerun_at()

    def flush_if_due(self) -> List[dict]:
        """Deferred-flush entry for the manager: run the coalesced re-run
        if the sub is dirty and the budget window elapsed."""
        if not self._rerun_dirty or not self.rerun_due():
            return []
        return self._rerun_now()

    def _rerun_now(self) -> List[dict]:
        import time as _time

        from ..metrics import REGISTRY

        t0 = _time.monotonic()
        events = self._diff_against_snapshot(self._query_all())
        self.state.commit()
        end = _time.monotonic()
        cost = end - t0
        # anchor the window at the END of the re-run: anchoring at the
        # start would open the next window exactly when an expensive
        # re-run finishes (100% duty cycle); end + max(interval, cost)
        # caps an expensive sub at ~50% of a core
        self._last_rerun_at = end
        self._last_rerun_cost = cost
        self._rerun_dirty = False
        REGISTRY.counter("corro_subs_rerun_total").inc()
        REGISTRY.histogram("corro_subs_rerun_seconds").observe(cost)
        return events

    def _handle_candidates(self, table: str, pks: Set[bytes]) -> List[dict]:
        """handle_candidates/handle_change (pubsub.rs:1434-1750): re-run the
        rewritten query restricted to changed pks, diff against snapshot."""
        alias_cols = self.pk_cols[table]
        events: List[dict] = []
        pk_tuples = [decode_pk(pk) for pk in pks]
        for i in range(0, len(pk_tuples), 100):
            chunk = pk_tuples[i : i + 100]
            where, args = self._in_clause(alias_cols, chunk)
            # fresh matching rows from the main DB
            new: Dict[str, Tuple[str, tuple]] = {}
            cur = self.main.execute(
                f"SELECT * FROM ({self.rewritten}) WHERE {where}",
                (*self.params, *args),
            )
            for row in cur.fetchall():
                cells = row[: -self.n_alias]
                alias_vals = tuple(row[-self.n_alias :])
                new[_enc_cells(alias_vals)] = (_enc_cells(cells), alias_vals)
            # current snapshot rows for those pks
            old: Dict[str, Tuple[int, str]] = {}
            for row in self.state.execute(
                f"SELECT k, rid, cells FROM q WHERE {where}", args
            ):
                old[row[0]] = (row[1], row[2])
            for key, (cells, alias_vals) in new.items():
                if key in old:
                    rid, old_cells = old[key]
                    if old_cells != cells:
                        self.state.execute(
                            "UPDATE q SET cells = ? WHERE rid = ?", (cells, rid)
                        )
                        events.append(self._log("update", rid, cells))
                else:
                    events.append(self._insert_row(key, cells, alias_vals, log=True))
            for key, (rid, old_cells) in old.items():
                if key not in new:
                    self.state.execute("DELETE FROM q WHERE rid = ?", (rid,))
                    events.append(self._log("delete", rid, old_cells))
        return events

    def _diff_against_snapshot(self, rows) -> List[dict]:
        """Full diff (fallback mode + restore resync): new full result vs
        stored snapshot, keyed by pk aliases (keyed) or ordinal (full)."""
        events: List[dict] = []
        new = {key: (cells, alias_vals) for key, cells, alias_vals in rows}
        old = {
            k: (rid, cells)
            for k, rid, cells in self.state.execute("SELECT k, rid, cells FROM q")
        }
        for key, (cells, alias_vals) in new.items():
            if key in old:
                rid, old_cells = old[key]
                if old_cells != cells:
                    self.state.execute(
                        "UPDATE q SET cells = ? WHERE rid = ?", (cells, rid)
                    )
                    events.append(self._log("update", rid, cells))
            else:
                events.append(self._insert_row(key, cells, alias_vals, log=True))
        for key, (rid, old_cells) in old.items():
            if key not in new:
                self.state.execute("DELETE FROM q WHERE rid = ?", (rid,))
                events.append(self._log("delete", rid, old_cells))
        return events

    def _insert_row(self, key: str, cells: str, alias_vals: tuple, log: bool):
        if self.keyed:
            all_alias = [c for cols in self.pk_cols.values() for c in cols]
            col_sql = "".join(f', "{c}"' for c in all_alias)
            ph = ", ?" * len(all_alias)
            cur = self.state.execute(
                f"INSERT INTO q (k, cells{col_sql}) VALUES (?, ?{ph})",
                (key, cells, *alias_vals),
            )
        else:
            cur = self.state.execute(
                "INSERT INTO q (k, cells) VALUES (?, ?)", (key, cells)
            )
        if log:
            return self._log("insert", cur.lastrowid, cells)
        return None

    def _log(self, typ: str, rid: int, cells: str) -> dict:
        cur = self.state.execute(
            "INSERT INTO changes (type, rid, cells) VALUES (?, ?, ?)",
            (typ, rid, cells),
        )
        event = {"change": [typ, rid, json.loads(cells), cur.lastrowid]}
        self._emit(event)
        return event

    def _in_clause(self, cols: List[str], tuples: List[tuple]):
        if len(cols) == 1:
            ph = ", ".join("?" for _ in tuples)
            return f'"{cols[0]}" IN ({ph})', [t[0] for t in tuples]
        colref = "(" + ", ".join(f'"{c}"' for c in cols) + ")"
        row_ph = "(" + ", ".join("?" for _ in cols) + ")"
        ph = ", ".join(row_ph for _ in tuples)
        args = [v for t in tuples for v in t]
        return f"{colref} IN (VALUES {ph})", args

    # -- catch-up ---------------------------------------------------------

    def changes_since(self, change_id: int) -> List[dict]:
        """Replay the change log for ?from= catch-up (pubsub.rs:100)."""
        return [
            {"change": [typ, rid, json.loads(cells), cid]}
            for cid, typ, rid, cells in self.state.execute(
                "SELECT id, type, rid, cells FROM changes WHERE id > ? ORDER BY id",
                (change_id,),
            )
        ]

    def snapshot_events(self) -> List[dict]:
        """columns + current rows + eoq, without re-running the query."""
        events = [{"columns": self.columns}]
        for rid, cells in self.state.execute("SELECT rid, cells FROM q ORDER BY rid"):
            events.append({"row": [rid, json.loads(cells)]})
        events.append({"eoq": {"time": 0.0, "change_id": self.last_change_id}})
        return events

    def close(self):
        self.state.close()
