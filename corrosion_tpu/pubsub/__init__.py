"""Streaming SQL subscriptions (L9: the reference's query/pubsub engine).

Rebuild of `crates/corro-types/src/pubsub.rs` (the `Matcher` SQL-rewriting
subscription engine + `SubsManager`) and `updates.rs` (`UpdatesManager`
per-table notifier).  See matcher.py / manager.py for the design.
"""

from .manager import SubsManager, UpdatesManager
from .matcher import Matcher, MatcherError

__all__ = ["SubsManager", "UpdatesManager", "Matcher", "MatcherError"]
