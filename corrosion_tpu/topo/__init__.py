"""Topology & peer-sampling subsystem (ISSUE 9).

Three legs, one vocabulary the campaign engine sweeps as axes:

- **generators** (`families`, `sim.topology.Topology`'s geo-tier
  fields): deterministic, seed-free topology tensors — geo-tiered WAN
  graphs (region × AZ latency/loss classes) and heterogeneous degree
  distributions — layered on the existing ``edge_delay``/``edge_alive``
  machinery;
- **churn schedules** (`churn`): flash-crowd joins and diurnal churn as
  range-selector `FaultPlan` events, compiled by the existing
  matrix/factored fault compilers so they ride the packed and
  mesh-sharded kernels unchanged, and replayed on the host tier via
  range-atom link epochs (`topology_link_events` gives a WAN-tiered
  cell its host parity point);
- **peer sampler** (`sampler`): the pluggable peer-selection seam —
  uniform (the bit-identical default) vs a PeerSwap-style view sampler
  maintained as on-device per-node state.

See doc/topologies.md for the guide and the `peer-sampler-frontier`
builtin campaign for the measured uniform-vs-PeerSwap comparison.
"""

from .churn import (
    CHURN_FAMILIES,
    az_blocks,
    churn_events,
    diurnal_events,
    flash_crowd_events,
    topology_link_events,
)
from .families import FAMILIES, family_topology, min_delay_slots

__all__ = [
    "CHURN_FAMILIES",
    "FAMILIES",
    "az_blocks",
    "churn_events",
    "diurnal_events",
    "family_topology",
    "flash_crowd_events",
    "min_delay_slots",
    "topology_link_events",
]
