"""Churn schedules and host-tier topology compilation (ISSUE 9).

Both halves lower into the existing `FaultPlan` event algebra — range
selectors keep every schedule O(events), the factored sim compiler
turns them into rank-1 tensors that ride the packed and mesh-sharded
kernels unchanged, and the host fault drivers replay the SAME events
through their range-atom link epochs (`FaultPlan.range_link_epochs`),
so a topology family or churn shape is one artifact consumed by every
tier.

- `flash_crowd_events` — a cold-join wave: the tail ``frac`` of the id
  space is down from round 0 and restarts (wiped) at ``join_round`` —
  the flash-crowd join shape, recovered purely via anti-entropy;
- `diurnal_events` — follow-the-sun churn: each cycle, a seed-derived
  contiguous block (a "region asleep") crashes for the night window and
  rejoins at dawn;
- `churn_events` — the registry the campaign spec's ``churn`` scenario
  key resolves through;
- `topology_link_events` — compile a geo-tiered `sim.topology.Topology`
  into per-tier delay/loss link events over the contiguous region/AZ
  blocks, so a WAN-tiered cell has a HOST parity point: the host
  drivers install the rectangles as seed-derived LinkModels without
  ever expanding pairs (tests/cluster/test_fault_parity.py extends the
  parity gate over it).
"""

from __future__ import annotations

from typing import List, Tuple

from ..faults import FaultEvent, derive_seed


def flash_crowd_events(
    n_nodes: int,
    frac: float = 0.25,
    join_round: int = 8,
    wipe: bool = True,
) -> Tuple[FaultEvent, ...]:
    """The tail ``frac`` of the cluster joins cold at ``join_round``
    (ONE range-selector crash event, O(1) at any scale)."""
    k = max(1, min(n_nodes - 1, int(round(n_nodes * frac))))
    lo = n_nodes - k
    return (
        FaultEvent(
            "crash", 0, max(1, int(join_round)),
            node=f"{lo}:{n_nodes}", wipe=wipe,
        ),
    )


def diurnal_events(
    n_nodes: int,
    frac: float = 0.25,
    day_rounds: int = 12,
    night_rounds: int = 6,
    cycles: int = 2,
    seed: int = 0,
    wipe: bool = False,
) -> Tuple[FaultEvent, ...]:
    """Follow-the-sun churn: per cycle, a seed-derived contiguous block
    of ``frac``·N nodes sleeps for ``night_rounds`` after each
    ``day_rounds`` window (contiguous blocks ARE geographic under the
    contiguous-region rule, so this models a region going dark)."""
    k = max(1, min(n_nodes - 1, int(round(n_nodes * frac))))
    evs: List[FaultEvent] = []
    for c in range(cycles):
        start = day_rounds + c * (day_rounds + night_rounds)
        lo = derive_seed(seed, "diurnal", c) % (n_nodes - k + 1)
        evs.append(
            FaultEvent(
                "crash", start, start + night_rounds,
                node=f"{lo}:{lo + k}", wipe=wipe,
            )
        )
    return tuple(evs)


#: churn family name → builder; the campaign spec's ``churn`` scenario
#: key resolves here (`CampaignSpec.churn_events_for`)
CHURN_FAMILIES = ("flash-crowd", "diurnal")


def churn_events(
    name: str,
    n_nodes: int,
    frac: float = 0.25,
    round_knob: int = 8,
    seed: int = 0,
) -> Tuple[FaultEvent, ...]:
    """Resolve a churn family by name.  ``round_knob`` is the family's
    one timing knob (flash-crowd: the join round; diurnal: the day
    length, with nights at half a day)."""
    if name == "flash-crowd":
        return flash_crowd_events(n_nodes, frac=frac, join_round=round_knob)
    if name == "diurnal":
        return diurnal_events(
            n_nodes, frac=frac, day_rounds=max(2, int(round_knob)),
            night_rounds=max(2, int(round_knob) // 2), seed=seed,
        )
    raise KeyError(
        f"unknown churn family {name!r} (have {sorted(CHURN_FAMILIES)})"
    )


# -- host-tier compilation of a geo-tiered topology --------------------------


def az_blocks(n_nodes: int, n_regions: int, n_azs: int) -> List[Tuple[int, int, int]]:
    """(region, lo, hi) contiguous AZ blocks — byte-for-byte the block
    rule of `sim.topology.regions`/`azs`, so the emitted range
    selectors cover exactly the node sets the sim kernels tier."""
    per_r = max(1, n_nodes // n_regions)
    out: List[Tuple[int, int, int]] = []
    for r in range(n_regions):
        r_lo = r * per_r
        r_hi = n_nodes if r == n_regions - 1 else (r + 1) * per_r
        if r_lo >= n_nodes:
            break
        per_az = max(1, per_r // n_azs)
        for a in range(n_azs):
            a_lo = r_lo + a * per_az
            a_hi = r_hi if a == n_azs - 1 else min(r_hi, r_lo + (a + 1) * per_az)
            if a_lo >= r_hi:
                break
            out.append((r, a_lo, a_hi))
    return out


def topology_link_events(
    topo, n_nodes: int, end: int, start: int = 0
) -> Tuple[FaultEvent, ...]:
    """Compile a geo-tiered Topology into FaultPlan link events active
    over ``[start, end)``: per ordered AZ-block pair, a delay event for
    the tier's delay class and a loss event for its drop probability —
    range-selector rectangles the host drivers' range-atom link epochs
    install without pair expansion, giving a WAN-tiered cell its host
    parity point.  Rectangles of one kind are disjoint by construction,
    so the factored sim compiler accepts the same events too."""
    from ..sim.topology import Topology, loss_tiers

    assert isinstance(topo, Topology)
    base, az_t, inter_t = loss_tiers(topo)
    blocks = az_blocks(n_nodes, topo.n_regions, topo.n_azs)
    evs: List[FaultEvent] = []
    for r_i, lo_i, hi_i in blocks:
        for r_j, lo_j, hi_j in blocks:
            same_block = (lo_i, hi_i) == (lo_j, hi_j)
            if topo.region_delay_matrix:
                # measured-RTT matrix (ISSUE 13): the matrix IS the
                # delay rule (n_azs == 1 enforced by Topology, so a
                # block is a region); loss keeps the 2-tier rule
                delay = topo.region_delay_matrix[r_i][r_j]
                thr = base if r_i == r_j else inter_t
            elif same_block:
                delay, thr = topo.intra_delay, base
            elif r_i == r_j:
                delay, thr = topo.az_delay, az_t
            else:
                delay, thr = topo.inter_delay, inter_t
            if same_block and hi_i - lo_i <= 1:
                continue  # a single-node diagonal block has no pairs
            src, dst = f"{lo_i}:{hi_i}", f"{lo_j}:{hi_j}"
            if delay > 0:
                evs.append(
                    FaultEvent(
                        "delay", start, end, src=src, dst=dst,
                        delay_rounds=int(delay),
                    )
                )
            if thr > 0:
                evs.append(
                    FaultEvent(
                        "loss", start, end, src=src, dst=dst,
                        p=min(1.0, thr / 256.0),
                    )
                )
    return tuple(evs)
