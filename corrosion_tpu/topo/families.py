"""Named topology families (ISSUE 9): the campaign axis vocabulary.

A family is a DICT of `sim.topology.Topology` kwargs — not an instance
— so spec/cell keys can override individual fields (the same
compose-then-construct rule every other campaign axis follows).  The
`topo_family` key rides `CampaignSpec.scenario`/`topology`/`grid` and
the CLI's ``--topology`` flag; `sim topo show` renders a family's tier
table without touching jax.

Families mirror deployment shapes the reference actually runs in:

- ``flat``          — the legacy single tier (every default);
- ``flat-lossy``    — one tier, 10% wire loss everywhere;
- ``wan-3x2``       — 3 regions × 2 AZs, the Fly.io geo shape: free
  same-AZ links, 1-round cross-AZ, 2-round cross-region, loss growing
  with distance;
- ``wan-2region``   — a two-region split with a long, lossy trunk;
- ``hetero-degree`` — flat latency but hub/leaf fan-out classes
  (3/2/1 round-robin), the heterogeneous-degree distribution axis;
- ``wan-fly-6r``  — the measured-RTT-matrix family (ISSUE 13): six
  real Fly.io regions with the committed `FLY_RTT_MS` median
  region-to-region RTT table quantized into per-(region, region)
  delay classes (`Topology.region_delay_matrix`) — real WAN geometry
  (asymmetric distances, the trans-Pacific long pole) instead of the
  3-class tier constants.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

#: Fly.io region slugs, in matrix order
FLY_REGIONS = ("iad", "ord", "sjc", "lhr", "fra", "nrt")

#: measured median region-to-region RTTs, milliseconds — a committed
#: CONSTANT table (public Fly.io backbone measurements, mid-2025
#: medians, symmetric), so the family is reproducible and diffable
#: rather than fetched.  Diagonal = in-region RTT.
FLY_RTT_MS: Tuple[Tuple[float, ...], ...] = (
    #  iad    ord    sjc    lhr    fra    nrt
    (   2.0,  20.0,  65.0,  75.0,  90.0, 165.0),  # iad
    (  20.0,   2.0,  50.0,  90.0, 100.0, 145.0),  # ord
    (  65.0,  50.0,   2.0, 140.0, 150.0, 105.0),  # sjc
    (  75.0,  90.0, 140.0,   2.0,  15.0, 220.0),  # lhr
    (  90.0, 100.0, 150.0,  15.0,   2.0, 235.0),  # fra
    ( 165.0, 145.0, 105.0, 220.0, 235.0,   2.0),  # nrt
)

#: quantization grain: one sim round ≈ this much wall RTT.  40 ms/round
#: spreads the table over delay classes 0..6 (a 500 ms flush tick would
#: flatten everything into one class and measure nothing).
FLY_MS_PER_ROUND = 40.0


def rtt_matrix_to_delay_classes(
    rtt_ms: Sequence[Sequence[float]], ms_per_round: float
) -> Tuple[Tuple[int, ...], ...]:
    """Quantize an RTT matrix (ms) into round-delay classes:
    ``ceil(rtt / ms_per_round) - 1`` floored at 0, so sub-round RTTs
    are the free same-rack class and each extra round covers one more
    ``ms_per_round`` of wire distance."""
    import math

    out: List[Tuple[int, ...]] = []
    for row in rtt_ms:
        out.append(
            tuple(
                max(0, math.ceil(ms / ms_per_round) - 1) for ms in row
            )
        )
    return tuple(out)


FAMILIES: Dict[str, Dict[str, object]] = {
    "flat": {},
    "flat-lossy": {"loss": 0.1},
    "wan-3x2": {
        "n_regions": 3, "n_azs": 2,
        "intra_delay": 0, "az_delay": 1, "inter_delay": 2,
        "loss": 0.0, "az_loss": 0.02, "inter_loss": 0.1,
    },
    "wan-2region": {
        "n_regions": 2,
        "intra_delay": 0, "inter_delay": 2,
        "loss": 0.01, "inter_loss": 0.2,
    },
    "hetero-degree": {"degree_classes": (3, 2, 1)},
    "wan-fly-6r": {
        "n_regions": len(FLY_REGIONS),
        "region_delay_matrix": rtt_matrix_to_delay_classes(
            FLY_RTT_MS, FLY_MS_PER_ROUND
        ),
        "loss": 0.0, "inter_loss": 0.05,
    },
}


def family_topology(name: str) -> Dict[str, object]:
    """Topology kwargs for a named family (a fresh dict — callers
    overlay their overrides)."""
    if name not in FAMILIES:
        raise KeyError(
            f"unknown topology family {name!r} (have {sorted(FAMILIES)})"
        )
    return dict(FAMILIES[name])


def min_delay_slots(topo_kwargs: Dict[str, object]) -> int:
    """Smallest ``n_delay_slots`` a family's delay classes fit in
    (`round.validate`'s envelope: every delay, and sync's t+1 slot,
    must be representable without ring wraparound)."""
    matrix = topo_kwargs.get("region_delay_matrix") or ()
    d = max(
        int(topo_kwargs.get("intra_delay", 0)),
        int(topo_kwargs.get("az_delay", 0)),
        int(topo_kwargs.get("inter_delay", 1)),
        max((int(v) for row in matrix for v in row), default=0),
        1,
    )
    return d + 1
