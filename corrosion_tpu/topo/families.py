"""Named topology families (ISSUE 9): the campaign axis vocabulary.

A family is a DICT of `sim.topology.Topology` kwargs — not an instance
— so spec/cell keys can override individual fields (the same
compose-then-construct rule every other campaign axis follows).  The
`topo_family` key rides `CampaignSpec.scenario`/`topology`/`grid` and
the CLI's ``--topology`` flag; `sim topo show` renders a family's tier
table without touching jax.

Families mirror deployment shapes the reference actually runs in:

- ``flat``          — the legacy single tier (every default);
- ``flat-lossy``    — one tier, 10% wire loss everywhere;
- ``wan-3x2``       — 3 regions × 2 AZs, the Fly.io geo shape: free
  same-AZ links, 1-round cross-AZ, 2-round cross-region, loss growing
  with distance;
- ``wan-2region``   — a two-region split with a long, lossy trunk;
- ``hetero-degree`` — flat latency but hub/leaf fan-out classes
  (3/2/1 round-robin), the heterogeneous-degree distribution axis.
"""

from __future__ import annotations

from typing import Dict

FAMILIES: Dict[str, Dict[str, object]] = {
    "flat": {},
    "flat-lossy": {"loss": 0.1},
    "wan-3x2": {
        "n_regions": 3, "n_azs": 2,
        "intra_delay": 0, "az_delay": 1, "inter_delay": 2,
        "loss": 0.0, "az_loss": 0.02, "inter_loss": 0.1,
    },
    "wan-2region": {
        "n_regions": 2,
        "intra_delay": 0, "inter_delay": 2,
        "loss": 0.01, "inter_loss": 0.2,
    },
    "hetero-degree": {"degree_classes": (3, 2, 1)},
}


def family_topology(name: str) -> Dict[str, object]:
    """Topology kwargs for a named family (a fresh dict — callers
    overlay their overrides)."""
    if name not in FAMILIES:
        raise KeyError(
            f"unknown topology family {name!r} (have {sorted(FAMILIES)})"
        )
    return dict(FAMILIES[name])


def min_delay_slots(topo_kwargs: Dict[str, object]) -> int:
    """Smallest ``n_delay_slots`` a family's delay classes fit in
    (`round.validate`'s envelope: every delay, and sync's t+1 slot,
    must be representable without ring wraparound)."""
    d = max(
        int(topo_kwargs.get("intra_delay", 0)),
        int(topo_kwargs.get("az_delay", 0)),
        int(topo_kwargs.get("inter_delay", 1)),
        1,
    )
    return d + 1
