"""PeerSwap-style peer sampler: on-device per-node view state (ISSUE 9).

The uniform sampler the kernels default to draws every broadcast/sync/
probe target independently from [0, N) — a perfect oracle no real
gossip layer has.  PeerSwap (PAPERS.md, arxiv 2408.03829) replaces the
oracle with a small per-node **view** mixed by pairwise swaps at
seeded clocks, and proves the sequence of peers a node observes stays
close to uniform.  This module is the sim's round-grained analog:

- ``pview[N, V] i32`` (`SimState.pview`): each node's view — V peer
  ids, -1 marking empty slots.  Seeded at init (`init_peer_view`),
  carried through the jitted round loops (dense AND packed — the field
  rides the slim state, so `shrink_state` keeps it full-size), wiped to
  empty on crash-with-wipe like the SWIM tables.
- `peerswap_step` — one swap tick per round: every node picks a partner
  from its view, the swap message rides the REAL wire (ground-truth
  reachability plus the FaultPlan cut/loss seam via `swim._reachable`,
  so partitions stall view mixing exactly as they stall gossip), and
  the pair exchanges one view entry each way — i takes the partner's
  rotating slot ``t % V``, the partner receives i's offered entry
  (conflicts resolve by scatter-max: deterministic under vmap and mesh
  sharding).  An announce-staggered refill re-seeds empty slots with a
  uniform random id — the bootstrap re-resolution analog that lets a
  wiped node rejoin the overlay.
- `psample_view_targets` — the selection seam `swim.sample_member_targets`
  dispatches to when ``cfg.peer_sampler == "peerswap"``: candidates are
  gathered from the view (instead of drawn uniformly), then filtered
  exactly like the uniform path (self, duplicates, believed-DOWN in
  coupled full-view mode) and prefix-compacted.

Everything is pure gather/scatter-max/elementwise on the node axis, so
the sampler is bit-identical across solo, vmapped-lane, and
mesh-sharded runs (tests/sim/test_packed_sharded.py extends its matrix
over it).  The uniform default touches NONE of this: the kernels
branch at trace time on ``cfg.peer_sampler`` and the pre-ISSUE-9
programs compile byte-identically (tests/sim/test_topo.py pins the
digests)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..sim.state import ALIVE, DOWN, SimConfig, SimState


def init_peer_view(cfg: SimConfig, key: jax.Array) -> jnp.ndarray:
    """i32[N, V] seed-derived initial views: uniform random peer ids,
    -1 where the draw landed on self (duplicates are allowed here — the
    selection-side dup filter handles them, and swaps mix them away)."""
    n, v = cfg.n_nodes, cfg.view_slots
    pid = jax.random.randint(key, (n, v), 0, n, jnp.int32)
    me = jnp.arange(n, dtype=jnp.int32)[:, None]
    return jnp.where(pid != me, pid, -1)


def psample_view_targets(
    state: SimState, cfg: SimConfig, key: jax.Array, count: int
) -> jnp.ndarray:
    """i32[N, count] fan-out targets drawn from each node's PeerSwap
    view; -1 marks unfilled slots.  The peerswap twin of the uniform
    branch in `swim.sample_member_targets`: same transposed [over, N]
    oversample layout, same self/dup/believed-DOWN filters, same
    prefix compaction — only the candidate source differs."""
    from ..sim.swim import _compact_targets, _dup_before

    n, v = state.pview.shape
    over = 4 * count
    slots = jax.random.randint(key, (over, n), 0, v, jnp.int32)
    me = jnp.arange(n, dtype=jnp.int32)[None, :]
    # cand[o, i] = pview[i, slots[o, i]] — one gather per oversample row
    cand = state.pview[me, slots]  # [over, N]
    valid = (cand >= 0) & (cand != me)
    safe = jnp.maximum(cand, 0)
    if cfg.couple_membership and cfg.swim_full_view:
        valid &= state.view[me, safe] != DOWN
    valid &= ~_dup_before(cand, valid)
    return _compact_targets(cand, valid, count)


def peerswap_step(
    state: SimState, cfg: SimConfig, topo, key: jax.Array, faults=None
) -> SimState:
    """One swap tick (see module doc).  Reads the OLD view for every
    gather, then applies the three writes in a fixed order — take into
    slot ``g``, incoming offers into slot ``t % V`` (scatter-max), then
    the staggered empty-slot refill — so the result is a pure function
    of (state, key) whatever the batching or sharding."""
    from ..sim.swim import _reachable

    pview = state.pview
    n, v = pview.shape
    k_slot, k_loss, k_rb, k_rid = jax.random.split(key, 4)
    me = jnp.arange(n, dtype=jnp.int32)
    up = state.alive == ALIVE
    t = state.t

    c = jax.random.randint(k_slot, (n,), 0, v, jnp.int32)  # partner slot
    partner = pview[me, c]
    pc = jnp.maximum(partner, 0)
    ok = (partner >= 0) & (pc != me) & up
    # the swap message rides the wire: ground-truth reachability (both
    # endpoints up, same partition group, topology/fault loss and cuts)
    ok &= _reachable(state, topo, k_loss, me, pc, faults)

    g = (c + 1) % v  # the slot i replaces / offers from
    offer = pview[me, g]
    take = pview[pc, t % v]  # partner's rotating slot t % V

    # -- i takes the partner's entry into its own slot g
    take_ok = ok & (take >= 0) & (take != me)
    out = pview.at[me, g].set(jnp.where(take_ok, take, pview[me, g]))

    # -- i's offer lands in the partner's slot t % V; concurrent offers
    # to one partner resolve by max (deterministic), and the slot is
    # REPLACED (a swap, not an accumulate) only when an offer arrived
    give = jnp.where(ok & (offer >= 0) & (offer != pc), offer, -1)
    winner = jnp.full((n,), -1, jnp.int32).at[pc].max(give)
    w = t % v
    out = out.at[me, w].set(jnp.where(winner >= 0, winner, out[me, w]))

    # -- staggered refill of empty slots (bootstrap re-resolution): a
    # wiped/cold view repopulates even when nobody swaps into it
    stagger = (t + me) % cfg.announce_interval_rounds == 0
    rb = jax.random.randint(k_rb, (n,), 0, v, jnp.int32)
    rid = jax.random.randint(k_rid, (n,), 0, n, jnp.int32)
    cur = out[me, rb]
    refill = stagger & up & (cur < 0) & (rid != me)
    out = out.at[me, rb].set(jnp.where(refill, rid, cur))

    return state._replace(pview=out)
