"""Workload generator + stream-consistency checker + measured driver.

Rebuild of the Antithesis rust-load-generator
(.antithesis/client/src/main.rs:65-308): flood ``/v1/transactions`` with
inserts, follow the same table through a SQL subscription and the
``/v1/updates`` feed, and validate that every write eventually appears on
every watched stream — the "no lost writes" property the reference's
``eventually_check_db.sh`` / ``check_bookkeeping.py`` checkers assert.

Since ISSUE 8 this is also the host tier's MEASURED workload driver:

- **N writers × M watchers** — writers round-robin across the write
  addresses with disjoint id ranges; every watcher follows its own
  subscription stream, and consistency means every write surfaced on
  every HEALTHY watcher (a dead stream reads as "checker broken", never
  as "writes lost" — the two are classified separately).
- **publish→subscriber-visible latency** — each write's client-observed
  ``execute()`` completion is stamped; each watcher stamps first sight
  of each row; `LoadReport.visible_latency_s` carries the cross-sample
  p50/p95/p99 (the SWARM metric of record, regression-banded by the
  campaign engine's host-serving cells).
- **FaultPlan underneath** — `run_serving_cluster_load` drives an
  in-process cluster with the host fault drivers running during the
  flood, then heals everything before the settle check.
- **flight recording** — with telemetry on, every agent gets a
  `telemetry.HostTelemetry` feeding one shared `HostFlightRecorder`;
  the per-write stage stamps land in a host flight JSONL
  (`sim trace show` renders it) and serving metric families land on a
  `metrics.Registry`.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Union

from .api.client import TRANSPORT_ERRORS, ApiClient, ApiError, Overloaded


@dataclass
class LoadReport:
    writes_attempted: int = 0
    writes_ok: int = 0
    write_errors: int = 0
    # why the LAST failed write failed (repr) — the count alone can't
    # distinguish a dead node from a driver bug when a lane regresses
    last_write_error: Optional[str] = None
    # -- writer-side retry/backpressure accounting (ISSUE 13) ----------
    # 429 admission refusals observed (each retried after Retry-After),
    # transport-error retries, cross-address failovers, and writes whose
    # whole retry budget ran dry.  A failed write is RETRIABLE by
    # construction: it was never acked, so it can never count as lost —
    # the loss checker convicts on ACKED ids only.
    retries_429: int = 0
    retries_transport: int = 0
    write_failovers: int = 0
    writes_gave_up: int = 0
    sub_rows_seen: int = 0
    update_events_seen: int = 0
    missing_on_sub: List[int] = field(default_factory=list)
    stream_errors: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0
    # -- measured-driver fields (ISSUE 8) ------------------------------
    writers: int = 1
    watchers: int = 1
    flood_s: float = 0.0       # wall for the write flood alone
    stream_deaths: int = 0     # watcher streams that died (checker loss)
    visible_latency_s: Optional[dict] = None  # publish→visible block
    write_latency_s: Optional[dict] = None    # client execute() latency

    @property
    def consistent(self) -> bool:
        """No lost writes AND the checker itself stayed attached — a dead
        watch stream must read as "checker broken", not "writes lost"."""
        return (
            self.writes_ok > 0
            and not self.missing_on_sub
            and not self.stream_errors
        )

    @property
    def checker_broken(self) -> bool:
        """A watch stream died or never attached: the consistency verdict
        is INCONCLUSIVE, not a replication failure."""
        return bool(self.stream_errors)

    @property
    def lost_writes(self) -> bool:
        """Writes committed but never surfaced on a HEALTHY watcher: the
        actual replication failure the checker exists to catch.
        ``missing_on_sub`` is computed ONLY from watchers that stayed
        attached to the end, so it convicts regardless of whether some
        OTHER stream also died — a dead stream elsewhere must not grant
        amnesty to a verified loss on a healthy one."""
        return bool(self.missing_on_sub)

    @property
    def throughput_wps(self) -> float:
        """Committed writes per second of flood wall."""
        if self.flood_s <= 0:
            return 0.0
        return self.writes_ok / self.flood_s

    def to_dict(self) -> dict:
        return {
            "writes_attempted": self.writes_attempted,
            "writes_ok": self.writes_ok,
            "write_errors": self.write_errors,
            "last_write_error": self.last_write_error,
            "sub_rows_seen": self.sub_rows_seen,
            "update_events_seen": self.update_events_seen,
            "missing_on_sub": len(self.missing_on_sub),
            "stream_errors": list(self.stream_errors),
            "consistent": self.consistent,
            "checker_broken": self.checker_broken,
            "lost_writes": self.lost_writes,
            "elapsed_s": round(self.elapsed_s, 3),
            "writers": self.writers,
            "watchers": self.watchers,
            "flood_s": round(self.flood_s, 3),
            "throughput_wps": round(self.throughput_wps, 1),
            "stream_deaths": self.stream_deaths,
            "visible_latency_s": self.visible_latency_s,
            "write_latency_s": self.write_latency_s,
            "retries_429": self.retries_429,
            "retries_transport": self.retries_transport,
            "write_failovers": self.write_failovers,
            "writes_gave_up": self.writes_gave_up,
        }


class LoadGenerator:
    """Drives one table (default the test schema's ``tests``) with N
    writer lanes over the write addresses while M watchers follow the
    read addresses (same node or different ones — cross-node watching
    also validates convergence).  The single-addr single-lane form is
    the original Antithesis shape and stays the default."""

    #: per-attempt retry budget (consecutive 429/transport failures on
    #: ONE address before failing over to the next)
    WRITE_MAX_RETRIES = 6
    #: address-rotation budget per write: every address gets this many
    #: full retry rounds before the write records an error (unacked →
    #: retriable, never lost)
    FAILOVER_ROUNDS = 2
    #: wall budget per address-attempt (ISSUE 15 satellite): server
    #: Retry-After hints are clamped against what's left of it, so a
    #: bogus `Retry-After: 3600` from a confused node costs at most
    #: this much before the writer fails over to the next address
    WRITE_GIVE_UP_S = 20.0

    def __init__(
        self,
        write_addr: Union[str, Sequence[str]],
        read_addr: Union[str, Sequence[str], None] = None,
        table: str = "tests",
        seed: int = 0,
        n_writers: int = 1,
        n_watchers: int = 1,
        retry_writes: bool = True,
    ):
        write_addrs = (
            [write_addr] if isinstance(write_addr, str) else list(write_addr)
        )
        if read_addr is None:
            read_addrs = list(write_addrs)
        elif isinstance(read_addr, str):
            read_addrs = [read_addr]
        else:
            read_addrs = list(read_addr)
        self.write_clients = [ApiClient(a) for a in write_addrs]
        self.read_clients = [ApiClient(a) for a in read_addrs]
        # original single-lane attribute names kept for callers/tests
        self.write_client = self.write_clients[0]
        self.read_client = self.read_clients[0]
        self.table = table
        self._rng = random.Random(seed)
        self.retry_writes = retry_writes
        self.n_writers = max(1, int(n_writers))
        self.n_watchers = max(1, int(n_watchers))
        self._written: Set[int] = set()
        self._write_ok_at: Dict[int, float] = {}
        self._write_lat: List[float] = []
        # per-watcher first-sight stamps; _sub_seen stays the union (the
        # events-flowed signal); consistency intersects HEALTHY watchers
        self._seen_at: List[Dict[int, float]] = []
        self._watcher_ok: List[bool] = []
        # a watcher KNOWN dead (attach failure, stream death, early
        # EOF): the settle loop stops waiting on it — its rows can
        # never arrive, and the death is already in stream_errors
        self._watcher_dead: List[bool] = []
        # snapshot rows, per watcher: they prove VISIBILITY (a
        # reconnecting watcher recovers missed writes as snapshot rows)
        # but carry no latency truth — a stale pre-run row against a
        # live cluster would read as ~0 ms and poison the percentiles,
        # so only live "change" events stamp _seen_at
        self._snap_seen: List[Set[int]] = []
        self._sub_seen: Set[int] = set()
        self.report = LoadReport(
            writers=self.n_writers, watchers=self.n_watchers
        )

    async def _write_one(self, w: int, rowid: int, rng) -> bool:
        """One write through the retry/backpressure stack (ISSUE 13):
        `execute_with_retry` rides the decorrelated-jitter Backoff on
        each address (429s sleep at least the server's Retry-After);
        an exhausted budget FAILS OVER to the next write address — a
        crashed-and-restarting node must cost retries, not the write.
        Returns committed?; an uncommitted write was never acked, so it
        classifies retriable, never lost."""
        stmts = [
            [
                f"INSERT OR REPLACE INTO {self.table} (id, text) "
                "VALUES (?, ?)",
                [rowid, f"load-{rowid}"],
            ]
        ]
        counters: Dict[str, int] = {}
        try:
            n_clients = len(self.write_clients)
            for attempt in range(self.FAILOVER_ROUNDS * n_clients):
                client = self.write_clients[(w + attempt) % n_clients]
                try:
                    await client.execute_with_retry(
                        stmts, max_retries=self.WRITE_MAX_RETRIES,
                        rng=rng, counters=counters,
                        give_up_s=self.WRITE_GIVE_UP_S,
                    )
                    return True
                except Overloaded as e:
                    self.report.last_write_error = repr(e)
                except ApiError as e:
                    # deterministic refusal (schema error, 4xx/5xx):
                    # retrying cannot help
                    self.report.last_write_error = repr(e)
                    return False
                except TRANSPORT_ERRORS as e:
                    self.report.last_write_error = repr(e)
                if attempt + 1 < self.FAILOVER_ROUNDS * n_clients:
                    self.report.write_failovers += 1
            self.report.writes_gave_up += 1
            return False
        finally:
            self.report.retries_429 += counters.get("retries_429", 0)
            self.report.retries_transport += counters.get(
                "retries_transport", 0
            )

    async def _writer(
        self, w: int, n_writes: int, rate_hz: float, base_id: int
    ):
        client = self.write_clients[w % len(self.write_clients)]
        # per-writer backoff stream: deterministic under the lane seed
        rng = random.Random((self._rng.getrandbits(32) << 8) | (w & 0xFF))
        interval = 1.0 / rate_hz if rate_hz > 0 else 0.0
        for i in range(n_writes):
            rowid = base_id + i
            self.report.writes_attempted += 1
            t0 = time.monotonic()
            try:
                if self.retry_writes:
                    ok = await self._write_one(w, rowid, rng)
                else:
                    await client.execute(
                        [
                            [
                                f"INSERT OR REPLACE INTO {self.table} "
                                "(id, text) VALUES (?, ?)",
                                [rowid, f"load-{rowid}"],
                            ]
                        ]
                    )
                    ok = True
                if ok:
                    now = time.monotonic()
                    self.report.writes_ok += 1
                    self._written.add(rowid)
                    self._write_ok_at[rowid] = now
                    self._write_lat.append(now - t0)
                else:
                    self.report.write_errors += 1
            except Exception as e:
                # counted for the report's verdict AND kept: "why" is
                # what distinguishes a dead node from a driver bug when
                # a campaign lane comes back inconsistent
                self.report.write_errors += 1
                self.report.last_write_error = repr(e)
            if interval:
                await asyncio.sleep(interval * self._rng.uniform(0.5, 1.5))

    def _saw(self, j: int, rowid, snapshot: bool = False) -> None:
        if not isinstance(rowid, int):
            return
        self._sub_seen.add(rowid)
        if snapshot:
            self._snap_seen[j].add(rowid)
        else:
            self._seen_at[j].setdefault(rowid, time.monotonic())
        self.report.sub_rows_seen += 1

    def _watcher_rows(self, j: int) -> Set[int]:
        """Everything watcher j has PROOF of seeing: live change events
        (latency-stamped) plus snapshot rows (visibility only)."""
        return set(self._seen_at[j]) | self._snap_seen[j]

    #: watch-stream attach budget: a black-holed read address must
    #: become a RECORDED checker death, not a silently hung task that
    #: the settle loop waits out (subscribe has no transport timeout)
    ATTACH_TIMEOUT_S = 10.0

    async def _subscriber(self, j: int, stop: asyncio.Event):
        client = self.read_clients[j % len(self.read_clients)]
        try:
            sub = await asyncio.wait_for(
                client.subscribe(
                    [f"SELECT id, text FROM {self.table}", []]
                ),
                self.ATTACH_TIMEOUT_S,
            )
        except asyncio.CancelledError:
            # cancelled before ever attaching (run ended while this
            # watcher was still dialing): it verified NOTHING — record
            # the death so the verdict can't silently shrink to the
            # watchers that did attach
            self.report.stream_errors.append(
                f"subscribe[{j}]: cancelled before attach"
            )
            self.report.stream_deaths += 1
            self._watcher_dead[j] = True
            raise
        except Exception as e:
            self.report.stream_errors.append(f"subscribe[{j}]: {e!r}")
            self.report.stream_deaths += 1
            self._watcher_dead[j] = True
            return
        try:
            async for event in sub:
                if stop.is_set():
                    break
                if "row" in event:
                    # initial-snapshot (or reconnect-snapshot) row
                    self._saw(j, event["row"][1][0], snapshot=True)
                elif "change" in event:
                    self._saw(j, event["change"][2][0])
            if stop.is_set():
                self._watcher_ok[j] = True
            else:
                # subscriptions are infinite: a "clean" EOF before we
                # asked means the serving node died (server close reads
                # as EOF, not an error) — checker broken, not lost writes
                self.report.stream_errors.append(
                    f"subscription[{j}]: stream ended early"
                )
                self.report.stream_deaths += 1
                self._watcher_dead[j] = True
        except asyncio.CancelledError:
            self._watcher_ok[j] = True  # stopped by us, not dead
        except Exception as e:
            self.report.stream_errors.append(f"subscription[{j}]: {e!r}")
            self.report.stream_deaths += 1
            self._watcher_dead[j] = True
        finally:
            sub.close()

    async def _updates_watcher(self, stop: asyncio.Event):
        try:
            stream = await asyncio.wait_for(
                self.read_client.updates(self.table),
                self.ATTACH_TIMEOUT_S,
            )
        except asyncio.CancelledError:
            self.report.stream_errors.append(
                "updates attach: cancelled before attach"
            )
            self.report.stream_deaths += 1
            raise
        except Exception as e:
            self.report.stream_errors.append(f"updates attach: {e!r}")
            self.report.stream_deaths += 1
            return
        try:
            async for _event in stream:
                if stop.is_set():
                    break
                self.report.update_events_seen += 1
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self.report.stream_errors.append(f"updates: {e!r}")
            self.report.stream_deaths += 1
        finally:
            stream.close()

    def _finalize_latency(self) -> None:
        from .telemetry import latency_block

        samples: List[float] = []
        for seen in self._seen_at:
            for rowid, seen_s in seen.items():
                ok_s = self._write_ok_at.get(rowid)
                if ok_s is not None:
                    # an event can beat the writer's HTTP response by a
                    # task-scheduling hair; clamp, don't record negatives
                    samples.append(max(0.0, seen_s - ok_s))
        self.report.visible_latency_s = latency_block(samples)
        self.report.write_latency_s = latency_block(self._write_lat)

    def _finalize_missing(self) -> None:
        missing: Set[int] = set()
        healthy = [
            self._watcher_rows(j)
            for j in range(self.n_watchers)
            if self._watcher_ok[j]
        ]
        for seen in healthy:
            missing |= self._written - seen
        if not healthy:
            # every watcher died or never settled: nothing to certify
            # against.  Ensure the checker reads BROKEN even if no
            # watcher got far enough to record an error (e.g. all hung
            # in attach until cancelled) — a run with zero visibility
            # evidence must never report consistent=True
            missing = set()
            if self._written and not self.report.stream_errors:
                self.report.stream_errors.append(
                    "no watcher settled: consistency unverified"
                )
        self.report.missing_on_sub = sorted(missing)

    async def run(
        self,
        n_writes: int = 100,
        rate_hz: float = 200.0,
        settle_timeout_s: float = 30.0,
        base_id: int = 1_000_000,
        settle_gate=None,
    ) -> LoadReport:
        """Flood ``n_writes`` total writes across the writer lanes, then
        wait until every healthy watcher saw every committed write (or
        ``settle_timeout_s``).  ``settle_gate`` (an awaitable) runs
        between the flood and the settle loop — the serving harness
        parks the fault driver's heal-everything completion there."""
        t0 = time.monotonic()
        stop = asyncio.Event()
        self._seen_at = [dict() for _ in range(self.n_watchers)]
        self._snap_seen = [set() for _ in range(self.n_watchers)]
        self._watcher_ok = [False] * self.n_watchers
        self._watcher_dead = [False] * self.n_watchers
        watch_tasks = [
            asyncio.create_task(self._subscriber(j, stop))
            for j in range(self.n_watchers)
        ]
        upd_task = asyncio.create_task(self._updates_watcher(stop))
        await asyncio.sleep(0.2)  # streams attached before the flood
        per = -(-n_writes // self.n_writers)  # ceil split, disjoint ids
        flood_t0 = time.monotonic()
        await asyncio.gather(
            *(
                self._writer(
                    w, min(per, n_writes - w * per), rate_hz,
                    base_id + w * per,
                )
                for w in range(self.n_writers)
                if n_writes - w * per > 0
            )
        )
        self.report.flood_s = time.monotonic() - flood_t0
        if settle_gate is not None:
            await settle_gate
        # eventually: every committed write visible on every LIVE
        # watcher's stream — known-dead watchers can never catch up, so
        # waiting on them would just burn the whole timeout (their
        # death is already recorded in stream_errors)
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if all(
                self._written <= self._watcher_rows(j)
                for j in range(self.n_watchers)
                if not self._watcher_dead[j]
            ):
                break
            await asyncio.sleep(0.2)
        stop.set()
        for t in watch_tasks + [upd_task]:
            t.cancel()
        await asyncio.gather(*watch_tasks, upd_task, return_exceptions=True)
        self._finalize_missing()
        self._finalize_latency()
        self.report.elapsed_s = time.monotonic() - t0
        return self.report


async def run_serving_cluster_load(
    n_nodes: int = 3,
    n_writes: int = 60,
    n_writers: int = 2,
    n_watchers: int = 2,
    rate_hz: float = 0.0,
    settle_timeout_s: float = 30.0,
    seed: int = 0,
    plan=None,
    telemetry: bool = False,
    registry=None,
    recorder=None,
    trace_path: Optional[str] = None,
    header: Optional[dict] = None,
    traceparent: Optional[str] = None,
    table: str = "tests",
) -> dict:
    """One measured serving run: boot an in-process ``n_nodes`` cluster
    with an ApiServer per node, flood it through `LoadGenerator`
    (writers round-robin the nodes; watchers follow the OTHER nodes, so
    visibility requires replication), optionally with ``plan`` (a
    `faults.FaultPlan`) replayed by `HostFaultDriver` during the flood,
    and return the LoadReport dict.

    ``telemetry`` arms the host flight recorder on every agent
    (`telemetry.attach_host_telemetry`): the result gains a
    ``telemetry`` summary block, ``trace_path`` writes the host flight
    JSONL, and serving metric families land on ``registry`` (a private
    `metrics.Registry` by default so runs don't bleed into each other —
    pass `metrics.REGISTRY` to scrape them from a live MetricsServer).

    The whole run executes inside a ``serving_loadgen`` span;
    ``traceparent`` (W3C) parents it — the campaign engine passes its
    cell span so serving runs join the existing trace tree."""
    from .api.http import ApiServer
    from .testing import Cluster
    from .tracing import extract, span

    cluster = Cluster(n_nodes, use_swim=False, seed=seed)
    await cluster.start()
    servers: List[ApiServer] = []
    rec = recorder
    reg = registry
    try:
        for agent in cluster.agents:
            srv = ApiServer(agent)
            await srv.start()
            servers.append(srv)
        if telemetry:
            from .metrics import Registry
            from .telemetry import (
                HostFlightRecorder,
                attach_host_telemetry,
            )

            rec = rec or HostFlightRecorder()
            reg = reg if reg is not None else Registry()
            for agent in cluster.agents:
                attach_host_telemetry(agent, recorder=rec, registry=reg)
        write_addrs = [s.addr for s in servers]
        # watchers read ONLY nodes writers do not write to (writer w
        # hits node w % n): publish→visible then always crosses the
        # gossip path.  When every node is a writer (n_writers ≥ n) the
        # overlap is unavoidable — rotate so each watcher at least
        # avoids its like-indexed writer; single-node clusters
        # self-watch.
        writer_nodes = {w % n_nodes for w in range(n_writers)}
        non_writers = [
            a for i, a in enumerate(write_addrs) if i not in writer_nodes
        ]
        read_addrs = non_writers or (
            # every node is a writer: rotate by one so watcher j still
            # avoids its like-indexed writer's node (reversed() would
            # map the middle watcher of an odd cluster onto itself)
            [write_addrs[(i + 1) % n_nodes] for i in range(n_nodes)]
            if n_nodes > 1
            else write_addrs
        )
        gen = LoadGenerator(
            write_addrs, read_addrs, table=table, seed=seed,
            n_writers=n_writers, n_watchers=n_watchers,
        )
        gate = None
        fault_error: List[str] = []
        if plan is not None:
            from .faults import HostFaultDriver

            driver = HostFaultDriver(plan, cluster)

            # the driver heals everything by the end of its schedule;
            # the loadgen's settle loop starts only after that, so a
            # consistent=False can never be "the partition was still
            # up".  A driver failure is RECORDED, never raised — one
            # broken lane must not crash a whole campaign — and the
            # gate is cancelled+consumed on any exit path so an
            # aborted run can't leave an orphaned task injecting
            # faults into the teardown.
            async def _drive():
                try:
                    await driver.run()
                except Exception as e:  # noqa: BLE001
                    fault_error.append(f"{type(e).__name__}: {e}")

            gate = asyncio.ensure_future(_drive())
        try:
            with span(
                "serving_loadgen",
                parent=extract(traceparent),
                nodes=n_nodes, writers=n_writers, watchers=n_watchers,
                writes=n_writes, faults=plan is not None,
            ) as sp:
                report = await gen.run(
                    n_writes=n_writes, rate_hz=rate_hz,
                    settle_timeout_s=settle_timeout_s, settle_gate=gate,
                )
                sp.set_attribute("consistent", report.consistent)
                sp.set_attribute("writes_ok", report.writes_ok)
                if report.visible_latency_s:
                    sp.set_attribute(
                        "publish_visible_p99_s",
                        report.visible_latency_s["p99"],
                    )
        finally:
            if gate is not None:
                gate.cancel()
                await asyncio.gather(gate, return_exceptions=True)
        out = report.to_dict()
        out["n_nodes"] = n_nodes
        out["faults"] = plan is not None
        if plan is not None:
            out["plan_horizon"] = plan.horizon
            if fault_error:
                # the schedule did not fully replay: the lane's numbers
                # stand, but the record says the faults were partial
                out["fault_driver_error"] = fault_error[0]
        if telemetry and rec is not None:
            out["telemetry"] = rec.summary()
            if trace_path:
                from .telemetry import write_host_flight_jsonl

                head = {
                    "n_nodes": n_nodes,
                    "writers": n_writers,
                    "watchers": n_watchers,
                    "seed": seed,
                    "traceparent": sp.context.traceparent(),
                }
                if header:
                    head.update(header)
                write_host_flight_jsonl(trace_path, rec, header=head)
        return out
    finally:
        for srv in servers:
            await srv.stop()
        await cluster.stop()
