"""Workload generator + stream-consistency checker.

Rebuild of the Antithesis rust-load-generator
(.antithesis/client/src/main.rs:65-308): flood ``/v1/transactions`` with
inserts, follow the same table through a SQL subscription and the
``/v1/updates`` feed, and validate that every write eventually appears on
every watched stream — the "no lost writes" property the reference's
``eventually_check_db.sh`` / ``check_bookkeeping.py`` checkers assert.
"""

from __future__ import annotations

import asyncio
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from .api.client import ApiClient


@dataclass
class LoadReport:
    writes_attempted: int = 0
    writes_ok: int = 0
    write_errors: int = 0
    sub_rows_seen: int = 0
    update_events_seen: int = 0
    missing_on_sub: List[int] = field(default_factory=list)
    stream_errors: List[str] = field(default_factory=list)
    elapsed_s: float = 0.0

    @property
    def consistent(self) -> bool:
        """No lost writes AND the checker itself stayed attached — a dead
        watch stream must read as "checker broken", not "writes lost"."""
        return (
            self.writes_ok > 0
            and not self.missing_on_sub
            and not self.stream_errors
        )

    def to_dict(self) -> dict:
        return {
            "writes_attempted": self.writes_attempted,
            "writes_ok": self.writes_ok,
            "write_errors": self.write_errors,
            "sub_rows_seen": self.sub_rows_seen,
            "update_events_seen": self.update_events_seen,
            "missing_on_sub": len(self.missing_on_sub),
            "stream_errors": list(self.stream_errors),
            "consistent": self.consistent,
            "elapsed_s": round(self.elapsed_s, 3),
        }


class LoadGenerator:
    """Drives one table (default the test schema's ``tests``) on a write
    address while watching a read address (same node or a different one —
    cross-node watching also validates convergence)."""

    def __init__(
        self,
        write_addr: str,
        read_addr: Optional[str] = None,
        table: str = "tests",
        seed: int = 0,
    ):
        self.write_client = ApiClient(write_addr)
        self.read_client = ApiClient(read_addr or write_addr)
        self.table = table
        self._rng = random.Random(seed)
        self._written: Set[int] = set()
        self._sub_seen: Set[int] = set()
        self.report = LoadReport()

    async def _writer(self, n_writes: int, rate_hz: float, base_id: int):
        interval = 1.0 / rate_hz if rate_hz > 0 else 0.0
        for i in range(n_writes):
            rowid = base_id + i
            self.report.writes_attempted += 1
            try:
                await self.write_client.execute(
                    [
                        [
                            f"INSERT OR REPLACE INTO {self.table} (id, text) "
                            "VALUES (?, ?)",
                            [rowid, f"load-{rowid}"],
                        ]
                    ]
                )
                self.report.writes_ok += 1
                self._written.add(rowid)
            except Exception:
                self.report.write_errors += 1
            if interval:
                await asyncio.sleep(interval * self._rng.uniform(0.5, 1.5))

    async def _subscriber(self, stop: asyncio.Event):
        try:
            sub = await self.read_client.subscribe(
                [f"SELECT id, text FROM {self.table}", []]
            )
        except Exception as e:
            self.report.stream_errors.append(f"subscribe: {e!r}")
            return
        try:
            async for event in sub:
                if stop.is_set():
                    break
                if "row" in event:
                    self._sub_seen.add(event["row"][1][0])
                    self.report.sub_rows_seen += 1
                elif "change" in event:
                    self._sub_seen.add(event["change"][2][0])
                    self.report.sub_rows_seen += 1
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self.report.stream_errors.append(f"subscription: {e!r}")
        finally:
            sub.close()

    async def _updates_watcher(self, stop: asyncio.Event):
        try:
            stream = await self.read_client.updates(self.table)
        except Exception as e:
            self.report.stream_errors.append(f"updates attach: {e!r}")
            return
        try:
            async for _event in stream:
                if stop.is_set():
                    break
                self.report.update_events_seen += 1
        except asyncio.CancelledError:
            pass
        except Exception as e:
            self.report.stream_errors.append(f"updates: {e!r}")
        finally:
            stream.close()

    async def run(
        self,
        n_writes: int = 100,
        rate_hz: float = 200.0,
        settle_timeout_s: float = 30.0,
        base_id: int = 1_000_000,
    ) -> LoadReport:
        t0 = time.monotonic()
        stop = asyncio.Event()
        sub_task = asyncio.create_task(self._subscriber(stop))
        upd_task = asyncio.create_task(self._updates_watcher(stop))
        await asyncio.sleep(0.2)  # streams attached before the flood
        await self._writer(n_writes, rate_hz, base_id)
        # eventually: every committed write visible on the subscription
        deadline = time.monotonic() + settle_timeout_s
        while time.monotonic() < deadline:
            if self._written <= self._sub_seen:
                break
            await asyncio.sleep(0.2)
        self.report.missing_on_sub = sorted(self._written - self._sub_seen)
        stop.set()
        for t in (sub_task, upd_task):
            t.cancel()
        await asyncio.gather(sub_task, upd_task, return_exceptions=True)
        self.report.elapsed_s = time.monotonic() - t0
        return self.report
