"""Native fast-path loader.

Compiles `crdt_core.cpp` to a shared library on first use (g++, cached by
source mtime under ``_build/``) and binds it via ctypes; every entry point
has a pure-Python fallback (`corrosion_tpu.core.crdt` is the spec), so the
framework runs without a toolchain.  Parity between the two is enforced by
tests/agent/test_native_core.py.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import List, Optional, Sequence, Tuple

from ..core.pkcodec import encode_value
from ..core.types import ActorId, SqliteValue

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "crdt_core.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB_PATH = os.path.join(_BUILD_DIR, "libcrdt_core.so")

_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _compile() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if (
        os.path.exists(_LIB_PATH)
        and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC)
    ):
        return _LIB_PATH
    try:
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB_PATH + ".tmp", _SRC],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
        return _LIB_PATH
    except (OSError, subprocess.SubprocessError):
        return None


def load() -> Optional[ctypes.CDLL]:
    """The compiled core, or None when unavailable (Python fallback used)."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    path = _compile()
    if path is None:
        _load_failed = True
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        _load_failed = True
        return None
    lib.crdt_value_cmp.restype = ctypes.c_int
    lib.crdt_value_cmp.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
    ]
    lib.crdt_merge_batch.restype = None
    lib.crdt_merge_batch.argtypes = [
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_char_p,
        ctypes.c_int32,
        ctypes.POINTER(ctypes.c_uint8),
    ]
    lib.crdt_core_version.restype = ctypes.c_int
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def value_cmp_native(a: SqliteValue, b: SqliteValue) -> int:
    lib = load()
    if lib is None:
        from ..core.crdt import value_cmp

        return value_cmp(a, b)
    ea, eb = encode_value(a), encode_value(b)
    return lib.crdt_value_cmp(ea, len(ea), eb, len(eb))


Cell = Tuple[int, SqliteValue, ActorId]  # (col_version, value, site_id)


def _pack(cells: Sequence[Optional[Cell]]):
    n = len(cells)
    colver = (ctypes.c_int64 * n)()
    off = (ctypes.c_int64 * (n + 1))()
    sites = bytearray(16 * n)
    vals = bytearray()
    for i, cell in enumerate(cells):
        if cell is None:
            off[i + 1] = len(vals) + 1
            vals += b"\x00"
            continue
        cv, val, site = cell
        colver[i] = cv
        enc = encode_value(val)
        vals += enc
        off[i + 1] = len(vals)
        sites[16 * i : 16 * (i + 1)] = site.bytes_
    return colver, bytes(vals), off, bytes(sites)


def merge_batch(
    existing: Sequence[Optional[Cell]],
    incoming: Sequence[Cell],
    merge_equal_values: bool = True,
) -> List[int]:
    """Vector of MergeOutcome ints for incoming[i] vs existing[i].
    Uses the C++ core when available, else the Python spec."""
    n = len(incoming)
    lib = load()
    if lib is None:
        from ..core.crdt import merge_cell

        return [
            merge_cell(existing[i], incoming[i], merge_equal_values)
            for i in range(n)
        ]
    mask = (ctypes.c_uint8 * n)(*[0 if e is None else 1 for e in existing])
    e_cv, e_vals, e_off, e_sites = _pack(existing)
    i_cv, i_vals, i_off, i_sites = _pack(incoming)
    out = (ctypes.c_uint8 * n)()
    lib.crdt_merge_batch(
        n, mask, e_cv, e_vals, e_off, e_sites,
        i_cv, i_vals, i_off, i_sites,
        1 if merge_equal_values else 0, out,
    )
    return list(out)
