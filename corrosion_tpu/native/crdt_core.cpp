// Native CRDT merge core.
//
// The reference ships its merge semantics as a prebuilt C SQLite extension
// (cr-sqlite, loaded at corro-types/src/sqlite.rs:121-139); this is the
// rebuild's native tier: the same column-LWW comparison rules
// (doc/crdts.md:235-248 — col_version, then SQLite value ordering, then
// site_id) over the framework's tag-encoded values, exposed as a C ABI for
// ctypes and used by the store's batched apply path.
//
// Values are tag-encoded (core/pkcodec.py):
//   0x00 NULL | 0x01 int64 BE | 0x02 float64 BE | 0x03 str (u32 len + utf8)
//   0x04 bytes (u32 len + raw)
//
// Build: g++ -O2 -shared -fPIC -o libcrdt_core.so crdt_core.cpp

#include <cstdint>
#include <cstring>

namespace {

constexpr uint8_t TAG_NULL = 0x00;
constexpr uint8_t TAG_INT = 0x01;
constexpr uint8_t TAG_FLOAT = 0x02;
constexpr uint8_t TAG_TEXT = 0x03;
constexpr uint8_t TAG_BLOB = 0x04;

int rank(uint8_t tag) {
  switch (tag) {
    case TAG_NULL: return 0;
    case TAG_INT:
    case TAG_FLOAT: return 1;
    case TAG_TEXT: return 2;
    default: return 3;
  }
}

uint64_t load_be64(const uint8_t* p) {
  uint64_t v = 0;
  for (int i = 0; i < 8; i++) v = (v << 8) | p[i];
  return v;
}

double as_double(const uint8_t* v) {
  if (v[0] == TAG_INT) {
    return static_cast<double>(static_cast<int64_t>(load_be64(v + 1)));
  }
  uint64_t bits = load_be64(v + 1);
  double d;
  std::memcpy(&d, &bits, sizeof(d));
  return d;
}

int64_t as_int(const uint8_t* v) {
  return static_cast<int64_t>(load_be64(v + 1));
}

uint32_t load_be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

// Exact int64-vs-double comparison (sqlite3IntFloatCompare's algorithm):
// converting the int to double loses precision above 2^53, so decide on the
// truncated integer part first and only then on the fraction — this keeps
// the native core bit-identical to the Python spec's exact comparison.
int int_float_cmp(int64_t i, double r) {
  if (r < -9223372036854775808.0) return 1;
  if (r >= 9223372036854775808.0) return -1;
  int64_t y = (int64_t)r;
  if (i < y) return -1;
  if (i > y) return 1;
  double s = (double)i;  // exact here: i == trunc(r) which is representable
  return s < r ? -1 : (s > r ? 1 : 0);
}

int bytes_cmp(const uint8_t* a, uint32_t alen, const uint8_t* b, uint32_t blen) {
  uint32_t n = alen < blen ? alen : blen;
  int c = n ? std::memcmp(a, b, n) : 0;
  if (c != 0) return c < 0 ? -1 : 1;
  if (alen == blen) return 0;
  return alen < blen ? -1 : 1;
}

}  // namespace

extern "C" {

// SQLite ORDER BY semantics over tag-encoded values: -1 / 0 / +1.
int crdt_value_cmp(const uint8_t* a, int64_t alen, const uint8_t* b,
                   int64_t blen) {
  (void)alen;
  (void)blen;
  int ra = rank(a[0]), rb = rank(b[0]);
  if (ra != rb) return ra < rb ? -1 : 1;
  switch (ra) {
    case 0:
      return 0;
    case 1: {
      if (a[0] == TAG_INT && b[0] == TAG_INT) {
        int64_t x = as_int(a), y = as_int(b);
        return x < y ? -1 : (x > y ? 1 : 0);
      }
      if (a[0] == TAG_INT) return int_float_cmp(as_int(a), as_double(b));
      if (b[0] == TAG_INT) return -int_float_cmp(as_int(b), as_double(a));
      double x = as_double(a), y = as_double(b);
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    default: {
      uint32_t la = load_be32(a + 1), lb = load_be32(b + 1);
      return bytes_cmp(a + 5, la, b + 5, lb);
    }
  }
}

// Batch per-cell merge decisions.  For each i:
//   existing_mask[i] == 0  -> no recorded cell, incoming WINs (1)
//   otherwise compare (col_version, value, site_id):
//     1 = WIN, 0 = LOSE, 2 = EQUAL_METADATA (only when merge_equal != 0).
// Values are concatenated tag-encoded blobs delimited by off[i]..off[i+1].
// Sites are 16-byte ids, concatenated.
void crdt_merge_batch(int64_t n, const uint8_t* existing_mask,
                      const int64_t* e_colver, const uint8_t* e_vals,
                      const int64_t* e_off, const uint8_t* e_sites,
                      const int64_t* i_colver, const uint8_t* i_vals,
                      const int64_t* i_off, const uint8_t* i_sites,
                      int32_t merge_equal, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    if (!existing_mask[i]) {
      out[i] = 1;
      continue;
    }
    if (i_colver[i] != e_colver[i]) {
      out[i] = i_colver[i] > e_colver[i] ? 1 : 0;
      continue;
    }
    int c = crdt_value_cmp(i_vals + i_off[i], i_off[i + 1] - i_off[i],
                           e_vals + e_off[i], e_off[i + 1] - e_off[i]);
    if (c != 0) {
      out[i] = c > 0 ? 1 : 0;
      continue;
    }
    int sc = std::memcmp(i_sites + 16 * i, e_sites + 16 * i, 16);
    if (sc > 0) {
      out[i] = 1;
    } else {
      out[i] = merge_equal ? 2 : 0;
    }
  }
}

// Reduce a run of incoming changes for the SAME cell to the single winner
// (merge is a join-semilattice, so pairwise max is order-free).  Indices
// idx[0..m) select rows from the batch arrays; returns the winning index.
int64_t crdt_fold_cell(const int64_t* idx, int64_t m, const int64_t* colver,
                       const uint8_t* vals, const int64_t* off,
                       const uint8_t* sites) {
  int64_t best = idx[0];
  for (int64_t k = 1; k < m; k++) {
    int64_t i = idx[k];
    bool win;
    if (colver[i] != colver[best]) {
      win = colver[i] > colver[best];
    } else {
      int c = crdt_value_cmp(vals + off[i], off[i + 1] - off[i],
                             vals + off[best], off[best + 1] - off[best]);
      if (c != 0) {
        win = c > 0;
      } else {
        win = std::memcmp(sites + 16 * i, sites + 16 * best, 16) > 0;
      }
    }
    if (win) best = i;
  }
  return best;
}

int crdt_core_version() { return 1; }

}  // extern "C"
