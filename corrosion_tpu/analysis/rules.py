"""The corrolint rule catalog, CT001–CT010.

Every rule is distilled from a bug this repo actually shipped and then
fixed (doc/lint.md carries the full incident write-ups):

- CT001 — ISSUE 7's GSPMD silent-wrong-values bug: raw u8 threefry
  draws diverge from single-device at shard-unaligned sizes;
  ``topology.aligned_u8_bits`` is the repo-wide rule, this enforces it.
- CT002 — host syncs inside jit-reachable code: a ``.item()`` three
  helpers down from a round loop stalls the pipelined dispatch (and on
  a real chip, the tunnel) — found via the jit-seeded call graph.
- CT003 — nondeterminism in the sim/campaign digest paths: replay
  identity (spec hashes, result digests) only holds when every
  stochastic stream derives from ``faults.derive_seed`` and wall-clock
  never feeds a digested value.
- CT004 — ISSUE 9's ``n_writers`` incident: a campaign meta key that
  shadows a real ``SimConfig`` field silently measured a 1-writer
  workload for a whole PR.  Shadowing keys must be declared in
  ``spec.FORWARDED_META_KEYS`` (whose runtime twin refuses them too).
- CT005 — ISSUE 7's sqlite-authorizer GIL-vs-db-mutex deadlock:
  blocking calls inside ``async def`` in the host tier.
- CT006 — broad ``except Exception`` that neither logs nor re-raises:
  the class that let every one of the above hide for a while.
- CT008 — ISSUE 13's backpressure incident class: an unbounded
  ``asyncio.Queue()``/``deque()`` in a host-tier serving path turns a
  flood (or one slow consumer) into unbounded memory instead of an
  explicit 429 / disconnect-with-reason policy.
- CT009 — ISSUE 15's gray-failure class: a bare ``await`` of an
  asyncio network primitive in ``agent/`` with no wait_for/timeout
  bound parks its task forever against a degraded-not-dead peer (the
  ``slow`` fault kind injects exactly that stall on purpose).
- CT010 — ISSUE 16's attribution-decay class: a ``jax.named_scope``
  string (or ``phase_scope`` key) in the sim tier that isn't in the
  sim/profile.py ``PHASES`` registry silently dumps its device time
  into the unattributed residual of the phase ledger.
- CT011 — ISSUE 19's second-pass class: a per-bit reduction loop over
  round-kernel state words (a reduction whose operand right-shifts the
  words by a ``range(32)`` loop variable) re-traverses the full array
  32 times — the exact counter anti-pattern the fused one-pass
  traversal (sim/fused.py) removed; only the oracle there may keep it.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CallGraph, ModuleIndex, _own_body_nodes
from .core import LintContext, Rule, SourceFile

#: the jitted/traced tier: RNG + kernel + sharding code
SIM_TIER = (
    "corrosion_tpu/sim/",
    "corrosion_tpu/topo/",
    "corrosion_tpu/parallel/",
)
#: digest paths: everything whose outputs feed replay digests / spec
#: hashes (the campaign layer serializes and hashes results)
DIGEST_TIER = SIM_TIER + ("corrosion_tpu/campaign/",)

#: the blessed draw site CT001 exempts — THE implementation of the
#: repo-wide aligned-u8 rule
ALIGNED_DRAW_FILE = "corrosion_tpu/sim/topology.py"
ALIGNED_DRAW_FUNC = "aligned_u8_bits"


def _host_tier(ctx: LintContext) -> List[SourceFile]:
    """Everything under corrosion_tpu/ that is NOT the jitted sim tier
    (agent, api, pubsub, pg, cli, utils, top-level modules...)."""
    return [
        f
        for f in ctx.files
        if not any(f.relpath.startswith(p) for p in SIM_TIER)
    ]


def _enclosing_funcs(tree: ast.AST) -> Dict[ast.AST, Optional[str]]:
    """node -> name of the innermost enclosing function (None at module
    level) — cheap parent tracking for per-function scoping."""
    out: Dict[ast.AST, Optional[str]] = {}

    def visit(node: ast.AST, fn: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out[child] = fn
                visit(child, child.name)
            else:
                out[child] = fn
                visit(child, fn)

    visit(tree, None)
    return out


class UnalignedU8Draw(Rule):
    """CT001: every ``jax.random.bits`` draw in the sim tier must route
    through ``topology.aligned_u8_bits`` — the u8 unpack of a raw draw
    silently produces different values than single-device when GSPMD
    partitions it on a non-word-aligned boundary (ISSUE 7)."""

    code = "CT001"
    name = "unaligned-u8-draw"
    incident = (
        "ISSUE 7: sharded fault-storm loss masks diverged bit-wise from "
        "single-device at shard-unaligned sizes"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in ctx.under(*SIM_TIER):
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            enclosing = _enclosing_funcs(sf.tree)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                if idx.canonical(node.func) != "jax.random.bits":
                    continue
                if (
                    sf.relpath == ALIGNED_DRAW_FILE
                    and enclosing.get(node) == ALIGNED_DRAW_FUNC
                ):
                    continue
                yield (
                    sf.relpath,
                    node.lineno,
                    "raw jax.random.bits draw outside "
                    "topology.aligned_u8_bits — u8 unpacks of raw draws "
                    "silently diverge from single-device at "
                    "shard-unaligned sizes (route the draw through "
                    "aligned_u8_bits)",
                )


#: canonical call names that force a device→host transfer / host sync
_HOST_SYNC_CALLS = {
    "jax.device_get",
    "jax.block_until_ready",
    "numpy.asarray",
    "numpy.array",
    "numpy.frombuffer",
}
#: zero-arg method calls that do the same on an array
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}


class HostSyncInKernel(Rule):
    """CT002: host-sync calls inside functions jit-reachable from the
    round loops, via a call graph seeded at jax.jit call sites."""

    code = "CT002"
    name = "host-sync-in-kernel"
    incident = (
        "class behind ISSUE 7's authorizer-adjacent stalls: one hidden "
        "host sync in a traced path serializes the whole dispatch"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        files = [f for f in ctx.under(*SIM_TIER) if f.tree is not None]
        graph = CallGraph(files)
        reachable = graph.reachable_from_jit()
        for key in sorted(reachable):
            info = graph.funcs.get(key)
            if info is None:
                continue
            idx = graph.indexes[info.module]
            for node in _own_body_nodes(info.node):
                if not isinstance(node, ast.Call):
                    continue
                dotted = idx.canonical(node.func)
                hit: Optional[str] = None
                if dotted in _HOST_SYNC_CALLS:
                    hit = dotted
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _HOST_SYNC_METHODS
                    and not node.args
                    and not node.keywords
                ):
                    hit = f".{node.func.attr}()"
                if hit:
                    yield (
                        info.sf.relpath,
                        node.lineno,
                        f"host sync {hit} inside jit-reachable "
                        f"{info.qualname} (reachable from the "
                        "jax.jit-seeded call graph) — host transfers "
                        "in traced code stall the dispatch pipeline",
                    )


#: canonical names that smuggle wall-clock / ambient randomness into
#: digest paths.  time.monotonic/perf_counter are ALLOWED: walls are
#: measured everywhere but digest-excluded by design (report.py).
_NONDET_CALLS = {
    "time.time",
    "time.time_ns",
    "os.urandom",
    "uuid.uuid4",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
_NONDET_PREFIXES = ("numpy.random.", "random.", "secrets.")


class NondeterminismInSimTier(Rule):
    """CT003: ambient randomness / wall-clock in sim+campaign digest
    paths — seeds must flow through ``faults.derive_seed`` and replay
    digests must be pure functions of the spec."""

    code = "CT003"
    name = "nondeterminism-in-sim-tier"
    incident = (
        "replay-identity contract (ISSUE 3): one ambient draw anywhere "
        "in a digest path and `identical_results` certification dies"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in ctx.under(*DIGEST_TIER):
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = idx.canonical(node.func)
                if dotted is None:
                    continue
                if dotted in _NONDET_CALLS or any(
                    dotted.startswith(p) for p in _NONDET_PREFIXES
                ):
                    yield (
                        sf.relpath,
                        node.lineno,
                        f"nondeterministic {dotted} in a sim/campaign "
                        "digest path — derive every stochastic stream "
                        "from the plan seed via faults.derive_seed "
                        "(wall measurement uses time.monotonic, which "
                        "is digest-excluded and allowed)",
                    )


def _tuple_strs(node: ast.AST) -> List[Tuple[str, int]]:
    """(value, lineno) for every string constant in a tuple/list/set
    literal (possibly wrapped in frozenset(...)/tuple(...))."""
    if isinstance(node, ast.Call) and node.args:
        node = node.args[0]
    out: List[Tuple[str, int]] = []
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                out.append((elt.value, elt.lineno))
    return out


SPEC_FILE = "corrosion_tpu/campaign/spec.py"
SIMCONFIG_FILE = "corrosion_tpu/sim/state.py"


def _module_assign(
    sf: SourceFile, name: str
) -> Optional[ast.AST]:
    for node in sf.tree.body:  # module level only
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return node.value
        elif isinstance(node, ast.AnnAssign):
            if (
                isinstance(node.target, ast.Name)
                and node.target.id == name
                and node.value is not None
            ):
                return node.value
    return None


def simconfig_fields(ctx: LintContext) -> Set[str]:
    """SimConfig's dataclass field names, read from the AST of
    sim/state.py (annotated assignments in the class body) — never by
    importing the jax-heavy module."""
    sf = ctx.get(SIMCONFIG_FILE)
    if sf is None or sf.tree is None:
        return set()
    for node in sf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "SimConfig":
            return {
                stmt.target.id
                for stmt in node.body
                if isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
            }
    return set()


class MetaKeyShadow(Rule):
    """CT004: campaign meta keys that collide with SimConfig dataclass
    fields must be declared in ``spec.FORWARDED_META_KEYS`` — the
    undeclared collision is exactly how ``n_writers`` silently measured
    a 1-writer workload for all of ISSUE 9's frontier campaign."""

    code = "CT004"
    name = "meta-key-shadow"
    incident = (
        "ISSUE 9 review round: the `n_writers` meta key shadowed the "
        "real SimConfig field and was stripped from sim cells — the "
        "frontier campaign measured the wrong workload"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        sf = ctx.get(SPEC_FILE)
        if sf is None or sf.tree is None:
            return
        fields = simconfig_fields(ctx)
        if not fields:
            return
        forwarded_node = _module_assign(sf, "FORWARDED_META_KEYS")
        forwarded = {
            v for v, _ in _tuple_strs(forwarded_node)
        } if forwarded_node is not None else set()
        for const_name in ("_SCENARIO_META_KEYS", "_TOPOLOGY_KEYS"):
            node = _module_assign(sf, const_name)
            if node is None:
                continue
            for key, line in _tuple_strs(node):
                if key in fields and key not in forwarded:
                    yield (
                        sf.relpath,
                        line,
                        f"meta key {key!r} in {const_name} shadows a "
                        "real SimConfig field but is not declared in "
                        "FORWARDED_META_KEYS — sim cells would "
                        "silently strip it (the ISSUE 9 n_writers "
                        "incident class)",
                    )


#: canonical names that block the event loop when awaited-around
_BLOCKING_CALLS = {
    "time.sleep",
    "sqlite3.connect",
    "socket.create_connection",
    "socket.getaddrinfo",
    "os.system",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "urllib.request.urlopen",
}
#: method names whose sync forms have bitten this repo inside async
#: code (the sqlite authorizer deadlock class)
_BLOCKING_METHODS = {"set_authorizer"}


class BlockingCallInAsync(Rule):
    """CT005: blocking calls lexically inside ``async def`` bodies in
    the host tier (nested sync ``def``s are excluded — they may be
    executor-bound; the rule is about code that runs ON the loop)."""

    code = "CT005"
    name = "blocking-call-in-async"
    incident = (
        "ISSUE 7 drive-by: a lingering sqlite authorizer deadlocked "
        "GIL-vs-db-mutex against the wal-checkpoint executor thread — "
        "a blocking call reachable from async code froze the tier-1 "
        "suite wholesale"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in _host_tier(ctx):
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            for fn in ast.walk(sf.tree):
                if not isinstance(fn, ast.AsyncFunctionDef):
                    continue
                for node in _own_body_nodes(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    dotted = idx.canonical(node.func)
                    hit = None
                    if dotted in _BLOCKING_CALLS:
                        hit = dotted
                    elif (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr in _BLOCKING_METHODS
                    ):
                        hit = f".{node.func.attr}(...)"
                    if hit:
                        yield (
                            sf.relpath,
                            node.lineno,
                            f"blocking {hit} inside async def "
                            f"{fn.name} — it stalls the event loop "
                            "(and sqlite hooks can deadlock "
                            "GIL-vs-db-mutex); await an async "
                            "equivalent or move it to an executor",
                        )


_LOG_METHODS = {
    "debug",
    "info",
    "warning",
    "error",
    "exception",
    "critical",
    "log",
    "print_exc",
}


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except
    names = []
    if isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    elif isinstance(t, ast.Name):
        names = [t.id]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_logs_or_raises(handler: ast.ExceptHandler) -> bool:
    """A handler is NOT a swallow when it re-raises, logs, or binds the
    exception (``as e``) and actually uses it — routing the error into
    a response body, a report record, or an error string is handling,
    just through a different channel than a logger."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr in _LOG_METHODS:
                return True
            if isinstance(fn, ast.Name) and fn.id in ("print",):
                return True
        if (
            handler.name
            and isinstance(node, ast.Name)
            and node.id == handler.name
        ):
            return True
    return False


class BroadExceptSwallow(Rule):
    """CT006: host-tier ``except Exception`` (or broader) that neither
    logs nor re-raises — the silent-swallow class that let real faults
    (lost frames, dead matchers, failed syncs) disappear without a
    trace until a tier-1 run hung."""

    code = "CT006"
    name = "broad-except-swallow"
    incident = (
        "repeated: silent handlers hid the transport sever races and "
        "sync failures behind ISSUE 7/8's flaky-suite hunts"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in _host_tier(ctx):
            if sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    if not _handler_is_broad(handler):
                        continue
                    if _handler_logs_or_raises(handler):
                        continue
                    yield (
                        sf.relpath,
                        handler.lineno,
                        "broad except swallows the error with neither "
                        "log nor re-raise — log it (exc_info/debug is "
                        "fine for best-effort cleanup) or let it "
                        "propagate",
                    )


#: the serving-path tier CT008 patrols: every queue between a client
#: and a commit/fan-out lives here (api ingress, agent broadcast/ingest,
#: pubsub fan-out).  The cli/pg/consul dirs are operator tooling, not
#: the flood path.
SERVING_TIER = (
    "corrosion_tpu/agent/",
    "corrosion_tpu/api/",
    "corrosion_tpu/pubsub/",
)


def _int_literal(node) -> Optional[int]:
    """The int value of a literal expression, unary minus included
    (``-1`` parses as UnaryOp(USub, Constant)); None for anything
    non-literal."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _int_literal(node.operand)
        return -inner if inner is not None else None
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    return None


class UnboundedQueueInHostTier(Rule):
    """CT008: ``asyncio.Queue()`` / ``collections.deque()`` constructed
    WITHOUT a bound in the host-tier serving paths.  ISSUE 13's incident
    class: a subscriber queue with no maxsize turns one slow consumer
    into unbounded server memory under a flood — the serving tier's
    rule is every queue carries a bound, and overflow is an EXPLICIT
    policy (429, disconnect-with-reason, counted drop-oldest), never
    silent growth.  A deliberately-elsewhere-bounded queue documents
    itself with a pragma naming the bound."""

    code = "CT008"
    name = "unbounded-queue-in-host-tier"
    incident = (
        "ISSUE 13: pre-backpressure, every per-subscriber fan-out queue "
        "and the write path queued unboundedly — 1000 writers of load "
        "became silent memory growth instead of 429s"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in ctx.under(*SERVING_TIER):
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            for node in ast.walk(sf.tree):
                if not isinstance(node, ast.Call):
                    continue
                dotted = idx.canonical(node.func)
                kws = {k.arg: k.value for k in node.keywords}
                if dotted == "asyncio.Queue":
                    bound = (
                        node.args[0] if node.args else kws.get("maxsize")
                    )
                    lit = _int_literal(bound)
                    if bound is None:
                        what = "asyncio.Queue() without maxsize"
                    elif lit is not None and lit <= 0:
                        # asyncio semantics: maxsize <= 0 IS infinite —
                        # the literal zero/negative spelling of the
                        # incident
                        what = f"asyncio.Queue({lit}) is unbounded"
                    else:
                        continue
                elif dotted == "collections.deque":
                    # (deque(maxlen=0) is bounded — it keeps nothing)
                    if len(node.args) >= 2 or "maxlen" in kws:
                        continue
                    what = "deque() without maxlen"
                else:
                    continue
                yield (
                    sf.relpath,
                    node.lineno,
                    f"{what} in a host-tier serving path — a flood "
                    "turns it into unbounded memory; bound it at "
                    "construction with an explicit overflow policy "
                    "(429 / disconnect-with-reason / counted drop), or "
                    "pragma-document where the bound actually lives",
                )


#: asyncio network primitives whose bare ``await`` can park a task
#: forever when the peer goes GRAY — alive at the TCP layer, never
#: sending another byte.  Connect/accept/read verbs only; the repo's
#: own wrappers (``BiStream.recv`` et al.) carry internal timeouts and
#: are deliberately not listed.
_NETWORK_AWAIT_CALLS = {
    "asyncio.open_connection",
    "asyncio.open_unix_connection",
}
_NETWORK_AWAIT_METHODS = {
    # StreamReader framed/line reads
    "readexactly",
    "readline",
    "readuntil",
    # raw loop.sock_* ops
    "sock_recv",
    "sock_recv_into",
    "sock_accept",
    "sock_connect",
    # datagram endpoints
    "recvfrom",
}
#: timeout context managers that bound every await in their body
_TIMEOUT_CTXES = ("asyncio.timeout", "asyncio.timeout_at")


class UnboundedNetworkAwait(Rule):
    """CT009: a bare ``await`` of an asyncio network primitive in the
    agent tier, with no ``asyncio.wait_for`` / ``asyncio.timeout``
    bound.  The gray-failure class ISSUE 15 injects on purpose: a peer
    that is degraded-not-dead keeps the TCP connection open and simply
    stops sending, so an unbounded read never errors and never returns
    — the awaiting task leaks for the process lifetime.  Detection is
    structural: a wait_for-wrapped op is never the *direct* operand of
    ``await`` (the wrapper is), so any direct await of a listed op is
    by definition unbounded unless an ``async with asyncio.timeout``
    ancestor bounds it lexically."""

    code = "CT009"
    name = "unbounded-network-await"
    incident = (
        "ISSUE 15: the `slow` gray-failure kind stalls live peers "
        "mid-stream; every unbounded network await becomes a leaked "
        "task that survives the fault and holds its stream slot"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in ctx.under("corrosion_tpu/agent/"):
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            for fn in ast.walk(sf.tree):
                if isinstance(fn, ast.AsyncFunctionDef):
                    yield from self._scan(sf, idx, fn)

    def _scan(
        self, sf: SourceFile, idx: ModuleIndex, fn: ast.AsyncFunctionDef
    ) -> Iterable[Tuple[str, int, str]]:
        def visit(node: ast.AST, guarded: bool):
            for child in ast.iter_child_nodes(node):
                if isinstance(
                    child,
                    (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
                ):
                    # separate scope: nested async defs are scanned on
                    # their own walk, unguarded — an enclosing timeout
                    # ctx bounds call SITES, not the def's body
                    continue
                g = guarded
                if isinstance(child, ast.AsyncWith) and any(
                    isinstance(item.context_expr, ast.Call)
                    and idx.canonical(item.context_expr.func)
                    in _TIMEOUT_CTXES
                    for item in child.items
                ):
                    g = True
                if (
                    isinstance(child, ast.Await)
                    and not g
                    and isinstance(child.value, ast.Call)
                ):
                    call = child.value
                    dotted = idx.canonical(call.func)
                    hit = None
                    if dotted in _NETWORK_AWAIT_CALLS:
                        hit = dotted
                    elif (
                        isinstance(call.func, ast.Attribute)
                        and call.func.attr in _NETWORK_AWAIT_METHODS
                    ):
                        hit = f".{call.func.attr}(...)"
                    if hit:
                        yield (
                            sf.relpath,
                            child.lineno,
                            f"unbounded await of {hit} in async def "
                            f"{fn.name} — a gray peer (alive, silent) "
                            "parks this task forever; wrap it in "
                            "asyncio.wait_for / asyncio.timeout, or "
                            "pragma-document why unbounded is the "
                            "design (e.g. a server read whose "
                            "liveness SWIM owns)",
                        )
                yield from visit(child, g)

        yield from visit(fn, False)


PROFILE_FILE = "corrosion_tpu/sim/profile.py"


def phase_registry(ctx: LintContext) -> Optional[Tuple[str, Set[str]]]:
    """(scope prefix, registered phase keys) read from the AST of
    sim/profile.py — never by importing it.  The registry dict and the
    prefix are pure literals by contract (profile.py documents that
    CT010 depends on it); None when the file or either literal is
    missing, in which case the rule stays silent rather than flagging
    the whole sim tier on a parse hiccup."""
    sf = ctx.get(PROFILE_FILE)
    if sf is None or sf.tree is None:
        return None
    phases = _module_assign(sf, "PHASES")
    prefix = _module_assign(sf, "_SCOPE_PREFIX")
    try:
        keys = set(ast.literal_eval(phases)) if phases is not None else None
        pre = ast.literal_eval(prefix) if prefix is not None else None
    except (ValueError, SyntaxError):
        return None
    if not keys or not isinstance(pre, str):
        return None
    return pre, keys


class UnregisteredPhaseScope(Rule):
    """CT010: every profiling annotation in the sim tier must use a
    registered phase.  The phase-attribution ledger (ISSUE 16,
    sim/profile.py) attributes device time to the scope strings the
    kernels emit; a ``jax.named_scope("...")`` string outside the
    ``PHASES`` registry — or a ``phase_scope("...")`` key that isn't
    registered — silently lands its ops in the unattributed residual
    until the PROFILE_BASELINE gate trips on a machine far from the
    edit.  profile.py itself is exempt (it implements the registry and
    composes the scope string dynamically)."""

    code = "CT010"
    name = "unregistered-phase-scope"
    incident = (
        "ISSUE 16: unregistered scope strings decay the cost ledger "
        "into the unattributed residual, failing the profile baseline "
        "one nightly later instead of at review time"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        reg = phase_registry(ctx)
        if reg is None:
            return
        prefix, keys = reg
        valid_scopes = {prefix + k for k in keys}
        for sf in ctx.under(*SIM_TIER):
            if sf.tree is None or sf.relpath == PROFILE_FILE:
                continue
            idx = ModuleIndex(sf)
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                arg = node.args[0]
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    continue
                dotted = idx.canonical(node.func) or ""
                if dotted == "jax.named_scope":
                    if arg.value not in valid_scopes:
                        yield (
                            sf.relpath,
                            node.lineno,
                            f"jax.named_scope({arg.value!r}) is not a "
                            "registered phase scope — its device time "
                            "lands in the unattributed residual; use "
                            f"phase_scope(<key>) with a key from "
                            "sim/profile.py PHASES (or register a new "
                            "phase there)",
                        )
                elif dotted.endswith("profile.phase_scope") or dotted.endswith(
                    "profile.scope_name"
                ):
                    if arg.value not in keys:
                        fn_name = dotted.rsplit(".", 1)[-1]
                        yield (
                            sf.relpath,
                            node.lineno,
                            f"{fn_name}({arg.value!r}): unregistered "
                            "phase key (registered: "
                            f"{', '.join(sorted(keys))}) — register it "
                            "in sim/profile.py PHASES so the ledger "
                            "and the baseline gate know the phase",
                        )


#: the fused one-pass traversal module (ISSUE 19) — the ONLY sanctioned
#: home for per-bit loop forms: it keeps them as the CORRO_FUSED_ROUND
#: legacy oracle that tests/sim/test_fused.py holds the fused forms to
FUSED_FILE = "corrosion_tpu/sim/fused.py"

_REDUCTION_CALLS = {"jax.numpy.sum", "numpy.sum"}


def _is_range32(iter_node: ast.AST) -> bool:
    """``range(32)`` as a literal call — the bit-lane unroll shape."""
    return (
        isinstance(iter_node, ast.Call)
        and isinstance(iter_node.func, ast.Name)
        and iter_node.func.id == "range"
        and len(iter_node.args) == 1
        and isinstance(iter_node.args[0], ast.Constant)
        and iter_node.args[0].value == 32
    )


def _range32_loops(
    tree: ast.AST,
) -> Iterable[Tuple[str, List[ast.AST]]]:
    """(loop variable name, body nodes to search) for every
    ``for ... in range(32)`` statement and comprehension generator."""
    for node in ast.walk(tree):
        if isinstance(node, ast.For):
            if _is_range32(node.iter) and isinstance(node.target, ast.Name):
                yield node.target.id, list(node.body)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            for gen in node.generators:
                if _is_range32(gen.iter) and isinstance(gen.target, ast.Name):
                    yield gen.target.id, [node.elt]


def _shifts_by(call: ast.Call, var: str) -> bool:
    """The call's operand right-shifts something by the loop variable
    (directly, ``w >> j``, or wrapped, ``w >> jnp.uint32(j)``)."""
    for sub in ast.walk(call):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.RShift):
            for leaf in ast.walk(sub.right):
                if isinstance(leaf, ast.Name) and leaf.id == var:
                    return True
    return False


class PerBitReductionLoop(Rule):
    """CT011: no per-bit reduction loops over round-kernel state words
    outside the fused traversal helpers.  A reduction whose operand
    right-shifts the u32 words by the loop variable of a ``range(32)``
    loop re-reads the full array once per bit — 32 memory passes where
    the one-pass bit-plane expansion in sim/fused.py does one (ISSUE
    19; the shape XLA fuses into a single traversal).  fused.py itself
    is exempt: it keeps the loop forms as the ``CORRO_FUSED_ROUND``
    legacy oracle the equality tests pin the fused forms against."""

    code = "CT011"
    name = "per-bit-reduction-loop"
    incident = (
        "ISSUE 19: telemetry counters re-walked the round's packed "
        "words as 32 shifted reductions each — a second full memory "
        "pass per round that held packed telemetry overhead at ~20%"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        for sf in ctx.under(*SIM_TIER):
            if sf.tree is None or sf.relpath == FUSED_FILE:
                continue
            idx = ModuleIndex(sf)
            for var, roots in _range32_loops(sf.tree):
                for root in roots:
                    for node in ast.walk(root):
                        if not isinstance(node, ast.Call):
                            continue
                        dotted = idx.canonical(node.func) or ""
                        is_sum = dotted in _REDUCTION_CALLS or (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr == "sum"
                        )
                        if not (is_sum and _shifts_by(node, var)):
                            continue
                        yield (
                            sf.relpath,
                            node.lineno,
                            f"per-bit reduction in a range(32) loop "
                            f"(sum over words >> {var}) re-traverses "
                            "the full state array once per bit — 32 "
                            "memory passes; use the one-pass helpers "
                            "in sim/fused.py (word_bit_counts / "
                            "word_byte_totals / word_send_stats) or "
                            "add a SWAR/byte-LUT helper there — only "
                            "fused.py may keep the legacy oracle form",
                        )


RULES = [
    UnalignedU8Draw,
    HostSyncInKernel,
    NondeterminismInSimTier,
    MetaKeyShadow,
    BlockingCallInAsync,
    BroadExceptSwallow,
    UnboundedQueueInHostTier,
    UnboundedNetworkAwait,
    UnregisteredPhaseScope,
    PerBitReductionLoop,
]
