"""Lightweight call graph + name canonicalization for the lint rules.

Two jobs, both pure-AST (nothing here imports the code it models):

- :class:`ModuleIndex` canonicalizes dotted names through a module's
  import table — ``np.asarray`` resolves to ``numpy.asarray``,
  ``jrandom.bits`` (via ``from jax import random as jrandom``) to
  ``jax.random.bits`` — so every rule matches *canonical* names and
  aliasing can't dodge a rule;
- :class:`CallGraph` builds a module-level call graph over a file set
  and BFSes reachability from **jit seeds** (functions decorated with
  ``jax.jit`` in any spelling this repo uses: ``@jax.jit``,
  ``@functools.partial(jax.jit, ...)``, ``@partial(jax.jit, ...)``).
  CT002 walks the reachable set for host-sync calls: a ``.item()``
  three helpers down from ``run_to_convergence`` is exactly the
  deadlock/perf class a grep can't see.

Deliberate approximations (documented in doc/lint.md): resolution is
by module-level name and import table — method calls (``self.f()``)
and dynamically-built callables don't resolve; function *references*
passed as arguments (``jax.lax.fori_loop(0, R, body, ...)``,
``jax.vmap(fn)``) do create edges, which is what the round loops'
body-function style needs.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import SourceFile


def module_name(relpath: str) -> str:
    """repo-relative path → dotted module name
    (``corrosion_tpu/sim/round.py`` → ``corrosion_tpu.sim.round``)."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class ModuleIndex:
    """Import table + canonical dotted-name resolution for one module."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.module = module_name(sf.relpath)
        # alias (as bound in this module) -> canonical dotted prefix
        self.aliases: Dict[str, str] = {}
        if sf.tree is None:
            return
        # the containing package for relative-import resolution: a
        # package __init__ IS its own package (module_name strips the
        # ".__init__" suffix, so splitting off the last part would
        # resolve `from .x import y` one level too high and silently
        # drop call-graph edges)
        if sf.relpath.endswith("/__init__.py"):
            pkg_parts = self.module.split(".")
        else:
            pkg_parts = self.module.split(".")[:-1]
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    prefix = ".".join(base + ([node.module] if node.module else []))
                else:
                    prefix = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = (
                        f"{prefix}.{a.name}" if prefix else a.name
                    )

    def canonical(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of a Name/Attribute chain, resolving
        the root through the import table; None when the root isn't an
        imported name (locals, attributes of self, subscripts...)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if not isinstance(node, ast.Name):
            return None
        root = self.aliases.get(node.id)
        if root is None:
            return None
        return ".".join([root] + list(reversed(parts)))


@dataclass
class FuncInfo:
    module: str
    qualname: str  # e.g. "run_packed.k_rounds_fn" for nested defs
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    sf: SourceFile
    parent: Optional[str] = None  # enclosing function qualname
    is_jit_seed: bool = False
    calls: Set[Tuple[str, str]] = field(default_factory=set)  # resolved edges

    @property
    def key(self) -> Tuple[str, str]:
        return (self.module, self.qualname)


def _jit_seed(dec: ast.AST, idx: ModuleIndex) -> bool:
    """True when a decorator expression references jax.jit anywhere —
    covers ``@jax.jit``, ``@partial(jax.jit, ...)``,
    ``@functools.partial(jax.jit, static_argnames=...)``."""
    for node in ast.walk(dec):
        if isinstance(node, (ast.Name, ast.Attribute)):
            if idx.canonical(node) == "jax.jit":
                return True
    return False


def _own_body_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's AST *excluding* nested function/lambda bodies
    (those are separate graph nodes with their own edges)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        stack.extend(ast.iter_child_nodes(node))


class CallGraph:
    """Module-level call graph over a file set (see module docstring)."""

    def __init__(self, files: Sequence[SourceFile]):
        self.indexes: Dict[str, ModuleIndex] = {}
        self.funcs: Dict[Tuple[str, str], FuncInfo] = {}
        # (module, parent qualname or None) -> {bare name -> key}
        self.scopes: Dict[Tuple[str, Optional[str]], Dict[str, Tuple[str, str]]] = {}
        for sf in files:
            if sf.tree is None:
                continue
            idx = ModuleIndex(sf)
            self.indexes[idx.module] = idx
            self._collect(sf, idx)
        for info in self.funcs.values():
            self._extract_edges(info)

    # -- construction ----------------------------------------------------

    def _collect(self, sf: SourceFile, idx: ModuleIndex) -> None:
        def visit(node: ast.AST, parent_qual: Optional[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = (
                        f"{parent_qual}.{child.name}"
                        if parent_qual
                        else child.name
                    )
                    info = FuncInfo(
                        module=idx.module,
                        qualname=qual,
                        node=child,
                        sf=sf,
                        parent=parent_qual,
                        is_jit_seed=any(
                            _jit_seed(d, idx) for d in child.decorator_list
                        ),
                    )
                    self.funcs[info.key] = info
                    self.scopes.setdefault(
                        (idx.module, parent_qual), {}
                    )[child.name] = info.key
                    visit(child, qual)
                elif isinstance(child, ast.ClassDef):
                    qual = (
                        f"{parent_qual}.{child.name}"
                        if parent_qual
                        else child.name
                    )
                    visit(child, qual)
                else:
                    visit(child, parent_qual)

        visit(sf.tree, None)

    def _resolve(
        self, info: FuncInfo, node: ast.AST
    ) -> Optional[Tuple[str, str]]:
        """Resolve a Name/Attribute reference to a known function key:
        enclosing-scope nested defs first, then module level, then the
        import table (cross-module)."""
        if isinstance(node, ast.Name):
            scope: Optional[str] = info.qualname
            while True:
                local = self.scopes.get((info.module, scope), {})
                if node.id in local:
                    return local[node.id]
                if scope is None:
                    break
                scope = (
                    scope.rsplit(".", 1)[0] if "." in scope else None
                )
        idx = self.indexes.get(info.module)
        if idx is None:
            return None
        dotted = idx.canonical(node)
        if dotted and "." in dotted:
            mod, attr = dotted.rsplit(".", 1)
            if (mod, attr) in self.funcs:
                return (mod, attr)
        return None

    def _extract_edges(self, info: FuncInfo) -> None:
        for node in _own_body_nodes(info.node):
            if not isinstance(node, ast.Call):
                continue
            target = self._resolve(info, node.func)
            if target is not None:
                info.calls.add(target)
            # function REFERENCES passed as arguments (fori_loop body,
            # vmap(fn), cond branches) are edges too
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    ref = self._resolve(info, arg)
                    if ref is not None:
                        info.calls.add(ref)

    # -- queries ---------------------------------------------------------

    def seeds(self) -> List[FuncInfo]:
        return [f for f in self.funcs.values() if f.is_jit_seed]

    def reachable_from_jit(self) -> Set[Tuple[str, str]]:
        """Function keys reachable from any jit seed (seeds included).
        A nested def inside a seed is reachable by construction — its
        body only exists inside the traced program."""
        out: Set[Tuple[str, str]] = set()
        stack = [f.key for f in self.seeds()]
        # nested functions of a seed are part of its traced body even
        # when only referenced implicitly (closures)
        while stack:
            key = stack.pop()
            if key in out:
                continue
            out.add(key)
            info = self.funcs.get(key)
            if info is None:
                continue
            stack.extend(info.calls)
            for (mod, parent), names in self.scopes.items():
                if mod == info.module and parent == info.qualname:
                    stack.extend(names.values())
        return out
