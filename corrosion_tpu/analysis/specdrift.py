"""CT007: spec-hash drift over the committed campaign baselines.

The committed band artifacts under ``doc/experiments/`` are the CI
gates' teeth — ``sim campaign compare`` holds every nightly candidate
against them by band, but nothing re-checked that the *spec* a
baseline embeds still hashes to the ``spec_hash`` it claims, or that
the builtin spec of the same name still produces that hash.  Either
drift silently un-anchors the gate:

- **serialization drift**: an edit to ``campaign/spec.py``'s
  ``to_dict``/``from_dict`` (a new default-serialized field, a type
  change) moves every spec hash — candidates stop matching baselines
  for reasons that have nothing to do with bands;
- **builtin drift**: an edit to a builtin spec (seeds, grid, scenario
  knobs) without regenerating its committed baseline leaves CI
  comparing apples to last month's oranges.

This check recomputes both, jax-free (``campaign.spec`` imports
lazily by design).  A deliberate spec change is legal — regenerate the
baseline in the same PR, as doc/campaigns.md already instructs.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, Tuple

from .core import LintContext, Rule

BASELINE_GLOB = os.path.join("doc", "experiments", "CAMPAIGN_BASELINE_*.json")


class SpecHashDrift(Rule):
    code = "CT007"
    name = "spec-hash-drift"
    incident = (
        "preventive (ISSUE 10): the n_writers fix in ISSUE 9 moved a "
        "baseline's workload shape — a drifted spec hash is how such a "
        "change would ship unnoticed"
    )

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        from ..campaign.spec import BUILTIN_SPECS, CampaignSpec, builtin_spec

        paths = sorted(glob.glob(os.path.join(ctx.root, BASELINE_GLOB)))
        for path in paths:
            rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as f:
                    art = json.load(f)
            except (OSError, ValueError) as e:
                yield rel, 1, f"unreadable campaign baseline: {e}"
                continue
            embedded = art.get("spec")
            claimed = art.get("spec_hash")
            if not embedded or not claimed:
                yield (
                    rel,
                    1,
                    "campaign baseline lacks spec/spec_hash — not a "
                    "replayable gate artifact",
                )
                continue
            try:
                spec = CampaignSpec.from_dict(embedded)
            except Exception as e:  # noqa: BLE001 — the yielded finding IS the report
                yield (
                    rel,
                    1,
                    f"embedded spec no longer rebuilds under the "
                    f"current campaign/spec.py: {e}",
                )
                continue
            recomputed = spec.spec_hash()
            if recomputed != claimed:
                yield (
                    rel,
                    1,
                    f"spec-hash drift: baseline claims {claimed} but "
                    f"the current campaign/spec.py serializes its "
                    f"embedded spec to {recomputed} — regenerate the "
                    "baseline in the same PR as the spec change",
                )
                continue
            name = spec.name
            if name in BUILTIN_SPECS:
                rebuilt = builtin_spec(name, seeds=spec.seeds)
                if rebuilt.spec_hash() != claimed:
                    yield (
                        rel,
                        1,
                        f"builtin drift: builtin spec {name!r} now "
                        f"hashes to {rebuilt.spec_hash()} but the "
                        f"committed baseline pins {claimed} — the "
                        "builtin changed without regenerating its "
                        "baseline",
                    )
