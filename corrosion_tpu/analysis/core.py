"""corrolint framework: files, pragmas, findings, baseline, runner.

Design constraints (ISSUE 10):

- **jax-free**: the linter parses source with :mod:`ast`; it never
  imports the modules it checks (CT004 reads ``SimConfig``'s fields
  out of the AST of ``sim/state.py``, not the dataclass), so a lint
  run costs seconds on a jax-less box and CI can gate on it without
  an accelerator install step.
- **pragmas**: ``# corrolint: disable=CT001`` on a finding's line (or
  the line above, for multi-line statements) suppresses it.  Pragmas
  are for *justified* exceptions — the comment next to one should say
  why, the way ``# noqa`` is used in this repo.
- **baseline**: accepted legacy findings live in a committed JSON file
  (:data:`BASELINE_NAME` at the repo root).  A finding's identity is
  content-stable — a blake2b fold over (rule, path, stripped source
  line, occurrence index), never the line *number* — so unrelated
  edits don't invalidate the baseline, while editing a flagged line
  re-surfaces it for a fresh triage.
- **determinism**: findings sort by (path, line, rule); the baseline
  serializes sorted with a trailing newline; two ``--baseline-write``
  runs over the same tree produce byte-identical files.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: the committed baseline's repo-root filename (kept alongside
#: BASELINE.json / BENCH_*.json — repo-level contract artifacts)
BASELINE_NAME = "LINT_BASELINE.json"

#: directories under corrosion_tpu/ the file walk skips
_SKIP_DIRS = {"__pycache__"}

_PRAGMA_RE = re.compile(r"#\s*corrolint:\s*disable=([A-Z0-9*,\s]+)")


def _fingerprint(rule: str, path: str, text: str, occurrence: int) -> str:
    payload = json.dumps(
        [rule, path, text, occurrence], separators=(",", ":")
    )
    return hashlib.blake2b(payload.encode(), digest_size=8).hexdigest()


@dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to a repo-relative ``path:line``."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    fingerprint: str = ""

    def ref(self) -> str:
        """The clickable ``file:line`` reference the text output prints."""
        return f"{self.path}:{self.line}"


class SourceFile:
    """One parsed source file: text, AST, and per-line pragma codes."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(self.source, filename=self.relpath)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.parse_error = f"syntax error: {e.msg} (line {e.lineno})"
        # line number -> set of disabled rule codes ("*" = all)
        self.pragmas: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _PRAGMA_RE.search(text)
            if m:
                codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
                self.pragmas[i] = codes

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def suppressed(self, line: int, rule: str) -> bool:
        """A pragma suppresses a finding when it sits on the finding's
        line, or anywhere in the contiguous run of comment-only lines
        directly above it — the natural home of the *justification* a
        pragma is supposed to carry (doc/lint.md)."""
        codes = self.pragmas.get(line)
        if codes and (rule in codes or "*" in codes):
            return True
        ln = line - 1
        while 1 <= ln <= len(self.lines) and self.lines[
            ln - 1
        ].strip().startswith("#"):
            codes = self.pragmas.get(ln)
            if codes and (rule in codes or "*" in codes):
                return True
            ln -= 1
        return False


class LintContext:
    """The parsed repo a lint run sees: every ``corrosion_tpu/**/*.py``
    plus the repo root (for the committed campaign baselines CT007
    reads).  Rules receive this and yield findings."""

    def __init__(self, root: str, files: Sequence[SourceFile]):
        self.root = root
        self.files = list(files)
        self.by_path = {f.relpath: f for f in self.files}

    def under(self, *prefixes: str) -> List[SourceFile]:
        """Files whose repo-relative path starts with any prefix."""
        return [
            f
            for f in self.files
            if any(f.relpath.startswith(p) for p in prefixes)
        ]

    def get(self, relpath: str) -> Optional[SourceFile]:
        return self.by_path.get(relpath)


def collect_files(root: str, package: str = "corrosion_tpu") -> List[SourceFile]:
    """Walk ``root/package`` for .py files, sorted for determinism."""
    out: List[SourceFile] = []
    base = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d not in _SKIP_DIRS)
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), root)
            out.append(SourceFile(root, rel))
    return out


# -- rule registry -----------------------------------------------------------


class Rule:
    """One lint rule.  Subclasses set ``code``/``name``/``incident`` and
    implement :meth:`run` yielding ``(path, line, message)`` triples —
    the runner owns pragma filtering, fingerprints, and sorting."""

    code: str = "CT000"
    name: str = ""
    #: the shipped incident that motivates the rule (doc/lint.md)
    incident: str = ""

    def run(self, ctx: LintContext) -> Iterable[Tuple[str, int, str]]:
        raise NotImplementedError


def all_rules() -> List[Rule]:
    """The registered rule set, in code order (import-light: rules and
    specdrift import nothing heavier than ast/json)."""
    from .rules import RULES
    from .specdrift import SpecHashDrift

    return sorted(
        [cls() for cls in RULES] + [SpecHashDrift()],
        key=lambda r: r.code,
    )


# -- runner ------------------------------------------------------------------


@dataclass
class LintResult:
    findings: List[Finding] = field(default_factory=list)  # not baselined
    baselined: List[Finding] = field(default_factory=list)
    suppressed: int = 0  # pragma-disabled count
    checked_files: int = 0
    rules: List[str] = field(default_factory=list)

    @property
    def clean(self) -> bool:
        return not self.findings


def _finalize(
    ctx: LintContext, raw: List[Tuple[str, str, int, str]]
) -> List[Finding]:
    """Attach content-stable fingerprints: the occurrence index
    disambiguates identical (rule, path, line-text) triples in line
    order, so two textually identical findings in one file keep
    distinct, stable identities."""
    raw = sorted(raw, key=lambda r: (r[1], r[2], r[0]))
    seen: Dict[Tuple[str, str, str], int] = {}
    out: List[Finding] = []
    for rule, path, line, message in raw:
        sf = ctx.get(path)
        text = sf.line_text(line) if sf else ""
        key = (rule, path, text)
        k = seen.get(key, 0)
        seen[key] = k + 1
        out.append(
            Finding(
                rule=rule,
                path=path,
                line=line,
                message=message,
                fingerprint=_fingerprint(rule, path, text, k),
            )
        )
    return out


def run_lint(
    root: str,
    rules: Optional[Sequence[Rule]] = None,
    baseline: Optional[Dict[str, dict]] = None,
) -> LintResult:
    """Lint the repo at ``root``.  ``baseline`` maps fingerprint →
    accepted-finding record (see :func:`load_baseline`); matched
    findings are reported separately and don't fail the run."""
    rules = list(rules) if rules is not None else all_rules()
    ctx = LintContext(root, collect_files(root))
    result = LintResult(
        checked_files=len(ctx.files), rules=[r.code for r in rules]
    )
    raw: List[Tuple[str, str, int, str]] = []
    for f in ctx.files:
        if f.parse_error:
            raw.append(("CT000", f.relpath, 1, f.parse_error))
    for rule in rules:
        for path, line, message in rule.run(ctx):
            sf = ctx.get(path)
            if sf is not None and sf.suppressed(line, rule.code):
                result.suppressed += 1
                continue
            raw.append((rule.code, path, line, message))
    baseline = baseline or {}
    for finding in _finalize(ctx, raw):
        if finding.fingerprint in baseline:
            result.baselined.append(finding)
        else:
            result.findings.append(finding)
    return result


# -- baseline ----------------------------------------------------------------


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint → record.  A missing file is an empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {rec["fingerprint"]: rec for rec in data.get("findings", [])}


def write_baseline(path: str, result: LintResult) -> None:
    """Regenerate the baseline from a run's findings (new + already
    baselined), deterministically: sorted by (path, line, rule), line
    numbers included for humans but excluded from identity."""
    records = sorted(
        (
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "line": f.line,
                "message": f.message,
            }
            for f in result.findings + result.baselined
        ),
        key=lambda r: (r["path"], r["line"], r["rule"], r["fingerprint"]),
    )
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": records}, f, indent=2, sort_keys=True)
        f.write("\n")


# -- rendering ---------------------------------------------------------------


def render_text(result: LintResult, verbose: bool = False) -> str:
    lines = []
    for f in result.findings:
        lines.append(f"{f.ref()}: {f.rule} {f.message}")
    if verbose:
        for f in result.baselined:
            lines.append(f"{f.ref()}: {f.rule} [baselined] {f.message}")
    lines.append(
        f"corrolint: {len(result.findings)} finding(s), "
        f"{len(result.baselined)} baselined, "
        f"{result.suppressed} pragma-disabled "
        f"({result.checked_files} files, rules {', '.join(result.rules)})"
    )
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    def rec(f: Finding) -> dict:
        return {
            "rule": f.rule,
            "path": f.path,
            "line": f.line,
            "message": f.message,
            "fingerprint": f.fingerprint,
        }

    return json.dumps(
        {
            "findings": [rec(f) for f in result.findings],
            "baselined": [rec(f) for f in result.baselined],
            "suppressed": result.suppressed,
            "checked_files": result.checked_files,
            "rules": result.rules,
            "clean": result.clean,
        },
        indent=2,
        sort_keys=True,
    )
