"""corrolint: repo-invariant static analysis (ISSUE 10).

Every hard bug this repo has shipped and then fixed belongs to a
mechanically detectable class — the GSPMD shard-unaligned u8 draw
(ISSUE 7), the ``n_writers`` meta-key shadow (ISSUE 9 review round),
the sqlite-authorizer GIL-vs-db-mutex deadlock (ISSUE 7 drive-by).
This package encodes those classes as AST rules over the repo's own
source, so the determinism / shard-alignment / async-discipline
invariants the docs describe are *enforced*, not folklore:

- :mod:`.core` — the framework: ``Finding``, the rule registry,
  ``# corrolint: disable=CTxxx`` pragmas, the committed baseline
  (accepted legacy findings), text + JSON rendering;
- :mod:`.callgraph` — a lightweight module-level call graph over the
  sim tier, seeded from ``jax.jit`` / ``functools.partial(jax.jit)``
  call sites (CT002's jit-reachability);
- :mod:`.rules` — the rule catalog, CT001–CT006 (doc/lint.md grounds
  each in its originating incident);
- :mod:`.specdrift` — CT007: recompute every committed campaign
  baseline's spec hash against the current ``campaign/spec.py``.

The whole package is importable **jax-free** (``campaign.spec`` already
guarantees this for CT007's imports) and lints the repo in seconds:
``sim lint`` / ``python -m corrosion_tpu.analysis`` are cheap enough
for CI and pre-commit alike.
"""

from .core import (  # noqa: F401
    BASELINE_NAME,
    Finding,
    LintContext,
    LintResult,
    load_baseline,
    render_json,
    render_text,
    run_lint,
    write_baseline,
)
