"""``python -m corrosion_tpu.analysis`` — the corrolint CLI.

The same implementation backs ``sim lint`` (cli/main.py); both are
jax-free and exit:

- **0** — no findings outside the committed baseline;
- **1** — at least one non-baselined finding (the CI gate's red);
- **2** — usage error (unknown flag, unreadable baseline path...).
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from .core import (
    BASELINE_NAME,
    load_baseline,
    render_json,
    render_text,
    run_lint,
    write_baseline,
)


def default_root() -> str:
    """The repo root this package sits in (…/corrosion_tpu/analysis →
    two levels up)."""
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corrolint",
        description=(
            "repo-invariant static analysis: determinism, "
            "shard-alignment, async discipline (doc/lint.md)"
        ),
    )
    p.add_argument(
        "--root",
        default=None,
        help="repo root to lint (default: the checkout this package is in)",
    )
    p.add_argument(
        "--format",
        choices=["text", "json"],
        default="text",
        help="output format (json is what CI archives)",
    )
    p.add_argument(
        "--baseline",
        default=None,
        help=f"baseline file (default: <root>/{BASELINE_NAME})",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the committed baseline (report everything)",
    )
    p.add_argument(
        "--baseline-write",
        action="store_true",
        help="regenerate the baseline from this run's findings "
        "(deterministic: sorted, content-stable fingerprints) and exit 0",
    )
    p.add_argument(
        "--verbose",
        action="store_true",
        help="text output also lists baselined findings",
    )
    return p


def lint_main(argv: Optional[List[str]] = None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors and 0 on --help; keep both
        return int(e.code or 0)
    root = args.root or default_root()
    if not os.path.isdir(os.path.join(root, "corrosion_tpu")):
        print(
            f"error: {root!r} does not contain a corrosion_tpu/ package",
            file=sys.stderr,
        )
        return 2
    baseline_path = args.baseline or os.path.join(root, BASELINE_NAME)
    if args.baseline and not args.baseline_write and not os.path.exists(
        args.baseline
    ):
        print(
            f"error: baseline {args.baseline!r} does not exist "
            "(use --baseline-write to create one)",
            file=sys.stderr,
        )
        return 2
    if args.no_baseline or args.baseline_write:
        baseline = {}
    else:
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, OSError, KeyError, TypeError, AttributeError) as e:
            # a truncated / merge-conflicted baseline is a USAGE error
            # (exit 2), not a findings red — triagers must see the
            # corrupt file, not a fake CI gate failure
            print(
                f"error: unreadable baseline {baseline_path!r}: {e}",
                file=sys.stderr,
            )
            return 2
    result = run_lint(root, baseline=baseline)
    if args.baseline_write:
        write_baseline(baseline_path, result)
        print(
            f"wrote {baseline_path}: "
            f"{len(result.findings) + len(result.baselined)} accepted "
            "finding(s)"
        )
        return 0
    if args.format == "json":
        print(render_json(result))
    else:
        print(render_text(result, verbose=args.verbose))
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(lint_main())
