"""OTLP/HTTP span exporter — the tracing ring finally leaves the process.

The reference builds a full OpenTelemetry OTLP batch pipeline with
service metadata at agent boot (corrosion/src/main.rs:57-150, enabled by
the [telemetry] config, command/agent.rs:132-188).  This is the
tpu-rebuild equivalent with zero external dependencies: spans recorded
by `corrosion_tpu.tracing.TRACER` are batched on a daemon thread and
POSTed as OTLP/HTTP **JSON** (the protobuf-free encoding every OTLP
collector accepts on :4318/v1/traces).

Design notes:
- a THREAD, not an asyncio task: `Tracer.record` fires synchronously
  from whatever thread finishes a span (event loop, executor workers,
  CLI), so the handoff must be a thread-safe queue and the network I/O
  must never touch the event loop;
- batch flush at ``batch_size`` spans or ``flush_interval_s``, whichever
  first (the reference's batch exporter shape);
- export failures are counted and logged once per streak, never raised —
  telemetry must not take the agent down;
- bounded queue: if the collector stalls, spans drop oldest-first
  (matching the ring-buffer semantics of the in-process collector).
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import urllib.request
from typing import Dict, List, Optional

from .tracing import Span, TRACER, Tracer

log = logging.getLogger("corrosion_tpu.otlp")


def _attr(key: str, value) -> dict:
    if isinstance(value, bool):
        v = {"boolValue": value}
    elif isinstance(value, int):
        v = {"intValue": str(value)}
    elif isinstance(value, float):
        v = {"doubleValue": value}
    else:
        v = {"stringValue": str(value)}
    return {"key": key, "value": v}


def span_to_otlp(s: Span) -> dict:
    """One tracing.Span → an OTLP JSON span object."""
    end_s = s.end_s if s.end_s is not None else s.start_s
    out = {
        "traceId": f"{s.context.trace_id:032x}",
        "spanId": f"{s.context.span_id:016x}",
        "name": s.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(int(s.start_s * 1e9)),
        "endTimeUnixNano": str(int(end_s * 1e9)),
        "attributes": [_attr(k, v) for k, v in s.attributes.items()],
        "status": (
            {"code": 1}
            if s.status == "ok"
            else {"code": 2, "message": s.status}
        ),
    }
    if s.parent_span_id:
        out["parentSpanId"] = f"{s.parent_span_id:016x}"
    return out


class OtlpHttpExporter:
    """Batching OTLP/HTTP JSON exporter; wire with ``install()``."""

    def __init__(
        self,
        endpoint: str,
        service_name: str = "corrosion-tpu",
        headers: Optional[Dict[str, str]] = None,
        batch_size: int = 64,
        flush_interval_s: float = 2.0,
        queue_cap: int = 8192,
        resource_attributes: Optional[Dict[str, object]] = None,
    ):
        # accept both a collector base URL and a full path
        ep = endpoint.rstrip("/")
        self.url = ep if ep.endswith("/v1/traces") else ep + "/v1/traces"
        self.headers = {"content-type": "application/json", **(headers or {})}
        self.batch_size = batch_size
        self.flush_interval_s = flush_interval_s
        self._q: "queue.Queue[Optional[Span]]" = queue.Queue(maxsize=queue_cap)
        self._resource = [
            _attr("service.name", service_name),
            *(_attr(k, v) for k, v in (resource_attributes or {}).items()),
        ]
        self.exported = 0
        self.dropped = 0
        self.failures = 0
        self._fail_streak = 0
        self._thread: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # -- producer side (called from Tracer.record, any thread) -----------

    def export(self, s: Span) -> None:
        try:
            self._q.put_nowait(s)
        except queue.Full:
            try:  # drop oldest, keep newest (ring semantics)
                self._q.get_nowait()
                self._q.put_nowait(s)
            except (queue.Empty, queue.Full):
                pass
            self.dropped += 1

    # -- lifecycle --------------------------------------------------------

    def install(self, tracer: Tracer = TRACER) -> "OtlpHttpExporter":
        self._thread = threading.Thread(
            target=self._run, name="otlp-exporter", daemon=True
        )
        self._thread.start()
        # add, don't set: several agents in one process (devcluster,
        # tests) may each install an exporter on the shared TRACER
        tracer.add_exporter(self.export)
        return self

    def shutdown(self, tracer: Optional[Tracer] = None, timeout: float = 15.0):
        """Stop accepting spans, flush what's queued (one bounded final
        post), join the thread.  Removes only OUR exporter hook, so other
        agents' telemetry in the same process keeps flowing."""
        if tracer is not None:
            tracer.remove_exporter(self.export)
        self._stopped.set()
        try:  # wake the batcher; the Event alone breaks a stalled backlog
            self._q.put_nowait(None)
        except queue.Full:
            pass
        if self._thread is not None:
            self._thread.join(timeout)

    # -- batcher thread ----------------------------------------------------

    def _run(self) -> None:
        import time as _time

        batch: List[Span] = []
        next_flush = _time.monotonic() + self.flush_interval_s
        while not self._stopped.is_set():
            wait = max(0.05, next_flush - _time.monotonic())
            try:
                item = self._q.get(timeout=wait)
            except queue.Empty:
                item = None
            if item is not None:
                batch.append(item)
            # flush on size OR deadline — a steady trickle must not sit
            # buffered until batch_size accumulates
            now = _time.monotonic()
            if batch and (len(batch) >= self.batch_size or now >= next_flush):
                self._post(batch)
                batch = []
                next_flush = now + self.flush_interval_s
            elif not batch:
                next_flush = now + self.flush_interval_s
        # shutdown: drain whatever is queued into ONE bounded final post —
        # never chew through a dead-collector backlog batch by batch
        while True:
            try:
                s = self._q.get_nowait()
            except queue.Empty:
                break
            if s is not None:
                batch.append(s)
        if batch:
            self._post(batch)

    def _post(self, batch: List[Span]) -> None:
        body = json.dumps(
            {
                "resourceSpans": [
                    {
                        "resource": {"attributes": self._resource},
                        "scopeSpans": [
                            {
                                "scope": {"name": "corrosion_tpu"},
                                "spans": [span_to_otlp(s) for s in batch],
                            }
                        ],
                    }
                ]
            }
        ).encode()
        req = urllib.request.Request(self.url, body, self.headers)
        try:
            with urllib.request.urlopen(req, timeout=10):
                pass
            self.exported += len(batch)
            if self._fail_streak:
                log.info("otlp export recovered after %d failures", self._fail_streak)
                self._fail_streak = 0
        except Exception as exc:
            self.failures += 1
            self._fail_streak += 1
            if self._fail_streak == 1:  # log once per streak, not per batch
                log.warning("otlp export to %s failed: %s", self.url, exc)


def exporter_from_config(cfg) -> Optional[OtlpHttpExporter]:
    """Build (but do not install) the exporter from Config.otlp_endpoint
    (the [telemetry] section; None when telemetry is off)."""
    endpoint = getattr(cfg, "otlp_endpoint", "")
    if not endpoint:
        return None
    return OtlpHttpExporter(
        endpoint,
        service_name=getattr(cfg, "otlp_service_name", "") or "corrosion-tpu",
    )
