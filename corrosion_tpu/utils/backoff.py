"""Decorrelated-jitter exponential backoff.

Rebuild of the reference's `backoff` crate (`crates/backoff/src/lib.rs:7-90`),
used by the sync cadence, announcer, and client reconnect loops."""

from __future__ import annotations

import random
import time
from typing import Optional


class Backoff:
    """Iterator of sleep durations: decorrelated jitter between min and max.

    next = min(max_s, uniform(min_s, prev * 3)), starting at min_s.

    ``max_retries`` caps the number of draws: once spent, ``__next__``
    raises StopIteration and :attr:`gave_up` turns True — the give-up
    signal reconnect loops need to surface a terminal error instead of
    iterating forever (a ``for`` over the backoff simply ends).
    ``give_up_s`` adds a WALL budget on top: once it elapses,
    :attr:`gave_up` turns True regardless of attempts, and
    :meth:`clamp` caps any externally-suggested sleep (a server's
    Retry-After) to the remaining budget — a bogus ``Retry-After: 3600``
    must not park a caller past its own deadline (ISSUE 15 satellite).
    ``reset()`` — called when a connection/sync succeeds — restores the
    interval, the retry budget, and the wall budget, so the caps bound
    CONSECUTIVE failures, not lifetime ones.  Draws come from the
    injected ``rng`` only, so a seeded ``random.Random`` replays the
    exact schedule."""

    def __init__(
        self,
        min_s: float,
        max_s: float,
        factor: float = 3.0,
        rng: Optional[random.Random] = None,
        max_retries: Optional[int] = None,
        give_up_s: Optional[float] = None,
    ):
        self.min_s = min_s
        self.max_s = max_s
        self.factor = factor
        self._rng = rng or random.Random()
        self._prev = min_s
        self.max_retries = max_retries
        self.attempts = 0
        self.give_up_s = give_up_s
        self._deadline = (
            time.monotonic() + give_up_s if give_up_s is not None else None
        )

    def remaining_s(self) -> Optional[float]:
        """Wall budget left (never negative); None when unbudgeted."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def clamp(self, sleep_s: float) -> float:
        """Cap a proposed sleep to the remaining wall budget — the
        Retry-After guard: honor the server's hint only as far as this
        caller's own deadline allows.  Identity when unbudgeted."""
        rem = self.remaining_s()
        return sleep_s if rem is None else min(sleep_s, rem)

    @property
    def gave_up(self) -> bool:
        """True once the retry budget or the wall budget is spent
        (always False uncapped)."""
        if self.max_retries is not None and self.attempts >= self.max_retries:
            return True
        rem = self.remaining_s()
        return rem is not None and rem <= 0.0

    def reset(self):
        self._prev = self.min_s
        self.attempts = 0
        if self.give_up_s is not None:
            self._deadline = time.monotonic() + self.give_up_s

    def __iter__(self):
        return self

    def __next__(self) -> float:
        if self.gave_up:
            raise StopIteration
        self.attempts += 1
        nxt = min(self.max_s, self._rng.uniform(self.min_s, self._prev * self.factor))
        self._prev = nxt
        return nxt
