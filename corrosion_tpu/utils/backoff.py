"""Decorrelated-jitter exponential backoff.

Rebuild of the reference's `backoff` crate (`crates/backoff/src/lib.rs:7-90`),
used by the sync cadence, announcer, and client reconnect loops."""

from __future__ import annotations

import random
from typing import Optional


class Backoff:
    """Iterator of sleep durations: decorrelated jitter between min and max.

    next = min(max_s, uniform(min_s, prev * 3)), starting at min_s.

    ``max_retries`` caps the number of draws: once spent, ``__next__``
    raises StopIteration and :attr:`gave_up` turns True — the give-up
    signal reconnect loops need to surface a terminal error instead of
    iterating forever (a ``for`` over the backoff simply ends).
    ``reset()`` — called when a connection/sync succeeds — restores both
    the interval and the retry budget, so the cap bounds CONSECUTIVE
    failures, not lifetime ones.  Draws come from the injected ``rng``
    only, so a seeded ``random.Random`` replays the exact schedule."""

    def __init__(
        self,
        min_s: float,
        max_s: float,
        factor: float = 3.0,
        rng: Optional[random.Random] = None,
        max_retries: Optional[int] = None,
    ):
        self.min_s = min_s
        self.max_s = max_s
        self.factor = factor
        self._rng = rng or random.Random()
        self._prev = min_s
        self.max_retries = max_retries
        self.attempts = 0

    @property
    def gave_up(self) -> bool:
        """True once the retry budget is spent (always False uncapped)."""
        return self.max_retries is not None and self.attempts >= self.max_retries

    def reset(self):
        self._prev = self.min_s
        self.attempts = 0

    def __iter__(self):
        return self

    def __next__(self) -> float:
        if self.gave_up:
            raise StopIteration
        self.attempts += 1
        nxt = min(self.max_s, self._rng.uniform(self.min_s, self._prev * self.factor))
        self._prev = nxt
        return nxt
