"""Decorrelated-jitter exponential backoff.

Rebuild of the reference's `backoff` crate (`crates/backoff/src/lib.rs:7-90`),
used by the sync cadence, announcer, and client reconnect loops."""

from __future__ import annotations

import random
from typing import Optional


class Backoff:
    """Iterator of sleep durations: decorrelated jitter between min and max.

    next = min(max_s, uniform(min_s, prev * 3)), starting at min_s."""

    def __init__(
        self,
        min_s: float,
        max_s: float,
        factor: float = 3.0,
        rng: Optional[random.Random] = None,
    ):
        self.min_s = min_s
        self.max_s = max_s
        self.factor = factor
        self._rng = rng or random.Random()
        self._prev = min_s

    def reset(self):
        self._prev = self.min_s

    def __iter__(self):
        return self

    def __next__(self) -> float:
        nxt = min(self.max_s, self._rng.uniform(self.min_s, self._prev * self.factor))
        self._prev = nxt
        return nxt
