from .backoff import Backoff
from .files import read_sql_files

__all__ = ["Backoff", "read_sql_files"]
