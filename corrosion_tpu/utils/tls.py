"""TLS certificate generation for gossip mTLS.

Rebuild of the reference's cert tooling (`corro-types/src/tls.rs:17-101`,
CLI `corrosion tls {ca,server,client} generate`, main.rs:333-453): a
self-signed CA, server certs bound to the gossip IP, and client certs for
mutual TLS — all ECDSA P-256, PEM-encoded.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import Optional, Tuple

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID

_ONE_DAY = datetime.timedelta(days=1)
_VALIDITY = datetime.timedelta(days=365 * 5)


def _write_pem(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)
    os.chmod(path, 0o600)


def _key_pem(key: ec.EllipticCurvePrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption(),
    )


def generate_ca(out_dir: str) -> Tuple[str, str]:
    """Self-signed CA (tls.rs:17-39). Returns (cert_path, key_path)."""
    key = ec.generate_private_key(ec.SECP256R1())
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "corrosion-tpu CA")]
    )
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _VALIDITY)
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(
            x509.KeyUsage(
                digital_signature=True, key_cert_sign=True, crl_sign=True,
                content_commitment=False, key_encipherment=False,
                data_encipherment=False, key_agreement=False,
                encipher_only=False, decipher_only=False,
            ),
            critical=True,
        )
        .sign(key, hashes.SHA256())
    )
    os.makedirs(out_dir, exist_ok=True)
    cert_path = os.path.join(out_dir, "ca_cert.pem")
    key_path = os.path.join(out_dir, "ca_key.pem")
    _write_pem(cert_path, cert.public_bytes(serialization.Encoding.PEM))
    _write_pem(key_path, _key_pem(key))
    return cert_path, key_path


def _load_ca(ca_cert_path: str, ca_key_path: str):
    with open(ca_cert_path, "rb") as f:
        ca_cert = x509.load_pem_x509_certificate(f.read())
    with open(ca_key_path, "rb") as f:
        ca_key = serialization.load_pem_private_key(f.read(), password=None)
    return ca_cert, ca_key


def _issue(
    ca_cert_path: str,
    ca_key_path: str,
    common_name: str,
    out_dir: str,
    prefix: str,
    ip: Optional[str] = None,
    server: bool = True,
) -> Tuple[str, str]:
    ca_cert, ca_key = _load_ca(ca_cert_path, ca_key_path)
    key = ec.generate_private_key(ec.SECP256R1())
    now = datetime.datetime.now(datetime.timezone.utc)
    builder = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - _ONE_DAY)
        .not_valid_after(now + _VALIDITY)
        .add_extension(x509.BasicConstraints(ca=False, path_length=None), critical=True)
        .add_extension(
            x509.ExtendedKeyUsage(
                [
                    x509.oid.ExtendedKeyUsageOID.SERVER_AUTH
                    if server
                    else x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH
                ]
            ),
            critical=False,
        )
    )
    if ip is not None:
        builder = builder.add_extension(
            x509.SubjectAlternativeName(
                [x509.IPAddress(ipaddress.ip_address(ip))]
            ),
            critical=False,
        )
    cert = builder.sign(ca_key, hashes.SHA256())
    os.makedirs(out_dir, exist_ok=True)
    cert_path = os.path.join(out_dir, f"{prefix}_cert.pem")
    key_path = os.path.join(out_dir, f"{prefix}_key.pem")
    _write_pem(cert_path, cert.public_bytes(serialization.Encoding.PEM))
    _write_pem(key_path, _key_pem(key))
    return cert_path, key_path


def generate_server_cert(
    ca_cert_path: str, ca_key_path: str, ip: str, out_dir: str
) -> Tuple[str, str]:
    """Server cert with the gossip IP as SAN (tls.rs:41-76)."""
    return _issue(
        ca_cert_path, ca_key_path, "corrosion-tpu server", out_dir, "server",
        ip=ip, server=True,
    )


def generate_client_cert(
    ca_cert_path: str, ca_key_path: str, out_dir: str
) -> Tuple[str, str]:
    """Client cert for gossip mTLS (tls.rs:78-101)."""
    return _issue(
        ca_cert_path, ca_key_path, "corrosion-tpu client", out_dir, "client",
        server=False,
    )


# -- ssl contexts for the gossip transport ----------------------------------
#
# The reference builds rustls ServerConfig/ClientConfig from the same PEM
# material (api/peer/mod.rs:149-339): server verifies client certs against
# the CA when mTLS is on; the client verifies the server cert (IP SAN)
# unless `insecure`.


def server_ssl_context(
    cert_path: str,
    key_path: str,
    ca_cert_path: Optional[str] = None,
    require_client_cert: bool = False,
):
    """TLS context for the gossip TCP listener (peer/mod.rs:149-231)."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    if require_client_cert:
        if not ca_cert_path:
            raise ValueError("mTLS requires a CA cert to verify clients")
        ctx.verify_mode = ssl.CERT_REQUIRED
        ctx.load_verify_locations(ca_cert_path)
    return ctx


def client_ssl_context(
    ca_cert_path: Optional[str] = None,
    cert_path: Optional[str] = None,
    key_path: Optional[str] = None,
    insecure: bool = False,
):
    """TLS context for outbound gossip connections (peer/mod.rs:233-339)."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if insecure:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    elif ca_cert_path:
        ctx.load_verify_locations(ca_cert_path)
    else:
        # ADVICE r2 (low): an empty trust store fails EVERY outbound dial
        # with an opaque certificate error — a silent misconfiguration
        # trap.  Gossip peers use a self-signed cluster CA, never a
        # public one, so "no CA, not insecure" is always a mistake.
        raise ValueError(
            "[gossip.tls] is enabled but no ca_file is set and "
            "insecure=false: outbound dials cannot verify any peer. "
            "Set ca_file (generate one with `corrosion-tpu tls ca "
            "generate`) or set insecure=true for trusted networks."
        )
    if cert_path and key_path:
        ctx.load_cert_chain(cert_path, key_path)
    return ctx
