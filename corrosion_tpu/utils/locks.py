"""LockRegistry: labeled lock/critical-section tracking + watchdog.

Rebuild of the reference's registry (`corro-types/src/agent.rs:830-1055`):
every Booked/Bookie lock acquisition registers label, kind and state with a
start time; a watchdog warns on holds >10 s and flags >60 s as an invariant
violation (`setup.rs:188-246`); `corrosion locks --top N` dumps it live
(`main.rs:472-476`).  This is the rebuild's race-detection tier (SURVEY §5):
there's no TSAN — discipline comes from the single writer lane plus this
registry making long holds visible.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

WARN_AFTER_S = 10.0  # setup.rs:191
FAIL_AFTER_S = 60.0  # setup.rs:231 (Antithesis assertion threshold)


@dataclass
class LockMeta:
    id: int
    label: str
    kind: str  # "read" | "write"
    state: str  # "acquiring" | "locked"
    started_at: float = field(default_factory=time.monotonic)

    @property
    def duration_s(self) -> float:
        return time.monotonic() - self.started_at


class LockRegistry:
    def __init__(self):
        self._ids = itertools.count(1)
        self._held: Dict[int, LockMeta] = {}
        self._mu = threading.Lock()
        self.long_holds = 0  # watchdog counter (>WARN)
        self.failed_holds = 0  # invariant violations (>FAIL)

    def acquire(self, label: str, kind: str = "write") -> int:
        meta = LockMeta(next(self._ids), label, kind, "acquiring")
        with self._mu:
            self._held[meta.id] = meta
        return meta.id

    def locked(self, lock_id: int):
        with self._mu:
            meta = self._held.get(lock_id)
            if meta:
                meta.state = "locked"
                meta.started_at = time.monotonic()

    def release(self, lock_id: int):
        with self._mu:
            self._held.pop(lock_id, None)

    def track(self, label: str, kind: str = "write"):
        """Context manager for a labeled critical section."""
        registry = self

        class _Track:
            def __enter__(self):
                self.id = registry.acquire(label, kind)
                registry.locked(self.id)
                return self

            def __exit__(self, *exc):
                registry.release(self.id)
                return False

        return _Track()

    def top(self, n: int = 10) -> List[dict]:
        """Longest-held entries (the `corrosion locks` dump)."""
        with self._mu:
            metas = sorted(self._held.values(), key=lambda m: -m.duration_s)
        return [
            {
                "id": m.id, "label": m.label, "kind": m.kind,
                "state": m.state, "duration_s": round(m.duration_s, 3),
            }
            for m in metas[:n]
        ]

    def check(self) -> Optional[dict]:
        """One watchdog sweep; returns the worst offender past WARN, if any."""
        worst = None
        with self._mu:
            for m in self._held.values():
                d = m.duration_s
                if d > WARN_AFTER_S and (worst is None or d > worst.duration_s):
                    worst = m
        if worst is None:
            return None
        self.long_holds += 1
        if worst.duration_s > FAIL_AFTER_S:
            self.failed_holds += 1
        return {
            "label": worst.label, "kind": worst.kind,
            "duration_s": round(worst.duration_s, 3),
            "failed": worst.duration_s > FAIL_AFTER_S,
        }
