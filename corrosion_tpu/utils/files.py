"""Schema-file loading (reference corro-utils/src/lib.rs:5
`read_files_from_paths`): read .sql files from paths/dirs, sorted."""

from __future__ import annotations

import os
from typing import List


def read_sql_files(path: str) -> List[str]:
    if os.path.isfile(path):
        with open(path) as f:
            return [f.read()]
    out = []
    if os.path.isdir(path):
        for name in sorted(os.listdir(path)):
            if name.endswith(".sql"):
                with open(os.path.join(path, name)) as f:
                    out.append(f.read())
    return out
