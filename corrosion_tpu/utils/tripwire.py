"""Graceful-shutdown plumbing: tripwire signal + counted task drain.

Rebuild of the reference's `tripwire` and `spawn` crates
(tripwire/src/tripwire.rs:21-100, preempt.rs:12-97, spawn/src/lib.rs:13-134)
on asyncio primitives:

- ``Tripwire`` — a broadcast shutdown signal any number of tasks can await;
  ``from_signals()`` arms it on SIGINT/SIGTERM (first signal trips, a
  second force-exits, matching the reference's double-ctrl-C behavior).
- ``preemptible(aw, tripwire)`` — race an awaitable against the tripwire;
  returns ``Outcome.COMPLETED(value)`` or ``Outcome.PREEMPTED`` with the
  awaitable cancelled (PreemptibleFutureExt).
- ``spawn_counted`` / ``wait_for_all_pending_handles`` — global counter of
  in-flight tasks and the shutdown drain loop (600 x 100 ms in the
  reference; here a deadline with the same default budget).
"""

from __future__ import annotations

import asyncio
import signal as _signal
from dataclasses import dataclass
from typing import Any, Awaitable, Optional, Set


class Tripwire:
    """Awaitable, idempotent shutdown signal."""

    def __init__(self):
        self._event = asyncio.Event()

    def trip(self) -> None:
        self._event.set()

    @property
    def is_tripped(self) -> bool:
        return self._event.is_set()

    async def wait(self) -> None:
        await self._event.wait()

    def __await__(self):
        return self._event.wait().__await__()

    @classmethod
    def from_signals(cls, *signals: int) -> "Tripwire":
        """Trip on the first OS signal; force-exit on the second
        (tripwire.rs signal stream + the conventional double-ctrl-C)."""
        tw = cls()
        loop = asyncio.get_running_loop()
        sigs = signals or (_signal.SIGINT, _signal.SIGTERM)

        def _on_signal():
            if tw.is_tripped:
                # second signal: give up waiting NOW.  SystemExit would
                # still await asyncio.run's task-cancellation cleanup,
                # which hangs on exactly the stuck task being escaped.
                import os

                os._exit(1)
            tw.trip()

        for s in sigs:
            loop.add_signal_handler(s, _on_signal)
        return tw


@dataclass
class Outcome:
    """Result of a preemptible await (tripwire's Outcome enum)."""

    preempted: bool
    value: Any = None

    @classmethod
    def completed(cls, value) -> "Outcome":
        return cls(preempted=False, value=value)

    def __bool__(self):  # truthy iff completed
        return not self.preempted


Outcome.PREEMPTED = Outcome(preempted=True)


async def preemptible(aw: Awaitable, tripwire: Tripwire) -> Outcome:
    """Run ``aw`` unless/until the tripwire trips; on preemption the
    awaitable is cancelled (preempt.rs:83)."""
    if tripwire.is_tripped:
        if asyncio.iscoroutine(aw):
            aw.close()  # never started; avoid the un-awaited warning
        return Outcome.PREEMPTED
    task = asyncio.ensure_future(aw)
    trip_task = asyncio.ensure_future(tripwire.wait())
    try:
        done, _ = await asyncio.wait(
            {task, trip_task}, return_when=asyncio.FIRST_COMPLETED
        )
        if task in done:
            return Outcome.completed(task.result())
        task.cancel()
        try:
            await task
        # corrolint: disable=CT006 — the task is being preempted: its
        # outcome (including any in-flight exception) is deliberately
        # discarded in favor of the PREEMPTED verdict below
        except (asyncio.CancelledError, Exception):
            pass
        return Outcome.PREEMPTED
    finally:
        trip_task.cancel()


# -- counted spawns (spawn/src/lib.rs) ---------------------------------------

_pending: Set[asyncio.Task] = set()


def spawn_counted(aw: Awaitable, name: Optional[str] = None) -> asyncio.Task:
    """Like asyncio.create_task but tracked for the shutdown drain
    (spawn_counted, spawn/src/lib.rs:17)."""
    task = asyncio.create_task(aw, name=name)
    _pending.add(task)
    task.add_done_callback(_pending.discard)
    return task


def pending_count() -> int:
    return len(_pending)


async def wait_for_all_pending_handles(timeout: float = 60.0) -> bool:
    """Drain counted tasks at shutdown; True if all finished within the
    budget (wait_for_all_pending_handles, spawn/src/lib.rs:117: 600 x
    100 ms)."""
    deadline = asyncio.get_running_loop().time() + timeout
    while _pending:
        remaining = deadline - asyncio.get_running_loop().time()
        if remaining <= 0:
            return False
        done, _ = await asyncio.wait(
            set(_pending), timeout=min(remaining, 0.1)
        )
        # loop: newly spawned counted tasks join the drain set too
    return True
