"""Test fixtures: in-process agent clusters.

Rebuild of the reference's corro-tests crate (`corro-tests/src/lib.rs:63-88`
`launch_test_agent`): boot complete real agents on an in-memory network (the
loopback-port-0 analog), tempdir DBs, shared schema — the workhorse for
multi-node integration tests (SURVEY.md §4.2) and the simulator's
ground-truth tier.
"""

from __future__ import annotations

import asyncio
import tempfile
from typing import List, Optional, Sequence

from .agent.agent import Agent
from .agent.config import Config, PerfConfig
from .agent.transport import LinkModel, MemoryNetwork

TEST_SCHEMA = """
CREATE TABLE tests (
    id INTEGER PRIMARY KEY NOT NULL,
    text TEXT NOT NULL DEFAULT ''
);
CREATE TABLE tests2 (
    id INTEGER PRIMARY KEY NOT NULL,
    text TEXT NOT NULL DEFAULT ''
);
"""


def fast_perf() -> PerfConfig:
    """Aggressive timers so convergence tests run in wall-clock seconds."""
    return PerfConfig(
        broadcast_flush_interval_s=0.02,
        sync_backoff_min_s=0.05,
        sync_backoff_max_s=0.3,
        swim_probe_interval_s=0.05,
        swim_probe_timeout_s=0.1,
        swim_suspect_timeout_s=0.5,
    )


class Cluster:
    """N in-process agents with full mesh (or custom bootstrap) membership."""

    def __init__(
        self,
        n: int,
        schema: str = TEST_SCHEMA,
        link: Optional[LinkModel] = None,
        connectivity: Optional[int] = None,
        seed: int = 0,
        use_swim: bool = True,
        cluster_id: int = 0,
        net: Optional[MemoryNetwork] = None,
        addr_prefix: str = "node",
    ):
        self.n = n
        self.schema = schema
        # a shared ``net`` lets two Clusters with different cluster_ids sit
        # on one network (the cross-cluster isolation tests)
        self.net = net or MemoryNetwork(default_link=link or LinkModel())
        self.agents: List[Agent] = []
        self.tmp = tempfile.TemporaryDirectory()
        self.connectivity = connectivity
        self.seed = seed
        self.use_swim = use_swim
        self.cluster_id = cluster_id
        self.addr_prefix = addr_prefix
        # crashed node indices (FaultPlan campaigns): excluded from
        # convergence checks until restarted
        self.down: set = set()
        self.configs: List[Config] = []

    async def start(self, extra_bootstrap: Optional[List[str]] = None):
        import random

        rng = random.Random(self.seed)
        addrs = [f"{self.addr_prefix}{i}" for i in range(self.n)]
        for i, addr in enumerate(addrs):
            if self.connectivity is None or self.connectivity >= self.n - 1:
                bootstrap = [a for a in addrs if a != addr]
            else:
                # random bootstrap graph (configurable_stress_test analog)
                bootstrap = rng.sample(
                    [a for a in addrs if a != addr], self.connectivity
                )
            if extra_bootstrap:
                bootstrap = bootstrap + list(extra_bootstrap)
            cfg = Config(
                db_path=f"{self.tmp.name}/node{i}.db",
                gossip_addr=addr,
                bootstrap=bootstrap,
                use_swim=self.use_swim,
                cluster_id=self.cluster_id,
                perf=fast_perf(),
            )
            agent = Agent(cfg, self.net.transport(addr))
            agent.store.execute_schema(self.schema)
            self.agents.append(agent)
            self.configs.append(cfg)
        for agent in self.agents:
            await agent.start()

    async def add_node(self) -> Agent:
        """Boot a COLD late joiner (the large_tx_sync shape,
        tests.rs:602-650): fresh empty DB, bootstrap = existing nodes, must
        catch up through anti-entropy sync."""
        i = len(self.agents)
        addr = f"{self.addr_prefix}{i}"
        cfg = Config(
            db_path=f"{self.tmp.name}/node{i}.db",
            gossip_addr=addr,
            bootstrap=[a.transport.addr for a in self.agents],
            use_swim=self.use_swim,
            cluster_id=self.cluster_id,
            perf=fast_perf(),
        )
        agent = Agent(cfg, self.net.transport(addr))
        agent.store.execute_schema(self.schema)
        self.agents.append(agent)
        self.configs.append(cfg)
        self.n += 1
        await agent.start()
        return agent

    async def crash_node(self, i: int) -> None:
        """Take node i down hard (the kill -9 analog of the process
        campaign): its transport leaves the network registry, so every
        send to it fails, and `converged()` excludes it until restart."""
        self.down.add(i)
        await self.agents[i].stop()

    async def restart_node(self, i: int, wipe: bool = False) -> Agent:
        """Restart a crashed node on its original state dir.  With
        ``wipe=True`` the durable state is deleted first, so the node
        rejoins as a cold joiner with a FRESH actor identity (site_id
        lives in the db) and must recover purely via anti-entropy —
        the restore-onto-empty shape of the reference's backup
        campaign."""
        import glob
        import os

        assert i in self.down, f"node {i} is not down"
        cfg = self.configs[i]
        if wipe:
            for path in glob.glob(cfg.db_path + "*"):
                if os.path.isdir(path):
                    import shutil

                    shutil.rmtree(path, ignore_errors=True)
                else:
                    os.unlink(path)
        addr = f"{self.addr_prefix}{i}"
        agent = Agent(cfg, self.net.transport(addr))
        agent.store.execute_schema(self.schema)
        self.agents[i] = agent
        await agent.start()
        self.down.discard(i)
        return agent

    async def stop(self):
        for i, agent in enumerate(self.agents):
            if i not in self.down:
                await agent.stop()
        self.tmp.cleanup()

    def converged(self) -> bool:
        """The cluster-wide convergence property the reference checks in
        check_bookkeeping.py:6-27: all needs empty, all heads equal —
        plus NO partials at all: a complete-but-not-yet-applied partial
        is invisible to generate_sync (it advertises no gaps) but its
        data has not landed in the tables yet."""
        live = [a for i, a in enumerate(self.agents) if i not in self.down]
        heads = {}
        for agent in live:
            s = agent.sync_state()
            if s.need or s.partial_need:
                return False
            for booked in agent.bookie.by_actor.values():
                if booked.partials:
                    return False
            for actor, head in s.heads.items():
                if heads.setdefault(actor, head) != head:
                    return False
        # every node must know every writer's head
        writers = {a for a in heads}
        for agent in live:
            s = agent.sync_state()
            for w in writers:
                if w != agent.actor_id and s.heads.get(w) != heads[w]:
                    return False
        return True

    async def wait_converged(self, timeout: float = 30.0) -> bool:
        deadline = asyncio.get_event_loop().time() + timeout
        while asyncio.get_event_loop().time() < deadline:
            if self.converged():
                return True
            await asyncio.sleep(0.05)
        return self.converged()

    def rows(self, i: int, sql: str, params: Sequence = ()) -> list:
        return [tuple(r) for r in self.agents[i].store.query(sql, params)]
