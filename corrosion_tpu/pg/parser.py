"""Recursive-descent PostgreSQL-subset parser + SQLite emitter.

The reference translates PG SQL by round-tripping two full ASTs
(sqlparser → sqlite3-parser, corro-pg/src/lib.rs:546-1906, 2840+).  This
module is the rebuild's equivalent: a real lexer (PG string forms,
dollar-quoting, nested comments, multi-char operators), a
recursive-descent grammar over statements (CTEs, set operations,
sub-selects, INSERT conflict clauses parsed structurally), and an
emitter that regenerates SQLite SQL applying dialect rewrites:

- ``$N`` placeholders → ``?N``;
- ``expr::type`` → ``CAST(expr AS type)`` with PG→SQLite type mapping
  (the old token scanner DROPPED casts; the parser preserves them);
- ``public.``/qualified-function stripping, catalog tables kept;
- ``ON CONFLICT ON CONSTRAINT name`` → ``ON CONFLICT (cols)`` via a
  schema-resolver callback (42704 when the constraint is unknown);
- ``OPERATOR(pg_catalog.~)`` and friends → plain operators (``~`` →
  ``REGEXP``, registered as a UDF) — the forms psql's ``\\d`` emits;
- ``COLLATE pg_catalog.default`` dropped; type names mapped in DDL.

Parse errors raise ``ParseError`` (→ SQLSTATE 42601 at the wire).
Statement classification (read/write/ddl/tx/session) falls out of the
grammar instead of regex prefix sniffing.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple, Union

# ---------------------------------------------------------------------------
# errors


class ParseError(ValueError):
    """Syntax error (SQLSTATE 42601)."""

    def __init__(self, message: str, pos: int = -1):
        super().__init__(message)
        self.pos = pos


class UnknownConstraint(ValueError):
    """ON CONSTRAINT name not found (SQLSTATE 42704)."""


class UnsupportedConstruct(ValueError):
    """Parsed fine, but has no SQLite execution strategy (SQLSTATE
    0A000 via translate.UnsupportedStatement)."""


# ---------------------------------------------------------------------------
# lexer

IDENT, NUMBER, STRING, PARAM, OP, PUNCT, EOF = (
    "ident", "number", "string", "param", "op", "punct", "eof",
)

_OPERATOR_CHARS = set("+-*/<>=~!@#%^&|`?")
# multi-char operators PG clients actually send (longest first)
_MULTI_OPS = (
    "::", "<=", ">=", "<>", "!=", "||", "->>", "->", "#>>", "#>", "~*",
    "!~*", "!~", "@>", "<@", "&&", "?|", "?&",
)


@dataclass(frozen=True)
class Token:
    kind: str
    value: str
    pos: int
    quoted: bool = False  # IDENT was "double-quoted"

    def iskw(self, *words: str) -> bool:
        return (
            self.kind == IDENT
            and not self.quoted
            and self.value.upper() in words
        )


def tokenize(sql: str) -> List[Token]:
    toks: List[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        # comments (PG block comments nest)
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if sql.startswith("/*", i):
            depth, j = 1, i + 2
            while j < n and depth:
                if sql.startswith("/*", j):
                    depth += 1
                    j += 2
                elif sql.startswith("*/", j):
                    depth -= 1
                    j += 2
                else:
                    j += 1
            if depth:
                raise ParseError("unterminated /* comment", i)
            i = j
            continue
        # strings
        if c == "'" or (
            c in "eEbBxX" and i + 1 < n and sql[i + 1] == "'"
        ):
            start = i
            escape_form = c in "eE" and sql[i + 1] == "'"
            if c != "'":
                i += 1  # skip the prefix letter
            i += 1  # opening quote
            while i < n:
                if escape_form and sql[i] == "\\":
                    i += 2
                    continue
                if sql[i] == "'":
                    if i + 1 < n and sql[i + 1] == "'":
                        i += 2
                        continue
                    break
                i += 1
            if i >= n:
                raise ParseError("unterminated string literal", start)
            i += 1
            toks.append(Token(STRING, sql[start:i], start))
            continue
        if c == "$":
            # dollar-quoted string: $$...$$ or $tag$...$tag$
            j = i + 1
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            if j < n and sql[j] == "$" and not sql[i + 1 : j].isdigit():
                delim = sql[i : j + 1]
                end = sql.find(delim, j + 1)
                if end < 0:
                    raise ParseError("unterminated dollar-quoted string", i)
                end += len(delim)
                toks.append(Token(STRING, sql[i:end], i))
                i = end
                continue
            if i + 1 < n and sql[i + 1].isdigit():
                j = i + 1
                while j < n and sql[j].isdigit():
                    j += 1
                toks.append(Token(PARAM, sql[i:j], i))
                i = j
                continue
            raise ParseError("unexpected '$'", i)
        if c == '"':
            start, j = i, i + 1
            while j < n:
                if sql[j] == '"':
                    if j + 1 < n and sql[j + 1] == '"':
                        j += 2
                        continue
                    break
                j += 1
            if j >= n:
                raise ParseError("unterminated quoted identifier", start)
            toks.append(
                Token(IDENT, sql[start : j + 1], start, quoted=True)
            )
            i = j + 1
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "_$"):
                j += 1
            toks.append(Token(IDENT, sql[i:j], i))
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            while j < n and (sql[j].isalnum() or sql[j] in "._"):
                # 1e+5 / 1e-5
                if sql[j] in "eE" and j + 1 < n and sql[j + 1] in "+-":
                    j += 2
                    continue
                j += 1
            toks.append(Token(NUMBER, sql[i:j], i))
            i = j
            continue
        if c in "(),.;[]":
            toks.append(Token(PUNCT, c, i))
            i += 1
            continue
        if c == ":" and sql.startswith("::", i):
            toks.append(Token(OP, "::", i))
            i += 2
            continue
        if c in _OPERATOR_CHARS or c == ":":
            for m in _MULTI_OPS:
                if sql.startswith(m, i):
                    toks.append(Token(OP, m, i))
                    i += len(m)
                    break
            else:
                toks.append(Token(OP, c, i))
                i += 1
            continue
        raise ParseError(f"unexpected character {c!r}", i)
    toks.append(Token(EOF, "", n))
    return toks


# ---------------------------------------------------------------------------
# AST: expressions are ordered item sequences (re-emitted in order); ::
# binds to the PREVIOUS item (PG's tightest precedence), parens/calls/
# CASE recurse.


@dataclass
class Name:
    """Possibly-qualified identifier (a.b.c); parts keep their quoting."""

    parts: List[Token]

    @property
    def last(self) -> str:
        t = self.parts[-1]
        return t.value[1:-1].replace('""', '"') if t.quoted else t.value

    def schema(self) -> Optional[str]:
        if len(self.parts) < 2:
            return None
        t = self.parts[-2]
        return (t.value[1:-1] if t.quoted else t.value).lower()


@dataclass
class Group:
    """( items... ) — sub-select, expression parens, or column lists."""

    items: List["Item"]
    is_select: bool = False


@dataclass
class Call:
    name: Name
    args: List["Item"]


@dataclass
class Cast:
    operand: "Item"
    pg_type: str  # normalized lower-case PG type name


@dataclass
class Case:
    items: List["Item"]  # WHEN/THEN/ELSE structure re-emitted in order


Item = Union[Token, Name, Group, Call, Cast, Case]


def item_is_kw(it: "Item", *words: str) -> bool:
    """Keyword test for parsed items: bare keywords surface as Tokens OR
    single-part unquoted Names (the name/call parser claims any IDENT)."""
    if isinstance(it, Token):
        return it.iskw(*words)
    if isinstance(it, Name) and len(it.parts) == 1:
        return it.parts[0].iskw(*words)
    return False


@dataclass
class Statement:
    verb: str  # SELECT/INSERT/UPDATE/DELETE/VALUES/CREATE TABLE/...
    kind: str  # read | write | ddl | tx | session
    items: List[Item] = field(default_factory=list)
    ctes: List[Tuple[Token, List[Item], "Statement"]] = field(
        default_factory=list
    )  # (name, opt column list items, sub-statement)
    recursive: bool = False
    n_params: int = 0
    returning: bool = False


# ---------------------------------------------------------------------------
# parser

_CLAUSE_STOP = ()  # item loop stops only on ) , ; EOF at depth 0

_TX_WORDS = {
    "BEGIN", "COMMIT", "END", "ROLLBACK", "ABORT", "START",
    # savepoints are tx-machine statements: the server routes them onto
    # the open interactive tx's connection (SQLite savepoints natively)
    "SAVEPOINT", "RELEASE",
}
_SESSION_WORDS = {
    "SET", "SHOW", "DEALLOCATE", "DISCARD", "RESET", "LISTEN", "UNLISTEN",
    "NOTIFY",
}
_READ_VERBS = {"SELECT", "VALUES", "TABLE", "EXPLAIN"}
_WRITE_VERBS = {"INSERT", "UPDATE", "DELETE", "REPLACE"}
# SQL-level prepared statements (PREPARE name AS .. / EXECUTE name(..))
# share the wire-protocol statement namespace in the server
_PREPARE_WORDS = {"PREPARE", "EXECUTE"}
_DDL_VERBS = {"CREATE", "DROP", "ALTER", "TRUNCATE"}


class Parser:
    def __init__(self, toks: List[Token]):
        self.toks = toks
        self.i = 0
        self.max_param = 0

    # -- token plumbing ----------------------------------------------------

    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != EOF:
            self.i += 1
        return t

    def expect_kw(self, word: str) -> Token:
        t = self.next()
        if not t.iskw(word):
            raise ParseError(f"expected {word}, got {t.value!r}", t.pos)
        return t

    def expect_punct(self, ch: str) -> Token:
        t = self.next()
        if not (t.kind == PUNCT and t.value == ch):
            raise ParseError(f"expected {ch!r}, got {t.value!r}", t.pos)
        return t

    # -- expressions -------------------------------------------------------

    def parse_items(self, *, stop_parens: bool = True) -> List[Item]:
        """The generic ordered item loop: consume until ``)`` (when
        ``stop_parens``), ``;`` or EOF at this nesting level.  Commas are
        plain tokens here — clause structure that needs them (column
        lists) re-walks the returned items."""
        items: List[Item] = []
        while True:
            t = self.peek()
            if t.kind == EOF:
                return items
            if t.kind == PUNCT and t.value == ";":
                return items
            if t.kind == PUNCT and t.value == ")" and stop_parens:
                return items
            items.append(self.parse_item())

    def parse_item(self) -> Item:
        t = self.peek()
        item: Item
        if t.kind == PUNCT and t.value == "(":
            self.next()
            is_select = self.peek().iskw("SELECT", "VALUES", "WITH", "TABLE")
            inner = self.parse_items()
            self.expect_punct(")")
            item = Group(inner, is_select=is_select)
        elif t.iskw("CASE"):
            self.next()
            inner: List[Item] = [t]
            while True:
                nt = self.peek()
                if nt.kind == EOF:
                    raise ParseError("unterminated CASE", t.pos)
                if nt.iskw("END"):
                    inner.append(self.next())
                    break
                inner.append(self.parse_item())
            item = Case(inner)
        elif t.iskw("CAST"):
            # CAST(expr AS type): keep structure so the type name maps
            self.next()
            self.expect_punct("(")
            inner = self.parse_items()
            self.expect_punct(")")
            item = Call(Name([t]), inner)
        elif t.kind == IDENT:
            # note: OPERATOR(pg_catalog.~) parses as a Call and is mapped
            # to the plain operator by the emitter (emit_call)
            item = self.parse_name_or_call()
        elif t.kind == PARAM:
            self.max_param = max(self.max_param, int(t.value[1:]))
            item = self.next()
        else:
            item = self.next()
        # postfix :: casts (left-binding, tightest; chains allowed)
        while self.peek().kind == OP and self.peek().value == "::":
            self.next()
            item = Cast(item, self.parse_type_name())
        return item

    def parse_name(self) -> Name:
        """Qualified name WITHOUT call detection (table positions, where
        `name (cols)` is a column list, not a function call)."""
        parts = [self.next()]
        if parts[0].kind != IDENT:
            raise ParseError(f"expected name, got {parts[0].value!r}",
                             parts[0].pos)
        while (
            self.peek().kind == PUNCT
            and self.peek().value == "."
            and self.peek(1).kind == IDENT
        ):
            self.next()
            parts.append(self.next())
        return Name(parts)

    def parse_name_or_call(self) -> Item:
        parts = [self.next()]
        while (
            self.peek().kind == PUNCT
            and self.peek().value == "."
            and (
                self.peek(1).kind == IDENT
                or (self.peek(1).kind == OP and self.peek(1).value == "*")
            )
        ):
            self.next()
            nxt = self.next()
            if nxt.kind == OP:  # tbl.*
                return Name(parts + [nxt])
            parts.append(nxt)
        name = Name(parts)
        if (
            self.peek().kind == PUNCT
            and self.peek().value == "("
            and not (
                len(parts) == 1 and parts[0].iskw(*self._NOT_CALLABLE)
            )
        ):
            self.next()
            args = self.parse_items()
            self.expect_punct(")")
            return Call(name, args)
        return name

    # clause keywords followed by "(" open a sub-expression/subquery, not
    # a function call — FROM (VALUES ...) must parse as Name + Group
    _NOT_CALLABLE = (
        "FROM", "JOIN", "WHERE", "AND", "OR", "NOT", "ON", "THEN", "ELSE",
        "WHEN", "HAVING", "UNION", "INTERSECT", "EXCEPT", "ALL",
        "DISTINCT", "BY", "SET", "LIMIT", "OFFSET", "RETURNING", "USING",
        "CROSS", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "SELECT",
    )

    def parse_type_name(self) -> str:
        """Type after ``::`` or ``AS`` in CAST: ident chain, optional
        (n[,m]) modifier, optional [] array suffix, two-word forms."""
        t = self.next()
        if t.kind == STRING:  # '...'::regclass-style literal casts
            raise ParseError("string where type name expected", t.pos)
        if t.kind != IDENT:
            raise ParseError(f"expected type name, got {t.value!r}", t.pos)
        words = [t.value]
        # qualified pg_catalog.int4
        while self.peek().kind == PUNCT and self.peek().value == ".":
            self.next()
            words = [self.next().value]  # keep only the last component
        two_word = {
            ("double", "precision"), ("character", "varying"),
            ("bit", "varying"), ("timestamp", "with"), ("timestamp",
            "without"), ("time", "with"), ("time", "without"),
        }
        while (
            self.peek().kind == IDENT
            and (words[-1].lower(), self.peek().value.lower()) in two_word
        ):
            words.append(self.next().value)
            # swallow "time zone" tail of with/without forms
            if words[-1].lower() in ("with", "without"):
                for _ in range(2):
                    if self.peek().kind == IDENT:
                        words.append(self.next().value)
        if self.peek().kind == PUNCT and self.peek().value == "(":
            self.next()
            self.parse_items()
            self.expect_punct(")")
        while (
            self.peek().kind == PUNCT and self.peek().value == "["
        ):
            self.next()
            if self.peek().value == "]":
                self.next()
        return " ".join(w.lower() for w in words)

    # -- statements --------------------------------------------------------

    def parse_statement(self) -> Statement:
        t = self.peek()
        if t.kind == EOF:
            return Statement(verb="", kind="empty")
        if t.iskw("WITH"):
            return self.parse_with()
        if t.kind == IDENT and not t.quoted:
            word = t.value.upper()
            if word in _TX_WORDS:
                return self.parse_plain(word, "tx")
            if word in _SESSION_WORDS:
                return self.parse_plain(word, "session")
            if word == "PRAGMA":
                return self.parse_plain("PRAGMA", "pragma")
            if word in _PREPARE_WORDS:
                return self.parse_plain(word, word.lower())
            if word == "COMMENT":
                # COMMENT ON .. IS ..: no SQLite analog; parsed so the
                # server can no-op it with the right command tag
                return self.parse_plain("COMMENT", "comment")
            if word in _READ_VERBS:
                # verb keeps the original word (TABLE needs a rewrite in
                # translate); the command tag maps to SELECT later
                return self.parse_plain(word, "read")
            if word == "INSERT" or word == "REPLACE":
                return self.parse_insert()
            if word in _WRITE_VERBS:
                return self.parse_plain(word, "write")
            if word == "CREATE" and (
                self.peek(1).iskw("TABLE")
                or (self.peek(1).iskw("TEMP", "TEMPORARY")
                    and self.peek(2).iskw("TABLE"))
            ):
                return self.parse_create_table()
            if word in _DDL_VERBS:
                st = self.parse_plain(word, "ddl")
                # two-word tag: CREATE TABLE / DROP INDEX / ... skipping
                # modifiers (PG's tag for CREATE UNIQUE INDEX is
                # "CREATE INDEX")
                skip = ("UNIQUE", "TEMP", "TEMPORARY", "OR", "REPLACE",
                        "IF", "CONCURRENTLY")
                for it in st.items[1:6]:
                    w = None
                    if isinstance(it, Token) and it.kind == IDENT:
                        w = it.value.upper()
                    elif isinstance(it, Name):
                        w = it.parts[0].value.upper()
                    if w is None or w in skip:
                        continue
                    st.verb = f"{word} {w}"
                    break
                return st
        raise ParseError(f"unrecognized statement start {t.value!r}", t.pos)

    def parse_plain(self, verb: str, kind: str) -> Statement:
        # stop at a depth-0 ")": sub-statements inside CTE/subquery parens
        # must leave the closer for their caller; at top level a stray ")"
        # surfaces as trailing-input in parse()
        items = self.parse_items()
        st = Statement(verb=verb, kind=kind, items=items)
        st.returning = any(item_is_kw(it, "RETURNING") for it in items)
        st.n_params = self.max_param
        return st

    def parse_with(self) -> Statement:
        self.expect_kw("WITH")
        recursive = False
        if self.peek().iskw("RECURSIVE"):
            self.next()
            recursive = True
        ctes: List[Tuple[Token, List[Item], Statement]] = []
        while True:
            name = self.next()
            if name.kind != IDENT:
                raise ParseError("expected CTE name", name.pos)
            cols: List[Item] = []
            if self.peek().kind == PUNCT and self.peek().value == "(":
                self.next()
                cols = self.parse_items()
                self.expect_punct(")")
            self.expect_kw("AS")
            # [NOT] MATERIALIZED
            if self.peek().iskw("NOT"):
                self.next()
                self.expect_kw("MATERIALIZED")
            elif self.peek().iskw("MATERIALIZED"):
                self.next()
            self.expect_punct("(")
            sub = self.parse_statement()
            self.expect_punct(")")
            ctes.append((name, cols, sub))
            if self.peek().kind == PUNCT and self.peek().value == ",":
                self.next()
                continue
            break
        main = self.parse_statement()
        if main.kind not in ("read", "write"):
            raise ParseError(
                f"WITH cannot precede a {main.kind} statement",
                self.peek().pos,
            )
        main.ctes = ctes + main.ctes
        main.recursive = recursive or main.recursive
        main.n_params = self.max_param
        return main

    def parse_insert(self) -> Statement:
        verb_tok = self.next()  # INSERT | REPLACE
        verb = verb_tok.value.upper()
        items: List[Item] = [verb_tok]
        if verb == "INSERT":
            items.append(self.expect_kw("INTO"))
        table = self.parse_name()
        items.append(table)
        # optional alias / column list / body — the generic loop handles
        # everything except the conflict clause, which we lift out
        while True:
            t = self.peek()
            if t.kind == EOF or (t.kind == PUNCT and t.value in ");"):
                break
            if t.iskw("ON") and self.peek(1).iskw("CONFLICT"):
                items.append(self.parse_conflict_clause(table))
                continue
            items.append(self.parse_item())
        st = Statement(verb=verb, kind="write", items=items)
        st.returning = any(item_is_kw(it, "RETURNING") for it in items)
        st.n_params = self.max_param
        return st

    _TABLE_CONSTRAINT_WORDS = (
        "CONSTRAINT", "PRIMARY", "UNIQUE", "CHECK", "FOREIGN",
    )

    def parse_create_table(self) -> Statement:
        items: List[Item] = [self.next()]  # CREATE
        while self.peek().iskw("TEMP", "TEMPORARY", "TABLE"):
            items.append(self.next())
        if self.peek().iskw("IF"):
            items.append(self.next())
            items.append(self.expect_kw("NOT"))
            items.append(self.expect_kw("EXISTS"))
        items.append(self.parse_name())
        if self.peek().iskw("AS"):
            # CTAS: no column list to parse structurally — keep generic
            # items; the schema layer decides supportability (0A000)
            items.extend(self.parse_items())
            st = Statement(verb="CREATE TABLE", kind="ddl", items=items)
            st.n_params = self.max_param
            return st
        self.expect_punct("(")
        elements: List[Union[ColumnDef, List[Item]]] = []
        while True:
            t = self.peek()
            if t.kind == EOF:
                raise ParseError("unterminated CREATE TABLE body", t.pos)
            if t.kind == PUNCT and t.value == ")":
                self.next()
                break
            if t.iskw(*self._TABLE_CONSTRAINT_WORDS):
                elements.append(self._parse_table_element_rest())
            else:
                elements.append(self._parse_column_def())
            if self.peek().kind == PUNCT and self.peek().value == ",":
                self.next()
        items.append(TableBody(elements))
        # table options tail (WITHOUT ROWID, STRICT, ...) passes through
        items.extend(self.parse_items())
        st = Statement(verb="CREATE TABLE", kind="ddl", items=items)
        st.n_params = self.max_param
        return st

    def _parse_table_element_rest(self) -> List[Item]:
        out: List[Item] = []
        while True:
            t = self.peek()
            if t.kind == EOF or (
                t.kind == PUNCT and t.value in "),"
            ):
                return out
            out.append(self.parse_item())

    def _parse_column_def(self) -> ColumnDef:
        name = self.next()
        if name.kind != IDENT:
            raise ParseError(f"expected column name, got {name.value!r}",
                             name.pos)
        pg_type: Optional[str] = None
        type_mod: Optional[Group] = None
        t = self.peek()
        if t.kind == IDENT and not t.iskw(
            "PRIMARY", "NOT", "NULL", "DEFAULT", "UNIQUE", "CHECK",
            "REFERENCES", "COLLATE", "GENERATED", "AS", "CONSTRAINT",
        ):
            # the TYPE position: ident chain + optional (n[,m]) + []
            words = [self.next().value]
            two_word = {
                ("double", "precision"), ("character", "varying"),
            }
            while (
                self.peek().kind == IDENT
                and (words[-1].lower(), self.peek().value.lower()) in two_word
            ):
                words.append(self.next().value)
            if words[-1].lower() in ("timestamp", "time") and self.peek().iskw(
                "WITH", "WITHOUT"
            ):
                words.append(self.next().value)  # with/without
                for _ in range(2):  # time zone
                    if self.peek().kind == IDENT:
                        words.append(self.next().value)
            pg_type = " ".join(w.lower() for w in words)
            if self.peek().kind == PUNCT and self.peek().value == "(":
                self.next()
                type_mod = Group(self.parse_items())
                self.expect_punct(")")
            while self.peek().kind == PUNCT and self.peek().value == "[":
                self.next()
                if self.peek().value == "]":
                    self.next()
        rest = self._parse_table_element_rest()
        return ColumnDef(name=name, pg_type=pg_type, type_mod=type_mod,
                         rest=rest)

    def parse_conflict_clause(self, table: Name) -> "ConflictClause":
        on = self.next()
        self.expect_kw("CONFLICT")
        target_cols: Optional[Group] = None
        constraint: Optional[Token] = None
        where: List[Item] = []
        if self.peek().kind == PUNCT and self.peek().value == "(":
            self.next()
            target_cols = Group(self.parse_items())
            self.expect_punct(")")
            if self.peek().iskw("WHERE"):
                where.append(self.next())
                while not self.peek().iskw("DO") and self.peek().kind != EOF:
                    where.append(self.parse_item())
        elif self.peek().iskw("ON"):
            self.next()
            self.expect_kw("CONSTRAINT")
            constraint = self.next()
            if constraint.kind != IDENT:
                raise ParseError("expected constraint name", constraint.pos)
        # DO NOTHING | DO UPDATE SET ...
        action: List[Item] = [self.expect_kw("DO")]
        if self.peek().iskw("NOTHING"):
            action.append(self.next())
        else:
            action.append(self.expect_kw("UPDATE"))
            action.append(self.expect_kw("SET"))
            while True:
                t = self.peek()
                if (
                    t.kind == EOF
                    or (t.kind == PUNCT and t.value in ");")
                    or t.iskw("RETURNING")
                ):
                    break
                action.append(self.parse_item())
        return ConflictClause(
            on=on, table=table, target_cols=target_cols,
            constraint=constraint, where=where, action=action,
        )


@dataclass
class ColumnDef:
    """One CREATE TABLE column: name, optional PG type (structurally
    parsed so a column NAMED like a type — `name`, `text`, `uuid` — is
    never type-mapped), optional (n[,m]) modifier, trailing constraints."""

    name: Token
    pg_type: Optional[str]
    type_mod: Optional[Group]
    rest: List[Item]


@dataclass
class TableBody:
    """CREATE TABLE (...) element list: ColumnDefs + table constraints."""

    elements: List[Union[ColumnDef, List[Item]]]


@dataclass
class ConflictClause:
    on: Token
    table: Name
    target_cols: Optional[Group]
    constraint: Optional[Token]
    where: List[Item]
    action: List[Item]


def parse(sql: str) -> Statement:
    p = Parser(tokenize(sql))
    st = p.parse_statement()
    # trailing ; tolerated; anything else is a syntax error
    while p.peek().kind == PUNCT and p.peek().value == ";":
        p.next()
    if p.peek().kind != EOF:
        t = p.peek()
        raise ParseError(f"unexpected trailing input {t.value!r}", t.pos)
    return st


# ---------------------------------------------------------------------------
# emitter

_TYPE_MAP = {
    "int2": "INTEGER", "int4": "INTEGER", "int8": "INTEGER",
    "smallint": "INTEGER", "int": "INTEGER", "integer": "INTEGER",
    "bigint": "INTEGER", "serial": "INTEGER", "bigserial": "INTEGER",
    "smallserial": "INTEGER", "oid": "INTEGER",
    "float4": "REAL", "float8": "REAL", "double precision": "REAL",
    "real": "REAL", "numeric": "REAL", "decimal": "REAL",
    "bool": "INTEGER", "boolean": "INTEGER",
    "bytea": "BLOB",
    "json": "TEXT", "jsonb": "TEXT", "uuid": "TEXT", "text": "TEXT",
    "varchar": "TEXT", "character varying": "TEXT", "character": "TEXT",
    "char": "TEXT", "name": "TEXT", "regclass": "TEXT", "citext": "TEXT",
    "date": "TEXT", "timestamptz": "TEXT", "timestamp": "TEXT",
    "timestamp with time zone": "TEXT",
    "timestamp without time zone": "TEXT",
    "time": "TEXT", "time with time zone": "TEXT",
    "time without time zone": "TEXT", "interval": "TEXT",
}

# operator spellings inside OPERATOR(pg_catalog.X) → SQLite operator
_OPERATOR_MAP = {"~": "REGEXP", "~~": "LIKE", "=": "=", "<>": "<>",
                 "!=": "!=", "~*": "REGEXP"}

# function renames applied at call sites (PG name → SQLite/UDF name;
# UDFs live in runtime.py and are registered on every PG-serving conn)
_CALL_RENAMES = {
    # UDFs, not SQLite MAX/MIN: PG's greatest/least IGNORE NULLs
    # (greatest(1, NULL, 3) = 3) where SQLite's scalar MAX returns NULL
    "greatest": "pg_greatest", "least": "pg_least",
    "string_agg": "group_concat",
    "array_agg": "json_group_array",
    "json_agg": "json_group_array", "jsonb_agg": "json_group_array",
    "json_object_agg": "json_group_object",
    "jsonb_object_agg": "json_group_object",
    "json_build_object": "json_object", "jsonb_build_object": "json_object",
    "json_build_array": "json_array", "jsonb_build_array": "json_array",
    "to_json": "pg_to_json", "to_jsonb": "pg_to_json",
    "left": "pg_left", "right": "pg_right",  # SQLite JOIN keywords
    "random": "pg_random",  # PG: float in [0,1); SQLite: int64
    "now": "pg_now", "transaction_timestamp": "pg_now",
    "statement_timestamp": "pg_now", "clock_timestamp": "pg_now",
    "char_length": "length", "character_length": "length",
    "strpos": "instr",
    "uuid_generate_v4": "gen_random_uuid",
}

# keyword Names that terminate a value expression (used to decide
# whether an item can be the LHS of an infix rewrite)
_CLAUSE_KWS = (
    "SELECT", "FROM", "WHERE", "AND", "OR", "NOT", "BY", "GROUP",
    "ORDER", "HAVING", "LIMIT", "OFFSET", "SET", "VALUES", "ON", "AS",
    "IN", "IS", "LIKE", "ILIKE", "BETWEEN", "CASE", "WHEN", "THEN",
    "ELSE", "END", "RETURNING", "UNION", "INTERSECT", "EXCEPT", "ALL",
    "DISTINCT", "JOIN", "LEFT", "RIGHT", "INNER", "OUTER", "CROSS",
    "FULL", "USING", "INTO", "INSERT", "UPDATE", "DELETE", "INTERVAL",
    "ASC", "DESC", "NULLS", "FIRST", "LAST", "OVER", "PARTITION",
    "FILTER", "EXISTS", "ANY", "SOME", "ARRAY", "ESCAPE", "COLLATE",
    "WITH",
)


def _is_valueish(it: "Item") -> bool:
    """Could `it` be a complete value expression (LHS of an infix
    operator rewrite)?"""
    if isinstance(it, (Call, Group, Cast)):
        return True
    if isinstance(it, Name):
        return not (
            len(it.parts) == 1
            and not it.parts[0].quoted
            and it.parts[0].value.upper() in _CLAUSE_KWS
        )
    if isinstance(it, Token):
        return it.kind in (STRING, NUMBER, PARAM)
    return False


def _split_args(args: Sequence["Item"]) -> List[List["Item"]]:
    """Split a Call's flat arg items on top-level ',' tokens."""
    out: List[List[Item]] = [[]]
    for a in args:
        if isinstance(a, Token) and a.kind == PUNCT and a.value == ",":
            out.append([])
        else:
            out[-1].append(a)
    if out == [[]]:
        return []
    return out


def _strip_order_by(args: Sequence["Item"]) -> Sequence["Item"]:
    """Drop a trailing ``ORDER BY ...`` from aggregate arguments:
    SQLite < 3.44 rejects it inside group_concat, and the SQLite
    aggregates have no ordered form anyway (the multiset is identical;
    the concatenation order deviation is documented in doc/api/pg.md)."""
    for k, a in enumerate(args):
        if (
            item_is_kw(a, "ORDER")
            and k + 1 < len(args)
            and item_is_kw(args[k + 1], "BY")
        ):
            return args[:k]
    return args


def _strip_quotes(tok: "Token") -> str:
    """STRING token → inner text ('' unescaped; E-string prefix shed)."""
    v = tok.value
    if v and v[0] in "eE":
        v = v[1:]
    if len(v) >= 2 and v[0] == "'" and v[-1] == "'":
        v = v[1:-1]
    return v.replace("''", "'")


def _literal_number(items: Sequence["Item"]):
    """[Token(2)] or [-, Token(2)] → float, else None."""
    sign = 1.0
    toks = list(items)
    if (
        len(toks) == 2
        and isinstance(toks[0], Token)
        and toks[0].kind == OP
        and toks[0].value in "+-"
    ):
        sign = -1.0 if toks[0].value == "-" else 1.0
        toks = toks[1:]
    if len(toks) == 1 and isinstance(toks[0], Token) and toks[0].kind == NUMBER:
        try:
            return sign * float(toks[0].value)
        except ValueError:
            return None
    return None


def _parse_srf_alias(items: Sequence["Item"], j: int):
    """Parse the alias tail of a FROM-position SRF: ``[AS] a``,
    ``[AS] a(c)`` — returns (alias, col, next_idx).  Raises on
    WITH ORDINALITY (no SQLite strategy)."""
    if j < len(items) and item_is_kw(items[j], "AS"):
        j += 1
    alias = None
    col = None
    if (
        j + 1 < len(items)
        and item_is_kw(items[j], "WITH")
        and item_is_kw(items[j + 1], "ORDINALITY")
    ):
        raise UnsupportedConstruct(
            "WITH ORDINALITY is not supported; join against "
            "generate_series or use row_number()"
        )
    if j < len(items) and isinstance(items[j], Call) and len(
        items[j].name.parts
    ) == 1:
        alias = items[j].name.parts[0].value
        cargs = _split_args(items[j].args)
        if len(cargs) == 1 and len(cargs[0]) == 1 and isinstance(
            cargs[0][0], Name
        ):
            col = cargs[0][0].parts[0].value
        j += 1
    elif (
        j < len(items)
        and isinstance(items[j], Name)
        and len(items[j].parts) == 1
        and _is_valueish(items[j])
    ):
        alias = items[j].parts[0].value
        j += 1
        if j < len(items) and isinstance(items[j], Group):
            sub = _split_args(items[j].items)
            if len(sub) == 1 and len(sub[0]) == 1 and isinstance(
                sub[0][0], Name
            ):
                col = sub[0][0].parts[0].value
                j += 1
    return alias, col, j


def _srf_args_correlated(args: Sequence["Item"]) -> bool:
    """Do the SRF arguments reference any column (a bare or qualified
    Name)?  Decides the emission strategy: correlated args need the
    bare table-valued json_each (SQLite's only lateral form, which
    leaks json_each's own column names); literal/param args get a
    clean renaming subquery."""
    for a in args:
        if isinstance(a, Name):
            if not (
                len(a.parts) == 1
                and not a.parts[0].quoted
                and a.parts[0].value.upper() in _CLAUSE_KWS
            ):
                return True
        elif isinstance(a, Call):
            if _srf_args_correlated(a.args):
                return True
        elif isinstance(a, Group):
            if _srf_args_correlated(a.items):
                return True
        elif isinstance(a, Cast):
            if _srf_args_correlated([a.operand]):
                return True
        elif isinstance(a, Case):
            if _srf_args_correlated(a.items):
                return True
    return False


# json_each-backed set-returning functions (FROM position)
_SRF_JSON_FAMILY = frozenset((
    "unnest",
    "jsonb_array_elements", "json_array_elements",
    "jsonb_array_elements_text", "json_array_elements_text",
    "jsonb_object_keys", "json_object_keys",
))


def _srf_column_expr(fname: str, table: str) -> str:
    """The expression a reference to the SRF's output column rewrites
    to, qualified by the emitted json_each table alias."""
    t = '"' + table.replace('"', '""') + '"'
    if fname in ("jsonb_object_keys", "json_object_keys"):
        return f"{t}.key"
    if fname in ("jsonb_array_elements", "json_array_elements"):
        # jsonb TEXT per element: containers pass through, booleans/
        # null keep their JSON spelling, scalars re-quote
        return (
            f"CASE WHEN {t}.type IN ('true', 'false', 'null') "
            f"THEN {t}.type "
            f"WHEN {t}.type IN ('object', 'array') THEN {t}.value "
            f"ELSE json_quote({t}.value) END"
        )
    if fname in ("jsonb_array_elements_text", "json_array_elements_text"):
        return (
            f"CASE WHEN {t}.type = 'null' THEN NULL "
            f"WHEN {t}.type IN ('true', 'false') THEN {t}.type "
            f"ELSE CAST({t}.value AS TEXT) END"
        )
    return f"{t}.value"  # unnest


def _clause_step(clause, it: "Item"):
    """Shared clause-keyword tracker for the emitter and the SRF
    scanner — the two MUST agree on what counts as FROM position.  A
    top-level comma while in the ON clause returns to the FROM list
    (``FROM a JOIN b ON cond, srf(...)``)."""
    if isinstance(it, Name) and len(it.parts) == 1 and not it.parts[0].quoted:
        up = it.parts[0].value.upper()
        if up in ("FROM", "JOIN"):
            return "FROM"
        if up in ("SELECT", "WHERE", "GROUP", "ORDER", "HAVING",
                  "SET", "VALUES", "RETURNING", "LIMIT", "ON"):
            return up
    elif (
        isinstance(it, Token) and it.kind == PUNCT and it.value == ","
        and clause == "ON"
    ):
        return "FROM"
    return clause


def scan_srf_renames(items: Sequence["Item"]):
    """Scan ONE scope (no Group recursion — the emitter re-scopes at
    each select subquery) for json-family SRFs in FROM position.
    Returns (renames, has_from): {referenced-column-name (lower) ->
    replacement expression} — PG names the SRF's single OUTPUT COLUMN
    after the alias, and the lateral-capable bare ``json_each(...)``
    emission needs every reference rewritten to the table-qualified
    expression — plus whether this scope has its own FROM clause
    (scope-shadowing policy in Emitter.emit_item)."""
    renames: dict = {}
    clause = None
    has_from = False
    for k, it in enumerate(items):
        if item_is_kw(it, "UNION", "INTERSECT", "EXCEPT"):
            # each set-operation branch is its own scope; the emitter
            # re-scans at the same boundary (Emitter._emit_items_inner)
            break
        clause = _clause_step(clause, it)
        if clause == "FROM":
            has_from = True
        if (
            clause == "FROM"
            and isinstance(it, Call)
            and len(it.name.parts) == 1
            and it.name.parts[0].value.lower() in _SRF_JSON_FAMILY
            # only the bare-TVF (correlated) emission needs renames;
            # the uncorrelated subquery form names its column directly.
            # MUST match _try_srf's choice of emission strategy.
            and _srf_args_correlated(it.args)
        ):
            fname = it.name.parts[0].value.lower()
            try:
                alias, col, _j = _parse_srf_alias(items, k + 1)
            except UnsupportedConstruct:
                continue  # _try_srf raises with position context later
            table = alias or fname
            colname = (col or alias or _srf_default_col(fname)).lower()
            renames[colname] = _srf_column_expr(fname, table)
    return renames, has_from


def _srf_default_col(fname: str) -> str:
    """PG's default output column name: functions with a named OUT
    parameter (the *_elements family: `value`) use it; the rest use
    the function name."""
    if fname in (
        "jsonb_array_elements", "json_array_elements",
        "jsonb_array_elements_text", "json_array_elements_text",
    ):
        return "value"
    return fname


# Name-position keyword spellings PG accepts bare (emit_name)
_NAME_RENAMES = {
    "localtimestamp": "CURRENT_TIMESTAMP",
    "localtime": "CURRENT_TIME",
    "current_user": "'postgres'", "session_user": "'postgres'",
    "current_role": "'postgres'",
}

_E_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "b": "\b", "f": "\f",
    "\\": "\\", "'": "'", '"': '"', "0": "\0",
}


def _sqlite_string(raw: str) -> str:
    """PG string literal → SQLite string literal.  Standard '...' passes
    through; E'...' decodes backslash escapes; $tag$...$tag$ re-quotes;
    X'...'/B'...' pass through (SQLite knows blob literals)."""
    if raw.startswith("'"):
        return raw
    head = raw[0].lower()
    if head in "xb":
        return raw
    if head == "e":
        body = raw[2:-1]
        out: List[str] = []
        i = 0
        while i < len(body):
            c = body[i]
            if c == "\\" and i + 1 < len(body):
                nxt = body[i + 1]
                if nxt in _E_ESCAPES:
                    out.append(_E_ESCAPES[nxt])
                    i += 2
                    continue
                if nxt in "xX" and i + 3 < len(body) + 1:
                    hexs = body[i + 2 : i + 4]
                    try:
                        out.append(chr(int(hexs, 16)))
                        i += 2 + len(hexs)
                        continue
                    except ValueError:
                        pass
                if nxt == "u" and i + 6 <= len(body) + 1:
                    try:
                        out.append(chr(int(body[i + 2 : i + 6], 16)))
                        i += 6
                        continue
                    except ValueError:
                        pass
                out.append(nxt)
                i += 2
                continue
            if c == "'" and body[i : i + 2] == "''":
                out.append("'")
                i += 2
                continue
            out.append(c)
            i += 1
        return "'" + "".join(out).replace("'", "''") + "'"
    if head == "$":
        delim_end = raw.find("$", 1) + 1
        body = raw[delim_end : len(raw) - delim_end]
        return "'" + body.replace("'", "''") + "'"
    return raw

ConstraintResolver = Callable[[str, str], Sequence[str]]


class Emitter:
    def __init__(
        self,
        constraint_resolver: Optional[ConstraintResolver] = None,
        srf_renames: Optional[dict] = None,
    ):
        self.resolver = constraint_resolver
        # SRF output-column name -> replacement expression (the
        # lateral-capable json_each emission; scan_srf_renames)
        self.srf_renames = srf_renames or {}
        # reference-position state for the rename guard: renames apply
        # only in value-reading clauses, never to name-DEFINING
        # positions (SELECT ... AS e, INSERT column lists, SET targets)
        self._clause = None
        self._prev_sig: Optional[Item] = None
        self.out: List[str] = []

    _SRF_VALUE_CLAUSES = (
        "SELECT", "WHERE", "GROUP", "ORDER", "HAVING", "ON",
        "RETURNING", "LIMIT",
    )

    # one space between emitted atoms except after ( . and before ) , . (
    _NO_SPACE_BEFORE = {")", ",", ".", ";", "[", "]", "("}
    _NO_SPACE_AFTER = {"(", ".", "["}

    def _emit(self, text: str) -> None:
        if (
            self.out
            and text not in self._NO_SPACE_BEFORE
            and self.out[-1] not in self._NO_SPACE_AFTER
        ):
            # no space before ( when it follows a function name — handled
            # by Call emission passing "(" directly
            self.out.append(" ")
        self.out.append(text)

    def text(self) -> str:
        return "".join(self.out)

    # -- item dispatch -----------------------------------------------------

    def emit_items(self, items: Sequence[Item]) -> None:
        # the clause state INHERITS into nested item lists (call args
        # inside a select list are still in SELECT position) and is
        # restored on exit
        entry_clause = self._clause
        entry_renames = self.srf_renames
        try:
            self._emit_items_inner(items, entry_clause)
        finally:
            self._clause = entry_clause
            self.srf_renames = entry_renames

    def _emit_items_inner(self, items: Sequence[Item], clause) -> None:
        idx = 0
        while idx < len(items):
            it = items[idx]
            if item_is_kw(it, "UNION", "INTERSECT", "EXCEPT"):
                # new set-operation branch = new SRF-rename scope
                # (scan_srf_renames stops at the same boundary; the
                # caller's emit_items restores on exit)
                self.srf_renames = scan_srf_renames(items[idx + 1:])[0]
            clause = _clause_step(clause, it)
            self._clause = clause
            self._prev_sig = items[idx - 1] if idx > 0 else None
            if clause == "FROM" and item_is_kw(it, "LATERAL"):
                # PG's explicit LATERAL spelling: the bare json_each
                # emission is already lateral; SQLite has no keyword
                idx += 1
                continue
            # COLLATE pg_catalog.default / COLLATE "default" → dropped
            if (
                item_is_kw(it, "COLLATE")
                and idx + 1 < len(items)
                and isinstance(items[idx + 1], Name)
                and items[idx + 1].last.lower() in ("default", "c", "posix")
            ):
                idx += 2
                continue
            if item_is_kw(it, "ILIKE"):
                # SQLite LIKE is already case-insensitive for ASCII
                self._emit("LIKE")
                idx += 1
                continue
            # (VALUES ...) AS t(c1, c2): SQLite has no column aliases on
            # subqueries — re-emit as a positional rename over the
            # guaranteed column1..columnN names of a VALUES list
            rewritten = self._try_values_alias(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_interval_arith(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_interval_literal(items, idx)
            if rewritten:
                idx += rewritten
                continue
            # any_all first: `~ ANY(...)` must hit the quantified-form
            # rejection, not the regex rewrite
            rewritten = self._try_any_all(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_regex_op(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_containment_op(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_concat_chain(items, idx)
            if rewritten:
                idx += rewritten
                continue
            rewritten = self._try_array_literal(items, idx)
            if rewritten:
                idx += rewritten
                continue
            # left(x, n) / right(x, n): JOIN keywords, so the parser
            # leaves them as Name + Group instead of a Call
            if (
                item_is_kw(it, "LEFT", "RIGHT")
                and idx + 1 < len(items)
                and isinstance(items[idx + 1], Group)
                and not items[idx + 1].is_select
            ):
                self._emit("pg_" + it.parts[0].value.lower())
                self.out.append("(")
                self.emit_items(items[idx + 1].items)
                self._emit(")")
                idx += 2
                continue
            rewritten = self._try_for_lock(items, idx)
            if rewritten:
                idx += rewritten
                continue
            if clause == "FROM":
                rewritten = self._try_srf(items, idx)
                if rewritten:
                    idx += rewritten
                    continue
            self.emit_item(it)
            idx += 1

    # -- PG-idiom pattern rewrites (each returns items consumed, 0 = no
    # match; execution-level fidelity the reference gets from PG itself
    # and we must synthesize over SQLite) ----------------------------------

    def _try_interval_arith(self, items: Sequence[Item], idx: int) -> int:
        """``<ts> ± interval '...' [± interval '...']...`` →
        nested ``pg_ts_offset(<ts>, '...', ±1)`` — a UDF rather than
        SQLite datetime() modifiers because PG clamps month overflow
        ('2026-01-31' + 1 mon = Feb 28) where datetime() normalizes it
        into March."""
        from .runtime import interval_to_seconds

        def match(k: int):
            """± interval-literal at k → (string-token, items-consumed);
            both the keyword form (``- interval '1 h'``) and the cast
            form (``- '1 h'::interval``) count — the cast form would
            otherwise fold to float seconds and silently coerce the
            text timestamp to numeric garbage."""
            if not (
                k + 1 < len(items)
                and isinstance(items[k], Token)
                and items[k].kind == OP
                and items[k].value in "+-"
            ):
                return None
            nxt = items[k + 1]
            if (
                item_is_kw(nxt, "INTERVAL")
                and k + 2 < len(items)
                and isinstance(items[k + 2], Token)
                and items[k + 2].kind == STRING
            ):
                return items[k + 2], 3
            if (
                isinstance(nxt, Cast)
                and nxt.pg_type == "interval"
                and isinstance(nxt.operand, Token)
                and nxt.operand.kind == STRING
            ):
                return nxt.operand, 2
            return None

        if not _is_valueish(items[idx]) or match(idx + 1) is None:
            return 0
        steps: List[tuple] = []  # (interval-text, sign)
        j = idx + 1
        while True:
            got = match(j)
            if got is None:
                break
            tok, width = got
            text = _strip_quotes(tok)
            try:
                interval_to_seconds(text)  # validate at emit time
            except ValueError:
                return 0  # unparseable interval: emit raw, fail at exec
            steps.append((text, -1 if items[j].value == "-" else 1))
            j += width
        # trailing +/- is fine (left-assoc: our fold IS PG's grouping);
        # trailing * / % ^ binds the interval first in PG and would be
        # regrouped — same for any arithmetic gluing to our left
        self._guard_arith_regroup(
            items, idx, j, "interval arithmetic",
            trailing=frozenset({"*", "/", "%", "^"}),
        )
        for _ in steps:
            self._emit("pg_ts_offset")
            self.out.append("(")
        self.emit_item(items[idx])
        for text, sign in steps:
            self._emit(",")
            self._emit("'" + text.replace("'", "''") + "'")
            self._emit(",")
            self._emit(str(sign))
            self._emit(")")
        return j - idx

    def _try_interval_literal(self, items: Sequence[Item], idx: int) -> int:
        """Standalone ``interval '...'`` → seconds as a float literal
        (the EXTRACT(EPOCH...) view; doc/pg.md#intervals)."""
        from .runtime import interval_to_seconds

        if not (
            item_is_kw(items[idx], "INTERVAL")
            and idx + 1 < len(items)
            and isinstance(items[idx + 1], Token)
            and items[idx + 1].kind == STRING
        ):
            return 0
        try:
            secs = interval_to_seconds(_strip_quotes(items[idx + 1]))
        except ValueError:
            return 0
        self._emit(repr(secs))
        return 2

    def _try_regex_op(self, items: Sequence[Item], idx: int) -> int:
        """Infix ``~  ~*  !~  !~*`` → [NOT] REGEXP (the regexp(p, s) UDF
        is registered by runtime.py); ``*`` variants prepend ``(?i)`` —
        SQLite's ``||`` binds tighter than REGEXP, so no parens needed."""
        it = items[idx]
        if not (
            isinstance(it, Token)
            and it.kind == OP
            and it.value in ("~", "~*", "!~", "!~*")
            and idx > 0
            and _is_valueish(items[idx - 1])
            and idx + 1 < len(items)
        ):
            return 0
        if it.value.startswith("!"):
            self._emit("NOT")
        self._emit("REGEXP")
        if it.value.endswith("*"):
            self._emit("'(?i)'")
            self._emit("||")
        return 1

    _CONTAINMENT_FNS = {
        "@>": "pg_jsonb_contains", "<@": "pg_jsonb_contained",
        "&&": "pg_array_overlap",
        "?": "pg_jsonb_exists", "?|": "pg_jsonb_exists_any",
        "?&": "pg_jsonb_exists_all",
    }

    # operators that extend a value expression without ending it — the
    # canonical idiom is `data -> 'tags' @> '[...]'`, where the @>'s
    # LHS is the whole arrow chain (PG: equal precedence, left-assoc)
    _CHAIN_OPS = ("->", "->>", "#>", "#>>", "||")

    def _unit_end(self, items: Sequence[Item], idx: int) -> int:
        """End (exclusive) of one chain unit: a valueish item or an
        ``ARRAY[...]`` constructor; -1 = neither."""
        if idx < len(items) and _is_valueish(items[idx]):
            return idx + 1
        if (
            idx + 1 < len(items)
            and item_is_kw(items[idx], "ARRAY")
            and isinstance(items[idx + 1], Token)
            and items[idx + 1].value == "["
        ):
            close = self._array_close(items, idx)
            if close > 0:
                return close + 1
        return -1

    def _chain_end(self, items: Sequence[Item], idx: int) -> int:
        """items[idx] starts a unit; extend over [chain-op, unit] pairs
        (units include ARRAY[...] constructors — `'{a}' || ARRAY['b']`
        is one operand); returns the index AFTER the maximal chain, or
        -1 when items[idx] is not a unit (a negative j would index
        items[-1] and walk a bogus chain from 0 — the emit loop then
        never terminates on malformed input)."""
        j = self._unit_end(items, idx)
        if j < 0:
            return -1
        while (
            j + 1 < len(items)
            and isinstance(items[j], Token)
            and items[j].kind == OP
            and items[j].value in self._CHAIN_OPS
        ):
            ue = self._unit_end(items, j + 1)
            if ue < 0:
                break
            j = ue
        return j

    def _array_close(self, items: Sequence[Item], idx: int) -> int:
        """items[idx] is kw ARRAY, items[idx+1] is '[' — index of the
        matching ']', or -1."""
        depth = 0
        for k in range(idx + 1, len(items)):
            t = items[k]
            if isinstance(t, Token):
                if t.value == "[":
                    depth += 1
                elif t.value == "]":
                    depth -= 1
                    if depth == 0:
                        return k
        return -1

    def _operand_end(
        self, items: Sequence[Item], idx: int, chain: bool = True
    ) -> int:
        """End index (exclusive) of a containment operand.
        ``chain=True`` (LHS only) extends over arrow/concat pairs —
        left-associativity pulls the whole chain into the LHS, but the
        RHS of an equal-precedence operator is always a SINGLE operand
        (``a ? 'x' || 'y'`` parses as ``(a ? 'x') || 'y'`` in PG)."""
        if chain:
            return self._chain_end(items, idx)
        return self._unit_end(items, idx)

    def _emit_operand(self, items: Sequence[Item], start: int, end: int):
        if end - start == 1 and isinstance(items[start], Cast):
            # a typed-array cast ($1::int[]) would emit CAST(? AS
            # INTEGER) and destroy the array text before the UDF parses
            # it — strip it, like _try_any_all does for = ANY($1::int[])
            self.emit_item(items[start].operand)
            return
        if (
            item_is_kw(items[start], "ARRAY")
            and self._array_close(items, start) + 1 == end
        ):
            # a pure ARRAY[...] constructor
            self._emit("json_array")
            self.out.append("(")
            self.emit_items(items[start + 2: end - 1])
            self._emit(")")
            return
        # split the span into chain units; PG resolves each `||` link
        # LEFT-TO-RIGHT by operand type — see _emit_concat_fold
        fold = self._fold_span(items, start, end)
        if fold is not None:
            units, ops, fend = fold
            if fend == end and self._fold_eligible(items, units, ops):
                self._emit_concat_fold(items, units)
                return
        self.emit_items(items[start:end])

    def _try_concat_chain(self, items: Sequence[Item], idx: int) -> int:
        """A bare ``... || ARRAY[...] || ...`` chain ANYWHERE (not just
        as a containment operand) gets the PG type-resolved fold —
        ``SELECT ARRAY[1] || ARRAY[2]`` is array concatenation, not
        SQLite string concat of two json_array() texts."""
        prev = items[idx - 1] if idx > 0 else None
        if (
            isinstance(prev, Token)
            and prev.kind == OP
            and prev.value in self._CHAIN_OPS
        ):
            # we are the RHS of an already-emitted chain operator
            # (`data #>> '{a}' || ...`): starting a fold here would
            # regroup PG's left-associative chain
            return 0
        fold = self._fold_span(items, idx, len(items))
        if fold is None:
            return 0
        units, ops, end = fold
        if not self._fold_eligible(items, units, ops):
            return 0
        self._emit_concat_fold(items, units)
        return end - idx

    def _fold_span(self, items: Sequence[Item], start: int, limit: int):
        """Maximal [unit, (chain-op, unit)*] span from ``start`` bounded
        by ``limit``; returns (units, ops, end) or None."""
        ue = self._unit_end(items, start)
        if ue < 0 or ue > limit:
            return None
        units = [(start, ue)]
        ops: List[Token] = []
        while ue < limit:
            op = items[ue]
            if not (
                isinstance(op, Token)
                and op.kind == OP
                and op.value in self._CHAIN_OPS
            ):
                break
            nxt = self._unit_end(items, ue + 1)
            if nxt < 0 or nxt > limit:
                break
            ops.append(op)
            units.append((ue + 1, nxt))
            ue = nxt
        return units, ops, ue

    def _fold_eligible(self, items, units, ops) -> bool:
        """The array-concat fold applies to all-``||`` chains that
        involve at least one ARRAY constructor (PG types the links)."""
        return (
            bool(ops)
            and all(o.value == "||" for o in ops)
            and any(item_is_kw(items[s], "ARRAY") for s, _ in units)
        )

    def _emit_concat_fold(self, items: Sequence[Item], units) -> None:
        """PG resolves each ``||`` link LEFT-TO-RIGHT by operand type:
        a link is ARRAY CONCATENATION (pg_array_cat) once the
        accumulated value or its right unit is array-typed (an ARRAY
        constructor); earlier links between untyped literals stay
        SQLite string concat."""
        is_cat = []  # per link
        acc_is_array = item_is_kw(items[units[0][0]], "ARRAY")
        for s, _e in units[1:]:
            cat = acc_is_array or item_is_kw(items[s], "ARRAY")
            is_cat.append(cat)
            acc_is_array = acc_is_array or cat

        def emit_fold(k: int):
            if k == 0:
                self._emit_operand(items, *units[0])
                return
            if is_cat[k - 1]:
                self._emit("pg_array_cat")
                self.out.append("(")
                emit_fold(k - 1)
                self._emit(",")
                self._emit_operand(items, *units[k])
                self._emit(")")
            else:
                emit_fold(k - 1)
                self._emit("||")
                self._emit_operand(items, *units[k])

        emit_fold(len(units) - 1)

    _ARITH_OPS = frozenset({"+", "-", "*", "/", "%", "^"})

    def _guard_arith_regroup(
        self,
        items: Sequence[Item],
        idx: int,
        end: int,
        opname: str,
        trailing: frozenset = _ARITH_OPS,
    ) -> None:
        """The lookahead rewrites (containment, interval arithmetic)
        capture ONE operand on each side, so an adjacent arithmetic
        operator that PG binds FIRST (``+`` binds tighter than ``@>``;
        ``*`` tighter than ``± interval``) would be silently regrouped
        — ``x + a @> b`` must mean ``(x + a) @> b``, not
        ``x + (a @> b)``.  Refuse with a parenthesize hint instead of
        emitting a wrong grouping (ADVICE r4, parser.py:1642)."""
        prev = items[idx - 1] if idx > 0 else None
        if (
            isinstance(prev, Token)
            and prev.kind == OP
            and prev.value in self._ARITH_OPS
            # a sign with nothing valueish before it is unary: no regroup
            and not (
                prev.value in "+-"
                and (idx < 2 or not _is_valueish(items[idx - 2]))
            )
        ):
            raise UnsupportedConstruct(
                f"arithmetic adjacent to {opname} is ambiguous here "
                "(PG binds the arithmetic first); parenthesize the "
                "left operand"
            )
        nxt = items[end] if end < len(items) else None
        if isinstance(nxt, Token) and nxt.kind == OP and nxt.value in trailing:
            raise UnsupportedConstruct(
                f"arithmetic adjacent to {opname} is ambiguous here "
                "(PG binds the arithmetic first); parenthesize the "
                "right operand"
            )

    def _try_containment_op(self, items: Sequence[Item], idx: int) -> int:
        """Infix jsonb/array operators with no SQLite spelling:
        ``a @> b`` / ``a <@ b`` (jsonb containment; PG array literals
        and ARRAY[...] constructors get PG array-type semantics),
        ``a && b`` (array overlap), and the key-existence family
        ``? ?| ?&`` — rewritten as UDF calls (runtime.py) via lhs
        lookahead, like the interval rewrite.  Operands capture their
        full arrow/concat chain or ARRAY constructor.

        NOTE: bare ``?`` params never reach this path — PG clients send
        ``$N``, and the tokenizer classifies ``?`` as an operator."""
        lhs_end = self._operand_end(items, idx)
        if lhs_end < 0 or lhs_end >= len(items):
            return 0
        op = items[lhs_end]
        if not (isinstance(op, Token) and op.kind == OP):
            return 0
        fn = self._CONTAINMENT_FNS.get(op.value)
        if fn is None or lhs_end + 1 >= len(items):
            return 0
        rhs_end = self._operand_end(items, lhs_end + 1, chain=False)
        # validate BEFORE emitting anything: a non-positive consumed
        # count would wedge the emit loop (idx += 0/negative forever)
        if rhs_end < 0 or rhs_end <= idx:
            return 0
        self._guard_arith_regroup(items, idx, rhs_end, op.value)
        # an ARRAY[...] constructor ANYWHERE in an operand (including a
        # || concat chain) pins PG ARRAY-type semantics for @>/<@ —
        # the same rule runtime.py applies to '{...}' literals
        if fn in ("pg_jsonb_contains", "pg_jsonb_contained") and any(
            item_is_kw(items[k], "ARRAY")
            for k in list(range(idx, lhs_end))
            + list(range(lhs_end + 1, rhs_end))
        ):
            fn += "_arr"
        self._emit(fn)
        self.out.append("(")
        self._emit_operand(items, idx, lhs_end)
        self._emit(",")
        self._emit_operand(items, lhs_end + 1, rhs_end)
        self._emit(")")
        return rhs_end - idx

    def _try_any_all(self, items: Sequence[Item], idx: int) -> int:
        """``= ANY(x)`` → ``IN (SELECT value FROM json_each(pg_array_json(x)))``
        and ``<> ALL(x)`` → ``NOT IN (...)`` — the psycopg list-parameter
        idioms; arrays are JSON/PG-literal text (runtime.pg_array_json)."""
        it = items[idx]
        is_op = (
            isinstance(it, Token)
            and it.kind == OP
            and it.value in ("=", "<>", "!=", "<", ">", "<=", ">=", "~", "~*")
        )
        is_like = item_is_kw(it, "LIKE", "ILIKE")
        if not ((is_op or is_like) and idx + 1 < len(items)):
            return 0
        # ANY/SOME parse as a Call; ALL is a reserved word, so
        # ``ALL('{..}')`` parses as Name + Group
        nxt = items[idx + 1]
        consumed = 2
        if isinstance(nxt, Call) and len(nxt.name.parts) == 1:
            fname = nxt.name.parts[0].value.lower()
            arg_items: Sequence[Item] = nxt.args
        elif (
            item_is_kw(nxt, "ANY", "SOME", "ALL")
            and idx + 2 < len(items)
            and isinstance(items[idx + 2], Group)
        ):
            fname = nxt.parts[0].value.lower()
            arg_items = items[idx + 2].items
            consumed = 3
        else:
            return 0
        if fname not in ("any", "some", "all"):
            return 0
        op_text = it.value if is_op else it.parts[0].value.upper()
        if op_text == "=" and fname in ("any", "some"):
            negate = False
        elif op_text in ("<>", "!=") and fname == "all":
            negate = True
        else:
            # <> ANY, = ALL, ordered comparisons: quantified forms with
            # no direct SQLite strategy — reject cleanly (emitting the
            # raw call would die later with "no such function: ANY")
            raise UnsupportedConstruct(
                f"{op_text} {fname.upper()}(...) quantified comparison "
                "is not supported; use = ANY / <> ALL or rewrite with "
                "EXISTS"
            )
        # a cast on the array argument ($1::int[]) would destroy the
        # array text before pg_array_json parses it — strip it
        if (
            len(arg_items) == 1
            and isinstance(arg_items[0], Cast)
        ):
            arg_items = [arg_items[0].operand]
        if negate:
            self._emit("NOT")
        self._emit("IN")
        self._emit("(")
        if arg_items and item_is_kw(
            arg_items[0], "SELECT", "VALUES", "WITH", "TABLE"
        ):
            # = ANY(subquery) ≡ IN (subquery) — no array wrapper
            self.emit_items(arg_items)
        else:
            self._emit("SELECT value FROM json_each")
            self.out.append("(")
            self._emit("pg_array_json")
            self.out.append("(")
            self.emit_items(arg_items)
            self._emit(")")
            self._emit(")")
        self._emit(")")
        return consumed

    def _try_array_literal(self, items: Sequence[Item], idx: int) -> int:
        """``ARRAY[a, b, ...]`` → ``json_array(a, b, ...)`` (arrays are
        JSON text everywhere in this dialect)."""
        if not (
            item_is_kw(items[idx], "ARRAY")
            and idx + 1 < len(items)
            and isinstance(items[idx + 1], Token)
            and items[idx + 1].value == "["
        ):
            return 0
        close = self._array_close(items, idx)
        if close < 0:
            return 0
        self._emit_operand(items, idx, close + 1)
        return close - idx + 1

    def _try_for_lock(self, items: Sequence[Item], idx: int) -> int:
        """``FOR UPDATE / FOR [NO KEY] SHARE/UPDATE [OF t, ...]
        [NOWAIT | SKIP LOCKED]`` → dropped: the store's single-writer
        lane serializes writes, so PG row-lock hints have no analog."""
        if not (
            item_is_kw(items[idx], "FOR")
            and idx + 1 < len(items)
            and item_is_kw(items[idx + 1], "UPDATE", "SHARE", "NO", "KEY")
        ):
            return 0
        j = idx + 1
        while j < len(items) and item_is_kw(items[j], "NO", "KEY", "UPDATE", "SHARE"):
            j += 1
        if j < len(items) and item_is_kw(items[j], "OF"):
            j += 1
            while j < len(items):
                if isinstance(items[j], Name) and not item_is_kw(
                    items[j], "NOWAIT", "SKIP"
                ):
                    j += 1
                    if (
                        j < len(items)
                        and isinstance(items[j], Token)
                        and items[j].value == ","
                    ):
                        j += 1
                        continue
                break
        if j < len(items) and item_is_kw(items[j], "NOWAIT"):
            j += 1
        elif (
            j + 1 < len(items)
            and item_is_kw(items[j], "SKIP")
            and item_is_kw(items[j + 1], "LOCKED")
        ):
            j += 2
        return j - idx

    def _try_srf(self, items: Sequence[Item], idx: int) -> int:
        """Set-returning functions in FROM position:
        ``generate_series(a, b[, step])`` → recursive-CTE subquery;
        ``unnest(arr)``, ``json[b]_array_elements[_text](j)``, and
        ``json[b]_object_keys(j)`` → ``json_each`` projections (with a
        json_type guard where PG would raise on the wrong container
        kind — we yield zero rows instead).  The PG aliasing rule (a
        bare alias names the single output column) is reproduced."""
        it = items[idx]
        if not (isinstance(it, Call) and len(it.name.parts) == 1):
            return 0
        fname = it.name.parts[0].value.lower()
        if fname not in (
            "generate_series", "unnest",
            "jsonb_array_elements", "json_array_elements",
            "jsonb_array_elements_text", "json_array_elements_text",
            "jsonb_object_keys", "json_object_keys",
        ):
            return 0

        alias, col, j = _parse_srf_alias(items, idx + 1)
        table = alias or fname
        colname = col or alias or _srf_default_col(fname)

        if fname != "generate_series":
            # Correlated args (the dominant PG shape — the lateral join
            # `FROM t, jsonb_array_elements(t.data) AS e`) emit as a
            # BARE table-valued json_each, the only SQLite form that
            # can reference earlier FROM entries; the output column
            # (PG names it after the alias) rewrites at reference
            # sites via srf_renames.  The bare form leaks json_each's
            # own column names (id/key/value/...), so literal/param
            # args take a clean renaming subquery instead.  The
            # correlation predicate MUST match scan_srf_renames.
            correlated = _srf_args_correlated(it.args)
            want_kind = None  # json_type the source must have
            if fname in ("jsonb_object_keys", "json_object_keys"):
                want_kind = "object"
            elif fname != "unnest":
                want_kind = "array"

            def emit_src():
                # SRF arguments are VALUE position even though the
                # clause is FROM — a chained SRF's args may reference
                # an earlier SRF's output column
                saved_clause = self._clause
                self._clause = "SELECT"
                try:
                    if fname == "unnest":
                        self._emit("pg_array_json")
                        self.out.append("(")
                        self.emit_items(it.args)
                        self._emit(")")
                    else:
                        self.emit_items(it.args)
                finally:
                    self._clause = saved_clause

            def emit_each():
                self._emit("json_each")
                self.out.append("(")
                if want_kind is not None:
                    # PG raises on the wrong container kind; feeding
                    # json_each an empty container yields zero rows.
                    # The guard evaluates the source twice per outer
                    # row — acceptable for the typical `t.col` /
                    # `t.col -> 'k'` argument; SQLite has no lateral
                    # derived table to bind it once
                    empty = "'[]'" if want_kind == "array" else "'{}'"
                    self._emit("iif")
                    self.out.append("(")
                    self._emit("json_type")
                    self.out.append("(")
                    emit_src()
                    self._emit(")")
                    self._emit(f"= '{want_kind}',")
                    emit_src()
                    self._emit(f", {empty})")
                else:
                    emit_src()
                self._emit(")")

            if correlated:
                emit_each()
                self._emit("AS")
                self._emit(f'"{table}"')
            else:
                self._emit("(")
                self._emit("SELECT")
                self._emit(
                    _srf_column_expr(fname, "json_each").replace(
                        '"json_each".', ""
                    )
                )
                self._emit("AS")
                self._emit(f'"{colname}"')
                self._emit("FROM")
                emit_each()
                self._emit(")")
                self._emit("AS")
                self._emit(f'"{table}"')
            return j - idx
        else:
            if _srf_args_correlated(it.args):
                # the recursive-CTE derived table this emits cannot be
                # correlated in SQLite — it would fail at execution with
                # an opaque "no such column"; reject cleanly instead
                # (same treatment as WITH ORDINALITY / dynamic step)
                raise UnsupportedConstruct(
                    "correlated generate_series (bounds referencing an "
                    "earlier FROM entry) is not supported; precompute the "
                    "bound or join against a literal series"
                )
            arglists = _split_args(it.args)
            if len(arglists) not in (2, 3):
                raise UnsupportedConstruct(
                    "generate_series over timestamps or with missing "
                    "bounds is not supported"
                )
            step = 1.0
            if len(arglists) == 3:
                lit = _literal_number(arglists[2])
                if lit is None:
                    raise UnsupportedConstruct(
                        "generate_series step must be a literal number"
                    )
                if lit == 0:
                    # PG: "step size cannot equal zero"; emitting it
                    # would make the recursive CTE spin forever
                    raise UnsupportedConstruct(
                        "generate_series step cannot be zero"
                    )
                step = lit
            cmp_op = "<=" if step >= 0 else ">="
            # integral steps emit as INTEGER so the series keeps PG's
            # int type (value + 2.0 would promote every row to REAL)
            step_text = (
                str(int(step)) if float(step).is_integer() else repr(step)
            )

            def emit_arg(arg_items):
                self.emit_items(arg_items)

            self._emit("(")
            self._emit('WITH RECURSIVE "__corro_gs"')
            self._emit("(")
            self._emit("value")
            self._emit(")")
            self._emit("AS")
            self._emit("(")
            self._emit("SELECT")
            emit_arg(arglists[0])
            self._emit("WHERE")
            emit_arg(arglists[0])
            self._emit(cmp_op)
            emit_arg(arglists[1])
            self._emit("UNION ALL SELECT value +")
            self._emit(step_text)
            self._emit('FROM "__corro_gs" WHERE value +')
            self._emit(step_text)
            self._emit(cmp_op)
            emit_arg(arglists[1])
            self._emit(")")
            self._emit("SELECT value AS")
            self._emit(f'"{colname}"')
            self._emit('FROM "__corro_gs"')
            self._emit(")")
        self._emit("AS")
        self._emit(f'"{table}"')
        return j - idx

    def _try_values_alias(self, items: Sequence[Item], idx: int) -> int:
        """Detect ``Group(VALUES …) [AS] alias (col, …)`` starting at idx;
        emit the SQLite rewrite and return how many items were consumed
        (0 = no match)."""
        it = items[idx]
        if not (isinstance(it, Group) and it.is_select and it.items):
            return 0
        first = it.items[0]
        is_values = item_is_kw(first, "VALUES") or (
            # `VALUES (1)` parses as a Call named VALUES
            isinstance(first, Call)
            and len(first.name.parts) == 1
            and first.name.parts[0].iskw("VALUES")
        )
        if not is_values:
            return 0
        j = idx + 1
        if j < len(items) and item_is_kw(items[j], "AS"):
            j += 1
        # alias may parse as Name or as Call(alias, cols) when the column
        # list directly follows
        alias: Optional[str] = None
        cols: Optional[List[str]] = None
        if j < len(items) and isinstance(items[j], Call):
            call = items[j]
            if len(call.name.parts) == 1:
                alias = call.name.parts[0].value
                cols = [
                    a.parts[0].value
                    for a in call.args
                    if isinstance(a, Name) and len(a.parts) == 1
                ]
                if len(cols) != sum(
                    0 if (isinstance(a, Token) and a.value == ",") else 1
                    for a in call.args
                ):
                    cols = None
            j += 1
        elif (
            j + 1 < len(items)
            and isinstance(items[j], Name)
            and isinstance(items[j + 1], Group)
        ):
            alias = items[j].parts[0].value
            cols = [
                a.parts[0].value
                for a in items[j + 1].items
                if isinstance(a, Name) and len(a.parts) == 1
            ]
            j += 2
        if alias is None or not cols:
            return 0
        self._emit("(")
        self._emit("SELECT")
        for k, cname in enumerate(cols):
            if k:
                self._emit(",")
            self._emit(f"column{k + 1}")
            self._emit("AS")
            self._emit(cname)
        self._emit("FROM")
        self.emit_item(items[idx])
        self._emit(")")
        self._emit("AS")
        self._emit(alias)
        return j - idx

    def _operator_group(self, grp: Group) -> Optional[str]:
        # Group items: [Name(pg_catalog)? , '.', OP] or just [OP]
        ops = [
            t.value
            for t in grp.items
            if isinstance(t, Token) and t.kind == OP
        ]
        names = [it for it in grp.items if isinstance(it, Name)]
        if len(ops) == 1 and len(grp.items) <= 3:
            return _OPERATOR_MAP.get(ops[0], ops[0])
        if not ops and len(names) == 1:
            return None
        return None

    def emit_item(self, it: Item) -> None:
        if isinstance(it, Token):
            if it.kind == PARAM:
                self._emit("?" + it.value[1:])
            elif it.kind == STRING:
                self._emit(_sqlite_string(it.value))
            else:
                self._emit(it.value)
            return
        if isinstance(it, TableBody):
            self._emit("(")
            for k, el in enumerate(it.elements):
                if k:
                    self._emit(",")
                if isinstance(el, ColumnDef):
                    self._emit(el.name.value)
                    if el.pg_type is not None:
                        self._emit(
                            _TYPE_MAP.get(el.pg_type, el.pg_type.upper())
                        )
                        if el.type_mod is not None:
                            self.emit_item(el.type_mod)
                    self.emit_items(el.rest)
                else:
                    self.emit_items(el)
            self._emit(")")
            return
        if isinstance(it, Name):
            self.emit_name(it)
            return
        if isinstance(it, Group):
            # a select subquery is its own SRF-rename SCOPE: it sees
            # the outer scope's SRF columns (correlation) UNLESS it has
            # its own FROM clause — then its names resolve against its
            # own tables, which we cannot enumerate, so outer renames
            # are dropped rather than hijacking same-named columns (a
            # correlated ref to an outer SRF column from inside such a
            # subquery errors instead of silently rewriting)
            saved = self.srf_renames
            if it.is_select:
                sub, sub_has_from = scan_srf_renames(it.items)
                self.srf_renames = (
                    {**sub} if sub_has_from else {**saved, **sub}
                )
            try:
                self._emit("(")
                self.emit_items(it.items)
                self._emit(")")
            finally:
                self.srf_renames = saved
            return
        if isinstance(it, Call):
            self.emit_call(it)
            return
        if isinstance(it, Cast):
            if it.pg_type == "interval":
                # '1 hour'::interval → seconds (the standalone-interval
                # model); literal folds at emit time, else UDF
                if isinstance(it.operand, Token) and it.operand.kind == STRING:
                    from .runtime import interval_to_seconds

                    try:
                        self._emit(repr(
                            interval_to_seconds(_strip_quotes(it.operand))
                        ))
                        return
                    except ValueError:
                        pass
                self._emit("pg_interval_seconds")
                self.out.append("(")
                self.emit_item(it.operand)
                self._emit(")")
                return
            self._emit("CAST")
            self._emit("(")
            self.emit_item(it.operand)
            self._emit("AS")
            self._emit(_TYPE_MAP.get(it.pg_type, it.pg_type.upper()))
            self._emit(")")
            return
        if isinstance(it, Case):
            self.emit_items(it.items)
            return
        if isinstance(it, ConflictClause):
            self.emit_conflict(it)
            return
        raise TypeError(f"unknown item {it!r}")

    def emit_name(self, name: Name) -> None:
        parts = name.parts
        schema = name.schema()
        if schema in ("public", "main") and len(parts) >= 2:
            parts = parts[-1:]
        elif schema == "information_schema":
            # served as is_* views INSIDE pg_catalog (SQLite forbids
            # cross-database views; catalog.attach builds them)
            self._emit(f"pg_catalog.is_{name.last.lower()}")
            return
        if len(parts) == 1 and not parts[0].quoted:
            srf = self.srf_renames.get(parts[0].value.lower())
            if (
                srf is not None
                and self._clause in self._SRF_VALUE_CLAUSES
                # name-DEFINING positions: `expr AS e` and the bare
                # implicit alias `expr e` — a name directly after a
                # complete value expression is an alias, not a ref
                and not item_is_kw(self._prev_sig, "AS")
                and not _is_valueish(self._prev_sig)
                and not isinstance(self._prev_sig, Case)
            ):
                self._emit(srf)
                return
            mapped = _NAME_RENAMES.get(parts[0].value.lower())
            if mapped is not None:
                self._emit(mapped)
                return
        self._emit(
            ".".join(
                p.value if p.kind != OP else "*"  # tbl.*
                for p in parts
            )
        )

    def emit_call(self, call: Call) -> None:
        name = call.name
        if (
            len(name.parts) == 1
            and name.parts[0].iskw("OPERATOR")
            and call.args
        ):
            # OPERATOR(pg_catalog.~) → the mapped plain operator
            op = self._operator_group(Group(call.args))
            if op is not None:
                self._emit(op)
                return
        if call.name.parts[0].iskw("CAST"):
            # CAST(expr AS type): map the trailing type name
            self._emit("CAST")
            self._emit("(")
            self._emit_cast_args(call.args)
            self._emit(")")
            return
        parts = name.parts
        if name.schema() in ("pg_catalog", "public", "information_schema"):
            parts = parts[-1:]  # UDFs have no schema in SQLite
        if call.args and item_is_kw(
            call.args[0], "SELECT", "VALUES", "WITH", "TABLE"
        ):
            # EXISTS(SELECT ...) / coalesce((SELECT ...)) parse their
            # subquery items FLAT into call.args — re-scope SRF renames
            # exactly like the Group subquery path
            self._emit(".".join(p.value for p in parts))
            self.out.append("(")
            saved = self.srf_renames
            sub, sub_has_from = scan_srf_renames(call.args)
            self.srf_renames = {**sub} if sub_has_from else {**saved, **sub}
            try:
                self.emit_items(call.args)
            finally:
                self.srf_renames = saved
            self._emit(")")
            return
        if len(parts) == 1 and not parts[0].quoted:
            fname = parts[0].value.lower()
            if self._try_kw_arg_call(fname, call):
                return
            if fname == "string_agg" and call.args and item_is_kw(
                call.args[0], "DISTINCT"
            ):
                # SQLite DISTINCT aggregates take exactly one argument;
                # only PG's default-comma separator maps cleanly
                groups = _split_args(_strip_order_by(call.args))
                is_comma = (
                    len(groups) == 2
                    and len(groups[1]) == 1
                    and isinstance(groups[1][0], Token)
                    and groups[1][0].kind == STRING
                    and _strip_quotes(groups[1][0]) == ","
                )
                if not is_comma:
                    raise UnsupportedConstruct(
                        "string_agg(DISTINCT ...) only supports the ',' "
                        "separator (SQLite DISTINCT aggregates are "
                        "single-argument)"
                    )
                self._emit("group_concat")
                self.out.append("(")
                self.emit_items(groups[0])  # includes the DISTINCT kw
                self._emit(")")
                return
            mapped = _CALL_RENAMES.get(fname)
            if mapped is not None:
                args = call.args
                if mapped in (
                    "group_concat", "json_group_array", "json_group_object"
                ):
                    args = _strip_order_by(args)
                self._emit(mapped)
                self.out.append("(")
                self.emit_items(args)
                self._emit(")")
                return
        self._emit(".".join(p.value for p in parts))
        self.out.append("(")  # no space: f(x)
        self.emit_items(call.args)
        self._emit(")")

    def _try_kw_arg_call(self, fname: str, call: Call) -> bool:
        """The SQL-standard keyword-argument call forms PG clients send:
        position(x IN y), substring(s FROM a FOR b), trim(BOTH c FROM s),
        extract(F FROM ts), overlay(s PLACING r FROM p FOR n)."""
        args = call.args

        def kw_index(*words: str) -> int:
            for k, a in enumerate(args):
                if item_is_kw(a, *words):
                    return k
            return -1

        def emit_fn(fn: str, *arg_groups) -> None:
            self._emit(fn)
            self.out.append("(")
            for k, grp in enumerate(arg_groups):
                if k:
                    self._emit(",")
                if isinstance(grp, str):
                    self._emit(grp)
                else:
                    self.emit_items(grp)
            self._emit(")")

        if fname == "position":
            k = kw_index("IN")
            if k < 0:
                return False
            emit_fn("instr", args[k + 1:], args[:k])
            return True

        if fname == "substring":
            k = kw_index("FROM")
            if k < 0:
                kf = kw_index("FOR")
                if kf >= 0:
                    # substring(s FOR n) ≡ substr(s, 1, n)
                    emit_fn("substr", args[:kf], "1", args[kf + 1:])
                    return True
                return False  # comma form: SQLite substring() is native
            kf = kw_index("FOR")
            if kf > k:
                emit_fn("substr", args[:k], args[k + 1: kf], args[kf + 1:])
            else:
                start = args[k + 1:]
                if (
                    len(start) == 1
                    and isinstance(start[0], Token)
                    and start[0].kind == STRING
                ):
                    # substring(s FROM 'regex') — the SIMILAR-free form
                    emit_fn("pg_substring_re", args[:k], start)
                else:
                    emit_fn("substr", args[:k], start)
            return True

        if fname == "trim":
            k = kw_index("FROM")
            direction = "BOTH"
            rest = args
            if rest and item_is_kw(rest[0], "BOTH", "LEADING", "TRAILING"):
                direction = rest[0].last.upper() if isinstance(
                    rest[0], Name
                ) else "BOTH"
                rest = rest[1:]
                k -= 1
            if k < 0:
                return False  # plain trim(s) / trim(s, c): native
            chars = rest[:k]
            subject = rest[k + 1:]
            fn = {"BOTH": "trim", "LEADING": "ltrim", "TRAILING": "rtrim"}[
                direction
            ]
            if chars:
                emit_fn(fn, subject, chars)
            else:
                emit_fn(fn, subject)
            return True

        if fname == "extract":
            k = kw_index("FROM")
            if k < 0:
                return False
            field = args[:k]
            ftext = ""
            if len(field) == 1:
                if isinstance(field[0], Name):
                    ftext = field[0].last.lower()
                elif isinstance(field[0], Token) and field[0].kind == STRING:
                    ftext = _strip_quotes(field[0]).lower()
            if not ftext:
                return False
            emit_fn("pg_date_part", f"'{ftext}'", args[k + 1:])
            return True

        if fname == "overlay":
            kp = kw_index("PLACING")
            kf = kw_index("FROM")
            if kp < 0 or kf < kp:
                return False
            kn = kw_index("FOR")
            if kn > kf:
                emit_fn(
                    "pg_overlay",
                    args[:kp], args[kp + 1: kf], args[kf + 1: kn],
                    args[kn + 1:],
                )
            else:
                emit_fn(
                    "pg_overlay",
                    args[:kp], args[kp + 1: kf], args[kf + 1:], "NULL",
                )
            return True

        return False

    def _emit_cast_args(self, args: Sequence[Item]) -> None:
        # ... AS <type words>: everything before AS emits normally.  Bare
        # keywords parse as single-part Names, so the AS split and the
        # type words must use item-level matching, not raw Tokens.
        split = None
        for k, a in enumerate(args):
            if item_is_kw(a, "AS"):
                split = k
        if split is None:
            self.emit_items(args)
            return
        self.emit_items(args[:split])
        self._emit("AS")
        tail = list(args[split + 1 :])
        type_words: List[str] = []
        for a in tail:
            if isinstance(a, Token) and a.kind == IDENT:
                type_words.append(a.value.lower())
            elif isinstance(a, Name) and len(a.parts) == 1:
                type_words.append(a.parts[0].value.lower())
            elif isinstance(a, Call) and len(a.name.parts) == 1:
                # VARCHAR(10): type word + modifier in one Call
                type_words.append(a.name.parts[0].value.lower())
        tname = " ".join(type_words)
        if tname in _TYPE_MAP:
            self._emit(_TYPE_MAP[tname])
            # re-emit any modifier group (e.g. VARCHAR(10) keeps (10))
            for a in tail:
                if isinstance(a, Group):
                    self.emit_item(a)
                elif isinstance(a, Call):
                    self._emit("(")
                    self.emit_items(a.args)
                    self._emit(")")
        else:
            self.emit_items(tail)

    def emit_conflict(self, c: ConflictClause) -> None:
        self._emit("ON")
        self._emit("CONFLICT")
        if c.constraint is not None:
            if self.resolver is None:
                raise UnknownConstraint(
                    "ON CONFLICT ON CONSTRAINT requires schema access "
                    "to resolve the constraint's columns"
                )
            cname = (
                c.constraint.value[1:-1].replace('""', '"')
                if c.constraint.quoted
                else c.constraint.value
            )
            cols = self.resolver(c.table.last, cname)
            if not cols:
                raise UnknownConstraint(
                    f'constraint "{cname}" for table '
                    f'"{c.table.last}" does not exist'
                )
            self._emit("(")
            for k, col in enumerate(cols):
                if k:
                    self._emit(",")
                self._emit(f'"{col}"')
            self._emit(")")
        elif c.target_cols is not None:
            self.emit_item(c.target_cols)
            if c.where:
                self.emit_items(c.where)
        self.emit_items(c.action)


def emit(
    st: Statement,
    constraint_resolver: Optional[ConstraintResolver] = None,
) -> str:
    em = Emitter(
        constraint_resolver=constraint_resolver,
        srf_renames=scan_srf_renames(st.items)[0],
    )
    if st.ctes:
        em._emit("WITH")
        if st.recursive:
            em._emit("RECURSIVE")
        for k, (name, cols, sub) in enumerate(st.ctes):
            if k:
                em._emit(",")
            em._emit(name.value)
            if cols:
                em._emit("(")
                em.emit_items(cols)
                em._emit(")")
            em._emit("AS")
            em._emit("(")
            em.out.append(emit(sub, constraint_resolver))
            em._emit(")")
    # DDL type mapping happens structurally in TableBody/ColumnDef
    # emission; everything else re-emits with the standard rewrites
    # (SQLite's affinity rules understand unmapped PG type names anyway)
    if (
        len(st.items) >= 3
        and item_is_kw(st.items[0], "SELECT")
        and item_is_kw(st.items[1], "DISTINCT")
        and item_is_kw(st.items[2], "ON")
    ):
        raise UnsupportedConstruct(
            "SELECT DISTINCT ON is not supported; rewrite with GROUP BY "
            "or a row_number() window"
        )
    if st.verb == "DELETE" and _emit_delete_using(em, st):
        return em.text()
    em.emit_items(st.items)
    return em.text()


def _emit_delete_using(em: Emitter, st: Statement) -> bool:
    """``DELETE FROM t [AS a] USING u, ... WHERE cond [RETURNING ...]``
    → ``DELETE FROM t WHERE rowid IN (SELECT a.rowid FROM t AS a, u, ...
    WHERE cond) [RETURNING ...]`` (PG's delete-join; SQLite has no
    USING on DELETE)."""
    items = st.items
    i_using = -1
    for k, it in enumerate(items):
        if item_is_kw(it, "USING"):
            i_using = k
            break
    if i_using < 0:
        return False
    # shape: DELETE FROM [ONLY] name [AS alias | alias] USING ...
    k = 1
    if k < len(items) and item_is_kw(items[k], "FROM"):
        k += 1
    if k < len(items) and item_is_kw(items[k], "ONLY"):
        k += 1
    if k >= len(items) or not isinstance(items[k], Name):
        return False
    tname = items[k]
    k += 1
    alias: Optional[Name] = None
    if k < i_using and item_is_kw(items[k], "AS"):
        k += 1
    if k < i_using and isinstance(items[k], Name) and _is_valueish(items[k]):
        alias = items[k]
        k += 1
    if k != i_using:
        return False
    i_where = -1
    i_ret = -1
    for k in range(i_using + 1, len(items)):
        if item_is_kw(items[k], "WHERE") and i_where < 0:
            i_where = k
        if item_is_kw(items[k], "RETURNING") and i_ret < 0:
            i_ret = k
    end = i_ret if i_ret >= 0 else len(items)
    using_items = items[i_using + 1: i_where if i_where >= 0 else end]
    cond_items = items[i_where + 1: end] if i_where >= 0 else []

    em._emit("DELETE FROM")
    em.emit_name(tname)
    if alias is not None:  # RETURNING may reference the alias
        em._emit("AS")
        em.emit_name(alias)
    em._emit("WHERE rowid IN")
    em._emit("(")
    em._emit("SELECT")
    em.emit_name(alias or tname)
    em.out.append(".rowid")
    em._emit("FROM")
    em.emit_name(tname)
    if alias is not None:
        em._emit("AS")
        em.emit_name(alias)
    em._emit(",")
    em.emit_items(using_items)
    if cond_items:
        em._emit("WHERE")
        em.emit_items(cond_items)
    em._emit(")")
    if i_ret >= 0:
        # SQLite RETURNING forbids table/alias qualifiers — strip them
        qualifiers = {tname.last.lower()}
        if alias is not None:
            qualifiers.add(alias.last.lower())
        for it in items[i_ret:]:
            if (
                isinstance(it, Name)
                and len(it.parts) >= 2
                and it.parts[0].value.lower().strip('"') in qualifiers
            ):
                em.emit_name(Name(parts=it.parts[1:]))
            else:
                em.emit_item(it)
    return True
